"""The geometry hardening battery: SDF quadrature parity, the
admissibility gate's accept/reject matrix, composite-domain solves
across engines (single + 1×2 sharded), the degenerate-cut
clamp-vs-stall measurement, the seeded fuzz invariants, and the exit-8
CLI contract.

Solve costs are kept tier-1-sized: everything runs f64 on grids ≤ 40²,
and operand-level solves share ONE jitted entry per shape
(``_solve_operands``) so the file pays a handful of compiles, not one
per case.
"""

from __future__ import annotations

import functools
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.geom import fuzz as geom_fuzz
from poisson_ellipse_tpu.geom import quadrature, sdf
from poisson_ellipse_tpu.geom import validate as geom_validate
from poisson_ellipse_tpu.models import ellipse
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.resilience import faultinject
from poisson_ellipse_tpu.resilience.errors import (
    EXIT_INVALID_GEOMETRY,
    InvalidGeometryError,
)
from poisson_ellipse_tpu.solver.pcg import pcg


# one compiled operand-level solver per (problem, shapes) — the whole
# file's solves ride a handful of compiles
# tpulint: disable=TPU004
@functools.partial(jax.jit, static_argnums=(0, 4))
def _solve_operands(problem, a, b, rhs, history=False):
    return pcg(problem, a, b, rhs, history=history)


def _solve(problem, geometry=None, theta=None, history=False):
    a, b, rhs = assembly.assemble(
        # tpulint: disable=TPU001 — x64 is on (conftest)
        problem, jnp.float64, geometry=geometry, theta=theta
    )
    return _solve_operands(problem, a, b, rhs, history)


def _crack_comb(problem, gap_frac, rows):
    """The deliberately-sliver-cut ellipse: internal slits ``gap_frac``
    of a cell wide centered on node rows — every slit-crossing face
    gets fraction 1 − gap_frac, whose blend coefficient carries the
    (1−l/h)/ε amplification the defense exists for."""
    rects = []
    for k in rows:
        y0 = problem.a2 + k * problem.h2
        g = gap_frac * problem.h2
        rects.append(
            sdf.Rectangle(x0=-0.9, y0=y0 - g / 2, x1=0.9, y1=y0 + g / 2)
        )
    return sdf.Difference(sdf.Ellipse(), sdf.Union(*rects))


# -- quadrature vs the closed form ------------------------------------------


def test_ellipse_quadrature_matches_closed_form_fractions():
    p = Problem(M=40, N=40)
    la, lb = quadrature.segment_lengths(p, sdf.Ellipse())
    gi = np.arange(p.M + 1, dtype=np.float64)
    gj = np.arange(p.N + 1, dtype=np.float64)
    x = p.a1 + gi * p.h1
    y = p.a2 + gj * p.h2
    xc, yc = x[:, None], y[None, :]
    la_cf = ellipse.segment_length_vertical(
        xc - 0.5 * p.h1, yc - 0.5 * p.h2, yc + 0.5 * p.h2, np
    )
    lb_cf = ellipse.segment_length_horizontal(
        yc - 0.5 * p.h2, xc - 0.5 * p.h1, xc + 0.5 * p.h1, np
    )
    # the acceptance bound: <= 1e-12 relative face-fraction error
    assert np.abs(la / p.h2 - la_cf / p.h2).max() <= 1e-12
    assert np.abs(lb / p.h1 - lb_cf / p.h1).max() <= 1e-12


def test_ellipse_sdf_assembly_matches_closed_form_operator():
    p = Problem(M=20, N=20)
    a_cf, b_cf, r_cf = assembly.assemble_numpy(p)
    a_q, b_q, r_q = assembly.assemble_numpy(
        p, geometry=sdf.Ellipse(), theta=0.0
    )
    # rhs indicator is sign-exact; coefficients inherit the 1e-12
    # fraction bound through the blend law (amplified by 1/eps on cut
    # faces, hence the relative comparison)
    np.testing.assert_array_equal(r_cf, r_q)
    np.testing.assert_allclose(a_q, a_cf, rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(b_q, b_cf, rtol=1e-9, atol=1e-12)


def test_default_path_untouched_and_sdf_ellipse_iteration_parity():
    # the closed-form default must remain the byte-for-byte operand set
    # (geometry=None short-circuits to the historical code), and the
    # ellipse THROUGH the quadrature path lands within +-2 iterations
    p = Problem(M=20, N=20)
    a1, b1, r1 = assembly.assemble_numpy(p)
    a2, b2, r2 = assembly.assemble_numpy(p, geometry=None)
    assert a1.tobytes() == a2.tobytes()
    assert b1.tobytes() == b2.tobytes()
    assert r1.tobytes() == r2.tobytes()

    ref = _solve(p)
    quad = _solve(p, geometry=sdf.Ellipse())
    assert bool(ref.converged) and bool(quad.converged)
    assert abs(int(ref.iters) - int(quad.iters)) <= 2


def test_safe_sqrt_gradients_are_finite_at_zero():
    # sqrt(maximum(0, v)) has a NaN cotangent at exactly v = 0; the
    # safe form pins it to 0 on the clamped branch in BOTH the segment
    # closed forms and the SDF primitives
    g = jax.grad(
        lambda x0: ellipse.segment_length_vertical(x0, -0.1, 0.1)
    )(1.0)
    assert np.isfinite(float(g))
    g2 = jax.grad(lambda v: ellipse.safe_sqrt(v))(0.0)
    assert float(g2) == 0.0
    # the ellipse SDF at its own center hits sqrt(0) too
    g3 = jax.grad(lambda x: sdf.Ellipse()(x, 0.0))(0.0)
    assert np.isfinite(float(g3))


def test_spec_roundtrip():
    shape = sdf.Translate(
        sdf.Difference(
            sdf.Union(sdf.Ellipse(), sdf.Circle(cx=0.2, r=0.2)),
            sdf.Intersection(
                sdf.Rectangle(), sdf.HalfPlane(nx=0.0, ny=1.0)
            ),
        ),
        dx=0.05, dy=-0.02,
    )
    spec = sdf.to_spec(shape)
    rebuilt = sdf.from_spec(json.loads(json.dumps(spec)))
    x = np.linspace(-0.9, 0.9, 23)[:, None]
    y = np.linspace(-0.5, 0.5, 17)[None, :]
    np.testing.assert_array_equal(
        np.asarray(shape(x, y, np)), np.asarray(rebuilt(x, y, np))
    )


# -- the admissibility gate -------------------------------------------------


@pytest.mark.parametrize(
    "spec,reason",
    [
        ({"kind": "tetrahedron"}, "malformed-spec"),
        ({"kind": "circle", "r": -1.0}, "malformed-spec"),
        ({"kind": "ellipse", "rx": float("nan")}, "malformed-spec"),
        ({"kind": "union", "shapes": []}, "malformed-spec"),
        ({"kind": "rectangle", "x0": 1.0, "x1": -1.0}, "malformed-spec"),
        ("not-a-dict", "malformed-spec"),
        # structurally fine, geometrically inadmissible:
        (sdf.Intersection(
            sdf.Circle(cx=-0.5, r=0.12), sdf.Circle(cx=0.5, r=0.12)
        ), "empty-domain"),
        (sdf.Circle(cx=0.95, cy=0.0, r=0.3), "boundary-contact"),
        (sdf.Rectangle(x0=-0.5, y0=0.004, x1=0.5, y1=0.016),
         "under-resolved"),
    ],
)
def test_gate_rejects_with_classified_reason(spec, reason):
    p = Problem(M=40, N=40)
    with pytest.raises(InvalidGeometryError) as exc:
        geom_validate.validate(p, spec)
    assert exc.value.reason == reason
    assert exc.value.exit_code == EXIT_INVALID_GEOMETRY
    assert exc.value.classification == "invalid-geometry"


@pytest.mark.parametrize(
    "shape",
    [
        sdf.Ellipse(),
        sdf.Difference(sdf.Ellipse(), sdf.Circle(r=0.2)),
        sdf.Union(
            sdf.Circle(cx=-0.35, r=0.2), sdf.Circle(cx=0.35, r=0.2)
        ),
        sdf.Intersection(
            sdf.Ellipse(), sdf.HalfPlane(nx=0.0, ny=1.0, offset=-0.1)
        ),
    ],
)
def test_gate_accepts_admissible_domains(shape):
    rep = geom_validate.validate(Problem(M=40, N=40), shape)
    assert rep["ok"] and rep["inside_nodes"] > 0
    assert "spd-lanczos" in rep["checks"]
    lo, hi = rep["ritz_interval"]
    # lambda(D^-1 A) lives in (0, 2] (Gershgorin); the interval carries
    # obs.spectrum's documented covering slack on the high side
    assert 0.0 < lo < hi <= 2.2


def test_gate_catches_inadmissible_operator():
    # sabotaged operands (a negative face coefficient) must trip the
    # M-matrix rung even when the level set itself is fine
    p = Problem(M=16, N=16)
    a, b, rhs = assembly.assemble_numpy(p)
    a_bad = a.copy()
    a_bad[8, 8] = -1.0
    with pytest.raises(InvalidGeometryError) as exc:
        geom_validate.validate(
            p, sdf.Ellipse(), operands=(a_bad, b, rhs)
        )
    assert exc.value.reason == "operator-not-m-matrix"


def test_gate_spd_probe_is_optional_and_reported():
    # positive-face 5-point operators are SPD by construction, so the
    # probe is the belt-and-suspenders rung: assert it is (a) skippable
    # and (b) recorded in the report when run, with a usable interval
    p = Problem(M=16, N=16)
    with_probe = geom_validate.validate(p, sdf.Ellipse())
    without = geom_validate.validate(p, sdf.Ellipse(), spd_probe=False)
    assert "spd-lanczos" in with_probe["checks"]
    assert with_probe["lanczos_steps"] > 0
    assert "spd-lanczos" not in without["checks"]
    assert "ritz_interval" not in without


# -- composite-domain solves across engines ---------------------------------

COMPOSITE = sdf.Difference(sdf.Ellipse(), sdf.Circle(r=0.2))


def test_composite_solves_classical_pipelined_mg():
    from poisson_ellipse_tpu.solver.engine import solve as engine_solve

    p = Problem(M=16, N=16)
    ref = _solve(p, geometry=COMPOSITE)
    assert bool(ref.converged)
    w_ref = np.asarray(ref.w)
    assert w_ref.min() >= -1e-10  # discrete maximum principle

    for engine in ("pipelined", "mg-pcg"):
        res = engine_solve(
            # tpulint: disable=TPU001 — x64 is on (conftest)
            p, engine, jnp.float64, geometry=COMPOSITE
        )
        assert bool(res.converged), engine
        w = np.asarray(res.w)
        assert np.abs(w - w_ref).max() <= 5e-6, engine
        assert w.min() >= -1e-10, engine


def test_composite_sharded_1x2_parity():
    from poisson_ellipse_tpu.parallel.mesh import AXIS_X, AXIS_Y
    from poisson_ellipse_tpu.parallel.pcg_sharded import (
        build_sharded_solver,
    )

    p = Problem(M=16, N=16)
    mesh = jax.sharding.Mesh(
        np.asarray(jax.devices()[:2]).reshape(1, 2), (AXIS_X, AXIS_Y)
    )
    solver, args = build_sharded_solver(
        # tpulint: disable=TPU001 — x64 is on (conftest)
        p, mesh, jnp.float64, geometry=COMPOSITE
    )
    sharded = solver(*args)
    single = _solve(p, geometry=COMPOSITE)
    assert bool(sharded.converged)
    assert int(sharded.iters) == int(single.iters)
    np.testing.assert_allclose(
        np.asarray(sharded.w), np.asarray(single.w), rtol=0, atol=1e-12
    )


def test_mg_hierarchy_stays_m_matrix_under_composite_sdf():
    from poisson_ellipse_tpu.mg import coarsen
    from poisson_ellipse_tpu.ops.stencil import apply_a_block

    p = Problem(M=16, N=16)
    hier = coarsen.coefficient_hierarchy(p, geometry=COMPOSITE)
    assert len(hier) >= 2
    for lv in hier:
        M, N = lv["M"], lv["N"]
        a, b = lv["a"], lv["b"]
        # sign structure: faces non-negative everywhere, strictly
        # positive on the valid range (no conjured or lost conductance)
        assert a.min() >= 0.0 and b.min() >= 0.0
        assert a[1:M + 1, 1:N + 1].min() > 0.0
        assert b[1:M + 1, 1:N + 1].min() > 0.0
        # dense SPD pin per level (grids here are tiny)
        n = (M - 1) * (N - 1)
        A = np.zeros((n, n))
        for k in range(n):
            e = np.zeros((M + 1, N + 1))
            i, j = divmod(k, N - 1)
            e[i + 1, j + 1] = 1.0
            ae = np.pad(apply_a_block(e, a, b, lv["h1"], lv["h2"]), 1)
            A[:, k] = ae[1:M, 1:N].ravel()
        assert np.abs(A - A.T).max() <= 1e-9 * np.abs(A).max()
        off = A - np.diag(np.diag(A))
        assert off.max() <= 1e-12          # off-diagonals <= 0
        assert np.diag(A).min() > 0.0      # diagonal > 0
        assert np.linalg.eigvalsh((A + A.T) / 2).min() > 0.0


# -- the degenerate-cut defense ---------------------------------------------


def test_degenerate_cut_clamp_rescues_stalled_solve(tmp_path):
    from poisson_ellipse_tpu.obs import spectrum, trace as obs_trace

    p = Problem(M=40, N=40, eps=1e-6)
    comb = _crack_comb(p, 1e-3, [p.N // 2 + k for k in range(-8, 8, 2)])

    # the clamp is REPORTED: assembling with the defense on emits one
    # geom:degenerate-cut event, schema-valid
    sink = tmp_path / "trace.jsonl"
    obs_trace.start(str(sink))
    try:
        res_clamped, tr_clamped = _solve(
            p, geometry=comb, theta=1e-2, history=True
        )
    finally:
        obs_trace.stop()
    events = [json.loads(line) for line in sink.read_text().splitlines()]
    cuts = [e for e in events if e.get("name") == "geom:degenerate-cut"]
    assert cuts and cuts[0]["fields"]["to_full"] > 0
    assert all(obs_trace.validate_record(e) is None for e in events)

    res_stalled, tr_stalled = _solve(
        p, geometry=comb, theta=0.0, history=True
    )

    # unclamped, the (1-l/h)/eps rods measurably stall diag-PCG;
    # clamped, the solve converges at plain-ellipse-like counts to
    # metamorphic tolerance (maximum principle; both converge in f64)
    assert bool(res_clamped.converged)
    assert int(res_stalled.iters) >= 2 * int(res_clamped.iters)
    assert np.asarray(res_clamped.w).min() >= -1e-10

    # the kappa(M^-1 A) delta, surfaced through obs.spectrum exactly as
    # harness diagnose reports it
    rep_stalled = spectrum.spectrum_report(tr_stalled, p.delta)
    rep_clamped = spectrum.spectrum_report(tr_clamped, p.delta)
    assert rep_stalled["available"] and rep_clamped["available"]
    assert rep_stalled["kappa"] >= 3.0 * rep_clamped["kappa"]


def test_clamp_lengths_reports_counts():
    lengths = np.array([0.0, 1e-9, 0.5, 1.0 - 1e-9, 1.0])
    clamped, lo, hi = quadrature.clamp_lengths(lengths, 1.0, 1e-6)
    assert lo == 1 and hi == 1
    np.testing.assert_array_equal(clamped, [0.0, 0.0, 0.5, 1.0, 1.0])
    # theta=0 disables the defense entirely
    same, lo0, hi0 = quadrature.clamp_lengths(lengths, 1.0, 0.0)
    np.testing.assert_array_equal(same, lengths)
    assert lo0 == 0 and hi0 == 0


# -- serve admission + chaos ------------------------------------------------


def test_serve_rejects_bad_geometry_at_admission_never_mid_solve(tmp_path):
    from poisson_ellipse_tpu.serve.chaos import run_chaos

    rep = run_chaos(
        n_requests=8, seed=3, journal_path=str(tmp_path / "j.json"),
        kill_after=5, nan_request=None, oom_request=None,
        malformed_request=1, degenerate_request=2,
    )
    assert rep.ok  # zero lost / zero double / all classified
    assert rep.outcomes["chaos-0001"] == "invalid"
    assert rep.outcomes["chaos-0002"] == "completed"
    # zero lane poisoning: every OTHER request completed normally
    others = [
        out for rid, out in rep.outcomes.items()
        if rid not in ("chaos-0001", "chaos-0002")
    ]
    assert others and all(out == "completed" for out in others)


def test_serve_request_spec_roundtrips_geometry():
    from poisson_ellipse_tpu.serve.request import ServeRequest

    req = ServeRequest(
        problem=Problem(M=10, N=10),
        geometry=sdf.to_spec(COMPOSITE), theta=1e-5,
    )
    req.enqueued_t = 0.0
    spec = json.loads(json.dumps(req.spec()))
    back = ServeRequest.from_spec(spec, now=1.0)
    assert back.geometry == req.geometry
    assert back.theta == 1e-5
    assert back.geometry_sdf()(0.5, 0.0, np) < 0  # parses to a live SDF


def test_faultinject_sliver_spec_passes_gate_on_serve_grids():
    for M, N in ((8, 8), (10, 10), (12, 12)):
        rep = geom_validate.validate(
            Problem(M=M, N=N), faultinject.sliver_spec()
        )
        assert rep["ok"]


# -- fuzz -------------------------------------------------------------------


def test_fuzz_thirty_cases_all_invariants_hold():
    report = geom_fuzz.run_fuzz(n_cases=30, seed=0)
    # classification totality: every case accepted or classified
    assert len(report["details"]) == 30
    assert report["rejected"].get("malformed-spec", 0) == 5
    # the inadmissible corpus never leaks through the gate
    inadmissible = sum(
        v for k, v in report["rejected"].items() if k != "malformed-spec"
    )
    assert inadmissible >= 5
    assert report["accepted"] >= 10
    assert report["solved"] >= 3
    # the metamorphic checks ran (they raise on violation)
    assert any("refinement" in d for d in report["details"])
    assert any(d.get("guard") for d in report["details"])


def test_fuzz_is_seed_deterministic():
    a = geom_fuzz.run_fuzz(n_cases=12, seed=7, solve_budget=0)
    b = geom_fuzz.run_fuzz(n_cases=12, seed=7, solve_budget=0)
    assert a["details"] == b["details"]
    c = geom_fuzz.run_fuzz(n_cases=12, seed=8, solve_budget=0)
    assert c["details"] != a["details"]


# -- the exit-8 CLI contract ------------------------------------------------


def test_cli_exit_8_on_invalid_geometry(tmp_path, capsys):
    from poisson_ellipse_tpu.harness.__main__ import main

    bad = tmp_path / "bad.json"
    bad.write_text('{"kind": "blob"}')
    rc = main(["12", "12", "--mode", "single", "--engine", "xla",
               "--geometry", str(bad)])
    assert rc == EXIT_INVALID_GEOMETRY
    err = capsys.readouterr().err
    assert "invalid-geometry" in err

    # inline JSON that is not JSON at all: same classified exit
    rc = main(["12", "12", "--geometry", "{not json"])
    assert rc == EXIT_INVALID_GEOMETRY

    # empty-domain spec: gate fires before any build/dispatch
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps(sdf.to_spec(sdf.Intersection(
        sdf.Circle(cx=-0.5, r=0.1), sdf.Circle(cx=0.5, r=0.1)
    ))))
    rc = main(["12", "12", "--mode", "single", "--engine", "xla",
               "--geometry", str(empty)])
    assert rc == EXIT_INVALID_GEOMETRY


def test_cli_solves_valid_geometry_with_nan_l2(tmp_path, capsys):
    from poisson_ellipse_tpu.harness.__main__ import main

    good = tmp_path / "good.json"
    good.write_text(json.dumps(sdf.to_spec(COMPOSITE)))
    rc = main(["12", "12", "--dtype", "f64", "--mode", "single",
               "--engine", "xla", "--geometry", str(good), "--json"])
    assert rc == 0
    line = [
        ln for ln in capsys.readouterr().out.splitlines()
        if ln.startswith("{")
    ][-1]
    rec = json.loads(line)
    assert rec["converged"] is True
    # the analytic metric is ellipse-only: serialized null (strict-JSON
    # safe), never a literal NaN token
    assert rec["l2_error"] is None
