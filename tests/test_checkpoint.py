"""Checkpoint/resume: chunked == straight, kill-and-resume, tamper guard."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.solver.checkpoint import (
    CheckpointingSolver,
    solve_with_checkpoints,
)
from poisson_ellipse_tpu.solver.pcg import advance, init_state, pcg, result_of


def test_chunked_advance_is_bit_identical_to_straight():
    problem = Problem(M=20, N=20)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    straight = pcg(problem, a, b, rhs)

    state = init_state(problem, a, b, rhs)
    for limit in (7, 14, 21, 28, 100):
        state = advance(problem, a, b, rhs, state, limit=limit)
    chunked = result_of(state)

    assert int(chunked.iters) == int(straight.iters) == 26
    np.testing.assert_array_equal(
        np.asarray(chunked.w), np.asarray(straight.w)
    )


def test_solve_with_checkpoints_matches_straight(tmp_path):
    problem = Problem(M=20, N=20)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    # jitted straight run: the checkpointed path runs through jit too, and
    # jit-vs-eager differ at the ulp level (fusion), which is not the
    # property under test
    straight = jax.jit(lambda a, b, rhs: pcg(problem, a, b, rhs))(a, b, rhs)
    res = solve_with_checkpoints(
        problem, str(tmp_path / "ck"), chunk=5, dtype=jnp.float64
    )
    assert int(res.iters) == int(straight.iters)
    assert bool(res.converged)
    np.testing.assert_allclose(
        np.asarray(res.w), np.asarray(straight.w), rtol=1e-12, atol=1e-16
    )


def test_resume_continues_from_disk(tmp_path):
    problem = Problem(M=20, N=20)
    directory = str(tmp_path / "ck")

    # simulate a run killed mid-solve: advance one chunk, save, drop state
    with CheckpointingSolver(
        problem, directory, chunk=5, dtype=jnp.float64
    ) as s1:
        state = init_state(problem, s1._a, s1._b, s1._rhs)
        state = s1._advance(state, jnp.asarray(5, jnp.int32))
        s1._save(state)
        assert s1.latest_step() == 5

    with CheckpointingSolver(
        problem, directory, chunk=5, dtype=jnp.float64
    ) as s2:
        res = s2.run(resume=True)

    a, b, rhs = assembly.assemble(problem, jnp.float64)
    straight = jax.jit(lambda a, b, rhs: pcg(problem, a, b, rhs))(a, b, rhs)
    assert int(res.iters) == int(straight.iters)
    np.testing.assert_allclose(
        np.asarray(res.w), np.asarray(straight.w), rtol=1e-12, atol=1e-16
    )


def test_resume_false_ignores_checkpoints(tmp_path):
    problem = Problem(M=10, N=10)
    directory = str(tmp_path / "ck")
    solve_with_checkpoints(problem, directory, chunk=4, dtype=jnp.float64)
    res = solve_with_checkpoints(
        problem, directory, chunk=4, dtype=jnp.float64, resume=False
    )
    assert bool(res.converged) and int(res.iters) == 15


def test_mismatched_problem_is_refused(tmp_path):
    directory = str(tmp_path / "ck")
    solve_with_checkpoints(
        Problem(M=10, N=10), directory, chunk=4, dtype=jnp.float64
    )
    with pytest.raises(ValueError, match="different problem"):
        solve_with_checkpoints(
            Problem(M=12, N=10), directory, chunk=4, dtype=jnp.float64
        )


def test_bad_chunk_rejected(tmp_path):
    with pytest.raises(ValueError, match="chunk"):
        CheckpointingSolver(Problem(M=10, N=10), str(tmp_path), chunk=0)


def _full_mesh():
    from poisson_ellipse_tpu.parallel.mesh import make_mesh

    return make_mesh()  # 4x2 over the 8 virtual CPU devices (conftest)


def test_sharded_chunked_advance_matches_straight_run():
    from poisson_ellipse_tpu.parallel.pcg_sharded import (
        build_sharded_stepper,
        sharded_result_of,
        solve_sharded,
    )

    problem = Problem(M=40, N=40)
    mesh = _full_mesh()
    straight = solve_sharded(problem, mesh, dtype=jnp.float64)

    init_fn, advance_fn = build_sharded_stepper(
        problem, mesh, dtype=jnp.float64
    )
    state = init_fn()
    limit = 0
    while not (bool(state[6]) or bool(state[7])) and limit < 1000:
        limit += 13
        state = advance_fn(state, limit)
    chunked = sharded_result_of(problem, state)

    assert int(chunked.iters) == int(straight.iters) == 50
    assert bool(chunked.converged)
    np.testing.assert_allclose(
        np.asarray(chunked.w), np.asarray(straight.w), rtol=1e-12, atol=1e-16
    )


def test_sharded_checkpoint_kill_and_resume(tmp_path):
    from poisson_ellipse_tpu.parallel.pcg_sharded import solve_sharded

    problem = Problem(M=40, N=40)
    mesh = _full_mesh()
    directory = str(tmp_path / "ck")
    straight = solve_sharded(problem, mesh, dtype=jnp.float64)

    # simulate a run killed mid-solve: advance two chunks, save, drop state
    with CheckpointingSolver(
        problem, directory, chunk=8, dtype=jnp.float64, mesh=mesh
    ) as s1:
        state = s1._init()
        state = s1._advance(state, jnp.asarray(8, jnp.int32))
        s1._save(state)
        state = s1._advance(state, jnp.asarray(16, jnp.int32))
        s1._save(state)
        assert s1.latest_step() == 16

    with CheckpointingSolver(
        problem, directory, chunk=8, dtype=jnp.float64, mesh=mesh
    ) as s2:
        res = s2.run(resume=True)

    # iteration-count parity with the straight sharded run (the reference's
    # cross-implementation oracle, SURVEY §4.2) and matching solution
    assert int(res.iters) == int(straight.iters) == 50
    assert bool(res.converged)
    assert res.w.shape == straight.w.shape
    np.testing.assert_allclose(
        np.asarray(res.w), np.asarray(straight.w), rtol=1e-12, atol=1e-16
    )


def test_sharded_checkpoint_restores_shardings(tmp_path):
    problem = Problem(M=20, N=20)
    mesh = _full_mesh()
    directory = str(tmp_path / "ck")
    with CheckpointingSolver(
        problem, directory, chunk=6, dtype=jnp.float64, mesh=mesh
    ) as s1:
        state = s1._advance(s1._init(), jnp.asarray(6, jnp.int32))
        s1._save(state)
        want = state[1].sharding

    with CheckpointingSolver(
        problem, directory, chunk=6, dtype=jnp.float64, mesh=mesh
    ) as s2:
        restored = s2._restore(s2.latest_step())
    # w comes back device-laid-out over the mesh, not host-gathered
    assert restored[1].sharding.is_equivalent_to(want, restored[1].ndim)
    assert int(restored[0]) == 6


def test_mismatched_mesh_is_refused(tmp_path):
    import jax

    from poisson_ellipse_tpu.parallel.mesh import AXIS_X, AXIS_Y

    problem = Problem(M=20, N=20)
    directory = str(tmp_path / "ck")
    solve_with_checkpoints(
        problem, directory, chunk=6, dtype=jnp.float64, mesh=_full_mesh()
    )
    # a 2x2 sub-mesh changes shard padding and psum grouping -> refused
    sub = jax.sharding.Mesh(
        np.asarray(jax.devices()[:4]).reshape(2, 2), (AXIS_X, AXIS_Y)
    )
    with pytest.raises(ValueError, match="different problem"):
        solve_with_checkpoints(
            problem, directory, chunk=6, dtype=jnp.float64, mesh=sub
        )


def test_mismatched_stencil_is_refused(tmp_path):
    directory = str(tmp_path / "ck")
    solve_with_checkpoints(
        Problem(M=10, N=10), directory, chunk=4, dtype=jnp.float64
    )
    with pytest.raises(ValueError, match="different problem"):
        solve_with_checkpoints(
            Problem(M=10, N=10),
            directory,
            chunk=4,
            dtype=jnp.float64,
            stencil="pallas",
        )
