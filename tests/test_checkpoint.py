"""Checkpoint/resume: chunked == straight, kill-and-resume, tamper guard."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.solver.checkpoint import (
    CheckpointingSolver,
    solve_with_checkpoints,
)
from poisson_ellipse_tpu.solver.pcg import advance, init_state, pcg, result_of


def test_chunked_advance_is_bit_identical_to_straight():
    problem = Problem(M=20, N=20)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    straight = pcg(problem, a, b, rhs)

    state = init_state(problem, a, b, rhs)
    for limit in (7, 14, 21, 28, 100):
        state = advance(problem, a, b, rhs, state, limit=limit)
    chunked = result_of(state)

    assert int(chunked.iters) == int(straight.iters) == 26
    np.testing.assert_array_equal(
        np.asarray(chunked.w), np.asarray(straight.w)
    )


def test_solve_with_checkpoints_matches_straight(tmp_path):
    problem = Problem(M=20, N=20)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    # jitted straight run: the checkpointed path runs through jit too, and
    # jit-vs-eager differ at the ulp level (fusion), which is not the
    # property under test
    straight = jax.jit(lambda a, b, rhs: pcg(problem, a, b, rhs))(a, b, rhs)
    res = solve_with_checkpoints(
        problem, str(tmp_path / "ck"), chunk=5, dtype=jnp.float64
    )
    assert int(res.iters) == int(straight.iters)
    assert bool(res.converged)
    np.testing.assert_allclose(
        np.asarray(res.w), np.asarray(straight.w), rtol=1e-12, atol=1e-16
    )


def test_resume_continues_from_disk(tmp_path):
    problem = Problem(M=20, N=20)
    directory = str(tmp_path / "ck")

    # simulate a run killed mid-solve: advance one chunk, save, drop state
    with CheckpointingSolver(
        problem, directory, chunk=5, dtype=jnp.float64
    ) as s1:
        state = init_state(problem, s1._a, s1._b, s1._rhs)
        state = s1._advance(state, jnp.asarray(5, jnp.int32))
        s1._save(state)
        assert s1.latest_step() == 5

    with CheckpointingSolver(
        problem, directory, chunk=5, dtype=jnp.float64
    ) as s2:
        res = s2.run(resume=True)

    a, b, rhs = assembly.assemble(problem, jnp.float64)
    straight = jax.jit(lambda a, b, rhs: pcg(problem, a, b, rhs))(a, b, rhs)
    assert int(res.iters) == int(straight.iters)
    np.testing.assert_allclose(
        np.asarray(res.w), np.asarray(straight.w), rtol=1e-12, atol=1e-16
    )


def test_resume_false_ignores_checkpoints(tmp_path):
    problem = Problem(M=10, N=10)
    directory = str(tmp_path / "ck")
    solve_with_checkpoints(problem, directory, chunk=4, dtype=jnp.float64)
    res = solve_with_checkpoints(
        problem, directory, chunk=4, dtype=jnp.float64, resume=False
    )
    assert bool(res.converged) and int(res.iters) == 15


def test_mismatched_problem_is_refused(tmp_path):
    directory = str(tmp_path / "ck")
    solve_with_checkpoints(
        Problem(M=10, N=10), directory, chunk=4, dtype=jnp.float64
    )
    with pytest.raises(ValueError, match="different problem"):
        solve_with_checkpoints(
            Problem(M=12, N=10), directory, chunk=4, dtype=jnp.float64
        )


def test_bad_chunk_rejected(tmp_path):
    with pytest.raises(ValueError, match="chunk"):
        CheckpointingSolver(Problem(M=10, N=10), str(tmp_path), chunk=0)


def test_finalized_steps_carry_integrity_manifests(tmp_path):
    import json
    import os

    problem = Problem(M=20, N=20)
    directory = str(tmp_path / "ck")
    solve_with_checkpoints(problem, directory, chunk=5, dtype=jnp.float64)
    steps = [d for d in os.listdir(directory) if d.isdigit()]
    assert steps  # max_to_keep=2 retains the newest two
    for step in steps:
        path = os.path.join(directory, step, "integrity.json")
        assert os.path.exists(path), f"step {step} lacks its manifest"
        with open(path) as fh:
            manifest = json.load(fh)
        assert manifest  # and it fingerprints real files
        for rel, size in manifest.items():
            assert os.path.getsize(os.path.join(directory, step, rel)) == size


def test_truncated_latest_step_is_quarantined_and_previous_used(tmp_path):
    """The kill-during-write shape: the newest step's largest file is
    truncated; resume must quarantine it and continue from the previous
    step — converging at the straight run's exact count — instead of
    crashing mid-restore."""
    import os

    from poisson_ellipse_tpu.resilience import truncate_latest_checkpoint

    problem = Problem(M=20, N=20)
    directory = str(tmp_path / "ck")
    first = solve_with_checkpoints(
        problem, directory, chunk=5, dtype=jnp.float64
    )
    truncate_latest_checkpoint(directory)
    with pytest.warns(RuntimeWarning, match="quarantined"):
        res = solve_with_checkpoints(
            problem, directory, chunk=5, dtype=jnp.float64
        )
    assert bool(res.converged)
    assert int(res.iters) == int(first.iters) == 26
    names = os.listdir(directory)
    assert any(n.startswith("quarantined-") for n in names)


def test_corrupt_step_without_manifest_falls_back_via_restore_failure(
    tmp_path,
):
    """Pre-manifest checkpoints (or a kill before the manifest cadence):
    the orbax restore attempt itself is the integrity check, and its
    failure quarantines the step the same way."""
    import os

    from poisson_ellipse_tpu.resilience import truncate_latest_checkpoint

    problem = Problem(M=20, N=20)
    directory = str(tmp_path / "ck")
    first = solve_with_checkpoints(
        problem, directory, chunk=5, dtype=jnp.float64
    )
    steps = sorted(
        (d for d in os.listdir(directory) if d.isdigit()), key=int
    )
    os.remove(os.path.join(directory, steps[-1], "integrity.json"))
    truncate_latest_checkpoint(directory)
    with pytest.warns(RuntimeWarning, match="quarantined"):
        res = solve_with_checkpoints(
            problem, directory, chunk=5, dtype=jnp.float64
        )
    assert bool(res.converged) and int(res.iters) == int(first.iters)


def test_all_steps_corrupt_restarts_from_zero(tmp_path):
    import os

    from poisson_ellipse_tpu.resilience import truncate_latest_checkpoint

    problem = Problem(M=10, N=10)
    directory = str(tmp_path / "ck")
    first = solve_with_checkpoints(
        problem, directory, chunk=4, dtype=jnp.float64
    )
    # damage EVERY retained step before resuming once: nothing survives,
    # so the resume quarantines them all and restarts from iteration 0
    n_steps = len([d for d in os.listdir(directory) if d.isdigit()])
    for _ in range(n_steps):
        truncate_latest_checkpoint(directory)
        # each truncation hits the then-newest intact step: quarantining
        # is done by the resume below, so rename the damaged one out of
        # the way by marking its manifest stale is not needed — the
        # largest file of each remaining step is simply truncated too
        steps = sorted(
            (d for d in os.listdir(directory) if d.isdigit()), key=int
        )
        if steps:
            # truncate_latest_checkpoint always picks the newest; demote
            # it so the next pass damages the next one down
            src = os.path.join(directory, steps[-1])
            os.rename(src, os.path.join(directory, f"damaged-{steps[-1]}"))
    # restore the damaged dirs under their step names so resume sees them
    for name in list(os.listdir(directory)):
        if name.startswith("damaged-"):
            os.rename(
                os.path.join(directory, name),
                os.path.join(directory, name.removeprefix("damaged-")),
            )
    with pytest.warns(RuntimeWarning, match="quarantined"):
        res = solve_with_checkpoints(
            problem, directory, chunk=4, dtype=jnp.float64
        )
    assert bool(res.converged) and int(res.iters) == int(first.iters)
    quarantined = [
        n for n in os.listdir(directory) if n.startswith("quarantined-")
    ]
    assert len(quarantined) == n_steps


def _full_mesh():
    from poisson_ellipse_tpu.parallel.mesh import make_mesh

    return make_mesh()  # 4x2 over the 8 virtual CPU devices (conftest)


def test_sharded_chunked_advance_matches_straight_run():
    from poisson_ellipse_tpu.parallel.pcg_sharded import (
        build_sharded_stepper,
        sharded_result_of,
        solve_sharded,
    )

    problem = Problem(M=40, N=40)
    mesh = _full_mesh()
    straight = solve_sharded(problem, mesh, dtype=jnp.float64)

    init_fn, advance_fn = build_sharded_stepper(
        problem, mesh, dtype=jnp.float64
    )
    state = init_fn()
    limit = 0
    while not (bool(state[6]) or bool(state[7])) and limit < 1000:
        limit += 13
        state = advance_fn(state, limit)
    chunked = sharded_result_of(problem, state)

    assert int(chunked.iters) == int(straight.iters) == 50
    assert bool(chunked.converged)
    np.testing.assert_allclose(
        np.asarray(chunked.w), np.asarray(straight.w), rtol=1e-12, atol=1e-16
    )


def test_sharded_checkpoint_kill_and_resume(tmp_path):
    from poisson_ellipse_tpu.parallel.pcg_sharded import solve_sharded

    problem = Problem(M=40, N=40)
    mesh = _full_mesh()
    directory = str(tmp_path / "ck")
    straight = solve_sharded(problem, mesh, dtype=jnp.float64)

    # simulate a run killed mid-solve: advance two chunks, save, drop state
    with CheckpointingSolver(
        problem, directory, chunk=8, dtype=jnp.float64, mesh=mesh
    ) as s1:
        state = s1._init()
        state = s1._advance(state, jnp.asarray(8, jnp.int32))
        s1._save(state)
        state = s1._advance(state, jnp.asarray(16, jnp.int32))
        s1._save(state)
        assert s1.latest_step() == 16

    with CheckpointingSolver(
        problem, directory, chunk=8, dtype=jnp.float64, mesh=mesh
    ) as s2:
        res = s2.run(resume=True)

    # iteration-count parity with the straight sharded run (the reference's
    # cross-implementation oracle, SURVEY §4.2) and matching solution
    assert int(res.iters) == int(straight.iters) == 50
    assert bool(res.converged)
    assert res.w.shape == straight.w.shape
    np.testing.assert_allclose(
        np.asarray(res.w), np.asarray(straight.w), rtol=1e-12, atol=1e-16
    )


def test_sharded_checkpoint_restores_shardings(tmp_path):
    problem = Problem(M=20, N=20)
    mesh = _full_mesh()
    directory = str(tmp_path / "ck")
    with CheckpointingSolver(
        problem, directory, chunk=6, dtype=jnp.float64, mesh=mesh
    ) as s1:
        state = s1._advance(s1._init(), jnp.asarray(6, jnp.int32))
        s1._save(state)
        want = state[1].sharding

    with CheckpointingSolver(
        problem, directory, chunk=6, dtype=jnp.float64, mesh=mesh
    ) as s2:
        restored = s2._restore(s2.latest_step())
    # w comes back device-laid-out over the mesh, not host-gathered
    assert restored[1].sharding.is_equivalent_to(want, restored[1].ndim)
    assert int(restored[0]) == 6


def _mesh_of(n: int, px: int, py: int):
    import jax

    from poisson_ellipse_tpu.parallel.mesh import AXIS_X, AXIS_Y

    return jax.sharding.Mesh(
        np.asarray(jax.devices()[:n]).reshape(px, py), (AXIS_X, AXIS_Y)
    )


def test_save_on_2x2_resume_on_1x2_reshards_to_parity(tmp_path):
    """The elastic resume: a checkpoint written on a mesh that no longer
    exists (degraded-mesh recovery's defining situation) re-shards onto
    the survivors instead of refusing — save on 2×2, kill, resume on
    1×2, and converge at the uninterrupted run's count and solution
    (decomposition changes only psum reduction grouping)."""
    problem = Problem(M=40, N=40)
    directory = str(tmp_path / "ck")
    big = _mesh_of(4, 2, 2)
    small = _mesh_of(2, 1, 2)

    from poisson_ellipse_tpu.parallel.pcg_sharded import solve_sharded

    straight = solve_sharded(problem, small, dtype=jnp.float64)

    with CheckpointingSolver(
        problem, directory, chunk=8, dtype=jnp.float64, mesh=big
    ) as s1:
        state = s1._advance(s1._init(), jnp.asarray(16, jnp.int32))
        s1._save(state)
        assert s1.latest_step() == 16

    with CheckpointingSolver(
        problem, directory, chunk=8, dtype=jnp.float64, mesh=small
    ) as s2:
        res = s2.run(resume=True)
    assert bool(res.converged)
    assert int(res.iters) == int(straight.iters) == 50
    np.testing.assert_allclose(
        np.asarray(res.w), np.asarray(straight.w), rtol=1e-11, atol=1e-14
    )


def test_sharded_checkpoint_resumes_on_single_chip(tmp_path):
    """The degenerate reshard: a sharded checkpoint wakes up with no
    mesh at all and finishes single-chip."""
    problem = Problem(M=20, N=20)
    directory = str(tmp_path / "ck")
    with CheckpointingSolver(
        problem, directory, chunk=6, dtype=jnp.float64, mesh=_mesh_of(4, 2, 2)
    ) as s1:
        state = s1._advance(s1._init(), jnp.asarray(6, jnp.int32))
        s1._save(state)

    a, b, rhs = assembly.assemble(problem, jnp.float64)
    straight = jax.jit(lambda a, b, rhs: pcg(problem, a, b, rhs))(a, b, rhs)
    with CheckpointingSolver(
        problem, directory, chunk=6, dtype=jnp.float64
    ) as s2:
        res = s2.run(resume=True)
    assert bool(res.converged)
    assert int(res.iters) == int(straight.iters)
    np.testing.assert_allclose(
        np.asarray(res.w), np.asarray(straight.w), rtol=1e-11, atol=1e-14
    )


def test_mismatched_stencil_is_refused(tmp_path):
    directory = str(tmp_path / "ck")
    solve_with_checkpoints(
        Problem(M=10, N=10), directory, chunk=4, dtype=jnp.float64
    )
    with pytest.raises(ValueError, match="different problem"):
        solve_with_checkpoints(
            Problem(M=10, N=10),
            directory,
            chunk=4,
            dtype=jnp.float64,
            stencil="pallas",
        )
