"""The memory-bandwidth-frontier battery: s-step CG + bf16 storage.

Covers the two new axes end to end:

- s-step parity: exact f64 oracle counts, the 400×600 f32 headline at
  EXACT classical parity (the f64-Gram accumulator fact —
  ``ops.sstep_pcg.gram_dtype``), sharded 1×2/2×2 parity, and the
  chunk-limit contract.
- the collective-cadence pins: ONE stacked psum + one 4-ppermute deep
  halo round per s iterations, abft on/off byte-identical, vs the
  classical 2-psum body — read from the jaxpr via ``obs.static_cost``.
- the storage axis: ``storage_dtype=None`` traces the byte-identical
  pre-storage jaxpr (pinned), the modeled HBM bytes halve under bf16,
  raw narrow engines converge to the storage floor, and the GUARD's
  storage-promotion rung recovers f32-level l2 on every loop engine.
- composition: streamed/xl operand narrowing, batched lanes, the warm
  pool's storage-keyed executables, harness reports and CLI flags.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.precision import (
    replace_every,
    resolve_storage_dtype,
    storage_itemsize,
)
from poisson_ellipse_tpu.ops.pipelined_pcg import pcg_pipelined
from poisson_ellipse_tpu.ops.sstep_pcg import (
    SSTEP_CHOICES,
    advance as sstep_advance,
    init_state as sstep_init,
    pcg_sstep,
)
from poisson_ellipse_tpu.solver.pcg import pcg
from poisson_ellipse_tpu.solver.engine import (
    ENGINES,
    SSTEP_ENGINES,
    STORAGE_ENGINES,
    build_solver,
    solve,
)

WEIGHTED_ORACLE = {(10, 10): 15, (20, 20): 26, (40, 40): 50}


def _mesh(shape):
    n = shape[0] * shape[1]
    return Mesh(np.asarray(jax.devices()[:n]).reshape(shape), ("x", "y"))


def _operands(problem, dtype=jnp.float32):
    return assembly.assemble(problem, dtype)


# -- registry / validation ---------------------------------------------------


def test_engine_registry_carries_the_new_axes():
    assert "sstep" in ENGINES and "sstep-pallas" in ENGINES
    assert set(SSTEP_ENGINES) <= set(ENGINES)
    assert "sstep" in STORAGE_ENGINES and "xla" in STORAGE_ENGINES
    # the identity request normalises away; widening is refused
    assert resolve_storage_dtype("f32", jnp.float32) is None
    assert resolve_storage_dtype(None, jnp.float32) is None
    assert resolve_storage_dtype("bf16", jnp.float32) == jnp.dtype(
        jnp.bfloat16
    )
    with pytest.raises(ValueError, match="wider"):
        resolve_storage_dtype("f32", jnp.bfloat16)
    with pytest.raises(ValueError, match="unknown storage dtype"):
        resolve_storage_dtype("nonsense", jnp.float32)
    with pytest.raises(ValueError, match="floating"):
        resolve_storage_dtype("int8", jnp.float32)


def test_build_solver_validates_the_new_axes():
    problem = Problem(M=10, N=10)
    with pytest.raises(ValueError, match="no storage-dtype form"):
        build_solver(problem, "resident", storage_dtype="bf16")
    with pytest.raises(ValueError, match="history"):
        build_solver(problem, "sstep", history=True)
    with pytest.raises(ValueError, match="s must be one of"):
        pcg_sstep(problem, *_operands(problem), s=3)
    # the cadence tightens under sub-compute storage and divides both s
    assert replace_every(None) == 32 and replace_every(jnp.bfloat16) == 8
    for s in SSTEP_CHOICES:
        assert replace_every(None) % s == 0
        assert replace_every(jnp.bfloat16) % s == 0


def test_storage_none_traces_the_identical_jaxpr():
    """The storage axis at None is byte-identical to the pre-storage
    code: same jaxpr for classical AND pipelined — the declared
    ``storage-identity`` contract (expectations from ENGINE_CAPS)."""
    from poisson_ellipse_tpu.analysis.contracts import assert_contract

    problem = Problem(M=20, N=20)
    assert_contract("storage-identity", "xla", problem=problem)
    assert_contract("storage-identity", "pipelined", problem=problem)


# -- s-step parity -----------------------------------------------------------


@pytest.mark.parametrize("s", SSTEP_CHOICES)
@pytest.mark.parametrize("grid", sorted(WEIGHTED_ORACLE))
def test_sstep_f64_oracle_parity(grid, s):
    """f64: exact classical-oracle iteration counts, both block sizes."""
    problem = Problem(M=grid[0], N=grid[1])
    a, b, rhs = _operands(problem, jnp.float64)
    r = pcg_sstep(problem, a, b, rhs, s=s)
    assert bool(r.converged)
    assert int(r.iters) == WEIGHTED_ORACLE[grid]


@pytest.mark.parametrize("s", SSTEP_CHOICES)
def test_sstep_headline_grid_f32_exact_parity(s):
    """400×600 f32: the published 546-iteration oracle, EXACTLY — the
    measured f64-Gram-accumulator fact (an f32 Gram loses it: 773)."""
    problem = Problem(M=400, N=600)
    a, b, rhs = _operands(problem)
    r = pcg_sstep(problem, a, b, rhs, s=s)
    assert bool(r.converged)
    assert int(r.iters) == 546


@pytest.mark.slow
def test_sstep_800x1200_f32_parity_within_replacement_band():
    """The second acceptance grid (slow: ~2000 iterations on CPU):
    iteration count within ±2 per replacement of the 989 oracle."""
    problem = Problem(M=800, N=1200)
    a, b, rhs = _operands(problem)
    r = pcg_sstep(problem, a, b, rhs, s=4)
    band = 2 * (989 // replace_every(None) + 1)
    assert bool(r.converged)
    assert abs(int(r.iters) - 989) <= band


def test_sstep_chunked_advance_honours_limit_exactly():
    """A chunk limit mid-block stops at EXACTLY that iteration (the
    guard/fault-injection contract) and the chunked run converges at
    the straight run's count (iteration-equivalence; the mid-block
    basis re-anchor is documented as not bitwise)."""
    problem = Problem(M=40, N=40)
    a, b, rhs = _operands(problem)
    straight = pcg_sstep(problem, a, b, rhs, s=4)
    state = sstep_init(problem, a, b, rhs)
    for limit in (13, 26, 39, problem.max_iterations):
        state = sstep_advance(problem, a, b, rhs, state, s=4, limit=limit)
        assert int(state[0]) <= max(limit, int(straight.iters))
        if not bool(state[6]):
            assert int(state[0]) == limit  # exact stop, not block-rounded
    assert bool(state[6])
    assert int(state[0]) == int(straight.iters)


@pytest.mark.parametrize("mesh_shape", [(1, 2), (2, 2)])
def test_sstep_sharded_matches_single_chip(mesh_shape):
    from poisson_ellipse_tpu.parallel.sstep_sharded import (
        solve_sstep_sharded,
    )

    problem = Problem(M=40, N=40)
    a, b, rhs = _operands(problem)
    single = pcg(problem, a, b, rhs)
    r = solve_sstep_sharded(problem, _mesh(mesh_shape), jnp.float32, s=4)
    assert bool(r.converged)
    assert abs(int(r.iters) - int(single.iters)) <= 2
    rel = np.linalg.norm(np.asarray(r.w) - np.asarray(single.w)) / (
        np.linalg.norm(np.asarray(single.w))
    )
    assert rel < 5e-3


# -- the collective-cadence pins --------------------------------------------


@pytest.mark.parametrize("s", SSTEP_CHOICES)
def test_sstep_sharded_pins_one_psum_per_s_iterations(s):
    """THE acceptance pin, as declared contracts: the sharded s-step
    while body holds exactly 1 psum and 4 ppermutes — per body = per s
    iterations — abft on and off byte-identical, vs the classical
    body's 2 psums. Expectations derive from ENGINE_CAPS; the exact
    (1, 4) cadence is re-pinned on the results."""
    from poisson_ellipse_tpu.analysis.contracts import assert_contract
    from poisson_ellipse_tpu.obs.static_cost import iters_per_loop_body

    problem = Problem(M=40, N=40)
    r = assert_contract(
        "collective-cadence", "sstep", problem=problem,
        mesh_shape=(1, 2), sstep_s=s,
    )
    assert r.expected == {"psum": 1, "ppermute": 4}
    assert iters_per_loop_body("sstep", s) == s
    # the stepper form, abft on == off, at the same (1, 4) cadence
    ra = assert_contract(
        "abft-identity", "sstep", problem=problem, mesh_shape=(1, 2),
        sstep_s=s,
    )
    assert ra.actual == {"off": (1, 4), "on": (1, 4)}, ra.actual
    rc = assert_contract(
        "collective-cadence", "xla", problem=problem, mesh_shape=(1, 2)
    )
    assert rc.expected["psum"] == 2


def test_engine_report_divides_body_counts_per_iteration():
    from poisson_ellipse_tpu.obs.static_cost import engine_report

    rep = engine_report(
        Problem(M=40, N=40), "sstep", mode="sharded", mesh_shape=(1, 2),
        with_xla_cost=False, sstep_s=4,
    )
    assert rep["iters_per_body"] == 4
    assert rep["psum_per_body"] == 1
    assert rep["ppermute_per_body"] == 4
    assert rep["psum_per_iter"] == pytest.approx(0.25)


# -- the storage axis --------------------------------------------------------


def test_modeled_bytes_halve_under_bf16():
    """The modeled-byte acceptance: every loop engine's bf16 bill sits
    at ~half the f32 bill and inside the ≤0.6× gate. The classical loop
    is exactly 0.5×; the recurrence engines carry the extra rebuild
    passes of their TIGHTENED replacement cadence (32 → 8 under bf16) in
    the narrow model, so their ratio sits slightly above 0.5 — the model
    tells the truth about the narrow build, not the optimistic half."""
    from poisson_ellipse_tpu.harness.roofline import (
        modeled_hbm_bytes_per_iter,
    )

    problem = Problem(M=400, N=600)
    for engine in ("xla", "pipelined", "sstep"):
        full = modeled_hbm_bytes_per_iter(problem, engine, jnp.float32)
        narrow = modeled_hbm_bytes_per_iter(
            problem, engine, jnp.float32, storage_dtype="bf16"
        )
        ratio = narrow / full
        assert 0.45 <= ratio <= 0.6, (engine, ratio)
    xla_full = modeled_hbm_bytes_per_iter(problem, "xla", jnp.float32)
    xla_narrow = modeled_hbm_bytes_per_iter(
        problem, "xla", jnp.float32, storage_dtype="bf16"
    )
    assert xla_narrow / xla_full == pytest.approx(0.5)
    assert storage_itemsize(jnp.float32, "bf16") == 2
    assert storage_itemsize(jnp.float32) == 4


@pytest.mark.parametrize("engine", ["xla", "pipelined", "sstep"])
def test_guarded_bf16_recovers_f32_l2_parity(engine):
    """The accuracy-recovered-not-hoped acceptance: the guard's
    storage-promotion rung finishes every narrow solve at full width,
    landing within a tight band of the f32 solution's analytic error."""
    from poisson_ellipse_tpu.resilience.guard import guarded_solve
    from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic

    problem = Problem(M=40, N=40)
    ref = solve(problem, "xla", jnp.float32)
    ref_l2 = float(l2_error_vs_analytic(problem, ref.w))
    g = guarded_solve(
        problem, engine, jnp.float32, storage_dtype="bf16", chunk=64
    )
    assert bool(g.result.converged)
    got_l2 = float(
        l2_error_vs_analytic(problem, g.result.w.astype(jnp.float32))
    )
    assert got_l2 <= 1.05 * ref_l2, (engine, got_l2, ref_l2)
    kinds = [e.kind for e in g.recoveries]
    # the promotion rung fired (directly, or as the escalation rung
    # after a restart — both spellings are the designed ladder)
    assert "storage-promotion" in kinds or "precision-escalation" in kinds


def test_raw_bf16_classical_converges_and_carries_bf16_state():
    problem = Problem(M=40, N=40)
    a, b, rhs = _operands(problem)
    r = pcg(problem, a, b, rhs, storage_dtype="bf16")
    assert r.w.dtype == jnp.bfloat16
    assert bool(r.converged)
    # the raw narrow engine's answer sits at the storage floor — close
    # to, but NOT at, f32 accuracy (which is the guard's job)
    ref = pcg(problem, a, b, rhs)
    rel = float(
        jnp.linalg.norm(r.w.astype(jnp.float32) - ref.w)
        / jnp.linalg.norm(ref.w)
    )
    assert rel < 0.05


def test_streamed_and_xl_narrow_operand_streams():
    """streamed/xl: bf16 operand streaming converges at the f32 cell's
    iteration count (the operator rounds once; state stays full-width)."""
    from poisson_ellipse_tpu.ops.streamed_pcg import build_streamed_solver
    from poisson_ellipse_tpu.ops.xl_pcg import build_xl_solver

    problem = Problem(M=20, N=20)
    for build in (build_streamed_solver, build_xl_solver):
        s_full, a_full = build(problem, jnp.float32, interpret=True)
        r_full = s_full(*a_full)
        s_bf, a_bf = build(
            problem, jnp.float32, interpret=True, storage_dtype="bf16"
        )
        assert a_bf[0].dtype == jnp.bfloat16  # dinv streams narrow
        assert a_bf[3].dtype == jnp.float32   # r0 stays compute-width
        r_bf = s_bf(*a_bf)
        assert bool(r_bf.converged)
        assert int(r_bf.iters) == int(r_full.iters)
        rel = float(
            jnp.linalg.norm(r_bf.w - r_full.w) / jnp.linalg.norm(r_full.w)
        )
        assert rel < 5e-3


def test_batched_lanes_compose_with_bf16_storage():
    from poisson_ellipse_tpu.batch.batched_pcg import pcg_batched

    problem = Problem(M=20, N=20)
    a, b, rhs = _operands(problem)
    stacked = jnp.stack([rhs, rhs * 1.5, rhs * 0.5])
    r = pcg_batched(problem, a, b, stacked, storage_dtype="bf16")
    assert r.w.dtype == jnp.bfloat16
    assert bool(jnp.all(r.converged))
    assert not bool(jnp.any(r.quarantined))
    # linearity spot-check at the storage floor: lane 1 ≈ 1.5 × lane 0
    w0 = np.asarray(r.w[0].astype(jnp.float32))
    w1 = np.asarray(r.w[1].astype(jnp.float32))
    assert np.linalg.norm(w1 - 1.5 * w0) / np.linalg.norm(w1) < 0.05


def test_warm_pool_keys_on_storage_dtype():
    from poisson_ellipse_tpu.runtime.compile_cache import WarmPool

    pool = WarmPool()
    full = pool.warmup("batched", (10, 10), lanes=2)
    again = pool.warmup("batched", (10, 10), lanes=2)
    narrow = pool.warmup("batched", (10, 10), lanes=2,
                         storage_dtype="bf16")
    assert again.compiled is full.compiled  # the hit-identity contract
    assert narrow.compiled is not full.compiled
    assert narrow.storage == "bfloat16" and full.storage == ""
    assert pool.hits == 1 and pool.misses == 2


def test_sstep_bf16_sharded_ships_narrow_state():
    """The sharded composition of BOTH axes: bf16 blocks through the
    (s+1)-deep exchange, converging to the storage floor with the
    cadence pin intact."""
    from poisson_ellipse_tpu.obs.static_cost import loop_collectives
    from poisson_ellipse_tpu.parallel.sstep_sharded import (
        build_sstep_sharded_stepper,
    )

    problem = Problem(M=40, N=40)
    mesh = _mesh((1, 2))
    init, adv = build_sstep_sharded_stepper(
        problem, mesh, jnp.float32, s=4, storage_dtype="bf16"
    )
    state = init()
    assert state[1].dtype == jnp.bfloat16
    assert loop_collectives(lambda st: adv(st, 100), (state,)) == (1, 4)
    out = adv(state, problem.max_iterations)
    # the raw narrow run reaches the storage floor and stays finite —
    # full-width finishing is the guard's promotion rung
    assert float(out[5]) < 1e-3
    assert bool(jnp.all(jnp.isfinite(out[1].astype(jnp.float32))))


# -- harness surfaces --------------------------------------------------------


def test_run_once_sstep_and_storage_reports():
    from poisson_ellipse_tpu.harness.run import run_once

    problem = Problem(M=20, N=20)
    rep = run_once(problem, mode="single", engine="sstep")
    assert rep.engine == "sstep" and rep.converged
    assert rep.json_dict()["engine"] == "sstep"
    guarded = run_once(
        problem, mode="single", engine="xla", guard=True,
        storage_dtype="bf16",
    )
    assert guarded.converged
    assert guarded.storage_dtype == "bf16"
    assert guarded.json_dict()["storage_dtype"] == "bf16"
    assert "storage bf16" in guarded.summary()
    with pytest.raises(ValueError, match="storage"):
        run_once(problem, mode="sharded", engine="xla",
                 mesh_shape=(1, 2), storage_dtype="bf16")


def test_harness_inspect_cli_reports_sstep_cadence(capsys):
    from poisson_ellipse_tpu.harness.__main__ import main as harness_main

    rc = harness_main([
        "inspect", "sstep", "--mode", "sharded", "--mesh", "1", "2",
        "--grid", "20x20", "--no-xla-cost",
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "per while-body (4 iters): 1 psum, 4 ppermute" in out
    rc = harness_main([
        "inspect", "sstep", "--grid", "20x20", "--no-xla-cost",
        "--storage-dtype", "bf16",
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "storage bfloat16" in out
