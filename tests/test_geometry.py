"""Unit tests for the ellipse geometry (reference L0) against an independent
scalar re-derivation of the closed forms in stage0/Withoutopenmp1.cpp:19-39."""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.models import ellipse


def seg_len_vertical_scalar(x0, ys, ye):
    if abs(x0) >= 1.0:
        return 0.0
    ym = math.sqrt(max(0.0, (1.0 - x0 * x0) / 4.0))
    return max(0.0, min(ye, ym) - max(ys, -ym))


def seg_len_horizontal_scalar(y0, xs, xe):
    if abs(2.0 * y0) >= 1.0:
        return 0.0
    xm = math.sqrt(max(0.0, 1.0 - 4.0 * y0 * y0))
    return max(0.0, min(xe, xm) - max(xs, -xm))


def test_membership_basic():
    assert bool(ellipse.is_in_d(jnp.float64(0.0), jnp.float64(0.0)))
    assert not bool(ellipse.is_in_d(jnp.float64(1.0), jnp.float64(0.0)))
    assert not bool(ellipse.is_in_d(jnp.float64(0.0), jnp.float64(0.5)))
    assert bool(ellipse.is_in_d(jnp.float64(0.9), jnp.float64(0.0)))


def test_segment_lengths_match_closed_form():
    rng = np.random.default_rng(0)
    const = rng.uniform(-1.3, 1.3, size=200)
    starts = rng.uniform(-1.3, 1.3, size=200)
    lens = rng.uniform(0.0, 0.7, size=200)
    ends = starts + lens

    got_v = np.asarray(
        ellipse.segment_length_vertical(
            jnp.asarray(const), jnp.asarray(starts), jnp.asarray(ends)
        )
    )
    got_h = np.asarray(
        ellipse.segment_length_horizontal(
            jnp.asarray(const), jnp.asarray(starts), jnp.asarray(ends)
        )
    )
    want_v = [seg_len_vertical_scalar(c, s, e) for c, s, e in zip(const, starts, ends)]
    want_h = [
        seg_len_horizontal_scalar(c, s, e) for c, s, e in zip(const, starts, ends)
    ]
    np.testing.assert_allclose(got_v, want_v, rtol=0, atol=1e-14)
    np.testing.assert_allclose(got_h, want_h, rtol=0, atol=1e-14)


def test_segment_length_bounds():
    rng = np.random.default_rng(1)
    const = rng.uniform(-1.5, 1.5, size=500)
    starts = rng.uniform(-1.5, 1.5, size=500)
    ends = starts + rng.uniform(0.0, 1.0, size=500)
    for fn in (ellipse.segment_length_vertical, ellipse.segment_length_horizontal):
        lengths = np.asarray(fn(jnp.asarray(const), jnp.asarray(starts), jnp.asarray(ends)))
        assert (lengths >= 0).all()
        assert (lengths <= (ends - starts) + 1e-15).all()


def test_analytic_solution_zero_on_boundary():
    theta = np.linspace(0, 2 * np.pi, 64)
    x, y = np.cos(theta), 0.5 * np.sin(theta)
    vals = np.asarray(ellipse.analytic_solution(jnp.asarray(x), jnp.asarray(y)))
    np.testing.assert_allclose(vals, 0.0, atol=1e-15)


def test_analytic_solution_satisfies_pde():
    # -Δu = 1 for u = (1 - x² - 4y²)/10: u_xx = -0.2, u_yy = -0.8.
    x = jnp.asarray([0.1, -0.3])
    y = jnp.asarray([0.05, 0.2])
    h = 1e-5
    u = ellipse.analytic_solution
    lap = (
        u(x + h, y) + u(x - h, y) - 2 * u(x, y)
    ) / h**2 + (u(x, y + h) + u(x, y - h) - 2 * u(x, y)) / h**2
    np.testing.assert_allclose(np.asarray(-lap), 1.0, rtol=1e-4)
