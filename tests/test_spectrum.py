"""obs/spectrum: Lanczos-from-CG spectral estimates, oracle-pinned.

The two load-bearing claims, each against an independent oracle:

- **κ is real**: on a small grid the Lanczos κ estimate from a solve's
  recorded α/β must match the directly computed κ(M⁻¹A) — a dense
  eigendecomposition of the preconditioned operator assembled column by
  column through the production ``apply_a`` — within 10% (measured:
  agreement to f64 round-off once the solve runs enough iterations).
- **κ explains the iteration counts**: on the published grids the
  Ritz-model iteration prediction lands within ±15% of the oracle
  counts (546 @ 400×600, 989 @ 800×1200), the κ bound is a true upper
  envelope, and κ grows with the grid the way the measured iteration
  growth says it must (iters ∝ √κ).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import spectrum as obs_spectrum
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.stencil import apply_a, diag_d
from poisson_ellipse_tpu.solver.engine import solve as engine_solve
from poisson_ellipse_tpu.solver.pcg import pcg


def dense_preconditioned_kappa(problem: Problem) -> float:
    """The oracle: κ of D^{-1/2} A D^{-1/2} from a dense assembly of the
    production operator (unit-vector columns through ``apply_a``),
    restricted to the interior nodes the CG iteration actually moves
    (boundary rows are identically zero) with the zero-padding nullspace
    dropped."""
    dtype = jnp.float64
    a, b, _ = assembly.assemble(problem, dtype)
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    d = np.asarray(diag_d(a, b, h1, h2)).ravel()
    g1, g2 = problem.node_shape
    n = g1 * g2
    op = jax.jit(lambda u: apply_a(u, a, b, h1, h2))
    eye = np.eye(n)
    cols = [
        np.asarray(op(jnp.asarray(eye[:, i].reshape(g1, g2), dtype))).ravel()
        for i in range(n)
    ]
    dense = np.stack(cols, axis=1)
    interior = np.abs(np.diag(dense)) > 0
    sub = dense[np.ix_(interior, interior)]
    scale = np.sqrt(d[interior])
    sym = sub / scale[:, None] / scale[None, :]
    ev = np.linalg.eigvalsh((sym + sym.T) / 2.0)
    ev = ev[ev > 1e-12 * ev.max()]
    return float(ev.max() / ev.min())


# ------------------------------------------------------ kappa vs oracle


@pytest.mark.parametrize("grid", [(16, 16), (24, 24)])
def test_kappa_matches_dense_oracle_within_10pct(grid):
    # delta small enough that the Lanczos process resolves both spectrum
    # edges before the solve stops (the converged-tolerance trace at
    # 1e-6 is already within a few percent; 1e-10 pins it tight — the
    # solve may end in a round-off-floor breakdown down there, whose
    # terminal alpha-0 entry the reconstruction skips by contract)
    problem = Problem(M=grid[0], N=grid[1], delta=1e-10)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    result, trace = pcg(problem, a, b, rhs, history=True)
    assert int(result.iters) > 20  # enough Lanczos steps to resolve edges
    rep = obs_spectrum.spectrum_report(trace, delta=problem.delta)
    assert rep["available"]
    oracle = dense_preconditioned_kappa(problem)
    assert rep["kappa"] == pytest.approx(oracle, rel=0.10)
    # with this much trace the agreement is actually round-off-tight
    assert rep["kappa"] == pytest.approx(oracle, rel=1e-6)
    ritz = obs_spectrum.ritz_values(trace)
    assert ritz.size and (ritz > 0).all()
    assert float(ritz[-1] / ritz[0]) == pytest.approx(rep["kappa"], rel=1e-9)


def test_kappa_close_even_from_converged_tolerance_trace():
    # the production delta (1e-6) stops earlier; the estimate must still
    # land within the acceptance band — this is what diagnose/bench see
    problem = Problem(M=16, N=16)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    _, trace = pcg(problem, a, b, rhs, history=True)
    rep = obs_spectrum.spectrum_report(trace, delta=problem.delta)
    assert rep["kappa"] == pytest.approx(
        dense_preconditioned_kappa(problem), rel=0.10
    )


def test_f32_trace_reconstruction_agrees_with_f64():
    # the recorded coefficients are f32 on the production path; the
    # reconstruction must not need f64 recording to be usable
    problem = Problem(M=20, N=20)
    _, tr32 = engine_solve(problem, "xla", jnp.float32, history=True)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    _, tr64 = pcg(problem, a, b, rhs, history=True)
    k32 = obs_spectrum.spectrum_report(tr32, delta=problem.delta)["kappa"]
    k64 = obs_spectrum.spectrum_report(tr64, delta=problem.delta)["kappa"]
    assert k32 == pytest.approx(k64, rel=5e-3)


def test_pipelined_trace_yields_the_same_spectrum():
    # the pipelined recurrence is a documented reordering: its recorded
    # alpha/beta drive the same operator's Lanczos matrix
    problem = Problem(M=20, N=20)
    _, classical = engine_solve(problem, "xla", jnp.float64, history=True)
    _, pipelined = engine_solve(
        problem, "pipelined", jnp.float64, history=True
    )
    kc = obs_spectrum.spectrum_report(classical, delta=problem.delta)["kappa"]
    kp = obs_spectrum.spectrum_report(pipelined, delta=problem.delta)["kappa"]
    assert kp == pytest.approx(kc, rel=1e-2)


# ------------------------------------------- prediction vs oracle counts


@pytest.mark.parametrize(
    "grid,oracle", [((400, 600), 546), ((800, 1200), 989)]
)
def test_predicted_iterations_within_15pct_on_published_grids(grid, oracle):
    problem = Problem(M=grid[0], N=grid[1])
    result, trace = engine_solve(problem, "xla", jnp.float32, history=True)
    assert bool(result.converged) and int(result.iters) == oracle
    rep = obs_spectrum.spectrum_report(
        trace, delta=problem.delta, actual_iters=oracle
    )
    assert rep["available"]
    # the sharp prediction: the Ritz model replays the solve's own
    # spectral measure (measured exact here; ±15% is the contract)
    assert rep["predicted_iters"] == pytest.approx(oracle, rel=0.15)
    # the kappa bound is a true upper envelope: never below the actual
    assert rep["iters_bound"] >= oracle
    # a converged healthy run shows no plateau
    assert rep["plateaus"] == [] and not rep["stagnated"]


def test_kappa_growth_tracks_iteration_growth_across_grids():
    # iters ~ sqrt(kappa): the 20x20 -> 40x40 iteration ratio must match
    # sqrt of the kappa ratio within 25% — the "observed iteration
    # growth" cross-validation of the estimator
    reps = {}
    iters = {}
    for m in (20, 40):
        problem = Problem(M=m, N=m)
        a, b, rhs = assembly.assemble(problem, jnp.float64)
        result, trace = pcg(problem, a, b, rhs, history=True)
        reps[m] = obs_spectrum.spectrum_report(trace, delta=problem.delta)
        iters[m] = int(result.iters)
    assert reps[40]["kappa"] > reps[20]["kappa"]
    growth = iters[40] / iters[20]
    predicted_growth = (reps[40]["kappa"] / reps[20]["kappa"]) ** 0.5
    assert growth == pytest.approx(predicted_growth, rel=0.25)


# ------------------------------------------------------- trace hygiene


def test_breakdown_alpha_zero_entries_are_skipped():
    # a breakdown iteration records alpha = 0 (obs.convergence contract);
    # the reconstruction must drop it instead of dividing by it
    problem = Problem(M=10, N=10)
    _, _, rhs = assembly.assemble(problem, jnp.float64)
    zeros = jnp.zeros_like(rhs)
    result, trace = pcg(problem, zeros, zeros, rhs, history=True)
    assert bool(result.breakdown)
    alpha, beta = obs_spectrum.cg_coefficients(trace)
    assert alpha.size == 0  # the only iteration broke down
    rep = obs_spectrum.spectrum_report(trace, delta=problem.delta)
    assert rep["available"] is False


def test_poisoned_tail_is_truncated_not_propagated():
    tr = {
        "alpha": np.array([0.5, 0.4, np.nan, 0.3]),
        "beta": np.array([0.9, 0.8, 0.7, 0.6]),
        "diff": np.array([1e-1, 1e-2, 1e-3, 1e-4]),
        "zr": np.ones(4),
    }
    alpha, beta = obs_spectrum.cg_coefficients(tr)
    assert list(alpha) == [0.5, 0.4]
    d, e = obs_spectrum.lanczos_tridiagonal(tr)
    assert d.size == 2 and e.size == 1 and np.isfinite(d).all()


def test_empty_trace_reports_unavailable():
    tr = {k: np.empty(0) for k in ("alpha", "beta", "diff", "zr")}
    rep = obs_spectrum.spectrum_report(tr, delta=1e-6)
    assert rep == {"available": False, "iters": 0, "lanczos_m": 0}
    assert obs_spectrum.ritz_values(tr).size == 0
    assert obs_spectrum.predicted_iterations(tr, 1e-6) is None


def test_detect_plateaus_flags_stalls_not_progress():
    healthy = 1e-1 * (0.9 ** np.arange(200))
    assert obs_spectrum.detect_plateaus(healthy) == []
    # non-monotone wiggle on a converging run is healthy too (the f32
    # trace shape): the running-min stance must not flag it
    rng = np.random.default_rng(0)
    noisy = healthy * np.exp(0.3 * rng.standard_normal(200))
    assert obs_spectrum.detect_plateaus(noisy) == []
    stalled = np.concatenate([
        1e-1 * (0.9 ** np.arange(50)),
        np.full(100, 1e-1 * 0.9**49),
        1e-1 * 0.9**49 * (0.9 ** np.arange(1, 51)),
    ])
    spans = obs_spectrum.detect_plateaus(stalled)  # auto window = 50
    assert spans, "a 100-iteration stall must be detected"
    (start, end), *_ = spans
    assert 95 <= start <= 105 and end > start
    # a stall shorter than the window stays silent
    wiggle = np.concatenate([
        1e-1 * (0.9 ** np.arange(80)),
        np.full(10, 1e-1 * 0.9**79),
        1e-1 * 0.9**79 * (0.9 ** np.arange(1, 100)),
    ])
    assert obs_spectrum.detect_plateaus(wiggle) == []
