"""tools/bench_compare.py: the perf regression gate, under test.

The gate's value is its exit-code contract — 0 = no regression,
nonzero naming the offending metric — so that contract is what the
tests pin, metric by metric, plus the tolerance-from-pyproject loading
and the skip-don't-fail stance on keys only one round carries (older
artifacts predate newer bench keys; that must never fail the gate).
"""

from __future__ import annotations

import importlib.util
import json
import os

import pytest

_TOOL = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools",
    "bench_compare.py",
)
_spec = importlib.util.spec_from_file_location("bench_compare", _TOOL)
bc = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bc)

TOL = dict(bc.DEFAULT_TOLERANCES)


def make_round(**overrides) -> dict:
    rec = {
        "metric": "T_solver 100x200 (42 PCG iters to 1e-6), f32, 1 chip",
        "value": 0.5,
        "valid": True,
        "grids": [
            {"grid": [100, 200], "t_solver_s": 0.5, "iters": 42,
             "converged": True, "engine": "resident", "hbm_gbps": 100.0},
            {"grid": [400, 600], "t_solver_s": 1.0, "iters": 99,
             "converged": True, "engine": "xl", "hbm_gbps": 200.0},
        ],
        "config2": {"grid": [64, 64], "t_solver_s": 0.01, "iters": 7},
        "f64": {"grid": [100, 200], "t_solver_s": 3.0, "iters": 42},
        "spectrum": [
            {"grid": [100, 200], "kappa": 5000.0, "predicted_iters": 42},
        ],
        "throughput": [
            {"grid": [100, 200], "lanes": 8, "solves_per_sec": 50.0},
        ],
    }
    rec.update(overrides)
    return rec


def regressions_between(old, new):
    regs, _notes = bc.compare(old, new, TOL)
    return [(r.metric, r.where) for r in regs]


# ------------------------------------------------------- per-metric gates


def test_identical_rounds_have_no_regressions():
    rec = make_round()
    assert regressions_between(rec, rec) == []


def test_t_solver_regression_is_named_per_grid():
    new = make_round()
    new["grids"][0]["t_solver_s"] = 0.5 * (1 + TOL["t-solver-pct"]) * 1.01
    assert regressions_between(make_round(), new) == [
        ("t_solver_s", "100x200")
    ]
    # within tolerance: silent
    new["grids"][0]["t_solver_s"] = 0.5 * (1 + TOL["t-solver-pct"]) * 0.99
    assert regressions_between(make_round(), new) == []
    # getting FASTER is never a regression
    new["grids"][0]["t_solver_s"] = 0.1
    assert regressions_between(make_round(), new) == []


def test_iters_regression_is_absolute():
    new = make_round()
    new["grids"][1]["iters"] = 99 + int(TOL["iters-abs"]) + 1
    assert regressions_between(make_round(), new) == [("iters", "400x600")]
    new["grids"][1]["iters"] = 99 + int(TOL["iters-abs"])  # the ±2 reorder
    assert regressions_between(make_round(), new) == []


def test_scalar_row_keys_are_gated_too():
    new = make_round()
    new["f64"]["t_solver_s"] = 3.0 * 2
    assert regressions_between(make_round(), new) == [("t_solver_s", "f64")]


def test_gbps_drop_and_kappa_drift_are_regressions():
    new = make_round()
    new["grids"][0]["hbm_gbps"] = 100.0 * (1 - TOL["gbps-pct"]) * 0.9
    assert regressions_between(make_round(), new) == [
        ("hbm_gbps", "100x200")
    ]
    # kappa drifts BOTH ways: the operator didn't change, the estimator did
    for factor in (1 + TOL["kappa-pct"] * 1.5, 1 - TOL["kappa-pct"] * 1.5):
        new = make_round()
        new["spectrum"][0]["kappa"] = 5000.0 * factor
        assert regressions_between(make_round(), new) == [
            ("kappa", "100x200")
        ]
    new = make_round()
    new["spectrum"][0]["kappa"] = 5000.0 * (1 + TOL["kappa-pct"] * 0.5)
    assert regressions_between(make_round(), new) == []


def test_throughput_drop_is_a_regression():
    new = make_round()
    new["throughput"][0]["solves_per_sec"] = 50.0 * (1 - TOL["sps-pct"]) / 2
    assert regressions_between(make_round(), new) == [
        ("solves_per_sec", "100x200 lanes=8")
    ]


def _abft_row(**overrides):
    row = {
        "available": True, "grid": [800, 1200], "mesh": [1, 2],
        "t_off_s": 1.0, "t_on_s": 1.01, "overhead_pct": 1.0,
        "gate_pct": 2.0, "iters_off": 99, "iters_on": 99,
        "psum_per_iter": 2, "ppermute_per_iter": 4,
        "collectives_identical": True, "ok": True,
    }
    row.update(overrides)
    return row


def test_abft_overhead_creep_is_a_regression():
    old = make_round(abft=_abft_row())
    new = make_round(
        abft=_abft_row(overhead_pct=1.0 + TOL["abft-pp"] * 1.5)
    )
    assert regressions_between(old, new) == [("abft_overhead_pct", "abft")]
    # within the percentage-point band: silent
    new = make_round(
        abft=_abft_row(overhead_pct=1.0 + TOL["abft-pp"] * 0.5)
    )
    assert regressions_between(old, new) == []


def test_abft_broken_cadence_pin_is_a_regression():
    old = make_round(abft=_abft_row())
    new = make_round(abft=_abft_row(collectives_identical=False))
    assert regressions_between(old, new) == [("abft_collectives", "abft")]


def test_abft_only_in_one_round_is_noted_not_failed():
    old = make_round()  # pre-abft artifact
    new = make_round(abft=_abft_row())
    regs, notes = bc.compare(old, new, TOL)
    assert regs == []
    assert any("abft" in n for n in notes)
    # an unavailable row (single-device bench box) skips the same way
    regs, notes = bc.compare(
        make_round(abft={"available": False}), new, TOL
    )
    assert regs == []


def _fleet_key(**overrides):
    key = {
        "rows": [
            {"replicas": 1, "lanes": 2, "solves_per_sec": 100.0,
             "completed": 24, "wall_s": 0.24},
            {"replicas": 2, "lanes": 2, "solves_per_sec": 110.0,
             "completed": 24, "wall_s": 0.22},
            {"replicas": 3, "lanes": 2, "solves_per_sec": 115.0,
             "completed": 24, "wall_s": 0.21},
        ],
        "non_decreasing": True,
        "handoff_p99_s": 0.002,
        "rejoin_latency_s": 0.2,
        "kill_completed": 24,
        "handoffs": 1,
        "adopted": 3,
        "rejoins": 1,
    }
    key.update(overrides)
    return key


def test_fleet_aggregate_drop_is_a_regression():
    old = make_round(fleet=_fleet_key())
    new_key = _fleet_key()
    new_key["rows"][1]["solves_per_sec"] = (
        110.0 * (1 - TOL["fleet-agg-pct"]) / 2
    )
    new = make_round(fleet=new_key)
    assert regressions_between(old, new) == [
        ("fleet_solves_per_sec", "fleet replicas=2")
    ]


def test_fleet_broken_scaling_pin_is_a_regression():
    old = make_round(fleet=_fleet_key())
    new = make_round(fleet=_fleet_key(non_decreasing=False))
    assert ("fleet_non_decreasing", "fleet") in regressions_between(old, new)


def test_fleet_within_tolerance_is_clean():
    old = make_round(fleet=_fleet_key())
    new_key = _fleet_key()
    new_key["rows"][0]["solves_per_sec"] = (
        100.0 * (1 - TOL["fleet-agg-pct"] / 2)
    )
    assert regressions_between(old, new_round := make_round(fleet=new_key)) == []
    assert new_round["fleet"]["non_decreasing"]


def test_fleet_rejoin_latency_growth_is_a_regression():
    old = make_round(fleet=_fleet_key())
    slow = 0.2 * (1 + TOL["rejoin-p99-pct"]) * 1.1
    new = make_round(fleet=_fleet_key(rejoin_latency_s=slow))
    assert ("fleet_rejoin_latency_s", "fleet") in regressions_between(
        old, new
    )


def test_fleet_rejoin_absent_in_old_round_is_noted_not_failed():
    # a pre-rejoin artifact has no rejoin_latency_s: the new round's
    # number is noted one-sided, never failed against the absence
    old = make_round(fleet=_fleet_key(rejoin_latency_s=None, rejoins=0))
    new = make_round(fleet=_fleet_key())
    regs, notes = bc.compare(old, new, TOL)
    assert regs == []
    assert any("rejoin_latency_s" in n for n in notes)


def test_fleet_rejoin_drill_without_latency_is_a_regression():
    # the drill RAN (rejoins >= 1) but the recovery number went
    # missing — a broken emitter, not tolerable absence
    old = make_round(fleet=_fleet_key())
    new = make_round(fleet=_fleet_key(rejoin_latency_s=None, rejoins=1))
    assert ("fleet_rejoin_latency_s", "fleet") in regressions_between(
        old, new
    )


def test_fleet_only_in_one_round_is_noted_not_failed():
    old = make_round()  # pre-fleet artifact
    new = make_round(fleet=_fleet_key())
    regs, notes = bc.compare(old, new, TOL)
    assert regs == []
    assert any("fleet" in n for n in notes)
    # a failed fleet key with no rows skips the same way
    regs, _ = bc.compare(
        make_round(fleet={"rows": []}), new, TOL
    )
    assert regs == []


def _precond_rows():
    return [
        {"grid": [100, 200], "engine": "mg-pcg", "iters": 30,
         "t_solver_s": 0.2, "converged": True, "l2_error": 1e-4,
         "diag_iters": 420, "diag_t_solver_s": 0.5,
         "iters_reduction": 14.0, "speedup_vs_diag": 2.5},
        {"grid": [100, 200], "engine": "cheb-pcg", "iters": 60,
         "t_solver_s": 0.3, "converged": True, "l2_error": 1e-4},
    ]


def test_precond_regressions_are_named_per_grid_and_engine():
    base = make_round(precond=_precond_rows())
    assert regressions_between(base, base) == []
    # iters are operator-determined: growth past the fractional band
    # means the V-cycle/bounds broke, and the row names grid AND engine
    new = make_round(precond=_precond_rows())
    new["precond"][0]["iters"] = int(
        30 * (1 + TOL["precond-iters-pct"]) * 1.1
    )
    assert regressions_between(base, new) == [
        ("precond_iters", "100x200 mg-pcg")
    ]
    # the wall-clock win the key exists to defend
    new = make_round(precond=_precond_rows())
    new["precond"][1]["t_solver_s"] = 0.3 * (1 + TOL["precond-t-pct"]) * 1.05
    assert regressions_between(base, new) == [
        ("precond_t_solver_s", "100x200 cheb-pcg")
    ]
    # within tolerance / getting faster: silent
    new = make_round(precond=_precond_rows())
    new["precond"][0]["t_solver_s"] = 0.05
    new["precond"][1]["iters"] = 58
    assert regressions_between(base, new) == []


def test_precond_only_in_one_round_is_noted_not_failed():
    # pre-multigrid artifacts lack the key: skip with a note, never fail
    old = make_round()
    new = make_round(precond=_precond_rows())
    regs, notes = bc.compare(old, new, TOL)
    assert regs == []
    assert any("precond" in n for n in notes)


def test_null_kappa_in_a_matched_row_is_noted_not_silent():
    # bench_spectrum writes kappa=null when the trace was unusable —
    # exactly the broken-estimator case the gate exists to surface, so
    # it must land in the notes even though both rounds carry the key
    new = make_round()
    new["spectrum"][0]["kappa"] = None
    regs, notes = bc.compare(make_round(), new, TOL)
    assert regs == []
    assert any("kappa" in n and "100x200" in n for n in notes)


def test_one_sided_keys_are_skipped_with_a_note_not_failed():
    old = make_round()
    del old["spectrum"]
    del old["throughput"]
    old["grids"] = old["grids"][:1]
    for row in old["grids"]:
        row.pop("hbm_gbps")
    regs, notes = bc.compare(old, make_round(), TOL)
    assert regs == []
    text = " ".join(notes)
    assert "spectrum" in text and "throughput" in text
    assert "400x600" in text and "hbm_gbps" in text


# --------------------------------------------------------- CLI contract


def write_rounds(tmp_path, old, new):
    po, pn = tmp_path / "BENCH_r01.json", tmp_path / "BENCH_r02.json"
    po.write_text(json.dumps({"parsed": old}))  # driver artifact form
    pn.write_text(json.dumps(new))  # raw bench.py line form
    return str(po), str(pn)


def test_cli_exit_0_on_clean_and_1_with_named_metric(tmp_path, capsys):
    po, pn = write_rounds(tmp_path, make_round(), make_round())
    assert bc.main([po, pn]) == 0
    assert "no regressions" in capsys.readouterr().out
    slow = make_round()
    slow["grids"][0]["t_solver_s"] = 5.0
    po, pn = write_rounds(tmp_path, make_round(), slow)
    assert bc.main([po, pn]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION t_solver_s @ 100x200" in out


def test_cli_json_mode_carries_the_regression_list(tmp_path, capsys):
    slow = make_round()
    slow["grids"][0]["t_solver_s"] = 5.0
    po, pn = write_rounds(tmp_path, make_round(), slow)
    assert bc.main(["--json", po, pn]) == 1
    rec = json.loads(capsys.readouterr().out)
    assert rec["regressions"][0]["metric"] == "t_solver_s"
    assert rec["tolerances"]["t-solver-pct"] == TOL["t-solver-pct"]


def test_cli_usage_and_unreadable_input_exit_2(tmp_path, capsys):
    assert bc.main(["one.json"]) == 2  # one path is not a comparison
    # unusable input is 2, NEVER 1: a gate reading 1 as "regression"
    # must not misclassify a corrupt artifact as a slowdown
    bad = tmp_path / "BENCH_r01.json"
    bad.write_text("{not json")
    assert bc.main([str(bad), str(bad)]) == 2
    assert "cannot read" in capsys.readouterr().err
    listy = tmp_path / "BENCH_r02.json"
    listy.write_text("[1, 2]")
    assert bc.main([str(listy), str(listy)]) == 2


def test_newest_rounds_orders_by_round_number(tmp_path):
    for name in ("BENCH_r9.json", "BENCH_r10.json", "BENCH_r2.json"):
        (tmp_path / name).write_text("{}")
    pair = [os.path.basename(p) for p in bc.newest_rounds(str(tmp_path))]
    assert pair == ["BENCH_r9.json", "BENCH_r10.json"]


def test_tolerances_load_from_pyproject_with_defaults(tmp_path):
    # the repo's own pyproject overrides nothing surprising
    repo_tol = bc.load_tolerances()
    assert set(repo_tol) == set(bc.DEFAULT_TOLERANCES)
    assert repo_tol["iters-abs"] == 2
    # an explicit table overrides; the fallback parser stores floats as
    # strings, so coercion is part of the contract under test
    (tmp_path / "pyproject.toml").write_text(
        "[tool.bench_compare]\nt-solver-pct = 0.5\niters-abs = 10\n"
    )
    tol = bc.load_tolerances(str(tmp_path))
    assert tol["t-solver-pct"] == 0.5
    assert tol["iters-abs"] == 10
    assert tol["kappa-pct"] == bc.DEFAULT_TOLERANCES["kappa-pct"]
    (tmp_path / "pyproject.toml").write_text(
        "[tool.bench_compare]\nt-solver-pct = banana\n"
    )
    with pytest.raises(SystemExit, match="t-solver-pct"):
        bc.load_tolerances(str(tmp_path))


# ------------------------------------------------------- geometry rows


def _geometry_row(**overrides):
    row = {
        "grid": [400, 600], "assembly_cf_s": 0.2, "assembly_quad_s": 1.0,
        "assembly_overhead_x": 5.0, "max_frac_err": 1e-14,
        "sdf_ellipse_iters": 42, "oracle_iters": 42,
        "composite": {"domain": "ellipse-minus-hole", "t_solver_s": 0.5,
                      "iters": 40, "converged": True, "min_u": 0.0},
    }
    row.update(overrides)
    return row


def test_geometry_composite_slowdown_is_a_regression():
    old = make_round(geometry=_geometry_row())
    comp = dict(_geometry_row()["composite"])
    comp["t_solver_s"] = 0.5 * (1 + TOL["geometry-t-pct"]) * 1.01
    new = make_round(geometry=_geometry_row(composite=comp))
    assert regressions_between(old, new) == [
        ("geometry_t_solver_s", "composite")
    ]
    comp["t_solver_s"] = 0.5 * (1 + TOL["geometry-t-pct"]) * 0.99
    new = make_round(geometry=_geometry_row(composite=comp))
    assert regressions_between(old, new) == []


def test_geometry_assembly_slowdown_and_frac_err_are_regressions():
    old = make_round(geometry=_geometry_row())
    new = make_round(geometry=_geometry_row(
        assembly_quad_s=1.0 * (1 + TOL["geometry-assembly-pct"]) * 1.01
    ))
    assert regressions_between(old, new) == [
        ("geometry_assembly_quad_s", "geometry")
    ]
    # the parity bound is a hard pin, not a relative drift band
    new = make_round(geometry=_geometry_row(max_frac_err=1e-11))
    assert regressions_between(old, new) == [
        ("geometry_max_frac_err", "geometry")
    ]


def test_geometry_only_in_one_round_is_noted_not_failed():
    old = make_round()
    new = make_round(geometry=_geometry_row())
    regs, notes = bc.compare(old, new, TOL)
    assert not regs
    assert any("geometry" in n for n in notes)


def _grad_row(**overrides) -> dict:
    row = {
        "grid": [400, 600], "lanes": 4, "n_requests": 8,
        "grad_solves_per_sec": 10.0, "wall_s": 0.8,
        "rows": [{"grid": [400, 600], "primal_iters": 546,
                  "adjoint_iters": 540, "ratio": 0.989}],
        "valid": True,
    }
    row.update(overrides)
    return row


def test_grad_throughput_drop_is_a_regression():
    old = make_round(grad=_grad_row())
    new = make_round(grad=_grad_row(
        grad_solves_per_sec=10.0 * (1 - TOL["grad-pct"]) * 0.99
    ))
    assert regressions_between(old, new) == [
        ("grad_solves_per_sec", "grad")
    ]
    new = make_round(grad=_grad_row(
        grad_solves_per_sec=10.0 * (1 - TOL["grad-pct"]) * 1.01
    ))
    assert regressions_between(old, new) == []


def test_grad_adjoint_ratio_growth_is_a_regression():
    old = make_round(grad=_grad_row())
    grown = [{"grid": [400, 600], "primal_iters": 546,
              "adjoint_iters": 1100, "ratio": 2.015}]
    new = make_round(grad=_grad_row(rows=grown))
    assert regressions_between(old, new) == [
        ("grad_adjoint_ratio", "grad 400x600")
    ]
    near = [{"grid": [400, 600], "primal_iters": 546,
             "adjoint_iters": 560, "ratio": 1.026}]
    assert regressions_between(old, make_round(grad=_grad_row(rows=near))) == []


def test_grad_only_in_one_round_is_noted_not_failed():
    old = make_round()
    new = make_round(grad=_grad_row())
    regs, notes = bc.compare(old, new, TOL)
    assert not regs
    assert any("grad" in n for n in notes)


# ------------------------------------------------ the bandwidth key's gates


def _bw(t_pipe_bf16=0.6, gbps_bf16=400.0, ratio=0.5, parity=True):
    return {
        "available": True,
        "grid": [2400, 3200],
        "byte_ratio_gate": 0.6,
        "l2_band": 1.10,
        "cells": [
            {"engine": "pipelined", "storage": "f32", "t_solver_s": 1.0,
             "hbm_gbps": 300.0, "l2_err": 1e-4},
            {"engine": "pipelined", "storage": "bf16",
             "t_solver_s": t_pipe_bf16, "hbm_gbps": gbps_bf16,
             "l2_err": 1.05e-4, "byte_ratio_vs_f32": ratio,
             "l2_parity": parity},
        ],
        "ok": True,
    }


def test_bandwidth_identical_rounds_pass_and_absence_is_noted():
    old = make_round(bandwidth=_bw())
    assert regressions_between(old, old) == []
    regs, notes = bc.compare(make_round(), make_round(bandwidth=_bw()), TOL)
    assert not [r for r in regs if "bandwidth_t" in r.metric]
    assert any("bandwidth" in n for n in notes)


def test_bandwidth_cell_slowdown_and_gbps_drop_are_regressions():
    old = make_round(bandwidth=_bw())
    slow = make_round(bandwidth=_bw(t_pipe_bf16=0.9))
    regs = regressions_between(old, slow)
    assert ("bandwidth_t_solver_s", "bandwidth pipelined/bf16") in regs
    dropped = make_round(bandwidth=_bw(gbps_bf16=200.0))
    regs = regressions_between(old, dropped)
    assert ("bandwidth_hbm_gbps", "bandwidth pipelined/bf16") in regs


def test_bandwidth_hard_pins_fire_on_the_new_round_alone():
    old = make_round(bandwidth=_bw())
    fat = make_round(bandwidth=_bw(ratio=0.75))
    regs = regressions_between(old, fat)
    assert ("bandwidth_byte_ratio", "bandwidth pipelined/bf16") in regs
    off = make_round(bandwidth=_bw(parity=False))
    regs = regressions_between(old, off)
    assert ("bandwidth_l2_parity", "bandwidth pipelined/bf16") in regs


# ------------------------------------------------------- fmg / autotune


def _fmg_round(t1=0.05, t2=0.4, wu=True, headline_speedup=1.4):
    return make_round(fmg={
        "work_units_constant": wu,
        "rows": [
            {"grid": [400, 600], "t_solver_s": t1, "iters": 3,
             "work_units_per_point": 60.0, "headline": False},
            {"grid": [4096, 4096], "t_solver_s": t2, "iters": 3,
             "work_units_per_point": 62.0, "headline": True,
             "speedup_vs_mg": headline_speedup},
        ],
    })


def test_fmg_slowdown_is_a_regression_per_grid():
    old, new = _fmg_round(), _fmg_round(t1=0.05 * 1.5)
    assert ("fmg_t_solver_s", "fmg 400x600") in regressions_between(old, new)
    assert regressions_between(old, _fmg_round(t1=0.05 * 1.1)) == []


def test_fmg_hard_pins_fire_on_the_new_round_alone():
    old = _fmg_round()
    regs = regressions_between(old, _fmg_round(wu=False))
    assert ("fmg_work_units", "fmg") in regs
    regs = regressions_between(old, _fmg_round(headline_speedup=0.8))
    assert ("fmg_headline_speedup", "fmg 4096x4096") in regs


def test_fmg_only_in_one_round_is_noted_not_failed():
    old, new = make_round(), _fmg_round()
    regs, notes = bc.compare(old, new, TOL)
    assert not regs
    assert any("fmg" in n for n in notes)


def _autotune_round(t=0.02, loses=False, roundtrip=True):
    return make_round(autotune={
        "rows": [
            {"grid": [400, 600], "tuned_engine": "fmg",
             "static_engine": "xl", "tuned_t_s": t, "static_t_s": 0.05,
             "tuned_loses": loses, "roundtrip_ok": roundtrip},
        ],
    })


def test_autotune_tuned_slowdown_is_a_regression():
    old, new = _autotune_round(), _autotune_round(t=0.02 * 1.5)
    assert ("autotune_tuned_t_s", "autotune 400x600") in \
        regressions_between(old, new)
    assert regressions_between(old, _autotune_round(t=0.02 * 1.1)) == []


def test_autotune_never_loses_pin_fires_on_the_new_round_alone():
    # a new round whose tuned config lost to the static default fails
    # even against an old round that also carried the key cleanly
    regs = regressions_between(_autotune_round(), _autotune_round(loses=True))
    assert ("autotune_tuned_loses", "autotune 400x600") in regs
    regs = regressions_between(
        _autotune_round(), _autotune_round(roundtrip=False)
    )
    assert ("autotune_roundtrip", "autotune 400x600") in regs


def test_autotune_only_in_one_round_is_noted_not_failed():
    old, new = make_round(), _autotune_round()
    regs, notes = bc.compare(old, new, TOL)
    assert not regs
    assert any("autotune" in n for n in notes)


# ------------------------------------------------ engine-contract stamping


def test_contracts_violated_new_round_is_a_regression():
    old = make_round(contracts={"hash": "a" * 64, "clean": True})
    new = make_round(contracts={"hash": "a" * 64, "clean": False})
    assert ("contracts_clean", "contracts") in regressions_between(old, new)


def test_contracts_hash_change_is_noted_not_failed():
    old = make_round(contracts={"hash": "a" * 64, "clean": True})
    new = make_round(contracts={"hash": "b" * 64, "clean": True})
    regs, notes = bc.compare(old, new, TOL)
    assert not regs
    assert any("contracts" in n and "hash changed" in n for n in notes)


def test_contracts_identical_state_is_silent():
    old = make_round(contracts={"hash": "a" * 64, "clean": True})
    new = make_round(contracts={"hash": "a" * 64, "clean": True})
    regs, notes = bc.compare(old, new, TOL)
    assert not regs
    assert not any("contracts" in n for n in notes)


def test_contracts_only_in_one_round_is_noted_not_failed():
    old = make_round()
    new = make_round(contracts={"hash": "a" * 64, "clean": True})
    regs, notes = bc.compare(old, new, TOL)
    assert not regs
    assert any("contracts: only in one round" in n for n in notes)


def test_stamp_embeds_contract_state(tmp_path, monkeypatch, capsys):
    from poisson_ellipse_tpu.analysis import matrix

    monkeypatch.setattr(
        matrix, "run_matrix", lambda *a, **k: {"clean": True, "cells": []}
    )
    p = tmp_path / "BENCH_r01.json"
    p.write_text(json.dumps({"parsed": make_round()}))
    assert bc.stamp(str(p)) == 0
    out = capsys.readouterr().out
    assert "contracts clean" in out
    stamped = json.loads(p.read_text())["parsed"]["contracts"]
    assert stamped["clean"] is True and len(stamped["hash"]) == 64
    # the stamped round now compares against an unstamped one as a note
    regs, notes = bc.compare(
        make_round(), json.loads(p.read_text())["parsed"], TOL
    )
    assert not regs
    assert any("contracts: only in one round" in n for n in notes)


def test_stamp_not_clean_exits_1_but_still_writes(tmp_path, monkeypatch):
    from poisson_ellipse_tpu.analysis import matrix

    monkeypatch.setattr(
        matrix, "run_matrix", lambda *a, **k: {"clean": False, "cells": []}
    )
    p = tmp_path / "BENCH_r02.json"
    p.write_text(json.dumps(make_round()))  # raw bench line, no "parsed"
    assert bc.stamp(str(p)) == 1
    assert json.loads(p.read_text())["contracts"]["clean"] is False


def test_stamp_unreadable_input_exits_2(tmp_path, capsys):
    bad = tmp_path / "BENCH_r03.json"
    bad.write_text("{not json")
    assert bc.main(["--stamp", str(bad)]) == 2
    assert bc.main(["--stamp"]) == 2  # missing operand is usage, not crash


# ------------------------------------------------------ recycle stream


def _recycle_round(cut=4.1, sps_warm=3.15, gap=0.05, converged=True):
    return make_round(recycle={
        "grid": [128, 128], "stream": 5, "ring_cap": 64, "basis_rank": 8,
        "capture_iters": 150, "iters_cold_mean": 149.6,
        "iters_warm_mean": 36.2, "iter_cut": cut, "l2_rel_gap_max": gap,
        "solves_per_s_cold": 2.77, "solves_per_s_warm": sps_warm,
        "converged": converged, "valid": True,
    })


def test_recycle_iter_cut_and_warm_throughput_are_gated():
    old = _recycle_round()
    limit = TOL["recycle-pct"]
    new = _recycle_round(cut=4.1 * (1 - limit) * 0.99)
    assert ("recycle_iter_cut", "recycle 128x128") in \
        regressions_between(old, new)
    new = _recycle_round(sps_warm=3.15 * (1 - limit) * 0.99)
    assert ("recycle_solves_per_s_warm", "recycle 128x128") in \
        regressions_between(old, new)
    # within tolerance (and identical rounds): silent
    assert regressions_between(old, _recycle_round(cut=4.1 * 0.9)) == []
    assert regressions_between(old, old) == []


def test_recycle_hard_pins_fire_on_the_new_round_alone():
    # the acceptance pins hold even against an old round that also
    # carried the key cleanly: >= 2x cut, <= 10% analytic-l2 gap,
    # every solve in the stream converged
    regs = regressions_between(_recycle_round(), _recycle_round(cut=1.7))
    assert ("recycle_cut_pin", "recycle 128x128") in regs
    regs = regressions_between(_recycle_round(), _recycle_round(gap=0.2))
    assert ("recycle_l2_gap", "recycle 128x128") in regs
    regs = regressions_between(
        _recycle_round(), _recycle_round(converged=False)
    )
    assert ("recycle_converged", "recycle 128x128") in regs
    # ...and fire on a brand-new key with no old counterpart at all
    regs = regressions_between(make_round(), _recycle_round(cut=1.7))
    assert ("recycle_cut_pin", "recycle 128x128") in regs


def test_recycle_only_in_one_round_is_noted_not_failed():
    regs, notes = bc.compare(make_round(), _recycle_round(), TOL)
    assert not regs
    assert any("recycle" in n for n in notes)
