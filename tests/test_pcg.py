"""Solver (L5) integration tests against the committed reference code's
iteration-count oracles and the analytic solution.

Oracle provenance: the reference's stage0 binary (compiled from
stage0/Withoutopenmp1.cpp, unweighted norm) prints 17/31/61 iterations at
10²/20²/40²; the stage1 binary (weighted norm, stages 1-4 convention,
Withopenmp1.cpp:182-189) prints 50 at 40². The stage-report PDFs quote 60
at 40² — that figure predates the committed code; the committed code is the
oracle here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.solver.pcg import pcg, solve
from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic, residual_norm

UNWEIGHTED_ORACLE = {(10, 10): 17, (20, 20): 31, (40, 40): 61}
WEIGHTED_ORACLE = {(10, 10): 15, (20, 20): 26, (40, 40): 50}


@pytest.mark.parametrize("M,N", sorted(UNWEIGHTED_ORACLE))
def test_iteration_counts_unweighted_stage0(M, N):
    problem = Problem(M=M, N=N, norm="unweighted")
    result = solve(problem, jnp.float64)
    assert int(result.iters) == UNWEIGHTED_ORACLE[(M, N)]
    assert bool(result.converged)
    assert not bool(result.breakdown)


@pytest.mark.parametrize("M,N", sorted(WEIGHTED_ORACLE))
def test_iteration_counts_weighted_stages1to4(M, N):
    problem = Problem(M=M, N=N, norm="weighted")
    result = solve(problem, jnp.float64)
    assert int(result.iters) == WEIGHTED_ORACLE[(M, N)]
    assert bool(result.converged)


@pytest.mark.parametrize(
    "M,N,expected_l2",
    [(10, 10, 5.604e-3), (20, 20, 7.663e-3), (40, 40, 3.677e-3)],
)
def test_l2_error_vs_analytic(M, N, expected_l2):
    problem = Problem(M=M, N=N, norm="unweighted")
    result = solve(problem, jnp.float64)
    err = float(l2_error_vs_analytic(problem, result.w))
    assert err == pytest.approx(expected_l2, rel=1e-3)


def test_solution_residual_small():
    problem = Problem(M=40, N=40)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    result = pcg(problem, a, b, rhs)
    res = float(residual_norm(problem, result.w, a, b, rhs))
    rhs_norm = float(jnp.sqrt(jnp.sum(rhs**2) * problem.h1 * problem.h2))
    # stopping rule is on ‖Δw‖, not the residual; the stiff 1/eps coefficients
    # leave a larger (but still small) relative residual at delta=1e-6
    assert res / rhs_norm < 1e-2


def test_pcg_jits_cleanly():
    problem = Problem(M=20, N=20)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    jitted = jax.jit(lambda a, b, rhs: pcg(problem, a, b, rhs))
    r1 = jitted(a, b, rhs)
    r2 = solve(problem, jnp.float64)
    assert int(r1.iters) == int(r2.iters)
    # jit fuses differently from op-by-op dispatch → last-ulp differences only
    np.testing.assert_allclose(
        np.asarray(r1.w), np.asarray(r2.w), rtol=1e-12, atol=1e-15
    )


def test_max_iter_cap_respected():
    problem = Problem(M=40, N=40, max_iter=5)
    result = solve(problem, jnp.float64)
    assert int(result.iters) == 5
    assert not bool(result.converged)


def test_l2_error_decreases_under_refinement():
    # fictitious-domain convergence: error at 80² well below error at 20²
    e20 = float(
        l2_error_vs_analytic(
            Problem(M=20, N=20), solve(Problem(M=20, N=20), jnp.float64).w
        )
    )
    e80 = float(
        l2_error_vs_analytic(
            Problem(M=80, N=80), solve(Problem(M=80, N=80), jnp.float64).w
        )
    )
    assert e80 < e20


def test_energy_error_monotonically_decreases():
    """SURVEY §4's 'PCG residual monotonicity' property, stated in the
    quantity CG actually guarantees: the A-norm (energy) of the error
    e_k = w_k − w* decreases strictly every iteration (the plain
    residual norm is NOT monotone in CG and would be a wrong assert).
    w* comes from a dense solve of the independently assembled interior
    operator; iterates come from the resumable init_state/advance
    stepper, whose chunking is bit-identical to a straight run."""
    from poisson_ellipse_tpu.solver.pcg import advance, init_state

    from tests.test_ops import dense_operator

    problem = Problem(M=20, N=20)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    A = dense_operator(problem, a, b)
    M, N = problem.M, problem.N
    w_star = np.linalg.solve(A, np.asarray(rhs)[1:M, 1:N].ravel())

    state = init_state(problem, a, b, rhs)
    energies = []
    for k in range(1, 15):
        state = advance(problem, a, b, rhs, state, limit=k)
        e = np.asarray(state[1])[1:M, 1:N].ravel() - w_star
        energies.append(float(e @ (A @ e)))
    assert all(b < a for a, b in zip(energies, energies[1:])), energies
    assert energies[-1] < 1e-3 * energies[0]


def test_float32_path_converges():
    problem = Problem(M=40, N=40, delta=1e-4)
    result = solve(problem, jnp.float32)
    assert result.w.dtype == jnp.float32
    assert bool(result.converged)
    err = float(l2_error_vs_analytic(problem, result.w))
    assert err < 5e-3
