"""Resilience: the fault matrix, the recovery ladder, classified exits.

The acceptance bar (ISSUE 4): every engine × fault-class cell either
converges to oracle parity after recovery (iterations within ±2 of the
clean run) or raises the classified error — no NaN (or drifted-finite)
result is ever returned as a converged PCGResult — and with no fault
injected the guarded chunk's jaxpr is IDENTICAL to the unguarded loop
(zero overhead when healthy, pinned below).

Everything here runs on the CPU backend (conftest pins 8 virtual
devices); the Pallas engines interpret.
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.harness.__main__ import main as harness_main
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.resilience import (
    DivergedError,
    Fault,
    FaultPlan,
    OutOfMemoryError,
    SolveError,
    SolveTimeout,
    classify_error,
    corrupt_halo,
    force_breakdown,
    guarded_solve,
    inject_nan,
    inject_stagnation,
    simulate_oom,
    simulated_vmem,
)
from poisson_ellipse_tpu.resilience.guard import (
    HEALTH_BREAKDOWN,
    HEALTH_CONVERGED,
    HEALTH_NONFINITE,
    HEALTH_STAGNATION,
    _ClassicalAdapter,
    health_name,
)
from poisson_ellipse_tpu.solver.engine import select_engine
from poisson_ellipse_tpu.solver.engine import solve as engine_solve

PROBLEM = Problem(M=20, N=20)
CHUNK = 8
FAULT_AT = 10

LOOP_ENGINES = ("xla", "pallas", "pipelined", "pipelined-pallas")

_clean_cache: dict[str, object] = {}


def clean_result(engine: str):
    """The unguarded solve each cell's parity is measured against."""
    if engine not in _clean_cache:
        _clean_cache[engine] = engine_solve(PROBLEM, engine, jnp.float32)
    return _clean_cache[engine]


def assert_parity(guarded, clean, engine: str, atol: float = 5e-6):
    """Oracle parity: iterations within ±2 and a solution that matches
    the clean run to engine-reordering tolerance. Never a NaN."""
    assert bool(guarded.result.converged), engine
    assert abs(int(guarded.result.iters) - int(clean.iters)) <= 2, (
        f"{engine}: {int(guarded.result.iters)} vs clean {int(clean.iters)}"
    )
    w = np.asarray(guarded.result.w)
    assert np.isfinite(w).all(), engine
    np.testing.assert_allclose(
        w, np.asarray(clean.w), rtol=0, atol=atol, err_msg=engine
    )


# ------------------------------------------------------------- errors


def test_exit_code_contract():
    assert DivergedError("x").exit_code == 2
    assert OutOfMemoryError("x").exit_code == 3
    assert SolveTimeout("x").exit_code == 4
    assert issubclass(DivergedError, SolveError)


def test_classify_error_sniffs_oom_spellings():
    assert classify_error(RuntimeError("RESOURCE_EXHAUSTED: foo")) == "oom"
    assert classify_error(RuntimeError("Out of memory allocating")) == "oom"
    assert classify_error(MemoryError()) == "oom"
    assert classify_error(SolveTimeout("t")) == "timeout"
    assert classify_error(ValueError("nope")) == "unknown"


def test_health_name_labels():
    assert health_name(0) == "healthy"
    assert health_name(HEALTH_BREAKDOWN | HEALTH_NONFINITE) == (
        "breakdown+nonfinite"
    )
    assert health_name(HEALTH_STAGNATION) == "stagnation"
    assert HEALTH_CONVERGED == 8


# ------------------------------------------- zero overhead when healthy


def test_guarded_chunk_jaxpr_is_identical_to_unguarded_advance():
    """The guard's per-chunk computation IS the production advance loop:
    same jaxpr, byte for byte — the zero-overhead-when-healthy pin, as
    the declared ``guard-overhead`` contract (the classical and the
    pipelined adapter families, per their ENGINE_CAPS rows)."""
    from poisson_ellipse_tpu.analysis.contracts import assert_contract

    problem = Problem(M=10, N=10)
    assert_contract("guard-overhead", "xla", problem=problem)
    assert_contract("guard-overhead", "pipelined", problem=problem)


@pytest.mark.parametrize("engine", LOOP_ENGINES)
def test_guarded_clean_run_matches_unguarded(engine):
    """No fault -> no recovery events, same iteration count, matching
    solution (chunking moves jit boundaries, so ulp-level, not bitwise)."""
    clean = clean_result(engine)
    guarded = guarded_solve(PROBLEM, engine, jnp.float32, chunk=CHUNK)
    assert guarded.recoveries == ()
    assert int(guarded.result.iters) == int(clean.iters)
    assert_parity(guarded, clean, engine)


# -------------------------------------------------- the fault matrix


FAULTS = {
    "nan": lambda: inject_nan(FAULT_AT, "r"),
    "breakdown": lambda: force_breakdown(FAULT_AT),
    "stagnation": lambda: inject_stagnation(FAULT_AT),
}


@pytest.mark.parametrize("engine", LOOP_ENGINES)
@pytest.mark.parametrize("fault", sorted(FAULTS))
def test_fault_matrix_recovers_to_oracle_parity(engine, fault):
    """engines × {nan, breakdown, stagnation}: one transient fault at an
    exact iteration; the guard detects it from the health word, applies
    a direction-preserving true-residual restart, and reconverges within
    ±2 of the clean count (measured: exactly equal)."""
    clean = clean_result(engine)
    guarded = guarded_solve(
        PROBLEM, engine, jnp.float32, chunk=CHUNK,
        faults=FaultPlan(FAULTS[fault]()),
    )
    kinds = [event.kind for event in guarded.recoveries]
    assert kinds == ["residual-restart"], (engine, fault, kinds)
    assert_parity(guarded, clean, engine)


@pytest.mark.parametrize("engine", LOOP_ENGINES)
def test_fault_matrix_oom_cell(engine):
    """engines × simulated-OOM: a RESOURCE_EXHAUSTED at dispatch takes
    the engine-fallback rung directly. xla has no smaller engine — the
    cell's contracted outcome is the classified OutOfMemoryError (exit
    3); every other engine falls back to the classical xla loop and
    still reconverges to parity."""
    plan = FaultPlan(simulate_oom(FAULT_AT))
    if engine == "xla":
        with pytest.raises(OutOfMemoryError) as exc:
            guarded_solve(
                PROBLEM, engine, jnp.float32, chunk=CHUNK, faults=plan
            )
        assert exc.value.exit_code == 3
        return
    clean = clean_result(engine)
    guarded = guarded_solve(
        PROBLEM, engine, jnp.float32, chunk=CHUNK, faults=plan
    )
    assert [event.kind for event in guarded.recoveries] == ["engine-fallback"]
    assert guarded.engine == "xla"
    assert_parity(guarded, clean, engine)


def test_false_convergence_is_never_returned():
    """The drifted-recurrence fault satisfies the step-norm stopping rule
    at a garbage iterate (diff ~ 1e-16); without the residual-drift check
    this would be a CONVERGED PCGResult with a wrong answer. The guard
    must instead recover and return the true solution."""
    clean = clean_result("pipelined")
    guarded = guarded_solve(
        PROBLEM, "pipelined", jnp.float32, chunk=CHUNK,
        faults=FaultPlan(inject_stagnation(FAULT_AT)),
    )
    # recovered, and the returned iterate solves the system for real
    assert_parity(guarded, clean, "pipelined")
    from poisson_ellipse_tpu.resilience.guard import _residual_drift

    adapter = _ClassicalAdapter(PROBLEM, jnp.float32)
    state = adapter.init()
    state = adapter.advance(state, PROBLEM.max_iterations)
    # sanity: the drift metric is tiny on a genuinely converged carry
    assert _residual_drift(adapter, state) < 1e-3


def test_persistent_fault_exhausts_ladder_with_classified_error():
    """A fault a restart cannot clear forces the guard up the ladder —
    restart, f32→f64 escalation — and ends in DivergedError (exit 2),
    never a poisoned result."""
    with pytest.raises(DivergedError) as exc:
        guarded_solve(
            PROBLEM, "xla", jnp.float32, chunk=CHUNK, max_recoveries=5,
            faults=FaultPlan(
                Fault("nan", at_iter=FAULT_AT, field="r", persistent=True)
            ),
        )
    assert exc.value.exit_code == 2
    assert exc.value.iters == FAULT_AT


def test_recovery_budget_is_enforced():
    with pytest.raises(DivergedError):
        guarded_solve(
            PROBLEM, "xla", jnp.float32, chunk=CHUNK, max_recoveries=0,
            faults=FaultPlan(inject_nan(FAULT_AT, "r")),
        )


def test_timeout_cancels_gracefully():
    with pytest.raises(SolveTimeout) as exc:
        guarded_solve(PROBLEM, "xla", jnp.float32, chunk=4, timeout=0.0)
    assert exc.value.exit_code == 4


# ------------------------------------------------------- sharded guard


def _mesh():
    from poisson_ellipse_tpu.parallel.mesh import make_mesh

    return make_mesh()  # 4x2 over the 8 virtual CPU devices


def test_sharded_guarded_clean_hits_oracle():
    from poisson_ellipse_tpu.parallel.pcg_sharded import solve_sharded

    problem = Problem(M=40, N=40)
    mesh = _mesh()
    clean = solve_sharded(problem, mesh, dtype=jnp.float64)
    guarded = guarded_solve(
        problem, "xla", jnp.float64, mesh=mesh, chunk=13
    )
    assert guarded.recoveries == ()
    assert int(guarded.result.iters) == int(clean.iters) == 50
    np.testing.assert_allclose(
        np.asarray(guarded.result.w), np.asarray(clean.w),
        rtol=1e-12, atol=1e-14,
    )


def test_sharded_halo_slab_corruption_recovers():
    """The corrupted-neighbour-exchange fault: a halo-width NaN slab in
    the sharded residual. Detected as nonfinite at the next chunk
    boundary, rolled back, replayed — oracle parity."""
    from poisson_ellipse_tpu.parallel.pcg_sharded import solve_sharded

    problem = Problem(M=40, N=40)
    mesh = _mesh()
    clean = solve_sharded(problem, mesh, dtype=jnp.float64)
    guarded = guarded_solve(
        problem, "xla", jnp.float64, mesh=mesh, chunk=13,
        faults=FaultPlan(corrupt_halo(13, field="r", rows=2)),
    )
    assert [event.kind for event in guarded.recoveries] == [
        "residual-restart"
    ]
    assert abs(int(guarded.result.iters) - int(clean.iters)) <= 2
    assert bool(guarded.result.converged)
    np.testing.assert_allclose(
        np.asarray(guarded.result.w), np.asarray(clean.w),
        rtol=0, atol=1e-10,
    )


# ------------------------------------------- capacity-gate degradation


def test_simulated_vmem_degrades_select_engine():
    """Shrinking the VMEM budget the capacity gates read walks the
    selection down the ladder — the deterministic simulated-OOM form of
    select_engine degradation (and it restores on exit)."""
    problem = Problem(M=400, N=600)
    assert select_engine(problem, jnp.float32) == "resident"
    with simulated_vmem(4 * 1024 * 1024):
        assert select_engine(problem, jnp.float32) == "xl"
    assert select_engine(problem, jnp.float32) == "resident"


def test_whole_solve_guard_mega_kernel_engine():
    """The VMEM mega-kernel engines guard at whole-solve granularity: a
    healthy run returns as-is; a simulated OOM degrades down the
    capacity ladder and still produces the oracle solve."""
    clean = clean_result("xla")
    guarded = guarded_solve(PROBLEM, "resident", jnp.float32)
    assert guarded.engine == "resident"
    assert guarded.recoveries == ()
    assert int(guarded.result.iters) == int(clean.iters)

    guarded = guarded_solve(
        PROBLEM, "resident", jnp.float32,
        faults=FaultPlan(simulate_oom()),
    )
    assert guarded.engine != "resident"
    assert [event.kind for event in guarded.recoveries] == ["engine-fallback"]
    # the event's engine field names the engine fallen back TO — the
    # same convention as the chunked path's fallback events
    assert guarded.recoveries[0].engine == guarded.engine
    assert int(guarded.result.iters) == int(clean.iters)
    assert bool(guarded.result.converged)


def test_whole_solve_guard_rejects_carry_faults():
    with pytest.raises(ValueError, match="chunked engine"):
        guarded_solve(
            PROBLEM, "resident", jnp.float32,
            faults=FaultPlan(inject_nan(5, "r")),
        )


# ------------------------------------------------------- CLI contract


def test_cli_guard_flag_and_recoveries_field(capsys):
    rc = harness_main(
        ["20", "20", "--mode", "single", "--engine", "xla", "--guard",
         "--json"]
    )
    assert rc == 0
    record = json.loads(capsys.readouterr().out.strip())
    assert record["converged"] is True
    assert "recoveries" not in record  # healthy run: the key is absent


def test_cli_timeout_exit_code_and_partial_artifact(tmp_path, capsys):
    trace_file = str(tmp_path / "t.jsonl")
    # timeout 0: already expired at the first chunk-boundary check, so
    # the cancel is deterministic regardless of jit-cache warmth
    rc = harness_main(
        ["40", "40", "--mode", "single", "--timeout", "0", "--json",
         "--trace", trace_file]
    )
    assert rc == 4
    record = json.loads(capsys.readouterr().out.strip())
    assert record["aborted"] == "timeout"
    # the partial trace artifact is schema-valid and carries the abort
    assert obs_trace.validate_file(trace_file) == []
    names = {r["name"] for r in obs_trace.read_jsonl(trace_file)}
    assert "recovery:timeout" in names
    assert "run_report_partial" in names


def test_cli_inject_subcommand_recovers(tmp_path, capsys):
    trace_file = str(tmp_path / "inject.jsonl")
    rc = harness_main(
        ["inject", "nan", "20", "20", "--at", "10", "--chunk", "8",
         "--json", "--trace", trace_file]
    )
    assert rc == 0
    record = json.loads(capsys.readouterr().out.strip())
    assert record["converged"] is True
    assert record["recoveries"] == ["residual-restart"]
    assert obs_trace.validate_file(trace_file) == []
    names = {r["name"] for r in obs_trace.read_jsonl(trace_file)}
    assert "recovery:residual-restart" in names
    assert "inject_report" in names


def test_cli_inject_persistent_fault_classified_exit(capsys):
    rc = harness_main(
        ["inject", "nan", "20", "20", "--at", "10", "--chunk", "8",
         "--persistent", "--json"]
    )
    assert rc == 2
    record = json.loads(capsys.readouterr().out.strip())
    assert record["aborted"] == "diverged"


def test_cli_inject_invalid_fault_spec_is_curated_and_stops_tracer(
    tmp_path, capsys
):
    # an invalid spec after tracer start must exit 2 with a curated
    # message AND release the process-global tracer (no leak into later
    # in-process callers)
    trace_file = str(tmp_path / "bad.jsonl")
    rc = harness_main(
        ["inject", "nan", "20", "20", "--at", "-1", "--trace", trace_file]
    )
    assert rc == 2
    assert "at_iter" in capsys.readouterr().err
    assert obs_trace.active() is None


def test_cli_timeout_rejects_native_mode(capsys):
    rc = harness_main(
        ["20", "20", "--mode", "native", "--timeout", "5"]
    )
    assert rc == 2
    assert "native" in capsys.readouterr().err


# ------------------- the new axes: s-step cells + bf16-storage cells


@pytest.mark.parametrize("fault", ("nan", "breakdown"))
def test_sstep_fault_matrix_recovers_to_parity(fault):
    """sstep × {nan, breakdown}: the classical carry layout means the
    classical recover applies verbatim — one residual restart, parity
    within ±2 of the clean s-step run."""
    clean = clean_result("sstep")
    guarded = guarded_solve(
        PROBLEM, "sstep", jnp.float32, chunk=CHUNK,
        faults=FaultPlan(FAULTS[fault]()),
    )
    kinds = [event.kind for event in guarded.recoveries]
    assert kinds == ["residual-restart"], (fault, kinds)
    assert_parity(guarded, clean, "sstep")


def test_sstep_fallback_hands_carry_to_pipelined_then_classical():
    """The sstep fallback ladder, walked adapter by adapter: the
    mid-solve classical-layout carry hands over to the PIPELINED
    recurrence through a ground-truth rebuild (x and the direction p
    carry across), and the pipelined adapter's own fallback continues
    to classical — each rung reconverging to the clean answer."""
    sstep_ad = _ClassicalAdapter(PROBLEM, jnp.float32, sstep_s=4)
    assert sstep_ad.engine == "sstep"
    mid = sstep_ad.advance(sstep_ad.init(), 12)
    # rung 1: sstep → pipelined, carry handoff
    pipe_ad, convert = sstep_ad.fallback()
    assert pipe_ad.engine == "pipelined"
    pipe_state = pipe_ad.recover(convert(mid))
    done = pipe_ad.advance(pipe_state, PROBLEM.max_iterations)
    res = pipe_ad.result(done)
    assert bool(res.converged)
    clean = clean_result("xla")
    np.testing.assert_allclose(
        np.asarray(res.w), np.asarray(clean.w), rtol=0, atol=5e-5
    )
    # rung 2: pipelined → classical exists (the pre-existing ladder)
    cl_ad, _ = pipe_ad.fallback()
    assert cl_ad.engine == "xla"


def test_sstep_persistent_fault_exhausts_ladder_classified():
    """A persistent NaN re-fires down every rung (sstep → pipelined →
    classical → f64): the contracted outcome is the classified
    DivergedError (exit 2), never a NaN dressed as converged."""
    plan = FaultPlan(
        Fault("nan", at_iter=FAULT_AT, field="r", persistent=True)
    )
    with pytest.raises(DivergedError) as exc:
        guarded_solve(
            PROBLEM, "sstep", jnp.float32, chunk=CHUNK, faults=plan,
            max_recoveries=6,
        )
    assert exc.value.exit_code == 2


@pytest.mark.parametrize("fault", ("nan", "breakdown"))
def test_bf16_storage_fault_cells_recover_through_promotion(fault):
    """bf16-storage × {nan, breakdown}: the fault fires inside the
    narrow phase; the ladder (restart → storage promotion) still ends
    at a full-width converged result at f32-level analytic accuracy."""
    from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic

    ref = clean_result("xla")
    ref_l2 = float(l2_error_vs_analytic(PROBLEM, ref.w))
    guarded = guarded_solve(
        PROBLEM, "xla", jnp.float32, chunk=CHUNK,
        storage_dtype="bf16", faults=FaultPlan(FAULTS[fault]()),
        max_recoveries=5,
    )
    assert bool(guarded.result.converged)
    got = float(l2_error_vs_analytic(
        PROBLEM, guarded.result.w.astype(jnp.float32)
    ))
    assert got <= 1.05 * ref_l2, (fault, got, ref_l2)
    kinds = [event.kind for event in guarded.recoveries]
    assert "storage-promotion" in kinds or "precision-escalation" in kinds


def test_bf16_storage_false_convergence_is_promoted_not_returned():
    """The raw bf16 classical loop 'converges' at the storage floor
    (diff < δ on quantised steps) with a true residual orders above an
    f32 run's — the guard must never return that carry as-is: the
    promotion rung re-earns convergence at full width first."""
    guarded = guarded_solve(
        PROBLEM, "xla", jnp.float32, chunk=64, storage_dtype="bf16"
    )
    assert bool(guarded.result.converged)
    # the finishing adapter runs at full width (dtype reported f32)
    assert guarded.dtype == "float32"
    a, b, rhs = __import__(
        "poisson_ellipse_tpu.ops.assembly", fromlist=["assemble"]
    ).assemble(PROBLEM, jnp.float32)
    from poisson_ellipse_tpu.ops.stencil import apply_a

    h1 = jnp.asarray(PROBLEM.h1, jnp.float32)
    h2 = jnp.asarray(PROBLEM.h2, jnp.float32)
    w = guarded.result.w.astype(jnp.float32)
    resid = float(jnp.linalg.norm(rhs - apply_a(w, a, b, h1, h2)))
    rhsn = float(jnp.linalg.norm(rhs))
    assert resid / rhsn < 1e-2  # the drift gate's bar, met at full width
