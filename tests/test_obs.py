"""obs/: on-device convergence history, JSONL tracing, static cost.

The observability layer's three contracts, each pinned:

- **History** — ``solve(..., history=True)`` returns the per-iteration
  (zr, diff, α, β) series recorded *inside* the fused while_loop; the
  buffers match a plain Python-loop replay of the recurrence exactly,
  the iterates are bit-identical with history on/off, and with history
  OFF the emitted jaxpr is exactly the historyless one (the feature
  costs zero when disabled).
- **Trace** — the JSONL emitter round-trips through its own validator;
  PhaseTimer is a shim over it; the report formatting guards its zero
  cases.
- **Static cost** — psum/ppermute per iteration read from the jaxpr via
  the product metric (``obs.static_cost``): classical sharded loop 2
  psum, pipelined 1, on a CPU mesh.
"""

from __future__ import annotations

import io
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import metrics as obs_metrics
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.obs.convergence import HISTORY_FIELDS
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.reduction import grid_dot, grid_dots
from poisson_ellipse_tpu.ops.stencil import apply_a, apply_dinv, diag_d
from poisson_ellipse_tpu.solver.pcg import DENOM_GUARD, pcg
from poisson_ellipse_tpu.solver.engine import solve as engine_solve


@pytest.fixture(autouse=True)
def _clean_trace_state():
    """Every test starts and ends with no ambient tracer and a clean
    default metrics registry (both are process-global by design)."""
    obs_trace.stop()
    obs_trace._env_checked = True  # tests control tracing explicitly
    obs_metrics.REGISTRY.reset()
    yield
    obs_trace.stop()
    obs_metrics.REGISTRY.reset()


# ------------------------------------------------------- history: values


def python_reference_trajectory(problem: Problem, a, b, rhs):
    """The classical recurrence replayed as a plain eager Python loop —
    the textbook form of ``solver.pcg.advance``'s body, with loop
    control, convergence decision and recording all on the HOST (the
    structure the on-device buffers replace)."""
    dtype = rhs.dtype
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    delta = float(problem.delta)
    weighted = problem.norm == "weighted"
    d = diag_d(a, b, h1, h2)
    r = rhs
    z = apply_dinv(r, d)
    p = z
    zr = grid_dot(z, r, h1, h2)
    w = jnp.zeros_like(rhs)
    rows = {name: [] for name in HISTORY_FIELDS}
    for _k in range(problem.max_iterations):
        ap = apply_a(p, a, b, h1, h2)
        denom = grid_dot(ap, p, h1, h2)
        assert float(denom) >= DENOM_GUARD, "reference replay hit breakdown"
        alpha = zr / denom
        w_new = w + alpha * p
        r_new = r - alpha * ap
        z = apply_dinv(r_new, d)
        dw = w_new - w
        sums = grid_dots((z, r_new), (dw, dw))
        zr_new = sums[0] * h1 * h2
        diff = jnp.sqrt(sums[1] * h1 * h2) if weighted else jnp.sqrt(sums[1])
        beta = zr_new / zr
        for name, val in zip(HISTORY_FIELDS, (zr_new, diff, alpha, beta)):
            rows[name].append(float(val))
        if float(diff) < delta:  # the host-side convergence decision
            break
        w, r, p, zr = w_new, r_new, z + beta * p, zr_new
    return {name: np.asarray(vals) for name, vals in rows.items()}


def test_history_matches_python_loop_reference():
    """Two references, two strengths of claim.

    (1) *Bit-exact* against a host-driven replay through the same
    compiled loop body: ``advance(limit=k)`` one iteration per dispatch
    (the chunking contract — chunking moves the while_loop boundary, not
    the arithmetic), harvesting zr/diff from the returned carries and β
    as the IEEE quotient of consecutive carried zr values. This proves
    the buffers record THE loop's values, not a reconstruction.

    (2) Within f64 round-off of the textbook eager Python replay for all
    four series (separately compiled computations may fuse reductions
    differently, so cross-compilation bit-equality is not a meaningful
    target — 1e-12 relative is)."""
    from poisson_ellipse_tpu.solver.pcg import advance, init_state

    problem = Problem(M=20, N=20)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    result, trace = pcg(problem, a, b, rhs, history=True)
    assert bool(result.converged)
    n = int(result.iters)
    got = trace.valid()

    # (1) host-driven replay, bit-exact
    state = init_state(problem, a, b, rhs)
    zr_carry = [float(state[4])]  # zr entering iteration k
    host_diff = []
    for k in range(1, n + 1):
        state = advance(problem, a, b, rhs, state, limit=k)
        host_diff.append(float(state[5]))
        zr_carry.append(float(state[4]))
    assert int(state[0]) == n and bool(state[6])
    np.testing.assert_array_equal(got["diff"], np.asarray(host_diff))
    # the terminal iteration freezes zr in the carry (the trace records
    # the raw zr_new); every non-terminal entry must match bitwise
    np.testing.assert_array_equal(
        got["zr"][:-1], np.asarray(zr_carry[1:n])
    )
    host_beta = np.asarray(
        [zr_carry[k + 1] / zr_carry[k] for k in range(n - 1)]
    )
    np.testing.assert_array_equal(got["beta"][:-1], host_beta)

    # (2) textbook eager replay, to f64 round-off
    want = python_reference_trajectory(problem, a, b, rhs)
    assert n == len(want["zr"])
    for name in HISTORY_FIELDS:
        np.testing.assert_allclose(
            got[name], want[name], rtol=1e-12, err_msg=name
        )
    # past-the-end entries stay zero (preallocated, never touched)
    tail = np.asarray(trace.zr)[n:]
    assert tail.size and not tail.any()


def test_history_off_is_bitwise_identical_and_free():
    """history=False must (a) be the default, (b) emit EXACTLY the same
    jaxpr as the default path — no dynamic_update_slice, original
    8-tuple carry (the declared ``history-free`` contract) — and (c)
    history=True must not perturb one bit of the iterates."""
    from poisson_ellipse_tpu.analysis.contracts import assert_contract

    problem = Problem(M=20, N=20)
    assert_contract(
        "history-free", "xla", problem=problem, dtype=jnp.float64
    )

    a, b, rhs = assembly.assemble(problem, jnp.float64)
    plain = pcg(problem, a, b, rhs)
    traced, _ = pcg(problem, a, b, rhs, history=True)
    np.testing.assert_array_equal(np.asarray(plain.w), np.asarray(traced.w))
    assert int(plain.iters) == int(traced.iters)
    assert float(plain.diff) == float(traced.diff)


def test_history_on_stays_device_resident():
    """The recording path must be pure array ops: no callback primitives,
    no device_get — 'zero extra host syncs' as a structural property
    (the declared ``history-resident`` contract)."""
    from poisson_ellipse_tpu.analysis.contracts import assert_contract

    assert_contract(
        "history-resident", "xla", problem=Problem(M=10, N=10),
        dtype=jnp.float64,
    )


# ------------------------------------------------------ history: engines


@pytest.mark.parametrize(
    "engine", ["xla", "pallas", "fused", "pipelined", "pipelined-pallas"]
)
def test_history_on_every_single_chip_engine(engine):
    """Every XLA-loop engine returns (PCGResult, ConvergenceTrace) with
    a self-consistent trace: the final recorded diff is the solver's own
    diff (the trace records the loop, not a reconstruction), and the
    converged iteration's step-norm is below δ."""
    problem = Problem(M=20, N=20)
    plain = engine_solve(problem, engine, jnp.float32)
    result, trace = engine_solve(problem, engine, jnp.float32, history=True)
    np.testing.assert_array_equal(np.asarray(plain.w), np.asarray(result.w))
    assert int(plain.iters) == int(result.iters)
    v = trace.valid()
    n = int(result.iters)
    assert all(v[name].shape == (n,) for name in HISTORY_FIELDS)
    assert v["diff"][-1] == float(result.diff)
    assert v["diff"][-1] < problem.delta
    assert np.isfinite(v["zr"]).all() and (v["zr"] > 0).all()


def test_history_breakdown_records_zero_alpha():
    """A breakdown iteration applies no update, so every engine's trace
    records α = 0 for it — identical telemetry for the identical event
    (the fused kernel's in-kernel guard and the XLA loops' recording
    must not disagree)."""
    from poisson_ellipse_tpu.ops.fused_pcg import pcg_fused
    from poisson_ellipse_tpu.ops.pipelined_pcg import pcg_pipelined

    problem = Problem(M=10, N=10)
    _, _, rhs = assembly.assemble(problem, jnp.float64)
    zeros = jnp.zeros_like(rhs)
    for fn in (pcg, pcg_pipelined):
        result, trace = fn(problem, zeros, zeros, rhs, history=True)
        assert bool(result.breakdown) and int(result.iters) == 1, fn
        assert float(trace.alpha[0]) == 0.0, fn
    rhs32 = rhs.astype(jnp.float32)
    z32 = jnp.zeros_like(rhs32)
    result, trace = pcg_fused(problem, z32, z32, rhs32, history=True)
    assert bool(result.breakdown) and float(trace.alpha[0]) == 0.0


def test_history_on_sharded_engine():
    from poisson_ellipse_tpu.parallel.mesh import make_mesh
    from poisson_ellipse_tpu.parallel.pcg_sharded import solve_sharded

    problem = Problem(M=20, N=20)
    mesh = make_mesh(jax.devices()[:2])
    plain = solve_sharded(problem, mesh, jnp.float64)
    result, trace = solve_sharded(problem, mesh, jnp.float64, history=True)
    np.testing.assert_array_equal(np.asarray(plain.w), np.asarray(result.w))
    assert int(plain.iters) == int(result.iters)
    v = trace.valid()
    assert v["diff"][-1] == float(result.diff)
    # the sharded trace must equal the single-chip one bit for bit: the
    # psum-reduced scalars are the same values the single loop computes
    _, single = pcg(
        problem, *assembly.assemble(problem, jnp.float64), history=True
    )
    sv = single.valid()
    for name in HISTORY_FIELDS:
        np.testing.assert_allclose(
            v[name], sv[name], rtol=1e-12, err_msg=name
        )


def test_history_unsupported_engines_fail_loudly_and_auto_degrades():
    from poisson_ellipse_tpu.solver.engine import build_solver

    problem = Problem(M=10, N=10)
    with pytest.raises(ValueError, match="history"):
        build_solver(problem, "resident", jnp.float32, history=True)
    _, _, resolved = build_solver(problem, "auto", jnp.float32, history=True)
    assert resolved == "xla"
    with pytest.raises(ValueError, match="history"):
        from poisson_ellipse_tpu.parallel.pcg_sharded import (
            build_sharded_solver,
        )

        build_sharded_solver(
            problem, stencil_impl="pipelined", history=True
        )


# ------------------------------------------------------------- trace


def test_trace_jsonl_roundtrips_and_validates(tmp_path):
    path = tmp_path / "run.jsonl"
    tracer = obs_trace.start(path)
    with obs_trace.span("phase:init", grid="20x20"):
        pass
    obs_trace.event("run_report", iters=26, converged=True)
    obs_metrics.counter("runs").inc()
    obs_metrics.gauge("last_iters").set(26)
    obs_metrics.REGISTRY.emit()
    run_id = tracer.run_id
    obs_trace.stop()

    records = obs_trace.read_jsonl(path)
    assert obs_trace.validate_file(path) == []
    kinds = [r["kind"] for r in records]
    assert kinds == ["meta", "span", "event", "counter", "gauge"]
    assert all(r["run"] == run_id for r in records)
    span = records[1]
    assert span["name"] == "phase:init" and span["dur"] >= 0
    assert span["fields"] == {"grid": "20x20"}
    assert records[3] == {
        "v": obs_trace.SCHEMA_VERSION, "run": run_id, "t": records[3]["t"],
        "kind": "counter", "name": "runs", "value": 1.0,
    }


def test_trace_validator_rejects_malformed_records():
    ok = {"v": 1, "run": "r1", "t": 0.5, "kind": "event", "name": "x"}
    assert obs_trace.validate_record(ok) is None
    # v1 (pre-lane), v2 (lane) and v3 (request_id) records all validate
    assert obs_trace.validate_record({**ok, "v": 2}) is None
    assert obs_trace.validate_record({**ok, "v": 3}) is None
    bad = [
        ({**ok, "kind": "bogus"}, "kind"),
        ({k: v for k, v in ok.items() if k != "run"}, "run"),
        ({**ok, "v": 99}, "version"),
        ({**ok, "t": -1}, "t must"),
        ({**ok, "extra": 1}, "unknown"),
        ({**ok, "kind": "span"}, "dur"),
        ({**ok, "kind": "gauge"}, "value"),
        ({**ok, "fields": [1]}, "fields"),
        ({**ok, "lane": -1}, "lane"),
        ({**ok, "lane": 1.5}, "lane"),
        ({**ok, "lane": True}, "lane"),
        ({**ok, "request_id": ""}, "request_id"),
        ({**ok, "request_id": 7}, "request_id"),
        ("not a dict", "object"),
    ]
    for rec, needle in bad:
        err = obs_trace.validate_record(rec)
        assert err is not None and needle in err, (rec, err)


def test_lane_addressed_events_validate_first_class(tmp_path):
    """The batched driver's quarantine events carry ``lane`` as a
    top-level schema key (v2), not a permissive fields poke — a lane
    filter needs no JSON spelunking, and the validator checks it."""
    ok = {"v": 2, "run": "r1", "t": 0.5, "kind": "event",
          "name": "recovery:lane-quarantine", "lane": 3}
    assert obs_trace.validate_record(ok) is None
    path = tmp_path / "lane.jsonl"
    obs_trace.start(path)
    obs_trace.event("recovery:lane-quarantine", lane=2, detail="lane 2")
    obs_trace.event("unaddressed")  # lane stays optional
    obs_trace.stop()
    assert obs_trace.validate_file(path) == []
    recs = obs_trace.read_jsonl(path)
    assert recs[1]["lane"] == 2 and "lane" not in recs[2]


def test_request_addressed_events_validate_first_class(tmp_path):
    """The serve scheduler's lifecycle events carry ``request_id`` as a
    top-level schema key (v3): one request's whole story — admit,
    refill, retire, shed, retry, replay — greps out of a mixed stream
    with no fields poke, and the validator checks the key's shape."""
    ok = {"v": 3, "run": "r1", "t": 0.5, "kind": "event",
          "name": "serve:admit", "request_id": "req-0001"}
    assert obs_trace.validate_record(ok) is None
    path = tmp_path / "request.jsonl"
    obs_trace.start(path)
    obs_trace.event("serve:admit", request_id="req-7", depth=3)
    obs_trace.event("serve:refill", request_id="req-7", lane=1)
    obs_trace.event("unaddressed")  # request_id stays optional
    obs_trace.stop()
    assert obs_trace.validate_file(path) == []
    recs = obs_trace.read_jsonl(path)
    assert recs[1]["request_id"] == "req-7"
    # lane and request_id compose on one record (refill names both)
    assert recs[2]["request_id"] == "req-7" and recs[2]["lane"] == 1
    assert "request_id" not in recs[3]


def test_histogram_window_occupancy_staleness_guard(tmp_path):
    """The stalled-server guard (ISSUE 7 satellite): window occupancy
    rides next to the quantiles in the summary, the OpenMetrics
    rendering and the trace emit — so a frozen p99 with a full window
    and a non-advancing count reads as a stall, not a quiet server."""
    from poisson_ellipse_tpu.obs import export as obs_export

    h = obs_metrics.Histogram("lat")
    assert h.window_occupancy == 0
    for v in (1.0, 2.0, 3.0):
        h.observe(v)
    assert h.window_occupancy == 3
    assert h.summary()["window"] == 3
    # occupancy saturates at the window bound while count keeps moving
    for _ in range(obs_metrics.HISTOGRAM_WINDOW + 5):
        h.observe(0.0)
    assert h.window_occupancy == obs_metrics.HISTOGRAM_WINDOW
    assert h.count == 3 + obs_metrics.HISTOGRAM_WINDOW + 5

    # OpenMetrics: the `<name>_window` sample renders inside the summary
    # family and round-trips through the parser/validator
    reg = obs_metrics.MetricsRegistry()
    reg.histogram("solve_seconds").observe(0.5)
    text = obs_export.render_openmetrics(reg.snapshot())
    assert obs_export.validate_openmetrics(text) == []
    assert "poisson_solve_seconds_window 1" in text
    parsed = obs_export.parse_openmetrics(text)
    assert parsed["histograms"]["poisson_solve_seconds"]["window"] == 1.0

    # the trace emit publishes the occupancy gauge
    path = tmp_path / "window.jsonl"
    tracer = obs_trace.start(path)
    reg.emit(tracer)
    obs_trace.stop()
    names = {
        (r["kind"], r["name"]) for r in obs_trace.read_jsonl(path)
    }
    assert ("gauge", "solve_seconds_window") in names


def test_batched_driver_emits_lane_on_quarantine_events(tmp_path):
    from poisson_ellipse_tpu.batch import solve_batched
    from poisson_ellipse_tpu.resilience import FaultPlan, inject_nan

    problem = Problem(M=10, N=10)
    path = tmp_path / "quarantine.jsonl"
    obs_trace.start(path)
    try:
        guarded = solve_batched(
            problem, 3, "batched", jnp.float32, chunk=4,
            faults=FaultPlan(inject_nan(4, "r", lane=1)),
        )
    finally:
        obs_trace.stop()
    assert list(np.asarray(guarded.result.quarantined)) == [
        False, True, False,
    ]
    assert obs_trace.validate_file(path) == []
    quar = [
        r for r in obs_trace.read_jsonl(path)
        if r["name"] == "recovery:lane-quarantine"
    ]
    assert quar and quar[0]["lane"] == 1


def test_trace_inactive_is_a_noop_and_env_activates(tmp_path, monkeypatch):
    # inactive: span/event/note must not raise and must not write
    with obs_trace.span("phase:x"):
        pass
    obs_trace.event("nothing")
    err = io.StringIO()
    obs_trace.note("hello", file=err)
    assert err.getvalue() == "hello\n"
    # POISSON_TRACE starts a tracer lazily on first active() lookup
    path = tmp_path / "env.jsonl"
    monkeypatch.setenv(obs_trace.ENV_VAR, str(path))
    obs_trace._env_checked = False
    obs_trace.event("from-env", x=1)
    obs_trace.stop()
    names = [r["name"] for r in obs_trace.read_jsonl(path)]
    assert names == ["trace-start", "from-env"]


def test_note_emits_structured_twin_when_tracing(tmp_path, capsys):
    path = tmp_path / "note.jsonl"
    obs_trace.start(path)
    obs_trace.note("  40x40: converged", row=1)
    obs_trace.stop()
    assert "40x40: converged" in capsys.readouterr().err
    recs = obs_trace.read_jsonl(path)
    assert recs[-1]["fields"] == {"message": "  40x40: converged", "row": 1}


def test_metrics_registry_snapshot_and_kind_collisions():
    reg = obs_metrics.MetricsRegistry()
    reg.counter("a").inc()
    reg.counter("a").inc(2)
    reg.gauge("b").set(7)
    assert reg.snapshot() == {
        "counters": {"a": 3.0}, "gauges": {"b": 7.0}, "histograms": {},
    }
    with pytest.raises(ValueError, match="already a counter"):
        reg.gauge("a")
    with pytest.raises(ValueError, match="already a counter"):
        reg.histogram("a")
    with pytest.raises(ValueError, match="already a gauge"):
        reg.counter("b")
    with pytest.raises(ValueError, match="cannot decrease"):
        reg.counter("a").inc(-1)
    assert reg.gauge("unset") and reg.snapshot()["gauges"] == {"b": 7.0}


def test_metrics_snapshot_is_name_sorted_not_creation_ordered():
    reg = obs_metrics.MetricsRegistry()
    for name in ("zeta", "alpha", "mid"):
        reg.counter(name).inc()
        reg.gauge(f"g_{name}").set(1)
        reg.histogram(f"h_{name}").observe(0.5)
    snap = reg.snapshot()
    assert list(snap["counters"]) == ["alpha", "mid", "zeta"]
    assert list(snap["gauges"]) == ["g_alpha", "g_mid", "g_zeta"]
    assert list(snap["histograms"]) == ["h_alpha", "h_mid", "h_zeta"]


def test_histogram_quantiles_and_window():
    h = obs_metrics.Histogram("t")
    assert h.quantile(0.5) is None
    for v in range(1, 101):
        h.observe(float(v))
    assert h.count == 100 and h.sum == 5050.0
    assert h.quantile(0.5) == 51.0  # nearest-rank over the window
    assert h.quantile(0.9) == 91.0
    assert h.quantile(0.99) == 100.0
    s = h.summary()
    assert s["count"] == 100 and s["p50"] == 51.0 and s["p99"] == 100.0
    with pytest.raises(ValueError):
        h.quantile(1.5)
    # the window is bounded: count/sum stay lifetime totals
    for v in range(obs_metrics.HISTOGRAM_WINDOW + 10):
        h.observe(0.0)
    assert h.count == 100 + obs_metrics.HISTOGRAM_WINDOW + 10
    assert len(h._window) == obs_metrics.HISTOGRAM_WINDOW
    assert h.quantile(0.99) == 0.0  # old observations aged out


def test_metrics_emit_guards_closed_tracer_and_publishes_histograms(tmp_path):
    path = tmp_path / "metrics.jsonl"
    tracer = obs_trace.start(path)
    reg = obs_metrics.MetricsRegistry()
    reg.counter("runs").inc()
    reg.histogram("lat").observe(2.0)
    reg.emit(tracer)
    obs_trace.stop()
    # a late emit into the closed tracer is a no-op, not a ValueError
    assert tracer.closed
    reg.emit(tracer)
    recs = obs_trace.read_jsonl(path)
    assert obs_trace.validate_file(path) == []
    names = {(r["kind"], r["name"]) for r in recs}
    assert ("counter", "runs") in names
    assert ("counter", "lat_count") in names
    assert ("gauge", "lat_p50") in names and ("gauge", "lat_sum") in names


# ---------------------------------------------------------- export


def test_openmetrics_renders_and_roundtrips_through_validator():
    from poisson_ellipse_tpu.obs import export as obs_export

    reg = obs_metrics.MetricsRegistry()
    reg.counter("runs").inc(3)
    reg.gauge("last_iters").set(546)
    for v in (0.001, 0.002, 0.004):
        reg.histogram("solve_seconds").observe(v)
    snap = reg.snapshot()
    text = obs_export.render_openmetrics(snap)
    assert obs_export.validate_openmetrics(text) == []
    assert text.endswith("# EOF\n")
    assert "# TYPE poisson_runs counter" in text
    assert "poisson_runs_total 3" in text
    assert 'poisson_solve_seconds{quantile="0.5"} 0.002' in text
    parsed = obs_export.parse_openmetrics(text)
    assert parsed["counters"] == {"poisson_runs": 3.0}
    assert parsed["gauges"] == {"poisson_last_iters": 546.0}
    hist = parsed["histograms"]["poisson_solve_seconds"]
    assert hist["count"] == 3.0 and hist["p50"] == 0.002
    # determinism: same registry renders byte-identically
    assert obs_export.render_openmetrics(reg.snapshot()) == text


def test_openmetrics_validator_rejects_malformed_expositions():
    from poisson_ellipse_tpu.obs import export as obs_export

    assert obs_export.validate_openmetrics("junk line\n# EOF\n")
    assert obs_export.validate_openmetrics("# TYPE x counter\nx_total 1\n")
    assert obs_export.validate_openmetrics(
        "x_total 1\n# TYPE x counter\n# EOF\n"
    )  # sample precedes its TYPE
    assert obs_export.validate_openmetrics(
        "# TYPE x counter\nx_total nan-ish\n# EOF\n"
    )
    assert obs_export.validate_openmetrics(
        "# TYPE x counter\n# TYPE x counter\nx_total 1\n# EOF\n"
    )
    # odd metric names sanitize into the grammar instead of failing
    assert obs_export.metric_name("95th %ile latency!", "p") == \
        "p_95th__ile_latency_"


def test_metrics_exporter_writes_atomic_snapshots(tmp_path):
    from poisson_ellipse_tpu.obs import export as obs_export

    reg = obs_metrics.MetricsRegistry()
    reg.counter("writes").inc()
    path = tmp_path / "metrics.prom"
    exporter = obs_export.MetricsExporter(path, registry=reg)
    assert exporter.write() == str(path)
    text = path.read_text()
    assert obs_export.validate_openmetrics(text) == []
    assert "poisson_writes_total 1" in text
    # no temp droppings next to the snapshot
    assert [p.name for p in tmp_path.iterdir()] == ["metrics.prom"]
    with pytest.raises(ValueError, match="interval_s"):
        exporter.start()
    # Event.wait(0) returns immediately: a zero cadence would busy-spin
    with pytest.raises(ValueError, match="positive"):
        obs_export.MetricsExporter(path, registry=reg, interval_s=0).start()
    # periodic mode: the context manager flushes at exit at minimum
    reg.counter("writes").inc()
    with obs_export.MetricsExporter(
        path, registry=reg, interval_s=30.0
    ):
        pass
    assert "poisson_writes_total 2" in path.read_text()


# ---------------------------------------------------------- PhaseTimer


def test_phase_timer_report_zero_guard_and_stable_order():
    from poisson_ellipse_tpu.utils.timing import PhaseTimer

    t = PhaseTimer()
    assert t.report() == ""  # 0 phases: renders, no division
    t.add("solver", 0.0)
    t.add("init", 0.0)
    zero = t.report()
    assert "0.0%" in zero  # 0-second total: guarded percentage
    # name-sorted, not insertion-sorted: diffs cleanly across runs
    assert zero.index("T_init") < zero.index("T_solver")
    t.add("solver", 3.0)
    t.add("init", 1.0)
    lines = t.report().splitlines()
    assert "25.0%" in lines[0] and "75.0%" in lines[1]


def test_phase_timer_is_a_trace_shim(tmp_path):
    from poisson_ellipse_tpu.utils.timing import PhaseTimer

    path = tmp_path / "phases.jsonl"
    obs_trace.start(path)
    t = PhaseTimer()
    with t.phase("init"):
        pass
    t.add("solver", 1.5)
    obs_trace.stop()
    recs = obs_trace.read_jsonl(path)
    spans = {r["name"]: r for r in recs if r["kind"] == "span"}
    assert set(spans) == {"phase:init", "phase:solver"}
    assert spans["phase:solver"]["dur"] == 1.5
    assert obs_trace.validate_file(path) == []


# ---------------------------------------------------------- static cost


def test_static_cost_classical_two_psum_pipelined_one():
    """THE metric: classical sharded loop = 2 psum/iter, pipelined = 1,
    on a 1×2 CPU mesh — the same engine_report record harness inspect
    prints and bench.py's artifact asserts."""
    from poisson_ellipse_tpu.obs.static_cost import engine_report

    problem = Problem(M=20, N=20)
    classical = engine_report(
        problem, "xla", mode="sharded", mesh_shape=(1, 2), with_xla_cost=False
    )
    pipelined = engine_report(
        problem, "pipelined", mode="sharded", mesh_shape=(1, 2),
        with_xla_cost=False,
    )
    assert classical["psum_per_iter"] == 2
    assert pipelined["psum_per_iter"] == 1
    assert classical["ppermute_per_iter"] == 4  # the halo ring
    assert classical["collectives_per_iter"] == {"psum": 2, "ppermute": 4}


def test_static_cost_single_chip_and_modeled_columns():
    from poisson_ellipse_tpu.obs.static_cost import engine_report

    problem = Problem(M=20, N=20)
    rep = engine_report(problem, "xla", mode="single")
    assert rep["psum_per_iter"] == 0 and rep["ppermute_per_iter"] == 0
    assert rep["modeled_passes_per_iter"] == 13.0
    g1, g2 = problem.node_shape
    assert rep["modeled_hbm_bytes_per_iter"] == 13.0 * g1 * g2 * 4
    # CPU XLA exposes a cost analysis: the measured-vs-modeled column
    # exists (values are backend estimates, only presence is pinned)
    assert rep["flops_per_iter_est"] is None or rep["flops_per_iter_est"] > 0


def test_collectives_table_shape():
    from poisson_ellipse_tpu.obs.static_cost import collectives_table

    t = collectives_table(Problem(M=20, N=20))
    assert t["available"] is True and t["mesh"] == [1, 2]
    assert t["engines"]["xla"]["psum_per_iter"] == 2
    assert t["engines"]["pipelined"]["psum_per_iter"] == 1


def test_multichip_table_carries_collectives():
    from poisson_ellipse_tpu.harness.bench_multichip import scaling_table

    t = scaling_table("strong", (20, 20), [(1, 2)], stencil_impl="pipelined")
    assert t["collectives_per_iter"]["psum"] == 1
    t2 = scaling_table("strong", (20, 20), [(1, 2)])
    assert t2["collectives_per_iter"]["psum"] == 2


# -------------------------------------------------------- inspect CLI


def test_harness_inspect_subcommand(capsys):
    from poisson_ellipse_tpu.harness.__main__ import main

    rc = main([
        "inspect", "pipelined", "--mode", "sharded", "--mesh", "1", "2",
        "--grid", "20x20", "--no-xla-cost", "--json",
    ])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["engine"] == "pipelined" and rep["psum_per_iter"] == 1

    rc = main(["inspect", "xla", "--grid", "10x10", "--no-xla-cost"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "psum/iter" in out and "modeled HBM bytes/iter" in out

    assert main(["inspect", "resident", "--mode", "sharded"]) == 2
    assert "error" in capsys.readouterr().err


def test_harness_trace_flag_end_to_end(tmp_path, capsys):
    from poisson_ellipse_tpu.harness.__main__ import main

    path = tmp_path / "cli.jsonl"
    rc = main(["10", "10", "--mode", "single", "--trace", str(path), "--json"])
    assert rc == 0
    assert obs_trace.validate_file(path) == []
    names = [r["name"] for r in obs_trace.read_jsonl(path)]
    for expected in ("trace-start", "cli-args", "phase:init", "phase:solver",
                     "phase:finalize", "run_report", "runs", "cli-exit"):
        assert expected in names, (expected, names)
    # the CLI closed its tracer: nothing ambient leaks into later runs
    assert obs_trace.active() is None


def test_harness_metrics_flag_writes_openmetrics_snapshot(tmp_path, capsys):
    from poisson_ellipse_tpu.harness.__main__ import main
    from poisson_ellipse_tpu.obs import export as obs_export

    path = tmp_path / "run.prom"
    rc = main(["10", "10", "--mode", "single", "--metrics", str(path),
               "--json"])
    assert rc == 0
    text = path.read_text()
    assert obs_export.validate_openmetrics(text) == []
    assert "poisson_runs_total 1" in text
    assert "poisson_last_iters" in text
    assert 'poisson_solve_seconds{quantile="0.5"}' in text


# ------------------------------------------------------- golden corpus


def test_trace_golden_corpus_from_a_batched_guarded_run(tmp_path):
    """One recorded batched+guarded run exercising every event family
    the schema carries — phase spans, recovery events (lane-addressed
    quarantine included), cache hit/miss, a bench artifact — validated
    record by record, so schema drift breaks loudly here instead of in
    a consumer's dashboard."""
    from poisson_ellipse_tpu.batch import solve_batched
    from poisson_ellipse_tpu.harness.__main__ import main
    from poisson_ellipse_tpu.resilience import FaultPlan, inject_nan
    from poisson_ellipse_tpu.runtime.compile_cache import WarmPool

    problem = Problem(M=10, N=10)
    path = tmp_path / "corpus.jsonl"
    # the harness CLI contributes the phase:*/run_report/counter records
    rc = main(["10", "10", "--mode", "single", "--trace", str(path),
               "--json"])
    assert rc == 0
    obs_trace.start(path)  # append the serving + resilience families
    try:
        pool = WarmPool()
        pool.warmup("batched", (10, 10), jnp.float32, lanes=3)
        pool.solve(problem, 3, "batched", jnp.float32)
        solve_batched(
            problem, 3, "batched", jnp.float32, chunk=4,
            faults=FaultPlan(inject_nan(4, "r", lane=1)),
        )
        obs_trace.event(
            "bench_artifact", metric="T_solver", value=0.001, valid=True
        )
    finally:
        obs_trace.stop()

    records = obs_trace.read_jsonl(path)
    assert obs_trace.validate_file(path) == []
    names = {r["name"] for r in records}
    for expected in (
        "phase:init", "phase:solver", "phase:finalize",  # phase:*
        "recovery:lane-quarantine",                       # recovery:*
        "cache:miss", "cache:hit",                        # cache:*
        "bench_artifact", "run_report",
    ):
        assert expected in names, (expected, sorted(names))
    lanes = [r for r in records if "lane" in r]
    assert lanes and all(
        isinstance(r["lane"], int) and r["lane"] >= 0 for r in lanes
    )
    kinds = {r["kind"] for r in records}
    assert kinds == {"meta", "span", "event", "counter", "gauge"}


# -------------------------------------------------------- diagnose CLI


def test_harness_diagnose_subcommand(tmp_path, capsys):
    from poisson_ellipse_tpu.harness.__main__ import main

    metrics = tmp_path / "diag.prom"
    trace = tmp_path / "diag.jsonl"
    rc = main([
        "diagnose", "xla", "--grid", "20x20", "--no-xla-cost",
        "--metrics", str(metrics), "--trace", str(trace), "--json",
    ])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    # the acceptance contract: diagnosing changes nothing
    assert rep["bit_identical"] is True
    assert rep["converged"] is True
    spec = rep["spectrum"]
    assert spec["available"] and spec["kappa"] > 1
    assert spec["predicted_iters"] == rep["iters"]  # measured-exact replay
    prof = rep["profile"]
    assert prof["iters"] == rep["iters"]
    assert prof["t_compile_s"] >= 0 and prof["t_solve_s"] > 0
    assert prof["modeled_hbm_bytes_per_iter"] > 0
    # exports validate: OpenMetrics file + schema-valid trace
    from poisson_ellipse_tpu.obs import export as obs_export

    assert obs_export.validate_openmetrics(metrics.read_text()) == []
    assert "poisson_diagnose_kappa" in metrics.read_text()
    assert obs_trace.validate_file(trace) == []
    assert "diagnose_report" in {
        r["name"] for r in obs_trace.read_jsonl(trace)
    }

    # human-readable form names the contract and the spectral story
    rc = main(["diagnose", "xla", "--grid", "10x10", "--no-profile"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "BIT-IDENTICAL" in out and "kappa" in out

    # engines that record no history are a curated error, not a traceback
    assert main(["diagnose", "resident"]) == 2
    assert "history" in capsys.readouterr().err
    # ... as are a bad repeat (checked BEFORE any solve is paid for), a
    # malformed grid, and a zero metrics cadence on the main prog
    assert main(["diagnose", "xla", "--repeat", "0"]) == 2
    assert "repeat" in capsys.readouterr().err
    assert main(["diagnose", "xla", "--grid", "40by40"]) == 2
    assert "error" in capsys.readouterr().err
    assert main(["diagnose", "xla",
                 "--metrics", "/nonexistent-dir/x.prom"]) == 2
    assert "cannot write" in capsys.readouterr().err
    assert main(["10", "10", "--metrics", "x.prom",
                 "--metrics-interval", "0"]) == 2
    assert "metrics-interval" in capsys.readouterr().err
    # an unwritable --metrics path fails FAST with the curated exit-2,
    # not a traceback out of the finally block after a paid-for solve
    assert main(["10", "10", "--metrics", "/nonexistent-dir/x.prom"]) == 2
    assert "cannot write" in capsys.readouterr().err
