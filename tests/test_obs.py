"""obs/: on-device convergence history, JSONL tracing, static cost.

The observability layer's three contracts, each pinned:

- **History** — ``solve(..., history=True)`` returns the per-iteration
  (zr, diff, α, β) series recorded *inside* the fused while_loop; the
  buffers match a plain Python-loop replay of the recurrence exactly,
  the iterates are bit-identical with history on/off, and with history
  OFF the emitted jaxpr is exactly the historyless one (the feature
  costs zero when disabled).
- **Trace** — the JSONL emitter round-trips through its own validator;
  PhaseTimer is a shim over it; the report formatting guards its zero
  cases.
- **Static cost** — psum/ppermute per iteration read from the jaxpr via
  the product metric (``obs.static_cost``): classical sharded loop 2
  psum, pipelined 1, on a CPU mesh.
"""

from __future__ import annotations

import io
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import metrics as obs_metrics
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.obs.convergence import HISTORY_FIELDS
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.reduction import grid_dot, grid_dots
from poisson_ellipse_tpu.ops.stencil import apply_a, apply_dinv, diag_d
from poisson_ellipse_tpu.solver.pcg import DENOM_GUARD, pcg
from poisson_ellipse_tpu.solver.engine import solve as engine_solve


@pytest.fixture(autouse=True)
def _clean_trace_state():
    """Every test starts and ends with no ambient tracer and a clean
    default metrics registry (both are process-global by design)."""
    obs_trace.stop()
    obs_trace._env_checked = True  # tests control tracing explicitly
    obs_metrics.REGISTRY.reset()
    yield
    obs_trace.stop()
    obs_metrics.REGISTRY.reset()


# ------------------------------------------------------- history: values


def python_reference_trajectory(problem: Problem, a, b, rhs):
    """The classical recurrence replayed as a plain eager Python loop —
    the textbook form of ``solver.pcg.advance``'s body, with loop
    control, convergence decision and recording all on the HOST (the
    structure the on-device buffers replace)."""
    dtype = rhs.dtype
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    delta = float(problem.delta)
    weighted = problem.norm == "weighted"
    d = diag_d(a, b, h1, h2)
    r = rhs
    z = apply_dinv(r, d)
    p = z
    zr = grid_dot(z, r, h1, h2)
    w = jnp.zeros_like(rhs)
    rows = {name: [] for name in HISTORY_FIELDS}
    for _k in range(problem.max_iterations):
        ap = apply_a(p, a, b, h1, h2)
        denom = grid_dot(ap, p, h1, h2)
        assert float(denom) >= DENOM_GUARD, "reference replay hit breakdown"
        alpha = zr / denom
        w_new = w + alpha * p
        r_new = r - alpha * ap
        z = apply_dinv(r_new, d)
        dw = w_new - w
        sums = grid_dots((z, r_new), (dw, dw))
        zr_new = sums[0] * h1 * h2
        diff = jnp.sqrt(sums[1] * h1 * h2) if weighted else jnp.sqrt(sums[1])
        beta = zr_new / zr
        for name, val in zip(HISTORY_FIELDS, (zr_new, diff, alpha, beta)):
            rows[name].append(float(val))
        if float(diff) < delta:  # the host-side convergence decision
            break
        w, r, p, zr = w_new, r_new, z + beta * p, zr_new
    return {name: np.asarray(vals) for name, vals in rows.items()}


def test_history_matches_python_loop_reference():
    """Two references, two strengths of claim.

    (1) *Bit-exact* against a host-driven replay through the same
    compiled loop body: ``advance(limit=k)`` one iteration per dispatch
    (the chunking contract — chunking moves the while_loop boundary, not
    the arithmetic), harvesting zr/diff from the returned carries and β
    as the IEEE quotient of consecutive carried zr values. This proves
    the buffers record THE loop's values, not a reconstruction.

    (2) Within f64 round-off of the textbook eager Python replay for all
    four series (separately compiled computations may fuse reductions
    differently, so cross-compilation bit-equality is not a meaningful
    target — 1e-12 relative is)."""
    from poisson_ellipse_tpu.solver.pcg import advance, init_state

    problem = Problem(M=20, N=20)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    result, trace = pcg(problem, a, b, rhs, history=True)
    assert bool(result.converged)
    n = int(result.iters)
    got = trace.valid()

    # (1) host-driven replay, bit-exact
    state = init_state(problem, a, b, rhs)
    zr_carry = [float(state[4])]  # zr entering iteration k
    host_diff = []
    for k in range(1, n + 1):
        state = advance(problem, a, b, rhs, state, limit=k)
        host_diff.append(float(state[5]))
        zr_carry.append(float(state[4]))
    assert int(state[0]) == n and bool(state[6])
    np.testing.assert_array_equal(got["diff"], np.asarray(host_diff))
    # the terminal iteration freezes zr in the carry (the trace records
    # the raw zr_new); every non-terminal entry must match bitwise
    np.testing.assert_array_equal(
        got["zr"][:-1], np.asarray(zr_carry[1:n])
    )
    host_beta = np.asarray(
        [zr_carry[k + 1] / zr_carry[k] for k in range(n - 1)]
    )
    np.testing.assert_array_equal(got["beta"][:-1], host_beta)

    # (2) textbook eager replay, to f64 round-off
    want = python_reference_trajectory(problem, a, b, rhs)
    assert n == len(want["zr"])
    for name in HISTORY_FIELDS:
        np.testing.assert_allclose(
            got[name], want[name], rtol=1e-12, err_msg=name
        )
    # past-the-end entries stay zero (preallocated, never touched)
    tail = np.asarray(trace.zr)[n:]
    assert tail.size and not tail.any()


def test_history_off_is_bitwise_identical_and_free():
    """history=False must (a) be the default, (b) emit EXACTLY the same
    jaxpr as the default path — no dynamic_update_slice, original
    8-tuple carry — and (c) history=True must not perturb one bit of the
    iterates."""
    problem = Problem(M=20, N=20)
    a, b, rhs = assembly.assemble(problem, jnp.float64)

    jx_default = jax.make_jaxpr(lambda a, b, r: pcg(problem, a, b, r))(a, b, rhs)
    jx_off = jax.make_jaxpr(
        lambda a, b, r: pcg(problem, a, b, r, history=False)
    )(a, b, rhs)
    assert str(jx_default) == str(jx_off)
    assert "dynamic_update_slice" not in str(jx_default)
    whiles = [e for e in jx_default.jaxpr.eqns if e.primitive.name == "while"]
    assert len(whiles) == 1
    assert len(whiles[0].params["body_jaxpr"].jaxpr.outvars) == 8

    plain = pcg(problem, a, b, rhs)
    traced, _ = pcg(problem, a, b, rhs, history=True)
    np.testing.assert_array_equal(np.asarray(plain.w), np.asarray(traced.w))
    assert int(plain.iters) == int(traced.iters)
    assert float(plain.diff) == float(traced.diff)


def test_history_on_stays_device_resident():
    """The recording path must be pure array ops: no callback primitives,
    no device_get — 'zero extra host syncs' as a structural property."""
    problem = Problem(M=10, N=10)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    text = str(
        jax.make_jaxpr(lambda a, b, r: pcg(problem, a, b, r, history=True))(
            a, b, rhs
        )
    )
    assert "dynamic_update_slice" in text
    assert "callback" not in text
    assert "device_get" not in text


# ------------------------------------------------------ history: engines


@pytest.mark.parametrize(
    "engine", ["xla", "pallas", "fused", "pipelined", "pipelined-pallas"]
)
def test_history_on_every_single_chip_engine(engine):
    """Every XLA-loop engine returns (PCGResult, ConvergenceTrace) with
    a self-consistent trace: the final recorded diff is the solver's own
    diff (the trace records the loop, not a reconstruction), and the
    converged iteration's step-norm is below δ."""
    problem = Problem(M=20, N=20)
    plain = engine_solve(problem, engine, jnp.float32)
    result, trace = engine_solve(problem, engine, jnp.float32, history=True)
    np.testing.assert_array_equal(np.asarray(plain.w), np.asarray(result.w))
    assert int(plain.iters) == int(result.iters)
    v = trace.valid()
    n = int(result.iters)
    assert all(v[name].shape == (n,) for name in HISTORY_FIELDS)
    assert v["diff"][-1] == float(result.diff)
    assert v["diff"][-1] < problem.delta
    assert np.isfinite(v["zr"]).all() and (v["zr"] > 0).all()


def test_history_breakdown_records_zero_alpha():
    """A breakdown iteration applies no update, so every engine's trace
    records α = 0 for it — identical telemetry for the identical event
    (the fused kernel's in-kernel guard and the XLA loops' recording
    must not disagree)."""
    from poisson_ellipse_tpu.ops.fused_pcg import pcg_fused
    from poisson_ellipse_tpu.ops.pipelined_pcg import pcg_pipelined

    problem = Problem(M=10, N=10)
    _, _, rhs = assembly.assemble(problem, jnp.float64)
    zeros = jnp.zeros_like(rhs)
    for fn in (pcg, pcg_pipelined):
        result, trace = fn(problem, zeros, zeros, rhs, history=True)
        assert bool(result.breakdown) and int(result.iters) == 1, fn
        assert float(trace.alpha[0]) == 0.0, fn
    rhs32 = rhs.astype(jnp.float32)
    z32 = jnp.zeros_like(rhs32)
    result, trace = pcg_fused(problem, z32, z32, rhs32, history=True)
    assert bool(result.breakdown) and float(trace.alpha[0]) == 0.0


def test_history_on_sharded_engine():
    from poisson_ellipse_tpu.parallel.mesh import make_mesh
    from poisson_ellipse_tpu.parallel.pcg_sharded import solve_sharded

    problem = Problem(M=20, N=20)
    mesh = make_mesh(jax.devices()[:2])
    plain = solve_sharded(problem, mesh, jnp.float64)
    result, trace = solve_sharded(problem, mesh, jnp.float64, history=True)
    np.testing.assert_array_equal(np.asarray(plain.w), np.asarray(result.w))
    assert int(plain.iters) == int(result.iters)
    v = trace.valid()
    assert v["diff"][-1] == float(result.diff)
    # the sharded trace must equal the single-chip one bit for bit: the
    # psum-reduced scalars are the same values the single loop computes
    _, single = pcg(
        problem, *assembly.assemble(problem, jnp.float64), history=True
    )
    sv = single.valid()
    for name in HISTORY_FIELDS:
        np.testing.assert_allclose(
            v[name], sv[name], rtol=1e-12, err_msg=name
        )


def test_history_unsupported_engines_fail_loudly_and_auto_degrades():
    from poisson_ellipse_tpu.solver.engine import build_solver

    problem = Problem(M=10, N=10)
    with pytest.raises(ValueError, match="history"):
        build_solver(problem, "resident", jnp.float32, history=True)
    _, _, resolved = build_solver(problem, "auto", jnp.float32, history=True)
    assert resolved == "xla"
    with pytest.raises(ValueError, match="history"):
        from poisson_ellipse_tpu.parallel.pcg_sharded import (
            build_sharded_solver,
        )

        build_sharded_solver(
            problem, stencil_impl="pipelined", history=True
        )


# ------------------------------------------------------------- trace


def test_trace_jsonl_roundtrips_and_validates(tmp_path):
    path = tmp_path / "run.jsonl"
    tracer = obs_trace.start(path)
    with obs_trace.span("phase:init", grid="20x20"):
        pass
    obs_trace.event("run_report", iters=26, converged=True)
    obs_metrics.counter("runs").inc()
    obs_metrics.gauge("last_iters").set(26)
    obs_metrics.REGISTRY.emit()
    run_id = tracer.run_id
    obs_trace.stop()

    records = obs_trace.read_jsonl(path)
    assert obs_trace.validate_file(path) == []
    kinds = [r["kind"] for r in records]
    assert kinds == ["meta", "span", "event", "counter", "gauge"]
    assert all(r["run"] == run_id for r in records)
    span = records[1]
    assert span["name"] == "phase:init" and span["dur"] >= 0
    assert span["fields"] == {"grid": "20x20"}
    assert records[3] == {
        "v": 1, "run": run_id, "t": records[3]["t"],
        "kind": "counter", "name": "runs", "value": 1.0,
    }


def test_trace_validator_rejects_malformed_records():
    ok = {"v": 1, "run": "r1", "t": 0.5, "kind": "event", "name": "x"}
    assert obs_trace.validate_record(ok) is None
    bad = [
        ({**ok, "kind": "bogus"}, "kind"),
        ({k: v for k, v in ok.items() if k != "run"}, "run"),
        ({**ok, "v": 99}, "version"),
        ({**ok, "t": -1}, "t must"),
        ({**ok, "extra": 1}, "unknown"),
        ({**ok, "kind": "span"}, "dur"),
        ({**ok, "kind": "gauge"}, "value"),
        ({**ok, "fields": [1]}, "fields"),
        ("not a dict", "object"),
    ]
    for rec, needle in bad:
        err = obs_trace.validate_record(rec)
        assert err is not None and needle in err, (rec, err)


def test_trace_inactive_is_a_noop_and_env_activates(tmp_path, monkeypatch):
    # inactive: span/event/note must not raise and must not write
    with obs_trace.span("phase:x"):
        pass
    obs_trace.event("nothing")
    err = io.StringIO()
    obs_trace.note("hello", file=err)
    assert err.getvalue() == "hello\n"
    # POISSON_TRACE starts a tracer lazily on first active() lookup
    path = tmp_path / "env.jsonl"
    monkeypatch.setenv(obs_trace.ENV_VAR, str(path))
    obs_trace._env_checked = False
    obs_trace.event("from-env", x=1)
    obs_trace.stop()
    names = [r["name"] for r in obs_trace.read_jsonl(path)]
    assert names == ["trace-start", "from-env"]


def test_note_emits_structured_twin_when_tracing(tmp_path, capsys):
    path = tmp_path / "note.jsonl"
    obs_trace.start(path)
    obs_trace.note("  40x40: converged", row=1)
    obs_trace.stop()
    assert "40x40: converged" in capsys.readouterr().err
    recs = obs_trace.read_jsonl(path)
    assert recs[-1]["fields"] == {"message": "  40x40: converged", "row": 1}


def test_metrics_registry_snapshot_and_kind_collisions():
    reg = obs_metrics.MetricsRegistry()
    reg.counter("a").inc()
    reg.counter("a").inc(2)
    reg.gauge("b").set(7)
    assert reg.snapshot() == {"counters": {"a": 3.0}, "gauges": {"b": 7.0}}
    with pytest.raises(ValueError, match="already a counter"):
        reg.gauge("a")
    with pytest.raises(ValueError, match="cannot decrease"):
        reg.counter("a").inc(-1)
    assert reg.gauge("unset") and reg.snapshot()["gauges"] == {"b": 7.0}


# ---------------------------------------------------------- PhaseTimer


def test_phase_timer_report_zero_guard_and_stable_order():
    from poisson_ellipse_tpu.utils.timing import PhaseTimer

    t = PhaseTimer()
    assert t.report() == ""  # 0 phases: renders, no division
    t.add("solver", 0.0)
    t.add("init", 0.0)
    zero = t.report()
    assert "0.0%" in zero  # 0-second total: guarded percentage
    # name-sorted, not insertion-sorted: diffs cleanly across runs
    assert zero.index("T_init") < zero.index("T_solver")
    t.add("solver", 3.0)
    t.add("init", 1.0)
    lines = t.report().splitlines()
    assert "25.0%" in lines[0] and "75.0%" in lines[1]


def test_phase_timer_is_a_trace_shim(tmp_path):
    from poisson_ellipse_tpu.utils.timing import PhaseTimer

    path = tmp_path / "phases.jsonl"
    obs_trace.start(path)
    t = PhaseTimer()
    with t.phase("init"):
        pass
    t.add("solver", 1.5)
    obs_trace.stop()
    recs = obs_trace.read_jsonl(path)
    spans = {r["name"]: r for r in recs if r["kind"] == "span"}
    assert set(spans) == {"phase:init", "phase:solver"}
    assert spans["phase:solver"]["dur"] == 1.5
    assert obs_trace.validate_file(path) == []


# ---------------------------------------------------------- static cost


def test_static_cost_classical_two_psum_pipelined_one():
    """THE metric: classical sharded loop = 2 psum/iter, pipelined = 1,
    on a 1×2 CPU mesh — the same engine_report record harness inspect
    prints and bench.py's artifact asserts."""
    from poisson_ellipse_tpu.obs.static_cost import engine_report

    problem = Problem(M=20, N=20)
    classical = engine_report(
        problem, "xla", mode="sharded", mesh_shape=(1, 2), with_xla_cost=False
    )
    pipelined = engine_report(
        problem, "pipelined", mode="sharded", mesh_shape=(1, 2),
        with_xla_cost=False,
    )
    assert classical["psum_per_iter"] == 2
    assert pipelined["psum_per_iter"] == 1
    assert classical["ppermute_per_iter"] == 4  # the halo ring
    assert classical["collectives_per_iter"] == {"psum": 2, "ppermute": 4}


def test_static_cost_single_chip_and_modeled_columns():
    from poisson_ellipse_tpu.obs.static_cost import engine_report

    problem = Problem(M=20, N=20)
    rep = engine_report(problem, "xla", mode="single")
    assert rep["psum_per_iter"] == 0 and rep["ppermute_per_iter"] == 0
    assert rep["modeled_passes_per_iter"] == 13.0
    g1, g2 = problem.node_shape
    assert rep["modeled_hbm_bytes_per_iter"] == 13.0 * g1 * g2 * 4
    # CPU XLA exposes a cost analysis: the measured-vs-modeled column
    # exists (values are backend estimates, only presence is pinned)
    assert rep["flops_per_iter_est"] is None or rep["flops_per_iter_est"] > 0


def test_collectives_table_shape():
    from poisson_ellipse_tpu.obs.static_cost import collectives_table

    t = collectives_table(Problem(M=20, N=20))
    assert t["available"] is True and t["mesh"] == [1, 2]
    assert t["engines"]["xla"]["psum_per_iter"] == 2
    assert t["engines"]["pipelined"]["psum_per_iter"] == 1


def test_multichip_table_carries_collectives():
    from poisson_ellipse_tpu.harness.bench_multichip import scaling_table

    t = scaling_table("strong", (20, 20), [(1, 2)], stencil_impl="pipelined")
    assert t["collectives_per_iter"]["psum"] == 1
    t2 = scaling_table("strong", (20, 20), [(1, 2)])
    assert t2["collectives_per_iter"]["psum"] == 2


# -------------------------------------------------------- inspect CLI


def test_harness_inspect_subcommand(capsys):
    from poisson_ellipse_tpu.harness.__main__ import main

    rc = main([
        "inspect", "pipelined", "--mode", "sharded", "--mesh", "1", "2",
        "--grid", "20x20", "--no-xla-cost", "--json",
    ])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["engine"] == "pipelined" and rep["psum_per_iter"] == 1

    rc = main(["inspect", "xla", "--grid", "10x10", "--no-xla-cost"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "psum/iter" in out and "modeled HBM bytes/iter" in out

    assert main(["inspect", "resident", "--mode", "sharded"]) == 2
    assert "error" in capsys.readouterr().err


def test_harness_trace_flag_end_to_end(tmp_path, capsys):
    from poisson_ellipse_tpu.harness.__main__ import main

    path = tmp_path / "cli.jsonl"
    rc = main(["10", "10", "--mode", "single", "--trace", str(path), "--json"])
    assert rc == 0
    assert obs_trace.validate_file(path) == []
    names = [r["name"] for r in obs_trace.read_jsonl(path)]
    for expected in ("trace-start", "cli-args", "phase:init", "phase:solver",
                     "phase:finalize", "run_report", "runs", "cli-exit"):
        assert expected in names, (expected, names)
    # the CLI closed its tracer: nothing ambient leaks into later runs
    assert obs_trace.active() is None
