"""Full-multigrid (``mg.fmg``) + autotuner (``runtime.autotune``) tests.

Four layers of assertion, mirroring the tentpole's claims:

- **O(N) solver contract**: the F-cycle reaches analytic-solution l2
  parity with mg-pcg across grids; the work-unit model is constant per
  grid point (±20%) across sizes — the asymptotic-work pin;
- **verified handoff**: accuracy is measured, never assumed — a
  crippled F-cycle (zero correction V-cycles) still converges to δ
  through the warm-started mg-pcg handoff, just with more iterations;
- **sharded + guarded forms**: 1×2/2×2 mesh parity with single-chip,
  the jaxpr-pinned per-level halo budget (``halos_per_fcycle``) with
  the classical psum cadence in the handoff loop, and NaN-injection
  recovery through the guard at clean-run iteration parity;
- **autotuner closed loop**: selection is a pure function of the
  telemetry (same telemetry → same config), the static default is
  never beaten by prediction noise (the margin rule), configs persist
  and reload deterministically next to the XLA cache, and
  ``build_solver(engine="auto")`` / the serve scheduler consult them.
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.mg import coarsen
from poisson_ellipse_tpu.mg.fmg import (
    FMGConfig,
    build_fmg_solver,
    default_fmg_config,
    work_units_per_point,
)
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.runtime import autotune
from poisson_ellipse_tpu.solver.engine import (
    ENGINE_CAPS,
    ENGINES,
    build_solver,
    solve as engine_solve,
)
from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic


def mesh_of(n):
    from poisson_ellipse_tpu.parallel.mesh import make_mesh

    return make_mesh(jax.devices()[:n])


# engine solves reused across tests (each fmg/mg-pcg build pays a
# Lanczos probe + hierarchy + compile — the suite sits near the tier-1
# wall-clock ceiling, so identical solves are computed once)
_SOLVES: dict = {}


def solved(engine: str, grid=(24, 24)):
    key = (engine, grid)
    if key not in _SOLVES:
        _SOLVES[key] = engine_solve(
            Problem(M=grid[0], N=grid[1]), engine, jnp.float32
        )
    return _SOLVES[key]


# -- the O(N) solver contract ------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("grid", [(24, 24), (40, 40)])
def test_fcycle_l2_parity_with_mg_pcg(grid):
    """F-cycle + handoff reaches the same discretization-level accuracy
    as mg-pcg (one-sided: ≤10% worse; the seed usually lands below) —
    the bench `fmg` key's parity rule at test scale."""
    problem = Problem(M=grid[0], N=grid[1])
    fmg = solved("fmg", grid)
    mg = solved("mg-pcg", grid)
    assert bool(fmg.converged) and bool(mg.converged)
    l2_fmg = float(l2_error_vs_analytic(problem, fmg.w))
    l2_mg = float(l2_error_vs_analytic(problem, mg.w))
    assert l2_mg > 0 and l2_fmg <= l2_mg * 1.10, (l2_fmg, l2_mg)
    # the handoff is a WARM start: it must not pay mg-pcg's full count
    assert int(fmg.iters) <= int(mg.iters)


def test_work_units_per_point_constant_across_grids():
    """The O(N) pin: fine-grid-equivalent stencil applications per grid
    point stay within ±20% across ≥3 grid sizes (the geometric level
    sum bounds the model regardless of depth)."""
    units = [
        work_units_per_point(coarsen.num_levels(M, N))
        for M, N in ((64, 64), (256, 256), (1024, 1024), (4096, 4096))
    ]
    assert max(units) <= min(units) * 1.20, units
    # and deeper hierarchies must not grow the per-point bill unboundedly
    assert all(u < 120.0 for u in units), units


@pytest.mark.slow
def test_fcycle_handoff_exits_fast_when_seed_is_good():
    """The verification loop's whole point: when the F-cycle already
    landed at discretization accuracy the handoff is a few polish
    iterations, not an mg-pcg solve from zero."""
    diag = solved("xla", (24, 24))
    fmg = solved("fmg", (24, 24))
    assert bool(fmg.converged)
    assert int(fmg.iters) < int(diag.iters) / 4


# -- the verified handoff ----------------------------------------------------


@pytest.mark.slow
def test_miss_delta_hands_off_to_mg_pcg():
    """A deliberately crippled F-cycle (zero correction V-cycles, a
    2-step coarsest sweep) misses δ — the handoff loop must still
    carry the solve to convergence, with MORE iterations than the
    healthy config: accuracy verified, never assumed."""
    problem = Problem(M=24, N=24)
    crippled = FMGConfig(
        levels=coarsen.num_levels(24, 24),
        n_vcycles=0,
        coarse_degree=2,
    )
    solver, args, _ = build_fmg_solver(problem, jnp.float32,
                                       config=crippled)
    res = solver(*args)
    healthy = solved("fmg", (24, 24))
    assert bool(res.converged)
    assert float(res.diff) < problem.delta
    assert int(res.iters) > int(healthy.iters)
    l2 = float(l2_error_vs_analytic(problem, res.w))
    l2_h = float(l2_error_vs_analytic(problem, healthy.w))
    assert l2 <= l2_h * 1.10


def test_warm_start_init_state_builds_true_residual():
    """``init_state(x0=...)`` must seed w = x0 with r = rhs − A·x0 (the
    handoff's verification contract); x0=None stays the historical
    zero start byte for byte."""
    from poisson_ellipse_tpu.ops import assembly
    from poisson_ellipse_tpu.ops.stencil import apply_a
    from poisson_ellipse_tpu.solver.pcg import init_state

    problem = Problem(M=10, N=10)
    a, b, rhs = assembly.assemble(problem, jnp.float32)
    x0 = jnp.ones_like(rhs) * 0.01
    state = init_state(problem, a, b, rhs, x0=x0)
    h1 = jnp.asarray(problem.h1, jnp.float32)
    h2 = jnp.asarray(problem.h2, jnp.float32)
    np.testing.assert_array_equal(np.asarray(state[1]), np.asarray(x0))
    np.testing.assert_allclose(
        np.asarray(state[2]),
        np.asarray(rhs - apply_a(x0, a, b, h1, h2)),
        rtol=0, atol=0,
    )
    zero = init_state(problem, a, b, rhs)
    assert not np.asarray(zero[1]).any()
    np.testing.assert_array_equal(np.asarray(zero[2]), np.asarray(rhs))


@pytest.mark.slow
def test_fmg_history_records_the_handoff():
    """``history=True`` returns the handoff loop's ConvergenceTrace with
    iterates bit-identical to the historyless run (the obs contract)."""
    problem = Problem(M=24, N=24)
    solver, args, _ = build_solver(problem, "fmg", jnp.float32,
                                   history=True)
    res, trace = solver(*args)
    plain = solved("fmg", (24, 24))
    assert int(res.iters) == int(plain.iters)
    assert float(res.diff) == float(plain.diff)
    np.testing.assert_array_equal(np.asarray(res.w), np.asarray(plain.w))


# -- sharded + guarded forms -------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("shape", [(1, 2), (2, 2)])
def test_fmg_sharded_parity(shape):
    from jax.sharding import Mesh

    from poisson_ellipse_tpu.parallel.mesh import AXIS_X, AXIS_Y
    from poisson_ellipse_tpu.parallel.mg_sharded import (
        build_fmg_sharded_solver,
    )

    problem = Problem(M=16, N=16)
    single = solved("fmg", (16, 16))
    devs = np.asarray(jax.devices()[: shape[0] * shape[1]]).reshape(shape)
    mesh = Mesh(devs, (AXIS_X, AXIS_Y))
    solver, args = build_fmg_sharded_solver(problem, mesh)
    res = solver(*args)
    assert bool(res.converged)
    assert int(res.iters) == int(single.iters)
    np.testing.assert_allclose(
        np.asarray(res.w), np.asarray(single.w), rtol=0, atol=5e-6,
    )


def test_fmg_sharded_collective_budget_jaxpr_pinned():
    """The sharded F-cycle's collective budget, read from the jaxpr:
    the handoff loop keeps the classical cadence (2 psum/iter — denom +
    the stacked convergence word — and the V-cycle's halo budget), and
    the whole computation's ppermute count covers exactly ONE F-cycle
    (``halos_per_fcycle``) + one handoff-loop body + the per-dispatch
    operand extension — no hidden exchanges."""
    from poisson_ellipse_tpu.analysis.contracts import assert_contract
    from poisson_ellipse_tpu.mg.fmg import DEFAULT_FMG_VCYCLES
    from poisson_ellipse_tpu.parallel.mg_sharded import (
        halos_per_fcycle,
        halos_per_precond,
    )

    problem = Problem(M=16, N=16)
    levels = coarsen.num_levels(16, 16)
    # per handoff iteration: one fine stencil + the V-cycle's halos
    r = assert_contract(
        "collective-cadence", "fmg", problem=problem, mesh_shape=(1, 2)
    )
    assert r.expected == {
        "psum": 2,  # the classical scalar cadence, untouched
        "ppermute": 4 * (1 + halos_per_precond(levels)),
    }, "contract derivation drifted from the hand budget"
    # whole-computation budget: levels' coefficient extensions (once per
    # dispatch), ONE F-cycle, init's precond+stencil, the loop body
    rb = assert_contract(
        "fcycle-budget", "fmg", problem=problem, mesh_shape=(1, 2)
    )
    fcycle_halos = halos_per_fcycle(levels,
                                    n_vcycles=DEFAULT_FMG_VCYCLES)
    init_halos = 1 + halos_per_precond(levels)  # r0 stencil + z0 precond
    loop_halos = 1 + halos_per_precond(levels)
    # coefficient extension: each level's (a, b) PAIR is halo-extended
    # once per dispatch — two exchanges per level
    extend = 2 * levels
    assert rb.expected["ppermute_total"] == 4 * (
        extend + fcycle_halos + init_halos + loop_halos
    ), (rb.expected, fcycle_halos)


@pytest.mark.slow
def test_fmg_guarded_nan_recovery():
    """A NaN injected into the handoff carry must be detected by the
    per-chunk health word and recovered by the residual restart — and
    because every recovery keeps the iterate, the F-cycle's head start
    survives: iteration parity with the clean run."""
    from poisson_ellipse_tpu.resilience import (
        FaultPlan,
        guarded_solve,
        inject_nan,
    )

    problem = Problem(M=24, N=24)
    clean = solved("fmg", (24, 24))
    guarded = guarded_solve(
        problem, "fmg", jnp.float32, chunk=2,
        faults=FaultPlan(inject_nan(2, "r")),
    )
    assert guarded.engine == "fmg"
    assert [e.kind for e in guarded.recoveries] == ["residual-restart"]
    assert bool(guarded.result.converged)
    assert np.isfinite(np.asarray(guarded.result.w)).all()
    assert abs(int(guarded.result.iters) - int(clean.iters)) <= 2


# -- the engine-capability table (the de-dup fix) ----------------------------


def test_engine_caps_is_the_single_source():
    """Every derived tuple must agree with the capability table — the
    one-row-per-engine contract a new engine registers through."""
    from poisson_ellipse_tpu.solver.engine import (
        BATCHED_ENGINES,
        CAPACITY_LADDER,
        HISTORY_ENGINES,
        PRECOND_ENGINES,
        PRECOND_KIND_BY_ENGINE,
        SSTEP_ENGINES,
        STORAGE_ENGINES,
    )

    assert set(ENGINES) == {"auto"} | set(ENGINE_CAPS)
    assert "fmg" in ENGINE_CAPS and ENGINE_CAPS["fmg"]["family"] == "fmg"
    assert set(STORAGE_ENGINES) == {
        e for e, c in ENGINE_CAPS.items() if c["storage"]
    }
    assert set(HISTORY_ENGINES) == {"auto"} | {
        e for e, c in ENGINE_CAPS.items() if c["history"]
    }
    assert set(BATCHED_ENGINES) == {
        e for e, c in ENGINE_CAPS.items() if c["family"] == "batched"
    }
    assert set(SSTEP_ENGINES) == {
        e for e, c in ENGINE_CAPS.items() if c["family"] == "sstep"
    }
    assert PRECOND_KIND_BY_ENGINE == {"mg-pcg": "mg", "cheb-pcg": "cheb"}
    assert set(PRECOND_ENGINES) == {"mg-pcg", "cheb-pcg"}
    assert CAPACITY_LADDER == ("resident", "streamed", "xl", "xla")
    # every tunable knob the table declares is a knob the lint rule
    # fences and the autotuner can sweep
    for engine, caps in ENGINE_CAPS.items():
        for knob in caps["tunables"]:
            assert knob in (
                "levels", "nu", "coarse_degree", "n_vcycles",
                "cheb_degree", "sstep_s", "chunk",
            ), (engine, knob)


# -- the autotuner closed loop -----------------------------------------------


def _fake_telemetry(predicted_iters=500, kappa=4.0e4, gbps=800.0):
    return {
        "grid": [400, 600], "delta": 1e-6, "kappa": kappa,
        "predicted_iters": predicted_iters, "probe_iters": 48,
        "probe_converged": False, "gbps": gbps,
    }


def test_select_is_deterministic_in_the_telemetry():
    """Same telemetry → same config, bit for bit — the replayability
    pin that makes a persisted registry auditable."""
    problem = Problem(M=400, N=600)
    tel = _fake_telemetry()
    a, rows_a = autotune.select(problem, tel)
    b, rows_b = autotune.select(problem, tel)
    assert a == b
    assert rows_a == rows_b


def test_select_never_beats_default_on_noise():
    """A candidate inside the margin of the static default's predicted
    cost must NOT displace it (coin-flip predictions keep the known-
    good policy)."""
    problem = Problem(M=40, N=40)
    # few predicted iterations: the diagonal default is already cheap,
    # so no iteration-count engine can clear the margin
    tel = _fake_telemetry(predicted_iters=3, kappa=4.0)
    chosen, _rows = autotune.select(problem, tel)
    assert chosen.engine == chosen.static_engine


def test_select_prefers_fmg_at_iteration_walls():
    """Many predicted iterations → the F-cycle's constant work wins on
    the model (the 8192²/28.7 s story in miniature)."""
    problem = Problem(M=400, N=600)
    chosen, _rows = autotune.select(
        problem, _fake_telemetry(predicted_iters=5000)
    )
    assert chosen.engine == "fmg"
    assert chosen.static_engine != "fmg"
    assert chosen.predicted_t_s < chosen.static_predicted_t_s
    # the serve chunk knob rides along for the scheduler's consult
    assert 8 <= chosen.knobs["chunk"] <= 128


def test_registry_persistence_round_trip(tmp_path):
    """put → save → load → get hands back the exact config (the
    determinism of select plus this round-trip is what makes the
    persisted winners reproducible)."""
    problem = Problem(M=40, N=40)
    path = os.path.join(tmp_path, "autotune.json")
    reg = autotune.TuneRegistry(path)
    chosen, _ = autotune.select(problem, _fake_telemetry())
    key = autotune.tune_key(problem)
    reg.put(key, chosen)
    reg.save()
    reloaded = autotune.TuneRegistry(path).load()
    assert reloaded.get(key) == chosen
    # the on-disk form is schema-versioned JSON (torn/old files refuse)
    with open(path) as fh:
        rec = json.load(fh)
    assert rec["version"] == autotune.SCHEMA_VERSION
    assert key in rec["entries"]


def test_registry_rejects_wrong_schema_and_torn_files(tmp_path):
    path = os.path.join(tmp_path, "autotune.json")
    with open(path, "w") as fh:
        json.dump({"version": 999, "entries": {"k": {}}}, fh)
    assert autotune.TuneRegistry(path).load().entries == {}
    with open(path, "w") as fh:
        fh.write("{torn")
    assert autotune.TuneRegistry(path).load().entries == {}


def test_tune_key_components(tmp_path):
    """Keys must separate everything that changes the executable or the
    accuracy contract: grid bucket, geometry, dtype, storage, norm."""
    p = Problem(M=40, N=40)
    base = autotune.tune_key(p)
    assert autotune.tune_key(Problem(M=38, N=38)) == base  # same bucket
    assert autotune.tune_key(Problem(M=100, N=100)) != base
    assert autotune.tune_key(p, storage_dtype="bf16") != base
    assert autotune.tune_key(p, jnp.float64) != base
    assert autotune.tune_key(Problem(M=40, N=40, norm="unweighted")) != base
    geom = {"kind": "circle", "r": 0.3}
    assert autotune.tune_key(p, geometry=geom) != base
    # geometry fingerprints are content-stable (key order irrelevant)
    assert autotune.geometry_fingerprint(
        {"r": 0.3, "kind": "circle"}
    ) == autotune.geometry_fingerprint(geom)


def test_build_solver_auto_consults_registry(tmp_path, monkeypatch):
    """A persisted tuned config must steer ``engine="auto"`` — and an
    absent registry must leave the static ladder byte-identical."""
    problem = Problem(M=16, N=16)
    path = os.path.join(tmp_path, "autotune.json")
    reg = autotune.TuneRegistry(path)
    key = autotune.tune_key(problem)
    reg.put(key, autotune.TunedConfig(engine="mg-pcg",
                                      static_engine="resident"))
    reg.save()
    monkeypatch.setattr(autotune, "_REGISTRY", None)
    monkeypatch.setattr(autotune, "registry_path", lambda *a, **k: path)
    _solver, _args, engine = build_solver(problem, "auto", jnp.float32)
    assert engine == "mg-pcg"
    # the kill switch: POISSON_AUTOTUNE=off restores the static pick
    monkeypatch.setenv(autotune.ENV_DISABLE, "off")
    _solver, _args, engine = build_solver(problem, "auto", jnp.float32)
    assert engine != "mg-pcg"


@pytest.mark.slow
def test_tune_end_to_end_persists_and_looks_up(tmp_path):
    """The closed loop on a real (tiny) shape: tune → persist → lookup
    hands back the same engine/knobs the report chose."""
    problem = Problem(M=24, N=24)
    reg = autotune.TuneRegistry(os.path.join(tmp_path, "autotune.json"))
    report = autotune.tune(problem, registry=reg, persist=True)
    got = autotune.lookup(problem, registry=reg)
    assert got is not None
    assert got.engine == report["chosen"]["engine"]
    assert got.knobs == report["chosen"]["knobs"]
    # determinism against the recorded telemetry
    again, _ = autotune.select(problem, report["telemetry"])
    assert again.engine == got.engine


@pytest.mark.slow
def test_scheduler_consults_tuned_chunk(tmp_path, monkeypatch):
    """Warm-pool admission (the scheduler's batch-context creation)
    picks up the tuned per-shape chunk; untuned shapes keep the
    scheduler-wide default."""
    from poisson_ellipse_tpu.serve import Scheduler

    problem = Problem(M=10, N=10)
    path = os.path.join(tmp_path, "autotune.json")
    reg = autotune.TuneRegistry(path)
    reg.put(
        autotune.tune_key(problem),
        autotune.TunedConfig(engine="resident", knobs={"chunk": 24}),
    )
    reg.save()
    monkeypatch.setattr(autotune, "_REGISTRY", None)
    monkeypatch.setattr(autotune, "registry_path", lambda *a, **k: path)
    sched = Scheduler(lanes=2, chunk=8)
    assert sched.submit(problem, request_id="t-0") is None
    sched.drain()
    ctx = next(iter(sched._ctxs.values()))
    assert ctx.chunk == 24
    # an untuned shape's context stays on the scheduler default
    other = Problem(M=100, N=100)
    sched2 = Scheduler(lanes=2, chunk=8)
    assert sched2.submit(other, request_id="t-1") is None
    sched2.drain()
    ctx2 = next(iter(sched2._ctxs.values()))
    assert ctx2.chunk is None


@pytest.mark.slow
def test_default_fmg_config_resolves_probe_once():
    """resolve_fmg_config fills the Lanczos interval only when the
    supplied config is degenerate — a probed config passes through."""
    from poisson_ellipse_tpu.mg.fmg import resolve_fmg_config
    from poisson_ellipse_tpu.ops import assembly

    problem = Problem(M=16, N=16)
    a, b, rhs = assembly.assemble(problem, jnp.float32)
    cfg = resolve_fmg_config(problem, a, b, rhs)
    assert cfg.lo > 0.0
    assert cfg.levels == default_fmg_config(problem).levels
    again = resolve_fmg_config(problem, a, b, rhs, cfg)
    assert again == cfg
    manual = dataclasses.replace(cfg, lo=0.25)
    assert resolve_fmg_config(problem, a, b, rhs, manual) == manual
