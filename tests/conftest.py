"""Test harness configuration.

Forces the CPU backend with 8 virtual XLA devices before any backend
initialisation, so the distributed path (mesh / ppermute halos / psum
reductions) is unit-testable with no TPU — the strategy SURVEY.md §4
prescribes (the reference analogously tests small grids at 1/2/4 ranks
via mpirun on one host). Enables x64 because the reference is entirely
double precision and the iteration-count oracles are f64 facts.

The order-sensitive flag/platform ritual lives in
``parallel.mesh.virtual_cpu_devices`` — the same helper the driver's
multichip dryrun gate and the virtual-mesh benchmark use, so the test
suite exercises the production pinning path rather than a hand-rolled
copy that could drift.
"""

import jax

from poisson_ellipse_tpu.parallel.mesh import virtual_cpu_devices

virtual_cpu_devices(8)
jax.config.update("jax_enable_x64", True)
