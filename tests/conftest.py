"""Test harness configuration.

Forces the CPU backend with 8 virtual XLA devices before any backend
initialisation, so the distributed path (mesh / ppermute halos / psum
reductions) is unit-testable with no TPU — the strategy SURVEY.md §4
prescribes (the reference analogously tests small grids at 1/2/4 ranks
via mpirun on one host). Enables x64 because the reference is entirely
double precision and the iteration-count oracles are f64 facts.

The order-sensitive flag/platform ritual lives in
``parallel.mesh.virtual_cpu_devices`` — the same helper the driver's
multichip dryrun gate and the virtual-mesh benchmark use, so the test
suite exercises the production pinning path rather than a hand-rolled
copy that could drift.
"""

import os

import jax

from poisson_ellipse_tpu.parallel.mesh import virtual_cpu_devices

virtual_cpu_devices(8)
jax.config.update("jax_enable_x64", True)


# -- tier-1 per-test wall-clock budget ---------------------------------------
#
# The full suite sits near the 870 s tier-1 ceiling, so one test ballooning
# past a minute is a CI outage in the making. Any non-slow-marked test whose
# CALL phase exceeds the budget fails the session at exit with a named list —
# the fix is to shrink the test or mark it `slow` (excluded from tier-1).
# Enforcement carries a 1.25× host-noise grace: the 2-core CI box is
# load-sensitive (a test measured at 60.5 s under contention is not a
# regression of a test that runs in 45 s quiet), so 60–75 s is a printed
# warning and only > 75 s fails — a genuinely ballooned test blows far past
# the band, a noisy-neighbour blip does not. POISSON_TIER1_TEST_BUDGET_S
# overrides the nominal ceiling (0 disables both tiers).

TEST_BUDGET_S = float(os.environ.get("POISSON_TIER1_TEST_BUDGET_S", "60"))
_GRACE = 1.25

_over_budget: list[tuple[str, float]] = []
_near_budget: list[tuple[str, float]] = []


def pytest_runtest_logreport(report):
    if (
        TEST_BUDGET_S > 0
        and report.when == "call"
        and report.duration > TEST_BUDGET_S
        and "slow" not in getattr(report, "keywords", {})
    ):
        bucket = (
            _over_budget if report.duration > TEST_BUDGET_S * _GRACE
            else _near_budget
        )
        bucket.append((report.nodeid, report.duration))


def pytest_sessionfinish(session, exitstatus):
    if _near_budget:
        lines = "\n".join(
            f"  {nodeid}: {dur:.1f}s (budget {TEST_BUDGET_S:g}s)"
            for nodeid, dur in _near_budget
        )
        print(
            "\ntier-1 per-test budget WARNING (inside the host-noise "
            f"grace band, <= {TEST_BUDGET_S * _GRACE:g}s):\n{lines}"
        )
    if _over_budget:
        lines = "\n".join(
            f"  {nodeid}: {dur:.1f}s > {TEST_BUDGET_S * _GRACE:g}s"
            for nodeid, dur in _over_budget
        )
        session.exitstatus = 1
        print(
            "\ntier-1 per-test budget exceeded (mark these `slow` or "
            f"shrink them):\n{lines}"
        )
