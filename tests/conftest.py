"""Test harness configuration.

Forces the CPU backend with 8 virtual XLA devices *before* jax is imported,
so the distributed path (mesh / ppermute halos / psum reductions) is
unit-testable with no TPU — the strategy SURVEY.md §4 prescribes (the
reference analogously tests small grids at 1/2/4 ranks via mpirun on one
host). Enables x64 because the reference is entirely double precision and
the iteration-count oracles are f64 facts.
"""

import os

# Note: the environment may pre-import jax (sitecustomize) and pin
# JAX_PLATFORMS to a hardware plugin, so env vars alone are not enough —
# XLA_FLAGS is still read lazily at CPU-backend init, and the platform is
# switched via jax.config below.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
