"""Pallas kernels vs the XLA ops (interpret mode on the CPU backend).

The reference's cross-implementation oracle is agreement between its CPU
and CUDA paths on identical grids (SURVEY §4.2); here the analog is
Pallas-vs-XLA agreement on the same arrays, plus solver-level parity of
iteration counts. On real TPU the compiled kernels match the XLA path to
1-2 ulps (verified on-chip); in interpret mode most are exact.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly, pallas_kernels as pk
from poisson_ellipse_tpu.ops.reduction import grid_dot
from poisson_ellipse_tpu.ops.stencil import apply_a_block, apply_dinv
from poisson_ellipse_tpu.solver.pcg import pcg


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(7)


@pytest.mark.parametrize("bm,bn", [(16, 18), (24, 130), (15, 33)])
def test_stencil_matches_xla(rng, bm, bn):
    w = jnp.asarray(rng.standard_normal((bm + 2, bn + 2)))
    a = jnp.asarray(rng.random((bm + 2, bn + 2)) + 0.5)
    b = jnp.asarray(rng.random((bm + 2, bn + 2)) + 0.5)
    ref = apply_a_block(w, a, b, 0.01, 0.02)
    out = pk.apply_a_block_pallas(w, a, b, 0.01, 0.02)
    assert out.shape == (bm, bn)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-12)


def test_stencil_on_assembled_problem(rng):
    problem = Problem(M=24, N=16)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    w = jnp.asarray(rng.standard_normal(problem.node_shape))
    ref = apply_a_block(w, a, b, problem.h1, problem.h2)
    out = pk.apply_a_block_pallas(w, a, b, problem.h1, problem.h2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-12)


def test_dinv_matches(rng):
    d = jnp.asarray(rng.standard_normal((32, 40)))
    d = jnp.where(jnp.abs(d) < 0.3, 0.0, d)  # exercise the zero guard
    r = jnp.asarray(rng.standard_normal((32, 40)))
    assert bool(jnp.all(pk.apply_dinv_pallas(r, d) == apply_dinv(r, d)))


def test_dot_matches(rng):
    x = jnp.asarray(rng.standard_normal((32, 40)))
    y = jnp.asarray(rng.standard_normal((32, 40)))
    got = pk.dot_pallas(x, y, 0.01, 0.02)
    want = grid_dot(x, y, 0.01, 0.02)
    assert float(abs(got - want)) < 1e-12 * abs(float(want))


def test_update_w_r_fused(rng):
    w = jnp.asarray(rng.standard_normal((16, 24)))
    r = jnp.asarray(rng.standard_normal((16, 24)))
    p = jnp.asarray(rng.standard_normal((16, 24)))
    ap = jnp.asarray(rng.standard_normal((16, 24)))
    alpha = jnp.asarray(0.37)
    w_new, r_new, dw2 = pk.update_w_r_pallas(alpha, w, r, p, ap)
    # FMA contraction differs between the paths: ulp-level agreement only
    np.testing.assert_allclose(
        np.asarray(w_new), np.asarray(w + alpha * p), rtol=1e-13
    )
    np.testing.assert_allclose(
        np.asarray(r_new), np.asarray(r - alpha * ap), rtol=1e-13
    )
    assert float(abs(dw2 - jnp.sum((alpha * p) ** 2))) < 1e-12


def test_update_p(rng):
    z = jnp.asarray(rng.standard_normal((16, 24)))
    p = jnp.asarray(rng.standard_normal((16, 24)))
    beta = jnp.asarray(0.9)
    # rtol alone is not enough: where z + βp cancels to ~0 the FMA-vs-mul
    # ulp difference is unbounded relatively
    np.testing.assert_allclose(
        np.asarray(pk.update_p_pallas(beta, z, p)),
        np.asarray(z + beta * p),
        rtol=1e-13,
        atol=1e-14,
    )


def test_pcg_with_pallas_stencil_matches_oracle():
    problem = Problem(M=10, N=10, norm="unweighted")
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    res = pcg(problem, a, b, rhs, stencil="pallas")
    # unweighted-norm oracle @ 10x10 (compiled reference stage0 binary)
    assert int(res.iters) == 17
    assert bool(res.converged)
    res_xla = pcg(problem, a, b, rhs, stencil="xla")
    np.testing.assert_allclose(
        np.asarray(res.w), np.asarray(res_xla.w), rtol=1e-10, atol=1e-14
    )


def test_pcg_rejects_unknown_stencil():
    problem = Problem(M=8, N=8)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    with pytest.raises(ValueError, match="unknown stencil"):
        pcg(problem, a, b, rhs, stencil="cuda")
