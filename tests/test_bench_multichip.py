"""Multi-chip scaling-table emitter: schema pin + mesh-invariance parity.

The table format mirrors the reference stage4 report's table 1 (grid,
config, iters, T_solver, speedup — Этап_4_1213.pdf p.11) plus the
weak-scaling efficiency its text discusses; the schema is pinned so
downstream parsing of driver-recorded tables cannot silently drift."""

import pytest

from poisson_ellipse_tpu.harness.bench_multichip import (
    ROW_SCHEMA,
    parse_meshes,
    scaling_table,
)

MESHES = [(1, 1), (2, 2), (2, 4)]


@pytest.fixture(scope="module")
def strong_table():
    return scaling_table("strong", (40, 40), MESHES)


def test_parse_meshes():
    assert parse_meshes("1x1,2x2,4x4") == [(1, 1), (2, 2), (4, 4)]
    assert parse_meshes("2") == [(2, 2)]


def test_strong_table_schema_pinned(strong_table):
    t = strong_table
    assert t["kind"] == "strong"
    assert t["base_grid"] == "40x40"
    assert len(t["rows"]) == len(MESHES)
    for row in t["rows"]:
        assert set(row) == ROW_SCHEMA, "row schema drifted"
        assert row["grid"] == "40x40"
        assert row["converged"] is True


def test_strong_table_iteration_parity(strong_table):
    """1-vs-8-device iteration parity in the emitted table — the
    reference's cross-implementation oracle, machine-checked."""
    t = strong_table
    by_devices = {r["devices"]: r for r in t["rows"]}
    assert by_devices[1]["iters"] == by_devices[8]["iters"] == 50
    assert t["iters_consistent"] is True
    # first row is the baseline of its own speedup column
    assert t["rows"][0]["speedup"] == 1.0
    assert t["rows"][0]["efficiency"] == 1.0


def test_weak_table_grows_grid():
    t = scaling_table("weak", (12, 12), [(1, 1), (2, 2), (2, 4)])
    assert [r["grid"] for r in t["rows"]] == ["12x12", "24x24", "24x48"]
    assert t["iters_consistent"] is None  # grids differ: oracle n/a
    for row in t["rows"]:
        assert set(row) == ROW_SCHEMA
        assert row["converged"] is True
        assert row["efficiency"] > 0


def test_strong_table_baseline_need_not_be_single_device():
    """Efficiency is relative to the first row's device count (a grid may
    not fit one chip), not absolute: ideal 4->8-device scaling is
    efficiency 1.0, not 1/8."""
    t = scaling_table("strong", (20, 20), [(2, 2), (2, 4)])
    r0, r1 = t["rows"]
    assert r0["devices"] == 4 and r0["efficiency"] == 1.0
    assert r1["efficiency"] == pytest.approx(
        r1["speedup"] * r0["devices"] / r1["devices"], abs=1e-3
    )


def test_rejects_unknown_kind():
    with pytest.raises(ValueError, match="strong"):
        scaling_table("diagonal", (10, 10), [(1, 1)])


def test_table_runs_fused_engine():
    """The fused two-kernel per-shard engine through the scaling-table
    machinery — the path a real pod bench would exercise."""
    t = scaling_table(
        "strong", (20, 20), [(1, 1), (2, 2)], stencil_impl="fused"
    )
    assert t["stencil_impl"] == "fused"
    assert t["iters_consistent"] is True
    assert all(r["converged"] for r in t["rows"])


def test_table_runs_pallas_engine():
    t = scaling_table(
        "strong", (20, 20), [(1, 1), (2, 2)], stencil_impl="pallas"
    )
    assert t["stencil_impl"] == "pallas"
    assert t["iters_consistent"] is True
    assert all(r["converged"] for r in t["rows"])
