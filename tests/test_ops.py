"""Operator (L3) tests: stencil vs dense matrix, SPD properties, block/global
consistency, preconditioner guard."""

import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.reduction import grid_dot
from poisson_ellipse_tpu.ops.stencil import (
    apply_a,
    apply_a_block,
    apply_dinv,
    diag_d,
    diag_d_block,
)


def dense_operator(problem, a, b):
    """Build A as a dense matrix over interior nodes by applying the stencil
    definition row by row (independent of the vectorised implementation)."""
    M, N = problem.M, problem.N
    h1, h2 = problem.h1, problem.h2
    a = np.asarray(a)
    b = np.asarray(b)
    n_int = (M - 1) * (N - 1)
    A = np.zeros((n_int, n_int))

    def idx(i, j):
        return (i - 1) * (N - 1) + (j - 1)

    for i in range(1, M):
        for j in range(1, N):
            row = idx(i, j)
            A[row, row] += (a[i + 1, j] + a[i, j]) / h1**2 + (
                b[i, j + 1] + b[i, j]
            ) / h2**2
            if i + 1 <= M - 1:
                A[row, idx(i + 1, j)] -= a[i + 1, j] / h1**2
            if i - 1 >= 1:
                A[row, idx(i - 1, j)] -= a[i, j] / h1**2
            if j + 1 <= N - 1:
                A[row, idx(i, j + 1)] -= b[i, j + 1] / h2**2
            if j - 1 >= 1:
                A[row, idx(i, j - 1)] -= b[i, j] / h2**2
    return A


@pytest.fixture(scope="module")
def small_problem():
    problem = Problem(M=10, N=12)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    return problem, a, b, rhs


def test_stencil_matches_dense_matrix(small_problem):
    problem, a, b, _ = small_problem
    M, N = problem.M, problem.N
    rng = np.random.default_rng(2)
    w = np.zeros((M + 1, N + 1))
    w[1:M, 1:N] = rng.standard_normal((M - 1, N - 1))
    got = np.asarray(apply_a(jnp.asarray(w), a, b, problem.h1, problem.h2))
    A = dense_operator(problem, a, b)
    want = (A @ w[1:M, 1:N].ravel()).reshape(M - 1, N - 1)
    np.testing.assert_allclose(got[1:M, 1:N], want, rtol=1e-10, atol=1e-8)
    # boundary ring untouched
    assert got[0].max() == 0 and got[-1].max() == 0
    assert got[:, 0].max() == 0 and got[:, -1].max() == 0


def test_operator_is_symmetric_positive_definite(small_problem):
    problem, a, b, _ = small_problem
    M, N = problem.M, problem.N
    rng = np.random.default_rng(3)
    h1, h2 = problem.h1, problem.h2
    for _ in range(5):
        u = np.zeros((M + 1, N + 1))
        v = np.zeros((M + 1, N + 1))
        u[1:M, 1:N] = rng.standard_normal((M - 1, N - 1))
        v[1:M, 1:N] = rng.standard_normal((M - 1, N - 1))
        u_j, v_j = jnp.asarray(u), jnp.asarray(v)
        au = apply_a(u_j, a, b, h1, h2)
        av = apply_a(v_j, a, b, h1, h2)
        lhs = float(grid_dot(au, v_j, h1, h2))
        rhs = float(grid_dot(u_j, av, h1, h2))
        assert lhs == pytest.approx(rhs, rel=1e-10)
        quad = float(grid_dot(au, u_j, h1, h2))
        assert quad > 0.0


@pytest.mark.parametrize("seed", range(8))
def test_operator_spd_on_random_configurations(seed):
    """SURVEY §4's prescription — 'A is SPD on random masks': random
    grids, boxes, ε and f over seeds (each seed yields a different
    fictitious-domain mask and coefficient field), not just random
    vectors on one fixed mask. Symmetry is checked on the dense interior
    matrix and positive-definiteness via its eigenvalues — independent
    of the vectorised stencil implementation."""
    rng = np.random.default_rng(seed)
    M = int(rng.integers(6, 18))
    N = int(rng.integers(6, 18))
    problem = Problem(
        M=M,
        N=N,
        # boxes always contain the ellipse x² + 4y² < 1 (|x|<1, |y|<0.5)
        a1=-float(rng.uniform(1.05, 1.8)),
        b1=float(rng.uniform(1.05, 1.8)),
        a2=-float(rng.uniform(0.55, 1.2)),
        b2=float(rng.uniform(0.55, 1.2)),
        eps=float(10.0 ** rng.uniform(-6, -1)),
        f_val=float(rng.uniform(0.2, 3.0)),
    )
    a, b, _ = assembly.assemble(problem, jnp.float64)
    A = dense_operator(problem, a, b)
    np.testing.assert_allclose(
        A, A.T, rtol=0, atol=1e-12 * np.abs(A).max()
    )
    eig = np.linalg.eigvalsh((A + A.T) / 2.0)
    assert eig.min() > 0.0, f"operator not PD: min eigenvalue {eig.min()}"


def test_diag_matches_dense_diagonal(small_problem):
    problem, a, b, _ = small_problem
    M, N = problem.M, problem.N
    d = np.asarray(diag_d(a, b, problem.h1, problem.h2))
    A = dense_operator(problem, a, b)
    np.testing.assert_allclose(
        d[1:M, 1:N].ravel(), np.diag(A), rtol=1e-12, atol=0
    )


def test_block_ops_match_global(small_problem):
    problem, a, b, _ = small_problem
    M, N = problem.M, problem.N
    rng = np.random.default_rng(4)
    w = np.zeros((M + 1, N + 1))
    w[1:M, 1:N] = rng.standard_normal((M - 1, N - 1))
    w_j = jnp.asarray(w)
    h1, h2 = problem.h1, problem.h2
    full = np.asarray(apply_a(w_j, a, b, h1, h2))
    # treat global rows 3..7, cols 2..9 as one device's owned block
    i0, i1, j0, j1 = 3, 8, 2, 10
    blk = apply_a_block(
        w_j[i0 - 1 : i1 + 1, j0 - 1 : j1 + 1],
        a[i0 - 1 : i1 + 1, j0 - 1 : j1 + 1],
        b[i0 - 1 : i1 + 1, j0 - 1 : j1 + 1],
        h1,
        h2,
    )
    np.testing.assert_allclose(np.asarray(blk), full[i0:i1, j0:j1], rtol=1e-12)
    d_full = np.asarray(diag_d(a, b, h1, h2))
    d_blk = diag_d_block(
        a[i0 - 1 : i1 + 1, j0 - 1 : j1 + 1],
        b[i0 - 1 : i1 + 1, j0 - 1 : j1 + 1],
        h1,
        h2,
    )
    np.testing.assert_allclose(np.asarray(d_blk), d_full[i0:i1, j0:j1], rtol=1e-12)


def test_apply_dinv_zero_guard():
    d = jnp.asarray([[0.0, 2.0], [4.0, 0.0]])
    r = jnp.asarray([[1.0, 1.0], [1.0, 1.0]])
    z = np.asarray(apply_dinv(r, d))
    np.testing.assert_allclose(z, [[0.0, 0.5], [0.25, 0.0]])
