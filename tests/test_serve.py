"""The continuous-batching serve layer (`serve/`) — ISSUE 7.

The contracts this file pins:

- admission is bounded and loud: a full queue sheds with
  ``retry_after_s`` (exit-code 5 contract), a deadline the projected
  wait already overruns is shed at the door;
- deadline semantics at chunk granularity: expiry while queued is shed
  un-dispatched; expiry mid-solve cancels at a chunk boundary with a
  partial result; expiry exactly at completion returns the result with
  no spurious miss (converged lanes retire first);
- the retry ladder walks quarantined lane → fresh lane → guarded
  single solve, each rung a classified outcome;
- the journal is write-ahead and replay-complete: a killed scheduler's
  admitted-but-unfinished requests are replayed by its successor, with
  double completion rejected at the journal;
- the chaos invariants hold under injected NaN + fake OOM + a
  mid-stream kill: zero lost, zero double-completed, all outcomes
  classified (seeded, ≥50 requests);
- the lane-refill chunk advance composes with the lane-sharded mesh at
  EXACTLY 1 psum/iter (jaxpr-pinned), refill included;
- every lifecycle event is request-addressed (trace schema v3) and the
  serving metrics (queue_depth, time_in_queue_seconds,
  deadline_miss_total, shed_total) land in the registry.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import metrics as obs_metrics
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.resilience.faultinject import Fault, FaultPlan
from poisson_ellipse_tpu.serve import (
    DoubleCompletionError,
    RequestJournal,
    Scheduler,
    ServeRequest,
    run_chaos,
)


class FakeClock:
    """A hand-cranked monotonic clock: deadline semantics become
    deterministic instead of racing the test host."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def make_scheduler(**kw):
    kw.setdefault("lanes", 2)
    kw.setdefault("chunk", 8)
    kw.setdefault("backoff_base_s", 0.001)
    return Scheduler(**kw)


# -- admission / backpressure ------------------------------------------------


def test_queue_full_sheds_with_retry_after():
    sched = make_scheduler(queue_capacity=2)
    assert sched.submit(Problem(M=10, N=10)) is None
    assert sched.submit(Problem(M=10, N=10)) is None
    shed = sched.submit(Problem(M=10, N=10))
    assert shed is not None and shed.outcome == "shed"
    assert shed.detail == "queue-full"
    assert shed.retry_after_s > 0
    assert shed.exit_code == 5
    assert not shed.dispatched
    results = sched.drain()
    # the two admitted requests still complete; the shed one is terminal
    done = [r for r in results.values() if r.outcome == "completed"]
    assert len(done) == 2


def test_shed_at_admission_allows_same_id_resubmission():
    # "shed" promises "never queued, safe to resubmit after
    # retry_after_s" (the request.py outcome table) — the recorded shed
    # result must not make the honest resubmission read as a duplicate
    sched = make_scheduler(lanes=1, queue_capacity=1)
    assert sched.submit(Problem(M=10, N=10), request_id="first") is None
    shed = sched.submit(Problem(M=10, N=10), request_id="again")
    assert shed is not None and shed.outcome == "shed"
    sched.drain()
    assert sched.submit(Problem(M=10, N=10), request_id="again") is None
    assert sched.drain()["again"].outcome == "completed"


def test_journal_write_failure_retracts_the_admission(tmp_path):
    # write-ahead means a failed journal write must un-queue the
    # request through the queue API (depth gauge stays consistent),
    # not promise durability the disk refused
    sched = make_scheduler(journal=str(tmp_path / "j.journal"))

    def refuse(req):
        raise OSError("disk full")

    sched.journal.record_admit = refuse
    with pytest.raises(OSError):
        sched.submit(Problem(M=10, N=10))
    assert len(sched.queue) == 0
    assert obs_metrics.REGISTRY.gauge("queue_depth").value == 0


def test_infeasible_deadline_shed_at_admission():
    clock = FakeClock()
    sched = make_scheduler(clock=clock, idle=clock.advance)
    # projected wait is strictly positive, so a deadline of 0 from now
    # cannot be met — reject at the door, never queue
    shed = sched.submit(Problem(M=10, N=10), deadline_s=0.0)
    assert shed is not None and shed.outcome == "shed"
    assert shed.detail == "deadline-infeasible"


# -- deadline semantics (the satellite matrix) -------------------------------


def test_deadline_expiry_while_queued_is_shed_never_dispatched():
    clock = FakeClock()
    sched = make_scheduler(lanes=1, clock=clock, idle=clock.advance)
    # a long-running request occupies the single lane...
    sched.submit(Problem(M=12, N=12, delta=1e-7), request_id="hog")
    sched.step()
    # ...so this one waits in queue past its (feasible-at-admission)
    # deadline
    assert sched.submit(
        Problem(M=10, N=10), deadline_s=10.0, request_id="late"
    ) is None
    clock.advance(11.0)
    results = sched.drain()
    late = results["late"]
    assert late.outcome == "deadline-miss"
    assert late.detail == "expired-in-queue"
    assert not late.dispatched and not late.partial
    assert results["hog"].outcome == "completed"


def test_deadline_expiry_mid_solve_cancels_with_partial_result():
    clock = FakeClock()
    sched = make_scheduler(clock=clock, idle=clock.advance)
    sched.submit(Problem(M=12, N=12, delta=1e-7), deadline_s=5.0,
                 request_id="victim")
    sched.step()  # dispatched, some chunks done
    assert "victim" not in sched.results
    clock.advance(6.0)
    results = sched.drain()
    res = results["victim"]
    assert res.outcome == "deadline-miss"
    assert res.detail == "expired-mid-solve"
    assert res.dispatched and res.partial
    # the partial contract: progress up to the cancelling chunk boundary
    assert res.iters > 0 and np.isfinite(res.diff)
    assert res.w is not None  # the partial iterate, cropped


def test_deadline_expiry_exactly_at_completion_returns_result():
    clock = FakeClock()
    # chunk larger than the solve: the lane converges inside the first
    # chunk, and the deadline passes during it — at the boundary both
    # "converged" and "expired" are true, and converged must win
    sched = make_scheduler(chunk=4096, clock=clock, idle=clock.advance)
    sched.submit(Problem(M=10, N=10), deadline_s=1.0, request_id="edge")
    sched._fill_lanes()
    clock.advance(2.0)  # deadline passes while the chunk runs
    results = sched.drain()
    res = results["edge"]
    assert res.outcome == "completed"
    assert res.converged and res.w is not None
    assert res.detail is None  # no spurious miss recorded


def test_deadline_miss_metric_counts():
    obs_metrics.REGISTRY.reset()
    try:
        clock = FakeClock()
        sched = make_scheduler(clock=clock, idle=clock.advance)
        sched.submit(Problem(M=12, N=12, delta=1e-7), deadline_s=5.0)
        sched.step()
        clock.advance(6.0)
        sched.drain()
        snap = obs_metrics.REGISTRY.snapshot()
        assert snap["counters"]["deadline_miss_total"] == 1
        assert "time_in_queue_seconds" in snap["histograms"]
    finally:
        obs_metrics.REGISTRY.reset()


# -- retire / refill ---------------------------------------------------------


def test_mixed_shapes_pack_one_bucket_and_solve_their_own_problems():
    # 10x10 and 12x12 both bucket to 12x12: one executable, per-lane
    # h/δ/mask — each request must still match its own single solve
    from poisson_ellipse_tpu.solver.pcg import solve as pcg_solve

    sched = make_scheduler()
    sched.submit(Problem(M=10, N=10), request_id="small")
    sched.submit(Problem(M=12, N=12), request_id="big")
    results = sched.drain()
    assert len(sched._ctxs) == 1  # one bucket context served both
    for rid, M in (("small", 10), ("big", 12)):
        single = pcg_solve(Problem(M=M, N=M), jnp.float32)
        res = results[rid]
        assert res.outcome == "completed"
        assert res.w.shape == (M + 1, M + 1)
        np.testing.assert_allclose(
            res.w, np.asarray(single.w), rtol=0, atol=1e-5
        )


def test_refill_reuses_the_compiled_bucket_executable():
    from poisson_ellipse_tpu.serve import scheduler as sched_mod

    sched = make_scheduler(lanes=1)
    fn_cache_info_before = sched_mod._bucket_advance.cache_info()
    for i in range(3):
        sched.submit(Problem(M=10, N=10), request_id=f"r{i}")
    sched.drain()
    info = sched_mod._bucket_advance.cache_info()
    # one bucket build at most (possibly cached from an earlier test):
    # serving 3 sequential requests through one lane never rebuilds
    assert info.misses - fn_cache_info_before.misses <= 1


def test_iteration_cap_classifies_cap_outcome():
    sched = make_scheduler()
    # δ unreachable in 5 iterations: the per-request cap must end it
    sched.submit(Problem(M=12, N=12, delta=1e-12, max_iter=5),
                 request_id="capped")
    res = sched.drain()["capped"]
    assert res.outcome == "cap"
    assert res.iters == 5
    assert res.exit_code == 1


# -- the retry ladder --------------------------------------------------------


def test_nan_fault_retries_on_fresh_lane_and_completes():
    plan = FaultPlan(Fault("nan", at_iter=4, field="r",
                           request_id="victim"))
    sched = make_scheduler(faults=plan, max_retries=1)
    sched.submit(Problem(M=10, N=10), request_id="victim")
    sched.submit(Problem(M=10, N=10), request_id="bystander")
    results = sched.drain()
    assert results["victim"].outcome == "completed"
    assert results["victim"].attempts == 2  # one quarantine, one retry
    assert results["bystander"].outcome == "completed"
    assert results["bystander"].attempts == 1
    assert plan.faults[0].fired


def test_oom_fault_walks_ladder_and_completes():
    plan = FaultPlan(Fault("oom", at_iter=2, request_id="victim"))
    sched = make_scheduler(faults=plan, max_retries=1)
    sched.submit(Problem(M=10, N=10), request_id="victim")
    res = sched.drain()["victim"]
    assert res.outcome == "completed"
    assert res.attempts == 2


def test_total_s_spans_retries_from_first_admission():
    clock = FakeClock()
    plan = FaultPlan(Fault("nan", at_iter=2, field="r",
                           request_id="victim"))
    sched = make_scheduler(faults=plan, max_retries=1, clock=clock,
                           idle=clock.advance, backoff_base_s=0.5)
    sched.submit(Problem(M=10, N=10), request_id="victim")
    sched.step()  # first attempt on the lane
    clock.advance(10.0)  # time the failed attempt burns
    res = sched.drain()["victim"]
    assert res.outcome == "completed" and res.attempts == 2
    # end-to-end latency anchors on the FIRST admission: the 10 s lost
    # to the poisoned attempt counts (bench's p99 reads this field) —
    # only the per-visit queue-wait is allowed to reset on requeue
    assert res.total_s >= 10.0
    assert res.time_in_queue_s < 10.0


def test_requeue_overflow_failure_reports_dispatched_and_no_shed():
    plan = FaultPlan(Fault("nan", at_iter=2, field="r",
                           request_id="victim"))
    sched = make_scheduler(lanes=1, queue_capacity=1, faults=plan,
                           max_retries=1)
    sched.submit(Problem(M=10, N=10), request_id="victim")
    sched.step()  # victim on the lane
    sched.submit(Problem(M=10, N=10), request_id="filler")  # queue full
    shed_before = obs_metrics.REGISTRY.counter("shed_total").value
    results = sched.drain()
    res = results["victim"]
    assert res.outcome == "failed"
    assert res.detail == "requeue-shed-under-overload"
    # the request really ran before its lane died: consumers use
    # `dispatched` to separate "never ran" from "ran and failed"
    assert res.dispatched
    # and its terminal outcome is failed, not shed — the shed counter
    # must keep equalling the number of shed OUTCOMES
    assert obs_metrics.REGISTRY.counter("shed_total").value == shed_before
    assert results["filler"].outcome == "completed"


def test_guarded_fallback_queue_wait_excludes_solve_time(monkeypatch):
    clock = FakeClock()
    plan = FaultPlan(Fault("nan", at_iter=2, field="r",
                           request_id="victim", persistent=True))
    sched = make_scheduler(faults=plan, max_retries=0, clock=clock,
                           idle=clock.advance)
    from poisson_ellipse_tpu.resilience import guard as guard_mod

    real = guard_mod.guarded_solve

    def slow(*args, **kwargs):
        clock.advance(30.0)  # the fallback solve takes 30 fake seconds
        return real(*args, **kwargs)

    monkeypatch.setattr(guard_mod, "guarded_solve", slow)
    sched.submit(Problem(M=10, N=10), request_id="victim")
    res = sched.drain()["victim"]
    assert res.outcome == "completed" and res.detail == "guarded-fallback"
    # queue-wait accounting stops at the fallback's dispatch: the solve
    # is service time, not queueing — while total_s keeps the whole span
    assert res.time_in_queue_s < 30.0
    assert res.total_s >= 30.0


def test_persistent_fault_exhausts_budget_then_guarded_fallback():
    plan = FaultPlan(Fault("nan", at_iter=2, field="r",
                           request_id="victim", persistent=True))
    sched = make_scheduler(faults=plan, max_retries=2)
    sched.submit(Problem(M=10, N=10), request_id="victim")
    res = sched.drain()["victim"]
    # every laned attempt is poisoned; the final rung is the guarded
    # single solve, which the request-addressed fault cannot reach
    assert res.outcome == "completed"
    assert res.detail == "guarded-fallback"
    assert res.attempts == 4  # 1 initial + 2 retries + the fallback


# -- journal / replay --------------------------------------------------------


def test_journal_snapshot_is_atomic_and_replay_complete(tmp_path):
    path = tmp_path / "journal.json"
    sched = make_scheduler(journal=RequestJournal(path))
    for i in range(4):
        sched.submit(Problem(M=10, N=10), request_id=f"r{i}")
    sched.step()  # two in flight, two queued; then the "kill"
    assert not list(tmp_path.glob(".journal-*")), "no temp litter"
    successor = make_scheduler(journal=RequestJournal(path))
    assert successor.replay() == 4
    results = successor.drain()
    assert {results[f"r{i}"].outcome for i in range(4)} == {"completed"}
    journal = RequestJournal(path)
    assert journal.counts() == {
        "admitted": 4, "finished": 4, "unfinished": 0,
    }


def test_journal_refuses_double_completion(tmp_path):
    journal = RequestJournal(tmp_path / "j.json")
    req = ServeRequest(problem=Problem(M=10, N=10), request_id="once")
    journal.record_admit(req)
    journal.record_outcome("once", "completed")
    with pytest.raises(DoubleCompletionError):
        journal.record_outcome("once", "completed")
    with pytest.raises(DoubleCompletionError):
        journal.record_admit(req)
    with pytest.raises(KeyError):
        journal.record_outcome("never-admitted", "completed")


def test_replay_overflow_waits_in_backlog_never_terminally_shed(tmp_path):
    # a restart can arrive with more journaled admissions than one
    # queue's worth; the overflow re-enters in waves as lanes drain —
    # durably-acknowledged requests are never terminally shed by replay
    path = tmp_path / "journal.json"
    journal = RequestJournal(path)
    for i in range(6):
        journal.record_admit(
            ServeRequest(problem=Problem(M=10, N=10), request_id=f"r{i}")
        )
    successor = make_scheduler(
        journal=RequestJournal(path), queue_capacity=2, lanes=1,
    )
    assert successor.replay() == 6
    assert len(successor.queue) == 2 and len(successor._replay_backlog) == 4
    results = successor.drain()
    assert {results[f"r{i}"].outcome for i in range(6)} == {"completed"}
    assert RequestJournal(path).counts()["unfinished"] == 0


def test_journal_compacts_finished_records_to_o_live_snapshots(tmp_path):
    import json as _json

    path = tmp_path / "j.json"
    journal = RequestJournal(path)
    for i in range(5):
        journal.record_admit(
            ServeRequest(problem=Problem(M=10, N=10), request_id=f"r{i}")
        )
        journal.record_outcome(f"r{i}", "completed")
    journal.record_admit(
        ServeRequest(problem=Problem(M=10, N=10), request_id="live")
    )
    # the snapshot holds only the live admission; finished requests
    # survive as a durable counter, not ever-growing records
    with open(path, encoding="utf-8") as fh:
        snap = _json.load(fh)
    assert set(snap["requests"]) == {"live"}
    assert snap["finished"] == 5
    reloaded = RequestJournal(path)
    assert reloaded.counts() == {
        "admitted": 6, "finished": 5, "unfinished": 1,
    }
    assert [r.request_id for r in reloaded.unfinished(0.0)] == ["live"]
    assert journal.state_of("r0") == {"state": "done"}
    assert journal.state_of("r0-nonexistent") is None


def test_duplicate_request_id_is_refused_without_touching_the_original(
        tmp_path):
    # a second live submission under the same id can never get its own
    # outcome slot: it must be refused at the door — not crash the serve
    # loop with a DoubleCompletionError, not overwrite the original
    sched = make_scheduler(journal=RequestJournal(tmp_path / "j.json"))
    assert sched.submit(Problem(M=10, N=10), request_id="dup") is None
    refused = sched.submit(Problem(M=12, N=12), request_id="dup")
    assert refused is not None and refused.outcome == "shed"
    assert refused.detail == "duplicate-request-id"
    results = sched.drain()
    assert results["dup"].outcome == "completed"
    # terminal ids stay refused too (the journal remembers)
    refused = sched.submit(Problem(M=10, N=10), request_id="dup")
    assert refused is not None and refused.detail == "duplicate-request-id"
    assert results["dup"].outcome == "completed"


def test_replay_infeasible_deadline_is_a_miss_not_a_shed(tmp_path):
    # an acknowledged admission whose restarted deadline budget can no
    # longer be met is a deadline-miss (exit 4) — "shed" would invite
    # resubmission of an id the journal already owns
    journal = RequestJournal(tmp_path / "j.json")
    req = ServeRequest(problem=Problem(M=10, N=10), request_id="r0",
                       deadline=0.0)
    req.enqueued_t = 0.0  # deadline_left_s journals as 0
    journal.record_admit(req)
    clock = FakeClock()
    successor = make_scheduler(
        journal=RequestJournal(tmp_path / "j.json"), clock=clock,
        idle=clock.advance,
    )
    shed_before = obs_metrics.REGISTRY.counter("shed_total").value
    successor.replay()
    res = successor.drain()["r0"]
    assert res.outcome == "deadline-miss"
    assert res.detail == "replay-deadline-infeasible"
    assert not res.dispatched
    # classified deadline-miss, so no shed event/counter may fire —
    # shed_total always equals the number of shed outcomes
    assert obs_metrics.REGISTRY.counter("shed_total").value == shed_before


def test_idle_bucket_rebases_its_iteration_clock():
    # the serve carry's global k only moves forward; a long-lived
    # server must rebase it between requests or walk into ITER_CEILING
    # and wedge. After a drain the bucket must sit at k == 0 again.
    sched = make_scheduler()
    sched.submit(Problem(M=10, N=10), request_id="r0")
    sched.drain()
    (ctx,) = sched._ctxs.values()
    assert int(ctx.state[0]) == 0
    # and a second stream through the same rebased bucket still works
    sched.submit(Problem(M=10, N=10), request_id="r1")
    assert sched.drain()["r1"].outcome == "completed"


def test_collect_evicts_results(tmp_path):
    # the hand-off path a long-lived server drains through: collect()
    # empties the scheduler's buffer (solutions included) — results
    # must not accumulate for the process lifetime
    sched = make_scheduler()
    sched.submit(Problem(M=10, N=10), request_id="r0")
    sched.drain()
    first = sched.collect()
    assert first["r0"].outcome == "completed"
    assert sched.results == {} and sched.collect() == {}
    sched.submit(Problem(M=10, N=10), request_id="r1")
    sched.drain()
    assert set(sched.collect()) == {"r1"}


def test_replayed_deadline_budget_restarts(tmp_path):
    clock = FakeClock(100.0)
    sched = make_scheduler(journal=RequestJournal(tmp_path / "j.json"),
                           clock=clock, idle=clock.advance)
    sched.submit(Problem(M=10, N=10), deadline_s=60.0, request_id="r0")
    # replay in a "new process": the journaled remaining budget applies
    # from the new clock, not the dead one's absolute deadline
    clock2 = FakeClock(0.0)
    successor = make_scheduler(
        journal=RequestJournal(tmp_path / "j.json"), clock=clock2,
        idle=clock2.advance,
    )
    assert successor.replay() == 1
    req = successor.queue.pop_ready(clock2())
    assert req.deadline == pytest.approx(60.0, abs=1.0)


# -- chaos: the acceptance invariants ----------------------------------------


def test_chaos_fifty_requests_nan_oom_kill_zero_lost(tmp_path):
    report = run_chaos(
        n_requests=50, seed=7,
        journal_path=os.path.join(tmp_path, "chaos.json"),
    )
    assert report.ok, (
        f"lost={report.lost} doubled={report.double_completed} "
        f"unclassified={report.unclassified}"
    )
    assert report.killed and report.replayed >= 1
    assert report.faults_fired == 2  # the NaN lane and the fake OOM
    assert sum(report.counts.values()) == 50
    assert set(report.counts) <= {
        "completed", "cap", "failed", "deadline-miss", "shed",
    }
    # the injected faults must not have cost the victims their results
    assert report.outcomes["chaos-0002"] == "completed"
    assert report.outcomes["chaos-0005"] == "completed"


def test_chaos_is_seed_deterministic(tmp_path):
    r1 = run_chaos(n_requests=10, seed=3,
                   journal_path=os.path.join(tmp_path, "c1.json"))
    r2 = run_chaos(n_requests=10, seed=3,
                   journal_path=os.path.join(tmp_path, "c2.json"))
    assert r1.outcomes == r2.outcomes
    assert r1.counts == r2.counts


def test_chaos_mesh_kill_zero_lost_zero_double(tmp_path):
    """The device-kill drill (`harness chaos --mesh`): a simulated
    device loss takes out every live batch carry mid-stream — every
    in-flight request re-enters through the journal/retry ladder — and
    the process kill + replay rides on top. Zero lost, zero doubled,
    all classified, across BOTH failure modes."""
    report = run_chaos(
        n_requests=24, seed=11,
        journal_path=os.path.join(tmp_path, "chaos.json"),
        mesh_kill_request=5,
    )
    assert report.ok, (
        f"lost={report.lost} doubled={report.double_completed} "
        f"unclassified={report.unclassified}"
    )
    assert report.mesh_killed and report.killed
    assert sum(report.counts.values()) == 24
    # the request hosting the killed device must still end classified
    assert report.outcomes["chaos-0005"] in {
        "completed", "cap", "failed", "deadline-miss",
    }


def test_chaos_mesh_kill_is_seed_deterministic(tmp_path):
    kw = dict(n_requests=14, seed=5, mesh_kill_request=4)
    r1 = run_chaos(journal_path=os.path.join(tmp_path, "m1.json"), **kw)
    r2 = run_chaos(journal_path=os.path.join(tmp_path, "m2.json"), **kw)
    assert r1.outcomes == r2.outcomes
    assert r1.mesh_killed and r2.mesh_killed


def test_scheduler_device_loss_reenters_in_flight(tmp_path):
    """Unit form of the drill: a device_loss fault fired mid-batch drops
    every batch context, and each in-flight request walks the retry
    ladder to a terminal outcome — nothing lost, nothing doubled."""
    from poisson_ellipse_tpu.resilience.faultinject import Fault

    sched = Scheduler(
        lanes=2, chunk=4, max_retries=1, backoff_base_s=0.0,
        journal=RequestJournal(os.path.join(tmp_path, "j.json")),
        faults=FaultPlan(
            Fault("device_loss", at_iter=1, device=0, request_id="dl-0")
        ),
    )
    for i in range(3):
        assert sched.submit(Problem(M=10, N=10), request_id=f"dl-{i}") is None
    results = sched.drain()
    assert set(results) == {"dl-0", "dl-1", "dl-2"}
    assert all(r.outcome == "completed" for r in results.values())
    # the kill really fired: attempts reflect the re-entry
    assert any(r.attempts > 1 for r in results.values())


# -- lane-sharded composition: the 1-psum pin --------------------------------


def test_sharded_chunk_advance_exactly_one_psum_per_iteration():
    from poisson_ellipse_tpu.obs.static_cost import (
        COLLECTIVE_PRIMS,
        loop_primitive_counts,
    )
    from poisson_ellipse_tpu.parallel.batched_sharded import (
        build_sharded_chunk_advance,
    )
    from poisson_ellipse_tpu.parallel.mesh import make_mesh
    from poisson_ellipse_tpu.serve.scheduler import _BatchCtx

    mesh = make_mesh(jax.devices()[:2])
    ctx = _BatchCtx((12, 12), lanes=2, dtype=jnp.float32, norm="weighted",
                    mesh=mesh)
    fn, _ = build_sharded_chunk_advance((12, 12), mesh=mesh, lanes=2)
    args = (ctx.a3, ctx.b3, ctx.mask, ctx.h1, ctx.h2, ctx.delta,
            ctx.state, jnp.asarray(8, jnp.int32))
    counts = loop_primitive_counts(fn, args, COLLECTIVE_PRIMS)
    # the refill machinery is host-side between chunks: the loop body
    # still carries exactly the one convergence-word psum
    assert counts["psum"] + counts["psum_invariant"] == 1
    assert counts["ppermute"] == 0


def test_scheduler_on_mesh_serves_and_refills():
    from poisson_ellipse_tpu.parallel.mesh import make_mesh
    from poisson_ellipse_tpu.solver.pcg import solve as pcg_solve

    mesh = make_mesh(jax.devices()[:2])
    sched = make_scheduler(mesh=mesh)
    for i in range(3):  # 3 requests over 2 lanes forces one refill
        sched.submit(Problem(M=12, N=12), request_id=f"r{i}")
    results = sched.drain()
    single = pcg_solve(Problem(M=12, N=12), jnp.float32)
    for i in range(3):
        res = results[f"r{i}"]
        assert res.outcome == "completed"
        assert res.iters == int(single.iters)
        np.testing.assert_allclose(
            res.w, np.asarray(single.w), rtol=0, atol=5e-6
        )


# -- observability -----------------------------------------------------------


def test_lifecycle_events_are_request_addressed_schema_v3(tmp_path):
    path = tmp_path / "serve.jsonl"
    obs_trace.start(str(path))
    try:
        plan = FaultPlan(Fault("nan", at_iter=4, field="r",
                               request_id="victim"))
        sched = make_scheduler(queue_capacity=1, faults=plan,
                               max_retries=1)
        sched.submit(Problem(M=10, N=10), request_id="victim")
        sched.submit(Problem(M=10, N=10), request_id="overflow")
        sched.drain()
    finally:
        obs_trace.stop()
    assert obs_trace.validate_file(str(path)) == []
    records = obs_trace.read_jsonl(str(path))
    by_name: dict[str, list] = {}
    for rec in records:
        by_name.setdefault(rec["name"], []).append(rec)
    for name in ("serve:admit", "serve:refill", "serve:retire",
                 "serve:shed", "serve:fault", "serve:retry"):
        assert name in by_name, f"missing {name}"
        assert all(r.get("request_id") for r in by_name[name]), (
            f"{name} events must carry request_id"
        )
    # the shed event names the overflow request
    assert by_name["serve:shed"][0]["request_id"] == "overflow"


def test_queue_depth_and_shed_metrics():
    obs_metrics.REGISTRY.reset()
    try:
        sched = make_scheduler(queue_capacity=1)
        sched.submit(Problem(M=10, N=10))
        sched.submit(Problem(M=10, N=10))  # shed
        snap = obs_metrics.REGISTRY.snapshot()
        assert snap["counters"]["shed_total"] == 1
        assert snap["gauges"]["queue_depth"] == 1
        sched.drain()
        snap = obs_metrics.REGISTRY.snapshot()
        assert snap["gauges"]["queue_depth"] == 0
        assert snap["counters"]["serve_completed_total"] == 1
    finally:
        obs_metrics.REGISTRY.reset()


# -- CLI ---------------------------------------------------------------------


def test_cli_serve_subcommand(tmp_path, capsys):
    import json

    from poisson_ellipse_tpu.harness.__main__ import main

    trace = tmp_path / "serve.jsonl"
    rc = main([
        "serve", "--requests", "3", "--grids", "10x10", "--rate", "1000",
        "--trace", str(trace), "--json",
    ])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["outcomes"] == {"completed": 3}
    assert rec["solves_per_sec"] > 0
    assert obs_trace.validate_file(str(trace)) == []


def test_cli_chaos_subcommand(capsys):
    import json

    from poisson_ellipse_tpu.harness.__main__ import main

    rc = main(["chaos", "--requests", "10", "--seed", "2", "--json"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["ok"] is True
    assert rec["killed"] is True and rec["replayed"] >= 0
    assert sum(rec["counts"].values()) == 10


def test_cli_serve_rejects_bad_args(capsys):
    from poisson_ellipse_tpu.harness.__main__ import main

    assert main(["serve", "--requests", "0"]) == 2
    assert main(["serve", "--replay"]) == 2
    assert main(["serve", "--rate", "0"]) == 2
    assert main(["serve", "--rate", "-5"]) == 2
