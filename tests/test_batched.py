"""Batched multi-solve engines (`batch/`) + the serving cache layer.

The contracts this file pins (ISSUE 5):

- lane 0 of a batched solve is BIT-identical to the single-engine solve
  (lane batching is free of cross-lane arithmetic, not approximately so);
- mixed-ε lanes each converge at their own single-solve oracle count;
- a NaN-poisoned lane is quarantined — masked out with a
  ``recovery:lane-quarantine`` trace event — while the healthy lanes
  match their oracle exactly;
- the lane-sharded composition issues EXACTLY one psum per while-body
  (jaxpr-pinned), independent of recurrence;
- a re-request for a bucketed shape is a warm-pool cache HIT returning
  the same executable object (no recompile);
- the batched Pallas kernels (lane dim on the kernel grid) are bitwise
  twins of the single-lane kernels, per lane.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.batch import (
    batched_operands,
    pcg_batched,
    pcg_batched_pipelined,
    solve_batched,
)
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.pipelined_pcg import pcg_pipelined
from poisson_ellipse_tpu.solver.engine import build_solver
from poisson_ellipse_tpu.solver.pcg import pcg


@pytest.fixture(scope="module")
def problem():
    return Problem(M=40, N=40)


@pytest.fixture(scope="module")
def single(problem):
    a, b, rhs = assembly.assemble(problem, jnp.float32)
    return jax.jit(lambda a, b, r: pcg(problem, a, b, r))(a, b, rhs)


# -- lane-0 bit parity -------------------------------------------------------


def test_lane0_bit_identical_to_single_solve(problem, single):
    solver, args, engine = build_solver(problem, "batched", jnp.float32,
                                        lanes=3)
    res = solver(*args)
    assert engine == "batched"
    assert bool(jnp.all(res.converged)) and not bool(jnp.any(res.quarantined))
    assert int(res.iters[0]) == int(single.iters) == 50
    assert float(res.diff[0]) == float(single.diff)
    assert bool(jnp.all(res.w[0] == single.w)), "lane 0 must be bitwise"
    # identical lanes take identical trajectories: all lanes bitwise
    assert bool(jnp.all(res.w[1] == res.w[0]))


def test_lane0_bit_identical_pipelined(problem):
    a, b, rhs = assembly.assemble(problem, jnp.float32)
    sp = jax.jit(lambda a, b, r: pcg_pipelined(problem, a, b, r))(a, b, rhs)
    solver, args, _ = build_solver(problem, "batched-pipelined",
                                  jnp.float32, lanes=3)
    res = solver(*args)
    assert bool(jnp.all(res.converged))
    assert int(res.iters[0]) == int(sp.iters)
    assert bool(jnp.all(res.w[0] == sp.w)), "pipelined lane 0 must be bitwise"


def test_distinct_rhs_lanes_solve_their_own_problems(problem, single):
    a, b, rhs = assembly.assemble(problem, jnp.float32)
    # lane 1 solves the doubled-RHS problem: by linearity its solution is
    # 2x lane 0's (up to round-off) and its iteration count the same
    rb = jnp.stack([rhs, rhs * 2.0])
    res = jax.jit(lambda a, b, r: pcg_batched(problem, a, b, r))(a, b, rb)
    assert bool(jnp.all(res.converged))
    assert bool(jnp.all(res.w[0] == single.w))
    # lane 1's 2x-scaled step norms cross δ a step later, so its tail
    # iterations differ — value-equivalence, not bitwise scaling
    np.testing.assert_allclose(
        np.asarray(res.w[1]), 2.0 * np.asarray(res.w[0]), rtol=1e-3,
        atol=1e-7,
    )


def test_refilled_lane_bit_identical_to_single_solve():
    """The lane-refill correctness pin (ISSUE 7): a lane swapped in
    MID-batch — nonzero global k, another lane still iterating — must
    produce the bit-identical solution of the same request solved
    single-lane. Per-lane arithmetic is lane-decoupled and k-independent,
    so swap-in is bitwise-free exactly like lane packing at k=0."""
    from poisson_ellipse_tpu.serve import Scheduler
    from poisson_ellipse_tpu.solver.pcg import solve as pcg_solve

    # 12x12 is bucket-exact (bucket_dim(12) == 12): no padding, so the
    # embedded problem IS the problem and bitwise comparison is fair
    p = Problem(M=12, N=12)
    single = pcg_solve(p, jnp.float32)
    sched = Scheduler(lanes=2, chunk=4)
    # lane 0 hosts a longer request; lane 1's first tenant retires early
    sched.submit(Problem(M=12, N=12, delta=1e-7), request_id="long")
    sched.submit(Problem(M=12, N=12, delta=5e-6), request_id="short")
    for _ in range(100):
        sched.step()
        if "short" in sched.results:
            break
    assert "short" in sched.results and "long" not in sched.results, (
        "need a retirement while the other lane is still in flight"
    )
    sched.submit(p, request_id="swapped")
    # dispatch at the next boundary, and read the swap-in offset BEFORE
    # any chunk advance: retirements rebase the batch clock, so base_k
    # is only meaningful at the moment of the swap-in itself
    sched._fill_lanes()
    located = sched._slot_of("swapped")
    assert located is not None and located[1].base_k > 0, (
        "the swap-in must happen mid-batch"
    )
    results = sched.drain()
    res = results["swapped"]
    assert res.outcome == "completed"
    assert res.iters == int(single.iters)
    assert float(res.diff) == float(single.diff)
    assert bool(np.all(res.w == np.asarray(single.w))), (
        "a refilled lane's solution must be bitwise identical to the "
        "single-lane solve"
    )


# -- mixed-ε lanes -----------------------------------------------------------


def test_mixed_eps_lanes_each_hit_their_oracle():
    base = Problem(M=32, N=32)
    eps_values = (base.eps_value, 1e-2, 1e-4)
    oracles = []
    for eps in eps_values:
        p = Problem(M=32, N=32, eps=eps)
        a, b, rhs = assembly.assemble(p, jnp.float32)
        r = jax.jit(lambda a, b, r: pcg(p, a, b, r))(a, b, rhs)
        assert bool(r.converged)
        oracles.append(int(r.iters))
    a, b, rhs = batched_operands(base, 3, jnp.float32,
                                 eps_values=eps_values)
    assert a.ndim == 3  # per-lane coefficients
    res = jax.jit(lambda a, b, r: pcg_batched(base, a, b, r))(a, b, rhs)
    assert bool(jnp.all(res.converged))
    for lane, oracle in enumerate(oracles):
        assert abs(int(res.iters[lane]) - oracle) <= 2, (
            f"lane {lane}: {int(res.iters[lane])} vs oracle {oracle}"
        )


# -- NaN-lane quarantine -----------------------------------------------------


def test_nan_lane_quarantined_healthy_lanes_match_oracle(problem, single):
    from poisson_ellipse_tpu.resilience.faultinject import (
        FaultPlan,
        inject_nan,
    )

    guarded = solve_batched(
        problem, 3, "batched", jnp.float32, chunk=16,
        faults=FaultPlan(inject_nan(10, "r", lane=1)),
    )
    res = guarded.result
    assert list(np.asarray(res.quarantined)) == [False, True, False]
    assert list(np.asarray(res.converged)) == [True, False, True]
    # the poisoned lane was masked out at the iteration after injection
    assert int(res.iters[1]) == 11
    # healthy lanes are untouched: oracle-exact, finite, mutually bitwise
    for lane in (0, 2):
        assert int(res.iters[lane]) == int(single.iters)
        assert np.isfinite(np.asarray(res.w[lane])).all()
    assert bool(jnp.all(res.w[0] == res.w[2]))
    kinds = [e.kind for e in guarded.recoveries]
    assert kinds == ["lane-quarantine"]
    assert guarded.recoveries[0].detail == "lane 1"


def test_quarantine_event_reaches_the_trace(problem, tmp_path):
    from poisson_ellipse_tpu.obs import trace as obs_trace
    from poisson_ellipse_tpu.resilience.faultinject import (
        FaultPlan,
        inject_nan,
    )

    path = tmp_path / "quarantine.jsonl"
    obs_trace.start(str(path))
    try:
        solve_batched(
            problem, 2, "batched", jnp.float32, chunk=16,
            faults=FaultPlan(inject_nan(8, "r", lane=0)),
        )
    finally:
        obs_trace.stop()
    assert obs_trace.validate_file(str(path)) == []
    names = {r["name"] for r in obs_trace.read_jsonl(str(path))}
    assert "recovery:lane-quarantine" in names


def test_chunked_driver_matches_fused_iteration_counts(problem):
    fused_solver, args, _ = build_solver(problem, "batched", jnp.float32,
                                         lanes=2)
    fused = fused_solver(*args)
    chunked = solve_batched(problem, 2, "batched", jnp.float32, chunk=16)
    assert chunked.recoveries == ()
    assert list(np.asarray(chunked.result.iters)) == list(
        np.asarray(fused.iters)
    )
    np.testing.assert_allclose(
        np.asarray(chunked.result.w), np.asarray(fused.w), rtol=0,
        atol=5e-6,
    )


def test_driver_rejects_unaddressed_or_out_of_range_faults(problem):
    from poisson_ellipse_tpu.resilience.faultinject import (
        FaultPlan,
        inject_nan,
    )

    with pytest.raises(ValueError, match="lane-addressed"):
        solve_batched(problem, 2, "batched", jnp.float32,
                      faults=FaultPlan(inject_nan(10, "r")))
    with pytest.raises(ValueError, match="outside"):
        solve_batched(problem, 2, "batched", jnp.float32,
                      faults=FaultPlan(inject_nan(10, "r", lane=5)))


def test_lane_fault_on_scalar_field_quarantines(problem):
    # zr is a (B,) per-lane scalar: lane addressing must work there too
    from poisson_ellipse_tpu.resilience.faultinject import (
        Fault,
        FaultPlan,
    )

    guarded = solve_batched(
        problem, 2, "batched", jnp.float32, chunk=16,
        faults=FaultPlan(Fault("nan", at_iter=10, field="zr", lane=0)),
    )
    assert bool(guarded.result.quarantined[0])
    assert bool(guarded.result.converged[1])


def test_pipelined_lane_fault_also_quarantined(problem):
    from poisson_ellipse_tpu.resilience.faultinject import (
        FaultPlan,
        inject_nan,
    )

    guarded = solve_batched(
        problem, 2, "batched-pipelined", jnp.float32, chunk=16,
        faults=FaultPlan(inject_nan(10, "r", lane=1)),
    )
    res = guarded.result
    assert bool(res.quarantined[1]) and not bool(res.quarantined[0])
    assert bool(res.converged[0])
    assert [e.kind for e in guarded.recoveries] == ["lane-quarantine"]


# -- lane-sharded mesh: the 1-psum pin ---------------------------------------


@pytest.mark.parametrize("pipelined", [True, False])
def test_lane_sharded_exactly_one_psum_per_while_body(pipelined):
    from poisson_ellipse_tpu.analysis.contracts import assert_contract

    # exactly ONE collective — the convergence word; the dot bundles are
    # lane-local (whole lanes per device), so the count is flat in B:
    # the declared batched-cadence contract, from the ENGINE_CAPS row
    engine = "batched-pipelined" if pipelined else "batched"
    r = assert_contract(
        "batched-cadence", engine, problem=Problem(M=40, N=40),
        mesh_shape=(1, 2), lanes=4,
    )
    assert r.expected == {"psum": 1, "ppermute": 0}


def test_lane_sharded_solves_match_single(problem, single):
    from poisson_ellipse_tpu.parallel.batched_sharded import (
        build_batched_sharded_solver,
    )
    from poisson_ellipse_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(jax.devices()[:2])
    solver, args = build_batched_sharded_solver(
        problem, mesh, lanes=4, dtype=jnp.float32
    )
    res = solver(*args)
    assert bool(jnp.all(res.converged))
    assert all(int(i) == int(single.iters) for i in res.iters)
    np.testing.assert_allclose(
        np.asarray(res.w[0]), np.asarray(single.w), rtol=0, atol=5e-6
    )


def test_lane_sharded_requires_whole_lanes_per_device():
    from poisson_ellipse_tpu.parallel.batched_sharded import (
        build_batched_sharded_solver,
    )
    from poisson_ellipse_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(jax.devices()[:2])
    with pytest.raises(ValueError, match="multiple of the mesh"):
        build_batched_sharded_solver(Problem(M=10, N=10), mesh, lanes=3)


# -- warm pool / bucketed AOT cache ------------------------------------------


def test_bucketed_cache_rerequest_is_a_hit_same_executable():
    from poisson_ellipse_tpu.runtime.compile_cache import WarmPool

    pool = WarmPool()
    first = pool.warmup("batched", (10, 10), jnp.float32, lanes=3)
    assert (pool.hits, pool.misses) == (0, 1)
    # a DIFFERENT request shape in the same bucket: hit, same executable
    second = pool.warmup("batched", (11, 12), jnp.float32, lanes=4)
    assert second.compiled is first.compiled
    assert (pool.hits, pool.misses) == (1, 1)
    # a different lane bucket is a different executable
    third = pool.warmup("batched", (10, 10), jnp.float32, lanes=5)
    assert third.compiled is not first.compiled
    assert pool.misses == 2


def test_bucketed_solve_serves_embedded_request():
    from poisson_ellipse_tpu.runtime.compile_cache import WarmPool
    from poisson_ellipse_tpu.solver.pcg import solve as single_solve

    p = Problem(M=10, N=10)
    clean = single_solve(p, jnp.float32)
    pool = WarmPool()
    res = pool.solve(p, 3, "batched", jnp.float32)
    assert res.w.shape == (3, 11, 11)
    assert bool(jnp.all(res.converged))
    # pad-and-mask embedding is value-equivalent (reduction-order ulps),
    # iteration counts within a step of the exact-shape solve
    assert all(abs(int(i) - int(clean.iters)) <= 2 for i in res.iters)
    np.testing.assert_allclose(
        np.asarray(res.w[0]), np.asarray(clean.w), rtol=0, atol=1e-5
    )
    # serving the request warmed the bucket: a second solve in the same
    # lane bucket (4 lanes -> bucket 4, same as 3) is a pure hit
    pool.solve(p, 4, "batched", jnp.float32)
    assert pool.hits >= 1


def test_cache_events_and_counters_emitted(tmp_path):
    from poisson_ellipse_tpu.obs import trace as obs_trace
    from poisson_ellipse_tpu.runtime.compile_cache import WarmPool

    path = tmp_path / "cache.jsonl"
    pool = WarmPool()
    obs_trace.start(str(path))
    try:
        pool.warmup("batched", (10, 10), jnp.float32, lanes=1)
        pool.warmup("batched", (10, 10), jnp.float32, lanes=1)
    finally:
        obs_trace.stop()
    names = [r["name"] for r in obs_trace.read_jsonl(str(path))]
    assert "cache:miss" in names and "cache:hit" in names


def test_bucket_ladder_shapes():
    from poisson_ellipse_tpu.runtime.compile_cache import (
        bucket_dim,
        grid_bucket,
        lane_bucket,
    )

    assert bucket_dim(8) == 8
    assert bucket_dim(9) == 12
    assert bucket_dim(400) == 512
    assert grid_bucket(400, 600) == (512, 768)
    assert lane_bucket(1) == 1
    assert lane_bucket(3) == 4
    assert lane_bucket(32) == 32


# -- batched Pallas kernels (lane dim on the kernel grid) --------------------


def test_batched_pallas_stencil_bitwise_per_lane(problem):
    from poisson_ellipse_tpu.ops.pallas_kernels import (
        apply_a_batched_pallas,
        apply_a_pallas,
    )

    a, b, rhs = assembly.assemble(problem, jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(0), (3,) + rhs.shape,
                          jnp.float32)
    w = w.at[:, 0].set(0).at[:, -1].set(0)
    w = w.at[:, :, 0].set(0).at[:, :, -1].set(0)
    single = jnp.stack([
        apply_a_pallas(w[i], a, b, problem.h1, problem.h2, interpret=True)
        for i in range(3)
    ])
    out = apply_a_batched_pallas(w, a, b, problem.h1, problem.h2,
                                 interpret=True)
    assert bool(jnp.all(out == single))


def test_batched_pallas_fused_dots_match_lane_dots(problem):
    from poisson_ellipse_tpu.batch.batched_pcg import lane_dots
    from poisson_ellipse_tpu.ops.pallas_kernels import (
        apply_a_dots_batched_pallas,
    )

    a, b, rhs = assembly.assemble(problem, jnp.float32)
    key = jax.random.PRNGKey(1)
    w = jax.random.normal(key, (2,) + rhs.shape, jnp.float32)
    w = w.at[:, 0].set(0).at[:, -1].set(0)
    w = w.at[:, :, 0].set(0).at[:, :, -1].set(0)
    pairs = ((w, w), (w, -w))
    out, sums = apply_a_dots_batched_pallas(
        w, a, b, problem.h1, problem.h2, pairs, interpret=True
    )
    ref = lane_dots(*pairs)
    np.testing.assert_allclose(
        np.asarray(sums), np.asarray(ref), rtol=1e-5
    )
    assert out.shape == (2,) + rhs.shape


def test_batched_engines_accept_pallas_stencil(problem):
    a, b, rhs = batched_operands(problem, 2, jnp.float32)
    for fn in (pcg_batched, pcg_batched_pipelined):
        res = jax.jit(
            lambda a, b, r, fn=fn: fn(problem, a, b, r, stencil="pallas",
                                      interpret=True)
        )(a, b, rhs)
        assert bool(jnp.all(res.converged))
        assert all(abs(int(i) - 50) <= 2 for i in res.iters)


# -- harness / registry plumbing ---------------------------------------------


def test_cli_lanes_auto_resolves_to_batched(capsys):
    import json

    from poisson_ellipse_tpu.harness.__main__ import main

    rc = main(["10", "10", "--lanes", "2", "--json"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["engine"] == "batched"
    assert rec["lanes"] == 2
    assert rec["solves_per_sec"] > 0
    assert rec["quarantined"] == 0


def test_cli_warmup_subcommand(capsys):
    import json

    from poisson_ellipse_tpu.harness.__main__ import main

    rc = main([
        "warmup", "--grids", "10x10", "--lanes", "1", "--engine",
        "batched", "--no-persistent", "--json",
    ])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["warmed"][0]["bucket"] == [12, 12]


def test_lanes_reject_non_batched_engines(problem):
    from poisson_ellipse_tpu.harness.run import run_once

    with pytest.raises(ValueError, match="one solve per dispatch"):
        run_once(problem, mode="single", engine="xla", lanes=4)
    with pytest.raises(ValueError, match="one solve per dispatch"):
        build_solver(problem, "pipelined", jnp.float32, lanes=2)
    with pytest.raises(ValueError, match="native"):
        run_once(problem, mode="native", lanes=2)
    with pytest.raises(ValueError, match="checkpoint"):
        run_once(problem, lanes=2, checkpoint_dir="/tmp/nope")


def test_lanes_with_chained_timing_protocol(problem):
    # --lanes (real batching) composes with --batch (the chained timing
    # protocol): the marginal-cost measurement runs over the batched
    # solver without perturbing its per-lane results
    from poisson_ellipse_tpu.harness.run import run_once

    report = run_once(
        problem, mode="single", engine="batched", lanes=2, repeat=1,
        batch=2,
    )
    assert report.converged and report.iters == 50
    assert report.lanes == 2 and report.solves_per_sec > 0


def test_guarded_lanes_run(problem):
    from poisson_ellipse_tpu.harness.run import run_once

    report = run_once(problem, mode="single", engine="batched", lanes=2,
                      guard=True)
    assert report.converged
    assert report.recoveries == []
    assert report.lanes == 2


def test_guard_ladder_rejects_batched_with_pointer(problem):
    from poisson_ellipse_tpu.resilience.guard import guarded_solve

    with pytest.raises(ValueError, match="lane "):
        guarded_solve(problem, "batched", jnp.float32)


def test_sharded_mode_lanes_through_run_once(problem):
    from poisson_ellipse_tpu.harness.run import run_once

    report = run_once(
        problem, mode="sharded", mesh_shape=(1, 2), engine="batched",
        lanes=4,
    )
    assert report.converged and report.iters == 50
    assert report.lanes == 4 and report.solves_per_sec > 0
