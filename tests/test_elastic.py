"""Mesh-level fault tolerance: ABFT SDC detection + elastic recovery.

Three contracts under test:

1. **ABFT is free and honest** — the silent-corruption checks add ZERO
   collectives (psum/ppermute per iteration identical checks-on vs
   checks-off, pinned from the jaxpr via ``obs.static_cost``) and never
   fire on a healthy solve, which still converges at oracle parity.
2. **The SDC matrix** — injected corruption (halo bit-flip, sign-flipped
   psum, NaN) × sharded engines {classical, pipelined, mg-pcg} is either
   detected-and-recovered to oracle iteration parity (±2) at analytic-
   solution accuracy, or raises the classified
   ``SilentCorruptionError`` — never a silently wrong solution.
3. **Elastic degraded-mesh recovery** — simulated device loss and
   straggler deadlines mid-solve shrink the mesh, re-shard the last
   durable checkpoint, and resume to the same l2-vs-analytic as an
   uninterrupted run (``resilience.meshguard`` + ``parallel.elastic``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.analysis.contracts import assert_contract
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.parallel import elastic
from poisson_ellipse_tpu.parallel.mesh import AXIS_X, AXIS_Y
from poisson_ellipse_tpu.parallel.pcg_sharded import (
    build_sharded_stepper,
    sharded_result_of,
    solve_sharded,
)
from poisson_ellipse_tpu.resilience import (
    DeviceLossError,
    FaultPlan,
    SilentCorruptionError,
    device_loss,
    elastic_solve,
    guarded_solve,
    halo_bitflip,
    inject_nan,
    psum_corrupt,
    straggler,
)
from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic

PROBLEM = Problem(M=40, N=40)
ORACLE = 50  # the 40x40 weighted-norm reference oracle


def _mesh(n: int, px: int, py: int):
    return jax.sharding.Mesh(
        np.asarray(jax.devices()[:n]).reshape(px, py), (AXIS_X, AXIS_Y)
    )


@pytest.fixture(scope="module")
def mesh22():
    return _mesh(4, 2, 2)


@pytest.fixture(scope="module")
def clean(mesh22):
    return solve_sharded(PROBLEM, mesh22, dtype=jnp.float64)


# -- 1. the zero-cost / healthy-path contract --------------------------------


def test_abft_adds_zero_collectives_classical():
    # the declared contract (abft-identity derives its expectations from
    # ENGINE_CAPS); the exact classical cadence is re-pinned on `actual`
    r = assert_contract(
        "abft-identity", "xla", problem=PROBLEM, dtype=jnp.float64,
        mesh_shape=(2, 2),
    )
    assert r.actual == {"off": (2, 4), "on": (2, 4)}, r.actual


def test_abft_adds_zero_collectives_pipelined():
    # the pipelined iteration's ONE stacked psum (+ the replacement
    # branch's halo traffic counted in the body) must not grow
    r = assert_contract(
        "abft-identity", "pipelined", problem=PROBLEM, dtype=jnp.float64,
        mesh_shape=(2, 2),
    )
    assert r.actual["on"][0] == 1, r.actual


def test_abft_adds_zero_collectives_mg():
    assert_contract(
        "abft-identity", "mg-pcg", problem=PROBLEM, dtype=jnp.float64,
        mesh_shape=(2, 2),
    )


def test_abft_healthy_path_is_silent_and_at_parity(mesh22, clean):
    init_fn, advance_fn = build_sharded_stepper(
        PROBLEM, mesh22, jnp.float64, abft=True
    )
    state = init_fn()
    limit = 0
    while not (bool(state[6]) or bool(state[7])) and limit < 1000:
        limit += 13
        state = advance_fn(state, limit)
    assert not bool(state[11]), "ABFT flagged a healthy solve"
    res = sharded_result_of(PROBLEM, state)
    assert bool(res.converged) and int(res.iters) == int(clean.iters)
    np.testing.assert_allclose(
        np.asarray(res.w), np.asarray(clean.w), rtol=1e-12, atol=1e-16
    )


# -- 2. the SDC matrix -------------------------------------------------------
#
# One adapter per engine, built ONCE (the builds — a V-cycle trace per
# chunk stepper for mg-pcg — dominate wall clock; guarded_solve is a
# thin wrapper over _run_chunked + _make_adapter, and reusing the
# adapter across cells exercises exactly the same guard logic).

ENGINE_FAULT_AT = {"xla": 13, "pipelined": 13, "mg-pcg": 4}
SDC_EVENTS = {"sdc-rollback", "residual-restart"}


@pytest.fixture(scope="module")
def adapters(mesh22):
    from poisson_ellipse_tpu.resilience.guard import _make_adapter

    return {
        engine: _make_adapter(
            PROBLEM, engine, jnp.float64, mesh22, None, abft=True
        )
        for engine in ("xla", "pipelined", "mg-pcg")
    }


def _run_guarded(adapter, engine, plan=None, max_recoveries=3):
    import time

    from poisson_ellipse_tpu.resilience.guard import _run_chunked

    return _run_chunked(
        PROBLEM, adapter, chunk=ENGINE_FAULT_AT[engine],
        max_recoveries=max_recoveries, timeout=None, t0=time.monotonic(),
        plan=plan if plan is not None else FaultPlan(), events=[],
    )


@pytest.fixture(scope="module")
def clean_guarded(adapters):
    """The healthy-path reference per engine — and the healthy-path
    assertion itself: the ABFT checks must never fire on a clean solve,
    which converges at its engine's oracle (mg-pcg's V-cycle cuts the
    count; the diagonal engines hit the reference 50±2)."""
    out = {}
    for engine, adapter in adapters.items():
        g = _run_guarded(adapter, engine)
        assert not g.recoveries, (
            f"ABFT flagged a healthy {engine} solve: {g.recoveries}"
        )
        assert bool(g.result.converged)
        if engine != "mg-pcg":
            assert abs(int(g.result.iters) - ORACLE) <= 2
        out[engine] = g
    return out


@pytest.mark.parametrize("engine", ["xla", "pipelined", "mg-pcg"])
@pytest.mark.parametrize("fault", ["halo_bitflip", "psum_corrupt", "nan"])
def test_sdc_matrix_detects_and_recovers_to_parity(
    adapters, clean_guarded, engine, fault
):
    at = ENGINE_FAULT_AT[engine]
    plan = {
        "halo_bitflip": lambda: FaultPlan(halo_bitflip(at, field="p")),
        "psum_corrupt": lambda: FaultPlan(psum_corrupt(at)),
        "nan": lambda: FaultPlan(inject_nan(at, "r")),
    }[fault]()
    guarded = _run_guarded(adapters[engine], engine, plan)
    # detected (never silent): at least one recovery event, of the
    # classified kinds — pure SDC rolls back, NaN walks the restart rung
    kinds = {e.kind for e in guarded.recoveries}
    assert kinds and kinds <= SDC_EVENTS, kinds
    if fault in ("halo_bitflip", "psum_corrupt"):
        assert "sdc-rollback" in kinds
    # recovered: converged at oracle parity and analytic accuracy
    clean_g = clean_guarded[engine]
    assert bool(guarded.result.converged)
    assert abs(int(guarded.result.iters) - int(clean_g.result.iters)) <= 2
    l2 = float(l2_error_vs_analytic(PROBLEM, guarded.result.w))
    l2_clean = float(l2_error_vs_analytic(PROBLEM, clean_g.result.w))
    assert l2 <= l2_clean * 1.01 + 1e-12


@pytest.mark.parametrize("engine", ["xla", "pipelined", "mg-pcg"])
def test_persistent_corruption_raises_classified_sdc(adapters, engine):
    at = ENGINE_FAULT_AT[engine]
    with pytest.raises(SilentCorruptionError) as exc:
        _run_guarded(
            adapters[engine], engine,
            FaultPlan(halo_bitflip(at, field="p", persistent=True)),
        )
    assert exc.value.exit_code == 6
    assert exc.value.classification == "sdc"


def test_guarded_solve_entrypoint_routes_abft_and_traces(mesh22, tmp_path):
    """The public wrapper end-to-end once (the matrix above drives the
    core directly to amortize adapter builds), with the emitted
    ``recovery:sdc-rollback`` event schema-validated."""
    path = tmp_path / "sdc.jsonl"
    obs_trace.start(str(path))
    try:
        g = guarded_solve(
            PROBLEM, "xla", jnp.float64, mesh=mesh22, chunk=13, abft=True,
            faults=FaultPlan(psum_corrupt(13)),
        )
    finally:
        obs_trace.stop()
    assert [e.kind for e in g.recoveries] == ["sdc-rollback"]
    assert bool(g.result.converged)
    assert obs_trace.validate_file(str(path)) == []
    names = {r["name"] for r in obs_trace.read_jsonl(str(path))}
    assert "recovery:sdc-rollback" in names


def test_abft_refused_off_mesh():
    with pytest.raises(ValueError, match="sharded"):
        guarded_solve(PROBLEM, "xla", jnp.float64, abft=True)


# -- faultinject primitives --------------------------------------------------


def test_bitflip_is_deterministic_and_single_element():
    from poisson_ellipse_tpu.resilience.faultinject import _corrupt

    fields = {"w": 1, "r": 2, "p": 3, "zr": 4}
    arr = jnp.ones((8, 8), jnp.float64)
    state = (jnp.asarray(0), arr, arr, arr, jnp.asarray(1.0), 0, 0, 0)
    f = halo_bitflip(0, field="r", shard=1, shards=2)
    out1 = _corrupt(state, f, fields, 7, 4)
    f2 = halo_bitflip(0, field="r", shard=1, shards=2)
    out2 = _corrupt(state, f2, fields, 7, 4)
    np.testing.assert_array_equal(np.asarray(out1[2]), np.asarray(out2[2]))
    changed = np.asarray(out1[2]) != np.asarray(state[2])
    assert changed.sum() == 1 and changed[4, 4]


def test_psum_corrupt_is_a_sign_flip():
    from poisson_ellipse_tpu.resilience.faultinject import _corrupt

    fields = {"w": 1, "r": 2, "p": 3, "zr": 4}
    state = (0, 0, 0, 0, jnp.asarray(2.5, jnp.float64), 0, 0, 0)
    out = _corrupt(state, psum_corrupt(0), fields, 7, 4)
    assert float(out[4]) == -2.5


def test_dispatch_fault_helpers_validate():
    with pytest.raises(ValueError, match="shard"):
        halo_bitflip(0, shard=3, shards=2)
    with pytest.raises(ValueError, match="delay"):
        straggler(-1.0)
    assert device_loss(chunk=5, device=2).at_iter == 5


# -- 3. elastic mesh surgery + the meshguard ---------------------------------


def test_shrink_mesh_factorization_and_floor(mesh22):
    small = elastic.shrink_mesh(mesh22, [jax.devices()[3].id])
    assert small.devices.size == 3
    smaller = elastic.shrink_mesh(
        mesh22, [d.id for d in jax.devices()[2:4]]
    )
    assert (smaller.shape[AXIS_X], smaller.shape[AXIS_Y]) == (1, 2)
    with pytest.raises(DeviceLossError):
        elastic.shrink_mesh(mesh22, [d.id for d in jax.devices()[:4]])


def test_reshard_state_round_trips_between_meshes(mesh22):
    init_fn, advance_fn = build_sharded_stepper(PROBLEM, mesh22, jnp.float64)
    state = advance_fn(init_fn(), 16)
    small = _mesh(2, 1, 2)
    moved = elastic.reshard_state(PROBLEM, state, small, jnp.float64)
    # resuming on the new mesh reaches the same solve (ulp-scale psum
    # regrouping only)
    init2, advance2 = build_sharded_stepper(PROBLEM, small, jnp.float64)
    done = advance2(moved, PROBLEM.max_iterations)
    res = sharded_result_of(PROBLEM, done)
    straight = solve_sharded(PROBLEM, small, dtype=jnp.float64)
    assert int(res.iters) == int(straight.iters)
    np.testing.assert_allclose(
        np.asarray(res.w), np.asarray(straight.w), rtol=1e-11, atol=1e-14
    )


def test_reshard_state_rejects_extended_carries(mesh22):
    init_fn, _adv = build_sharded_stepper(
        PROBLEM, mesh22, jnp.float64, abft=True
    )
    with pytest.raises(ValueError, match="8-field"):
        elastic.reshard_state(PROBLEM, init_fn(), mesh22, jnp.float64)


def test_meshguard_device_loss_recovers_on_degraded_mesh(
    mesh22, clean, tmp_path
):
    """The acceptance pin: simulated device loss mid-solve on 2×2
    recovers (through the last durable checkpoint) down to 1×2 and
    reaches the same l2-vs-analytic as the uninterrupted run — with
    schema-valid ``degrade:mesh`` events on the trace."""
    path = tmp_path / "mesh.jsonl"
    obs_trace.start(str(path))
    try:
        r = elastic_solve(
            PROBLEM, mesh22, jnp.float64, directory=str(tmp_path / "ck"),
            chunk=8,
            faults=FaultPlan(
                device_loss(16, device=jax.devices()[3].id),
                device_loss(16, device=jax.devices()[2].id),
            ),
            max_degrades=2,
        )
    finally:
        obs_trace.stop()
    assert r.mesh_shape == (1, 2) and r.degrades == 2
    assert bool(r.result.converged)
    assert int(r.result.iters) == int(clean.iters)
    l2 = float(l2_error_vs_analytic(PROBLEM, r.result.w))
    l2_clean = float(l2_error_vs_analytic(PROBLEM, clean.w))
    assert l2 <= l2_clean * 1.01 + 1e-12
    assert [e.kind for e in r.events] == ["degrade:mesh", "degrade:mesh"]
    assert obs_trace.validate_file(str(path)) == []
    degrade = [
        rec for rec in obs_trace.read_jsonl(str(path))
        if rec["name"] == "degrade:mesh"
    ]
    assert len(degrade) == 2
    assert degrade[0]["fields"]["from_mesh"] == [2, 2]
    assert degrade[-1]["fields"]["to_mesh"] == [1, 2]


def test_meshguard_straggler_deadline_degrades(mesh22, clean, tmp_path):
    r = elastic_solve(
        PROBLEM, mesh22, jnp.float64, directory=str(tmp_path / "ck"),
        chunk=8, chunk_deadline_s=0.9,
        faults=FaultPlan(
            straggler(2.0, at_iter=16, device=jax.devices()[1].id)
        ),
    )
    assert r.degrades == 1
    assert r.events[0].cause == "straggler-deadline"
    assert bool(r.result.converged)
    assert int(r.result.iters) == int(clean.iters)


def test_meshguard_degrade_budget_raises_classified(mesh22, tmp_path):
    with pytest.raises(DeviceLossError) as exc:
        elastic_solve(
            PROBLEM, mesh22, jnp.float64, directory=str(tmp_path / "ck"),
            chunk=8, max_degrades=0,
            faults=FaultPlan(device_loss(8, device=jax.devices()[0].id)),
        )
    assert exc.value.exit_code == 7


def test_meshguard_abft_sdc_reloads_checkpoint(mesh22, clean, tmp_path):
    r = elastic_solve(
        PROBLEM, mesh22, jnp.float64, directory=str(tmp_path / "ck"),
        chunk=8, abft=True,
        faults=FaultPlan(halo_bitflip(16, field="p")),
    )
    assert r.degrades == 0
    assert [e.kind for e in r.events] == ["sdc-rollback"]
    assert bool(r.result.converged)
    assert int(r.result.iters) == int(clean.iters)


# ----------------- the s-step sharded cells + the bf16 drift alarm


SSTEP_FAULT_AT = 12  # a block boundary (s=4): faults land exactly


@pytest.fixture(scope="module")
def sstep_adapter(mesh22):
    from poisson_ellipse_tpu.resilience.guard import _make_adapter

    return _make_adapter(
        PROBLEM, "sstep", jnp.float64, mesh22, None, abft=True
    )


def _run_sstep(adapter, plan=None, max_recoveries=3):
    import time

    from poisson_ellipse_tpu.resilience.guard import _run_chunked

    return _run_chunked(
        PROBLEM, adapter, chunk=SSTEP_FAULT_AT,
        max_recoveries=max_recoveries, timeout=None, t0=time.monotonic(),
        plan=plan if plan is not None else FaultPlan(), events=[],
    )


@pytest.fixture(scope="module")
def sstep_clean(sstep_adapter):
    g = _run_sstep(sstep_adapter)
    assert not g.recoveries, g.recoveries  # ABFT silent on health
    assert bool(g.result.converged)
    assert abs(int(g.result.iters) - ORACLE) <= 2
    return g


@pytest.mark.parametrize("fault", [
    "halo_bitflip_p", "halo_bitflip_r", "nan", "breakdown", "psum_corrupt",
])
def test_sstep_sdc_matrix_recovers_or_classifies(
    sstep_adapter, sstep_clean, fault
):
    """{nan, breakdown, halo_bitflip, psum_corrupt} × sstep: every cell
    recovers to oracle-iteration parity (detected via the block-level
    shadow recurrences → sdc-rollback; NaN/breakdown via the health
    word → restart) or is structurally absorbed — psum_corrupt lands on
    the carried zr scalar, which the s-step block RE-DERIVES from the
    Gram diagonal, so that corruption cannot touch the iterate at all
    (absorbed at exact parity, zero events — the re-derivation defense;
    Gram-diagonal positivity still catches a sign-flipped reduction
    inside the block). The r-flip rides the detection model: its
    single-element drift sits against the dtype-scaled rtol, so it is
    flagged or numerically absorbed — either way the final result must
    converge at clean accuracy, never a silent wrong answer."""
    from poisson_ellipse_tpu.resilience import force_breakdown

    at = SSTEP_FAULT_AT
    plan = {
        "halo_bitflip_p": lambda: FaultPlan(halo_bitflip(at, field="p")),
        "halo_bitflip_r": lambda: FaultPlan(halo_bitflip(at, field="r")),
        "psum_corrupt": lambda: FaultPlan(psum_corrupt(at)),
        "nan": lambda: FaultPlan(inject_nan(at, "r")),
        "breakdown": lambda: FaultPlan(force_breakdown(at)),
    }[fault]()
    guarded = _run_sstep(sstep_adapter, plan)
    kinds = {e.kind for e in guarded.recoveries}
    assert kinds <= SDC_EVENTS, kinds
    if fault == "halo_bitflip_p":
        assert "sdc-rollback" in kinds  # the shadow Σp prediction fired
    if fault in ("nan", "breakdown"):
        assert "residual-restart" in kinds
    if fault == "psum_corrupt":
        assert not kinds  # structurally absorbed by re-derivation
    assert bool(guarded.result.converged)
    assert abs(
        int(guarded.result.iters) - int(sstep_clean.result.iters)
    ) <= 2 + (4 if fault == "halo_bitflip_r" else 0)
    l2 = float(l2_error_vs_analytic(PROBLEM, guarded.result.w))
    l2_clean = float(l2_error_vs_analytic(PROBLEM, sstep_clean.result.w))
    assert l2 <= l2_clean * 1.01 + 1e-12


def test_sstep_persistent_corruption_raises_classified_sdc(sstep_adapter):
    with pytest.raises(SilentCorruptionError) as exc:
        _run_sstep(
            sstep_adapter,
            FaultPlan(halo_bitflip(
                SSTEP_FAULT_AT, field="p", persistent=True
            )),
        )
    assert exc.value.exit_code == 6


def test_abft_drift_alarm_is_dtype_scaled():
    """The low-precision drift alarm (the PR 9 shadow recurrences with
    the dtype-scaled rtol): the SAME injected perturbation that the f32
    path FLAGS (its drift clears the f32 band) is numerically absorbed
    by the bf16-storage path — whose band sits above its own storage-
    rounding noise, so the bf16 run reaches its floor with NO false
    alarm — while a storage-scale corruption (a top-exponent flip, far
    above bf16's band) still fires even there. One alarm, three
    regimes, all keyed on ``ops.precision.effective_dtype``."""
    from poisson_ellipse_tpu.ops.precision import effective_dtype
    from poisson_ellipse_tpu.parallel.sstep_sharded import (
        build_sstep_sharded_stepper,
    )
    from poisson_ellipse_tpu.resilience.abft import abft_rtol

    # the rtol scaling fact itself
    assert abft_rtol(jnp.bfloat16) > abft_rtol(jnp.float32) > abft_rtol(
        jnp.float64
    )
    assert effective_dtype(jnp.float32, "bf16") == jnp.dtype(jnp.bfloat16)
    mesh = _mesh(2, 1, 2)
    fields = {"w": 1, "r": 2, "p": 3, "zr": 4}

    def run_cell(storage, bit):
        init, adv = build_sstep_sharded_stepper(
            PROBLEM, mesh, jnp.float32, s=4, abft=True,
            storage_dtype=storage,
        )
        st = adv(init(), 16)
        plan = FaultPlan(halo_bitflip(16, field="p", bit=bit))
        st = plan.apply(16, st, fields, 7, 4)
        return adv(st, PROBLEM.max_iterations)

    # f32 path: the default-magnitude flip clears the f32 band → flagged
    out = run_cell(None, None)
    assert bool(out[11])
    # bf16-storage path: the SAME flip sits inside the bf16 band (which
    # must tolerate bf16 storage rounding) → absorbed; the run reaches
    # its floor with no alarm and a finite iterate
    out = run_cell("bf16", None)
    assert not bool(out[11])
    assert bool(jnp.all(jnp.isfinite(out[1].astype(jnp.float32))))
    # ... and the absorbed flip is ABSORBED, not laundered: the run
    # still reaches the storage floor (the detection model's honest
    # boundary — below the band, CG's own self-correction plus the
    # replacement discipline wash the perturbation out, and the guard's
    # final true-residual gate validates whatever is returned)
    assert float(out[5]) < 1e-3


def test_sstep_healthy_bf16_storage_no_false_alarm():
    """bf16 storage + ABFT, healthy: the tightened replacement cadence
    and the restart-aware Σp check keep the alarm silent all the way to
    the storage floor (the false-fire this test pins against was
    measured and fixed during development)."""
    from poisson_ellipse_tpu.parallel.sstep_sharded import (
        build_sstep_sharded_stepper,
    )

    mesh = _mesh(2, 1, 2)
    init, adv = build_sstep_sharded_stepper(
        PROBLEM, mesh, jnp.float32, s=4, abft=True, storage_dtype="bf16"
    )
    out = adv(init(), PROBLEM.max_iterations)
    assert not bool(out[11])
    assert float(out[5]) < 1e-3  # reached the storage floor
