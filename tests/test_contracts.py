"""The engine-contract checker gate (analysis/): matrix clean + every
contract kind fires.

Three layers of pinning:

1. The full ENGINE_CAPS-derived matrix runs clean, UNSUPPRESSED — a
   ``[tool.engine_contracts]`` suppression can quiet the CLI but never
   hide a contract regression from tier-1.
2. A golden snapshot of the reduced report (engine, axis, kind, status,
   expected) — adding an engine, declaring a new contract, or changing a
   derived budget must show up as a reviewed diff of
   ``tests/golden_contract_matrix.json``. Deliberate drift: regenerate
   with the snippet in that file's sibling test below.
3. Injected-violation fixtures: every contract kind must FIRE when fed
   a wrong expectation or a tampered trace — a checker that cannot fail
   is not a check.

The snapshot deliberately excludes ``actual`` values that the contracts
leave unpinned (e.g. the pipelined body's replacement-branch ppermutes),
so it ratchets exactly what the contracts pin and nothing more.
"""

from __future__ import annotations

import json
import os

import jax.numpy as jnp
import pytest

from poisson_ellipse_tpu.analysis import contracts, jaxpr_scan, matrix
from poisson_ellipse_tpu.analysis.contracts import (
    CONTRACT_KINDS,
    assert_contract,
    check_contract,
    check_engine_metadata,
    engine_contract_spec,
)
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.solver.engine import ENGINE_CAPS

GOLDEN = os.path.join(
    os.path.dirname(__file__), "golden_contract_matrix.json"
)


@pytest.fixture(scope="module")
def full_report():
    # suppressions={} — the tier-1 gate always runs unsuppressed
    return matrix.run_matrix(suppressions={})


# -- 1. the full matrix, clean -----------------------------------------------


def test_full_contract_matrix_is_clean(full_report):
    assert full_report["clean"], "\n".join(full_report["violations"])
    s = full_report["summary"]
    assert s["fail"] == 0 and s["error"] == 0 and s["suppressed"] == 0
    assert matrix.exit_code(full_report) == 0


def test_matrix_covers_every_engine_and_kind(full_report):
    """Coverage, not just cleanliness: every registered engine holds at
    least one cell, and every contract kind runs somewhere — an engine
    or kind silently dropping out of the sweep is itself a failure."""
    cells = full_report["cells"]
    swept_engines = {r["engine"] for r in cells} - {"*"}
    assert swept_engines == set(ENGINE_CAPS)
    swept_kinds = {r["kind"] for r in cells}
    assert swept_kinds == set(CONTRACT_KINDS)


def test_contract_report_matches_golden_snapshot(full_report):
    """Regenerate (after a REVIEWED contract change) with::

        python -m poisson_ellipse_tpu.analysis --format json \\
            --no-suppressions -o /tmp/report.json
        python - <<'PY'
        import json
        rep = json.load(open("/tmp/report.json"))
        reduced = sorted(({k: r[k] for k in
            ("engine", "axis", "kind", "status", "expected")}
            for r in rep["cells"]),
            key=lambda r: (r["engine"], r["axis"], r["kind"]))
        with open("tests/golden_contract_matrix.json", "w") as f:
            json.dump(reduced, f, indent=2, sort_keys=True); f.write("\\n")
        PY
    """
    reduced = sorted(
        (
            {
                k: r[k]
                for k in ("engine", "axis", "kind", "status", "expected")
            }
            for r in full_report["cells"]
        ),
        key=lambda r: (r["engine"], r["axis"], r["kind"]),
    )
    with open(GOLDEN, encoding="utf-8") as f:
        golden = json.load(f)
    assert reduced == golden, (
        "the contract matrix drifted from tests/golden_contract_matrix"
        ".json — if the change is deliberate, regenerate per the "
        "docstring"
    )


def test_report_hash_is_deterministic(full_report):
    h = matrix.report_hash(full_report)
    assert h == matrix.report_hash(json.loads(json.dumps(full_report)))
    mutated = json.loads(json.dumps(full_report))
    mutated["cells"][0]["status"] = "fail"
    assert matrix.report_hash(mutated) != h


# -- 2. classification, suppression, ratchet ---------------------------------


def _force_fail(monkeypatch, kind="guard-overhead"):
    def fake(k, engine, **kw):
        return contracts.ContractResult(
            kind=k, engine=engine, status="fail",
            expected={"identical": True}, actual={"identical": False},
            violations=(contracts.Violation(k, engine, "injected"),),
        )

    monkeypatch.setattr(contracts, "check_contract", fake)
    return "xla:guarded:" + kind


def test_matrix_exit_1_on_violation_and_0_when_suppressed(monkeypatch):
    cid = _force_fail(monkeypatch)
    rep = matrix.run_matrix(("xla",), ("guarded",), suppressions={})
    assert not rep["clean"] and matrix.exit_code(rep) == 1
    assert any(m.endswith("injected") for m in rep["violations"])

    rep2 = matrix.run_matrix(
        ("xla",), ("guarded",), suppressions={cid: "known drift, #123"}
    )
    row = [r for r in rep2["cells"] if r["kind"] == "guard-overhead"][0]
    assert row["status"] == "suppressed"
    assert row["suppressed_reason"] == "known drift, #123"
    assert rep2["clean"] and matrix.exit_code(rep2) == 0
    assert rep2["unused_suppressions"] == []
    # the render names the suppressed cell with its reason
    assert "known drift, #123" in matrix.render_report(rep2)


def test_matrix_reports_unused_suppressions(monkeypatch):
    _force_fail(monkeypatch)
    rep = matrix.run_matrix(
        ("xla",), ("guarded",), suppressions={"stale:cell:kind": "gone"}
    )
    assert rep["unused_suppressions"] == ["stale:cell:kind"]
    assert "unused suppression: stale:cell:kind" in matrix.render_report(rep)


def test_matrix_classifies_checker_crash_as_exit_2(monkeypatch):
    def boom(kind, engine, **kw):
        raise RuntimeError("tracer exploded")

    monkeypatch.setattr(contracts, "check_contract", boom)
    rep = matrix.run_matrix(("xla",), ("guarded",), suppressions={})
    rows = [r for r in rep["cells"] if r["axis"] != "registry"]
    assert rows and all(r["status"] == "error" for r in rows)
    assert "RuntimeError: tracer exploded" in rows[0]["messages"][0]
    assert matrix.exit_code(rep) == 2  # error trumps fail


def test_load_suppressions_parses_reasons_and_rejects_garbage(tmp_path):
    (tmp_path / "pyproject.toml").write_text(
        "[tool.engine_contracts]\n"
        'suppress = ["xla:sharded:collective-cadence: tracked in #7",'
        ' "fmg:sharded:fcycle-budget"]\n',
        encoding="utf-8",
    )
    sup = matrix.load_suppressions(str(tmp_path))
    assert sup == {
        "xla:sharded:collective-cadence": "tracked in #7",
        "fmg:sharded:fcycle-budget": "(no reason given)",
    }
    (tmp_path / "pyproject.toml").write_text(
        '[tool.engine_contracts]\nsuppress = ["not a cell id"]\n',
        encoding="utf-8",
    )
    with pytest.raises(SystemExit, match="not a cell id"):
        matrix.load_suppressions(str(tmp_path))
    assert matrix.load_suppressions(str(tmp_path / "missing")) == {}


def test_repo_pyproject_suppressions_load_and_are_all_used(full_report):
    """The checked-in suppress list parses, and (ratchet) every entry
    still matches a failing cell — with a clean matrix that means the
    list must be empty."""
    sup = matrix.load_suppressions()
    failing = {
        matrix.cell_id(r["engine"], r["axis"], r["kind"])
        for r in full_report["cells"]
        if r["status"] == "fail"
    }
    stale = set(sup) - failing
    assert not stale, f"stale [tool.engine_contracts] entries: {stale}"


# -- 3. every contract kind fires on an injected violation -------------------


def test_engine_metadata_fires_on_undeclared_engine():
    caps = {"good": {"contracts": {}}, "bad": {"family": "loop"}}
    v = check_engine_metadata(caps)
    assert [x.engine for x in v] == ["bad"]
    assert "without contract metadata" in v[0].message
    assert v[0].render().startswith("bad: engine-metadata:")


def test_engine_metadata_fires_on_unknown_key():
    caps = {"typo": {"contracts": {"sharded_psums": 2}}}
    v = check_engine_metadata(caps)
    assert len(v) == 1 and "unknown contract key" in v[0].message
    with pytest.raises(ValueError, match="sharded_psums"):
        engine_contract_spec("typo", caps)


def test_single_collective_free_fires_on_collective_trace(monkeypatch):
    # feed the sharded build (which legitimately holds collectives)
    # through the single-chip check: the contract must fire
    monkeypatch.setattr(
        contracts,
        "_build_single",
        lambda problem, engine, dtype, **kw: contracts._build_sharded(
            problem, "xla", dtype, (1, 2)
        ),
    )
    r = check_contract("single-collective-free", "xla")
    assert r.status == "fail"
    assert "holds collectives" in r.violations[0].message


def test_collective_cadence_fires_on_wrong_expectation():
    r = check_contract("collective-cadence", "xla", expect=(99, 0))
    assert r.status == "fail" and len(r.violations) == 2
    assert r.actual == {"psum": 2, "ppermute": 4}
    with pytest.raises(AssertionError, match="99"):
        assert_contract("collective-cadence", "xla", expect=(99, 0))


def test_batched_cadence_fires_on_wrong_expectation():
    r = check_contract("batched-cadence", "batched", expect=(99, 4))
    assert r.status == "fail" and len(r.violations) == 2
    assert r.actual == {"psum": 1, "ppermute": 0}


def test_abft_identity_fires_on_wrong_declared_psum():
    spec = dict(engine_contract_spec("xla"))
    spec["sharded_psum"] = 99
    r = contracts._check_abft_identity(
        "xla", spec, Problem(M=16, N=16), jnp.float32, mesh_shape=(1, 2)
    )
    assert r.status == "fail"
    assert "contract says 99" in r.violations[0].message


def _tamper_every_second_trace(monkeypatch, extra="\n# tampered"):
    real = jaxpr_scan.trace_text
    calls = {"n": 0}

    def tampered(fn, args):
        calls["n"] += 1
        text = real(fn, args)
        return text + extra if calls["n"] % 2 == 0 else text

    monkeypatch.setattr(jaxpr_scan, "trace_text", tampered)


def test_guard_overhead_fires_on_divergent_trace(monkeypatch):
    _tamper_every_second_trace(monkeypatch)
    r = check_contract("guard-overhead", "xla")
    assert r.status == "fail"
    assert "zero-overhead-when-healthy" in r.violations[0].message


def test_storage_identity_fires_on_divergent_trace(monkeypatch):
    _tamper_every_second_trace(monkeypatch)
    r = check_contract("storage-identity", "xla")
    assert r.status == "fail"
    assert "free-when-off" in r.violations[0].message


def test_storage_narrow_fires_when_no_conversions_found(monkeypatch):
    monkeypatch.setattr(
        jaxpr_scan, "convert_dtype_pairs", lambda body: []
    )
    r = check_contract("storage-narrow", "xla")
    assert r.status == "fail" and len(r.violations) == 2
    assert r.actual == {"widens": False, "narrows": False}


def test_history_free_fires_on_divergent_trace(monkeypatch):
    _tamper_every_second_trace(monkeypatch)
    r = check_contract("history-free", "xla")
    assert r.status == "fail"
    assert "not free when off" in r.violations[0].message


def test_history_resident_fires_on_host_bound_trace(monkeypatch):
    monkeypatch.setattr(
        jaxpr_scan,
        "trace_text",
        lambda fn, args: "while ... callback ... device_get",
    )
    r = check_contract("history-resident", "xla")
    assert r.status == "fail"
    msgs = " ".join(v.message for v in r.violations)
    assert "dynamic_update_slice" in msgs and "device-resident" in msgs


def test_fcycle_budget_fires_on_missing_exchanges(monkeypatch):
    monkeypatch.setattr(
        jaxpr_scan, "count_primitives", lambda jaxpr, names: {"ppermute": 0}
    )
    r = check_contract("fcycle-budget", "fmg")
    assert r.status == "fail"
    assert "hidden exchange" in r.violations[0].message
    assert r.expected["ppermute_total"] > 0


def test_fleet_chaos_fires_on_poisoned_report_and_missing_rejoin():
    r = check_contract(
        "fleet-chaos", "xla",
        expect={"lost": ["chaos-0001"], "rejoins": 0},
    )
    assert r.status == "fail" and len(r.violations) == 2
    msgs = " ".join(v.message for v in r.violations)
    assert "broke its invariants" in msgs and "chaos-0001" in msgs
    assert "0 rejoin(s)" in msgs


def test_fleet_chaos_fires_on_insensitive_verdict(monkeypatch):
    # a verdict that ignored a survivability field must be named: probe
    # a field ok() does not fold over and the sensitivity prong fires
    monkeypatch.setitem(
        contracts._FLEET_INVARIANT_PROBES, "replayed", 99
    )
    r = check_contract("fleet-chaos", "xla")
    assert r.status == "fail"
    assert "ignores invariant field(s) replayed" in r.violations[0].message
    assert "replayed" in r.actual["insensitive"]


def test_check_contract_rejects_unknown_and_inapplicable():
    with pytest.raises(ValueError, match="unknown contract kind"):
        check_contract("no-such-contract", "xla")
    # fcycle-budget is fmg-only: running it elsewhere is a usage error,
    # not a silent pass
    with pytest.raises(ValueError, match="does not apply"):
        check_contract("fcycle-budget", "xla")


# -- 4. SARIF + CLI surface --------------------------------------------------


def test_report_to_sarif_carries_non_pass_cells():
    report = {
        "cells": [
            {"engine": "xla", "axis": "sharded",
             "kind": "collective-cadence", "status": "pass",
             "messages": []},
            {"engine": "xla", "axis": "guarded", "kind": "guard-overhead",
             "status": "fail", "messages": ["broke"]},
            {"engine": "fmg", "axis": "sharded", "kind": "fcycle-budget",
             "status": "suppressed", "messages": [],
             "suppressed_reason": "tracked"},
        ],
    }
    doc = matrix.report_to_sarif(report)
    results = doc["runs"][0]["results"]
    assert [r["level"] for r in results] == ["error", "note"]
    assert results[0]["ruleId"] == "guard-overhead"
    assert "xla:guarded:guard-overhead: broke" in (
        results[0]["message"]["text"]
    )
    rule_ids = {
        r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]
    }
    assert rule_ids == set(CONTRACT_KINDS)


def test_cli_list_contracts(capsys):
    from poisson_ellipse_tpu.analysis.__main__ import main

    assert main(["--list-contracts"]) == 0
    out = capsys.readouterr().out
    for kind in CONTRACT_KINDS:
        assert kind in out


def test_cli_restricted_run_json_sarif_and_hash(tmp_path, capsys):
    from poisson_ellipse_tpu.analysis.__main__ import main

    out_json = tmp_path / "report.json"
    rc = main(
        ["--engine", "xla", "--axis", "guarded", "--format", "json",
         "-o", str(out_json), "--hash"]
    )
    assert rc == 0
    rep = json.loads(out_json.read_text(encoding="utf-8"))
    assert rep["clean"] and rep["summary"]["fail"] == 0
    assert "report-hash: " in capsys.readouterr().out

    out_sarif = tmp_path / "report.sarif"
    rc = main(
        ["--engine", "xla", "--axis", "guarded", "--format", "sarif",
         "-o", str(out_sarif)]
    )
    assert rc == 0
    doc = json.loads(out_sarif.read_text(encoding="utf-8"))
    assert doc["version"] == "2.1.0"
    assert doc["runs"][0]["tool"]["driver"]["name"] == "engine-contracts"
    assert doc["runs"][0]["results"] == []  # clean run, no findings
