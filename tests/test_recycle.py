"""Krylov recycling + the semantic solve cache.

The correctness story under test is deliberately one-sided: the basis /
cache only ever *propose* an x0, and ``solver.pcg.init_state`` verifies
every proposal by TRUE residual — so recycling can cut iterations but
can never change what a solve converges to. The tests pin both halves:

- the mechanism works (capture → harvest → deflated restart cuts
  iterations at unchanged analytic l2, on the solver and through the
  harness surface);
- the mechanism is inert when off or wrong (recycle=None/x0=None trace
  the byte-identical jaxpr; a poisoned cache entry costs iterations,
  never correctness; replays run cold so journaled outcomes are
  bitwise-independent of cache state; chaos invariants hold with
  recycling on).
"""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.analysis import jaxpr_scan
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.stencil import apply_a
from poisson_ellipse_tpu.resilience.faultinject import (
    Fault,
    FaultPlan,
    poisoned_guess,
)
from poisson_ellipse_tpu.runtime.solvecache import (
    SolveCache,
    rhs_sketch,
    sketch_distance,
    solve_key,
)
from poisson_ellipse_tpu.serve import Scheduler, run_chaos
from poisson_ellipse_tpu.solver import recycle as rec
from poisson_ellipse_tpu.solver.pcg import pcg
from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic

# analytic-l2 parity band for warm vs cold solves of the same system:
# both sit on the same discretisation floor and stop on the same
# step-norm delta, so the residual wiggle is solver-tolerance-level
# (same stance as bench.bench_recycle's gate)
L2_REL_GAP = 0.10


@pytest.fixture(scope="module")
def capture64():
    """One ring-carrying capture solve + its harvested basis (64x64
    f32 — large enough that the ring respects the basis-quality rule,
    small enough for the tier-1 budget)."""
    problem = Problem(M=64, N=64)
    a, b, rhs = assembly.assemble(problem, jnp.float32)
    res, trace, ring = pcg(
        problem, a, b, rhs, history=True, recycle=rec.RECYCLE_CAP
    )
    basis = rec.harvest(problem, a, b, trace, ring)
    return problem, a, b, rhs, res, basis


# -- capture / harvest / deflated restart ------------------------------------


def test_capture_converges_and_harvest_yields_rank_k_basis(capture64):
    problem, a, b, rhs, res, basis = capture64
    assert bool(res.converged)
    assert basis is not None
    assert basis.rank == rec.RECYCLE_K
    assert basis.w.shape == (rec.RECYCLE_K, problem.M + 1, problem.N + 1)
    assert np.all(np.isfinite(basis.gram))
    # Ritz values come out ascending and positive (an SPD operator)
    assert np.all(basis.thetas > 0)


def test_deflated_restart_cuts_iterations_at_same_l2(capture64):
    problem, a, b, rhs, res, basis = capture64
    x0 = rec.deflated_x0(basis, rhs)
    assert x0 is not None
    warm = pcg(problem, a, b, rhs, x0=x0)
    assert bool(warm.converged)
    assert int(warm.iters) < int(res.iters)
    l2_cold = float(l2_error_vs_analytic(problem, res.w))
    l2_warm = float(l2_error_vs_analytic(problem, warm.w))
    assert abs(l2_warm - l2_cold) / l2_cold <= L2_REL_GAP


def test_semantic_hit_plus_deflation_on_correlated_rhs(capture64):
    """The bench_recycle per-request shape: a scaled rhs seeded with the
    UNSCALED previous solution (a related, not identical, cache hit) and
    deflated on top of its true residual."""
    problem, a, b, rhs, res, basis = capture64
    s = 1.03
    rhs_s = rhs * s
    h1 = jnp.asarray(problem.h1, rhs.dtype)
    h2 = jnp.asarray(problem.h2, rhs.dtype)
    r0 = rhs_s - apply_a(res.w, a, b, h1, h2)
    x0 = rec.deflated_x0(basis, rhs_s, x0=res.w, residual=r0)
    assert x0 is not None
    warm = pcg(problem, a, b, rhs_s, x0=x0)
    assert bool(warm.converged)
    # the ISSUE's headline: >= 2x on the correlated stream
    assert int(warm.iters) * 2 <= int(res.iters)
    l2_cold = float(l2_error_vs_analytic(problem, res.w))
    l2_warm = float(l2_error_vs_analytic(problem, warm.w / s))
    assert abs(l2_warm - l2_cold) / l2_cold <= L2_REL_GAP


def test_harvest_declines_short_trace_and_caller_runs_cold():
    problem = Problem(M=10, N=10, max_iter=4)
    a, b, rhs = assembly.assemble(problem, jnp.float32)
    res, trace, ring = pcg(problem, a, b, rhs, history=True, recycle=8)
    # k >= usable Lanczos steps: no deflated remainder, decline
    assert rec.harvest(problem, a, b, trace, ring, k=8) is None


def test_check_warm_start_drops_nonfinite_and_flags_poisoned(capture64):
    problem, a, b, rhs, res, basis = capture64
    bad = jnp.full_like(rhs, jnp.nan)
    kept, ratio = rec.check_warm_start(problem, a, b, rhs, bad)
    assert kept is None and not math.isfinite(ratio)
    poison = jnp.asarray(poisoned_guess(rhs.shape, np.float32))
    kept, ratio = rec.check_warm_start(problem, a, b, rhs, poison)
    # the poisoned seed is KEPT (true-residual init absorbs it) but its
    # ratio is unambiguously worse than cold — the bad-hit signal
    assert kept is not None
    assert ratio > rec.BAD_HIT_RATIO


def test_recycle_requires_history():
    problem = Problem(M=10, N=10)
    a, b, rhs = assembly.assemble(problem, jnp.float32)
    with pytest.raises(ValueError, match="history"):
        pcg(problem, a, b, rhs, recycle=8)


def test_recycle_off_and_x0_none_trace_byte_identical_jaxpr():
    problem = Problem(M=12, N=12)
    a, b, rhs = assembly.assemble(problem, jnp.float32)
    base = jaxpr_scan.trace_text(
        lambda *o: pcg(problem, *o), (a, b, rhs)
    )
    off = jaxpr_scan.trace_text(
        lambda *o: pcg(problem, *o, x0=None, recycle=None), (a, b, rhs)
    )
    assert base == off


def test_ring_model_bytes_is_cap_full_grids():
    problem = Problem(M=64, N=64)
    assert rec.ring_model_bytes(problem, cap=64, dtype=jnp.float32) == (
        64 * 65 * 65 * 4
    )


# -- the semantic solve cache ------------------------------------------------


def test_rhs_sketch_is_deterministic_and_ranks_relatedness():
    rng = np.random.default_rng(1)
    rhs = rng.normal(size=(33, 33))
    s1 = rhs_sketch(rhs)
    s2 = rhs_sketch(rhs.copy())
    assert np.array_equal(s1, s2)
    near = sketch_distance(s1, rhs_sketch(rhs * 1.02))
    far = sketch_distance(s1, rhs_sketch(rng.normal(size=(33, 33))))
    assert near < 0.05 < far


def test_cache_hit_decline_and_miss():
    cache = SolveCache()
    problem = Problem(M=16, N=16)
    key = solve_key(problem)
    rng = np.random.default_rng(2)
    rhs = rng.normal(size=(17, 17))
    sol = rng.normal(size=(17, 17))
    cache.put(key, rhs, sol, iters=12)
    hit, dist = cache.lookup(key, rhs * 1.01)
    assert hit is sol and dist < cache.max_distance
    # an unrelated rhs under the same key: nearest exists but too far
    declined, dist = cache.lookup(key, rng.normal(size=(17, 17)))
    assert declined is None and dist is not None
    # unknown key: a plain miss
    assert cache.lookup("other", rhs) == (None, None)
    stats = cache.stats()
    assert (stats.hits, stats.declined, stats.misses) == (1, 1, 1)


def test_cache_is_bounded_on_both_axes():
    cache = SolveCache(max_keys=2, per_key=2)
    rng = np.random.default_rng(3)
    for i in range(3):
        cache.put(f"k{i}", rng.normal(size=(9, 9)), i)
    # LRU over keys: k0 evicted, the two newest live
    assert cache.lookup("k0", rng.normal(size=(9, 9))) == (None, None)
    for _ in range(3):
        cache.put("k2", rng.normal(size=(9, 9)), 0)
    assert len(cache) <= 2 * 2
    assert cache.stats().evicted >= 2


# -- serve wiring: pools, poisoning, replay, chaos ---------------------------


def _drain_one(sched, problem, request_id):
    assert sched.submit(problem, request_id=request_id) is None
    return sched.drain()[request_id]


def test_scheduler_pool_warm_starts_second_request():
    sched = Scheduler(lanes=2, chunk=8, warm_start=True)
    problem = Problem(M=10, N=10)
    first = _drain_one(sched, problem, "seed")
    second = _drain_one(sched, problem, "hit")
    assert first.outcome == second.outcome == "completed"
    pools = [c.pool for c in sched._ctxs.values() if c.pool is not None]
    assert pools and sum(p.stats().hits for p in pools) >= 1
    # the identical re-request is the degenerate cache hit: near-free
    assert second.iters < first.iters
    l2_first = float(l2_error_vs_analytic(problem, first.w))
    l2_second = float(l2_error_vs_analytic(problem, second.w))
    assert abs(l2_second - l2_first) / l2_first <= L2_REL_GAP


def test_cache_poison_costs_iterations_never_correctness(tmp_path):
    sink = os.path.join(tmp_path, "trace.jsonl")
    obs_trace.start(sink)
    try:
        plan = FaultPlan(Fault("cache_poison", request_id="victim"))
        sched = Scheduler(lanes=2, chunk=8, warm_start=True, faults=plan)
        problem = Problem(M=10, N=10)
        seed = _drain_one(sched, problem, "seed")
        victim = _drain_one(sched, problem, "victim")
    finally:
        obs_trace.stop()
    assert victim.outcome == "completed"
    # the poisoned consult must cost iterations (vs the clean warm hit
    # the pool would have given), not correctness
    assert victim.iters >= seed.iters
    l2_seed = float(l2_error_vs_analytic(problem, seed.w))
    l2_victim = float(l2_error_vs_analytic(problem, victim.w))
    assert abs(l2_victim - l2_seed) / l2_seed <= L2_REL_GAP
    events = [json.loads(line) for line in open(sink)]
    kinds = {e.get("name") for e in events}
    assert "serve:fault" in kinds  # the injection fired...
    assert "recycle:bad-hit" in kinds  # ...and admission flagged it


def test_replayed_outcomes_bitwise_identical_regardless_of_cache(tmp_path):
    """The journal contract: replays run cold, so a successor WITH the
    recycle pools on journals bitwise the same outcomes as one without."""
    problem = Problem(M=10, N=10)

    def journal_with_backlog(name):
        path = os.path.join(tmp_path, name)
        sched = Scheduler(lanes=2, chunk=8, journal=path, warm_start=True)
        for i in range(3):
            assert sched.submit(problem, request_id=f"r{i}") is None
        return path  # dropped un-drained: the SIGKILL shape

    warm = Scheduler(
        lanes=2, chunk=8, warm_start=True,
        journal=journal_with_backlog("warm.json"),
    )
    cold = Scheduler(
        lanes=2, chunk=8, warm_start=False,
        journal=journal_with_backlog("cold.json"),
    )
    assert warm.replay() == cold.replay() == 3
    rw, rc = warm.drain(), cold.drain()
    assert set(rw) == set(rc)
    for rid in rw:
        assert rw[rid].outcome == rc[rid].outcome == "completed"
        assert rw[rid].iters == rc[rid].iters
        assert np.array_equal(rw[rid].w, rc[rid].w)


def test_chaos_with_recycling_on_keeps_invariants_and_determinism(tmp_path):
    kw = dict(
        n_requests=12, seed=5, warm_start=True, poison_request=3,
    )
    r1 = run_chaos(journal_path=os.path.join(tmp_path, "c1.json"), **kw)
    r2 = run_chaos(journal_path=os.path.join(tmp_path, "c2.json"), **kw)
    for rep in (r1, r2):
        assert rep.ok, (
            f"lost={rep.lost} doubled={rep.double_completed} "
            f"unclassified={rep.unclassified}"
        )
        assert sum(rep.counts.values()) == 12
    assert r1.outcomes == r2.outcomes
    assert r1.counts == r2.counts


def test_chaos_poison_requires_warm_start(tmp_path):
    with pytest.raises(ValueError, match="warm_start"):
        run_chaos(
            n_requests=4, seed=0, poison_request=1,
            journal_path=os.path.join(tmp_path, "j.json"),
        )


# -- autotune + spectrum predictor -------------------------------------------


def test_spectrum_deflated_prediction_beats_cold(capture64):
    from poisson_ellipse_tpu.obs import spectrum

    problem, a, b, rhs, res, basis = capture64
    _, trace, _ = pcg(
        problem, a, b, rhs, history=True, recycle=rec.RECYCLE_CAP
    )
    spec = spectrum.spectrum_report(
        trace, delta=problem.delta, actual_iters=int(res.iters),
        deflated_k=rec.RECYCLE_K,
    )
    assert spec["available"]
    assert spec["predicted_iters_recycled"] < spec["predicted_iters_cold"]
    # with deflated_k, predicted_iters IS the recycled value
    assert spec["predicted_iters"] == spec["predicted_iters_recycled"]


def test_autotune_telemetry_and_select_carry_recycle_verdict():
    from poisson_ellipse_tpu.runtime import autotune

    problem = Problem(M=48, N=48)
    telemetry = autotune.collect_telemetry(
        problem, jnp.float32, measure_gbps=False
    )
    assert "predicted_iters_recycled" in telemetry
    cfg, scored = autotune.select(problem, telemetry)
    assert isinstance(cfg.recycle, bool)
    if cfg.recycle:
        assert cfg.predicted_iters_recycled is not None
        # the verdict must clear the same margin every selection uses
        assert cfg.predicted_iters_recycled < telemetry["predicted_iters"]


# -- harness surface ---------------------------------------------------------


def test_run_once_recycle_cuts_iterations(capture64):
    from poisson_ellipse_tpu.harness.run import run_once

    problem, _, _, _, res, _ = capture64
    rep = run_once(
        Problem(M=64, N=64), mode="single", engine="xla", dtype="f32",
        recycle=rec.RECYCLE_CAP,
    )
    assert rep.converged
    assert rep.iters < int(res.iters)
    l2_cold = float(l2_error_vs_analytic(problem, res.w))
    assert abs(rep.l2_error - l2_cold) / l2_cold <= L2_REL_GAP


def test_run_once_warm_start_is_the_cache_hit_shape():
    from poisson_ellipse_tpu.harness.run import run_once

    rep = run_once(
        Problem(M=24, N=24), mode="single", engine="xla", dtype="f32",
        warm_start=True,
    )
    assert rep.converged
    assert rep.iters <= 3  # re-solving the solved system is near-free


@pytest.mark.parametrize(
    "kw",
    [
        dict(lanes=4),
        dict(guard=True),
        dict(mode="sharded"),
        dict(storage_dtype="bf16"),
        dict(engine="pipelined"),
        dict(recycle=0),
    ],
)
def test_run_once_recycle_flag_conflicts(kw):
    from poisson_ellipse_tpu.harness.run import run_once

    kw.setdefault("mode", "single")
    with pytest.raises(ValueError):
        run_once(Problem(M=10, N=10), recycle=kw.pop("recycle", 8), **kw)


def test_cli_recycle_flag(capsys):
    from poisson_ellipse_tpu.harness.__main__ import main

    rc = main(
        ["24", "24", "--mode", "single", "--recycle", "8", "--warm-start",
         "--json"]
    )
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    record = json.loads(line)
    assert record["engine"] == "xla"
    assert record["converged"]


# -- inspect line ------------------------------------------------------------


def test_engine_report_carries_recycle_ring_model():
    from poisson_ellipse_tpu.obs import static_cost

    problem = Problem(M=16, N=16)
    rep = static_cost.engine_report(
        problem, "xla", jnp.float32, with_xla_cost=False
    )
    assert rep["recycle_ring_cap"] == rec.RECYCLE_CAP
    assert rep["recycle_ring_model_bytes"] == rec.ring_model_bytes(
        problem, cap=rec.RECYCLE_CAP, dtype=jnp.float32
    )
    assert "recycle ring" in static_cost.render_report(rep)
    # engines without the contract row stay silent
    rep2 = static_cost.engine_report(
        problem, "pipelined", jnp.float32, with_xla_cost=False
    )
    assert rep2["recycle_ring_model_bytes"] is None
    assert "recycle ring" not in static_cost.render_report(rep2)
