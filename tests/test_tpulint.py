"""tpulint rule tests: one positive and one negative fixture per rule.

Each fixture is a small source snippet fed through ``lint_source`` — the
same path the CLI and the CI gate take, minus the filesystem. Positives
assert the rule fires with its stable code; negatives assert the nearby
trace-safe idiom stays silent (a lint gate that cries wolf gets deleted
from CI, so the negatives are as load-bearing as the positives).
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap

import pytest

from poisson_ellipse_tpu.lint import (
    AUDIT_CODE,
    LintConfig,
    RULES,
    apply_baseline,
    audit_suppressions,
    finding_key,
    lint_source,
)
from poisson_ellipse_tpu.lint.report import Finding, render_report


def codes_of(source: str, **cfg) -> list[str]:
    config = LintConfig(**cfg) if cfg else None
    return [f.code for f in lint_source(textwrap.dedent(source), config=config)]


# -- registry shape ---------------------------------------------------------


def test_registry_has_all_twenty_rules():
    assert sorted(RULES) == [f"TPU00{i}" for i in range(1, 10)] + [
        "TPU010", "TPU011", "TPU012", "TPU013", "TPU014", "TPU015",
        "TPU016", "TPU017", "TPU018", "TPU019", "TPU020", "TPU021",
        "TPU022",
    ]
    for code, rule in RULES.items():
        assert rule.code == code
        assert rule.name and rule.summary


# -- TPU001: f64 literals ---------------------------------------------------


def test_tpu001_positive_dtype_kwarg_and_positional():
    src = """
        import jax.numpy as jnp
        import numpy as np
        x = jnp.zeros((4, 4), dtype=np.float64)
        y = jnp.asarray([1.0], float)
        z = jnp.array([1.0], dtype="float64")
    """
    assert codes_of(src) == ["TPU001", "TPU001", "TPU001"]


def test_tpu001_positive_bare_jnp_float64_reference():
    src = """
        import jax.numpy as jnp
        DTYPES = {"f64": jnp.float64}
    """
    assert codes_of(src) == ["TPU001"]


def test_tpu001_negative_narrow_and_host_numpy():
    # explicit narrow dtypes and *host* numpy float64 are both fine: only
    # jnp is subject to the silent x64 downcast
    src = """
        import jax.numpy as jnp
        import numpy as np
        a = jnp.zeros((4, 4), dtype=jnp.float32)
        b = np.zeros((4, 4), np.float64)
        c = np.arange(5, dtype=np.float64)
    """
    assert codes_of(src) == []


def test_tpu001_suppression_comment():
    src = """
        import jax.numpy as jnp
        x = jnp.zeros(3, dtype=jnp.float64)  # tpulint: disable=TPU001
        # tpulint: disable=TPU001
        y = jnp.ones(3, dtype=jnp.float64)
    """
    assert codes_of(src) == []


# -- TPU002: Python control flow on traced values ---------------------------


def test_tpu002_positive_if_in_jit_def():
    src = """
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
    """
    assert "TPU002" in codes_of(src)


def test_tpu002_positive_while_in_loop_body():
    src = """
        from jax import lax

        def solve(state):
            def body(carry):
                r = carry
                while r > 1e-6:
                    r = r * 0.5
                return r
            return lax.while_loop(lambda c: c > 0, body, state)
    """
    assert "TPU002" in codes_of(src)


def test_tpu002_negative_static_branches():
    # branches on shapes/closure config are trace-time static, and
    # static_argnums-marked params are Python values
    src = """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnums=(1,))
        def f(x, mode):
            if mode == "fast":
                return x * 2
            if x.ndim == 2:
                return x.T
            return x
    """
    assert codes_of(src) == []


# -- TPU003: host syncs reachable from jitted functions ---------------------


def test_tpu003_positive_direct_and_reachable():
    src = """
        import jax
        import numpy as np

        def helper(v):
            return float(v) * 2.0

        @jax.jit
        def hot(x):
            x.block_until_ready()
            y = np.asarray(x)
            return helper(x) + y
    """
    codes = codes_of(src)
    assert codes.count("TPU003") == 3  # method sync, np.asarray, float-in-callee


def test_tpu003_negative_host_side_fencing():
    # the same calls OUTSIDE traced functions are the normal host idiom
    src = """
        import jax
        import numpy as np

        def bench(solver, args):
            out = solver(*args)
            jax.block_until_ready(out)
            return float(np.asarray(out)[0])
    """
    assert codes_of(src) == []


def test_tpu003_negative_float_of_static():
    src = """
        import jax

        @jax.jit
        def f(x):
            scale = float(1e-3)
            return x * scale
    """
    assert codes_of(src) == []


# -- TPU004: jit without donate_argnums -------------------------------------


def test_tpu004_positive_many_param_jit_call():
    src = """
        import jax

        def build(problem):
            def solver(a, b, rhs):
                return a + b + rhs
            return jax.jit(solver)
    """
    assert codes_of(src) == ["TPU004"]


def test_tpu004_positive_decorated_def():
    src = """
        import jax

        @jax.jit
        def step(w, r, p):
            return w + r + p
    """
    assert codes_of(src) == ["TPU004"]


def test_tpu004_negative_donated_or_small():
    src = """
        import jax

        def build():
            def solver(a, b, rhs):
                return a + b + rhs
            def tiny(x):
                return x
            return jax.jit(solver, donate_argnums=(2,)), jax.jit(tiny)
    """
    assert codes_of(src) == []


def test_tpu004_static_argnums_shrink_arity():
    # 3 positional params but one is static: below the default threshold
    src = """
        import jax

        def build():
            def solver(a, b, mode):
                return a + b
            return jax.jit(solver, static_argnums=(2,))
    """
    assert codes_of(src) == []


def test_tpu004_threshold_configurable():
    src = """
        import jax

        def build():
            def solver(a, b):
                return a + b
            return jax.jit(solver)
    """
    assert codes_of(src) == []
    assert codes_of(src, min_donate_params=2) == ["TPU004"]


# -- TPU005: Pallas tile alignment / VMEM budget ----------------------------


def test_tpu005_positive_misaligned_blockspec():
    src = """
        from jax.experimental import pallas as pl
        spec = pl.BlockSpec((7, 100), lambda i: (i, 0))
    """
    codes = codes_of(src)
    assert codes == ["TPU005", "TPU005"]  # lane AND sublane misaligned


def test_tpu005_positive_vmem_overflow():
    # 5 × (8192, 1024) f32 scratch = 160 MiB > the smallest part's budget
    src = """
        import jax
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu
        import jax.numpy as jnp

        out = pl.pallas_call(
            lambda o: None,
            out_shape=jax.ShapeDtypeStruct((8, 128), "float32"),
            scratch_shapes=[
                pltpu.VMEM((8192, 1024), jnp.float32),
                pltpu.VMEM((8192, 1024), jnp.float32),
                pltpu.VMEM((8192, 1024), jnp.float32),
                pltpu.VMEM((8192, 1024), jnp.float32),
                pltpu.VMEM((8192, 1024), jnp.float32),
            ],
        )
    """
    assert "TPU005" in codes_of(src)


def test_tpu005_negative_aligned_dynamic_and_smem():
    # aligned literals, dynamic tiles, and SMEM scalar specs all pass
    src = """
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def make(tm, g2):
            a = pl.BlockSpec((128, 256), lambda i: (i, 0))
            b = pl.BlockSpec((tm, g2), lambda i: (i, 0))
            c = pl.BlockSpec(memory_space=pltpu.SMEM)
            d = pl.BlockSpec((1, 1), memory_space=pltpu.SMEM)
            return a, b, c, d
    """
    assert codes_of(src) == []


def test_tpu005_capability_table_is_read_statically():
    from poisson_ellipse_tpu.lint.rules import _min_vmem_capacity
    from poisson_ellipse_tpu.utils.device import _VMEM_CAPACITY

    # the static AST read of utils/device.py must agree with the runtime
    # table — the whole point of cross-checking against one source
    assert _min_vmem_capacity() == min(_VMEM_CAPACITY.values())


# -- TPU006: per-call jit construction --------------------------------------


def test_tpu006_positive_jit_in_loop():
    src = """
        import jax

        def sweep(fns, x):
            outs = []
            for fn in fns:
                outs.append(jax.jit(fn)(x))
            return outs
    """
    codes = codes_of(src)
    assert "TPU006" in codes


def test_tpu006_positive_construct_and_call():
    src = """
        import jax

        def run(f, x):
            return jax.jit(f)(x)
    """
    assert "TPU006" in codes_of(src)


def test_tpu006_negative_module_scope_and_factories():
    src = """
        import jax

        step = jax.jit(lambda x: x + 1)

        def build_solver(f):
            solver = jax.jit(f)
            return solver

        def stepper(f):
            return jax.jit(f)
    """
    assert codes_of(src) == []


# -- TPU007: adjacent un-fused global reductions ----------------------------


def test_tpu007_positive_independent_psums_in_loop_body():
    src = """
        from jax import lax

        def advance(state):
            def body(c):
                a, b = c
                s1 = lax.psum(a, "x")
                s2 = lax.psum(b, "x")
                return (s1, s2)
            return lax.while_loop(lambda c: True, body, state)
    """
    assert codes_of(src) == ["TPU007"]


def test_tpu007_positive_independent_jnp_sums():
    src = """
        import jax.numpy as jnp
        from jax import lax

        def advance(state):
            def body(c):
                a, b = c
                zr = jnp.sum(a * a)
                dw2 = jnp.sum(b * b)
                return (a * zr, b * dw2)
            return lax.while_loop(lambda c: True, body, state)
    """
    assert codes_of(src) == ["TPU007"]


def test_tpu007_negative_dependent_reductions_stay_silent():
    """denom -> alpha -> r_new -> second dot is the algorithm's critical
    path, not a fusion miss: the sequenced pair must not fire."""
    src = """
        import jax.numpy as jnp
        from jax import lax

        def advance(state):
            def body(c):
                r, p, ap = c
                denom = jnp.sum(ap * p)
                alpha = 1.0 / denom
                r_new = r - alpha * ap
                zr = jnp.sum(r_new * r_new)
                return (r_new, p * zr, ap)
            return lax.while_loop(lambda c: True, body, state)
    """
    assert codes_of(src) == []


def test_tpu007_negative_stacked_single_statement():
    """The cure — partials stacked into one statement / one collective —
    must lint clean, and reductions outside loop bodies are not the
    rule's business."""
    src = """
        import jax.numpy as jnp
        from jax import lax

        def init(a, b):
            zr = jnp.sum(a * a)
            dw = jnp.sum(b * b)
            return zr + dw

        def advance(state):
            def body(c):
                a, b = c
                parts = jnp.stack([jnp.sum(a * a), jnp.sum(b * b)])
                sums = lax.psum(parts, ("x", "y"))
                return (a * sums[0], b * sums[1])
            return lax.while_loop(lambda c: True, body, state)
    """
    assert codes_of(src) == []


def test_tpu007_negative_axis_sum_is_not_global():
    """Partial reductions (keyword OR positional axis) stay arrays and
    are not collective candidates."""
    src = """
        import jax.numpy as jnp
        from jax import lax

        def advance(state):
            def body(c):
                a, b = c
                rows = jnp.sum(a, axis=0)
                cols = jnp.sum(a, 0)
                tot = jnp.sum(b)
                return (a + rows + cols, b * tot)
            return lax.while_loop(lambda c: True, body, state)
    """
    assert codes_of(src) == []


def test_tpu007_negative_reduction_inside_compound_statement():
    """A reduction assigned inside a compound statement (here an
    unrolled `for`) still taints its target: the dependent follow-up
    reduction is sequential, not fusable."""
    src = """
        from jax import lax

        def advance(state):
            def body(c):
                a, b = c
                for _ in range(2):
                    s1 = lax.psum(a, "x")
                tot = lax.psum(s1 * b, "x")
                return (a, b * tot)
            return lax.while_loop(lambda c: True, body, state)
    """
    assert codes_of(src) == []


def test_tpu007_reduction_roots_config_knob():
    """Project-named reduction wrappers (grid_dot-style) are only seen
    through the reduction-roots config, matching resolved qualnames."""
    src = """
        from jax import lax
        from mylib.reduce import grid_dot

        def advance(state):
            def body(c):
                a, b = c
                d1 = grid_dot(a, a)
                d2 = grid_dot(b, b)
                return (a * d1, b * d2)
            return lax.while_loop(lambda c: True, body, state)
    """
    assert codes_of(src) == []
    assert codes_of(src, reduction_roots=("*.reduce.grid_dot",)) == ["TPU007"]


def test_tpu007_pyproject_roots_loaded():
    import os

    from poisson_ellipse_tpu.lint import load_config

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    config = load_config(repo_root)
    assert "*.ops.reduction.grid_dot" in config.reduction_roots


# -- TPU008: host syncs / host callbacks inside loop bodies -----------------


def only_008(src: str, **cfg) -> list[str]:
    cfg.setdefault("select", frozenset({"TPU008"}))
    return codes_of(src, **cfg)


def test_tpu008_positive_item_and_device_get_in_loop_body():
    src = """
        import jax
        from jax import lax

        def body(state):
            k, x = state
            bad = x.sum().item()
            jax.device_get(x)
            return (k + 1, x * bad)

        def run(x0):
            return lax.while_loop(lambda s: s[0] < 5, body, (0, x0))
    """
    codes = only_008(src)
    assert codes == ["TPU008", "TPU008"]


def test_tpu008_positive_callback_registration_in_loop_body():
    src = """
        import jax
        from jax import lax

        def log_it(v):
            print(v)

        def body(s):
            jax.debug.callback(log_it, s)
            return s + 1

        def run(x0):
            return lax.fori_loop(0, 5, lambda i, s: body(s), x0)
    """
    # the per-iteration callback registered inside the fori body closure
    src2 = """
        import jax
        from jax import lax

        def body(i, s):
            jax.pure_callback(lambda v: v, s, s)
            return s + 1

        def run(x0):
            return lax.fori_loop(0, 5, body, x0)
    """
    assert only_008(src2) == ["TPU008"]


def test_tpu008_positive_float_on_traced_carry():
    src = """
        from jax import lax

        def body(i, s):
            alpha = float(s)
            return s + alpha

        def run(x0):
            return lax.fori_loop(0, 5, body, x0)
    """
    assert only_008(src) == ["TPU008"]


def test_tpu008_positive_fence_wrapper_in_host_measurement_loop():
    src = """
        from poisson_ellipse_tpu.utils.timing import fence

        def measure(solver, args, repeat):
            times = []
            for _ in range(repeat):
                out = solver(*args)
                fence(out)
                times.append(1.0)
            return times
    """
    assert only_008(src) == ["TPU008"]


def test_tpu008_negative_host_side_fence_outside_loops():
    # a fence after a single dispatch (warm-up, result fetch) is the
    # host-side idiom, not a per-iteration sync
    src = """
        from poisson_ellipse_tpu.utils.timing import fence

        def warmup(solver, args):
            out = solver(*args)
            fence(out)
            return out
    """
    assert only_008(src) == []


def test_tpu008_owns_loop_bodies_no_tpu003_double_report():
    # one defect, one code: a sync inside a loop body is TPU008 only —
    # TPU003 keeps the jit-def/jit-call surface (suppressing the one
    # reported code must actually silence the gate)
    src = """
        import jax
        from jax import lax

        @jax.jit
        def run(x):
            def body(s):
                s.item()
                return s * 0.5
            return lax.while_loop(lambda s: s.sum() > 1, body, x)
    """
    assert codes_of(src) == ["TPU008"]
    # the jit-def surface outside the loop body stays TPU003
    src2 = """
        import jax

        @jax.jit
        def hot(x):
            x.block_until_ready()
            return x
    """
    assert codes_of(src2) == ["TPU003"]


def test_tpu008_negative_untainted_numpy_in_loop_body():
    # np.asarray of a host constant inside a loop body is trace-time
    # constant folding, not a per-iteration sync — same taint semantics
    # as TPU003 (the classifier is shared, so they cannot drift)
    src = """
        import numpy as np
        from jax import lax

        TABLE = [1.0, 2.0]

        def body(i, s):
            c = np.asarray(TABLE)
            return s + c[0]

        def run(x0):
            return lax.fori_loop(0, 5, body, x0)
    """
    assert only_008(src) == []
    src_tainted = """
        import numpy as np
        from jax import lax

        def body(i, s):
            c = np.asarray(s)
            return s + c[0]

        def run(x0):
            return lax.fori_loop(0, 5, body, x0)
    """
    assert only_008(src_tainted) == ["TPU008"]


def test_tpu008_negative_device_resident_body_stays_silent():
    # the obs.convergence idiom: per-iteration scalars scattered into an
    # on-device buffer — exactly what the rule steers people toward
    src = """
        import jax.numpy as jnp
        from jax import lax

        def body(state):
            k, x, hist = state
            zr = jnp.sum(x * x)
            hist = lax.dynamic_update_slice(hist, jnp.reshape(zr, (1,)), (k,))
            return (k + 1, x * 0.5, hist)

        def run(x0, hist0):
            return lax.while_loop(lambda s: s[0] < 5, body, (0, x0, hist0))
    """
    assert only_008(src) == []


def test_tpu008_suppression_and_config_knob():
    src = """
        from poisson_ellipse_tpu.utils.timing import fence

        def measure(solver, args, repeat):
            for _ in range(repeat):
                out = solver(*args)
                fence(out)  # tpulint: disable=TPU008
            return out
    """
    assert only_008(src) == []
    # a project can point host-sync-fns at its own wrapper name
    src2 = """
        from mylib.sync import wait_for

        def measure(solver, args, repeat):
            for _ in range(repeat):
                out = solver(*args)
                wait_for(out)
            return out
    """
    assert only_008(src2, host_sync_fns=("mylib.sync.wait_for",)) == ["TPU008"]
    assert only_008(src2, host_sync_fns=()) == []


def test_tpu008_pyproject_sync_fns_loaded():
    import os

    from poisson_ellipse_tpu.lint import load_config

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    config = load_config(repo_root)
    assert "*.timing.fence" in config.host_sync_fns


# -- plumbing: suppression scope, CLI, report -------------------------------


# -- TPU009: swallowed broad exceptions -------------------------------------


def test_tpu009_positive_bare_and_broad_swallows():
    src = """
        def run(solver, args):
            try:
                return solver(*args)
            except:
                pass

        def run2(solver, args):
            try:
                return solver(*args)
            except Exception:
                return None

        def run3(solver, args):
            try:
                return solver(*args)
            except (ValueError, BaseException) as e:
                log(e)
    """
    assert codes_of(src) == ["TPU009", "TPU009", "TPU009"]


def test_tpu009_negative_narrow_reraise_and_classified():
    # a deliberately narrow class, a handler that re-raises (bare or a
    # classified SolveError), and an else-path all stay silent
    src = """
        def run(solver, args):
            try:
                return solver(*args)
            except ValueError:
                return None

        def run2(solver, args):
            try:
                return solver(*args)
            except Exception as e:
                if transient(e):
                    retry()
                raise

        def run3(solver, args):
            try:
                return solver(*args)
            except Exception as e:
                raise SolveError(str(e)) from e
    """
    assert codes_of(src) == []


def test_tpu009_positive_raise_only_defined_in_nested_scope():
    # a raise inside a nested def/lambda is never executed BY the
    # handler — the broad except still swallows
    src = """
        def run(fn):
            try:
                return fn()
            except Exception:
                def retry_later():
                    raise
                return None
    """
    assert codes_of(src) == ["TPU009"]


def test_tpu009_reraise_fns_config_knob():
    src = """
        from mypkg.resilience.errors import raise_classified

        def run(solver, args):
            try:
                return solver(*args)
            except Exception as e:
                raise_classified(e)
    """
    assert codes_of(src) == ["TPU009"]
    assert codes_of(src, reraise_fns=("*.errors.raise_classified",)) == []


def test_tpu009_suppression_with_note():
    src = """
        def accounting(fn):
            try:
                return fn()
            except Exception:  # tpulint: disable=TPU009 — best-effort
                return None
    """
    assert codes_of(src) == []


def test_tpu009_pyproject_reraise_fns_loaded():
    from poisson_ellipse_tpu.lint import load_config

    # the key parses from [tool.tpulint] (empty today — the repo's own
    # recovery paths carry literal raises)
    config = load_config()
    assert isinstance(config.reraise_fns, tuple)


# -- TPU010: recompile hazards ----------------------------------------------


def test_tpu010_positive_lower_compile_in_loop():
    src = """
        import jax

        def serve(requests, fn):
            out = []
            for req in requests:
                exe = jax.jit(fn).lower(req).compile()
                out.append(exe(req))
            return out
    """
    # jax.jit inside the loop is TPU006's finding; the AOT chain is ours
    codes = codes_of(src)
    assert "TPU010" in codes and "TPU006" in codes


def test_tpu010_negative_warmup_and_factory_fns_exempt():
    # a warm pool filling its buckets once, and a build_* factory
    # probing a capacity ladder, are the deliberate AOT sites
    src = """
        def warmup_buckets(jitted, buckets):
            pool = {}
            for shape in buckets:
                pool[shape] = jitted.lower(shape).compile()
            return pool

        def build_solver(chain, jitted, args):
            for cand in chain:
                jitted.lower(*args).compile()
            return jitted
    """
    assert codes_of(src) == []
    # the knob is configurable: renaming the exempt pattern re-arms it
    assert "TPU010" in codes_of(
        src, aot_warmup_fns=("somethingelse*",),
        jit_factory_patterns=("nope*",),
    )


def test_tpu010_negative_single_shot_aot_outside_loops():
    src = """
        import jax

        def precompile(fn, shape):
            return jax.jit(fn).lower(shape).compile()
    """
    assert codes_of(src) == []


def test_tpu010_positive_loop_varying_static_arg():
    src = """
        import jax

        step = jax.jit(run_chunk, static_argnums=(1,))

        def drive(state, chunks):
            for limit in chunks:
                state = step(state, limit)
            return state
    """
    assert codes_of(src) == ["TPU010"]


def test_tpu010_positive_loop_varying_static_argname():
    src = """
        import jax

        step = jax.jit(run_chunk, static_argnames=("limit",))

        def drive(state, chunks):
            k = 0
            while k < 10:
                k = k + 1
                state = step(state, limit=k)
            return state
    """
    assert codes_of(src) == ["TPU010"]


def test_tpu010_negative_traced_and_loop_invariant_statics():
    # the house pattern: the bound rides as a TRACED operand (position 1
    # is not static), and a static that does not vary with the loop is
    # one compile, not one per iteration
    src = """
        import jax

        step = jax.jit(run_chunk)
        shaped = jax.jit(run_chunk, static_argnums=(1,))

        def drive(state, chunks, bucket):
            for limit in chunks:
                state = step(state, limit)
                state = shaped(state, bucket)
            return state
    """
    assert codes_of(src) == []


def test_tpu010_negative_nonliteral_static_spec_stays_silent():
    src = """
        import jax

        step = jax.jit(run_chunk, static_argnums=SPEC)

        def drive(state, chunks):
            for limit in chunks:
                state = step(state, limit)
            return state
    """
    assert codes_of(src) == []
    # a non-literal argnames keyword must not crash the pass when a
    # literal argnums follows it in the same jit call — the binding is
    # simply not trusted (conservative silence, not an AttributeError)
    mixed = """
        import jax

        step = jax.jit(run_chunk, static_argnames=(NAME,), static_argnums=(1,))

        def drive(state, chunks):
            for limit in chunks:
                state = step(state, limit)
            return state
    """
    assert codes_of(mixed) == []


def test_tpu010_suppression_and_pyproject_knob():
    src = """
        def refresh(jitted, shapes):
            for s in shapes:
                jitted.lower(s).compile()  # tpulint: disable=TPU010 — drill
    """
    assert codes_of(src) == []
    from poisson_ellipse_tpu.lint import load_config

    config = load_config()
    assert "warmup*" in config.aot_warmup_fns


# -- TPU012: unbounded module/class-level queues ----------------------------


def test_tpu012_positive_module_level_list_and_deque():
    src = """
        from collections import deque

        PENDING = []
        EVENTS = deque()

        def enqueue(req):
            PENDING.append(req)
            EVENTS.appendleft(req)
    """
    assert codes_of(src) == ["TPU012", "TPU012"]


def test_tpu012_positive_instance_queue_grown_in_method():
    src = """
        import collections

        class Server:
            def __init__(self):
                self.queue = collections.deque()
                self.log = []

            def handle(self, req):
                self.queue.append(req)
                self.log.append(req.id)
    """
    assert codes_of(src) == ["TPU012", "TPU012"]


def test_tpu012_positive_annotated_instance_queue():
    # a type annotation on the initialiser must not exempt the exact
    # unbounded-queue leak the rule exists to catch
    src = """
        import collections

        class Server:
            def __init__(self):
                self.pending: list = []
                self.events: collections.deque = collections.deque()

            def handle(self, req):
                self.pending.append(req)
                self.events.append(req)
    """
    assert codes_of(src) == ["TPU012", "TPU012"]


def test_tpu012_positive_dataclass_field_default_factory():
    src = """
        import dataclasses

        @dataclasses.dataclass
        class Buffer:
            items: list = dataclasses.field(default_factory=list)

            def push(self, x):
                self.items.append(x)
    """
    assert codes_of(src) == ["TPU012"]


def test_tpu012_negative_bounded_queues_stay_silent():
    # maxlen at the source, a windowed del (the obs.metrics.Histogram
    # idiom), and a draining pop each count as a bound
    src = """
        import collections
        import dataclasses

        RING = collections.deque(maxlen=64)

        @dataclasses.dataclass
        class Histogram:
            _window: list = dataclasses.field(default_factory=list)

            def observe(self, v):
                self._window.append(v)
                if len(self._window) > 10:
                    del self._window[: len(self._window) - 10]

        class Worker:
            def __init__(self):
                self.inbox = []

            def put(self, x):
                self.inbox.append(x)

            def drain(self):
                while self.inbox:
                    self.inbox.pop()

        def feed(x):
            RING.append(x)
    """
    assert codes_of(src) == []


def test_tpu012_negative_function_locals_stay_silent():
    # a local list is scoped to one call — no residue across requests
    src = """
        def collect(xs):
            out = []
            for x in xs:
                out.append(x)
            return out
    """
    assert codes_of(src) == []


def test_tpu012_negative_copied_and_never_grown():
    src = """
        class Plan:
            def __init__(self, faults):
                self.faults = list(faults)
        TABLE = []
    """
    assert codes_of(src) == []


def test_tpu012_method_local_sharing_attr_name_is_not_the_attr():
    # a never-grown attribute must not inherit a same-named local's
    # growth (false positive), and a grown attribute must not be
    # silenced by a same-named local's pop (false negative)
    src = """
        class Server:
            def __init__(self):
                self.buf = []
            def work(self, xs):
                buf = []
                buf.append(xs)
                return buf

        class Leaky:
            def __init__(self):
                self.events = []
            def on(self, e):
                self.events.append(e)
            def other(self, xs):
                events = list(xs)
                events.pop()
                return events
    """
    assert codes_of(src) == ["TPU012"]


def test_tpu012_shadowing_function_local_is_not_the_module_queue():
    # a function that rebinds the name operates on its local — neither
    # its growth nor its draining belongs to the module-level binding;
    # a `global` declaration un-shadows
    src = """
        pending = []

        def local_noise():
            pending = []
            pending.append(1)
            return pending

        backlog = []

        def drain_a_copy(backlog):
            backlog.pop()

        def push(x):
            global backlog
            backlog.append(x)
    """
    assert codes_of(src) == ["TPU012"]  # backlog only


def test_tpu012_negative_swap_and_reset_drain_is_a_bound():
    # rebinding to a fresh empty container empties the old one for gc —
    # the swap-and-reset drain idiom is a bound, but the candidate's
    # own initialiser must not count as one
    src = """
        class Collector:
            def __init__(self):
                self.buf = []

            def add(self, x):
                self.buf.append(x)

            def flush(self):
                out, self.buf = self.buf, []
                return out

        backlog = []

        def push(x):
            global backlog
            backlog.append(x)

        def drain():
            global backlog
            got = backlog
            backlog = []
            return got
    """
    assert codes_of(src) == []


def test_tpu012_nested_def_local_does_not_shadow_the_encloser():
    # a NESTED def's local rebinding belongs to the nested scope only —
    # it must not mark the enclosing function as shadowing and thereby
    # silence the encloser's real growth of the module-level queue
    src = """
        PENDING = []

        def worker(req):
            PENDING.append(req)
            def helper(xs):
                PENDING = list(xs)
                return PENDING
            return helper
    """
    assert codes_of(src) == ["TPU012"]


# -- TPU011: unfenced timing spans ------------------------------------------


def test_tpu011_positive_unfenced_span_around_jit():
    src = """
        import time
        import jax

        solver = jax.jit(lambda x: x + 1)

        def measure(x):
            t0 = time.perf_counter()
            out = solver(x)
            return time.perf_counter() - t0
    """
    assert codes_of(src) == ["TPU011"]


def test_tpu011_positive_factory_bound_and_aot_callables():
    # names tuple-unpacked from a jit factory (build_*) and bound from a
    # .lower().compile() chain both count as dispatchable
    src = """
        import time

        def run(problem):
            solver, args, engine = build_solver(problem)
            t0 = time.monotonic()
            r = solver(*args)
            return time.monotonic() - t0
    """
    assert codes_of(src) == ["TPU011"]
    aot = """
        import time
        import jax

        compiled = jax.jit(f).lower(x).compile()
        t0 = time.time()
        out = compiled(x)
        t = time.time() - t0
    """
    assert [c for c in codes_of(aot) if c == "TPU011"] == ["TPU011"]


def test_tpu011_negative_fenced_spans():
    # all three fence spellings silence the span: the configured wrapper
    # (host-sync-fns — the TPU008 allowlist, reused), jax.block_until_ready,
    # and the .block_until_ready() method
    src = """
        import time
        import jax
        from poisson_ellipse_tpu.utils.timing import fence

        solver = jax.jit(lambda x: x + 1)

        def wrapper(x):
            t0 = time.perf_counter()
            out = solver(x)
            fence(out)
            return time.perf_counter() - t0

        def direct(x):
            t0 = time.perf_counter()
            out = solver(x)
            jax.block_until_ready(out)
            return time.perf_counter() - t0

        def method(x):
            t0 = time.perf_counter()
            out = solver(x).block_until_ready()
            return time.perf_counter() - t0
    """
    assert codes_of(src) == []


def test_tpu011_negative_host_only_and_deadline_patterns():
    # a host-only bracket has nothing to fence; a deadline check reads a
    # clock against a t0 *parameter* (the guard's _check_deadline shape) —
    # no span opens in that scope, so no finding
    src = """
        import time
        import jax

        solver = jax.jit(lambda x: x + 1)

        def host_only(xs):
            # perf_counter, not time.time(): a wall-clock span would be
            # TPU021's wall-clock-lease finding, which this TPU011
            # fixture is not about
            t0 = time.perf_counter()
            total = sum(xs)
            return time.perf_counter() - t0

        def deadline(timeout, t0, k):
            if time.monotonic() - t0 > timeout:
                raise TimeoutError(k)

        def dispatch_before_span(x):
            out = solver(x)
            t0 = time.perf_counter()
            host = out is None
            return time.perf_counter() - t0
    """
    assert codes_of(src) == []


def test_tpu011_suppression_and_fence_allowlist_config():
    # the enqueue-is-the-measurement case carries an annotated disable;
    # the fence allowlist is the TPU008 host-sync-fns knob, shared
    src = """
        import time
        import jax

        solver = jax.jit(lambda x: x + 1)

        def enqueue_cost(x):
            t0 = time.perf_counter()
            out = solver(x)
            return time.perf_counter() - t0  # tpulint: disable=TPU011 — enqueue IS the measurement
    """
    assert codes_of(src) == []
    custom = """
        import time
        import jax

        solver = jax.jit(lambda x: x + 1)

        def measure(x):
            t0 = time.perf_counter()
            out = solver(x)
            my_sync(out)
            return time.perf_counter() - t0
    """
    assert codes_of(custom) == ["TPU011"]
    assert codes_of(custom, host_sync_fns=("my_sync",)) == []


# -- TPU013: retraced levels (recursion / loop-varying factory calls) -------


def test_tpu013_positive_recursive_jit_construction():
    # the MG-levels hazard: a V-cycle recursing on the host and jitting
    # per level traces a fresh callable every recursion step
    src = """
        import jax

        def vcycle(levels, r):
            if not levels:
                return r
            smooth = jax.jit(levels[0].smoother)
            return vcycle(levels[1:], smooth(r))
    """
    # TPU006 owns the per-call-construction half of this fixture; the
    # recursion angle is TPU013's — both must name the same site
    assert codes_of(src) == ["TPU006", "TPU013"]


def test_tpu013_positive_loop_varying_factory_call():
    src = """
        def run_levels(problem, levels):
            for depth in levels:
                solver, args = build_solver(problem, depth)
                solver(*args)
    """
    assert codes_of(src) == ["TPU013"]


def test_tpu013_negative_static_unrolled_recursion():
    # the house pattern (mg.vcycle): Python recursion over a STATIC
    # level list inside one traced function — no jit construction, no
    # finding
    src = """
        def cycle(levels, l, r):
            ops = levels[l]
            if l == len(levels) - 1:
                return ops.solve(r)
            x = ops.smooth(r)
            return x + ops.prolong(cycle(levels, l + 1, ops.restrict(r)))
    """
    assert codes_of(src) == []


def test_tpu013_negative_factory_and_warmup_scopes_exempt():
    # a factory recursing through itself (the auto-engine chain) and a
    # warm-up loop filling a pool are the deliberate build sites
    src = """
        import jax

        def build_solver(problem, engine):
            if engine == "auto":
                return build_solver(problem, "xla")
            return jax.jit(lambda x: x)

        def warmup_pool(pool, grids):
            for grid in grids:
                pool[grid] = build_solver(grid, "xla")
    """
    assert codes_of(src) == []


def test_tpu013_negative_loop_invariant_factory_call_and_jax_helpers():
    # a factory call whose arguments do not vary with the loop, and
    # jax's own make_* in-trace helpers, both stay silent
    src = """
        from jax.experimental.pallas import tpu as pltpu

        def drive(problem, reps):
            solver, args = build_solver(problem, "xla")
            for _ in range(reps):
                solver(*args)

        def kernel_body(ref, out):
            for i in range(4):
                pltpu.make_async_copy(ref, out, i).start()
    """
    assert codes_of(src) == []


# -- TPU014: retry loops without backoff or cap -----------------------------


def test_tpu014_positive_hot_spin_retry():
    src = """
        def serve_forever(dispatch):
            while True:
                try:
                    return dispatch()
                except RuntimeError:
                    continue
    """
    assert codes_of(src) == ["TPU014"]


def test_tpu014_positive_swallow_and_fall_through():
    # no explicit continue: falling off the handler re-enters the loop
    # just the same
    src = """
        def poll(fetch, log):
            while True:
                try:
                    item = fetch()
                    handle(item)
                except ConnectionError as e:
                    log(e)
    """
    assert codes_of(src) == ["TPU014"]


def test_tpu014_negative_backoff_paced_retry():
    src = """
        import time

        def serve_forever(dispatch):
            while True:
                try:
                    return dispatch()
                except RuntimeError:
                    time.sleep(0.1)
    """
    assert codes_of(src) == []


def test_tpu014_negative_attempt_capped_retry():
    src = """
        def bounded(dispatch, budget):
            attempt = 0
            while True:
                try:
                    return dispatch()
                except RuntimeError:
                    attempt += 1
                if attempt > budget:
                    raise RuntimeError("budget exhausted")
    """
    assert codes_of(src) == []
    # the inverted spelling caps through the else-arm — same bound
    inverted = """
        def bounded(dispatch, budget):
            attempt = 0
            while True:
                try:
                    return dispatch()
                except RuntimeError:
                    attempt += 1
                if attempt <= budget:
                    continue
                else:
                    raise RuntimeError("budget exhausted")
    """
    assert codes_of(inverted) == []


def test_tpu014_negative_conditioned_loop_and_reraising_handler():
    # a tested loop condition is itself a bound; a handler that
    # re-raises is not a retry
    src = """
        def drain(queue):
            while queue:
                try:
                    queue.pop()
                except IndexError:
                    continue

        def loud(dispatch):
            while True:
                try:
                    return dispatch()
                except RuntimeError:
                    raise
    """
    assert codes_of(src) == []


def test_tpu014_backoff_fns_configurable_and_suppression():
    src = """
        def custom(dispatch, pace):
            while True:
                try:
                    return dispatch()
                except RuntimeError:
                    pace()
    """
    # the custom pacer is not in the default patterns -> fires; naming
    # it via the knob silences the loop
    assert codes_of(src) == ["TPU014"]
    assert codes_of(src, retry_backoff_fns=("pace",)) == []
    suppressed = """
        def drain_worklist(steps):
            while True:
                try:
                    return steps.pop()
                except KeyError:  # tpulint: disable=TPU014 — pop consumes the worklist
                    continue
    """
    assert codes_of(suppressed) == []


# -- TPU015: host round-trips on traced / xp-dual values --------------------


def test_tpu015_positive_float_in_jitted_fn():
    src = """
        import jax

        @jax.jit
        def f(x):
            scale = x * 2.0
            return float(scale)
    """
    # the same site is also a host sync (TPU003 — a jitted float() is
    # both); select isolates the purity rule's own verdict
    assert codes_of(src, select=frozenset({"TPU015"})) == ["TPU015"]


def test_tpu015_positive_item_in_xp_dual_fn():
    src = """
        def segment_length(x0, y0, xp):
            v = xp.sqrt(x0 * x0 + y0 * y0)
            return v.item()
    """
    assert codes_of(src) == ["TPU015"]


def test_tpu015_positive_bool_on_xp_dual_param():
    src = """
        def is_inside(shape, x, y, xp):
            return bool(shape(x, y, xp) < 0)
    """
    assert codes_of(src) == ["TPU015"]


def test_tpu015_negative_static_facts_and_host_driver():
    # x.shape/len() are static facts, and a plain host driver (no jit,
    # no xp param) converting device results is the normal idiom
    src = """
        import jax

        @jax.jit
        def f(x):
            return x * len(x.shape)

        def driver(solver, args):
            result = solver(*args)
            return float(result.diff), int(result.iters)
    """
    assert codes_of(src) == []


def test_tpu015_negative_xp_module_itself_untainted():
    # calling int() on a non-parameter-derived value inside an xp-dual
    # fn is fine; so is arithmetic on xp itself
    src = """
        def fractions(x, xp, samples=16):
            k = int(samples) + 1
            return xp.linspace(0.0, 1.0, k) * x
    """
    assert codes_of(src) == []


def test_tpu015_suppression_comment():
    src = """
        import jax

        @jax.jit
        def f(x):
            return float(x)  # tpulint: disable=TPU015
    """
    assert codes_of(src, select=frozenset({"TPU015"})) == []


# -- TPU016: wall-clock deadlines -------------------------------------------


def test_tpu016_positive_time_time_in_comparison():
    src = """
        import time

        def expired(deadline):
            return time.time() > deadline

        def timed_out(t0, timeout):
            if time.time() - t0 > timeout:
                return True
    """
    assert codes_of(src, select=frozenset({"TPU016"})) == [
        "TPU016", "TPU016",
    ]


def test_tpu016_positive_binding_later_compared():
    src = """
        import time

        lease_s = 0.5
        deadline = time.time() + lease_s

        def check(now):
            return now > deadline
    """
    assert codes_of(src, select=frozenset({"TPU016"})) == ["TPU016"]


def test_tpu016_positive_self_attribute_deadline():
    src = """
        import time

        class Lease:
            def renew(self, lease_s):
                self.deadline = time.time() + lease_s

            def expired(self, now):
                return now > self.deadline
    """
    assert codes_of(src, select=frozenset({"TPU016"})) == ["TPU016"]


def test_tpu016_negative_lazy_init_guard_is_not_a_deadline():
    # the lazy-init idiom reads the timestamp's PRESENCE (`is None`),
    # not the clock's order — a record-only stamp stays silent; so do
    # equality/membership tests on names that also touch a wall read
    src = """
        import time

        class Stamps:
            t_start = None

            def ensure(self):
                if self.t_start is None:
                    self.t_start = time.time()

        seen = {}

        def note(rid):
            if rid in seen:
                return
            seen[rid] = time.time()
    """
    assert codes_of(src, select=frozenset({"TPU016"})) == []


def test_tpu016_negative_self_attr_scoped_to_the_class():
    # another class's same-named attribute is a different instance's
    # slot: a record-only wall-clock stamp in A must not be flagged
    # because unrelated B compares ITS self.t0 (a monotonic deadline)
    src = """
        import time

        class Stamper:
            def stamp(self):
                self.t0 = time.time()  # record-only

        class Deadline:
            def arm(self, budget):
                self.t0 = time.monotonic() + budget

            def expired(self):
                return time.monotonic() > self.t0
    """
    assert codes_of(src, select=frozenset({"TPU016"})) == []


def test_tpu016_negative_recorded_timestamps_and_monotonic():
    src = """
        import time

        record = {"t_admit_unix": time.time()}

        def stamp(records, rid):
            # a record-only wall-clock timestamp whose SUBSCRIPT index
            # appears in an unrelated membership comparison: the dict
            # item is not a deadline and must stay silent
            if rid in records:
                return
            records[rid] = {"t": time.time()}

        t0 = time.monotonic()

        def deadline_ok(timeout):
            # monotonic deadline arithmetic is the fix, never a finding
            return time.monotonic() - t0 > timeout
    """
    assert codes_of(src, select=frozenset({"TPU016"})) == []


def test_tpu016_suppression_comment():
    src = """
        import time

        def expired(deadline):
            return time.time() > deadline  # tpulint: disable=TPU016
    """
    assert codes_of(src, select=frozenset({"TPU016"})) == []


def test_suppression_is_per_code_not_blanket():
    src = """
        import jax

        def run(f, x):
            return jax.jit(f)(x)  # tpulint: disable=TPU004
    """
    # suppressing an unrelated code must not hide the TPU006 finding
    assert "TPU006" in codes_of(src)


def test_unknown_codes_are_rejected_not_silently_selected():
    # --select TPU999 must not turn the gate into a passing no-op
    import argparse

    from poisson_ellipse_tpu.lint.__main__ import _codes

    assert _codes("tpu001,TPU006") == frozenset({"TPU001", "TPU006"})
    with pytest.raises(argparse.ArgumentTypeError, match="TPU999"):
        _codes("TPU999")


def test_render_report_is_flake8_shaped():
    f = Finding(path="pkg/mod.py", line=3, col=5, code="TPU002", message="m")
    assert f.render() == "pkg/mod.py:3:5: TPU002 m"
    out = render_report([f, f], statistics=True)
    assert out.endswith("TPU002: 2")


@pytest.mark.slow
def test_cli_exits_nonzero_on_fixture(tmp_path):
    import os

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax.numpy as jnp\nx = jnp.zeros(3, dtype=jnp.float64)\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "poisson_ellipse_tpu.lint", str(bad)],
        capture_output=True,
        text=True,
        env=env,
        cwd=repo_root,
    )
    assert proc.returncode == 1
    assert "TPU001" in proc.stdout


# -- TPU017: reverse-mode autodiff over a while_loop solver entry -----------


def test_tpu017_positive_lambda_local_def_and_direct_reference():
    src = """
        import jax
        from poisson_ellipse_tpu.solver.pcg import pcg

        def bad_lambda(problem, a, b, rhs):
            return jax.grad(lambda p: pcg(problem, a * p, b, rhs).diff)(1.0)

        def bad_local_def(problem, a, b, rhs):
            def loss(p):
                return pcg_pipelined(problem, a * p, b, rhs).diff
            return jax.value_and_grad(loss)(2.0)

        g = jax.vjp(guarded_solve, 3.0)
    """
    assert codes_of(src) == ["TPU017", "TPU017", "TPU017"]


def test_tpu017_positive_partial_and_attribute_callee():
    src = """
        import functools
        import jax

        def bad_partial(solver, x):
            return jax.jacrev(functools.partial(
                lambda q: solver.pcg_batched(q).diff
            ))(x)
    """
    assert codes_of(src) == ["TPU017"]


def test_tpu017_positive_partial_of_direct_reference():
    # the documented hazard spelled exactly: a partial over an IMPORTED
    # solver entry (no local def to walk — the name itself must match)
    src = """
        import functools
        import jax
        from poisson_ellipse_tpu.solver.pcg import pcg

        def bad(problem, a, b, x):
            return jax.grad(functools.partial(pcg, problem, a, b))(x)

        g = jax.vjp(functools.partial(guarded_solve, 1), 2.0)
    """
    assert codes_of(src) == ["TPU017", "TPU017"]


def test_tpu017_negative_partial_of_benign_reference():
    src = """
        import functools
        import jax

        def ok(fn, x):
            return jax.grad(functools.partial(my_smooth_fn, 1))(x)
    """
    assert codes_of(src) == []


def test_tpu017_negative_implicit_wrapper_and_forward_mode():
    # routing through the implicit wrapper, forward-mode entries, and
    # opaque targets all stay silent — the conservative stance
    src = """
        import jax
        from poisson_ellipse_tpu.diff.adjoint import solve_implicit

        def good_wrapper(problem, params):
            def loss(p):
                u = solve_implicit(problem, p)
                return (u * u).sum()
            return jax.grad(loss)(params)

        def good_solver_obj(solver, params):
            return jax.grad(
                lambda p: solver.solve_operands(p, p, p).sum()
            )(params)

        def good_opaque(fn, x):
            return jax.grad(fn)(x)

        def good_forward(x):
            return jax.jvp(pcg, (x,), (1.0,))
    """
    assert codes_of(src) == []


def test_tpu017_config_knobs():
    # a project's own loop-solver name fires only when configured, and
    # a custom implicit wrapper name silences when configured
    src = """
        import jax
        g = jax.grad(lambda x: my_loop_solve(x).w)(1.0)
    """
    assert codes_of(src) == []
    assert codes_of(src, loop_solver_fns=("my_loop_solve",)) == ["TPU017"]
    routed = """
        import jax
        def f(x):
            def loss(p):
                my_wrapper(p)
                return my_loop_solve(p).w
            return jax.grad(loss)(x)
    """
    assert codes_of(routed, loop_solver_fns=("my_loop_solve",)) == ["TPU017"]
    assert codes_of(
        routed,
        loop_solver_fns=("my_loop_solve",),
        implicit_solver_fns=("my_wrapper",),
    ) == []


def test_tpu017_suppression_comment():
    src = """
        import jax
        g = jax.grad(lambda x: pcg(x).w)(1.0)  # tpulint: disable=TPU017
    """
    assert codes_of(src) == []


# -- TPU018: silent downcast into a reduction --------------------------------


def test_tpu018_positive_astype_and_narrow_arithmetic():
    src = """
        import jax.numpy as jnp

        def f(x, y):
            xb = x.astype(jnp.bfloat16)
            a = jnp.sum(xb)
            b = jnp.sum(xb * y.astype(jnp.bfloat16))
            c = jnp.dot(x.astype("bfloat16"), x.astype("bf16"))
            return a, b, c
    """
    assert codes_of(src) == ["TPU018", "TPU018", "TPU018"]


def test_tpu018_positive_name_propagation_and_half():
    src = """
        import jax.numpy as jnp

        def f(x):
            xb = x.astype(jnp.float16)
            scaled = xb * 2.0
            return jnp.einsum("i,i->", scaled, scaled)
    """
    assert codes_of(src) == ["TPU018"]


def test_tpu018_negative_wide_accumulator_routes():
    src = """
        import jax.numpy as jnp

        def upcast_first(x):
            xb = x.astype(jnp.bfloat16)
            return jnp.sum(xb.astype(jnp.float32))

        def wide_dtype_kwarg(x):
            return jnp.sum(x.astype("bf16"), dtype=jnp.float32)

        def mixed_fn_route(x):
            xb = x.astype(jnp.bfloat16)
            return apply_a_dots_mixed_pallas(xb)

        def rebound_wide(x):
            xb = x.astype(jnp.bfloat16)
            xb = xb.astype(jnp.float32)
            return jnp.sum(xb)
    """
    assert codes_of(src) == []


def test_tpu018_negative_opaque_and_wide_mixed():
    src = """
        import jax.numpy as jnp

        def opaque_dtype(x, dt):
            return jnp.sum(x.astype(dt))

        def promotes_wide(x, y):
            # bf16 * f32 promotes to f32 — not a narrow accumulation
            return jnp.sum(x.astype(jnp.bfloat16) * y)
    """
    assert codes_of(src) == []


def test_tpu018_config_knob_and_reduction_roots():
    src = """
        import jax.numpy as jnp

        def f(x):
            xb = x.astype(jnp.bfloat16)
            return my_reducer(xb)
    """
    # the project's own reduction wrapper, seen through reduction_roots
    assert codes_of(src, reduction_roots=("my_reducer",)) == ["TPU018"]
    # ... unless it is a sanctioned mixed accumulator
    assert codes_of(
        src, reduction_roots=("my_reducer",),
        mixed_accum_fns=("my_reducer",),
    ) == []


def test_tpu018_suppression_comment():
    src = """
        import jax.numpy as jnp
        s = jnp.sum(x.astype(jnp.bfloat16))  # tpulint: disable=TPU018
    """
    assert codes_of(src) == []


# -- TPU019: hardcoded tunable knobs -----------------------------------------


def test_tpu019_positive_literal_knob_at_builder_call():
    src = """
        solver, args, _ = build_solver(problem, "sstep", dtype, sstep_s=2)
        factory, cfg = make_precond(problem, dtype, "cheb", cheb_degree=16)
    """
    assert codes_of(src) == ["TPU019", "TPU019"]


def test_tpu019_positive_chunk_and_fcycle_knobs():
    src = """
        guarded = guarded_solve(problem, "xla", dtype, chunk=4)
        cyc = make_fcycle(ops, n_vcycles=3)
    """
    assert codes_of(src) == ["TPU019", "TPU019"]


def test_tpu019_negative_named_constant_and_variable():
    src = """
        DEGREE = 16
        factory, cfg = make_precond(problem, dtype, "cheb", cheb_degree=DEGREE)
        solver, args, _ = build_solver(problem, "sstep", dtype, sstep_s=args.s)
        cyc = make_fcycle(ops, n_vcycles=cfg.n_vcycles)
    """
    assert codes_of(src) == []


def test_tpu019_negative_default_config_and_tuner_exempt():
    src = """
        def default_fmg_config(problem):
            return make_fcycle(ops, n_vcycles=2, coarse_degree=24)

        def tune_candidates(problem):
            return [make_precond(problem, dtype, "cheb", cheb_degree=8)]
    """
    # the registry-definition sites: static defaults and candidate
    # sweeps are the one place a knob literal must live
    assert codes_of(src) == []


def test_tpu019_negative_non_builder_call_and_other_kwargs():
    src = """
        x = compute(problem, chunk=4)
        solver, args, _ = build_solver(problem, "xla", dtype, lanes=1)
    """
    # `compute` is not a tunable-fns callee; `lanes` is not a knob
    assert codes_of(src) == []


def test_tpu019_tunable_fns_config_knob():
    src = """
        s = my_builder(problem, cheb_degree=12)
    """
    assert codes_of(src) == []
    assert codes_of(src, tunable_fns=("my_builder",)) == ["TPU019"]


def test_tpu019_suppression_comment():
    src = """
        g = guarded_solve(problem, "xla", dtype, chunk=4)  # tpulint: disable=TPU019
    """
    assert codes_of(src) == []


# -- TPU020: raw collectives outside the communication layer ----------------


def lint_at(source: str, path: str, **cfg) -> list[str]:
    config = LintConfig(**cfg) if cfg else None
    return [
        f.code
        for f in lint_source(textwrap.dedent(source), path=path, config=config)
    ]


def test_tpu020_positive_raw_psum_outside_parallel():
    src = """
        import jax

        def reduce(x):
            return jax.lax.psum(x, "i")
    """
    findings = lint_source(textwrap.dedent(src), path="pkg/obs/history.py")
    assert [f.code for f in findings] == ["TPU020"]
    assert "psum" in findings[0].message


def test_tpu020_positive_aliased_lax_and_other_collectives():
    src = """
        from jax import lax

        def gather(x):
            return lax.all_gather(x, "lanes")

        def shift(x):
            return lax.ppermute(x, "px", [(0, 1)])
    """
    assert lint_at(src, "pkg/solver/engine.py") == ["TPU020", "TPU020"]


def test_tpu020_negative_licensed_parallel_layer():
    src = """
        import jax

        def halo(x):
            return jax.lax.ppermute(x, "px", [(0, 1)])
    """
    assert lint_at(src, "poisson_ellipse_tpu/parallel/halo.py") == []


def test_tpu020_negative_snippet_path_stays_silent():
    # every other rule's psum fixtures lint under "<snippet>"; TPU020
    # cannot judge an unknown layer, so it must not cry wolf there
    src = """
        import jax
        s = jax.lax.psum(x, "i")
    """
    assert codes_of(src) == []


def test_tpu020_negative_non_collective_lax_call():
    src = """
        import jax

        def f(x):
            return jax.lax.cumsum(jax.lax.exp(x))
    """
    assert lint_at(src, "pkg/obs/m.py") == []


def test_tpu020_collective_modules_config_knob():
    src = """
        import jax

        def reduce(x):
            return jax.lax.psum(x, "i")
    """
    cfg = {"collective_modules": ("*/comm/*",)}
    assert lint_at(src, "pkg/comm/reduce.py", **cfg) == []
    assert lint_at(src, "pkg/parallel/reduce.py", **cfg) == ["TPU020"]


def test_tpu020_suppression_comment():
    src = """
        import jax
        s = jax.lax.psum(x, "i")  # tpulint: disable=TPU020
    """
    assert lint_at(src, "pkg/obs/m.py") == []


# -- TPU021: wall-clock reads in lease/deadline arithmetic ------------------


def test_tpu021_positive_wall_clock_in_arithmetic():
    src = """
        import time
        import datetime

        def lease(lease_s):
            return time.time() + lease_s

        def age(started):
            return datetime.datetime.now() - started
    """
    assert codes_of(src, select=frozenset({"TPU021"})) == [
        "TPU021", "TPU021",
    ]


def test_tpu021_positive_binding_later_in_arithmetic():
    src = """
        import time

        def span(work):
            t0 = time.time()
            work()
            return time.time() - t0
    """
    # the t0 binding feeds arithmetic (prong 2) AND the closing read is
    # an arithmetic operand itself (prong 1)
    assert codes_of(src, select=frozenset({"TPU021"})) == [
        "TPU021", "TPU021",
    ]


def test_tpu021_positive_self_attribute_duration():
    src = """
        import time

        class Tracker:
            def start(self):
                self.t0 = time.time()

            def elapsed(self):
                return time.time() - self.t0
    """
    assert "TPU021" in codes_of(src, select=frozenset({"TPU021"}))


def test_tpu021_negative_record_only_timestamps():
    # the journal/trace idiom: a bare wall-clock read stored in a
    # record touches no arithmetic and stays silent
    src = """
        import time

        def record(rid, records):
            records[rid] = {"state": "admitted", "t_admit_unix": time.time()}

        def stamp():
            return {"unix_time": time.time()}
    """
    assert codes_of(src, select=frozenset({"TPU021"})) == []


def test_tpu021_negative_monotonic_arithmetic_is_fine():
    src = """
        import time

        def lease(lease_s):
            return time.monotonic() + lease_s

        def span(t0):
            return time.monotonic() - t0
    """
    assert codes_of(src, select=frozenset({"TPU021"})) == []


def test_tpu021_disjoint_from_tpu016_comparison_scope():
    # a read INSIDE an ordering comparison is TPU016's finding — TPU021
    # must stay silent there, and TPU016 must not fire on pure
    # arithmetic with no comparison (the scopes partition the hazard)
    compare_src = """
        import time

        def expired(deadline):
            return time.time() - deadline > 0
    """
    assert codes_of(compare_src, select=frozenset({"TPU021"})) == []
    assert codes_of(compare_src, select=frozenset({"TPU016"})) == ["TPU016"]
    arith_src = """
        import time

        def lease(lease_s):
            return time.time() + lease_s
    """
    assert codes_of(arith_src, select=frozenset({"TPU016"})) == []
    assert codes_of(arith_src, select=frozenset({"TPU021"})) == ["TPU021"]


def test_tpu021_wall_clock_fns_config_knob():
    src = """
        import clocklib

        def lease(lease_s):
            return clocklib.wall_now() + lease_s
    """
    assert codes_of(src, select=frozenset({"TPU021"})) == []
    assert codes_of(
        src,
        select=frozenset({"TPU021"}),
        wall_clock_fns=("clocklib.wall_now",),
    ) == ["TPU021"]


def test_tpu021_suppression_comment():
    src = """
        import time
        AGE = time.time() - 1700000000.0  # tpulint: disable=TPU021
    """
    assert lint_at(src, "pkg/obs/m.py") == []


# -- TPU022: unbounded dict caches ------------------------------------------


def test_tpu022_positive_module_level_cache():
    src = """
        _cache = {}

        def lookup(key, build):
            if key not in _cache:
                _cache[key] = build(key)
            return _cache[key]
    """
    assert codes_of(src, select=frozenset({"TPU022"})) == ["TPU022"]


def test_tpu022_positive_instance_cache_and_setdefault():
    src = """
        class Server:
            def __init__(self):
                self.result_cache = dict()

            def handle(self, req):
                return self.result_cache.setdefault(req.key, req.solve())
    """
    assert codes_of(src, select=frozenset({"TPU022"})) == ["TPU022"]


def test_tpu022_positive_dataclass_field_memo():
    src = """
        import dataclasses

        @dataclasses.dataclass
        class Ctx:
            memo: dict = dataclasses.field(default_factory=dict)

            def get(self, k, v):
                self.memo[k] = v
    """
    assert codes_of(src, select=frozenset({"TPU022"})) == ["TPU022"]


def test_tpu022_negative_evicting_caches_stay_silent():
    # every house eviction idiom silences the rule: LRU popitem,
    # clear-on-rebuild, del-by-key, and the drop-the-pool rebind
    src = """
        from collections import OrderedDict

        _cache = OrderedDict()

        def put(key, value, cap):
            _cache[key] = value
            while len(_cache) > cap:
                _cache.popitem(last=False)

        class Ctx:
            def __init__(self):
                self.pool_cache = {}

            def put(self, k, v):
                self.pool_cache[k] = v

            def degrade(self):
                self.pool_cache = {}
    """
    assert codes_of(src, select=frozenset({"TPU022"})) == []


def test_tpu022_negative_unnamed_dict_and_locals_stay_silent():
    # a dict not NAMED like a cache is a data structure, not a finding;
    # a function-local cache dies with the call and stays silent
    src = """
        _registry = {}

        def register(name, fn):
            _registry[name] = fn

        def solve_all(keys, build):
            cache = {}
            for k in keys:
                cache[k] = build(k)
            return cache
    """
    assert codes_of(src, select=frozenset({"TPU022"})) == []


def test_tpu022_suppression_comment():
    src = """
        _cache = {}  # tpulint: disable=TPU022

        def put(k, v):
            _cache[k] = v
    """
    assert lint_at(src, "pkg/runtime/m.py") == []


# -- suppression parsing: real comments only --------------------------------


def test_annotation_mention_inside_a_string_is_not_live():
    # suppressions are read from COMMENT tokens, not raw lines: a string
    # literal documenting the syntax is not a waiver for its own line
    src = """
        import jax.numpy as jnp
        HELP = "# tpulint: disable=TPU001"; x = jnp.zeros(3, dtype=jnp.float64)
    """
    assert codes_of(src) == ["TPU001"]


# -- suppression audit (TPU000) ---------------------------------------------


def audit_of(source: str, path: str = "<snippet>", **cfg) -> list[Finding]:
    config = LintConfig(**cfg) if cfg else None
    return audit_suppressions(textwrap.dedent(source), path=path, config=config)


def test_audit_used_suppression_is_silent():
    src = """
        import jax.numpy as jnp
        x = jnp.zeros(3, dtype=jnp.float64)  # tpulint: disable=TPU001
    """
    assert audit_of(src) == []


def test_audit_stale_suppression_fires():
    src = """
        import jax.numpy as jnp
        x = jnp.zeros(3, dtype=jnp.float32)  # tpulint: disable=TPU001
    """
    findings = audit_of(src)
    assert [f.code for f in findings] == [AUDIT_CODE]
    assert "TPU001" in findings[0].message


def test_audit_standalone_covers_the_next_line():
    src = """
        import jax.numpy as jnp
        # tpulint: disable=TPU001
        x = jnp.zeros(3, dtype=jnp.float64)
    """
    assert audit_of(src) == []


def test_audit_is_per_code_within_one_comment():
    src = """
        import jax.numpy as jnp
        x = jnp.zeros(3, dtype=jnp.float64)  # tpulint: disable=TPU001,TPU006
    """
    findings = audit_of(src)
    assert [f.code for f in findings] == [AUDIT_CODE]
    assert "TPU006" in findings[0].message  # TPU001 is earning its keep


def test_audit_unknown_code_always_flagged():
    src = """
        x = 1  # tpulint: disable=TPU999
    """
    findings = audit_of(src)
    assert [f.code for f in findings] == [AUDIT_CODE]
    assert "TPU999" in findings[0].message


def test_audit_disable_all_judged_as_a_unit():
    used = """
        import jax.numpy as jnp
        x = jnp.zeros(3, dtype=jnp.float64)  # tpulint: disable=all
    """
    assert audit_of(used) == []
    stale = """
        x = 1  # tpulint: disable=all
    """
    assert [f.code for f in audit_of(stale)] == [AUDIT_CODE]


def test_audit_inactive_rule_is_not_judged():
    src = """
        import jax.numpy as jnp
        x = jnp.zeros(3, dtype=jnp.float32)  # tpulint: disable=TPU001
    """
    # the audit cannot re-run an ignored rule, so it cannot call the
    # annotation stale — it stays silent rather than guessing
    assert audit_of(src, ignore=frozenset({"TPU001"})) == []


def test_audit_ignores_doc_text_mentions():
    src = '''
        """Suppress with ``# tpulint: disable=TPU999`` plus a reason."""
        x = 1
    '''
    assert audit_of(src) == []


# -- SARIF round-trip -------------------------------------------------------


def test_sarif_round_trip_preserves_findings():
    from poisson_ellipse_tpu.analysis.sarif import (
        findings_to_sarif,
        sarif_findings,
    )

    findings = [
        Finding(path="a.py", line=3, col=5, code="TPU002", message="m1"),
        Finding(path="b.py", line=1, col=1, code="TPU006", message="m2"),
    ]
    doc = findings_to_sarif(
        findings, rules={code: r.summary for code, r in RULES.items()}
    )
    assert doc["version"] == "2.1.0"
    rule_ids = [r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]]
    assert rule_ids == sorted(RULES)
    # the reader inverts the writer exactly (JSON-string input too)
    back = sarif_findings(json.dumps(doc))
    assert back == [
        (f.path, f.code, f.line, f.col, f.message) for f in findings
    ]


# -- baseline: accept then ratchet ------------------------------------------


def test_baseline_accept_then_ratchet(tmp_path):
    bl = str(tmp_path / "baseline.json")
    old = Finding(path="a.py", line=1, col=1, code="TPU001", message="m")
    new = Finding(path="b.py", line=2, col=1, code="TPU006", message="m")

    # adoption: a missing file swallows today's debt and is written
    kept, note = apply_baseline(bl, [old], [])
    assert kept == [] and "accepted 1" in note
    assert json.load(open(bl))["accepted"] == [finding_key(old)]

    # accepted keys stay silent; anything new fails through
    kept, note = apply_baseline(bl, [old, new], [])
    assert kept == [new] and note is None

    # a fixed entry is NOT shed while the run still has new findings
    kept, note = apply_baseline(bl, [new], [])
    assert kept == [new] and "deferred" in note
    assert json.load(open(bl))["accepted"] == [finding_key(old)]

    # ... and IS shed once the run is otherwise clean
    kept, note = apply_baseline(bl, [], [])
    assert kept == [] and "ratcheted 1" in note
    assert json.load(open(bl))["accepted"] == []


# -- CLI: --format sarif / --baseline / --audit-suppressions ----------------


def test_cli_format_sarif(tmp_path, capsys):
    from poisson_ellipse_tpu.lint.__main__ import main

    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax.numpy as jnp\nx = jnp.zeros(3, dtype=jnp.float64)\n"
    )
    rc = main([str(bad), "--format", "sarif"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert [r["ruleId"] for r in doc["runs"][0]["results"]] == ["TPU001"]


def test_cli_audit_mode(tmp_path, capsys):
    from poisson_ellipse_tpu.lint.__main__ import main

    stale = tmp_path / "stale.py"
    stale.write_text("x = 1  # tpulint: disable=TPU001\n")
    rc = main([str(stale), "--audit-suppressions"])
    out = capsys.readouterr().out
    assert rc == 1 and AUDIT_CODE in out

    stale.write_text("x = 1\n")
    rc = main([str(stale), "--audit-suppressions"])
    out = capsys.readouterr().out
    assert rc == 0 and "0 stale suppressions" in out


def test_cli_baseline_flow(tmp_path, capsys):
    from poisson_ellipse_tpu.lint.__main__ import main

    bad = tmp_path / "bad.py"
    bl = tmp_path / "bl.json"
    bad.write_text(
        "import jax.numpy as jnp\nx = jnp.zeros(3, dtype=jnp.float64)\n"
    )
    assert main([str(bad), "--baseline", str(bl)]) == 0  # adoption run
    capsys.readouterr()
    assert main([str(bad), "--baseline", str(bl)]) == 0  # accepted debt
    capsys.readouterr()
    bad.write_text("import jax.numpy as jnp\nx = jnp.zeros(3)\n")
    assert main([str(bad), "--baseline", str(bl)]) == 0  # clean: ratchets
    assert json.load(open(bl))["accepted"] == []
