"""The replicated serving fleet (`fleet/`) — ISSUE 12.

The contracts this file pins:

- lease semantics under a hand-cranked monotonic clock: renewal at
  chunk boundaries, expiry declared by the ROUTER's clock, the zombie
  (hung process, lapsed lease) fenced before its journal is replayed;
- fencing at the journal choke point: a stale token's write raises
  ``StaleLeaseError`` BEFORE the record is touched, is trace-evented
  (``fleet:stale-write-rejected``) and counted, and every flushed
  snapshot embeds the writing token;
- handoff preserves the remaining-deadline budget (the journal's
  ``deadline_left_s`` contract, unchanged across the replica boundary)
  and never terminally sheds on capacity (backlog waves);
- a handed-off request's solution is bit-identical to the same request
  served by an uninterrupted scheduler — the kill/handoff machinery
  must not perturb one bit of the answer;
- routing: warm compile-bucket affinity that still load-spreads,
  per-replica backpressure aggregated with the minimum retry hint,
  hedging around suspect leases, fleet-level duplicate-id refusal;
- all-replicas-down is the classified ``FleetUnavailableError``
  (exit 9) — loud, carrying ``retry_after_s``, never a hang;
- graceful drain: ``begin_drain`` refuses new work with a redirectable
  shed and finishes everything admitted; SIGTERM on ``harness serve``
  drains (exit 0, trace tail flushed) instead of dying mid-stream;
- the chaos invariant triple (zero lost / zero double / all
  classified) holds across replica kill, kill-during-handoff, and
  zombie resurrection (stale write observed and rejected),
  deterministically per seed.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from poisson_ellipse_tpu.fleet import (
    FenceAuthority,
    FleetRouter,
    StaleLeaseError,
)
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import metrics as obs_metrics
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.resilience.errors import FleetUnavailableError
from poisson_ellipse_tpu.resilience.faultinject import (
    FaultPlan,
    lease_clock_skew,
    replica_hang,
)
from poisson_ellipse_tpu.serve import RequestJournal, ServeRequest, run_chaos
from poisson_ellipse_tpu.serve.scheduler import Scheduler


class FakeClock:
    """Hand-cranked monotonic clock (the test_serve idiom): lease and
    deadline semantics become deterministic instead of racing the
    host."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def make_router(tmp_path, replicas=2, clock=None, **kw):
    kw.setdefault("lanes", 2)
    kw.setdefault("chunk", 8)
    kw.setdefault("backoff_base_s", 0.001)
    kw.setdefault("keep_solutions", False)
    router_kw = {}
    if clock is not None:
        router_kw["clock"] = clock
        router_kw["idle"] = clock.advance
    return FleetRouter(
        replicas=replicas, journal_dir=str(tmp_path / "journals"),
        **router_kw, **kw,
    )


# -- fencing: the zero-double choke point ------------------------------------


def test_stale_token_write_rejected_and_trace_evented(tmp_path):
    authority = FenceAuthority()
    token = authority.issue(0)
    journal = RequestJournal(tmp_path / "j.json", fence=token)
    req = ServeRequest(problem=Problem(M=10, N=10), request_id="r0")
    journal.record_admit(req)  # valid token: lands
    path = tmp_path / "fence.jsonl"
    obs_trace.start(str(path))
    stale_before = obs_metrics.REGISTRY.counter(
        obs_metrics.FLEET_STALE_WRITES_TOTAL
    ).value
    try:
        authority.fence(0)
        with pytest.raises(StaleLeaseError):
            journal.record_outcome("r0", "completed")
        with pytest.raises(StaleLeaseError):
            journal.record_admit(
                ServeRequest(problem=Problem(M=10, N=10), request_id="r1")
            )
    finally:
        obs_trace.stop()
    # the rejected write never touched the record: r0 is still live
    # (admitted, unfinished) and r1 was never admitted
    reloaded = RequestJournal(tmp_path / "j.json")
    assert [r.request_id for r in reloaded.unfinished(0.0)] == ["r0"]
    # trace-evented + counted — the drill is observable, not silent
    names = [r["name"] for r in obs_trace.read_jsonl(str(path))]
    assert names.count("fleet:stale-write-rejected") == 2
    assert obs_trace.validate_file(str(path)) == []
    assert obs_metrics.REGISTRY.counter(
        obs_metrics.FLEET_STALE_WRITES_TOTAL
    ).value == stale_before + 2


def test_journal_snapshot_embeds_the_fencing_token(tmp_path):
    import json

    authority = FenceAuthority()
    token = authority.issue(3)
    journal = RequestJournal(tmp_path / "j.json", fence=token)
    journal.record_admit(
        ServeRequest(problem=Problem(M=10, N=10), request_id="r0")
    )
    with open(tmp_path / "j.json", encoding="utf-8") as fh:
        snap = json.load(fh)
    assert snap["fence_token"] == token.value == "r3:e1"
    # and the loaded journal surfaces the writing epoch
    assert RequestJournal(tmp_path / "j.json").loaded_fence_token == "r3:e1"


def test_reissue_stales_the_previous_incarnation(tmp_path):
    # a restarted replica under the same id mints a NEW epoch; the dead
    # incarnation's token is stale from its first write
    authority = FenceAuthority()
    old = authority.issue(0)
    new = authority.issue(0)
    assert old.stale and not new.stale
    journal = RequestJournal(tmp_path / "j.json", fence=old)
    with pytest.raises(StaleLeaseError):
        journal.record_admit(
            ServeRequest(problem=Problem(M=10, N=10), request_id="r0")
        )


# -- leases ------------------------------------------------------------------


def test_lease_expiry_declares_dead_fences_and_hands_off(tmp_path):
    clock = FakeClock()
    router = make_router(
        tmp_path, replicas=2, clock=clock, lease_s=1.0, lanes=1, chunk=4,
    )
    hang = replica_hang(delay_s=float("inf"), at_request=0, replica=0)
    router.faults.faults.append(hang)
    for i in range(3):
        assert router.submit(Problem(M=10, N=10),
                             request_id=f"r{i}") is None
    # the hang fault fired at the first arrival: replica 0 stops
    # heartbeating while its process object lives
    rep0 = router.replicas[0]
    assert rep0.hung(clock())
    expired_before = obs_metrics.REGISTRY.counter(
        obs_metrics.LEASE_EXPIRY_TOTAL
    ).value
    # advance in sub-lease increments (heartbeats are continuous in the
    # world this simulates): the healthy replica renews at every step's
    # sweep, the hung one never does — only IT crosses its deadline
    for _ in range(3):
        clock.advance(0.6)
        router.step()
    assert not rep0.live and rep0.token.stale
    assert router.replicas[1].live
    assert router.handoffs == 1
    assert obs_metrics.REGISTRY.counter(
        obs_metrics.LEASE_EXPIRY_TOTAL
    ).value == expired_before + 1
    # the survivor finishes everything the dead replica owned
    results = router.drain()
    assert {results[f"r{i}"].outcome for i in range(3)} == {"completed"}
    # zombie resurrection: the hung replica's own loop comes back and
    # every completion it attempts is rejected at its fenced journal
    rep0.hung_until = 0.0
    with pytest.raises(StaleLeaseError):
        for _ in range(200):
            if not rep0.resurrect_step():
                break
    # nothing the zombie did after the fence is visible anywhere
    assert not rep0.scheduler.results


def test_drain_waits_out_a_hung_replicas_lease(tmp_path):
    # drain with work stuck behind a hung replica must IDLE toward the
    # lease expiry (then fence + hand off), not hot-spin into the
    # max_steps backstop before the expiry can land
    clock = FakeClock()
    router = make_router(
        tmp_path, replicas=2, clock=clock, lease_s=1.0, lanes=1, chunk=4,
        faults=FaultPlan(
            replica_hang(delay_s=float("inf"), at_request=0, replica=0)
        ),
    )
    for i in range(2):
        assert router.submit(Problem(M=10, N=10),
                             request_id=f"h{i}") is None
    results = router.drain()
    assert {results[f"h{i}"].outcome for i in range(2)} == {"completed"}
    assert not router.replicas[0].live and router.handoffs == 1


def test_lease_clock_skew_fences_the_skewed_replica(tmp_path):
    # the NTP-step drill: a skewed replica's renewals land short, so it
    # reads as expired under the router clock while perfectly healthy —
    # it must be fenced and its work handed off, not co-owned
    clock = FakeClock()
    router = make_router(
        tmp_path, replicas=2, clock=clock, lease_s=1.0, lanes=1, chunk=4,
        faults=FaultPlan(
            lease_clock_skew(skew_s=5.0, at_request=0, replica=0)
        ),
    )
    for i in range(2):
        assert router.submit(Problem(M=10, N=10),
                             request_id=f"s{i}") is None
    router.step()  # skewed renewal: deadline lands 4s in the past
    clock.advance(0.01)
    router.step()
    rep0 = router.replicas[0]
    assert not rep0.live and rep0.token.stale
    results = router.drain()
    assert {results[f"s{i}"].outcome for i in range(2)} == {"completed"}


# -- handoff -----------------------------------------------------------------


def test_handoff_preserves_remaining_deadline_budget(tmp_path):
    clock = FakeClock(100.0)
    router = make_router(
        tmp_path, replicas=2, clock=clock, lanes=1, chunk=4,
    )
    assert router.submit(
        Problem(M=10, N=10), deadline_s=60.0, request_id="budget"
    ) is None
    clock.advance(5.0)
    # find the owner and kill it: the handoff replays the journaled
    # remaining-at-admission budget from the handoff clock (the PR 7
    # replay contract, unchanged across the replica boundary)
    owner = next(
        rep for rep in router.replicas
        if rep.scheduler._knows("budget")
    )
    router.kill_replica(owner.replica_id)
    survivor = next(rep for rep in router.replicas if rep.live)
    assert survivor.scheduler._knows("budget")
    req = survivor.scheduler.queue.pop_ready(clock())
    assert req is not None and req.request_id == "budget"
    assert req.deadline == pytest.approx(clock() + 60.0, abs=1.0)


def test_handed_off_solution_bit_identical_to_uninterrupted(tmp_path):
    # the kill/handoff machinery must not perturb one bit of the
    # answer: the same request through (a) a fleet whose owner dies
    # mid-solve and (b) a plain uninterrupted scheduler must agree
    # exactly (both re-run from a clean carry on the same embedding)
    router = make_router(
        tmp_path, replicas=2, lanes=1, chunk=2, keep_solutions=True,
    )
    assert router.submit(Problem(M=12, N=12), request_id="bits") is None
    router.step()  # a couple of chunks in flight on the owner
    owner = next(
        rep for rep in router.replicas if rep.scheduler._knows("bits")
    )
    router.kill_replica(owner.replica_id)
    res = router.drain()["bits"]
    assert res.outcome == "completed"

    plain = Scheduler(lanes=1, chunk=2, keep_solutions=True)
    plain.submit(Problem(M=12, N=12), request_id="bits")
    ref = plain.drain()["bits"]
    assert ref.outcome == "completed"
    assert res.iters == ref.iters
    assert np.array_equal(res.w, ref.w), (
        "handed-off solution departs bitwise from the uninterrupted one"
    )


def test_kill_with_requests_in_flight_adopts_them(tmp_path):
    router = make_router(tmp_path, replicas=3, lanes=2, chunk=2)
    for i in range(6):
        assert router.submit(Problem(M=10, N=10),
                             request_id=f"k{i}") is None
    router.step()
    router.kill_replica(0)
    assert router.handoffs == 1 and router.adopted_total >= 1
    results = router.drain()
    assert {results[f"k{i}"].outcome for i in range(6)} == {"completed"}
    # handoff latency was measured
    hist = obs_metrics.REGISTRY.histogram(
        obs_metrics.HANDOFF_LATENCY_SECONDS
    )
    assert hist.count >= 1


# -- routing -----------------------------------------------------------------


def test_affinity_prefers_warm_replica_until_lanes_fill(tmp_path):
    from poisson_ellipse_tpu.runtime.compile_cache import warm_affinity_key

    router = make_router(tmp_path, replicas=2, lanes=2, chunk=4)
    key = warm_affinity_key(10, 10, "weighted")
    assert router.submit(Problem(M=10, N=10), request_id="a0") is None
    router.step()  # replica 0 builds the bucket: it is now warm
    warm = [rep for rep in router.replicas if key in rep.warm_keys()]
    assert [r.replica_id for r in warm] == [0]
    # with a free lane left, the warm replica keeps winning...
    assert router.submit(Problem(M=10, N=10), request_id="a1") is None
    assert router.replicas[0].scheduler._knows("a1")
    # ...but once its lanes fill, the cold replica with free lanes wins
    # (affinity must not defeat scaling)
    assert router.submit(Problem(M=10, N=10), request_id="a2") is None
    assert router.replicas[1].scheduler._knows("a2")


def test_all_replicas_shed_returns_min_retry_hint(tmp_path):
    router = make_router(tmp_path, replicas=2, lanes=1,
                         queue_capacity=1)
    for i in range(2):  # one queued request fills each replica's slot
        assert router.submit(Problem(M=10, N=10),
                             request_id=f"fill{i}") is None
    shed = router.submit(Problem(M=10, N=10), request_id="over")
    assert shed is not None and shed.outcome == "shed"
    assert shed.detail == "fleet-backpressure"
    assert shed.retry_after_s is not None and shed.retry_after_s > 0
    results = router.drain()
    assert results["over"].outcome == "shed"
    done = [r for r in results.values() if r.outcome == "completed"]
    assert len(done) == 2


def test_probe_shed_leaves_no_record_on_the_refusing_replica(tmp_path):
    # a replica that sheds while the router probes candidates answered
    # a ROUTING question, not a lifecycle one: no terminal record may
    # linger there, or a later harvest would merge a stale shed over
    # the completion the next replica delivers
    router = make_router(tmp_path, replicas=2, lanes=1,
                         queue_capacity=1)
    assert router.submit(Problem(M=10, N=10), request_id="p0") is None
    # replica holding p0 is full (capacity 1): p1 probes it, gets shed,
    # lands on the other replica
    assert router.submit(Problem(M=10, N=10), request_id="p1") is None
    assert all(
        "p1" not in rep.scheduler.results for rep in router.replicas
    )
    results = router.drain()
    assert results["p0"].outcome == "completed"
    assert results["p1"].outcome == "completed"
    assert router.double_delivered == []


def test_anonymous_all_shed_is_recorded_once_under_a_real_id(tmp_path):
    # the harness submits without ids and discards the return: the
    # rejection must still land in fleet accounting exactly once,
    # under one real id — not vanish while each probed replica logs a
    # phantom shed under its own uuid
    router = make_router(tmp_path, replicas=2, lanes=1,
                         queue_capacity=1)
    for _ in range(2):
        assert router.submit(Problem(M=10, N=10)) is None
    shed = router.submit(Problem(M=10, N=10))  # no request_id
    assert shed is not None and shed.detail == "fleet-backpressure"
    assert shed.request_id and shed.request_id != "rejected"
    results = router.drain()
    sheds = [r for r in results.values() if r.outcome == "shed"]
    assert len(sheds) == 1 and sheds[0].request_id == shed.request_id
    assert sum(1 for r in results.values()
               if r.outcome == "completed") == 2


def test_harvest_ledger_catches_cross_replica_double_delivery(tmp_path):
    # the zero-double detector must live where deliveries pass exactly
    # once: forge the fencing-failure shape (two replicas both deliver
    # a terminal record for one id) and the ledger must name it
    from poisson_ellipse_tpu.serve.request import ServeResult

    router = make_router(tmp_path, replicas=2, lanes=1)
    for rep in router.replicas:
        rep.scheduler.results["forged"] = ServeResult(
            request_id="forged", outcome="completed",
        )
    router.harvest()
    assert router.double_delivered == ["forged"]


def test_duplicate_request_id_refused_fleet_wide(tmp_path):
    router = make_router(tmp_path, replicas=2, lanes=1)
    assert router.submit(Problem(M=10, N=10), request_id="dup") is None
    refused = router.submit(Problem(M=12, N=12), request_id="dup")
    assert refused is not None and refused.outcome == "shed"
    assert refused.detail == "duplicate-request-id"
    # the original is untouched and completes exactly once
    results = router.drain()
    assert results["dup"].outcome == "completed"


def test_retry_of_request_completed_by_dead_replica_is_refused(tmp_path):
    # the client-retry-after-owner-crash race: replica 0 completes X
    # and is then killed; the results were collected (evicted); a
    # client retry of X must be refused as a duplicate — the DEAD
    # replica's journal is what remembers the delivery, and consulting
    # it is what keeps the retry from double-completing on a survivor
    router = make_router(tmp_path, replicas=2, lanes=1)
    assert router.submit(Problem(M=10, N=10), request_id="retry") is None
    router.drain()
    router.collect()  # results evicted, the harness-loop shape
    owner = next(
        rep for rep in router.replicas
        if rep.scheduler.owns_request("retry")
    )
    router.kill_replica(owner.replica_id)
    refused = router.submit(Problem(M=10, N=10), request_id="retry")
    assert refused is not None and refused.detail == "duplicate-request-id"
    # and nothing new was admitted anywhere
    assert all(
        not rep.scheduler.queue.holds("retry") for rep in router.replicas
    )


def test_fleet_backpressure_shed_allows_resubmission(tmp_path):
    # "shed ... safe to resubmit after retry_after_s" must hold at the
    # ROUTER's door too: a fleet-backpressure rejection is not
    # ownership, and the resubmission supersedes it
    router = make_router(tmp_path, replicas=2, lanes=1,
                         queue_capacity=1)
    for i in range(2):
        assert router.submit(Problem(M=10, N=10),
                             request_id=f"fill{i}") is None
    shed = router.submit(Problem(M=10, N=10), request_id="again")
    assert shed is not None and shed.detail == "fleet-backpressure"
    router.drain()  # capacity frees up
    assert router.submit(Problem(M=10, N=10), request_id="again") is None
    assert router.drain()["again"].outcome == "completed"


def test_death_during_shutdown_adopts_into_draining_survivor(tmp_path):
    # shutdown races a death: the dead replica's journaled work must be
    # adopted by a DRAINING survivor (already-acknowledged fleet work is
    # not a new admission) — never silently abandoned
    clock = FakeClock()
    router = make_router(
        tmp_path, replicas=2, clock=clock, lease_s=1.0, lanes=1, chunk=4,
    )
    for i in range(3):
        assert router.submit(Problem(M=10, N=10),
                             request_id=f"x{i}") is None
    for rep in router.replicas:
        rep.begin_drain()
    owner = next(
        rep for rep in router.replicas
        if rep.scheduler.owns_request("x0")
    )
    router.kill_replica(owner.replica_id)
    results = router.drain()
    assert {results[f"x{i}"].outcome for i in range(3)} == {"completed"}


def test_all_replicas_down_is_classified_exit_9_never_a_hang(tmp_path):
    router = make_router(tmp_path, replicas=2, lanes=1)
    assert router.submit(Problem(M=10, N=10), request_id="r0") is None
    router.drain()
    router.kill_replica(0)
    router.kill_replica(1)
    with pytest.raises(FleetUnavailableError) as exc:
        router.submit(Problem(M=10, N=10), request_id="r1")
    assert exc.value.exit_code == 9
    assert exc.value.retry_after_s is not None
    assert exc.value.classification == "fleet-unavailable"


# -- drain -------------------------------------------------------------------


def test_begin_drain_sheds_new_and_finishes_in_flight(tmp_path):
    router = make_router(tmp_path, replicas=2, lanes=1)
    for i in range(3):
        assert router.submit(Problem(M=10, N=10),
                             request_id=f"d{i}") is None
    router.step()
    results = router.shutdown()
    assert {results[f"d{i}"].outcome for i in range(3)} == {"completed"}
    # every replica now refuses new work with a redirectable shed, so
    # the fleet-level answer is the classified exit 9
    with pytest.raises(FleetUnavailableError):
        router.submit(Problem(M=10, N=10), request_id="late")


def test_draining_scheduler_shed_is_not_recorded_as_terminal():
    # the drain shed is a redirect for the router, not a lifecycle
    # event: recording it would double-count the id once another
    # replica completes the request
    sched = Scheduler(lanes=1, chunk=8, keep_solutions=False)
    sched.begin_drain()
    shed = sched.submit(Problem(M=10, N=10), request_id="redirected")
    assert shed is not None and shed.outcome == "shed"
    assert shed.detail == "draining"
    assert shed.retry_after_s is not None
    assert "redirected" not in sched.results
    assert len(sched.queue) == 0


# -- chaos: the fleet invariant triple ---------------------------------------


def test_fleet_chaos_replica_kill_zero_lost_zero_double(tmp_path):
    report = run_chaos(
        n_requests=12, seed=7, replicas=3, chunk=2,
        journal_path=os.path.join(tmp_path, "journals"),
        replica_kill=4,
    )
    assert report.ok, (
        f"lost={report.lost} doubled={report.double_completed} "
        f"unclassified={report.unclassified}"
    )
    assert report.killed and report.handoffs >= 1
    assert report.replicas == 3
    assert sum(report.counts.values()) == 12
    # the injected per-request faults REALLY fired (the plan is shared
    # fleet-wide: nan + oom + the kill = 3) on whichever replica hosted
    # their victims, and cost them nothing
    assert report.faults_fired == 3
    assert report.outcomes["chaos-0002"] == "completed"
    assert report.outcomes["chaos-0005"] == "completed"


def test_fleet_chaos_is_seed_deterministic(tmp_path):
    kw = dict(n_requests=10, seed=3, replicas=2, chunk=2, replica_kill=3)
    r1 = run_chaos(journal_path=os.path.join(tmp_path, "c1"), **kw)
    r2 = run_chaos(journal_path=os.path.join(tmp_path, "c2"), **kw)
    assert r1.ok and r2.ok
    assert r1.outcomes == r2.outcomes
    assert r1.counts == r2.counts
    assert r1.handoffs == r2.handoffs


def test_fleet_chaos_kill_during_handoff(tmp_path):
    # the adopting survivor dies at the same boundary the first handoff
    # finished on: journal-first adoption is what keeps the adopted
    # requests alive through the second kill
    report = run_chaos(
        n_requests=12, seed=5, replicas=3, chunk=2,
        journal_path=os.path.join(tmp_path, "journals"),
        replica_kill=4, kill_during_handoff=True,
    )
    assert report.ok, (
        f"lost={report.lost} doubled={report.double_completed}"
    )
    assert report.handoffs >= 2
    assert sum(report.counts.values()) == 12


def test_fleet_chaos_refuses_single_scheduler_drills_loudly(tmp_path):
    # drills the fleet path cannot run must be refused, never silently
    # dropped into a vacuously-green invariant report
    for kw in (
        dict(kill_after=3),
        dict(mesh_kill_request=3),
        dict(malformed_request=3),
        dict(degenerate_request=3),
    ):
        with pytest.raises(ValueError, match="single-scheduler"):
            run_chaos(
                n_requests=8, seed=0, replicas=2,
                journal_path=os.path.join(tmp_path, "journals"), **kw,
            )


def test_fleet_chaos_kill_during_handoff_needs_three_replicas(tmp_path):
    # with 2 replicas the double kill is the total-loss drill, not the
    # handoff drill — refused loudly instead of crashing mid-stream
    with pytest.raises(ValueError, match="replicas >= 3"):
        run_chaos(
            n_requests=8, seed=0, replicas=2,
            journal_path=os.path.join(tmp_path, "journals"),
            replica_kill=3, kill_during_handoff=True,
        )


def test_fleet_chaos_zombie_resurrection_stale_write_rejected(tmp_path):
    report = run_chaos(
        n_requests=10, seed=4, replicas=2, chunk=2,
        journal_path=os.path.join(tmp_path, "journals"),
        zombie=True, nan_request=None, oom_request=None,
    )
    assert report.ok, (
        f"lost={report.lost} doubled={report.double_completed}"
    )
    assert report.zombie_drill
    # the fenced stale write was OBSERVED and REJECTED — the zero-double
    # pin is a mechanism, not an accident of timing
    assert report.stale_writes_rejected >= 1
    assert report.handoffs >= 1


# -- CLI ---------------------------------------------------------------------


def test_cli_fleet_subcommand(tmp_path, capsys):
    import json

    from poisson_ellipse_tpu.harness.__main__ import main

    trace = tmp_path / "fleet.jsonl"
    rc = main([
        "fleet", "--replicas", "2", "--requests", "6",
        "--grids", "10x10", "--rate", "1000", "--chunk", "4",
        "--kill-replica-at", "2",
        "--trace", str(trace), "--json",
    ])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["outcomes"] == {"completed": 6}
    assert rec["replicas"] == 2
    assert rec["handoffs"] >= 1
    assert rec["live_replicas"] == [1]
    assert obs_trace.validate_file(str(trace)) == []
    names = {r["name"] for r in obs_trace.read_jsonl(str(trace))}
    assert "fleet:replica-kill" in names and "fleet_report" in names


def test_cli_fleet_rejects_bad_args(capsys):
    from poisson_ellipse_tpu.harness.__main__ import main

    assert main(["fleet", "--replicas", "0"]) == 2
    assert main(["fleet", "--requests", "0"]) == 2
    assert main(["fleet", "--rate", "0"]) == 2


# -- SIGTERM graceful shutdown (subprocess) ----------------------------------


@pytest.mark.skipif(os.name == "nt", reason="POSIX signals")
def test_sigterm_drains_serve_gracefully(tmp_path):
    """SIGTERM on `harness serve` must drain (stop admitting, finish
    in-flight, flush the trace) and exit 0 — not die mid-stream with
    the trace tail lost."""
    import signal
    import subprocess
    import sys
    import time

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    trace = tmp_path / "sigterm.jsonl"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "poisson_ellipse_tpu.harness", "serve",
            "--requests", "500", "--grids", "10x10", "--rate", "3",
            "--journal", str(tmp_path / "j.json"),
            "--trace", str(trace), "--json",
        ],
        env=env, cwd=repo_root,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        # wait until the stream is actually running (first admit on the
        # trace) so the handler is installed before the signal lands
        deadline = time.monotonic() + 120.0
        started = False
        while time.monotonic() < deadline:
            if trace.exists() and "serve:admit" in trace.read_text():
                started = True
                break
            if proc.poll() is not None:
                break
            time.sleep(0.25)
        assert started, (
            f"serve never started (rc={proc.poll()}): "
            f"{proc.stderr.read() if proc.poll() is not None else ''}"
        )
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, f"SIGTERM drain exited {proc.returncode}: {err}"
    # the drain really ran: the trace tail holds the drain event AND
    # the final report (flushed, not lost with a hard kill)
    assert obs_trace.validate_file(str(trace)) == []
    names = [r["name"] for r in obs_trace.read_jsonl(str(trace))]
    assert "serve:sigterm-drain" in names
    assert "serve:drain-begin" in names
    assert "serve_report" in names
    import json

    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["drained_on_sigterm"] is True
