"""The replicated serving fleet (`fleet/`) — ISSUE 12.

The contracts this file pins:

- lease semantics under a hand-cranked monotonic clock: renewal at
  chunk boundaries, expiry declared by the ROUTER's clock, the zombie
  (hung process, lapsed lease) fenced before its journal is replayed;
- fencing at the journal choke point: a stale token's write raises
  ``StaleLeaseError`` BEFORE the record is touched, is trace-evented
  (``fleet:stale-write-rejected``) and counted, and every flushed
  snapshot embeds the writing token;
- handoff preserves the remaining-deadline budget (the journal's
  ``deadline_left_s`` contract, unchanged across the replica boundary)
  and never terminally sheds on capacity (backlog waves);
- a handed-off request's solution is bit-identical to the same request
  served by an uninterrupted scheduler — the kill/handoff machinery
  must not perturb one bit of the answer;
- routing: warm compile-bucket affinity that still load-spreads,
  per-replica backpressure aggregated with the minimum retry hint,
  hedging around suspect leases, fleet-level duplicate-id refusal;
- all-replicas-down is the classified ``FleetUnavailableError``
  (exit 9) — loud, carrying ``retry_after_s``, never a hang;
- graceful drain: ``begin_drain`` refuses new work with a redirectable
  shed and finishes everything admitted; SIGTERM on ``harness serve``
  drains (exit 0, trace tail flushed) instead of dying mid-stream;
- the chaos invariant triple (zero lost / zero double / all
  classified) holds across replica kill, kill-during-handoff, and
  zombie resurrection (stale write observed and rejected),
  deterministically per seed.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from poisson_ellipse_tpu.fleet import (
    FenceAuthority,
    FileLeaseStore,
    FleetRouter,
    StaleLeaseError,
)
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import metrics as obs_metrics
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.resilience.errors import FleetUnavailableError
from poisson_ellipse_tpu.resilience.faultinject import (
    FaultPlan,
    lease_clock_skew,
    replica_hang,
)
from poisson_ellipse_tpu.serve import RequestJournal, ServeRequest, run_chaos
from poisson_ellipse_tpu.serve.scheduler import Scheduler


class FakeClock:
    """Hand-cranked monotonic clock (the test_serve idiom): lease and
    deadline semantics become deterministic instead of racing the
    host."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def make_router(tmp_path, replicas=2, clock=None, **kw):
    kw.setdefault("lanes", 2)
    kw.setdefault("chunk", 8)
    kw.setdefault("backoff_base_s", 0.001)
    kw.setdefault("keep_solutions", False)
    router_kw = {}
    if clock is not None:
        router_kw["clock"] = clock
        router_kw["idle"] = clock.advance
    return FleetRouter(
        replicas=replicas, journal_dir=str(tmp_path / "journals"),
        **router_kw, **kw,
    )


# -- fencing: the zero-double choke point ------------------------------------


def test_stale_token_write_rejected_and_trace_evented(tmp_path):
    authority = FenceAuthority()
    token = authority.issue(0)
    journal = RequestJournal(tmp_path / "j.json", fence=token)
    req = ServeRequest(problem=Problem(M=10, N=10), request_id="r0")
    journal.record_admit(req)  # valid token: lands
    path = tmp_path / "fence.jsonl"
    obs_trace.start(str(path))
    stale_before = obs_metrics.REGISTRY.counter(
        obs_metrics.FLEET_STALE_WRITES_TOTAL
    ).value
    try:
        authority.fence(0)
        with pytest.raises(StaleLeaseError):
            journal.record_outcome("r0", "completed")
        with pytest.raises(StaleLeaseError):
            journal.record_admit(
                ServeRequest(problem=Problem(M=10, N=10), request_id="r1")
            )
    finally:
        obs_trace.stop()
    # the rejected write never touched the record: r0 is still live
    # (admitted, unfinished) and r1 was never admitted
    reloaded = RequestJournal(tmp_path / "j.json")
    assert [r.request_id for r in reloaded.unfinished(0.0)] == ["r0"]
    # trace-evented + counted — the drill is observable, not silent
    names = [r["name"] for r in obs_trace.read_jsonl(str(path))]
    assert names.count("fleet:stale-write-rejected") == 2
    assert obs_trace.validate_file(str(path)) == []
    assert obs_metrics.REGISTRY.counter(
        obs_metrics.FLEET_STALE_WRITES_TOTAL
    ).value == stale_before + 2


def test_journal_snapshot_embeds_the_fencing_token(tmp_path):
    import json

    authority = FenceAuthority()
    token = authority.issue(3)
    journal = RequestJournal(tmp_path / "j.json", fence=token)
    journal.record_admit(
        ServeRequest(problem=Problem(M=10, N=10), request_id="r0")
    )
    with open(tmp_path / "j.json", encoding="utf-8") as fh:
        snap = json.load(fh)
    assert snap["fence_token"] == token.value == "r3:e1"
    # and the loaded journal surfaces the writing epoch
    assert RequestJournal(tmp_path / "j.json").loaded_fence_token == "r3:e1"


def test_reissue_stales_the_previous_incarnation(tmp_path):
    # a restarted replica under the same id mints a NEW epoch; the dead
    # incarnation's token is stale from its first write
    authority = FenceAuthority()
    old = authority.issue(0)
    new = authority.issue(0)
    assert old.stale and not new.stale
    journal = RequestJournal(tmp_path / "j.json", fence=old)
    with pytest.raises(StaleLeaseError):
        journal.record_admit(
            ServeRequest(problem=Problem(M=10, N=10), request_id="r0")
        )


# -- leases ------------------------------------------------------------------


def test_lease_expiry_declares_dead_fences_and_hands_off(tmp_path):
    clock = FakeClock()
    router = make_router(
        tmp_path, replicas=2, clock=clock, lease_s=1.0, lanes=1, chunk=4,
    )
    hang = replica_hang(delay_s=float("inf"), at_request=0, replica=0)
    router.faults.faults.append(hang)
    for i in range(3):
        assert router.submit(Problem(M=10, N=10),
                             request_id=f"r{i}") is None
    # the hang fault fired at the first arrival: replica 0 stops
    # heartbeating while its process object lives
    rep0 = router.replicas[0]
    assert rep0.hung(clock())
    expired_before = obs_metrics.REGISTRY.counter(
        obs_metrics.LEASE_EXPIRY_TOTAL
    ).value
    # advance in sub-lease increments (heartbeats are continuous in the
    # world this simulates): the healthy replica renews at every step's
    # sweep, the hung one never does — only IT crosses its deadline
    for _ in range(3):
        clock.advance(0.6)
        router.step()
    assert not rep0.live and rep0.token.stale
    assert router.replicas[1].live
    assert router.handoffs == 1
    assert obs_metrics.REGISTRY.counter(
        obs_metrics.LEASE_EXPIRY_TOTAL
    ).value == expired_before + 1
    # the survivor finishes everything the dead replica owned
    results = router.drain()
    assert {results[f"r{i}"].outcome for i in range(3)} == {"completed"}
    # zombie resurrection: the hung replica's own loop comes back and
    # every completion it attempts is rejected at its fenced journal
    rep0.hung_until = 0.0
    with pytest.raises(StaleLeaseError):
        for _ in range(200):
            if not rep0.resurrect_step():
                break
    # nothing the zombie did after the fence is visible anywhere
    assert not rep0.scheduler.results


def test_drain_waits_out_a_hung_replicas_lease(tmp_path):
    # drain with work stuck behind a hung replica must IDLE toward the
    # lease expiry (then fence + hand off), not hot-spin into the
    # max_steps backstop before the expiry can land
    clock = FakeClock()
    router = make_router(
        tmp_path, replicas=2, clock=clock, lease_s=1.0, lanes=1, chunk=4,
        faults=FaultPlan(
            replica_hang(delay_s=float("inf"), at_request=0, replica=0)
        ),
    )
    for i in range(2):
        assert router.submit(Problem(M=10, N=10),
                             request_id=f"h{i}") is None
    results = router.drain()
    assert {results[f"h{i}"].outcome for i in range(2)} == {"completed"}
    assert not router.replicas[0].live and router.handoffs == 1


def test_lease_clock_skew_fences_the_skewed_replica(tmp_path):
    # the NTP-step drill: a skewed replica's renewals land short, so it
    # reads as expired under the router clock while perfectly healthy —
    # it must be fenced and its work handed off, not co-owned
    clock = FakeClock()
    router = make_router(
        tmp_path, replicas=2, clock=clock, lease_s=1.0, lanes=1, chunk=4,
        faults=FaultPlan(
            lease_clock_skew(skew_s=5.0, at_request=0, replica=0)
        ),
    )
    for i in range(2):
        assert router.submit(Problem(M=10, N=10),
                             request_id=f"s{i}") is None
    router.step()  # skewed renewal: deadline lands 4s in the past
    clock.advance(0.01)
    router.step()
    rep0 = router.replicas[0]
    assert not rep0.live and rep0.token.stale
    results = router.drain()
    assert {results[f"s{i}"].outcome for i in range(2)} == {"completed"}


# -- handoff -----------------------------------------------------------------


def test_handoff_preserves_remaining_deadline_budget(tmp_path):
    clock = FakeClock(100.0)
    router = make_router(
        tmp_path, replicas=2, clock=clock, lanes=1, chunk=4,
    )
    assert router.submit(
        Problem(M=10, N=10), deadline_s=60.0, request_id="budget"
    ) is None
    clock.advance(5.0)
    # find the owner and kill it: the handoff replays the journaled
    # remaining-at-admission budget from the handoff clock (the PR 7
    # replay contract, unchanged across the replica boundary)
    owner = next(
        rep for rep in router.replicas
        if rep.scheduler._knows("budget")
    )
    router.kill_replica(owner.replica_id)
    survivor = next(rep for rep in router.replicas if rep.live)
    assert survivor.scheduler._knows("budget")
    req = survivor.scheduler.queue.pop_ready(clock())
    assert req is not None and req.request_id == "budget"
    assert req.deadline == pytest.approx(clock() + 60.0, abs=1.0)


def test_handed_off_solution_bit_identical_to_uninterrupted(tmp_path):
    # the kill/handoff machinery must not perturb one bit of the
    # answer: the same request through (a) a fleet whose owner dies
    # mid-solve and (b) a plain uninterrupted scheduler must agree
    # exactly (both re-run from a clean carry on the same embedding)
    router = make_router(
        tmp_path, replicas=2, lanes=1, chunk=2, keep_solutions=True,
    )
    assert router.submit(Problem(M=12, N=12), request_id="bits") is None
    router.step()  # a couple of chunks in flight on the owner
    owner = next(
        rep for rep in router.replicas if rep.scheduler._knows("bits")
    )
    router.kill_replica(owner.replica_id)
    res = router.drain()["bits"]
    assert res.outcome == "completed"

    plain = Scheduler(lanes=1, chunk=2, keep_solutions=True)
    plain.submit(Problem(M=12, N=12), request_id="bits")
    ref = plain.drain()["bits"]
    assert ref.outcome == "completed"
    assert res.iters == ref.iters
    assert np.array_equal(res.w, ref.w), (
        "handed-off solution departs bitwise from the uninterrupted one"
    )


def test_kill_with_requests_in_flight_adopts_them(tmp_path):
    router = make_router(tmp_path, replicas=3, lanes=2, chunk=2)
    for i in range(6):
        assert router.submit(Problem(M=10, N=10),
                             request_id=f"k{i}") is None
    router.step()
    router.kill_replica(0)
    assert router.handoffs == 1 and router.adopted_total >= 1
    results = router.drain()
    assert {results[f"k{i}"].outcome for i in range(6)} == {"completed"}
    # handoff latency was measured
    hist = obs_metrics.REGISTRY.histogram(
        obs_metrics.HANDOFF_LATENCY_SECONDS
    )
    assert hist.count >= 1


# -- routing -----------------------------------------------------------------


def test_affinity_prefers_warm_replica_until_lanes_fill(tmp_path):
    from poisson_ellipse_tpu.runtime.compile_cache import warm_affinity_key

    router = make_router(tmp_path, replicas=2, lanes=2, chunk=4)
    key = warm_affinity_key(10, 10, "weighted")
    assert router.submit(Problem(M=10, N=10), request_id="a0") is None
    router.step()  # replica 0 builds the bucket: it is now warm
    warm = [rep for rep in router.replicas if key in rep.warm_keys()]
    assert [r.replica_id for r in warm] == [0]
    # with a free lane left, the warm replica keeps winning...
    assert router.submit(Problem(M=10, N=10), request_id="a1") is None
    assert router.replicas[0].scheduler._knows("a1")
    # ...but once its lanes fill, the cold replica with free lanes wins
    # (affinity must not defeat scaling)
    assert router.submit(Problem(M=10, N=10), request_id="a2") is None
    assert router.replicas[1].scheduler._knows("a2")


def test_all_replicas_shed_returns_min_retry_hint(tmp_path):
    router = make_router(tmp_path, replicas=2, lanes=1,
                         queue_capacity=1)
    for i in range(2):  # one queued request fills each replica's slot
        assert router.submit(Problem(M=10, N=10),
                             request_id=f"fill{i}") is None
    shed = router.submit(Problem(M=10, N=10), request_id="over")
    assert shed is not None and shed.outcome == "shed"
    assert shed.detail == "fleet-backpressure"
    assert shed.retry_after_s is not None and shed.retry_after_s > 0
    results = router.drain()
    assert results["over"].outcome == "shed"
    done = [r for r in results.values() if r.outcome == "completed"]
    assert len(done) == 2


def test_probe_shed_leaves_no_record_on_the_refusing_replica(tmp_path):
    # a replica that sheds while the router probes candidates answered
    # a ROUTING question, not a lifecycle one: no terminal record may
    # linger there, or a later harvest would merge a stale shed over
    # the completion the next replica delivers
    router = make_router(tmp_path, replicas=2, lanes=1,
                         queue_capacity=1)
    assert router.submit(Problem(M=10, N=10), request_id="p0") is None
    # replica holding p0 is full (capacity 1): p1 probes it, gets shed,
    # lands on the other replica
    assert router.submit(Problem(M=10, N=10), request_id="p1") is None
    assert all(
        "p1" not in rep.scheduler.results for rep in router.replicas
    )
    results = router.drain()
    assert results["p0"].outcome == "completed"
    assert results["p1"].outcome == "completed"
    assert router.double_delivered == []


def test_anonymous_all_shed_is_recorded_once_under_a_real_id(tmp_path):
    # the harness submits without ids and discards the return: the
    # rejection must still land in fleet accounting exactly once,
    # under one real id — not vanish while each probed replica logs a
    # phantom shed under its own uuid
    router = make_router(tmp_path, replicas=2, lanes=1,
                         queue_capacity=1)
    for _ in range(2):
        assert router.submit(Problem(M=10, N=10)) is None
    shed = router.submit(Problem(M=10, N=10))  # no request_id
    assert shed is not None and shed.detail == "fleet-backpressure"
    assert shed.request_id and shed.request_id != "rejected"
    results = router.drain()
    sheds = [r for r in results.values() if r.outcome == "shed"]
    assert len(sheds) == 1 and sheds[0].request_id == shed.request_id
    assert sum(1 for r in results.values()
               if r.outcome == "completed") == 2


def test_harvest_ledger_catches_cross_replica_double_delivery(tmp_path):
    # the zero-double detector must live where deliveries pass exactly
    # once: forge the fencing-failure shape (two replicas both deliver
    # a terminal record for one id) and the ledger must name it
    from poisson_ellipse_tpu.serve.request import ServeResult

    router = make_router(tmp_path, replicas=2, lanes=1)
    for rep in router.replicas:
        rep.scheduler.results["forged"] = ServeResult(
            request_id="forged", outcome="completed",
        )
    router.harvest()
    assert router.double_delivered == ["forged"]


def test_duplicate_request_id_refused_fleet_wide(tmp_path):
    router = make_router(tmp_path, replicas=2, lanes=1)
    assert router.submit(Problem(M=10, N=10), request_id="dup") is None
    refused = router.submit(Problem(M=12, N=12), request_id="dup")
    assert refused is not None and refused.outcome == "shed"
    assert refused.detail == "duplicate-request-id"
    # the original is untouched and completes exactly once
    results = router.drain()
    assert results["dup"].outcome == "completed"


def test_retry_of_request_completed_by_dead_replica_is_refused(tmp_path):
    # the client-retry-after-owner-crash race: replica 0 completes X
    # and is then killed; the results were collected (evicted); a
    # client retry of X must be refused as a duplicate — the DEAD
    # replica's journal is what remembers the delivery, and consulting
    # it is what keeps the retry from double-completing on a survivor
    router = make_router(tmp_path, replicas=2, lanes=1)
    assert router.submit(Problem(M=10, N=10), request_id="retry") is None
    router.drain()
    router.collect()  # results evicted, the harness-loop shape
    owner = next(
        rep for rep in router.replicas
        if rep.scheduler.owns_request("retry")
    )
    router.kill_replica(owner.replica_id)
    refused = router.submit(Problem(M=10, N=10), request_id="retry")
    assert refused is not None and refused.detail == "duplicate-request-id"
    # and nothing new was admitted anywhere
    assert all(
        not rep.scheduler.queue.holds("retry") for rep in router.replicas
    )


def test_fleet_backpressure_shed_allows_resubmission(tmp_path):
    # "shed ... safe to resubmit after retry_after_s" must hold at the
    # ROUTER's door too: a fleet-backpressure rejection is not
    # ownership, and the resubmission supersedes it
    router = make_router(tmp_path, replicas=2, lanes=1,
                         queue_capacity=1)
    for i in range(2):
        assert router.submit(Problem(M=10, N=10),
                             request_id=f"fill{i}") is None
    shed = router.submit(Problem(M=10, N=10), request_id="again")
    assert shed is not None and shed.detail == "fleet-backpressure"
    router.drain()  # capacity frees up
    assert router.submit(Problem(M=10, N=10), request_id="again") is None
    assert router.drain()["again"].outcome == "completed"


def test_death_during_shutdown_adopts_into_draining_survivor(tmp_path):
    # shutdown races a death: the dead replica's journaled work must be
    # adopted by a DRAINING survivor (already-acknowledged fleet work is
    # not a new admission) — never silently abandoned
    clock = FakeClock()
    router = make_router(
        tmp_path, replicas=2, clock=clock, lease_s=1.0, lanes=1, chunk=4,
    )
    for i in range(3):
        assert router.submit(Problem(M=10, N=10),
                             request_id=f"x{i}") is None
    for rep in router.replicas:
        rep.begin_drain()
    owner = next(
        rep for rep in router.replicas
        if rep.scheduler.owns_request("x0")
    )
    router.kill_replica(owner.replica_id)
    results = router.drain()
    assert {results[f"x{i}"].outcome for i in range(3)} == {"completed"}


def test_all_replicas_down_is_classified_exit_9_never_a_hang(tmp_path):
    router = make_router(tmp_path, replicas=2, lanes=1)
    assert router.submit(Problem(M=10, N=10), request_id="r0") is None
    router.drain()
    router.kill_replica(0)
    router.kill_replica(1)
    with pytest.raises(FleetUnavailableError) as exc:
        router.submit(Problem(M=10, N=10), request_id="r1")
    assert exc.value.exit_code == 9
    assert exc.value.retry_after_s is not None
    assert exc.value.classification == "fleet-unavailable"


# -- drain -------------------------------------------------------------------


def test_begin_drain_sheds_new_and_finishes_in_flight(tmp_path):
    router = make_router(tmp_path, replicas=2, lanes=1)
    for i in range(3):
        assert router.submit(Problem(M=10, N=10),
                             request_id=f"d{i}") is None
    router.step()
    results = router.shutdown()
    assert {results[f"d{i}"].outcome for i in range(3)} == {"completed"}
    # every replica now refuses new work with a redirectable shed, so
    # the fleet-level answer is the classified exit 9
    with pytest.raises(FleetUnavailableError):
        router.submit(Problem(M=10, N=10), request_id="late")


def test_draining_scheduler_shed_is_not_recorded_as_terminal():
    # the drain shed is a redirect for the router, not a lifecycle
    # event: recording it would double-count the id once another
    # replica completes the request
    sched = Scheduler(lanes=1, chunk=8, keep_solutions=False)
    sched.begin_drain()
    shed = sched.submit(Problem(M=10, N=10), request_id="redirected")
    assert shed is not None and shed.outcome == "shed"
    assert shed.detail == "draining"
    assert shed.retry_after_s is not None
    assert "redirected" not in sched.results
    assert len(sched.queue) == 0


# -- chaos: the fleet invariant triple ---------------------------------------


def test_fleet_chaos_replica_kill_zero_lost_zero_double(tmp_path):
    report = run_chaos(
        n_requests=12, seed=7, replicas=3, chunk=2,
        journal_path=os.path.join(tmp_path, "journals"),
        replica_kill=4,
    )
    assert report.ok, (
        f"lost={report.lost} doubled={report.double_completed} "
        f"unclassified={report.unclassified}"
    )
    assert report.killed and report.handoffs >= 1
    assert report.replicas == 3
    assert sum(report.counts.values()) == 12
    # the injected per-request faults REALLY fired (the plan is shared
    # fleet-wide: nan + oom + the kill = 3) on whichever replica hosted
    # their victims, and cost them nothing
    assert report.faults_fired == 3
    assert report.outcomes["chaos-0002"] == "completed"
    assert report.outcomes["chaos-0005"] == "completed"


def test_fleet_chaos_is_seed_deterministic(tmp_path):
    kw = dict(n_requests=10, seed=3, replicas=2, chunk=2, replica_kill=3)
    r1 = run_chaos(journal_path=os.path.join(tmp_path, "c1"), **kw)
    r2 = run_chaos(journal_path=os.path.join(tmp_path, "c2"), **kw)
    assert r1.ok and r2.ok
    assert r1.outcomes == r2.outcomes
    assert r1.counts == r2.counts
    assert r1.handoffs == r2.handoffs


def test_fleet_chaos_kill_during_handoff(tmp_path):
    # the adopting survivor dies at the same boundary the first handoff
    # finished on: journal-first adoption is what keeps the adopted
    # requests alive through the second kill
    report = run_chaos(
        n_requests=12, seed=5, replicas=3, chunk=2,
        journal_path=os.path.join(tmp_path, "journals"),
        replica_kill=4, kill_during_handoff=True,
    )
    assert report.ok, (
        f"lost={report.lost} doubled={report.double_completed}"
    )
    assert report.handoffs >= 2
    assert sum(report.counts.values()) == 12


def test_fleet_chaos_refuses_single_scheduler_drills_loudly(tmp_path):
    # drills the fleet path cannot run must be refused, never silently
    # dropped into a vacuously-green invariant report
    for kw in (
        dict(kill_after=3),
        dict(mesh_kill_request=3),
        dict(malformed_request=3),
        dict(degenerate_request=3),
    ):
        with pytest.raises(ValueError, match="single-scheduler"):
            run_chaos(
                n_requests=8, seed=0, replicas=2,
                journal_path=os.path.join(tmp_path, "journals"), **kw,
            )


def test_fleet_chaos_kill_during_handoff_needs_three_replicas(tmp_path):
    # with 2 replicas the double kill is the total-loss drill, not the
    # handoff drill — refused loudly instead of crashing mid-stream
    with pytest.raises(ValueError, match="replicas >= 3"):
        run_chaos(
            n_requests=8, seed=0, replicas=2,
            journal_path=os.path.join(tmp_path, "journals"),
            replica_kill=3, kill_during_handoff=True,
        )


def test_fleet_chaos_zombie_resurrection_stale_write_rejected(tmp_path):
    report = run_chaos(
        n_requests=10, seed=4, replicas=2, chunk=2,
        journal_path=os.path.join(tmp_path, "journals"),
        zombie=True, nan_request=None, oom_request=None,
    )
    assert report.ok, (
        f"lost={report.lost} doubled={report.double_completed}"
    )
    assert report.zombie_drill
    # the fenced stale write was OBSERVED and REJECTED — the zero-double
    # pin is a mechanism, not an accident of timing
    assert report.stale_writes_rejected >= 1
    assert report.handoffs >= 1


# -- survivability: rejoin, lease-store faults, tenants (ISSUE 19) -----------


def test_rejoin_after_kill_fresh_epoch_replay_and_event(tmp_path):
    path = tmp_path / "rejoin.jsonl"
    router = make_router(tmp_path, replicas=2, lanes=1)
    rejoin_before = obs_metrics.REGISTRY.counter(
        obs_metrics.FLEET_REJOIN_TOTAL
    ).value
    obs_trace.start(str(path))
    try:
        for i in range(3):
            assert router.submit(Problem(M=10, N=10),
                                 request_id=f"rj{i}") is None
        victim = router.replicas[0]
        old_epoch = victim.token.epoch
        journal_path = victim.journal_path
        router.kill_replica(0)
        new_rep = router.rejoin_replica(0)
        # fresh incarnation: the epoch advanced past the fence bump, the
        # old ledger is archived under the dead epoch, and the new
        # incarnation starts its own journal at the original path
        assert new_rep.token.epoch > old_epoch
        assert os.path.exists(f"{journal_path}.e{old_epoch}")
        assert router.rejoins == 1
        assert router.replicas[0] is new_rep and new_rep.live
        # no id is co-owned across the epoch boundary at any point
        assert router.audit_ownership() == []
        for i in range(3, 5):
            assert router.submit(Problem(M=10, N=10),
                                 request_id=f"rj{i}") is None
        results = router.drain()
    finally:
        obs_trace.stop()
    assert {results[f"rj{i}"].outcome for i in range(5)} == {"completed"}
    assert router.audit_ownership() == []
    assert obs_metrics.REGISTRY.counter(
        obs_metrics.FLEET_REJOIN_TOTAL
    ).value == rejoin_before + 1
    events = [r for r in obs_trace.read_jsonl(str(path))
              if r["name"] == "fleet:rejoin"]
    assert len(events) == 1
    assert events[0]["fields"]["old_epoch"] == old_epoch
    assert events[0]["fields"]["new_epoch"] == new_rep.token.epoch
    assert obs_trace.validate_file(str(path)) == []


def test_rejoin_refuses_a_live_replica(tmp_path):
    router = make_router(tmp_path, replicas=2)
    with pytest.raises(ValueError, match="live"):
        router.rejoin_replica(0)


def test_rejoin_observes_recovery_latency(tmp_path):
    hist = obs_metrics.REGISTRY.histogram(
        obs_metrics.REJOIN_LATENCY_SECONDS
    )
    count_before = hist.count
    router = make_router(tmp_path, replicas=2, lanes=1)
    assert router.submit(Problem(M=10, N=10), request_id="warm") is None
    router.drain()
    router.kill_replica(0)
    router.rejoin_replica(0)
    # the latency sample lands at the rejoiner's FIRST completed
    # delivery, not at rejoin time: it measures recovery to capacity
    assert hist.count == count_before
    for i in range(4):
        assert router.submit(Problem(M=10, N=10),
                             request_id=f"lat{i}") is None
    router.drain()
    assert hist.count == count_before + 1


# -- the pluggable lease store ------------------------------------------------


def test_fence_authority_epochs_monotonic_under_concurrent_issue_revoke():
    import threading

    authority = FenceAuthority()
    issued: list[int] = []
    lock = threading.Lock()

    def hammer():
        for _ in range(50):
            token = authority.issue(0)
            with lock:
                issued.append(token.epoch)
            authority.fence(0)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # every issue minted a UNIQUE epoch (a duplicate would let two
    # incarnations validate the same token — split-brain), and the
    # final epoch accounts for every one of the 400 locked mutations
    assert len(set(issued)) == len(issued) == 200
    assert authority.current_epoch(0) == 400


def test_file_lease_store_round_trips_and_leaves_no_temp(tmp_path):
    path = tmp_path / "lease-store.json"
    store = FileLeaseStore(path)
    token = store.issue(0)
    store.issue(1)
    store.fence(1)
    # a second process opening the same file sees the same epochs
    reopened = FileLeaseStore(path)
    assert reopened.current_epoch(0) == token.epoch
    assert reopened.current_epoch(1) == store.current_epoch(1)
    assert reopened.valid(0, token.epoch)
    assert not reopened.valid(1, 1)
    # atomic temp-then-rename never strands its temp files
    assert [p for p in os.listdir(tmp_path) if p.endswith(".tmp")] == []


def test_file_lease_store_torn_write_classified_never_reset(tmp_path):
    from poisson_ellipse_tpu.resilience.errors import (
        LeaseStoreCorruptError,
    )

    path = tmp_path / "lease-store.json"
    FileLeaseStore(path).issue(0)
    # truncation mid-document (a torn write): classified corruption,
    # never a silent re-initialisation (a reset would re-validate the
    # fenced zombie's token — split-brain by construction)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write('{"v": 1, "epoch": {"0"')
    with pytest.raises(LeaseStoreCorruptError, match="torn"):
        FileLeaseStore(path)
    # parseable but shape-wrong (an external writer): also classified
    with open(path, "w", encoding="utf-8") as fh:
        fh.write('[1, 2, 3]')
    with pytest.raises(LeaseStoreCorruptError, match="epoch table"):
        FileLeaseStore(path)
    # a MISSING file is first boot, not corruption
    fresh = FileLeaseStore(tmp_path / "never-written.json")
    assert fresh.current_epoch(0) == 0


def test_router_accepts_file_lease_store(tmp_path):
    store = FileLeaseStore(tmp_path / "lease-store.json")
    router = make_router(tmp_path, replicas=2, lease_store=store)
    assert router.submit(Problem(M=10, N=10), request_id="f0") is None
    results = router.drain()
    assert results["f0"].outcome == "completed"
    # the fleet's epochs are on disk: a reopened store agrees
    reopened = FileLeaseStore(tmp_path / "lease-store.json")
    for rep in router.replicas:
        assert reopened.valid(rep.replica_id, rep.token.epoch)


def test_lease_store_outage_fail_safe_grace_then_capped_backoff(tmp_path):
    from poisson_ellipse_tpu.resilience.faultinject import (
        lease_store_outage,
    )

    clock = FakeClock()
    router = make_router(
        tmp_path, replicas=2, clock=clock, lease_s=1.0, lanes=1, chunk=4,
        faults=FaultPlan(lease_store_outage(4.0, at_request=1)),
    )
    assert router.store_grace_s == 2.0  # DEFAULT_STORE_GRACE_LEASES
    assert router.submit(Problem(M=10, N=10), request_id="g0") is None
    # the outage fires as g1 arrives; inside the grace window replicas
    # hold unexpired leases and admission continues — the fleet
    # degrades on membership change, not the steady-state path
    assert router.submit(Problem(M=10, N=10), request_id="g1") is None
    # cross the grace window in sub-lease increments: heartbeats are
    # LOCAL renewals, so serving continues while the store is dark
    for _ in range(6):
        clock.advance(0.5)
        router.step()
    hints = []
    for i in range(3):
        with pytest.raises(FleetUnavailableError) as exc:
            router.submit(Problem(M=10, N=10), request_id=f"g{i + 2}")
        assert exc.value.exit_code == 9
        hints.append(exc.value.retry_after_s)
    # capped-exponential hints (TPU014): strictly increasing here,
    # doubling from one lease length
    assert hints == [1.0, 2.0, 4.0]
    # recovery: once the outage duration lapses, the step probe's ping
    # answers, leases re-validate, and admission resumes
    for _ in range(4):
        clock.advance(0.5)
        router.step()
    assert router.submit(Problem(M=10, N=10), request_id="g9") is None
    results = router.drain()
    assert results["g0"].outcome == "completed"
    assert results["g1"].outcome == "completed"
    assert results["g9"].outcome == "completed"


def test_death_during_outage_deferred_until_store_recovers(tmp_path):
    clock = FakeClock()
    router = make_router(
        tmp_path, replicas=2, clock=clock, lease_s=1.0, lanes=1, chunk=4,
    )
    for i in range(3):
        assert router.submit(Problem(M=10, N=10),
                             request_id=f"o{i}") is None
    router.authority.fail_for(5.0)
    # the fence round-trip cannot reach the store: the death is
    # DEFERRED, not dropped — no handoff yet, ownership stays single
    router.kill_replica(0)
    assert router.handoffs == 0
    # wait out the outage in sub-lease increments (the survivor's
    # heartbeat is local, so its lease stays fresh the whole time);
    # the first answered ping runs the recovery protocol, which
    # completes the deferred fence + handoff
    for _ in range(12):
        clock.advance(0.5)
        router.step()
    assert router.handoffs == 1
    results = router.drain()
    assert {results[f"o{i}"].outcome for i in range(3)} == {"completed"}
    assert router.audit_ownership() == []


def test_rejoin_during_outage_refused_classified(tmp_path):
    clock = FakeClock()
    router = make_router(
        tmp_path, replicas=2, clock=clock, lease_s=1.0, lanes=1,
    )
    assert router.submit(Problem(M=10, N=10), request_id="x0") is None
    router.drain()
    router.kill_replica(0)
    journal_path = router.replicas[0].journal_path
    router.authority.fail_for(5.0)
    with pytest.raises(FleetUnavailableError, match="rejoin"):
        router.rejoin_replica(0)
    # the refused rejoin undid its archive: the dead incarnation's
    # ledger stays the durable truth at the ORIGINAL path until a
    # rejoin actually happens
    assert os.path.exists(journal_path)
    assert not any(
        p.startswith(os.path.basename(journal_path) + ".e")
        for p in os.listdir(tmp_path / "journals")
    )
    assert router.rejoins == 0
    clock.advance(6.0)
    router.rejoin_replica(0)
    assert router.rejoins == 1


def test_lease_store_latency_stalls_through_the_idle_hook(tmp_path):
    clock = FakeClock()
    router = make_router(
        tmp_path, replicas=2, clock=clock, lease_s=100.0, lanes=1,
    )
    router.authority.delay_for(0.5)
    t0 = clock()
    router.kill_replica(0)  # the fence round-trip eats the delay
    # injected latency ran through the router's OWN idle (the
    # FakeClock), not a real sleep — deterministic slow-quorum drill
    assert clock() > t0
    results = router.drain()
    assert results == {} or all(
        r.outcome == "completed" for r in results.values()
    )


# -- multi-tenant admission ---------------------------------------------------


def test_tenant_and_priority_round_trip_the_journal(tmp_path):
    token = FenceAuthority().issue(0)
    journal = RequestJournal(tmp_path / "t.json", fence=token)
    journal.record_admit(ServeRequest(
        problem=Problem(M=10, N=10), request_id="t0",
        tenant="batch", priority=3,
    ))
    reloaded = RequestJournal(tmp_path / "t.json")
    (req,) = reloaded.unfinished(0.0)
    assert req.tenant == "batch" and req.priority == 3


def test_class_quota_shed_names_the_tenant_class():
    from poisson_ellipse_tpu.serve.queue import AdmissionQueue

    q = AdmissionQueue(capacity=8, lanes=1,
                       class_quotas={"batch": 1})
    ok, _, _ = q.admit(ServeRequest(
        problem=Problem(M=10, N=10), request_id="b0", tenant="batch",
    ))
    assert ok
    ok, retry_after, reason = q.admit(ServeRequest(
        problem=Problem(M=10, N=10), request_id="b1", tenant="batch",
    ))
    assert not ok and reason == "tenant-quota"
    assert retry_after is not None
    # the quota binds per class: another tenant still admits
    ok, _, _ = q.admit(ServeRequest(
        problem=Problem(M=10, N=10), request_id="i0",
        tenant="interactive",
    ))
    assert ok


def test_priority_preemption_evicts_strictly_lower_never_equal():
    from poisson_ellipse_tpu.serve.queue import AdmissionQueue

    q = AdmissionQueue(capacity=1, lanes=1)
    ok, _, _ = q.admit(ServeRequest(
        problem=Problem(M=10, N=10), request_id="low", priority=1,
    ))
    assert ok
    ok, _, _ = q.admit(ServeRequest(
        problem=Problem(M=10, N=10), request_id="high", priority=2,
    ))
    assert ok  # preempted its way in
    assert [r.request_id for r in q.take_evicted()] == ["low"]
    # equal priority never preempts: FIFO fairness within a class
    ok, _, reason = q.admit(ServeRequest(
        problem=Problem(M=10, N=10), request_id="peer", priority=2,
    ))
    assert not ok and reason == "queue-full"


def test_scheduler_classifies_preemption_victims_terminally(tmp_path):
    sched = Scheduler(
        lanes=1, chunk=8, queue_capacity=1, keep_solutions=False,
        journal=str(tmp_path / "p.json"),
    )
    assert sched.submit(Problem(M=10, N=10), request_id="low",
                        tenant="batch", priority=1) is None
    assert sched.submit(Problem(M=10, N=10), request_id="high",
                        tenant="interactive", priority=2) is None
    results = sched.drain()
    assert results["high"].outcome == "completed"
    assert results["low"].outcome == "shed"
    assert results["low"].detail == "preempted-by-priority"


def test_starvation_detected_and_announced_loudly(tmp_path):
    from poisson_ellipse_tpu.serve.queue import AdmissionQueue

    clock = FakeClock()
    q = AdmissionQueue(capacity=8, lanes=1, clock=clock,
                       starvation_after_s=1.0)
    path = tmp_path / "starve.jsonl"
    assert q.admit(ServeRequest(
        problem=Problem(M=10, N=10), request_id="b0", tenant="batch",
        priority=1,
    ))[0]
    assert q.admit(ServeRequest(
        problem=Problem(M=10, N=10), request_id="i0",
        tenant="interactive", priority=2,
    ))[0]
    clock.advance(2.0)
    obs_trace.start(str(path))
    try:
        served = q.pop_ready(clock())
    finally:
        obs_trace.stop()
    assert served.request_id == "i0"  # priority wins the pop
    # batch sat ready past the threshold while interactive got served:
    # ONE episode, detected and announced in the same breath
    assert q.starvation_episodes == {"batch": 1}
    assert q.starvation_announced == {"batch": 1}
    events = [r for r in obs_trace.read_jsonl(str(path))
              if r["name"] == "fleet:starvation"]
    assert len(events) == 1 and events[0]["fields"]["tenant"] == "batch"
    assert obs_trace.validate_file(str(path)) == []


def test_drain_shed_counted_fleet_wide_without_a_record(tmp_path):
    router = make_router(tmp_path, replicas=2, lanes=1)
    assert router.drain_shed_total() == 0
    sched = router.replicas[0].scheduler
    sched.begin_drain()
    # the draining scheduler's shed is a redirect, not a lifecycle
    # event: COUNTED (zero-lost stays provable across a kill-mid-drain)
    # but never recorded as the request's terminal outcome
    shed = sched.submit(Problem(M=10, N=10), request_id="redir")
    assert shed is not None and shed.detail == "draining"
    assert "redir" not in sched.results
    assert router.drain_shed_total() == 1
    # the router routes around the draining replica: the same id
    # completes on the survivor, and the count stands
    assert router.submit(Problem(M=10, N=10), request_id="redir") is None
    results = router.drain()
    assert results["redir"].outcome == "completed"
    assert router.drain_shed_total() == 1
    # the count survives the incarnation's retirement: kill + rejoin
    # must not lose retired counters (the fold-in bound)
    router.kill_replica(0)
    router.rejoin_replica(0)
    assert router.drain_shed_total() == 1


# -- chaos: the survivability drills ------------------------------------------


def test_fleet_chaos_rejoin_ladder_kill_rejoin_kill_again(tmp_path):
    report = run_chaos(
        n_requests=14, seed=2, replicas=2, chunk=2,
        journal_path=os.path.join(tmp_path, "journals"),
        replica_kill=4, replica_rejoin=7, replica_kill_again=10,
        nan_request=None, oom_request=None,
    )
    assert report.ok, (
        f"lost={report.lost} doubled={report.double_completed} "
        f"co_owned={report.co_owned}"
    )
    assert report.rejoins == 1
    assert report.handoffs >= 2  # the original death AND the re-death
    assert report.co_owned == []
    assert sum(report.counts.values()) == 14


def test_fleet_chaos_lease_store_outage_spanning_a_kill(tmp_path):
    report = run_chaos(
        n_requests=12, seed=1, replicas=2, chunk=2,
        journal_path=os.path.join(tmp_path, "journals"),
        replica_kill=5, lease_store_outage=4, lease_store_outage_s=0.05,
        nan_request=None, oom_request=None,
    )
    assert report.ok, (
        f"lost={report.lost} doubled={report.double_completed} "
        f"co_owned={report.co_owned}"
    )
    assert report.killed and report.handoffs >= 1
    assert report.faults_fired == 2  # the outage AND the kill
    assert sum(report.counts.values()) == 12


def test_fleet_chaos_zombie_then_rejoin(tmp_path):
    report = run_chaos(
        n_requests=12, seed=6, replicas=2, chunk=2,
        journal_path=os.path.join(tmp_path, "journals"),
        zombie=True, replica_rejoin=8,
        nan_request=None, oom_request=None,
    )
    assert report.ok, (
        f"lost={report.lost} doubled={report.double_completed} "
        f"co_owned={report.co_owned}"
    )
    assert report.zombie_drill and report.stale_writes_rejected >= 1
    assert report.rejoins == 1
    assert report.co_owned == []


def test_fleet_chaos_tenant_mix_all_classified_none_starved_silent(tmp_path):
    report = run_chaos(
        n_requests=16, seed=9, replicas=2, chunk=2,
        journal_path=os.path.join(tmp_path, "journals"),
        tenant_mix=[("interactive", 2), ("batch", 1)],
        class_quotas={"batch": 6}, starvation_after_s=0.5,
        nan_request=None, oom_request=None,
    )
    assert report.ok, (
        f"lost={report.lost} starved_silent={report.starved_silent}"
    )
    assert set(report.tenants) <= {"interactive", "batch"}
    assert sum(
        n for per in report.tenants.values() for n in per.values()
    ) == 16
    # every starvation episode that happened was ANNOUNCED
    assert report.starved_silent == []


def test_fleet_chaos_survivability_drills_refused_on_single_path(tmp_path):
    for kw in (
        dict(replica_rejoin=3),
        dict(lease_store_outage=3),
        dict(tenant_mix=[("a", 1)]),
    ):
        with pytest.raises(ValueError, match="fleet drills"):
            run_chaos(
                n_requests=8, seed=0, replicas=1,
                journal_path=os.path.join(tmp_path, "journal.json"), **kw,
            )


# -- CLI ---------------------------------------------------------------------


def test_cli_fleet_subcommand(tmp_path, capsys):
    import json

    from poisson_ellipse_tpu.harness.__main__ import main

    trace = tmp_path / "fleet.jsonl"
    rc = main([
        "fleet", "--replicas", "2", "--requests", "6",
        "--grids", "10x10", "--rate", "1000", "--chunk", "4",
        "--kill-replica-at", "2",
        "--trace", str(trace), "--json",
    ])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["outcomes"] == {"completed": 6}
    assert rec["replicas"] == 2
    assert rec["handoffs"] >= 1
    assert rec["live_replicas"] == [1]
    assert obs_trace.validate_file(str(trace)) == []
    names = {r["name"] for r in obs_trace.read_jsonl(str(trace))}
    assert "fleet:replica-kill" in names and "fleet_report" in names


def test_cli_fleet_rejects_bad_args(capsys):
    from poisson_ellipse_tpu.harness.__main__ import main

    assert main(["fleet", "--replicas", "0"]) == 2
    assert main(["fleet", "--requests", "0"]) == 2
    assert main(["fleet", "--rate", "0"]) == 2


# -- SIGTERM graceful shutdown (subprocess) ----------------------------------


@pytest.mark.skipif(os.name == "nt", reason="POSIX signals")
def test_sigterm_drains_serve_gracefully(tmp_path):
    """SIGTERM on `harness serve` must drain (stop admitting, finish
    in-flight, flush the trace) and exit 0 — not die mid-stream with
    the trace tail lost."""
    import signal
    import subprocess
    import sys
    import time

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    trace = tmp_path / "sigterm.jsonl"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "poisson_ellipse_tpu.harness", "serve",
            "--requests", "500", "--grids", "10x10", "--rate", "3",
            "--journal", str(tmp_path / "j.json"),
            "--trace", str(trace), "--json",
        ],
        env=env, cwd=repo_root,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        # wait until the stream is actually running (first admit on the
        # trace) so the handler is installed before the signal lands
        deadline = time.monotonic() + 120.0
        started = False
        while time.monotonic() < deadline:
            if trace.exists() and "serve:admit" in trace.read_text():
                started = True
                break
            if proc.poll() is not None:
                break
            time.sleep(0.25)
        assert started, (
            f"serve never started (rc={proc.poll()}): "
            f"{proc.stderr.read() if proc.poll() is not None else ''}"
        )
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, f"SIGTERM drain exited {proc.returncode}: {err}"
    # the drain really ran: the trace tail holds the drain event AND
    # the final report (flushed, not lost with a hard kill)
    assert obs_trace.validate_file(str(trace)) == []
    names = [r["name"] for r in obs_trace.read_jsonl(str(trace))]
    assert "serve:sigterm-drain" in names
    assert "serve:drain-begin" in names
    assert "serve_report" in names
    import json

    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["drained_on_sigterm"] is True
