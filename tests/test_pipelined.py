"""Pipelined PCG: parity, breakdown, chunking, sharding, and the
one-psum-per-iteration structural guarantee.

The pipelined engine's contract is deliberately weaker than the classical
engines' bitwise oracle parity — it is a *reordering* of the recurrence
(``ops.pipelined_pcg``), so iteration counts are held to ±2 of the
``xla`` engine and solutions to a fraction of the L2 error, while the
structural claim that motivates it (ONE stacked psum collective per
sharded iteration, versus the classical loop's two) is pinned exactly,
from the jaxpr."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.pipelined_pcg import (
    advance,
    init_state,
    pcg_pipelined,
    result_of,
    solve as solve_pipelined,
)
from poisson_ellipse_tpu.ops.reduction import grid_dots
from poisson_ellipse_tpu.parallel.mesh import make_mesh
from poisson_ellipse_tpu.solver.pcg import pcg, solve as solve_xla
from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic

# committed reference code oracles (provenance: tests/test_pcg.py)
UNWEIGHTED_ORACLE = {(10, 10): 17, (20, 20): 31, (40, 40): 61}
WEIGHTED_ORACLE = {(10, 10): 15, (20, 20): 26, (40, 40): 50}


def mesh_of(n):
    return make_mesh(jax.devices()[:n])


# ------------------------------------------------------------- parity


@pytest.mark.parametrize("norm,oracle", [
    ("unweighted", UNWEIGHTED_ORACLE), ("weighted", WEIGHTED_ORACLE),
])
@pytest.mark.parametrize("M,N", sorted(WEIGHTED_ORACLE))
def test_oracle_parity_within_two(M, N, norm, oracle):
    """Iters within ±2 of xla (and of the published count), converged,
    L2-vs-analytic within 10% — the pipelined accuracy contract."""
    problem = Problem(M=M, N=N, norm=norm)
    ref = solve_xla(problem, jnp.float64)
    got = solve_pipelined(problem, jnp.float64)
    assert abs(int(got.iters) - int(ref.iters)) <= 2
    assert abs(int(got.iters) - oracle[(M, N)]) <= 2
    assert bool(got.converged)
    assert not bool(got.breakdown)
    l2_ref = float(l2_error_vs_analytic(problem, ref.w))
    l2_got = float(l2_error_vs_analytic(problem, got.w))
    assert l2_got <= 1.1 * l2_ref


@pytest.mark.parametrize("stencil", ["xla", "pallas"])
def test_f32_parity_general_grid(stencil):
    """f32 on a non-square, non-aligned grid, both stencil flavours —
    the fused stencil+partials kernel drives the 'pallas' loop."""
    problem = Problem(M=44, N=132)
    ref = solve_xla(problem, jnp.float32)
    got = solve_pipelined(problem, jnp.float32, stencil=stencil)
    assert abs(int(got.iters) - int(ref.iters)) <= 2
    assert bool(got.converged)
    l2_ref = float(l2_error_vs_analytic(problem, ref.w))
    assert float(l2_error_vs_analytic(problem, got.w)) <= 1.1 * l2_ref


@pytest.mark.parametrize("seed", range(3))
def test_parity_on_random_configurations(seed):
    """±2-parity over randomly drawn boxes/ε/f/grids (the SURVEY §4
    invariance suite, under the pipelined tolerance)."""
    rng = np.random.default_rng(2000 + seed)
    problem = Problem(
        M=int(rng.integers(24, 56)),
        N=int(rng.integers(24, 56)),
        a1=-float(rng.uniform(1.05, 1.6)),
        b1=float(rng.uniform(1.05, 1.6)),
        a2=-float(rng.uniform(0.55, 1.0)),
        b2=float(rng.uniform(0.55, 1.0)),
        eps=float(10.0 ** rng.uniform(-6, -1)),
        f_val=float(rng.uniform(0.2, 3.0)),
    )
    ref = solve_xla(problem, jnp.float64)
    got = solve_pipelined(problem, jnp.float64)
    assert bool(ref.converged) and bool(got.converged), problem
    assert abs(int(got.iters) - int(ref.iters)) <= 2, problem


def test_headline_grid_f32_oracle():
    """546±2 at 400×600 f32 — the smallest published bench oracle, the
    regime where the unstabilised recurrence used to break down (the
    residual-replacement cadence is load-bearing here)."""
    problem = Problem(M=400, N=600)
    got = solve_pipelined(problem, jnp.float32)
    assert bool(got.converged)
    assert not bool(got.breakdown)
    assert abs(int(got.iters) - 546) <= 2
    assert float(l2_error_vs_analytic(problem, got.w)) < 1e-3


# ------------------------------------------------------------- breakdown


def test_breakdown_guard_exit():
    """Zero coefficients make the α-denominator 0 < DENOM_GUARD on the
    first iteration: the pipelined loop must exit via the breakdown flag
    with the pre-update iterate held — the same exit the classical loop
    takes (stage0/Withoutopenbmp1.cpp:128-style early return)."""
    problem = Problem(M=10, N=10)
    _, _, rhs = assembly.assemble(problem, jnp.float64)
    zeros = jnp.zeros_like(rhs)
    got = pcg_pipelined(problem, zeros, zeros, rhs)
    ref = pcg(problem, zeros, zeros, rhs)
    assert bool(got.breakdown) and bool(ref.breakdown)
    assert not bool(got.converged)
    assert int(got.iters) == int(ref.iters) == 1
    np.testing.assert_array_equal(np.asarray(got.w), np.asarray(zeros))


# ------------------------------------------------------------- chunking


def test_chunked_advance_bit_identical():
    """init_state + advance in limit-chunks is bit-identical to one
    straight run (the resumable-solver contract ``solver.pcg`` has,
    carried over: chunking moves the while_loop boundary only)."""
    problem = Problem(M=20, N=20)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    straight = advance(problem, a, b, rhs, init_state(problem, a, b, rhs))

    state = init_state(problem, a, b, rhs)
    for limit in (3, 7, 11, 200):
        state = advance(problem, a, b, rhs, state, limit=limit)
    chunked = state

    for lhs, rhs_ in zip(straight, chunked):
        np.testing.assert_array_equal(np.asarray(lhs), np.asarray(rhs_))
    result = result_of(chunked)
    assert int(result.iters) == WEIGHTED_ORACLE[(20, 20)]
    assert bool(result.converged)


def test_chunk_boundary_on_replacement_iteration():
    """A chunk boundary landing exactly on the residual-replacement
    cadence must not change anything — the replacement is keyed on the
    iteration counter, not the dispatch."""
    from poisson_ellipse_tpu.ops.pipelined_pcg import REPLACE_EVERY

    problem = Problem(M=40, N=40)  # 50 iterations: crosses k=32
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    straight = advance(problem, a, b, rhs, init_state(problem, a, b, rhs))
    state = init_state(problem, a, b, rhs)
    for limit in (REPLACE_EVERY, REPLACE_EVERY + 1, 200):
        state = advance(problem, a, b, rhs, state, limit=limit)
    for lhs, rhs_ in zip(straight, state):
        np.testing.assert_array_equal(np.asarray(lhs), np.asarray(rhs_))


# ------------------------------------------------------------- sharded


@pytest.mark.parametrize("mesh_shape", [(1, 2), (2, 2)])
def test_sharded_pipelined_matches_single_chip(mesh_shape):
    """The one-psum sharded variant on a CPU mesh (through the
    ``parallel.compat`` shard_map shim): iters within ±2 of the sharded
    xla path and elementwise agreement with the single-chip pipelined
    solve."""
    from poisson_ellipse_tpu.parallel.pcg_sharded import solve_sharded
    from poisson_ellipse_tpu.parallel.pipelined_sharded import (
        solve_pipelined_sharded,
    )

    px, py = mesh_shape
    mesh = mesh_of(px * py)
    problem = Problem(M=40, N=40)
    single = solve_pipelined(problem, jnp.float64)
    ref = solve_sharded(problem, mesh, jnp.float64)
    got = solve_pipelined_sharded(problem, mesh, jnp.float64)
    assert abs(int(got.iters) - int(ref.iters)) <= 2
    assert bool(got.converged)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(single.w), rtol=0, atol=1e-10
    )


def test_sharded_pipelined_uneven_grid():
    """Shard padding on both axes (14×18 nodes over a 2×4 mesh)."""
    from poisson_ellipse_tpu.parallel.pipelined_sharded import (
        solve_pipelined_sharded,
    )

    problem = Problem(M=13, N=17)
    ref = solve_pipelined(problem, jnp.float64)
    got = solve_pipelined_sharded(problem, mesh_of(8), jnp.float64)
    assert got.w.shape == (14, 18)
    assert abs(int(got.iters) - int(ref.iters)) <= 2
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=0, atol=1e-10
    )


def test_sharded_pipelined_through_dispatch_and_cli():
    """stencil_impl='pipelined' routes through build_sharded_solver and
    the harness sharded mode (the product entry points)."""
    from poisson_ellipse_tpu.harness.run import run_once
    from poisson_ellipse_tpu.parallel.pcg_sharded import solve_sharded

    problem = Problem(M=20, N=20)
    got = solve_sharded(
        problem, mesh_of(2), jnp.float64, stencil_impl="pipelined"
    )
    assert abs(int(got.iters) - WEIGHTED_ORACLE[(20, 20)]) <= 2
    report = run_once(
        problem, mode="sharded", mesh_shape=(1, 2), dtype="f64",
        engine="pipelined",
    )
    assert report.engine == "pipelined"
    assert report.converged
    with pytest.raises(ValueError, match="host"):
        solve_sharded(
            problem, mesh_of(2), jnp.float64,
            assembly_mode="device", stencil_impl="pipelined",
        )


def test_multichip_scaling_table_runs_pipelined():
    from poisson_ellipse_tpu.harness.bench_multichip import scaling_table

    t = scaling_table(
        "strong", (20, 20), [(1, 1), (2, 2)], dtype="f64",
        stencil_impl="pipelined",
    )
    assert t["stencil_impl"] == "pipelined"
    assert all(r["converged"] for r in t["rows"])
    assert all(
        abs(r["iters"] - WEIGHTED_ORACLE[(20, 20)]) <= 2 for r in t["rows"]
    )


# ------------------------------------------------ structural (static cost)


def test_pipelined_iteration_issues_exactly_one_psum():
    """THE structural claim, asserted from the declared contract
    (``analysis.contracts`` — the same checker the matrix CLI sweeps,
    with expectations derived from ENGINE_CAPS, not a test-local jaxpr
    walk): the pipelined sharded loop body holds exactly 1 psum
    collective per iteration; the classical sharded loop holds 2 with
    the 4-ppermute halo ring. (The pipelined body's ppermutes are
    deliberately unpinned: the replacement branch's stacked exchanges
    are static upper-bound accounting, not steady-state cost.)"""
    from poisson_ellipse_tpu.analysis.contracts import assert_contract

    problem = Problem(M=40, N=40)
    pipe = assert_contract(
        "collective-cadence", "pipelined", problem=problem,
        mesh_shape=(2, 2),
    )
    classical = assert_contract(
        "collective-cadence", "xla", problem=problem, mesh_shape=(2, 2),
    )
    assert pipe.expected["psum"] == 1
    assert classical.expected == {"psum": 2, "ppermute": 4}


# ------------------------------------------------------------ grid_dots


def test_grid_dots_matches_individual_sums():
    rng = np.random.default_rng(7)
    u = jnp.asarray(rng.standard_normal((13, 17)))
    v = jnp.asarray(rng.standard_normal((13, 17)))
    w = jnp.asarray(rng.standard_normal((13, 17)))
    sums = grid_dots((u, v), (v, w), (w, w))
    assert sums.shape == (3,)
    for got, (x, y) in zip(sums, ((u, v), (v, w), (w, w))):
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(jnp.sum(x * y))
        )


# ------------------------------------------- fused stencil+partials kernel


def test_apply_a_dots_pallas_matches_stencil_and_dots():
    """The fused kernel must agree with its two unfused constituents:
    the Pallas stencil twin (exactly — same expression tree, same
    tiling) and the separate dot sums (to f32 reduction-order slack)."""
    from poisson_ellipse_tpu.ops.pallas_kernels import (
        apply_a_dots_pallas,
        apply_a_pallas,
    )

    problem = Problem(M=44, N=132)
    a, b, rhs = assembly.assemble(problem, jnp.float32)
    rng = np.random.default_rng(3)
    mk = lambda: jnp.asarray(rng.standard_normal(rhs.shape), jnp.float32)
    m, r, u, w, p = mk(), mk(), mk(), mk(), mk()
    pairs = ((r, u), (w, u), (u, u), (u, p), (p, p))
    n, sums = apply_a_dots_pallas(m, a, b, problem.h1, problem.h2, pairs)
    np.testing.assert_array_equal(
        np.asarray(n), np.asarray(apply_a_pallas(m, a, b, problem.h1, problem.h2))
    )
    expected = [
        float(jnp.sum(x[1:-1, 1:-1] * y[1:-1, 1:-1])) for x, y in pairs
    ]
    np.testing.assert_allclose(np.asarray(sums), expected, rtol=2e-5)
    with pytest.raises(ValueError, match="pair"):
        apply_a_dots_pallas(m, a, b, problem.h1, problem.h2, ())


# ------------------------------------------------------------ engine zoo


def test_engine_registration_and_policy():
    from poisson_ellipse_tpu.solver.engine import (
        ENGINES,
        build_solver,
        select_engine,
    )

    assert "pipelined" in ENGINES and "pipelined-pallas" in ENGINES
    # auto never picks it: single-chip it is a collectives optimisation
    # paying ~2x streamed passes — the policy table documents why
    for problem in (Problem(M=40, N=40), Problem(M=4096, N=4096)):
        assert select_engine(problem) != "pipelined"

    problem = Problem(M=20, N=20)
    ref = solve_xla(problem, jnp.float32)
    for engine in ("pipelined", "pipelined-pallas"):
        solver, args, resolved = build_solver(problem, engine, jnp.float32)
        assert resolved == engine
        got = solver(*args)
        assert abs(int(got.iters) - int(ref.iters)) <= 2
        assert bool(got.converged)


def test_run_once_single_pipelined_reports_roofline():
    from poisson_ellipse_tpu.harness.run import run_once

    report = run_once(
        Problem(M=20, N=20), mode="single", engine="pipelined"
    )
    assert report.engine == "pipelined"
    assert report.converged
    assert report.passes_per_iter > 13.0  # the documented traffic price
