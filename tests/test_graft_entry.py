"""Driver-gate tests.

The multichip dryrun is the only multi-chip correctness evidence this
environment can produce, so it must be hermetic to the accelerator
runtime: round 3's artifact was killed by a libtpu client/terminal
version mismatch that the gate walked into via default-backend calls
(``jax.devices()`` + an oracle solve on the default device) even though
the gate itself only needs a virtual CPU mesh.
"""

import os
import subprocess
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_survives_dead_accelerator_runtime():
    env = dict(os.environ)
    # Simulate an unusable accelerator runtime: the environment's
    # sitecustomize registers the hardware plugin only when
    # PALLAS_AXON_POOL_IPS is set, while JAX_PLATFORMS stays pinned to
    # that plugin — so with the variable removed, any default-backend
    # touch raises exactly like the round-3 libtpu mismatch did.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # The dryrun must also provision its own virtual CPU devices.
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "from __graft_entry__ import dryrun_multichip; "
            "dryrun_multichip(8); print('hermetic-ok')",
        ],
        cwd=_REPO_ROOT,
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "hermetic-ok" in proc.stdout
