"""The lint gate: the package must lint clean on every PR.

This is the CI wiring the ISSUE asks for — tier-1 already runs pytest,
so a pytest-visible assertion over ``lint_paths`` makes tpulint a gate
with no extra infrastructure. It uses the same ``[tool.tpulint]`` config
as the CLI, so ``python -m poisson_ellipse_tpu.lint`` reproducing a CI
failure locally is exact, not approximate.
"""

from __future__ import annotations

import os

from poisson_ellipse_tpu.lint import audit_paths, lint_paths, load_config
from poisson_ellipse_tpu.lint.report import render_report

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_package_lints_clean():
    config = load_config(REPO_ROOT)
    paths = [os.path.join(REPO_ROOT, p) for p in config.paths]
    findings, errors = lint_paths(paths, config)
    assert not errors, "\n".join(e.render() for e in errors)
    assert not findings, (
        "tpulint findings (fix, or annotate with "
        "`# tpulint: disable=CODE` plus a justification):\n"
        + render_report(findings, statistics=True)
    )


def test_package_suppressions_all_earn_their_keep():
    # the annotation ratchet: every `# tpulint: disable` in the package
    # must still suppress a live finding — stale waivers get deleted
    config = load_config(REPO_ROOT)
    paths = [os.path.join(REPO_ROOT, p) for p in config.paths]
    findings, errors = audit_paths(paths, config)
    assert not errors, "\n".join(e.render() for e in errors)
    assert not findings, (
        "stale tpulint suppressions (the hazard is gone — remove the "
        "annotation):\n" + render_report(findings)
    )


def test_config_comes_from_pyproject():
    # the gate and the CLI must share one config: spot-check that the
    # pyproject table actually loaded rather than silently defaulting
    config = load_config(REPO_ROOT)
    assert config.paths == ("poisson_ellipse_tpu",)
    assert "poisson_ellipse_tpu/runtime/*" in config.per_path_ignores
