"""Native C++ runtime vs oracles and vs the JAX path.

Mirrors the reference's cross-implementation oracle (identical PCG
iteration counts across its sequential/OpenMP/MPI/CUDA stages, SURVEY
§4.2): the C++ runtime and the JAX solver must agree on iteration counts
and, in f64, on the solution itself.
"""

import numpy as np
import pytest

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.runtime import (
    assemble_native,
    native_available,
    solve_native,
)
from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic

pytestmark = pytest.mark.skipif(
    not native_available(), reason="C++ runtime could not be built"
)

ORACLES_UNWEIGHTED = {(10, 10): 17, (20, 20): 31, (40, 40): 61}
ORACLES_WEIGHTED = {(10, 10): 15, (20, 20): 26, (40, 40): 50}


@pytest.mark.parametrize("grid,iters", sorted(ORACLES_UNWEIGHTED.items()))
def test_iteration_oracles_unweighted(grid, iters):
    r = solve_native(Problem(M=grid[0], N=grid[1], norm="unweighted"))
    assert r.converged and r.iters == iters


@pytest.mark.parametrize("grid,iters", sorted(ORACLES_WEIGHTED.items()))
def test_iteration_oracles_weighted(grid, iters):
    r = solve_native(Problem(M=grid[0], N=grid[1], norm="weighted"))
    assert r.converged and r.iters == iters


def test_assembly_matches_jax_host_assembly():
    problem = Problem(M=24, N=18)
    a_c, b_c, rhs_c = assemble_native(problem)
    a_j, b_j, rhs_j = assembly.assemble_numpy(problem)
    np.testing.assert_allclose(a_c, a_j, rtol=1e-14)
    np.testing.assert_allclose(b_c, b_j, rtol=1e-14)
    np.testing.assert_array_equal(rhs_c, rhs_j)


def test_solution_matches_jax_f64():
    import jax.numpy as jnp

    from poisson_ellipse_tpu.solver.pcg import solve

    problem = Problem(M=40, N=40)
    r_c = solve_native(problem)
    r_j = solve(problem, jnp.float64)
    assert r_c.iters == int(r_j.iters)
    np.testing.assert_allclose(
        r_c.w, np.asarray(r_j.w), rtol=1e-8, atol=1e-12
    )
    err = float(l2_error_vs_analytic(problem, jnp.asarray(r_c.w)))
    assert err == pytest.approx(3.68e-3, rel=0.05)


def test_thread_count_does_not_change_iterations():
    problem = Problem(M=40, N=40)
    base = solve_native(problem, threads=1)
    for threads in (2, 4):
        r = solve_native(problem, threads=threads)
        assert r.iters == base.iters
        np.testing.assert_allclose(r.w, base.w, rtol=1e-12, atol=1e-15)


def test_max_iter_cap_reports_not_converged():
    r = solve_native(Problem(M=40, N=40, max_iter=3))
    assert not r.converged and not r.breakdown and r.iters == 3


def test_bad_args_raise():
    with pytest.raises(ValueError):
        Problem(M=1, N=1)  # guarded upstream
