"""diff/: IFT adjoints, differentiable assembly, inverse workloads,
and the grad=True serving kind.

The heart is the gradient-correctness battery: the adjoint gradient of
a functional of the converged solution must match central finite
differences of THE SAME traceable forward to rtol 1e-4 on f64, for
every parameter kind (SDF shape vector, per-node source field, ε) ×
{classical xla, pipelined, mg-pcg, 1×2 sharded} — the acceptance
criterion of the differentiable-solving milestone. Everything runs at
tightened δ (the tolerance contract: gradient error is O(δ)), small
grids, f64 (conftest enables x64).
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.diff.adjoint import ImplicitSolver, solve_implicit
from poisson_ellipse_tpu.diff import assembly as diff_assembly
from poisson_ellipse_tpu.diff.objectives import (
    dirichlet_energy,
    objective_from_spec,
)
from poisson_ellipse_tpu.diff.serving import solve_grad_direct
from poisson_ellipse_tpu.geom import sdf
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.serve.request import ServeRequest
from poisson_ellipse_tpu.serve.scheduler import Scheduler

# asymmetric template so every shape component carries real signal
TPL = sdf.Ellipse(cx=0.07, cy=-0.04, rx=0.9, ry=0.45)


def _mesh_1x2():
    from poisson_ellipse_tpu.parallel.mesh import make_mesh

    return make_mesh(jax.devices("cpu")[:2])


def _loss_of(solver):
    def loss(params):
        u = solver.solve(params)
        return jnp.sum(u * u)

    return loss


def _fd(loss, params, key, h, idx=None):
    """Central finite difference of ``loss`` in params[key] (component
    ``idx``, or the scalar)."""

    def bump(s):
        q = dict(params)
        arr = np.array(params[key], np.float64)
        if idx is None:
            arr = arr + s
        else:
            arr[idx] += s
        q[key] = jnp.asarray(arr)
        return q

    return float((loss(bump(h)) - loss(bump(-h))) / (2.0 * h))


# -- the gradient-correctness battery ---------------------------------------


# The mg-pcg and sharded FD sweeps are the suite's heaviest single tests
# (each runs ~20 solves: per-component central FD probes through the full
# build); with tier-1 near the 870 s ceiling they are slow-marked — the
# xla and pipelined sweeps keep the adjoint-vs-FD contract in tier-1 for
# every param kind, and the engine-dispatch parity the heavy variants add
# is still pinned (bitwise) by test_vjp_and_linear_modes_agree below.
@pytest.mark.parametrize("engine", [
    "xla",
    "pipelined",
    pytest.param("mg-pcg", marks=pytest.mark.slow),
    pytest.param("sharded", marks=pytest.mark.slow),
])
def test_adjoint_matches_fd_all_param_kinds(engine):
    """Every param kind × this engine: adjoint vs central FD at
    rtol 1e-4 (components measured against the FD value, floored at 1%
    of the kind's gradient scale so a symmetry-zero component cannot
    manufacture an infinite relative error), plus a directional
    derivative over the whole shape vector."""
    # δ=1e-11 asks for the tightest solve this grid can give: the
    # reference's 1e-15 denominator guard stops the iteration at a
    # step-norm floor ~2e-9 here, which is what bounds the IFT
    # consistency error (measured ~7e-5 relative on the smallest
    # component — inside the 1e-4 acceptance; at δ=1e-8 it is not)
    problem = Problem(M=16, N=16, delta=1e-11)
    mesh = _mesh_1x2() if engine == "sharded" else None
    solver = ImplicitSolver(problem, TPL, engine=engine,
                            dtype=jnp.float64, mesh=mesh)
    src = np.full(problem.node_shape, problem.f_val)
    src[7:10, 7:10] += 0.5  # structure, so source grads vary by node
    params = {
        "shape": jnp.asarray(sdf.params_of(TPL)),
        "source": jnp.asarray(src),
        "eps": jnp.asarray(problem.eps_value),
    }
    loss = _loss_of(solver)
    g = jax.grad(loss)(params)

    # the tolerance quote, read before any FD probe resets the log: a
    # gradient cost exactly primal + adjoint, each quoting the achieved
    # step-norm (the breakdown floor sits under δ here, so the loop may
    # terminate on the denominator guard rather than the step rule —
    # the quote, not the flag, is the contract)
    quotes = list(solver.last)
    assert len(quotes) == 2, quotes
    assert all(q["iters"] > 0 and q["diff"] <= 1e-7 for q in quotes), quotes

    # shape kind: all four components + a directional probe
    gs = np.asarray(g["shape"])
    scale = np.abs(gs).max()
    assert scale > 0 and np.all(np.isfinite(gs))
    for i in range(4):
        fd = _fd(loss, params, "shape", 1e-5, (i,))
        assert abs(gs[i] - fd) <= 1e-4 * max(abs(fd), 1e-2 * scale), (
            f"{engine}: shape[{i}] adjoint {gs[i]:.8e} vs FD {fd:.8e}"
        )
    v = np.asarray([0.3, -0.2, 0.5, 1.0])

    def bump_dir(s):
        q = dict(params)
        q["shape"] = jnp.asarray(np.asarray(params["shape"]) + s * v)
        return q

    fdir = float((loss(bump_dir(1e-6)) - loss(bump_dir(-1e-6))) / 2e-6)
    assert abs(float(gs @ v) - fdir) <= 1e-4 * abs(fdir)

    # eps kind (scalar)
    ge = float(g["eps"])
    fd = _fd(loss, params, "eps", 1e-7)
    assert abs(ge - fd) <= 1e-4 * abs(fd), (
        f"{engine}: eps adjoint {ge:.8e} vs FD {fd:.8e}"
    )

    # source kind: probe entries (inside, near-boundary, outside-domain)
    gsrc = np.asarray(g["source"])
    src_scale = np.abs(gsrc).max()
    assert src_scale > 0 and np.all(np.isfinite(gsrc))
    for ij in ((8, 8), (5, 10), (1, 1)):
        fd = _fd(loss, params, "source", 1e-5, ij)
        assert abs(gsrc[ij] - fd) <= 1e-4 * max(abs(fd), 1e-2 * src_scale), (
            f"{engine}: source{ij} adjoint {gsrc[ij]:.8e} vs FD {fd:.8e}"
        )


def test_grad_of_grad_hvp_forward_over_reverse():
    """The grad-of-grad smoke: HVP via forward-over-reverse through the
    ``adjoint='linear'`` (custom_linear_solve) surface, checked against
    a central FD of the gradient — and reverse-over-reverse agrees."""
    problem = Problem(M=10, N=10, delta=1e-12)
    solver = ImplicitSolver(problem, TPL, engine="xla",
                            dtype=jnp.float64, adjoint="linear")
    loss = _loss_of(solver)
    p0 = {"shape": jnp.asarray(sdf.params_of(TPL))}
    v = {"shape": jnp.asarray([0.3, -0.2, 0.5, 1.0])}

    hvp = jax.jvp(jax.grad(loss), (p0,), (v,))[1]["shape"]
    h = 1e-5
    gp = jax.grad(loss)(
        {"shape": p0["shape"] + h * v["shape"]}
    )["shape"]
    gm = jax.grad(loss)(
        {"shape": p0["shape"] - h * v["shape"]}
    )["shape"]
    fd = (np.asarray(gp) - np.asarray(gm)) / (2 * h)
    rel = np.abs(np.asarray(hvp) - fd).max() / np.abs(fd).max()
    assert rel <= 1e-3, f"HVP vs FD-of-grad rel {rel:.2e}"

    rr = jax.grad(
        lambda q: jnp.vdot(jax.grad(loss)(q)["shape"], v["shape"])
    )(p0)["shape"]
    np.testing.assert_allclose(np.asarray(rr), np.asarray(hvp),
                               rtol=1e-8, atol=1e-10)


def test_vjp_and_linear_modes_agree_and_custom_vjp_is_first_order():
    problem = Problem(M=10, N=10, delta=1e-12)
    p0 = {"shape": jnp.asarray(sdf.params_of(TPL))}
    sol_v = ImplicitSolver(problem, TPL, engine="xla", dtype=jnp.float64)
    sol_l = ImplicitSolver(problem, TPL, engine="xla", dtype=jnp.float64,
                           adjoint="linear")
    gv = jax.grad(_loss_of(sol_v))(p0)["shape"]
    gl = jax.grad(_loss_of(sol_l))(p0)["shape"]
    # identical machinery under both wrappers: bitwise-equal gradients
    assert np.array_equal(np.asarray(gv), np.asarray(gl))
    # custom_vjp is documented first-order-only: forward mode refuses
    with pytest.raises(TypeError, match="forward-mode"):
        jax.jvp(jax.grad(_loss_of(sol_v)), (p0,),
                ({"shape": jnp.ones(4)},))


def test_solve_implicit_one_shot_and_engine_validation():
    problem = Problem(M=10, N=10)
    u = solve_implicit(problem, {"shape": jnp.asarray(sdf.params_of(TPL))},
                       template=TPL)
    assert np.all(np.isfinite(np.asarray(u)))
    with pytest.raises(ValueError, match="not in"):
        ImplicitSolver(problem, TPL, engine="resident")
    with pytest.raises(ValueError, match="host-orchestrated"):
        ImplicitSolver(problem, TPL, engine="sharded", adjoint="linear")


# -- the differentiable assembly --------------------------------------------


def test_diff_assembly_tracks_production_quadrature():
    """The linear cut rule's values agree with the bisection quadrature
    to its documented O((1/samples)²) on the curved ellipse, and the
    operands stay SPD-signed (positive coefficients)."""
    from poisson_ellipse_tpu.ops import assembly as prod_assembly

    problem = Problem(M=20, N=20)
    a_d, b_d, rhs_d = diff_assembly.assemble_theta(
        problem, sdf.Ellipse(), samples=16, dtype=jnp.float64
    )
    a_p, b_p, rhs_p = prod_assembly.assemble_numpy(
        problem, geometry=sdf.Ellipse()
    )
    # coefficients: the blend amplifies fraction error by 1/eps — bound
    # the FRACTION error instead, via the face lengths
    la_d, lb_d = diff_assembly.face_lengths_theta(
        problem, sdf.Ellipse(), samples=16, dtype=jnp.float64
    )
    from poisson_ellipse_tpu.geom import quadrature

    la_p, lb_p = quadrature.segment_lengths(problem, sdf.Ellipse())
    frac_err = max(
        np.abs(np.asarray(la_d) / problem.h2 - la_p / problem.h2).max(),
        np.abs(np.asarray(lb_d) / problem.h1 - lb_p / problem.h1).max(),
    )
    assert frac_err <= 1.5 * (1.0 / 16) ** 2, frac_err
    # the RHS indicator is sign-exact (no quadrature in it)
    np.testing.assert_array_equal(np.asarray(rhs_d), rhs_p)
    assert float(jnp.min(a_d[1:-1, 1:-1])) > 0
    assert float(jnp.min(b_d[1:-1, 1:-1])) > 0


def test_diff_assembly_gradients_are_finite_everywhere():
    problem = Problem(M=12, N=12)

    def total(vec):
        shape = sdf.with_params(TPL, vec)
        a, b, rhs = diff_assembly.assemble_theta(problem, shape,
                                                 dtype=jnp.float64)
        return jnp.sum(a) + jnp.sum(b) + jnp.sum(rhs)

    g = jax.grad(total)(jnp.asarray(sdf.params_of(TPL)))
    assert np.all(np.isfinite(np.asarray(g)))
    # the reference ellipse touches (±1, 0) — tangency must not NaN
    g0 = jax.grad(total)(jnp.asarray(sdf.params_of(sdf.Ellipse())))
    assert np.all(np.isfinite(np.asarray(g0)))


# -- spec ↔ pytree round trip (geom/sdf satellite) ---------------------------


def test_params_roundtrip_nested_composite():
    shape = sdf.Difference(
        sdf.Union(
            sdf.Ellipse(cx=0.1, cy=-0.05, rx=0.8, ry=0.4),
            sdf.Translate(sdf.Circle(r=0.2), dx=0.3, dy=0.1),
        ),
        sdf.Rectangle(x0=-0.2, y0=-0.1, x1=0.2, y1=0.1),
    )
    params = sdf.params_of(shape)
    assert params.shape == (sdf.n_params(shape),) == (13,)
    rebuilt = sdf.with_params(shape, params)
    assert json.dumps(sdf.to_spec(rebuilt), sort_keys=True) == \
        json.dumps(sdf.to_spec(shape), sort_keys=True)
    # a perturbed vector re-serialises to valid RFC JSON and re-parses
    wire = json.loads(json.dumps(sdf.to_spec(
        sdf.with_params(shape, params + 1e-3)
    )))
    assert np.array_equal(sdf.params_of(sdf.from_spec(wire)),
                          sdf.params_of(sdf.with_params(shape, params + 1e-3)))


def test_with_params_accepts_tracers_and_length_mismatch_classifies():
    from poisson_ellipse_tpu.resilience.errors import InvalidGeometryError

    shape = sdf.Ellipse()

    def f(vec):
        s = sdf.with_params(shape, vec)
        return s(jnp.asarray(0.3), jnp.asarray(0.1))

    g = jax.grad(f)(jnp.asarray(sdf.params_of(shape)))
    assert np.all(np.isfinite(np.asarray(g)))
    with pytest.raises(InvalidGeometryError):
        sdf.with_params(shape, [1.0, 2.0])


def test_fuzz_check_param_roundtrip_runs():
    from poisson_ellipse_tpu.geom.fuzz import check_param_roundtrip

    assert check_param_roundtrip(sdf.Ellipse()) == 4
    assert check_param_roundtrip(
        sdf.Intersection(sdf.Circle(), sdf.HalfPlane(nx=0.5, ny=0.5))
    ) == 6


# -- objectives ---------------------------------------------------------------


def test_objective_specs_and_validation():
    problem = Problem(M=8, N=8)
    a, b, rhs = diff_assembly.assemble_theta(problem, sdf.Ellipse(),
                                             dtype=jnp.float64)
    u = jnp.ones(problem.node_shape, jnp.float64)
    for spec in (None, {"kind": "energy"}, {"kind": "mean"},
                 {"kind": "flux"},
                 {"kind": "l2",
                  "target": np.zeros(problem.node_shape).tolist()}):
        fn = objective_from_spec(spec, problem)
        val = fn(u, a, b, rhs)
        assert np.isfinite(float(val))
    for bad in ({"kind": "nope"}, {"kind": "l2"}, "energy",
                {"kind": "l2", "target": [[1.0]]}):
        with pytest.raises(ValueError):
            objective_from_spec(bad, problem)
    # energy at the solution equals half the compliance <u, rhs>
    solver = ImplicitSolver(problem, sdf.Ellipse(), engine="xla",
                            dtype=jnp.float64)
    a0, b0, r0 = solver.operands(None)
    u0 = solver.solve_operands(a0, b0, r0)
    e = float(dirichlet_energy(problem, u0, a0, b0))
    compliance = 0.5 * float(
        jnp.sum(u0 * r0) * problem.h1 * problem.h2
    )
    assert abs(e - compliance) <= 1e-8 * max(abs(compliance), 1e-12)


# -- the end-to-end inverse workloads ----------------------------------------


def test_recover_ellipse_end_to_end():
    from poisson_ellipse_tpu.diff.optimize import recover_ellipse

    report = recover_ellipse(grid=(20, 20), seed=0, steps=60)
    assert report["ok"], report
    assert report["rel_err"] <= 1e-3
    # the recovered spec is a valid JSON wire form (round-trip satellite)
    rebuilt = sdf.from_spec(json.loads(json.dumps(report["recovered_spec"])))
    assert isinstance(rebuilt, sdf.Ellipse)
    # seeded-deterministic (pinned on short runs — same trajectory
    # prefix, a fraction of the full workload's wall clock)
    short = recover_ellipse(grid=(20, 20), seed=0, steps=6)
    again = recover_ellipse(grid=(20, 20), seed=0, steps=6)
    assert again["recovered"] == short["recovered"]
    assert again["misfit_final"] == short["misfit_final"]


def test_recover_source_end_to_end():
    from poisson_ellipse_tpu.diff.optimize import recover_source

    report = recover_source(grid=(14, 14), seed=1, steps=40)
    assert report["ok"], report
    assert report["misfit_drop"] >= 100.0
    again = recover_source(grid=(14, 14), seed=1, steps=40)
    assert again["misfit_final"] == report["misfit_final"]


# -- serving: the grad=True request kind -------------------------------------

# δ=1e-8 at these grids: tight enough for ~1e-5 gradient agreement,
# loose enough that the batched lane's denom breakdown guard (the
# reference's 1e-15) cannot fire before the step-norm rule does
SERVE_PROBLEM = Problem(M=12, N=12, delta=1e-8)
SERVE_SPEC = {"kind": "ellipse", "cx": 0.05, "cy": -0.02, "rx": 0.9,
              "ry": 0.45}


def _grad_request(request_id, problem=SERVE_PROBLEM, objective=None):
    return ServeRequest(
        problem=problem, grad=True, geometry=dict(SERVE_SPEC),
        objective=objective or {"kind": "energy"}, request_id=request_id,
    )


def test_serve_grad_request_completes_with_value_and_grad():
    sched = Scheduler(lanes=2, chunk=8, dtype=jnp.float64)
    assert sched.submit_request(_grad_request("g-1")) is None
    res = sched.drain()["g-1"]
    assert res.outcome == "completed" and res.detail == "grad"
    assert res.value is not None and res.grad is not None
    assert len(res.grad) == 4 and np.all(np.isfinite(res.grad))
    # the lane pair agrees with the direct implicit solve
    value, grad, _ = solve_grad_direct(_grad_request("direct"))
    assert abs(res.value - value) <= 1e-9 * max(abs(value), 1e-12)
    rel = np.abs(np.asarray(res.grad) - grad).max() / np.abs(grad).max()
    assert rel <= 1e-4, rel
    # a non-grad request never builds grad state
    assert not sched._grad_jobs


def test_serve_grad_mid_adjoint_kill_replays_identical_gradient(tmp_path):
    journal = os.path.join(str(tmp_path), "journal.json")
    s1 = Scheduler(lanes=2, chunk=4, dtype=jnp.float64, journal=journal)
    assert s1.submit_request(_grad_request("g-2")) is None
    # step the real scheduler until the request is MID-ADJOINT, then
    # drop the process state (SIGKILL semantics)
    for _ in range(500):
        s1.step()
        job = s1._grad_jobs.get("g-2")
        if job is not None and job.stage == "adjoint":
            break
    job = s1._grad_jobs.get("g-2")
    assert job is not None and job.stage == "adjoint", "never reached adjoint"

    # the uninterrupted gradient, for the identity pin
    s0 = Scheduler(lanes=2, chunk=4, dtype=jnp.float64)
    s0.submit_request(_grad_request("g-2"))
    clean = s0.drain()["g-2"]

    s2 = Scheduler(lanes=2, chunk=4, dtype=jnp.float64, journal=journal)
    assert s2.replay() == 1
    res = s2.drain()["g-2"]
    assert res.outcome == "completed"
    # deterministic recompute: the replayed gradient is IDENTICAL
    assert res.grad == clean.grad
    assert res.value == clean.value


def test_serve_grad_spec_journal_roundtrip():
    req = _grad_request("g-3")
    req.enqueued_t = 100.0
    req.deadline = 105.0
    spec = req.spec()
    back = ServeRequest.from_spec(json.loads(json.dumps(spec)), now=0.0)
    assert back.grad is True
    assert back.objective == {"kind": "energy"}
    assert back.geometry == SERVE_SPEC
    assert back.deadline == pytest.approx(5.0)
    # non-grad requests round-trip grad=False
    plain = ServeRequest(problem=SERVE_PROBLEM, request_id="p-1")
    assert ServeRequest.from_spec(plain.spec(), now=0.0).grad is False


def test_serve_grad_invalid_objective_classified_at_admission():
    sched = Scheduler(lanes=2, dtype=jnp.float64)
    res = sched.submit_request(
        _grad_request("g-4", objective={"kind": "nope"})
    )
    assert res is not None and res.outcome == "invalid"
    assert "objective" in res.detail
    # nothing journaled, nothing queued: the id is resubmittable
    assert not sched.queue.holds("g-4")
    # a non-numeric nested payload (numpy raises TypeError) must ALSO
    # end classified, never crash the admission path
    for i, bad in enumerate((
        {"kind": "l2", "target": {"a": 1}},
        {"kind": "l2", "target": [[None]]},
        {"kind": "flux", "weight": "grid"},
    )):
        r = sched.submit_request(_grad_request(f"g-4-{i}", objective=bad))
        assert r is not None and r.outcome == "invalid", (bad, r)


def test_serve_grad_fallback_honors_deadline():
    """The grad rung of the guarded fallback enforces the deadline at
    its (whole-solve) granularity: a gradient finishing past the
    deadline is classified deadline-miss, never delivered completed."""
    from poisson_ellipse_tpu.resilience.faultinject import Fault, FaultPlan

    t = [0.0]

    def clock():
        return t[0]

    def idle(s):
        t[0] += s

    sched = Scheduler(
        lanes=1, chunk=4, dtype=jnp.float64, max_retries=0,
        clock=clock, idle=idle,
        faults=FaultPlan(Fault("nan", at_iter=2, field="r",
                               request_id="g-7", persistent=True)),
    )
    req = _grad_request("g-7")
    req.deadline = 60.0  # alive at fallback entry...
    assert sched.submit_request(req) is None

    from poisson_ellipse_tpu.diff import serving as diff_serving

    orig = diff_serving.solve_grad_direct

    def slow_direct(r, **kw):
        out = orig(r, **kw)
        t[0] += 120.0  # ...but the solve outlives the deadline
        return out

    diff_serving.solve_grad_direct = slow_direct
    try:
        res = sched.drain()["g-7"]
    finally:
        diff_serving.solve_grad_direct = orig
    assert res.outcome == "deadline-miss", (res.outcome, res.detail)
    assert "grad-fallback-exceeded-deadline" in res.detail
    assert res.grad is None


def test_serve_grad_retry_resets_to_primal():
    """A faulted lane mid-gradient walks the normal retry ladder and
    the job restarts from the primal — the eventual gradient matches
    the clean run's (deterministic recompute)."""
    from poisson_ellipse_tpu.resilience.faultinject import Fault, FaultPlan

    sched = Scheduler(
        lanes=2, chunk=4, dtype=jnp.float64, max_retries=2,
        faults=FaultPlan(Fault("nan", at_iter=3, field="r",
                               request_id="g-5")),
    )
    assert sched.submit_request(_grad_request("g-5")) is None
    res = sched.drain()["g-5"]
    assert res.outcome == "completed"
    assert res.attempts >= 2  # the ladder really fired
    clean = Scheduler(lanes=2, chunk=4, dtype=jnp.float64)
    clean.submit_request(_grad_request("g-5"))
    ref = clean.drain()["g-5"]
    assert res.grad == ref.grad


def test_serve_grad_adjoint_reentry_survives_full_queue():
    """The adjoint re-queue goes through the replay-backlog waves: a
    full bounded queue (capacity 1, occupied by another admission) must
    neither lose the gradient request nor evict the other admission —
    push_front on a deque(maxlen) would have silently dropped one."""
    s = Scheduler(lanes=1, chunk=4, queue_capacity=1, dtype=jnp.float64)
    assert s.submit_request(_grad_request("g-6")) is None
    s.step()  # the primal takes the only lane
    assert s.submit_request(
        ServeRequest(problem=SERVE_PROBLEM, request_id="plain")
    ) is None  # fills the single queue slot
    results = s.drain()
    assert results["g-6"].outcome == "completed"
    assert results["g-6"].grad is not None
    assert results["plain"].outcome == "completed"


def test_chaos_stream_with_grad_requests(tmp_path):
    from poisson_ellipse_tpu.serve.chaos import run_chaos

    journal = os.path.join(str(tmp_path), "chaos.json")
    report = run_chaos(
        n_requests=10, seed=3, journal_path=journal, kill_after=5,
        nan_request=1, oom_request=None, grad_requests=(2, 7),
    )
    assert report.ok, report.json_dict()
    assert report.grad_requests == 2
    assert not report.grad_missing_payload
    # deterministic in the seed
    report2 = run_chaos(
        n_requests=10, seed=3,
        journal_path=os.path.join(str(tmp_path), "chaos2.json"),
        kill_after=5, nan_request=1, oom_request=None,
        grad_requests=(2, 7),
    )
    assert report2.outcomes == report.outcomes


# -- CLI ---------------------------------------------------------------------


def test_harness_grad_cli_source_workload(capsys):
    from poisson_ellipse_tpu.harness.__main__ import main as harness_main

    rc = harness_main([
        "grad", "--workload", "source", "--grid", "12x12",
        "--steps", "30", "--seed", "1", "--json",
    ])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    report = json.loads(out)
    assert rc == 0 and report["ok"]
    assert report["misfit_drop"] >= 100.0
