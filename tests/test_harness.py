"""Harness layer: run_once reports, CLI contract, phase profiler.

The reference's manual oracle is its printed rank-0 summary (iteration
count + time, ``stage2-mpi/poisson_mpi_decomp.cpp:493-498``); these tests
pin the same facts programmatically: oracle iteration counts, convergence,
L2 error magnitude, and that the CLI accepts the reference's argv shape
(``argv[1]=M argv[2]=N``, ``poisson_mpi_cuda2.cu:995-999``).
"""

import json

import jax.numpy as jnp
import pytest

from poisson_ellipse_tpu.harness import run_once
from poisson_ellipse_tpu.harness.__main__ import main as cli_main
from poisson_ellipse_tpu.harness.profile import (
    format_phases,
    profile_single,
)
from poisson_ellipse_tpu.models.problem import Problem


def test_run_once_single_matches_oracle():
    report = run_once(Problem(M=40, N=40), mode="single", dtype="f64")
    assert report.iters == 50  # weighted-norm oracle @ 40x40
    assert report.converged and not report.breakdown
    assert report.l2_error == pytest.approx(3.68e-3, rel=0.05)
    assert report.t_solver > 0 and report.t_init > 0
    assert "Converged after 50 iterations" in report.summary()


def test_run_once_sharded_matches_single():
    single = run_once(Problem(M=40, N=40), mode="single", dtype="f64")
    sharded = run_once(Problem(M=40, N=40), mode="sharded", dtype="f64")
    assert sharded.mesh_shape == (2, 4)  # 8 virtual devices, near-square
    assert sharded.iters == single.iters
    assert sharded.l2_error == pytest.approx(single.l2_error, rel=1e-6)


def test_run_once_sharded_fused_engine():
    """mode=sharded engine=fused drives the two-kernel per-shard path
    end-to-end through the harness (oracle + report plumbing)."""
    report = run_once(
        Problem(M=40, N=40), mode="sharded", dtype="f32", engine="fused"
    )
    assert report.engine == "fused"
    assert report.iters == 50 and report.converged


def test_run_once_explicit_mesh_shape():
    report = run_once(
        Problem(M=20, N=20), mode="sharded", mesh_shape=(2, 2), dtype="f64"
    )
    assert report.mesh_shape == (2, 2)
    assert report.converged


def test_cli_positional_grid_and_json(capsys):
    rc = cli_main(["40", "40", "--mode", "single", "--dtype", "f64", "--json"])
    assert rc == 0
    line = capsys.readouterr().out.strip()
    rec = json.loads(line)
    assert rec["M"] == 40 and rec["N"] == 40
    assert rec["iters"] == 50 and rec["converged"] is True


def test_cli_grid_sweep_and_eps_sweep(capsys):
    rc = cli_main(
        [
            "--grids",
            "10x10,20x20",
            "--mode",
            "single",
            "--dtype",
            "f64",
            "--json",
        ]
    )
    assert rc == 0
    recs = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert [r["iters"] for r in recs] == [15, 26]  # weighted oracles

    rc = cli_main(
        [
            "20",
            "20",
            "--mode",
            "single",
            "--dtype",
            "f64",
            "--eps-sweep",
            "1e-2,1e-4",
            "--json",
        ]
    )
    assert rc == 0
    recs = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert [r["eps"] for r in recs] == [1e-2, 1e-4]
    # stiffer fictitious domain (smaller eps) must not take fewer iters
    assert recs[1]["iters"] >= recs[0]["iters"]


def test_cli_unconverged_exit_code():
    rc = cli_main(
        ["40", "40", "--mode", "single", "--dtype", "f64", "--max-iter", "3"]
    )
    assert rc == 1


def test_readme_python_surfaces_importable():
    """Every import the README's Python examples advertise must exist —
    the public API surface the docs promise is pinned here so it cannot
    silently drift from the documentation."""
    from poisson_ellipse_tpu import Problem as _P, solve as _s  # noqa: F401
    from poisson_ellipse_tpu.parallel import solve_sharded  # noqa: F401
    from poisson_ellipse_tpu.parallel.multihost import (  # noqa: F401
        global_mesh,
        initialize_multihost,
        process_info,
        shutdown_multihost,
    )
    from poisson_ellipse_tpu.runtime import solve_native  # noqa: F401
    from poisson_ellipse_tpu.solver import solve_with_checkpoints  # noqa: F401


def test_phase_timer_decomposition_sums_to_total():
    """SURVEY §4's benchmark smoke: the named phase accumulators must
    decompose the wall clock — their sum matches an outer total timer
    (the stage4 init/solver/finalize split's defining invariant), and
    re-entering a phase accumulates rather than overwrites."""
    import time as _time

    from poisson_ellipse_tpu.utils.timing import PhaseTimer

    t = PhaseTimer()
    t0 = _time.perf_counter()
    with t.phase("init"):
        _time.sleep(0.02)
    with t.phase("solver"):
        _time.sleep(0.03)
    with t.phase("solver"):
        _time.sleep(0.01)
    total = _time.perf_counter() - t0
    assert set(t.totals) == {"init", "solver"}
    assert t.totals["solver"] > t.totals["init"]
    phase_sum = sum(t.totals.values())
    # phases cover everything but the negligible inter-phase gaps
    assert 0.9 * phase_sum <= total <= phase_sum + 0.05
    assert "T_solver" in t.report()


def test_profile_single_phases():
    phases = profile_single(Problem(M=32, N=32), jnp.float64, reps=5)
    assert set(phases) == {"stencil", "dot", "precond", "update", "halo"}
    assert phases["halo"] == 0.0
    assert all(v >= 0.0 for v in phases.values())
    text = format_phases(phases, iters=10)
    assert "t_stencil" in text and "x10 iters" in text


def test_profile_sharded_phases():
    """The sharded table covers every stage4 accumulator analog —
    including the update/axpy phase (``update_w_r_kernel``), which used
    to be single-device-only (``poisson_mpi_cuda2.cu:696-700``)."""
    from poisson_ellipse_tpu.harness.profile import profile_sharded

    phases = profile_sharded(Problem(M=32, N=32), reps=5)
    assert set(phases) == {
        "halo", "stencil", "stencil_pure", "precond", "dot", "update",
    }
    assert all(v >= 0.0 for v in phases.values())


def test_cli_native_backend(capsys):
    from poisson_ellipse_tpu.runtime import native_available

    if not native_available():
        pytest.skip("C++ runtime unavailable")
    rc = cli_main(["40", "40", "--mode", "native", "--threads", "1", "--json"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["iters"] == 50 and rec["dtype"] == "f64"


def test_cli_checkpointed_sharded_run(tmp_path, capsys):
    ck = str(tmp_path / "ck")
    argv = [
        "40", "40", "--mode", "sharded", "--dtype", "f64",
        "--checkpoint-dir", ck, "--chunk", "12", "--json",
    ]
    rc = cli_main(argv)
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["iters"] == 50 and rec["converged"] is True
    assert rec["mesh"] == [2, 4]
    # a second invocation resumes from the finished checkpoint: the carry
    # is already converged, so it completes without re-iterating
    rc = cli_main(argv)
    assert rc == 0
    rec2 = json.loads(capsys.readouterr().out.strip())
    assert rec2["iters"] == 50 and rec2["converged"] is True


def test_run_once_checkpointed_single(tmp_path):
    report = run_once(
        Problem(M=20, N=20),
        mode="single",
        dtype="f64",
        checkpoint_dir=str(tmp_path / "ck"),
        chunk=7,
    )
    assert report.iters == 26 and report.converged


@pytest.mark.parametrize("engine", ["resident", "streamed", "xl", "fused"])
def test_run_once_checkpoint_rejects_whole_kernel_engines(tmp_path, engine):
    """Checkpointing persists the XLA-loop PCG carry; the whole-solve
    kernel engines (whose state lives in VMEM scratch / kernel-private
    HBM) must be rejected with the xla-or-pallas pointer."""
    with pytest.raises(ValueError, match="xla or pallas"):
        run_once(
            Problem(M=20, N=20),
            mode="single",
            engine=engine,
            checkpoint_dir=str(tmp_path / "ck"),
        )


def test_cli_checkpoint_sweep_uses_per_run_subdirs(tmp_path):
    ck = str(tmp_path / "ck")
    rc = cli_main([
        "--grids", "10x10,20x20", "--mode", "single", "--dtype", "f64",
        "--checkpoint-dir", ck, "--chunk", "6", "--json",
    ])
    assert rc == 0
    import os

    assert os.path.isdir(os.path.join(ck, "10x10"))
    assert os.path.isdir(os.path.join(ck, "20x20"))


def test_run_once_checkpoint_rejects_repeat_batch(tmp_path):
    with pytest.raises(ValueError, match="repeat/batch"):
        run_once(
            Problem(M=10, N=10),
            mode="single",
            checkpoint_dir=str(tmp_path / "ck"),
            repeat=3,
        )


def test_run_once_unknown_mode_raises_with_checkpoint(tmp_path):
    with pytest.raises(ValueError, match="unknown mode"):
        run_once(
            Problem(M=10, N=10),
            mode="bogus",
            checkpoint_dir=str(tmp_path / "ck"),
        )


def test_cli_threads_sweep(capsys):
    from poisson_ellipse_tpu.runtime import native_available

    if not native_available():
        pytest.skip("C++ runtime unavailable")
    rc = cli_main(
        ["40", "40", "--mode", "native", "--threads-sweep", "1,2", "--json"]
    )
    assert rc == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 2
    recs = [json.loads(l) for l in lines]
    # the stage1 invariant: iteration count is thread-invariant
    assert [r["iters"] for r in recs] == [50, 50]
    assert [r["threads"] for r in recs] == [1, 2]
    assert recs[0]["speedup_vs_first"] == 1.0


def test_cli_threads_sweep_requires_native_mode(capsys):
    rc = cli_main(["40", "40", "--mode", "single", "--threads-sweep", "1,2"])
    assert rc == 2
    assert "requires --mode native" in capsys.readouterr().err


def test_readme_bench_generator(tmp_path):
    """tools/update_readme_bench.py regenerates exactly the marker
    blocks from a bench artifact (driver format), leaves surrounding
    text untouched, and rejects artifacts predating the
    machine-readable rows."""
    import importlib.util
    import os
    import sys

    spec = importlib.util.spec_from_file_location(
        "urb",
        os.path.join(
            os.path.dirname(__file__), "..", "tools", "update_readme_bench.py"
        ),
    )
    urb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(urb)

    readme = tmp_path / "README.md"
    readme.write_text(
        "intro\n<!-- bench:headline -->\nOLD\n<!-- /bench:headline -->\n"
        "mid\n<!-- bench:table -->\nOLD\n<!-- /bench:table -->\noutro\n"
    )
    row = {
        "grid": [800, 1200], "t_solver_s": 0.008, "iters": 989,
        "converged": True, "engine": "resident", "l2_error": 2e-4,
        "ref_p100_s": 0.83, "vs_p100": 103.75,
    }
    artifact = tmp_path / "BENCH_r99.json"
    artifact.write_text(json.dumps({"parsed": {
        "metric": "m", "value": 0.008, "unit": "s", "vs_baseline": 103.75,
        "valid": True, "grids": [row],
        "config2": {**row, "grid": [1024, 1024]},
        "north_star": {**row, "grid": [4096, 4096], "engine": "xl"},
        "eps_sweep": [
            {"eps": 1e-2, "iters": 921, "converged": True,
             "t_solver_s": 0.01, "l2_error": 2e-4},
            {"eps": 1e-6, "iters": 921, "converged": True,
             "t_solver_s": 0.01, "l2_error": 2e-4},
        ],
        "f64": {**row},
    }}))
    summary = urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "OLD" not in text
    assert "103.75×" in text and "| 800×1200 |" in text
    assert text.startswith("intro\n") and text.rstrip().endswith("outro")
    assert "BENCH_r99.json" in summary
    # config4_1chip absent (older artifact shape): tolerated, no row
    assert "config-4" not in text
    # pre-machine-readable artifact is rejected with a pointer
    legacy = tmp_path / "legacy.json"
    legacy.write_text(json.dumps({"parsed": {"value": 1}}))
    with pytest.raises(SystemExit, match="machine-readable"):
        urb.regenerate(str(readme), str(legacy))


def test_bench_eps_sweep_solver_reuse_is_exact():
    """bench.py's eps-sweep reuses ONE jitted XLA solver across eps
    values (eps reaches the solve only through the assembled operands).
    Guard that assumption: a solver built for one eps, fed another eps's
    operands, must reproduce the fresh per-problem solve exactly."""
    from poisson_ellipse_tpu.ops import assembly as asm
    from poisson_ellipse_tpu.solver.engine import build_solver
    from poisson_ellipse_tpu.solver.pcg import solve as solve_xla

    p_a = Problem(M=24, N=24, eps=1e-2)
    p_b = Problem(M=24, N=24, eps=1e-5)
    reused, _, _ = build_solver(p_a, "xla", jnp.float32)
    fresh, _, _ = build_solver(p_b, "xla", jnp.float32)
    args_b = asm.assemble(p_b, jnp.float32)
    got = reused(*args_b)
    ref = fresh(*args_b)
    assert bool(got.converged)
    assert int(got.iters) == int(ref.iters)
    # also iteration-identical to the independent solve() entry point
    assert int(got.iters) == int(solve_xla(p_b, jnp.float32).iters)
    import numpy as np

    np.testing.assert_array_equal(np.asarray(got.w), np.asarray(ref.w))


def test_bench_f64_row_oracle():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    ok, row = bench.bench_f64_row(grid=(40, 40), oracle=50)
    assert ok is True
    assert row["grid"] == [40, 40] and row["iters"] == 50
    ok, _ = bench.bench_f64_row(grid=(40, 40), oracle=999)
    assert ok is False


def test_cli_threads_sweep_conflicting_flags(capsys):
    rc = cli_main(
        ["40", "40", "--mode", "native", "--threads-sweep", "1,2",
         "--threads", "8"]
    )
    assert rc == 2 and "--threads conflicts" in capsys.readouterr().err
    rc = cli_main(
        ["40", "40", "--mode", "native", "--threads-sweep", "1,2",
         "--checkpoint-dir", "ck"]
    )
    assert rc == 2 and "not native" in capsys.readouterr().err


def test_resumed_checkpoint_report_suppresses_roofline(tmp_path):
    ck = str(tmp_path / "ck")
    first = run_once(
        Problem(M=20, N=20), mode="single", dtype="f64",
        checkpoint_dir=ck, chunk=7,
    )
    assert first.timed_iters == first.iters == 26
    assert first.roofline_line() != ""
    # resume of a finished run: zero iterations timed -> no roofline
    again = run_once(
        Problem(M=20, N=20), mode="single", dtype="f64",
        checkpoint_dir=ck, chunk=7,
    )
    assert again.iters == 26 and again.timed_iters == 0
    assert again.roofline_line() == ""
    assert again.hbm_gbps == 0.0 and again.passes_per_iter == 0.0


def test_roofline_line_vmem_resident_wording():
    from poisson_ellipse_tpu.harness.run import RunReport

    rep = RunReport(
        problem=Problem(M=40, N=40), mesh_shape=(1, 1), dtype="f32",
        engine="resident", iters=50, converged=True, breakdown=False,
        diff=1e-7, l2_error=1e-3, t_init=0.1, t_solver=0.001,
        passes_per_iter=0.0, hbm_gbps=0.0, hbm_peak_frac=0.0,
    )
    line = rep.roofline_line()
    assert "VMEM-resident" in line and "0 GB/s" not in line


def test_acceptance_gate_passes_on_cpu():
    # on CPU the Pallas engines run in interpret mode; the oracle/contract
    # logic is identical, and the real-compile value comes from running
    # the same module on the chip (python -m ...harness.acceptance)
    from poisson_ellipse_tpu.harness.acceptance import run_acceptance
    import io

    buf = io.StringIO()
    assert run_acceptance(headline=False, out=buf) is True
    text = buf.getvalue()
    assert "ACCEPTANCE PASS" in text and "FAIL" not in text
