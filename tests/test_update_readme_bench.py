"""tools/update_readme_bench.py: the README generator, under test.

The README's performance blocks are generated, so the generator is
load-bearing documentation infrastructure: a silent regression here
re-introduces exactly the hand-typed-numbers drift the tool exists to
prevent. Covered: artifact selection (round-number order, not
lexicographic), partial-artifact rejection with the curated message,
headline derivation from the artifact's own rows (no hardcoded grid/
chip/baseline), and marker-splice round-tripping (idempotence).
"""

from __future__ import annotations

import importlib.util
import json
import os
import time

import pytest

_TOOL = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools",
    "update_readme_bench.py",
)
_spec = importlib.util.spec_from_file_location("update_readme_bench", _TOOL)
urb = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(urb)


def make_artifact(**overrides) -> dict:
    rec = {
        "metric": "T_solver 100x200 (42 PCG iters to 1e-6), f32, 1 chip",
        "value": 0.5,
        "unit": "s",
        "vs_baseline": 4.0,
        "valid": True,
        "device": "TPU v6e",
        "grids": [
            {"grid": [100, 200], "t_solver_s": 0.5, "iters": 42,
             "converged": True, "engine": "resident",
             "ref_p100_s": 2.0, "vs_p100": 4.0},
            {"grid": [400, 600], "t_solver_s": 1.25, "iters": 99,
             "converged": True, "engine": "xl",
             "ref_p100_s": None, "vs_p100": None},
        ],
        "config2": {"grid": [64, 64], "t_solver_s": 0.01, "iters": 7,
                    "converged": True, "engine": "resident"},
        "eps_sweep": [
            {"eps": 1e-2, "iters": 7, "converged": True, "t_solver_s": 0.01},
            {"eps": 1e-6, "iters": 9, "converged": True, "t_solver_s": 0.01},
        ],
        "f64": {"grid": [100, 200], "t_solver_s": 3.0, "iters": 42,
                "converged": True, "engine": "xla"},
    }
    rec.update(overrides)
    return rec


def test_pipelined_row_rendered_when_present(workspace):
    _tmp, readme, artifact = workspace
    rec = make_artifact(
        pipelined={
            "grid": [100, 200], "t_solver_s": 0.45, "iters": 41,
            "converged": True, "engine": "pipelined", "l2_error": 1e-4,
            "t_xla_s": 0.5, "vs_xla": 1.111,
        }
    )
    artifact.write_text(json.dumps(rec))
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "| 100×200 | 41 | pipelined | 0.4500 s |" in text
    assert "1 fused reduction/iter" in text
    assert "1.111× vs xla" in text
    # pre-pipelined artifacts still regenerate, without the row
    artifact.write_text(json.dumps(make_artifact()))
    urb.regenerate(str(readme), str(artifact))
    assert "pipelined" not in readme.read_text()


def test_observability_fields_rendered_when_present(workspace):
    _tmp, readme, artifact = workspace
    rec = make_artifact(
        convergence={
            "grid": [100, 200], "engine": "xla", "iters": 42,
            "converged": True, "diff_first": 0.02, "diff_final": 9.7e-7,
            "zr_first": 1e-3, "zr_final": 1e-14,
        },
        collectives={
            "available": True, "grid": [40, 40], "mesh": [1, 2],
            "engines": {
                "xla": {"psum_per_iter": 2, "ppermute_per_iter": 4},
                "pipelined": {"psum_per_iter": 1, "ppermute_per_iter": 12},
            },
        },
    )
    artifact.write_text(json.dumps(rec))
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "42 iterations traced" in text
    assert "2.0e-02 → 9.7e-07" in text
    assert "**2** psum/iteration, pipelined **1**" in text
    assert "obs.static_cost" in text


def test_observability_fields_absent_is_supported(workspace):
    # pre-obs artifacts (no convergence/collectives keys) and skipped
    # accounting (available: false) both render without the lines
    _tmp, readme, artifact = workspace
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "iterations traced" not in text
    assert "psum/iteration" not in text
    artifact.write_text(
        json.dumps(make_artifact(collectives={"available": False}))
    )
    urb.regenerate(str(readme), str(artifact))
    assert "psum/iteration" not in readme.read_text()


def test_spectrum_table_rendered_when_present(workspace):
    _tmp, readme, artifact = workspace
    rec = make_artifact(
        spectrum=[
            {"grid": [100, 200], "engine": "xla", "iters": 42,
             "converged": True, "kappa": 5432.1, "cg_rate": 0.97325,
             "iters_bound": 80, "predicted_iters": 42,
             "predicted_err": 0.0, "stagnated": False},
        ]
    )
    artifact.write_text(json.dumps(rec))
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "Spectral diagnostics" in text
    assert "| 100×200 | 5432 | 0.97325 | 80 | 42 (+0.0%) | 42 |" in text
    assert "bench_compare" in text


def test_spectrum_absent_or_failed_is_supported(workspace):
    # pre-diagnostics artifacts lack the key; a failed row carries no
    # kappa — neither renders the table
    _tmp, readme, artifact = workspace
    urb.regenerate(str(readme), str(artifact))
    assert "Spectral diagnostics" not in readme.read_text()
    artifact.write_text(json.dumps(make_artifact(
        spectrum=[{"grid": [100, 200], "engine": "xla", "iters": 42,
                   "converged": False}]
    )))
    urb.regenerate(str(readme), str(artifact))
    assert "Spectral diagnostics" not in readme.read_text()


def test_precond_table_rendered_when_present(workspace):
    _tmp, readme, artifact = workspace
    rec = make_artifact(
        precond=[
            {"grid": [400, 600], "engine": "mg-pcg", "iters": 31,
             "t_solver_s": 0.0123, "converged": True, "l2_error": 1e-4,
             "diag_iters": 546, "diag_t_solver_s": 0.05,
             "iters_reduction": 17.6, "speedup_vs_diag": 4.07},
            {"grid": [800, 1200], "engine": "cheb-pcg", "iters": 90,
             "t_solver_s": 0.02, "converged": True, "l2_error": 2e-5},
        ]
    )
    artifact.write_text(json.dumps(rec))
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "Preconditioning" in text
    assert (
        "| 400×600 | mg-pcg | 31 (diag 546) | **17.6× fewer** | "
        "0.0123 s | 4.07× |" in text
    )
    # a row without the diag yardstick still renders, with dashes
    assert "| 800×1200 | cheb-pcg | 90 | — | 0.0200 s | — |" in text


def test_precond_absent_or_failed_is_supported(workspace):
    # pre-multigrid artifacts lack the key; a failed row (the run
    # aborted before an iteration count) is skipped, not a crash
    _tmp, readme, artifact = workspace
    urb.regenerate(str(readme), str(artifact))
    assert "Preconditioning (`mg/`" not in readme.read_text()
    artifact.write_text(json.dumps(make_artifact(
        precond=[{"grid": [400, 600], "engine": "mg-pcg",
                  "converged": False}]
    )))
    urb.regenerate(str(readme), str(artifact))
    assert "Preconditioning (`mg/`" not in readme.read_text()


def test_recovery_field_rendered_when_present(workspace):
    _tmp, readme, artifact = workspace
    rec = make_artifact(
        recovery={
            "grid": [100, 200], "engine": "xla", "fault": "nan", "at": 21,
            "iters": 42, "clean_iters": 42, "converged": True,
            "recoveries": ["residual-restart"],
        }
    )
    artifact.write_text(json.dumps(rec))
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "Resilience drill" in text
    assert "iteration 21" in text
    assert "residual-restart" in text
    assert "reconverges in 42 iterations" in text
    assert "oracle parity after recovery" in text


def test_recovery_field_absent_or_failed_is_supported(workspace):
    # pre-resilience artifacts lack the key entirely; an aborted drill
    # carries converged: false — neither renders the line
    _tmp, readme, artifact = workspace
    urb.regenerate(str(readme), str(artifact))
    assert "Resilience drill" not in readme.read_text()
    artifact.write_text(json.dumps(make_artifact(
        recovery={"grid": [100, 200], "engine": "xla", "fault": "nan",
                  "at": 21, "converged": False, "aborted": "diverged"}
    )))
    urb.regenerate(str(readme), str(artifact))
    assert "Resilience drill" not in readme.read_text()


def test_abft_field_rendered_when_present(workspace):
    _tmp, readme, artifact = workspace
    rec = make_artifact(
        abft={
            "available": True, "grid": [800, 1200], "mesh": [1, 2],
            "t_off_s": 1.0, "t_on_s": 1.012, "overhead_pct": 1.2,
            "gate_pct": 2.0, "iters_off": 99, "iters_on": 99,
            "psum_per_iter": 2, "ppermute_per_iter": 4,
            "collectives_identical": True, "ok": True,
        }
    )
    artifact.write_text(json.dumps(rec))
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "ABFT silent-corruption checks" in text
    assert "+1.20%" in text
    assert "collective counts identical on/off" in text
    assert "2 psum/iteration" in text


def test_abft_field_absent_or_failed_is_supported(workspace):
    # pre-abft artifacts lack the key; a single-device bench box emits
    # available: false — neither renders the line
    _tmp, readme, artifact = workspace
    urb.regenerate(str(readme), str(artifact))
    assert "ABFT silent-corruption checks" not in readme.read_text()
    artifact.write_text(json.dumps(make_artifact(
        abft={"available": False}
    )))
    urb.regenerate(str(readme), str(artifact))
    assert "ABFT silent-corruption checks" not in readme.read_text()


def test_throughput_and_coldstart_rendered_when_present(workspace):
    _tmp, readme, artifact = workspace
    rec = make_artifact(
        throughput=[
            {"grid": [100, 200], "lanes": 1, "engine": "batched",
             "t_batch_s": 0.5, "solves_per_sec": 2.0,
             "speedup_vs_1lane": 1.0, "iters": 42, "converged": True},
            {"grid": [100, 200], "lanes": 8, "engine": "batched",
             "t_batch_s": 1.0, "solves_per_sec": 8.0,
             "speedup_vs_1lane": 4.0, "iters": 42, "converged": True},
        ],
        coldstart={
            "grid": [100, 200], "engine": "batched", "lanes": 8,
            "t_compile_s": 2.5, "t_solve_s": 0.5,
            "t_pool_cold_s": 2.4, "t_pool_warm_s": 0.0002,
            "pool_hit": True,
        },
    )
    artifact.write_text(json.dumps(rec))
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "Serving throughput" in text
    assert "| 100×200 | 8 | 1.00 s | 8 | **4×** |" in text
    assert "Cold-start split (100×200, lanes=8)" in text
    assert "compile 2.50 s vs solve 0.5000 s" in text
    assert "cache HIT returning the same executable (0.20 ms)" in text


def test_throughput_absent_or_failed_is_supported(workspace):
    # pre-batch artifacts lack the keys; a failed throughput row (no
    # solves_per_sec — the run aborted) is skipped, a missed warm pool
    # renders as the regression it is
    _tmp, readme, artifact = workspace
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "Serving throughput" not in text
    assert "Cold-start split" not in text
    artifact.write_text(json.dumps(make_artifact(
        throughput=[{"grid": [100, 200], "lanes": 8, "engine": "batched",
                     "converged": False}],
        coldstart={"grid": [100, 200], "engine": "batched", "lanes": 8,
                   "t_compile_s": 2.5, "t_solve_s": 0.5,
                   "t_pool_cold_s": 2.4, "t_pool_warm_s": 2.3,
                   "pool_hit": False},
    )))
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "Serving throughput" not in text  # no renderable rows
    assert "MISSED the warm pool (regression)" in text


README_STUB = """# stub

<!-- bench:headline -->
stale headline
<!-- /bench:headline -->

prose between the blocks

<!-- bench:table -->
stale table
<!-- /bench:table -->
"""


@pytest.fixture
def workspace(tmp_path):
    readme = tmp_path / "README.md"
    readme.write_text(README_STUB)
    artifact = tmp_path / "BENCH_r02.json"
    artifact.write_text(json.dumps({"parsed": make_artifact()}))
    return tmp_path, readme, artifact


def test_fleet_table_rendered_when_present(workspace):
    _tmp, readme, artifact = workspace
    rec = make_artifact(fleet={
        "rows": [
            {"replicas": 1, "lanes": 2, "solves_per_sec": 100.0},
            {"replicas": 3, "lanes": 2, "solves_per_sec": 115.0},
        ],
        "non_decreasing": True,
        "handoff_p99_s": 0.0025,
        "rejoin_latency_s": 0.31,
        "handoffs": 1,
        "adopted": 3,
        "rejoins": 1,
        "kill_completed": 24,
    })
    artifact.write_text(json.dumps(rec))
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "Replicated fleet" in text
    assert "| 1 | 2 | 100 |" in text
    assert "| 3 | 2 | 115 |" in text
    assert "handoff latency p99 2.50 ms" in text
    assert "3 request(s) adopted" in text
    # the kill-drill sentence states only what the artifact carries
    assert "24 request(s) completed after the kill" in text
    assert "zero requests lost" not in text
    assert "kill→first-completed-solve p99 310.00 ms" in text
    assert "1 rejoin(s)" in text


def test_fleet_absent_or_failed_is_supported(workspace):
    # pre-fleet artifacts lack the key; a failed key (no usable rows)
    # renders nothing; rows without a kill drill render the table alone
    _tmp, readme, artifact = workspace
    urb.regenerate(str(readme), str(artifact))
    assert "Replicated fleet" not in readme.read_text()
    artifact.write_text(json.dumps(make_artifact(fleet={"rows": []})))
    urb.regenerate(str(readme), str(artifact))
    assert "Replicated fleet" not in readme.read_text()
    artifact.write_text(json.dumps(make_artifact(fleet={
        "rows": [{"replicas": 2, "lanes": 2, "solves_per_sec": 90.0}],
        "non_decreasing": True,
        "handoff_p99_s": None,
    })))
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "| 2 | 2 | 90 |" in text
    assert "Kill drill" not in text
    assert "Rejoin drill" not in text
    # a pre-rejoin artifact (kill drill but no recovery number) renders
    # the kill line alone
    artifact.write_text(json.dumps(make_artifact(fleet={
        "rows": [{"replicas": 2, "lanes": 2, "solves_per_sec": 90.0}],
        "non_decreasing": True,
        "handoff_p99_s": 0.002,
        "handoffs": 1,
    })))
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "Kill drill" in text
    assert "Rejoin drill" not in text


def test_regenerate_derives_everything_from_artifact(workspace):
    tmp, readme, artifact = workspace
    summary = urb.regenerate(str(readme), str(artifact), root=str(tmp))
    text = readme.read_text()
    assert "BENCH_r02.json" in summary
    head = text.split("<!-- bench:headline -->")[1].split(
        "<!-- /bench:headline -->"
    )[0]
    # grid, iters, δ, chip and baseline all come from the artifact rows
    assert "**0.5000 s** for 100×200" in head
    assert "42 iterations to δ=1e-6" in head
    assert "TPU v6e" in head
    assert "single-P100 2.0 s" in head
    assert "**4×**" in head
    # the headline row is bolded in the table; non-reference rows dashed
    assert "| 100×200 | 42 | resident | **0.5000 s** | 2.0 s | **4×** |" in text
    assert "| 400×600 | 99 | xl | 1.25 s | — | — |" in text
    # prose outside the markers untouched
    assert "prose between the blocks" in text


def test_regenerate_is_idempotent(workspace):
    tmp, readme, artifact = workspace
    urb.regenerate(str(readme), str(artifact), root=str(tmp))
    once = readme.read_text()
    urb.regenerate(str(readme), str(artifact), root=str(tmp))
    assert readme.read_text() == once


def test_device_falls_back_to_measured_part(workspace):
    tmp, readme, artifact = workspace
    rec = make_artifact()
    del rec["device"]
    artifact.write_text(json.dumps(rec))  # raw bench.py line form
    urb.regenerate(str(readme), str(artifact), root=str(tmp))
    assert urb.MEASURED_DEVICE in readme.read_text()


@pytest.mark.parametrize("missing", ["grids", "config2", "eps_sweep", "f64"])
def test_partial_artifact_gets_curated_error(workspace, missing):
    tmp, readme, artifact = workspace
    rec = make_artifact()
    del rec[missing]
    artifact.write_text(json.dumps(rec))
    with pytest.raises(SystemExit) as exc:
        urb.regenerate(str(readme), str(artifact), root=str(tmp))
    assert missing in str(exc.value)
    assert "re-run" in str(exc.value)


def test_empty_rows_get_the_curated_error_too(workspace):
    # an aborted driver run can serialize "grids": [] — as unusable as
    # an absent key, and it must not surface as a raw IndexError
    tmp, readme, artifact = workspace
    artifact.write_text(json.dumps(make_artifact(grids=[])))
    with pytest.raises(SystemExit) as exc:
        urb.regenerate(str(readme), str(artifact), root=str(tmp))
    assert "grids" in str(exc.value)


def test_newest_artifact_by_round_number_not_lexicographic(tmp_path, capsys):
    # lexicographic sort would pick r9 over r10 and r100; round-number
    # parse must not
    for name in ("BENCH_r9.json", "BENCH_r10.json", "BENCH_r100.json"):
        (tmp_path / name).write_text("{}")
        time.sleep(0.01)
    # make the lexicographic winner also the mtime winner, so only the
    # round-number key can produce the right answer
    os.utime(tmp_path / "BENCH_r9.json")
    picked = urb.newest_artifact(str(tmp_path))
    assert os.path.basename(picked) == "BENCH_r100.json"
    assert "BENCH_r100.json" in capsys.readouterr().out


def test_missing_marker_is_a_curated_error(workspace):
    tmp, readme, artifact = workspace
    readme.write_text("# no markers here\n")
    with pytest.raises(SystemExit) as exc:
        urb.regenerate(str(readme), str(artifact), root=str(tmp))
    assert "marker" in str(exc.value)


def test_geometry_field_rendered_when_present(workspace):
    _tmp, readme, artifact = workspace
    rec = make_artifact(
        geometry={
            "grid": [400, 600], "assembly_cf_s": 0.2,
            "assembly_quad_s": 1.0, "assembly_overhead_x": 5.0,
            "max_frac_err": 3.7e-15, "sdf_ellipse_iters": 99,
            "oracle_iters": 99,
            "composite": {"domain": "ellipse-minus-hole",
                          "t_solver_s": 0.75, "iters": 88,
                          "converged": True, "min_u": 0.0},
        }
    )
    artifact.write_text(json.dumps(rec))
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "Geometry (SDF quadrature" in text
    assert "3.7e-15" in text
    assert "Composite domain (ellipse-minus-hole)" in text
    assert "maximum principle held" in text


def test_geometry_field_absent_or_failed_is_supported(workspace):
    # pre-geometry artifacts lack the key; a failed composite half
    # (no t_solver_s) renders the parity line only
    _tmp, readme, artifact = workspace
    urb.regenerate(str(readme), str(artifact))
    assert "Geometry (SDF quadrature" not in readme.read_text()
    artifact.write_text(json.dumps(make_artifact(
        geometry={
            "grid": [400, 600], "max_frac_err": 1e-14,
            "sdf_ellipse_iters": 99, "oracle_iters": 99,
            "composite": {"domain": "ellipse-minus-hole",
                          "converged": False},
        }
    )))
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "Geometry (SDF quadrature" in text
    assert "Composite domain" not in text


def test_grad_field_rendered_when_present(workspace):
    _tmp, readme, artifact = workspace
    rec = make_artifact(
        grad={
            "grid": [400, 600], "lanes": 4, "n_requests": 8,
            "grad_solves_per_sec": 12.5, "wall_s": 0.64,
            "rows": [{"grid": [400, 600], "primal_iters": 546,
                      "adjoint_iters": 540, "ratio": 0.989}],
            "valid": True,
        }
    )
    artifact.write_text(json.dumps(rec))
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "Differentiable solving" in text
    assert "12.5 grad-solves/sec" in text
    assert "540/546" in text
    assert "grad-pct" in text


def test_grad_field_absent_or_failed_is_supported(workspace):
    # pre-diff artifacts lack the key; a failed throughput half (no
    # grad_solves_per_sec) still renders the ratio rows it carries
    _tmp, readme, artifact = workspace
    urb.regenerate(str(readme), str(artifact))
    assert "Differentiable solving" not in readme.read_text()
    artifact.write_text(json.dumps(make_artifact(
        grad={
            "grid": [400, 600], "grad_solves_per_sec": None,
            "rows": [{"grid": [400, 600], "primal_iters": 546,
                      "adjoint_iters": 560, "ratio": 1.026}],
            "valid": False,
        }
    )))
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "Differentiable solving" not in text
    assert "Adjoint-vs-primal iterations" in text
    assert "560/546" in text


def test_bandwidth_table_rendered_when_present(workspace):
    rec = make_artifact(bandwidth={
        "available": True,
        "grid": [2400, 3200],
        "byte_ratio_gate": 0.6,
        "cells": [
            {"engine": "sstep", "storage": "f32", "t_solver_s": 1.2,
             "hbm_gbps": 310.0, "l2_err": 9.9e-5},
            {"engine": "sstep", "storage": "bf16", "t_solver_s": 0.7,
             "hbm_gbps": 520.0, "l2_err": 1.01e-4,
             "byte_ratio_vs_f32": 0.5, "l2_parity": True},
        ],
        "ok": True,
    })
    lines = urb.bandwidth_lines(rec)
    text = "\n".join(lines)
    assert "Memory-bandwidth frontier at 2400×3200" in text
    assert "| sstep | bf16 | 0.7 s | 520 |" in text
    assert "0.50×" in text


def test_bandwidth_absent_or_failed_is_supported(workspace):
    assert urb.bandwidth_lines(make_artifact()) == []
    assert urb.bandwidth_lines(
        make_artifact(bandwidth={"available": False, "error": "x"})
    ) == []
    assert urb.bandwidth_lines(
        make_artifact(bandwidth={"available": True, "cells": []})
    ) == []


def test_fmg_table_rendered_when_present(workspace):
    rec = make_artifact(fmg={
        "work_units_constant": True,
        "rows": [
            {"grid": [400, 600], "t_solver_s": 0.012, "iters": 3,
             "work_units_per_point": 60.5, "speedup_vs_mg": 1.3},
            {"grid": [4096, 4096], "t_solver_s": 0.31, "iters": 3,
             "work_units_per_point": 62.1, "speedup_vs_mg": 2.4,
             "headline": True},
        ],
    })
    text = "\n".join(urb.fmg_lines(rec))
    assert "Full multigrid as the solver" in text
    assert "work units per grid point constant" in text
    assert "| 4096×4096 (headline) |" in text
    assert "**2.4×**" in text


def test_fmg_absent_or_failed_is_supported(workspace):
    assert urb.fmg_lines(make_artifact()) == []
    assert urb.fmg_lines(make_artifact(fmg={"rows": []})) == []
    # a failed row (no t_solver_s) is skipped, not a crash
    assert urb.fmg_lines(make_artifact(fmg={
        "work_units_constant": True,
        "rows": [{"grid": [400, 600], "error": "OOM"}],
    })) == []


def test_autotune_table_rendered_when_present(workspace):
    rec = make_artifact(autotune={
        "rows": [
            {"grid": [400, 600], "tuned_engine": "fmg",
             "static_engine": "xl", "tuned_t_s": 0.012,
             "static_t_s": 0.05, "tuned_loses": False,
             "roundtrip_ok": True},
            {"grid": [100, 200], "tuned_engine": "resident",
             "static_engine": "resident", "tuned_t_s": 0.004,
             "static_t_s": 0.004, "tuned_loses": False,
             "roundtrip_ok": True},
        ],
    })
    text = "\n".join(urb.autotune_lines(rec))
    assert "Telemetry-driven autotuning" in text
    assert "tuned wins" in text
    assert "static stands" in text


def test_autotune_absent_or_failed_is_supported(workspace):
    assert urb.autotune_lines(make_artifact()) == []
    assert urb.autotune_lines(make_artifact(autotune={"rows": []})) == []
    assert urb.autotune_lines(make_artifact(autotune={
        "rows": [{"grid": [400, 600], "error": "probe failed"}],
    })) == []


def test_fmg_and_autotune_ride_the_table_block(workspace):
    _tmp, readme, artifact = workspace
    rec = make_artifact(
        fmg={"work_units_constant": True, "rows": [
            {"grid": [400, 600], "t_solver_s": 0.012, "iters": 3,
             "work_units_per_point": 60.5, "speedup_vs_mg": 1.3},
        ]},
        autotune={"rows": [
            {"grid": [400, 600], "tuned_engine": "fmg",
             "static_engine": "xl", "tuned_t_s": 0.012,
             "static_t_s": 0.05, "tuned_loses": False,
             "roundtrip_ok": True},
        ]},
    )
    artifact.write_text(json.dumps(rec))
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "Full multigrid as the solver" in text
    assert "Telemetry-driven autotuning" in text


def _recycle_key(cut=4.13, valid=True, **overrides):
    row = {
        "grid": [128, 128], "stream": 5, "ring_cap": 64, "basis_rank": 8,
        "capture_iters": 150, "iters_cold_mean": 149.6,
        "iters_warm_mean": 36.2, "iter_cut": cut, "l2_rel_gap_max": 0.0501,
        "solves_per_s_cold": 2.77, "solves_per_s_warm": 3.15,
        "converged": True, "valid": valid,
    }
    row.update(overrides)
    return row


def test_recycle_table_rendered_when_present(workspace):
    _tmp, readme, artifact = workspace
    artifact.write_text(json.dumps(make_artifact(recycle=_recycle_key())))
    urb.regenerate(str(readme), str(artifact))
    text = readme.read_text()
    assert "Krylov recycling" in text
    assert "149.6 → 36.2 | **4.13× cut**" in text
    assert "2.77 → 3.15 | 5.0% |" in text
    # a round whose cut fell below the pin renders the broken verdict
    # loudly instead of a bold headline
    artifact.write_text(json.dumps(
        make_artifact(recycle=_recycle_key(cut=1.7, valid=False))
    ))
    urb.regenerate(str(readme), str(artifact))
    assert "1.7× (PIN BROKEN)" in readme.read_text()


def test_recycle_absent_or_failed_is_supported(workspace):
    # pre-recycling artifacts lack the key; a declined capture carries
    # no iter_cut — neither renders the block
    _tmp, readme, artifact = workspace
    urb.regenerate(str(readme), str(artifact))
    assert "Krylov recycling" not in readme.read_text()
    assert urb.recycle_lines(make_artifact()) == []
    assert urb.recycle_lines(
        make_artifact(recycle={"grid": [128, 128], "valid": False})
    ) == []
