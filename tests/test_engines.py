"""The single-chip engine suite: fused/resident/streamed/xl vs the XLA path.

The reference's cross-implementation correctness oracle is iteration-count
invariance across its five implementations (SURVEY §4.2: the same grid
converges in the same number of PCG iterations in every stage). The
TPU engines are held to the same standard — identical iteration counts and
matching solutions on the oracle grids — plus capacity-gate and selection-
policy checks. Pallas kernels run in interpret mode on the CPU backend
(the engines' own ``_interpret_default``), so this suite needs no TPU.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.harness.__main__ import main as cli_main
from poisson_ellipse_tpu.harness.run import _chain_solver, run_once
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops.fused_pcg import interior_normalized, solve_fused
from poisson_ellipse_tpu.ops.resident_pcg import fits_resident, solve_resident
from poisson_ellipse_tpu.ops.streamed_pcg import (
    StreamPlan,
    build_streamed_solver,
    fits_streamed,
    solve_streamed,
)
from poisson_ellipse_tpu.ops.xl_pcg import XLPlan, build_xl_solver, solve_xl
from poisson_ellipse_tpu.solver.engine import build_solver, select_engine, solve
from poisson_ellipse_tpu.solver.pcg import solve as solve_xla

ENGINES = {
    "fused": solve_fused,
    "resident": solve_resident,
    "streamed": solve_streamed,
    "xl": solve_xl,
}

# committed reference code oracles (see tests/test_pcg.py for provenance)
UNWEIGHTED_ORACLE = {(10, 10): 17, (20, 20): 31, (40, 40): 61}
WEIGHTED_ORACLE = {(10, 10): 15, (20, 20): 26, (40, 40): 50}


@pytest.mark.parametrize("engine", sorted(ENGINES))
@pytest.mark.parametrize("M,N", sorted(UNWEIGHTED_ORACLE))
def test_parity_unweighted(engine, M, N):
    problem = Problem(M=M, N=N, norm="unweighted")
    ref = solve_xla(problem, jnp.float32)
    got = ENGINES[engine](problem, jnp.float32)
    assert int(got.iters) == int(ref.iters) == UNWEIGHTED_ORACLE[(M, N)]
    assert bool(got.converged)
    assert not bool(got.breakdown)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), atol=5e-6
    )


@pytest.mark.parametrize("engine", sorted(ENGINES))
@pytest.mark.parametrize("M,N", [(20, 20), (40, 40)])
def test_parity_weighted(engine, M, N):
    problem = Problem(M=M, N=N, norm="weighted")
    ref = solve_xla(problem, jnp.float32)
    got = ENGINES[engine](problem, jnp.float32)
    assert int(got.iters) == int(ref.iters) == WEIGHTED_ORACLE[(M, N)]
    assert bool(got.converged)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), atol=5e-6
    )


@pytest.mark.parametrize("engine", sorted(ENGINES))
def test_parity_non_aligned_multi_tile(engine):
    """A shape that is neither row-tile- nor lane-aligned, spanning
    multiple tiles in every engine's tiling."""
    problem = Problem(M=44, N=132, norm="weighted")
    ref = solve_xla(problem, jnp.float32)
    got = ENGINES[engine](problem, jnp.float32)
    assert int(got.iters) == int(ref.iters)
    assert bool(got.converged)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), atol=5e-6
    )


@pytest.mark.parametrize("engine", sorted(ENGINES))
def test_max_iter_cap(engine):
    problem = Problem(M=40, N=40, max_iter=5)
    got = ENGINES[engine](problem, jnp.float32)
    assert int(got.iters) == 5
    assert not bool(got.converged)
    assert not bool(got.breakdown)


def test_bf16_path_converges_on_every_engine():
    """bf16 is an advertised dtype on every Pallas engine and the XLA
    path: with a bf16-reachable threshold each converges to an L2 error
    in the same decade as the converged f32/f64 result at this grid
    (~3.7e-3), and iteration counts stay within bf16-rounding slack of
    the XLA path (exact invariance is an f32/f64 contract only)."""
    problem = Problem(M=40, N=40, delta=1e-4)
    ref = solve_xla(problem, jnp.bfloat16)
    assert bool(ref.converged)
    from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic

    for name, fn in {**ENGINES, "xla": solve_xla}.items():
        got = fn(problem, jnp.bfloat16)
        assert bool(got.converged), name
        assert abs(int(got.iters) - int(ref.iters)) <= 3, name
        assert float(l2_error_vs_analytic(problem, got.w)) < 1e-2, name


@pytest.mark.parametrize("dtype", ["f64"])
def test_engines_reject_f64(dtype):
    problem = Problem(M=10, N=10)
    for fn in ENGINES.values():
        with pytest.raises(ValueError):
            fn(problem, jnp.float64)


# ---------------------------------------------------------------- capacity


def test_fits_resident_small_and_large():
    assert fits_resident(Problem(M=40, N=40))
    assert fits_resident(Problem(M=800, N=1200))
    assert not fits_resident(Problem(M=1600, N=2400))


def test_fits_streamed_gate():
    assert fits_streamed(Problem(M=1600, N=2400))
    assert fits_streamed(Problem(M=2400, N=3200))
    # north-star 4096²: state alone (~201 MB) exceeds VMEM
    assert not fits_streamed(Problem(M=4096, N=4096))


def test_streamed_build_rejects_oversize():
    with pytest.raises(ValueError, match="VMEM"):
        build_streamed_solver(Problem(M=4096, N=4096))


def test_streamed_forced_all_streaming_parity(monkeypatch):
    """Force resident={all False} so the double-buffered DMA pipeline
    (slot reads, ap store lag, tail drain) actually executes — every grid
    small enough for tests otherwise resolves to an all-resident plan."""
    import poisson_ellipse_tpu.ops.streamed_pcg as sp

    problem = Problem(M=200, N=132, norm="weighted")
    ref = solve_xla(problem, jnp.float32)
    # pin tm=64: the budget arithmetic below assumes one tile size (the
    # auto policy would otherwise re-spend the forced budget on tm=128)
    base_plan = StreamPlan(problem, jnp.float32, tm=64)
    state_bytes = (3 * base_plan.g1p + 16) * base_plan.g2p * 4
    monkeypatch.setattr(
        sp, "_VMEM_USABLE", state_bytes + base_plan.min_stream_bytes
    )
    plan = sp.StreamPlan(problem, jnp.float32, tm=64)
    assert plan.fits and not any(plan.resident.values())
    assert plan.n_tiles >= 3  # exercises even/odd slots + tail drain
    solver, args = sp.build_streamed_solver(problem, jnp.float32, tm=64)
    got = solver(*args)
    assert int(got.iters) == int(ref.iters)
    assert bool(got.converged)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), atol=5e-6
    )


def test_stream_plan_residency_prefers_ap(monkeypatch):
    """The greedy residency upgrade takes ap (written+read = 2 HBM
    passes/iter) before dinv (1 pass — the z-state regime reads it only
    in pass C); with budget for exactly one full array the plan must
    keep ap resident and stream dinv, and the solve in that mixed
    regime (z-state + resident ap) must still match the XLA path."""
    import poisson_ellipse_tpu.ops.streamed_pcg as sp

    problem = Problem(M=200, N=132, norm="weighted")
    ref = solve_xla(problem, jnp.float32)
    base = StreamPlan(problem, jnp.float32, tm=64)
    state_bytes = (3 * base.g1p + 16) * base.g2p * 4
    ap_upgrade = (
        base.full_rows["ap"] - base.tile_rows["ap"]
    ) * base.g2p * 4
    monkeypatch.setattr(
        sp, "_VMEM_USABLE",
        state_bytes + base.min_stream_bytes + ap_upgrade,
    )
    plan = sp.StreamPlan(problem, jnp.float32, tm=64)
    assert plan.resident["ap"] and not plan.resident["dinv"]
    solver, args = sp.build_streamed_solver(problem, jnp.float32, tm=64)
    got = solver(*args)
    assert int(got.iters) == int(ref.iters)
    assert bool(got.converged)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), atol=5e-6
    )


def test_select_engine_scales_with_device_vmem(monkeypatch):
    """The capacity gates key off device_kind VMEM capacity
    (``utils.device``): a small-VMEM part must drop 800x1200 out of the
    resident engine, a large-VMEM part must pull 1600x2400 into it —
    both with the injected kinds, while unknown kinds reproduce the
    measured bench-part behaviour exactly."""
    from poisson_ellipse_tpu.solver.engine import select_engine
    from poisson_ellipse_tpu.utils import device as devmod

    class _Fake:
        def __init__(self, kind):
            self.device_kind = kind

    monkeypatch.setitem(devmod._VMEM_CAPACITY, "TPU tiny-test", 32 * 1024 * 1024)
    monkeypatch.setitem(devmod._VMEM_CAPACITY, "TPU big-test", 512 * 1024 * 1024)
    small, big = _Fake("TPU tiny-test"), _Fake("TPU big-test")
    # measured part: 800x1200 resident, 1600x2400 streamed
    assert select_engine(Problem(M=800, N=1200)) == "resident"
    assert select_engine(Problem(M=1600, N=2400)) == "streamed"
    # quarter-VMEM part: 800x1200 no longer fits resident
    assert not fits_resident(Problem(M=800, N=1200), device=small)
    assert select_engine(Problem(M=800, N=1200), device=small) == "streamed"
    # 4x-VMEM part: 1600x2400 becomes resident, 4096^2 becomes streamable
    assert select_engine(Problem(M=1600, N=2400), device=big) == "resident"
    assert select_engine(Problem(M=4096, N=4096), device=big) == "streamed"
    # a grid beyond the small part's streamed gate takes the xl kernel
    assert select_engine(Problem(M=2400, N=3200), device=small) == "xl"
    # unknown kind falls back to the measured budgets
    assert select_engine(
        Problem(M=800, N=1200), device=_Fake("mystery")
    ) == "resident"


def test_vmem_capacity_table_and_scaling():
    """utils.device directly: known kinds hit the table, unknown kinds
    (including the CPU devices the suite runs on) fall back to the
    measured 128 MiB part — so a budget scales by exactly 1.0 there —
    and scaled_vmem_budget is proportional for table entries."""
    from poisson_ellipse_tpu.utils.device import (
        scaled_vmem_budget,
        vmem_capacity_bytes,
    )

    class _Fake:
        def __init__(self, kind):
            self.device_kind = kind

    mib = 1024 * 1024
    assert vmem_capacity_bytes(_Fake("TPU v5 lite")) == 128 * mib
    assert vmem_capacity_bytes(_Fake("not-a-tpu")) == 128 * mib
    assert scaled_vmem_budget(114 * mib, _Fake("unknown")) == 114 * mib
    # the suite's default (CPU) device takes the fallback too
    assert scaled_vmem_budget(125 * mib) == 125 * mib


def test_cli_engine_xl(capsys):
    """--engine xl through the CLI surface (interpret mode on CPU)."""
    rc = cli_main(["40", "40", "--mode", "single", "--engine", "xl", "--json"])
    assert rc == 0
    import json as _json

    rec = _json.loads(capsys.readouterr().out.strip())
    assert rec["engine"] == "xl" and rec["iters"] == 50
    assert rec["converged"] is True


def test_xl_plan_tile_policy_and_forced_tiles():
    """The default tile minimises padded rows (96 at 4097 node rows ->
    g1p 4128, vs 4224 with 128); forced small tiles exercise the
    multi-tile ring/store-lag pipeline on a grid tests can afford."""
    plan = XLPlan(Problem(M=4096, N=4096), jnp.float32)
    assert plan.tm == 96 and plan.g1p == 4128
    assert XLPlan(Problem(M=4096, N=4096), jnp.float32).passes_per_iter() \
        == pytest.approx(12.0 + 8.0 / 96)
    with pytest.raises(ValueError, match="multiple of 8"):
        XLPlan(Problem(M=100, N=100), jnp.float32, tm=100)
    problem = Problem(M=40, N=40)
    ref = solve_xla(problem, jnp.float32)
    for tm in (8, 16):
        solver, args = build_xl_solver(problem, tm=tm)
        got = solver(*args)
        assert int(got.iters) == int(ref.iters) == 50, tm
        np.testing.assert_allclose(
            np.asarray(got.w), np.asarray(ref.w), atol=5e-6
        )


def test_stream_plan_shapes():
    plan = StreamPlan(Problem(M=1600, N=2400), jnp.float32)
    assert plan.g1p % plan.tm == 0
    assert plan.g2p % 128 == 0
    assert plan.n_tiles == plan.g1p // plan.tm
    assert plan.fits
    # residency must be a subset of what the budget allows; the always-
    # resident state is excluded from the dict
    assert set(plan.resident) == {"dinv", "ap", "a", "b"}
    assert plan.streamed_passes_per_iter() >= 0.0


def test_stream_plan_auto_tile_policy():
    # all-resident at both tile sizes -> auto takes the bigger tile
    p_mid = Problem(M=1600, N=2400)
    assert StreamPlan(p_mid, jnp.float32).tm == 128
    assert StreamPlan(p_mid, jnp.float32, tm=64).tm == 64
    # auto never trades HBM traffic for tile size: whatever it picks
    # streams no more passes per iteration than tm=64 would
    for M, N in ((1600, 2400), (2000, 2800), (2400, 3200)):
        plan = StreamPlan(Problem(M=M, N=N), jnp.float32)
        plan64 = StreamPlan(Problem(M=M, N=N), jnp.float32, tm=64)
        assert (
            plan.streamed_passes_per_iter()
            <= plan64.streamed_passes_per_iter()
        )
    with pytest.raises(ValueError, match="multiple of 8"):
        StreamPlan(p_mid, jnp.float32, tm=100)


# ---------------------------------------------------------------- policy


def test_select_engine_policy():
    assert select_engine(Problem(M=40, N=40)) == "resident"
    assert select_engine(Problem(M=800, N=1200)) == "resident"
    assert select_engine(Problem(M=1600, N=2400)) == "streamed"
    # past the streamed gate the state-streaming xl kernel beats the
    # XLA loop (measured 4.28 s vs 5.16 s at the 4096² north-star)
    assert select_engine(Problem(M=4096, N=4096)) == "xl"
    # f64 always takes the XLA path (Pallas engines are f32/bf16)
    assert select_engine(Problem(M=40, N=40), jnp.float64) == "xla"


def test_build_solver_resolves_auto_and_rejects_unknown():
    solver, args, engine = build_solver(Problem(M=20, N=20), "auto")
    assert engine == "resident"
    result = solver(*args)
    assert int(result.iters) == WEIGHTED_ORACLE[(20, 20)]
    with pytest.raises(ValueError, match="unknown engine"):
        build_solver(Problem(M=20, N=20), "cuda")


def test_engine_solve_entry_point():
    result = solve(Problem(M=20, N=20), engine="auto")
    assert int(result.iters) == WEIGHTED_ORACLE[(20, 20)]
    assert bool(result.converged)


# ---------------------------------------------------------------- shared ops


def test_interior_normalized_shared_dinv():
    """The streamed engine's dinv must be the exact fused-engine value
    (they share interior_normalized — this pins the contract)."""
    problem = Problem(M=20, N=20)
    from poisson_ellipse_tpu.ops import assembly

    a64, b64, _ = assembly.assemble_numpy(problem)
    an, as_, bw, be, d, dinv = interior_normalized(problem, a64, b64)
    assert dinv.dtype == np.float64
    inner = d[1:-1, 1:-1]
    np.testing.assert_allclose(
        dinv[1:-1, 1:-1][inner != 0], 1.0 / inner[inner != 0], rtol=0
    )
    # ring is exactly zero
    assert (dinv[0] == 0).all() and (dinv[-1] == 0).all()


# ---------------------------------------------------------------- protocol


def test_chain_solver_value_exact():
    """The chained differential timing protocol must not change values."""
    problem = Problem(M=20, N=20)
    solver, args, _ = build_solver(problem, "xla", jnp.float32)
    ref = solver(*args)
    chained = _chain_solver(solver, args, 3)
    got = chained(*args)
    assert int(got.iters) == int(ref.iters)
    np.testing.assert_array_equal(np.asarray(got.w), np.asarray(ref.w))


def test_run_once_engine_auto_reports_engine():
    report = run_once(
        Problem(M=20, N=20), mode="single", engine="auto", repeat=1, batch=2
    )
    assert report.engine == "resident"
    assert report.iters == WEIGHTED_ORACLE[(20, 20)]
    assert report.converged


# ---------------------------------------------------------------- roofline


def test_roofline_passes_model():
    from poisson_ellipse_tpu.harness.roofline import passes_per_iter, roofline

    p_small = Problem(M=40, N=40)
    assert passes_per_iter(p_small, "resident") == 0.0
    assert passes_per_iter(p_small, "xla") == 13.0
    assert passes_per_iter(p_small, "fused") == 17.0
    # streamed: a fully resident plan streams nothing
    assert passes_per_iter(p_small, "streamed") == 0.0
    big = Problem(M=2400, N=3200)
    plan = StreamPlan(big, jnp.float32)
    assert passes_per_iter(big, "streamed") == pytest.approx(
        plan.streamed_passes_per_iter()
    )
    assert plan.streamed_passes_per_iter() > 0
    with pytest.raises(ValueError, match="traffic model"):
        passes_per_iter(p_small, "cuda")

    # 13 passes * 41*41*4 bytes * 10 iters in 1 ms => 0.874 GB/s
    r = roofline(p_small, "xla", iters=10, t_solver=1e-3, dtype=jnp.float32)
    assert r["hbm_gbps"] == pytest.approx(0.874, rel=1e-2)
    # CPU test runs have no known HBM peak
    assert r["hbm_peak_frac"] is None


def test_run_once_carries_roofline():
    report = run_once(Problem(M=20, N=20), mode="single", engine="xla")
    assert report.passes_per_iter == 13.0
    assert report.hbm_gbps > 0
    rec = report.json_dict()
    assert {"passes_per_iter", "hbm_gbps", "hbm_peak_frac"} <= set(rec)
    assert "Roofline:" in report.summary()


def test_fits_resident_measured_edge():
    # chip-measured envelope (resident_pcg._ARRAYS_RESIDENT comment):
    # 1100x1650 compiles and solves on the bench part; 1200x1800 does not
    assert fits_resident(Problem(M=1100, N=1650))
    assert not fits_resident(Problem(M=1200, N=1800))
    assert select_engine(Problem(M=1100, N=1650)) == "resident"
    assert select_engine(Problem(M=1200, N=1800)) == "streamed"


def test_auto_falls_back_when_selected_engine_fails(monkeypatch):
    """Capacity gates are bench-chip budgets; on a part where the chosen
    Pallas engine cannot build, auto must degrade down the chain instead
    of surfacing the compile error."""
    import poisson_ellipse_tpu.ops.resident_pcg as rp

    def boom(*a, **k):
        raise RuntimeError("Mosaic: RESOURCE_EXHAUSTED (simulated)")

    monkeypatch.setattr(rp, "build_resident_solver", boom)
    problem = Problem(M=40, N=40)
    # degradation must be loud: the failed engine is named in a warning
    with pytest.warns(RuntimeWarning, match="'resident' failed"):
        solver, args, engine = build_solver(problem, "auto")
    assert engine in ("streamed", "xla")  # resident was the selection
    result = solver(*args)
    assert int(result.iters) == WEIGHTED_ORACLE[(40, 40)]
    # explicit requests still fail loudly
    with pytest.raises(RuntimeError, match="simulated"):
        build_solver(problem, "resident")


@pytest.mark.parametrize("cfg", [
    dict(a1=-1.5, b1=1.5, a2=-1.0, b2=1.0, f_val=2.5),
    dict(a1=-1.2, b1=1.1, a2=-0.7, b2=0.65, delta=1e-5, norm="unweighted"),
    dict(eps=1e-3, f_val=0.5),
])
def test_engines_agree_on_general_problems(cfg):
    """The reference hardcodes its box/rhs/eps as compile-time constants;
    the framework generalises them. Every engine must track the XLA path
    on arbitrary configurations — the engines' geometry/masking logic
    cannot be specialised to the reference's exact domain."""
    problem = Problem(M=52, N=44, **cfg)
    ref = solve_xla(problem, jnp.float32)
    assert bool(ref.converged)
    for name, fn in ENGINES.items():
        got = fn(problem, jnp.float32)
        assert int(got.iters) == int(ref.iters), name
        assert bool(got.converged), name
        np.testing.assert_allclose(
            np.asarray(got.w), np.asarray(ref.w), atol=5e-6, err_msg=name
        )


@pytest.mark.parametrize("seed", range(4))
def test_engine_parity_on_random_configurations(seed):
    """Oracle invariance over RANDOM configurations (SURVEY §4): every
    engine must converge in the same iteration count as the XLA path on
    randomly drawn boxes/ε/f/grids, seed-parametrised — the fixed-config
    generality cases above can miss mask geometries the random draw
    hits (cut cells at different face fractions, extreme ε)."""
    rng = np.random.default_rng(1000 + seed)
    problem = Problem(
        M=int(rng.integers(24, 56)),
        N=int(rng.integers(24, 56)),
        a1=-float(rng.uniform(1.05, 1.6)),
        b1=float(rng.uniform(1.05, 1.6)),
        a2=-float(rng.uniform(0.55, 1.0)),
        b2=float(rng.uniform(0.55, 1.0)),
        eps=float(10.0 ** rng.uniform(-6, -1)),
        f_val=float(rng.uniform(0.2, 3.0)),
    )
    ref = solve_xla(problem, jnp.float32)
    assert bool(ref.converged)
    for name, fn in ENGINES.items():
        got = fn(problem, jnp.float32)
        assert int(got.iters) == int(ref.iters), (name, problem)
        assert bool(got.converged), (name, problem)
        np.testing.assert_allclose(
            np.asarray(got.w), np.asarray(ref.w), atol=5e-6, err_msg=name
        )


def test_sharded_agrees_on_general_problem():
    from poisson_ellipse_tpu.parallel.pcg_sharded import solve_sharded
    from poisson_ellipse_tpu.solver.pcg import solve as solve_single

    problem = Problem(M=36, N=28, a1=-1.4, b1=1.3, a2=-0.8, b2=0.75,
                      f_val=1.7)
    single = solve_single(problem, jnp.float64)
    sharded = solve_sharded(problem, dtype=jnp.float64)
    assert int(sharded.iters) == int(single.iters)
    np.testing.assert_allclose(
        np.asarray(sharded.w), np.asarray(single.w), rtol=1e-12, atol=1e-16
    )
