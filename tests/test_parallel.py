"""Distributed-path tests on a virtual CPU mesh (1/2/4/8 devices) —
SURVEY §4's prescription: the identical small-grid test matrix the reference
runs at 1/2/4 mpirun ranks, with simulated devices instead of ranks.

Asserts iteration-count parity with the single-chip solver and elementwise
agreement of the solution — the reference's strongest cross-implementation
oracle (same grid → same iteration count in every implementation)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.parallel.compat import shard_map
from poisson_ellipse_tpu.parallel.halo import halo_extend
from poisson_ellipse_tpu.parallel.mesh import (
    choose_process_grid,
    make_mesh,
    padded_dims,
)
from poisson_ellipse_tpu.parallel.pcg_sharded import solve_sharded
from poisson_ellipse_tpu.solver.pcg import solve
from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic


def mesh_of(n):
    return make_mesh(jax.devices()[:n])


def test_choose_process_grid_matches_reference():
    # stage2-mpi/poisson_mpi_decomp.cpp:60-64 semantics
    assert choose_process_grid(1) == (1, 1)
    assert choose_process_grid(2) == (1, 2)
    assert choose_process_grid(4) == (2, 2)
    assert choose_process_grid(6) == (2, 3)
    assert choose_process_grid(8) == (2, 4)
    assert choose_process_grid(7) == (1, 7)
    assert choose_process_grid(16) == (4, 4)


def test_padded_dims():
    mesh = mesh_of(8)  # 2 x 4
    assert padded_dims((41, 41), mesh) == (42, 44)
    assert padded_dims((42, 44), mesh) == (42, 44)


def test_halo_extend_reconstructs_neighbors():
    """On a 2x4 mesh, halo_extend must deliver exactly the neighbouring
    block rows/cols of a globally known array, zeros at the physical edge."""
    mesh = mesh_of(8)
    g = jnp.arange(8 * 12, dtype=jnp.float64).reshape(8, 12)

    def f(blk):
        return halo_extend(blk, 2, 4)

    ext = jax.jit(
        shard_map(
            f,
            mesh=mesh,
            in_specs=(jax.sharding.PartitionSpec("x", "y"),),
            out_specs=jax.sharding.PartitionSpec("x", "y"),
        )
    )(g)
    # device block (0,0) owns rows 0..3, cols 0..2 → extended 6x5 lives at
    # ext rows 0..5, cols 0..4 of the (12, 20) output
    ext = np.asarray(ext)
    g_np = np.asarray(g)
    blk00 = ext[:6, :5]
    np.testing.assert_array_equal(blk00[1:-1, 1:-1], g_np[0:4, 0:3])
    np.testing.assert_array_equal(blk00[0, :], 0)  # no north neighbour
    np.testing.assert_array_equal(blk00[:, 0], 0)  # no west neighbour
    np.testing.assert_array_equal(blk00[1:-1, -1], g_np[0:4, 3])  # east halo
    np.testing.assert_array_equal(blk00[-1, 1:-1], g_np[4, 0:3])  # south halo
    # an interior device block (1,1): rows 4..7, cols 3..5
    blk11 = ext[6:12, 5:10]
    np.testing.assert_array_equal(blk11[1:-1, 1:-1], g_np[4:8, 3:6])
    np.testing.assert_array_equal(blk11[0, 1:-1], g_np[3, 3:6])  # north halo
    np.testing.assert_array_equal(blk11[1:-1, 0], g_np[4:8, 2])  # west halo
    # corners propagate (second round operates on x-extended block)
    assert blk11[0, 0] == g_np[3, 2]


@pytest.mark.parametrize("n_devices", [1, 2, 4, 8])
def test_sharded_matches_single_chip(n_devices):
    problem = Problem(M=40, N=40)
    ref = solve(problem, jnp.float64)
    got = solve_sharded(problem, mesh_of(n_devices), jnp.float64)
    assert int(got.iters) == int(ref.iters) == 50
    assert bool(got.converged)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=0, atol=1e-10
    )


@pytest.mark.parametrize("n_devices", [1, 2, 4, 8])
def test_sharded_pallas_stencil_matches_single_chip(n_devices):
    """Mesh decomposition × per-shard Pallas stencil kernel in one program
    — the stage4 composition (kernel per rank in the hot loop, halo
    exchange + scalar collectives around it, ``gradient_solver_mpi``,
    ``poisson_mpi_cuda2.cu:846-939``). Interpret mode on CPU devices."""
    problem = Problem(M=40, N=40)
    ref = solve(problem, jnp.float32)
    got = solve_sharded(
        problem, mesh_of(n_devices), jnp.float32, stencil_impl="pallas"
    )
    assert int(got.iters) == int(ref.iters) == 50
    assert bool(got.converged)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=0, atol=5e-6
    )


def test_sharded_pallas_uneven_blocks():
    """Non-aligned per-shard blocks (padding on both axes) through the
    per-shard kernel path."""
    problem = Problem(M=13, N=17)
    ref = solve(problem, jnp.float32)
    got = solve_sharded(
        problem, mesh_of(8), jnp.float32, stencil_impl="pallas"
    )
    assert got.w.shape == (14, 18)
    assert int(got.iters) == int(ref.iters)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=0, atol=5e-6
    )


@pytest.mark.parametrize("n_devices", [1, 2, 4, 8])
def test_fused_sharded_matches_single_chip(n_devices):
    """The fused two-kernel iteration composed with the mesh: K1
    (p-update + stencil + denom partial) and K2 (updates + partials) per
    shard, a stacked (z, p) halo exchange and two psums per iteration —
    2 kernels + 2 psum + 4 ppermute vs the ~8 XLA fusions of the plain
    sharded loop (``parallel.fused_sharded``). Interpret mode on CPU."""
    from poisson_ellipse_tpu.parallel.fused_sharded import solve_fused_sharded

    problem = Problem(M=40, N=40)
    ref = solve(problem, jnp.float32)
    got = solve_fused_sharded(problem, mesh_of(n_devices))
    assert int(got.iters) == int(ref.iters) == 50
    assert bool(got.converged)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=0, atol=5e-6
    )


def test_fused_sharded_headline_oracle():
    """546 iterations at 400×600 (the published stage1-4 oracle) on the
    full 8-device mesh — the fused-sharded path at a bench-relevant
    size, through the ``stencil_impl`` dispatch."""
    problem = Problem(M=400, N=600)
    got = solve_sharded(
        problem, mesh_of(8), jnp.float32, stencil_impl="fused"
    )
    assert bool(got.converged)
    assert int(got.iters) == 546


def test_fused_sharded_uneven_blocks():
    """Both axes need tile-aligned shard padding (13×17 nodes over 2×4)."""
    problem = Problem(M=13, N=17)
    ref = solve(problem, jnp.float32)
    got = solve_sharded(
        problem, mesh_of(8), jnp.float32, stencil_impl="fused"
    )
    assert got.w.shape == (14, 18)
    assert int(got.iters) == int(ref.iters)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=0, atol=5e-6
    )


def test_fused_sharded_rejects_f64():
    from poisson_ellipse_tpu.parallel.fused_sharded import solve_fused_sharded

    with pytest.raises(ValueError, match="f32/bf16"):
        solve_fused_sharded(Problem(M=10, N=10), mesh_of(2), jnp.float64)


def test_fused_sharded_rejects_device_assembly():
    with pytest.raises(ValueError, match="host"):
        solve_sharded(
            Problem(M=10, N=10), mesh_of(2), jnp.float32,
            assembly_mode="device", stencil_impl="fused",
        )


def test_halo_extend_stacked_matches_per_array():
    """The stacked (k, bm, bn) exchange must deliver exactly what k
    separate halo_extend calls deliver, in 4 ppermutes instead of 4k."""
    from jax.sharding import PartitionSpec as P

    from poisson_ellipse_tpu.parallel.halo import halo_extend_stacked
    from poisson_ellipse_tpu.parallel.mesh import AXIS_X, AXIS_Y

    mesh = mesh_of(8)
    px, py = mesh.shape[AXIS_X], mesh.shape[AXIS_Y]
    u = jnp.arange(8 * 12, dtype=jnp.float64).reshape(8, 12)
    v = -2.0 * u + 1.0
    spec = P(AXIS_X, AXIS_Y)

    singles = jax.jit(
        shard_map(
            lambda a, b: (halo_extend(a, px, py), halo_extend(b, px, py)),
            mesh=mesh,
            in_specs=(spec, spec),
            out_specs=(spec, spec),
        )
    )(u, v)
    stacked = jax.jit(
        shard_map(
            lambda a, b: halo_extend_stacked(jnp.stack([a, b]), px, py),
            mesh=mesh,
            in_specs=(spec, spec),
            out_specs=P(None, AXIS_X, AXIS_Y),
        )
    )(u, v)
    np.testing.assert_array_equal(np.asarray(stacked[0]), np.asarray(singles[0]))
    np.testing.assert_array_equal(np.asarray(stacked[1]), np.asarray(singles[1]))


def test_sharded_rejects_unknown_stencil_impl():
    with pytest.raises(ValueError, match="stencil_impl"):
        solve_sharded(
            Problem(M=10, N=10), mesh_of(1), jnp.float32, stencil_impl="cuda"
        )


@pytest.mark.parametrize("assembly_mode", ["host", "device"])
def test_assembly_modes_agree(assembly_mode):
    problem = Problem(M=24, N=20)
    ref = solve(problem, jnp.float64)
    got = solve_sharded(
        problem, mesh_of(4), jnp.float64, assembly_mode=assembly_mode
    )
    assert int(got.iters) == int(ref.iters)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=0, atol=1e-10
    )


def test_sharded_uneven_grid_padding():
    # node grid 14x18 over a 2x4 mesh: both axes need padding
    problem = Problem(M=13, N=17)
    ref = solve(problem, jnp.float64)
    got = solve_sharded(problem, mesh_of(8), jnp.float64)
    assert got.w.shape == (14, 18)
    assert int(got.iters) == int(ref.iters)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(ref.w), rtol=0, atol=1e-10
    )


def test_sharded_l2_error_matches():
    problem = Problem(M=40, N=40)
    got = solve_sharded(problem, mesh_of(8), jnp.float64)
    err = float(l2_error_vs_analytic(problem, got.w))
    assert err == pytest.approx(3.677e-3, rel=1e-3)


def test_halo_extend_wider_width():
    """width>1 slab exchange (the CP-analog primitive, SURVEY §5)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from poisson_ellipse_tpu.parallel.mesh import AXIS_X, AXIS_Y, make_mesh

    mesh = make_mesh(jax.devices()[:4])
    px, py = mesh.shape[AXIS_X], mesh.shape[AXIS_Y]
    bm, bn = 6, 6
    global_u = jnp.arange(px * bm * py * bn, dtype=jnp.float64).reshape(
        px * bm, py * bn
    )
    width = 2
    spec = P(AXIS_X, AXIS_Y)
    ext = jax.jit(
        shard_map(
            lambda u: halo_extend(u, px, py, width=width),
            mesh=mesh,
            in_specs=spec,
            out_specs=spec,
        )
    )(global_u)
    ext = np.asarray(ext)
    # device (0,0)'s extended block sits at rows 0..bm+2w of the stacked
    # output; its interior must match, its high-x halo must equal the
    # first `width` rows of device (1,0)'s block, and the boundary side
    # must be zero
    blk = ext[: bm + 2 * width, : bn + 2 * width]
    np.testing.assert_array_equal(
        blk[width:-width, width:-width], np.asarray(global_u[:bm, :bn])
    )
    np.testing.assert_array_equal(
        blk[-width:, width:-width], np.asarray(global_u[bm : bm + width, :bn])
    )
    np.testing.assert_array_equal(blk[:width, :], np.zeros((width, bn + 2 * width)))


def test_halo_extend_rejects_bad_width():
    with pytest.raises(ValueError, match="width"):
        halo_extend(jnp.zeros((4, 4)), 1, 1, width=0)
    with pytest.raises(ValueError, match="width"):
        halo_extend(jnp.zeros((4, 4)), 1, 1, width=5)


def test_multihost_helpers_single_process():
    """Single-process semantics of the MPI-lifecycle analogs."""
    from poisson_ellipse_tpu.parallel.multihost import (
        global_mesh,
        process_info,
    )

    pid, nproc = process_info()
    assert pid == 0 and nproc == 1
    mesh = global_mesh()
    assert mesh.devices.size == len(jax.devices())


def test_initialize_multihost_idempotent_guard():
    """The is_initialized() guard path (single-process: not initialised)."""
    from poisson_ellipse_tpu.parallel.multihost import shutdown_multihost

    # not initialised -> shutdown is a no-op rather than an error
    shutdown_multihost()
