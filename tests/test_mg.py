"""Multigrid/Chebyshev preconditioning tests (``poisson_ellipse_tpu.mg``).

Four layers of assertion, mirroring the subsystem's own claims:

- **transfer algebra**: restriction is exactly the scaled adjoint of
  prolongation (R = Pᵀ/4 as dense matrices, boundary handling included)
  — the identity the V-cycle's symmetry proof stands on;
- **operator structure**: every coarsened operator is SPD and the
  ε-jump survives coarsening (harmonic face averaging); the Chebyshev
  smoother's error propagator contracts (ρ < 1) on the model problem;
- **preconditioner contract**: the V-cycle applier is a LINEAR,
  symmetric, positive-definite operator (⟨Mx, y⟩ = ⟨x, My⟩ on random
  vectors in f64) — fixed smoother counts keep standard PCG valid;
- **engine behaviour**: mg-pcg/cheb-pcg hit l2 parity with diag-PCG at
  ≥3× fewer iterations, record history bit-identically, walk the guard
  ladder mg → cheb → diag, and the sharded form matches single-chip
  with the classical scalar-collective cadence (2 psum/iter — the
  stacked convergence word still exactly 1 — and a jaxpr-pinned halo
  ppermute budget).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.mg import cheby, coarsen, vcycle
from poisson_ellipse_tpu.mg.engine import (
    build_precond_solver,
    default_config,
    make_precond,
)
from poisson_ellipse_tpu.mg.transfer import (
    prolong_bilinear,
    restrict_full_weighting,
)
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.stencil import apply_a, apply_dinv, diag_d
from poisson_ellipse_tpu.solver.pcg import solve as diag_solve
from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic


def dense_of(op, shape):
    """Dense matrix of a linear grid operator by applying it to the
    standard basis (small grids only)."""
    n = shape[0] * shape[1]
    cols = []
    for j in range(n):
        e = np.zeros(shape)
        e.flat[j] = 1.0
        cols.append(np.asarray(op(jnp.asarray(e))).ravel())
    return np.stack(cols, axis=1)


def interior_indices(M, N):
    return [i * (N + 1) + j for i in range(1, M) for j in range(1, N)]


# -- transfer algebra --------------------------------------------------------


def test_restriction_is_scaled_adjoint_of_prolongation():
    """R = Pᵀ/4 EXACTLY, as matrices on the full node space — including
    the Dirichlet-ring masking on both sides (the identity the V-cycle
    symmetry argument needs, checked rather than assumed)."""
    fine_shape, coarse_shape = (9, 9), (5, 5)
    P = dense_of(lambda u: prolong_bilinear(u, fine_shape), coarse_shape)
    R = dense_of(restrict_full_weighting, fine_shape)
    np.testing.assert_array_equal(R, P.T / 4.0)


def test_prolongation_reproduces_bilinear_values():
    uc = jnp.asarray(np.arange(25, dtype=np.float64).reshape(5, 5))
    uf = np.asarray(prolong_bilinear(uc, (9, 9)))
    ucm = np.array(uc)
    ucm[0, :] = ucm[-1, :] = ucm[:, 0] = ucm[:, -1] = 0.0  # masked ring
    assert uf[2, 2] == ucm[1, 1]
    assert uf[3, 2] == 0.5 * (ucm[1, 1] + ucm[2, 1])
    assert uf[2, 3] == 0.5 * (ucm[1, 1] + ucm[1, 2])
    assert uf[3, 3] == 0.25 * (
        ucm[1, 1] + ucm[2, 1] + ucm[1, 2] + ucm[2, 2]
    )
    # ring stays Dirichlet-zero
    assert not uf[0, :].any() and not uf[-1, :].any()


# -- operator structure ------------------------------------------------------


def test_coarse_operators_spd_across_hierarchy():
    """Every level of the coarsened hierarchy is symmetric positive
    definite on its interior — the tentpole's stated validation."""
    problem = Problem(M=16, N=16)
    hier = coarsen.build_hierarchy(problem, jnp.float64)
    assert len(hier) == coarsen.num_levels(16, 16) == 3
    for lv in hier[1:]:
        h1 = jnp.asarray(lv.h1, jnp.float64)
        h2 = jnp.asarray(lv.h2, jnp.float64)
        A = dense_of(
            lambda u, lv=lv, h1=h1, h2=h2: apply_a(u, lv.a, lv.b, h1, h2),
            lv.node_shape,
        )
        idx = interior_indices(lv.M, lv.N)
        Ai = A[np.ix_(idx, idx)]
        np.testing.assert_allclose(Ai, Ai.T, atol=1e-12)
        assert np.linalg.eigvalsh(Ai).min() > 0


def test_coarsening_preserves_eps_jump():
    """Harmonic-in-normal averaging keeps both coefficient regimes: the
    inside-D faces stay O(1), the fictitious-exterior faces stay
    O(1/ε), and no coarse face exceeds the fine range (a coarse value
    above max(fine) would mean the average manufactured conductance)."""
    problem = Problem(M=32, N=32)
    a, b, _ = assembly.assemble_numpy(problem)
    ac, bc = coarsen.coarsen_coefficients(a, b, np)
    one_over_eps = 1.0 / problem.eps_value
    for fine, coarse in ((a, ac), (b, bc)):
        cv = coarse[1:, 1:]
        assert cv.min() > 0
        assert cv.max() <= fine.max() * (1 + 1e-12)
        # both regimes survive: some faces still ~1, some still ~1/eps
        assert (np.abs(cv - 1.0) < 0.5).any()
        assert (cv > 0.5 * one_over_eps).any()


def test_chebyshev_smoother_contracts_on_model_problem():
    """ρ(I − B·A) < 1 on the interior of the 10×10 model problem for
    the V-cycle's smoothing band — a divergent smoother would poison
    every level, so the radius is measured, not assumed."""
    problem = Problem(M=10, N=10)
    a, b, _rhs = assembly.assemble(problem, jnp.float64)
    h1 = jnp.asarray(problem.h1, jnp.float64)
    h2 = jnp.asarray(problem.h2, jnp.float64)
    d = diag_d(a, b, h1, h2)
    lo, hi = cheby.smoother_interval(cheby.GERSHGORIN_LMAX)

    def error_propagator(e):
        # E e = e − B (A e): one pre-smoother application from zero
        ae = apply_a(e, a, b, h1, h2)
        be = cheby.chebyshev_apply(
            lambda x: apply_a(x, a, b, h1, h2),
            lambda x: apply_dinv(x, d),
            ae, lo, hi, vcycle.DEFAULT_NU,
        )
        return e - be

    E = dense_of(error_propagator, problem.node_shape)
    idx = interior_indices(problem.M, problem.N)
    rho = np.abs(np.linalg.eigvals(E[np.ix_(idx, idx)])).max()
    assert rho < 1.0, f"smoother spectral radius {rho} >= 1"


# -- the preconditioner contract ---------------------------------------------


@pytest.fixture(scope="module")
def mg_precond_f64():
    problem = Problem(M=16, N=16)
    factory, cfg = make_precond(problem, jnp.float64, "mg")
    a, b, _ = assembly.assemble(problem, jnp.float64)
    return problem, factory(a, b), cfg


def test_vcycle_preconditioner_symmetric(mg_precond_f64):
    """⟨M⁻¹x, y⟩ = ⟨x, M⁻¹y⟩ on random vectors (f64): the fixed-degree
    symmetric V-cycle is a symmetric operator, so standard PCG remains
    valid — the assertion the tentpole demands instead of silently
    requiring flexible CG."""
    problem, precond, _cfg = mg_precond_f64
    rng = np.random.default_rng(7)
    for _ in range(3):
        x = jnp.asarray(rng.standard_normal(problem.node_shape))
        y = jnp.asarray(rng.standard_normal(problem.node_shape))
        mx = precond(x)
        my = precond(y)
        lhs = float(jnp.sum(mx * y))
        rhs = float(jnp.sum(x * my))
        assert abs(lhs - rhs) <= 1e-10 * max(abs(lhs), abs(rhs))


def test_vcycle_preconditioner_positive_definite_and_linear(mg_precond_f64):
    problem, precond, _cfg = mg_precond_f64
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.standard_normal(problem.node_shape))
    y = jnp.asarray(rng.standard_normal(problem.node_shape))
    # positive on the interior subspace (ring components map to 0)
    from poisson_ellipse_tpu.mg.transfer import zero_ring

    xi = zero_ring(x)
    assert float(jnp.sum(precond(xi) * xi)) > 0
    # linearity: M⁻¹(2x + 3y) = 2 M⁻¹x + 3 M⁻¹y (fixed polynomials only)
    lin = precond(2.0 * x + 3.0 * y)
    np.testing.assert_allclose(
        np.asarray(lin),
        2.0 * np.asarray(precond(x)) + 3.0 * np.asarray(precond(y)),
        rtol=1e-12, atol=1e-12,
    )


def test_make_precond_rejects_unknown_kind():
    with pytest.raises(ValueError, match="unknown preconditioner kind"):
        default_config(Problem(M=8, N=8), "ilu")


def test_num_levels_static_rules():
    assert coarsen.num_levels(10, 10) == 2  # 5x5 is odd: stops
    assert coarsen.num_levels(9, 9) == 1  # odd at the root
    assert coarsen.num_levels(1024, 1024) == coarsen.MAX_LEVELS
    assert coarsen.num_levels(16, 8) == 2  # 4-cell floor on the short side


# -- engine behaviour --------------------------------------------------------


ORACLE_40 = 50  # weighted-norm diag-PCG oracle at 40x40 (committed ref)


@pytest.mark.parametrize("engine,max_iters", [("mg-pcg", 15), ("cheb-pcg", 20)])
def test_precond_engines_l2_parity_and_iteration_cut(engine, max_iters):
    problem = Problem(M=40, N=40)
    diag = diag_solve(problem, jnp.float32)
    assert int(diag.iters) == ORACLE_40
    l2_diag = float(l2_error_vs_analytic(problem, diag.w))
    solver, args, resolved = build_precond_solver(problem, engine, jnp.float32)
    res = solver(*args)
    assert resolved == engine
    assert bool(res.converged)
    assert int(res.iters) <= max_iters  # >= 3.3x fewer than the oracle 50
    l2 = float(l2_error_vs_analytic(problem, res.w))
    # the bench parity criterion, one-sided: only WORSE than diag by
    # >10% fails — at equal δ the V-cycle lands at-or-below diag's
    # algebraic error (measured 2× below at 1600×2400)
    assert l2 <= l2_diag * 1.10


def test_mg_iteration_reduction_grows_with_grid():
    """The point of the subsystem: at 128² the diagonal preconditioner
    pays ~3× the 40×40 count while mg-pcg stays O(10) — ≥3× reduction
    with margin (the bench asserts the same on the published grids)."""
    problem = Problem(M=128, N=128)
    diag = diag_solve(problem, jnp.float32)
    solver, args, _ = build_precond_solver(problem, "mg-pcg", jnp.float32)
    res = solver(*args)
    assert bool(res.converged) and bool(diag.converged)
    assert int(diag.iters) >= 3 * int(res.iters), (
        f"mg {int(res.iters)} vs diag {int(diag.iters)}"
    )


def test_engine_registry_and_history_contract():
    """mg-pcg through the real ``solver.engine`` entry point, history
    on and off: same iterates bit-for-bit (the obs.convergence
    contract), and the trace's κ(M⁻¹A) sits an order of magnitude under
    diag-PCG's — the spectral claim, measured."""
    from poisson_ellipse_tpu.obs import spectrum as obs_spectrum
    from poisson_ellipse_tpu.solver.engine import (
        ENGINES,
        HISTORY_ENGINES,
        solve as engine_solve,
    )

    assert "mg-pcg" in ENGINES and "cheb-pcg" in ENGINES
    assert "mg-pcg" in HISTORY_ENGINES and "cheb-pcg" in HISTORY_ENGINES
    problem = Problem(M=40, N=40)
    plain = engine_solve(problem, "mg-pcg", jnp.float32)
    res, trace = engine_solve(problem, "mg-pcg", jnp.float32, history=True)
    assert int(plain.iters) == int(res.iters)
    np.testing.assert_array_equal(np.asarray(plain.w), np.asarray(res.w))
    rep = obs_spectrum.spectrum_report(
        trace, delta=problem.delta, actual_iters=int(res.iters)
    )
    _diag, diag_trace = engine_solve(problem, "xla", jnp.float32, history=True)
    diag_rep = obs_spectrum.spectrum_report(diag_trace, delta=problem.delta)
    assert rep["available"] and diag_rep["available"]
    assert rep["kappa"] * 10 < diag_rep["kappa"]


def test_eigenvalue_bounds_helper():
    """The shared Lanczos-bounds helper: widened outward from the Ritz
    extremes (covering slack), None on an unusable trace."""
    from poisson_ellipse_tpu.obs import spectrum as obs_spectrum
    from poisson_ellipse_tpu.solver.engine import solve as engine_solve

    problem = Problem(M=20, N=20)
    _res, trace = engine_solve(problem, "xla", jnp.float32, history=True)
    ritz = obs_spectrum.ritz_values(trace)
    lo, hi = obs_spectrum.eigenvalue_bounds(trace)
    assert lo < ritz[0] and hi > ritz[-1]
    empty = {"zr": [], "diff": [], "alpha": [], "beta": []}
    assert obs_spectrum.eigenvalue_bounds(empty) is None


def test_build_solver_rejects_lanes_for_precond_engines():
    from poisson_ellipse_tpu.solver.engine import build_solver

    with pytest.raises(ValueError, match="lanes"):
        build_solver(Problem(M=10, N=10), "mg-pcg", jnp.float32, lanes=2)


# -- guard ladder ------------------------------------------------------------


def test_guard_ladder_walks_mg_cheb_diag():
    from poisson_ellipse_tpu.resilience.guard import _make_adapter

    problem = Problem(M=10, N=10)
    mg = _make_adapter(problem, "mg-pcg", jnp.float32, None, None)
    assert mg.engine == "mg-pcg"
    assert mg.escalate() is None  # the precond ladder skips the f64 rung
    cheb, _ = mg.fallback()
    assert cheb.engine == "cheb-pcg"
    diag, _ = cheb.fallback()
    assert diag.engine == "xla"
    assert diag.precond_kind is None


def test_guarded_mg_recovers_injected_nan_to_parity():
    from poisson_ellipse_tpu.resilience import (
        FaultPlan,
        guarded_solve,
        inject_nan,
    )

    problem = Problem(M=20, N=20)
    clean = guarded_solve(problem, "mg-pcg", jnp.float32, chunk=4)
    assert bool(clean.result.converged) and not clean.recoveries
    hurt = guarded_solve(
        problem, "mg-pcg", jnp.float32, chunk=4,
        faults=FaultPlan(inject_nan(4, "r")),
    )
    assert bool(hurt.result.converged)
    assert [e.kind for e in hurt.recoveries] == ["residual-restart"]
    assert hurt.engine == "mg-pcg"
    assert abs(int(hurt.result.iters) - int(clean.result.iters)) <= 2


# -- sharded form ------------------------------------------------------------


def mesh_of(n):
    from poisson_ellipse_tpu.parallel.mesh import make_mesh

    return make_mesh(jax.devices()[:n])


@pytest.mark.parametrize("n_devices", [2, 8])
@pytest.mark.parametrize("kind", ["mg", "cheb"])
def test_sharded_matches_single_chip(n_devices, kind):
    from poisson_ellipse_tpu.parallel.mg_sharded import solve_mg_sharded

    problem = Problem(M=40, N=40)
    engine = {"mg": "mg-pcg", "cheb": "cheb-pcg"}[kind]
    solver, args, _ = build_precond_solver(problem, engine, jnp.float32)
    single = solver(*args)
    got = solve_mg_sharded(problem, mesh_of(n_devices), jnp.float32, kind=kind)
    assert bool(got.converged)
    assert int(got.iters) == int(single.iters)
    np.testing.assert_allclose(
        np.asarray(got.w), np.asarray(single.w), rtol=0, atol=5e-6
    )


def test_sharded_collective_discipline_jaxpr_pinned():
    """THE mesh regression pin, as a declared contract: the convergence
    word stays EXACTLY one stacked psum per iteration (total psum = 2
    with the denom — the classical cadence, preconditioner adds ZERO),
    and the V-cycle's halo traffic is exactly the static ppermute budget
    (``halos_per_precond``), read back from the jaxpr by
    ``analysis.contracts`` with the expectations derived from
    ENGINE_CAPS — cross-checked here against the hand expression."""
    from poisson_ellipse_tpu.analysis.contracts import assert_contract
    from poisson_ellipse_tpu.parallel.mg_sharded import halos_per_precond

    problem = Problem(M=40, N=40)
    for kind, engine in (("mg", "mg-pcg"), ("cheb", "cheb-pcg")):
        r = assert_contract(
            "collective-cadence", engine, problem=problem,
            dtype=jnp.float32, mesh_shape=(1, 2),
        )
        cfg = default_config(problem, kind)
        halos = 1 + halos_per_precond(
            cfg.levels,
            cfg.nu,
            cfg.coarse_degree if kind == "mg" else cfg.cheb_degree,
        )
        assert r.expected == {"psum": 2, "ppermute": 4 * halos}, (
            f"{kind}: contract derivation drifted from the hand budget"
        )


def test_static_cost_engine_report_covers_mg():
    from poisson_ellipse_tpu.obs import static_cost

    rep = static_cost.engine_report(
        Problem(M=40, N=40), "mg-pcg", jnp.float32, mode="sharded",
        mesh_shape=(1, 2), with_xla_cost=False,
    )
    assert rep["psum_per_iter"] == 2
    assert rep["ppermute_per_iter"] > 0
    assert rep["modeled_passes_per_iter"] > 13.0


# -- CLI surface -------------------------------------------------------------


def test_cli_runs_mg_engine(capsys):
    from poisson_ellipse_tpu.harness.__main__ import main as harness_main

    rc = harness_main(["20", "20", "--engine", "mg-pcg", "--json"])
    assert rc == 0
    import json

    record = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert record["engine"] == "mg-pcg"
    assert record["converged"] is True


def test_cli_diagnose_reports_precond_kappa_next_to_diag(capsys):
    from poisson_ellipse_tpu.harness.__main__ import main as harness_main

    rc = harness_main([
        "diagnose", "cheb-pcg", "--grid", "20x20", "--no-profile", "--json",
    ])
    assert rc == 0
    import json

    record = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert record["engine"] == "cheb-pcg"
    assert record["bit_identical"] is True
    assert record["spectrum"]["eigenvalue_bounds"] is not None
    diag = record["diag_spectrum"]
    assert diag["available"] and diag["kappa"] > record["spectrum"]["kappa"]
