"""Assembly (L1) tests: golden comparison against an independent numpy port
of the reference's fic_reg (stage0/Withoutopenmp1.cpp:42-61), plus
block-local assembly consistency (the fictitious_regions_setup_local
contract, poisson_mpi_cuda2.cu:146-192)."""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly


def reference_assembly_numpy(problem: Problem):
    """Literal scalar-loop port of fic_reg for golden comparison."""
    M, N, h1, h2 = problem.M, problem.N, problem.h1, problem.h2
    eps = problem.eps_value
    a = np.zeros((M + 1, N + 1))
    b = np.zeros((M + 1, N + 1))
    rhs = np.zeros((M + 1, N + 1))

    def seg_v(x0, ys, ye):
        if abs(x0) >= 1.0:
            return 0.0
        ym = math.sqrt(max(0.0, (1.0 - x0 * x0) / 4.0))
        return max(0.0, min(ye, ym) - max(ys, -ym))

    def seg_h(y0, xs, xe):
        if abs(2.0 * y0) >= 1.0:
            return 0.0
        xm = math.sqrt(max(0.0, 1.0 - 4.0 * y0 * y0))
        return max(0.0, min(xe, xm) - max(xs, -xm))

    for i in range(1, M + 1):
        for j in range(1, N + 1):
            x = problem.a1 + i * h1
            y = problem.a2 + j * h2
            la = seg_v(x - 0.5 * h1, y - 0.5 * h2, y + 0.5 * h2)
            lb = seg_h(y - 0.5 * h2, x - 0.5 * h1, x + 0.5 * h1)
            a[i, j] = (
                1.0
                if abs(la - h2) < 1e-9
                else (1.0 / eps if la < 1e-9 else la / h2 + (1.0 - la / h2) / eps)
            )
            b[i, j] = (
                1.0
                if abs(lb - h1) < 1e-9
                else (1.0 / eps if lb < 1e-9 else lb / h1 + (1.0 - lb / h1) / eps)
            )
    for i in range(1, M):
        for j in range(1, N):
            x = problem.a1 + i * h1
            y = problem.a2 + j * h2
            rhs[i, j] = problem.f_val if x * x + 4 * y * y < 1 else 0.0
    return a, b, rhs


@pytest.mark.parametrize("M,N", [(10, 10), (20, 20), (13, 17)])
def test_assembly_matches_reference_port(M, N):
    problem = Problem(M=M, N=N)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    a_ref, b_ref, rhs_ref = reference_assembly_numpy(problem)
    np.testing.assert_allclose(np.asarray(a), a_ref, rtol=0, atol=1e-12)
    np.testing.assert_allclose(np.asarray(b), b_ref, rtol=0, atol=1e-12)
    np.testing.assert_array_equal(np.asarray(rhs), rhs_ref)


def test_coefficient_values_in_expected_set():
    problem = Problem(M=40, N=40)
    a, b, _ = assembly.assemble(problem, jnp.float64)
    inv_eps = 1.0 / problem.eps_value
    for arr in (np.asarray(a), np.asarray(b)):
        interior = arr[1:, 1:]
        assert interior.min() >= 1.0 - 1e-12
        assert interior.max() <= inv_eps + 1e-6
        # both regimes must actually occur on this grid
        assert (np.abs(interior - 1.0) < 1e-12).any()
        assert (np.abs(interior - inv_eps) < 1e-6 * inv_eps).any()


def test_boundary_rows_are_zero():
    problem = Problem(M=12, N=14)
    a, b, rhs = assembly.assemble(problem, jnp.float64)
    assert np.asarray(a[0]).max() == 0.0 and np.asarray(a[:, 0]).max() == 0.0
    assert np.asarray(b[0]).max() == 0.0 and np.asarray(b[:, 0]).max() == 0.0
    # rhs vanishes on the entire Dirichlet ring
    r = np.asarray(rhs)
    assert r[0].max() == 0 and r[-1].max() == 0
    assert r[:, 0].max() == 0 and r[:, -1].max() == 0


def test_block_local_assembly_matches_global_slices():
    """Assembling a halo-extended block from global indices must equal the
    corresponding slice of the global arrays — the stage2/4 local-assembly
    contract (no communication needed for coefficients)."""
    problem = Problem(M=16, N=12)
    a_g, b_g, rhs_g = assembly.assemble(problem, jnp.float64)
    # a block owning global rows 4..9, cols 6..11, extended by one halo ring
    gi = jnp.arange(4 - 1, 10 + 1)
    gj = jnp.arange(6 - 1, 12 + 1)
    a_blk, b_blk = assembly.coefficients_at(problem, gi, gj, jnp.float64)
    rhs_blk = assembly.rhs_at(problem, gi, gj, jnp.float64)
    np.testing.assert_array_equal(np.asarray(a_blk), np.asarray(a_g[3:11, 5:13]))
    np.testing.assert_array_equal(np.asarray(b_blk), np.asarray(b_g[3:11, 5:13]))
    np.testing.assert_array_equal(np.asarray(rhs_blk), np.asarray(rhs_g[3:11, 5:13]))


def test_f32_assembly_stays_positive_on_fine_grids():
    """Regression: f32 on-device geometry noise amplified by 1/eps used to
    produce negative (SPD-breaking) coefficients at fine grids; host f64
    assembly + cast must keep every face coefficient >= 1."""
    problem = Problem(M=1024, N=1024)
    a, b, _ = assembly.assemble(problem, jnp.float32)
    assert a.dtype == jnp.float32
    a_int = np.asarray(a)[1:, 1:]
    b_int = np.asarray(b)[1:, 1:]
    assert a_int.min() >= 1.0 - 1e-6
    assert b_int.min() >= 1.0 - 1e-6
    # f64 assembly of the same grid, cast afterwards, must agree exactly
    a64, b64, _ = assembly.assemble(problem, jnp.float64)
    np.testing.assert_array_equal(
        np.asarray(a), np.asarray(a64).astype(np.float32)
    )
    np.testing.assert_array_equal(
        np.asarray(b), np.asarray(b64).astype(np.float32)
    )


def test_on_device_assembly_matches_host_in_f64():
    problem = Problem(M=24, N=18)
    a_h, b_h, r_h = assembly.assemble(problem, jnp.float64)
    a_d, b_d, r_d = assembly.assemble_on_device(problem, jnp.float64)
    np.testing.assert_allclose(np.asarray(a_h), np.asarray(a_d), rtol=0, atol=1e-12)
    np.testing.assert_allclose(np.asarray(b_h), np.asarray(b_d), rtol=0, atol=1e-12)
    np.testing.assert_array_equal(np.asarray(r_h), np.asarray(r_d))


def test_block_assembly_out_of_range_is_zero():
    problem = Problem(M=8, N=8)
    gi = jnp.arange(6, 12)  # extends past M=8
    gj = jnp.arange(-2, 4)  # extends below 0
    a_blk, b_blk = assembly.coefficients_at(problem, gi, gj, jnp.float64)
    rhs_blk = assembly.rhs_at(problem, gi, gj, jnp.float64)
    a_np, b_np, r_np = map(np.asarray, (a_blk, b_blk, rhs_blk))
    assert a_np[np.asarray(gi) > 8, :].max(initial=0) == 0.0
    assert b_np[:, np.asarray(gj) < 1].max(initial=0) == 0.0
    assert r_np[np.asarray(gi) > 7, :].max(initial=0) == 0.0
