import time, jax, jax.numpy as jnp
from jax import lax
from poisson_ellipse_tpu.utils.timing import fence

def t_chain(step, x0, n, reps=3):
    f = jax.jit(lambda x: lax.fori_loop(0, n, lambda i, s: step(s, i), x))
    out = f(x0); fence(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter(); out = f(x0); fence(out)
        ts.append(time.perf_counter() - t0)
    return min(ts)

for (M, N) in [(801, 1201), (1601, 2401), (2401, 3201)]:
    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (M, N), jnp.float32)
    p = jax.random.normal(key, (M, N), jnp.float32)
    MB = M*N*4/1e6
    def saxpy(s, i):
        return s + (1e-6*(i.astype(jnp.float32)+1.0)) * p
    n1, n2 = 200, 2000
    t1, t2 = t_chain(saxpy, w, n1), t_chain(saxpy, w, n2)
    per = (t2-t1)/(n2-n1)
    print(f"{M}x{N} saxpy(3-pass): {per*1e6:.1f} us/iter -> {3*MB/per/1e3:.0f} GB/s")
