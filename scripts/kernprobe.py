import time, functools, jax, jax.numpy as jnp
from jax import lax
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops.fused_pcg import build_kernels, fused_operands, _pad
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.stencil import apply_a
from poisson_ellipse_tpu.utils.timing import fence

def t_chain(step, x0, n, reps=3):
    f = jax.jit(lambda x: lax.fori_loop(0, n, lambda i, s: step(s, i), x))
    out = f(x0); fence(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter(); out = f(x0); fence(out)
        ts.append(time.perf_counter() - t0)
    return min(ts)

def per_iter(step, x0, n1=100, n2=600):
    t1 = t_chain(step, x0, n1); t2 = t_chain(step, x0, n2)
    return (t2 - t1) / (n2 - n1)

for (M, N) in [(1600,2400),(2400,3200)]:
    prob = Problem(M=M, N=N)
    g1, g2 = prob.node_shape
    kern = build_kernels(prob, g1, g2, jnp.float32)
    an, as_, bw, be, d_p, dinv_p = fused_operands(prob, kern.g1p, kern.g2p, jnp.float32)
    a, b, rhs = assembly.assemble(prob, jnp.float32)
    r0 = _pad(rhs, kern.g1p, kern.g2p)
    z0 = r0 * dinv_p
    h1 = jnp.float32(prob.h1); h2 = jnp.float32(prob.h2)

    def k1_step(state, i):
        z, p = state
        beta = 1e-3 * (i.astype(jnp.float32) + 1)
        pn, ap, dn = kern.k1(beta, z, p, an, as_, bw, be, d_p)
        return (p, pn)   # keep data-dependence
    def k2_step(state, i):
        w, r = state
        alpha = jnp.float32(1e-3) * (i.astype(jnp.float32) + 1)
        w2, r2, z2, sums = kern.k2(jnp.float32(1.0), alpha, w, r, z0, z0, dinv_p)
        return (w2, r2)
    def xla_stencil_step(u, i):
        return apply_a(u, a, b, h1, h2) + 1e-9 * i.astype(jnp.float32)

    print(f"{M}x{N} (tile rows g1p={kern.g1p}, g2p={kern.g2p}):")
    print(f"  K1: {per_iter(k1_step, (z0, r0))*1e6:.1f} us")
    print(f"  K2: {per_iter(k2_step, (r0, z0))*1e6:.1f} us")
    print(f"  XLA stencil: {per_iter(xla_stencil_step, rhs)*1e6:.1f} us")
