import time, jax, jax.numpy as jnp
from jax import lax
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops.streamed_pcg import build_streamed_solver, StreamPlan
from poisson_ellipse_tpu.utils.timing import fence

def t_run(f, args, reps=4):
    out = f(*args); fence(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter(); out = f(*args); fence(out)
        ts.append(time.perf_counter() - t0)
    return min(ts), out

def per_solve(solver, args, n=4):
    def chained(k):
        def g(*ops):
            r0 = ops[-1]
            def one(i, acc):
                res = solver(*ops[:-1], r0 * (1.0 + 1e-12 * acc))
                return acc + res.diff
            return lax.fori_loop(0, k, one, jnp.float32(0.0))
        return jax.jit(g)
    t1, _ = t_run(chained(1), args)
    tn, _ = t_run(chained(n), args)
    return (tn - t1) / (n - 1)

for (M, N, oracle, xla_t) in [(1600,2400,1858,0.2833),(2400,3200,2449,1.1386)]:
    prob = Problem(M=M, N=N)
    plan = StreamPlan(prob, jnp.float32)
    solver, args = build_streamed_solver(prob, jnp.float32)
    _, out = t_run(solver, args, reps=1)
    it = int(out.iters)
    t = per_solve(solver, args)
    print(f"{M}x{N}: streamed {t:.4f}s ({t/oracle*1e6:.1f} us/it) iters={it} "
          f"(oracle {oracle}) conv={bool(out.converged)} resident={plan.resident} "
          f"| vs XLA {xla_t}s: {xla_t/t:.2f}x")
