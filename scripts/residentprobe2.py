import time, jax, jax.numpy as jnp
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops.resident_pcg import build_resident_solver
from poisson_ellipse_tpu.utils.timing import fence

def t_run(f, args, reps=5):
    out = f(*args); fence(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter(); out = f(*args); fence(out)
        ts.append(time.perf_counter() - t0)
    return min(ts)

for (M, N, oracle) in [(400,600,546),(800,1200,989),(1024,1024,921)]:
    ts = {}
    for ni, n in ((1, oracle//5), (2, oracle-10)):
        prob = Problem(M=M, N=N, max_iter=n)
        f, args = build_resident_solver(prob, jnp.float32)
        ts[ni] = t_run(f, args)
    per = (ts[2]-ts[1])/((oracle-10)-(oracle//5))
    print(f"{M}x{N}: {per*1e6:.2f} us/iter  (t1={ts[1]:.4f} t2={ts[2]:.4f})")
