import time, jax, jax.numpy as jnp
from jax import lax
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.fused_pcg import build_fused_solver
from poisson_ellipse_tpu.solver.pcg import pcg
from poisson_ellipse_tpu.utils.timing import fence

def t_run(f, args, reps=4):
    out = f(*args); fence(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter(); out = f(*args); fence(out)
        ts.append(time.perf_counter() - t0)
    return min(ts)

def per_solve(make_chained, args, n=5):
    t1 = t_run(make_chained(1), args)
    tn = t_run(make_chained(n), args)
    return (tn - t1) / (n - 1)

for (M, N, oracle) in [(1600,2400,1858),(2400,3200,2449)]:
    prob = Problem(M=M, N=N)
    a, b, rhs = assembly.assemble(prob, jnp.float32)
    def xchained(n):
        def g(a_, b_, rhs_):
            def one(i, acc):
                res = pcg(prob, a_, b_, rhs_ * (1.0 + 1e-12 * acc))
                return acc + res.diff
            return lax.fori_loop(0, n, one, jnp.float32(0.0))
        return jax.jit(g)
    xt = per_solve(xchained, (a, b, rhs))

    solver, fargs = build_fused_solver(prob, jnp.float32)
    def fchained(n):
        def g(*ops):
            r0 = ops[-1]
            def one(i, acc):
                res = solver(*ops[:-1], r0 * (1.0 + 1e-12 * acc))
                return acc + res.diff
            return lax.fori_loop(0, n, one, jnp.float32(0.0))
        return jax.jit(g)
    ft = per_solve(fchained, fargs)
    print(f"{M}x{N}: XLA {xt:.4f}s ({xt/oracle*1e6:.1f} us/it) | "
          f"fused {ft:.4f}s ({ft/oracle*1e6:.1f} us/it) | ratio {xt/ft:.2f}x")
