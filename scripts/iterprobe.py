import time, jax, jax.numpy as jnp
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.solver.pcg import pcg
from poisson_ellipse_tpu.utils.timing import fence

def t_solve(problem, a, b, rhs, n_iter, reps=3):
    p2 = Problem(M=problem.M, N=problem.N, max_iter=n_iter)
    f = jax.jit(lambda a, b, rhs: pcg(p2, a, b, rhs))
    out = f(a, b, rhs); fence(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = f(a, b, rhs); fence(out)
        ts.append(time.perf_counter() - t0)
    return min(ts)

for (M, N, oracle) in [(400,600,546),(800,1200,989),(1600,2400,1858),(2400,3200,2449)]:
    prob = Problem(M=M, N=N)
    a, b, rhs = assembly.assemble(prob, jnp.float32)
    n1, n2 = oracle // 5, oracle - 10
    t1 = t_solve(prob, a, b, rhs, n1)
    t2 = t_solve(prob, a, b, rhs, n2)
    per = (t2 - t1) / (n2 - n1)
    mb = (M+1)*(N+1)*4/1e6
    print(f"{M}x{N}: t({n1})={t1:.4f} t({n2})={t2:.4f} -> {per*1e6:.1f} us/iter "
          f"= {per*1e6*819e9*1e-12/mb:.1f} passes @819GB/s (array={mb:.2f}MB)")
