import time, jax, jax.numpy as jnp
from jax import lax
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops.resident_pcg import build_resident_solver
from poisson_ellipse_tpu.solver.pcg import pcg
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.utils.timing import fence

def t_run(f, args, reps=4):
    out = f(*args); fence(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter(); out = f(*args); fence(out)
        ts.append(time.perf_counter() - t0)
    return min(ts), out

def chain_solver(build, n):
    """Run the solve n times with a data dependence between runs."""
    solver, args = build()
    def chained(*a):
        r0 = a[-1]
        def one(i, acc):
            res = solver(*a[:-1], r0 * (1.0 + 1e-12 * acc))
            return acc + res.diff
        acc = lax.fori_loop(0, n, one, jnp.float32(0.0))
        return acc
    return jax.jit(chained), args

for (M, N, oracle) in [(400,600,546),(800,1200,989),(1024,1024,921)]:
    prob = Problem(M=M, N=N)
    # resident path
    f1, a1 = chain_solver(lambda: build_resident_solver(prob, jnp.float32), 1)
    f9, _ = chain_solver(lambda: build_resident_solver(prob, jnp.float32), 9)
    t1, _ = t_run(f1, a1); t9, _ = t_run(f9, a1)
    per_solve = (t9 - t1) / 8
    # XLA path same protocol
    a, b, rhs = assembly.assemble(prob, jnp.float32)
    def xchained(n):
        def g(a_, b_, rhs_):
            def one(i, acc):
                res = pcg(prob, a_, b_, rhs_ * (1.0 + 1e-12 * acc))
                return acc + res.diff
            return lax.fori_loop(0, n, one, jnp.float32(0.0))
        return jax.jit(g)
    tx1, _ = t_run(xchained(1), (a, b, rhs)); tx9, _ = t_run(xchained(9), (a, b, rhs))
    xper = (tx9 - tx1) / 8
    print(f"{M}x{N}: resident {per_solve:.4f}s/solve ({per_solve/oracle*1e6:.2f} us/iter) | "
          f"XLA {xper:.4f}s/solve ({xper/oracle*1e6:.2f} us/iter) | speedup {xper/per_solve:.1f}x")
