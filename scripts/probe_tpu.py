"""Probe: f32 single-chip solve on real TPU — iters, L2 error, timing."""
import sys
import time

import jax
import jax.numpy as jnp

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.solver.pcg import pcg
from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic

print("devices:", jax.devices(), file=sys.stderr)

for (M, N) in [(40, 40), (400, 600), (800, 1200)]:
    prob = Problem(M=M, N=N)
    t0 = time.perf_counter()
    a, b, rhs = assembly.assemble(prob, jnp.float32)
    t1 = time.perf_counter()
    run = jax.jit(lambda a, b, rhs, p=prob: pcg(p, a, b, rhs))
    res = run(a, b, rhs)
    res.w.block_until_ready()
    t2 = time.perf_counter()
    res = run(a, b, rhs)
    res.w.block_until_ready()
    t3 = time.perf_counter()
    err = float(l2_error_vs_analytic(prob, res.w))
    print(
        f"{M}x{N}: iters={int(res.iters)} diff={float(res.diff):.3e} "
        f"conv={bool(res.converged)} bd={bool(res.breakdown)} "
        f"assemble={t1-t0:.3f}s compile+run={t2-t1:.2f}s run={t3-t2:.4f}s "
        f"l2err={err:.4e}",
        file=sys.stderr,
    )
