import time, jax, jax.numpy as jnp
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops.resident_pcg import build_resident_solver, fits_resident
from poisson_ellipse_tpu.utils.timing import fence

def t_run(f, args, reps=5):
    out = f(*args); fence(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter(); out = f(*args); fence(out)
        ts.append(time.perf_counter() - t0)
    return min(ts), out

for (M, N, oracle) in [(40,40,60),(400,600,546),(800,1200,989),(1024,1024,None)]:
    prob = Problem(M=M, N=N)
    if not fits_resident(prob):
        print(f"{M}x{N}: does not fit resident budget"); continue
    f, args = build_resident_solver(prob, jnp.float32)
    t, out = t_run(f, args)
    it = int(out.iters)
    print(f"{M}x{N}: resident {t:.4f}s iters={it} (oracle {oracle}) "
          f"conv={bool(out.converged)} -> {t/it*1e6:.1f} us/iter(incl dispatch)")
