"""Probe timing semantics under axon: block_until_ready vs host transfer."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.solver.pcg import pcg

for (M, N) in [(400, 600), (800, 1200)]:
    prob = Problem(M=M, N=N)
    a, b, rhs = assembly.assemble(prob, jnp.float32)
    run = jax.jit(lambda a, b, rhs, p=prob: pcg(p, a, b, rhs))
    r = run(a, b, rhs)
    jax.block_until_ready(r)
    for rep in range(4):
        t0 = time.perf_counter()
        r = run(a, b, rhs)
        jax.block_until_ready(r)
        t1 = time.perf_counter()
        it = int(r.iters)  # forced host transfer
        t2 = time.perf_counter()
        w_host = np.asarray(r.w)
        t3 = time.perf_counter()
        print(
            f"{M}x{N} rep{rep}: block={t1-t0:.4f}s +scalar={t2-t1:.4f}s "
            f"+w_to_host={t3-t2:.4f}s iters={it}",
            file=sys.stderr,
        )
