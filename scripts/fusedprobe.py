"""Back-to-back XLA vs fused comparison on the reference grids."""
import time, jax, jax.numpy as jnp
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.fused_pcg import build_fused_solver
from poisson_ellipse_tpu.solver.pcg import pcg
from poisson_ellipse_tpu.utils.timing import fence

def t_run(f, args, reps=5):
    out = f(*args); fence(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter(); out = f(*args); fence(out)
        ts.append(time.perf_counter() - t0)
    return min(ts), out

for (M, N, oracle) in [(400,600,546),(800,1200,989),(1600,2400,1858),(2400,3200,2449)]:
    prob = Problem(M=M, N=N)
    a, b, rhs = assembly.assemble(prob, jnp.float32)
    fx = jax.jit(lambda a, b, rhs: pcg(prob, a, b, rhs))
    tx, ox = t_run(fx, (a, b, rhs))
    ff, fargs = build_fused_solver(prob, jnp.float32)
    tf, of = t_run(ff, fargs)
    print(f"{M}x{N}: XLA {tx:.4f}s ({int(ox.iters)}it) | fused {tf:.4f}s "
          f"({int(of.iters)}it, oracle {oracle}) | ratio {tx/tf:.2f}x")
