"""Multi-chip scaling benchmark: stage4-report-format tables as JSON.

BASELINE.json configs 3/4 on hardware (a real 2x2 / 4x4 pod slice):

  python bench_multichip.py --kind strong --grid 4096x4096 --meshes 1x1,2x2
  python bench_multichip.py --kind weak   --grid 2048x2048 --meshes 1x1,2x2,4x4

(the weak series visits 2048² -> 4096² @ 2x2 -> 8192² @ 4x4 — exactly the
configs-3/4 grids with a constant per-device block).

Without a pod this emits the same tables on a virtual CPU mesh with
scaled-down grids (default: 40x40 strong + 24x24-base weak over
1x1/2x2/2x4), proving the sharding/collective path and the table schema;
the reference does the equivalent 40x40 sanity runs at 1/2/4 mpirun ranks
(Этап2.pdf table 1). Prints one JSON object per table on stdout.
"""

from __future__ import annotations

import argparse
import json
import sys

from poisson_ellipse_tpu.obs import metrics as obs_metrics
from poisson_ellipse_tpu.obs.trace import event as trace_event, note


def _record_table_metrics(table: dict) -> None:
    """Fold one scaling/throughput table into the process metrics
    registry (counters/gauges/histograms), so ``--metrics`` exports the
    whole run as one OpenMetrics snapshot a scraper can diff."""
    obs_metrics.counter("multichip_tables").inc()
    t_hist = obs_metrics.histogram("multichip_t_solver_seconds")
    for row in table.get("rows", []):
        if row.get("t_solver_s") is not None:
            t_hist.observe(row["t_solver_s"])
        if row.get("solves_per_sec") is not None:
            mesh = row.get("mesh") or ["?", "?"]
            obs_metrics.gauge(
                f"multichip_solves_per_sec_{mesh[0]}x{mesh[1]}"
            ).set(row["solves_per_sec"])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bench_multichip.py")
    ap.add_argument("--kind", choices=("strong", "weak", "both"), default="both")
    ap.add_argument("--grid", help="MxN base grid (strong: the grid; weak: per-device base)")
    ap.add_argument("--meshes", help="comma list of PXxPY meshes, e.g. 1x1,2x2,4x4")
    ap.add_argument("--dtype", default="f32")
    ap.add_argument(
        "--engine", choices=("xla", "pallas", "fused", "pipelined"),
        default="xla",
        help="sharded engine: xla block stencil, per-shard pallas "
        "stencil kernel, the fused two-kernel iteration (f32/bf16), or "
        "the pipelined one-psum-per-iteration recurrence",
    )
    ap.add_argument("--repeat", type=int, default=1)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument(
        "--lanes-per-device", type=int, default=2,
        help="lane count per device for the batched throughput series "
        "(lanes sharded over the mesh; 0 disables the series)",
    )
    ap.add_argument(
        "--real",
        action="store_true",
        help="run on the real device mesh (a pod slice). Default: a "
        "virtual CPU mesh with scaled-down grids — the platform choice "
        "must happen before jax initialises, so it is a flag, not "
        "autodetected",
    )
    ap.add_argument(
        "--virtual-devices", type=int, default=8,
        help="virtual CPU device count for the default (non --real) mode",
    )
    ap.add_argument(
        "--metrics", metavar="FILE",
        help="export the run's table metrics (t_solver histogram, per-mesh "
        "solves/sec gauges) as an OpenMetrics snapshot (obs.export)",
    )
    args = ap.parse_args(argv)

    exporter = None
    if args.metrics:
        from poisson_ellipse_tpu.obs.export import MetricsExporter

        exporter = MetricsExporter(args.metrics)
        # fail FAST: a metrics-path typo must not read as a bench
        # failure after the whole scaling suite has run
        err = exporter.try_write()
        if err is not None:
            print(
                f"error: cannot write --metrics {args.metrics}: {err}",
                file=sys.stderr,
            )
            return 2

    if not args.real:
        # the virtual-device flag and platform pin must land before the
        # first backend initialisation (the shared helper handles the
        # ordering and keeps the accelerator backend untouched)
        from poisson_ellipse_tpu.parallel.mesh import virtual_cpu_devices

        n_virtual = len(virtual_cpu_devices(args.virtual_devices))
        if n_virtual != args.virtual_devices:
            # a pre-set XLA_FLAGS count wins (XLA parses the flags once)
            # — say so instead of claiming the requested number
            note(
                f"note: XLA_FLAGS already pins "
                f"{n_virtual} host devices; --virtual-devices "
                f"{args.virtual_devices} ignored",
            )
        note(
            f"note: virtual {n_virtual}-device CPU mesh "
            "(scaled-down grids unless --grid given); pass --real on a "
            "pod slice for the BASELINE configs",
        )
        default_strong, default_weak = (40, 40), (24, 24)
        default_meshes = [(1, 1), (2, 2), (2, 4)]
    else:
        default_strong, default_weak = (4096, 4096), (2048, 2048)
        default_meshes = [(1, 1), (2, 2)]

    from poisson_ellipse_tpu.harness.bench_multichip import (
        parse_meshes,
        scaling_table,
    )

    meshes = parse_meshes(args.meshes) if args.meshes else default_meshes
    if args.grid:
        grid = parse_meshes(args.grid)[0]  # same MxN spec syntax
        grids = {"strong": grid, "weak": grid}
    else:
        grids = {"strong": default_strong, "weak": default_weak}

    kinds = ("strong", "weak") if args.kind == "both" else (args.kind,)
    rc = 0
    # with the default engine, the strong series also runs the pipelined
    # one-psum-per-iteration recurrence, so the artifact carries the
    # 2-collectives-vs-1 comparison side by side (its iteration counts
    # are held to ±2 of xla's, not equality — a documented reordering)
    series = [(kind, args.engine) for kind in kinds]
    if args.engine == "xla" and "strong" in kinds:
        series.append(("strong", "pipelined"))
    xla_strong_iters = None
    for kind, engine in series:
        table = scaling_table(
            kind,
            grids[kind],
            meshes,
            dtype=args.dtype,
            stencil_impl=engine,
            repeat=args.repeat,
            batch=args.batch,
        )
        trace_event("multichip_table", **table)
        _record_table_metrics(table)
        print(json.dumps(table))
        iters_ok = table["iters_consistent"] is not False
        if kind == "strong" and engine == "xla":
            xla_strong_iters = table["rows"][0]["iters"]
        if engine == "pipelined" and kind == "strong":
            # the pipelined engine's contract is ±2 of xla, never exact
            # mesh-invariance: judge against the xla baseline when this
            # run produced one, else against the rows' own spread
            # (weak tables vary the grid, so per-row counts differ by
            # design and the generic converged check is the gate)
            iters = [r["iters"] for r in table["rows"]]
            anchor = (
                xla_strong_iters
                if xla_strong_iters is not None
                else min(iters)
            )
            iters_ok = all(abs(i - anchor) <= 2 for i in iters)
        if not iters_ok or not all(r["converged"] for r in table["rows"]):
            rc = 1
    if args.lanes_per_device > 0:
        from poisson_ellipse_tpu.harness.bench_multichip import (
            throughput_table,
        )

        # the serving scale-out series: the SAME grid, lanes sharded
        # over a growing mesh (parallel.batched_sharded) — aggregate
        # solves/sec should track the device count at exactly 1
        # psum/iteration (carried in collectives_per_iter)
        table = throughput_table(
            grids["strong"],
            meshes,
            lanes_per_device=args.lanes_per_device,
            dtype=args.dtype,
            pipelined=args.engine == "pipelined",
            repeat=args.repeat,
        )
        trace_event("multichip_table", **table)
        _record_table_metrics(table)
        print(json.dumps(table))
        coll = table["collectives_per_iter"]
        if not all(r["converged"] for r in table["rows"]) or (
            coll is not None and coll["psum"] != 1
        ):
            rc = 1
    if exporter is not None:
        # guarded final write: a filesystem dying mid-suite must warn,
        # not crash away the computed bench verdict
        err = exporter.try_write()
        if err is not None:
            note(f"warning: metrics snapshot failed: {err}")
        else:
            note(f"metrics snapshot: {exporter.path}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
