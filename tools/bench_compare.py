"""Threshold-based regression gate between two BENCH_r*.json rounds.

Every perf PR gets one number story: the round driver archives
`bench.py`'s JSON line as `BENCH_r{N}.json`, and this tool diffs any two
rounds metric by metric against named tolerances, exiting nonzero with
the offending metric spelled out — a perf regression becomes a failing
check, not an archaeology project:

    python tools/bench_compare.py BENCH_r04.json BENCH_r05.json
    python tools/bench_compare.py              # newest two rounds

Compared, where both rounds carry them (absence is skipped and noted —
older artifacts predate newer keys, which must never fail the gate):

- per-grid `t_solver_s` (grids / config2 / north_star / config4_1chip /
  pipelined / f64 rows): slower than `t-solver-pct` is a regression
- per-grid `iters`: growth beyond `iters-abs` (the oracle counts are
  exact, so the default allows only the pipelined-style ±2 reordering)
- per-grid `hbm_gbps` (grids rows, emitted since the diagnostics PR):
  achieved bandwidth dropping more than `gbps-pct`
- `spectrum` rows: `kappa` drifting more than `kappa-pct` in either
  direction (same grid + same operator ⇒ same κ; a drift means the
  trace or the estimator broke, not the hardware)
- `throughput` rows (keyed grid × lanes): `solves_per_sec` dropping
  more than `sps-pct`
- `precond` rows (keyed grid × engine): `iters` growing more than
  `precond-iters-pct` (operator-determined, like κ) or `t_solver_s`
  more than `precond-t-pct` slower
- the `abft` row: checks-on overhead creeping more than `abft-pp`
  percentage points between rounds, or the collective-cadence pin
  (`collectives_identical`) breaking — bench.py's own ≤2% gate bounds
  the absolute; this catches the trend
- `fleet` rows (keyed by replica count): aggregate `solves_per_sec`
  through the replicated fleet dropping more than `fleet-agg-pct`, the
  `non_decreasing` scaling pin breaking in the new round, and the
  kill→rejoin recovery p99 (`rejoin_latency_s`) growing more than
  `rejoin-p99-pct` (a drill that ran but lost the number is a broken
  emitter, gated unconditionally)
- the `grad` row: grad-solves/sec through the scheduler dropping more
  than `grad-pct`, and the per-grid adjoint/primal iteration ratio
  growing past the same band (the adjoint must stay "one extra solve
  with the same operator", not drift into its own convergence story)
- `fmg` rows (keyed by grid): F-cycle `t_solver_s` slower than
  `fmg-pct`, the constant-work-units-per-point pin breaking in the new
  round (the O(N) claim), or a headline row's wall-clock-vs-mg-pcg
  acceptance breaking
- `autotune` rows (keyed by grid): `tuned_t_s` slower than
  `autotune-pct` between rounds; hard pins in the new round — a tuned
  config that measures slower than the static default (`tuned_loses`)
  or a broken registry round-trip is a regression outright
- the `recycle` row (Krylov recycling on the correlated stream):
  `iter_cut` shrinking or warm `solves_per_s_warm` dropping more than
  `recycle-pct` between rounds; hard pins in the new round — a cut
  below 2× or an analytic-l2 gap beyond 10% (the equal-accuracy
  contract of the warm start) is a regression outright

- the `contracts` key (written by `--stamp`): a new round measured
  under a violated engine-contract state is a regression outright, and
  a report-hash change between rounds is noted — two perf numbers are
  only comparable under the same, clean contract state

`python tools/bench_compare.py --stamp BENCH_rN.json` runs the
engine-contract matrix (`poisson_ellipse_tpu.analysis`) and embeds
`{"contracts": {"hash", "clean"}}` into the round, so the next compare
can tell structural drift from noise.

Tolerances live in `pyproject.toml [tool.bench_compare]` (shared by the
CLI and the driver-dryrun smoke gate); built-in defaults apply when the
table or a key is absent. Exit codes: 0 = no regression, 1 = regression
(each named on stdout as `REGRESSION <metric> @ <where>: old -> new`),
2 = unusable input.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# fractional unless -abs; overridable via [tool.bench_compare]
DEFAULT_TOLERANCES = {
    "t-solver-pct": 0.25,
    "iters-abs": 2,
    "gbps-pct": 0.25,
    "kappa-pct": 0.20,
    "sps-pct": 0.25,
    # precond rows (mg-pcg/cheb-pcg): iteration counts are operator-
    # determined like kappa but sit at O(10) where ±2 would be 20%, so
    # they get a fractional band; time shares the wall-clock noise floor
    "precond-iters-pct": 0.15,
    "precond-t-pct": 0.25,
    # abft overhead drift between rounds, in absolute percentage POINTS
    # (the quantity is already a percent — a fractional band of a small
    # percent would be noise-tight)
    "abft-pp": 1.0,
    # geometry rows: the composite-domain solve shares the wall-clock
    # noise floor; quadrature assembly is host work (noisier on a
    # shared CI box), so its band is wider
    "geometry-t-pct": 0.25,
    "geometry-assembly-pct": 0.50,
    # fleet aggregate solves/sec per replica count: the replicated
    # serving layer's throughput shares the serving noise floor
    "fleet-agg-pct": 0.25,
    # fleet kill→rejoin recovery-time-to-capacity p99: dominated by the
    # rejoiner's replay + pre-warm compile, so it gets a wide band
    "rejoin-p99-pct": 0.50,
    # grad key: grad-solves/sec through the scheduler shares the
    # serving noise floor; the adjoint/primal iteration ratio gets the
    # same band (same-operator adjoints must keep tracking the primal)
    "grad-pct": 0.25,
    # bandwidth key ({f32, bf16-storage} × {pipelined, sstep} cells):
    # per-cell T_solver/GB/s share the wall-clock noise floor; the
    # ≤0.6× byte ratio and the l2 parity flag are hard pins per round
    "bandwidth-pct": 0.25,
    # fmg rows (full multigrid as the solver): per-grid T_solver shares
    # the wall-clock noise floor; the work-units-constant pin and the
    # headline wall-clock-vs-mg-pcg acceptance are hard pins per round
    "fmg-pct": 0.25,
    # autotune rows: tuned wall clock per shape shares the noise floor;
    # `tuned_loses` (a tuned config measuring slower than the static
    # default) and a broken registry round-trip are hard pins per round
    "autotune-pct": 0.25,
    # recycle key (Krylov recycling, solver.recycle): the correlated-
    # stream iteration cut and warm solves/sec between rounds; the ≥2×
    # cut and the ≤10% analytic-l2 gap are hard pins per round
    "recycle-pct": 0.25,
}

# scalar-row artifact keys carrying {grid, t_solver_s, iters}
ROW_KEYS = (
    "config2", "north_star", "config4_1chip", "pipelined", "f64",
)

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def load_tolerances(root: str = ROOT) -> dict:
    """DEFAULT_TOLERANCES overlaid with `[tool.bench_compare]`.

    Reuses the tpulint loader's tomllib-with-subset-fallback reader
    (this interpreter may predate tomllib); the fallback stores floats
    as strings, so values are coerced here.
    """
    tol = dict(DEFAULT_TOLERANCES)
    pyproject = os.path.join(root, "pyproject.toml")
    if not os.path.exists(pyproject):
        return tol
    try:
        from poisson_ellipse_tpu.lint import _read_pyproject

        table = _read_pyproject(pyproject).get("tool", {}).get(
            "bench_compare", {}
        )
    except Exception:  # loader unavailable: the defaults still gate
        return tol
    for key in tol:
        if key in table:
            try:
                tol[key] = float(table[key])
            except (TypeError, ValueError):
                raise SystemExit(
                    f"[tool.bench_compare] {key} = {table[key]!r} is not "
                    "a number"
                )
    return tol


def _round_key(path: str) -> tuple[int, float]:
    m = _ROUND_RE.search(os.path.basename(path))
    n = int(m.group(1)) if m else -1
    return n, os.path.getmtime(path)


def newest_rounds(root: str = ROOT, n: int = 2) -> list[str]:
    """The n highest-round BENCH_r*.json paths, oldest first."""
    rounds = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")),
                    key=_round_key)
    return rounds[-n:]


def load_round(path: str) -> dict:
    """One bench record (driver `{"parsed": ...}` or raw bench line)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise SystemExit(f"cannot read bench round {path}: {e}")
    rec = data.get("parsed", data) if isinstance(data, dict) else data
    if not isinstance(rec, dict):
        raise SystemExit(f"{path}: not a bench record")
    return rec


class Regression:
    """One named threshold violation."""

    def __init__(self, metric: str, where: str, old, new, limit: str):
        self.metric = metric
        self.where = where
        self.old = old
        self.new = new
        self.limit = limit

    def __str__(self) -> str:
        return (
            f"REGRESSION {self.metric} @ {self.where}: "
            f"{self.old:g} -> {self.new:g} ({self.limit})"
        )


def _by_grid(rows) -> dict:
    out = {}
    for row in rows or []:
        grid = row.get("grid")
        if grid:
            out[tuple(grid)] = row
    return out


def _grid_label(key) -> str:
    return "x".join(str(k) for k in key) if isinstance(key, tuple) else str(key)


def compare(old: dict, new: dict, tol: dict) -> tuple[list[Regression], list[str]]:
    """(regressions, notes) between two bench records.

    Only metrics present on BOTH sides are judged; one-sided metrics
    land in notes — a new bench key must not fail its first gated round,
    and an old artifact must not fail for predating one.
    """
    regressions: list[Regression] = []
    notes: list[str] = []

    def one_sided(metric, where, o, n) -> bool:
        """Note-and-skip when a metric exists on exactly one side of a
        matched row — the 'absence is skipped and NOTED' half of the
        contract (silent per-row absence would let a broken emitter
        read as a clean gate)."""
        if (o is None) != (n is None):
            notes.append(f"{metric} @ {where}: only in one round, skipped")
            return True
        return False

    def check_time(where, o, n):
        if one_sided("t_solver_s", where, o, n):
            return
        limit = tol["t-solver-pct"]
        if o and n is not None and n > o * (1.0 + limit):
            regressions.append(Regression(
                "t_solver_s", where, o, n,
                f"+{(n / o - 1):.0%} > {limit:.0%} slower",
            ))

    def check_iters(where, o, n):
        if one_sided("iters", where, o, n):
            return
        limit = tol["iters-abs"]
        if o is not None and n is not None and n > o + limit:
            regressions.append(Regression(
                "iters", where, o, n, f"+{n - o} > +{limit:g} iterations",
            ))

    def scalar_rows(rec, key):
        row = rec.get(key)
        return row if isinstance(row, dict) and row.get("grid") else None

    # the reference-grid table, matched per grid
    old_grids = _by_grid(old.get("grids"))
    new_grids = _by_grid(new.get("grids"))
    for key in sorted(old_grids.keys() & new_grids.keys()):
        o, n = old_grids[key], new_grids[key]
        where = _grid_label(key)
        check_time(where, o.get("t_solver_s"), n.get("t_solver_s"))
        check_iters(where, o.get("iters"), n.get("iters"))
        og, ng = o.get("hbm_gbps"), n.get("hbm_gbps")
        if not one_sided("hbm_gbps", where, og, ng) and og and ng is not None:
            limit = tol["gbps-pct"]
            if ng < og * (1.0 - limit):
                regressions.append(Regression(
                    "hbm_gbps", where, og, ng,
                    f"{(ng / og - 1):.0%} > {limit:.0%} bandwidth drop",
                ))
    for key in sorted(set(old_grids) ^ set(new_grids)):
        notes.append(f"grid {_grid_label(key)}: only in one round, skipped")

    # single-config rows
    for key in ROW_KEYS:
        o, n = scalar_rows(old, key), scalar_rows(new, key)
        if o is None or n is None:
            if (o is None) != (n is None):
                notes.append(f"{key}: only in one round, skipped")
            continue
        check_time(key, o.get("t_solver_s"), n.get("t_solver_s"))
        check_iters(key, o.get("iters"), n.get("iters"))

    # spectral diagnostics: κ is a property of grid + operator, not of
    # the hardware — drift EITHER way is a broken estimator/trace
    old_spec = _by_grid(old.get("spectrum"))
    new_spec = _by_grid(new.get("spectrum"))
    for key in sorted(old_spec.keys() & new_spec.keys()):
        ok, nk = old_spec[key].get("kappa"), new_spec[key].get("kappa")
        if one_sided("kappa", _grid_label(key), ok, nk):
            continue  # a null kappa IS the broken-estimator case: noted
        if ok and nk is not None:
            limit = tol["kappa-pct"]
            if abs(nk - ok) > ok * limit:
                regressions.append(Regression(
                    "kappa", _grid_label(key), ok, nk,
                    f"{(nk / ok - 1):+.0%} drift > ±{limit:.0%}",
                ))
    if bool(old.get("spectrum")) != bool(new.get("spectrum")):
        notes.append("spectrum: only in one round, skipped")

    # preconditioner rows, keyed grid × engine: iteration counts are
    # operator-determined (growth means the V-cycle/bounds broke, not
    # the hardware — fractional band at their O(10) scale), t_solver is
    # the wall-clock win the key exists to defend
    def by_grid_engine(rows):
        out = {}
        for row in rows or []:
            if row.get("grid") and row.get("engine"):
                out[(tuple(row["grid"]), row["engine"])] = row
        return out

    old_pre = by_grid_engine(old.get("precond"))
    new_pre = by_grid_engine(new.get("precond"))
    for key in sorted(old_pre.keys() & new_pre.keys()):
        o_row, n_row = old_pre[key], new_pre[key]
        where_pre = f"{_grid_label(key[0])} {key[1]}"
        o, n = o_row.get("iters"), n_row.get("iters")
        if not one_sided("precond iters", where_pre, o, n) and o and \
                n is not None:
            limit = tol["precond-iters-pct"]
            if n > o * (1.0 + limit):
                regressions.append(Regression(
                    "precond_iters", where_pre, o, n,
                    f"+{(n / o - 1):.0%} > {limit:.0%} more iterations",
                ))
        o, n = o_row.get("t_solver_s"), n_row.get("t_solver_s")
        if not one_sided("precond t_solver_s", where_pre, o, n) and o and \
                n is not None:
            limit = tol["precond-t-pct"]
            if n > o * (1.0 + limit):
                regressions.append(Regression(
                    "precond_t_solver_s", where_pre, o, n,
                    f"+{(n / o - 1):.0%} > {limit:.0%} slower",
                ))
    if bool(old.get("precond")) != bool(new.get("precond")):
        notes.append("precond: only in one round, skipped")

    # serving throughput, keyed grid × lanes
    def by_grid_lanes(rows):
        out = {}
        for row in rows or []:
            if row.get("grid") and row.get("lanes") is not None:
                out[(tuple(row["grid"]), row["lanes"])] = row
        return out

    old_thr = by_grid_lanes(old.get("throughput"))
    new_thr = by_grid_lanes(new.get("throughput"))
    for key in sorted(old_thr.keys() & new_thr.keys()):
        o = old_thr[key].get("solves_per_sec")
        n = new_thr[key].get("solves_per_sec")
        where_thr = f"{_grid_label(key[0])} lanes={key[1]}"
        if one_sided("solves_per_sec", where_thr, o, n):
            continue
        if o and n is not None:
            limit = tol["sps-pct"]
            if n < o * (1.0 - limit):
                regressions.append(Regression(
                    "solves_per_sec", where_thr, o, n,
                    f"{(n / o - 1):.0%} > {limit:.0%} throughput drop",
                ))
    if bool(old.get("throughput")) != bool(new.get("throughput")):
        notes.append("throughput: only in one round, skipped")

    # the ABFT overhead row: bench.py's own ≤2% gate bounds the absolute
    # per round; this catches creep between rounds (percentage POINTS —
    # the quantity is already a percent) and the cadence pin breaking
    def live_abft(rec):
        row = rec.get("abft")
        return row if isinstance(row, dict) and row.get("available") else None

    o_row, n_row = live_abft(old), live_abft(new)
    if o_row is not None and n_row is not None:
        o, n = o_row.get("overhead_pct"), n_row.get("overhead_pct")
        if not one_sided("abft overhead_pct", "abft", o, n) and \
                o is not None and n is not None:
            limit = tol["abft-pp"]
            if n > o + limit:
                regressions.append(Regression(
                    "abft_overhead_pct", "abft", o, n,
                    f"+{n - o:.2f}pp > +{limit:g}pp overhead creep",
                ))
        if n_row.get("collectives_identical") is False:
            regressions.append(Regression(
                "abft_collectives", "abft", 1, 0,
                "checks-on added collectives (the identical-cadence pin "
                "broke)",
            ))
    elif (o_row is None) != (n_row is None):
        notes.append("abft: only in one round, skipped")

    # the fleet key: aggregate solves/sec per replica count (the
    # replicated layer's throughput story) and the non-decreasing
    # scaling pin — a new round whose own pin broke is a regression
    # even if every per-width number stayed inside the band
    def fleet_rows(rec):
        fleet = rec.get("fleet")
        if not isinstance(fleet, dict):
            return {}
        return {
            row["replicas"]: row
            for row in fleet.get("rows") or []
            if row.get("replicas") is not None
        }

    old_fleet, new_fleet = fleet_rows(old), fleet_rows(new)
    for key in sorted(old_fleet.keys() & new_fleet.keys()):
        o = old_fleet[key].get("solves_per_sec")
        n = new_fleet[key].get("solves_per_sec")
        where_fleet = f"fleet replicas={key}"
        if one_sided("fleet solves_per_sec", where_fleet, o, n):
            continue
        if o and n is not None:
            limit = tol["fleet-agg-pct"]
            if n < o * (1.0 - limit):
                regressions.append(Regression(
                    "fleet_solves_per_sec", where_fleet, o, n,
                    f"{(n / o - 1):.0%} > {limit:.0%} aggregate drop",
                ))
    if old_fleet and new_fleet:
        if new.get("fleet", {}).get("non_decreasing") is False:
            regressions.append(Regression(
                "fleet_non_decreasing", "fleet", 1, 0,
                "aggregate solves/sec now DECREASES with replica count "
                "(the scaling pin broke)",
            ))
        # the kill→rejoin recovery number: p99 of kill→first-completed-
        # solve on the rejoined incarnation. One-sided absence is noted
        # (pre-rejoin artifacts must keep comparing), but a new round
        # that DID run the drill and lost the number (rejoins executed,
        # no latency observed) is a broken emitter, not noise.
        o_rj = old.get("fleet", {}).get("rejoin_latency_s")
        n_rj = new.get("fleet", {}).get("rejoin_latency_s")
        if not one_sided("fleet rejoin_latency_s", "fleet", o_rj, n_rj):
            if o_rj and n_rj is not None:
                limit = tol["rejoin-p99-pct"]
                if n_rj > o_rj * (1.0 + limit):
                    regressions.append(Regression(
                        "fleet_rejoin_latency_s", "fleet", o_rj, n_rj,
                        f"+{(n_rj / o_rj - 1):.0%} > {limit:.0%} slower "
                        "recovery to capacity",
                    ))
        if new.get("fleet", {}).get("rejoins", 0) >= 1 and n_rj is None:
            regressions.append(Regression(
                "fleet_rejoin_latency_s", "fleet", 1, 0,
                "rejoin drill ran but observed no recovery latency "
                "(the emitter broke)",
            ))
    elif bool(old_fleet) != bool(new_fleet):
        notes.append("fleet: only in one round, skipped")

    # the geometry key: the composite-domain solve time and the
    # quadrature assembly cost, plus the parity fields as hard pins —
    # face-fraction error growing past the acceptance bound is a
    # regression even within a round that still said valid
    o_geo, n_geo = old.get("geometry"), new.get("geometry")
    if isinstance(o_geo, dict) and isinstance(n_geo, dict):
        o_c = (o_geo.get("composite") or {}).get("t_solver_s")
        n_c = (n_geo.get("composite") or {}).get("t_solver_s")
        if not one_sided("geometry composite t_solver_s", "geometry",
                         o_c, n_c) and o_c and n_c is not None:
            limit = tol["geometry-t-pct"]
            if n_c > o_c * (1.0 + limit):
                regressions.append(Regression(
                    "geometry_t_solver_s", "composite", o_c, n_c,
                    f"+{(n_c / o_c - 1):.0%} > +{limit:.0%}",
                ))
        o_a, n_a = o_geo.get("assembly_quad_s"), n_geo.get("assembly_quad_s")
        if not one_sided("geometry assembly_quad_s", "geometry",
                         o_a, n_a) and o_a and n_a is not None:
            limit = tol["geometry-assembly-pct"]
            if n_a > o_a * (1.0 + limit):
                regressions.append(Regression(
                    "geometry_assembly_quad_s", "geometry", o_a, n_a,
                    f"+{(n_a / o_a - 1):.0%} > +{limit:.0%}",
                ))
        o_e, n_e = o_geo.get("max_frac_err"), n_geo.get("max_frac_err")
        if o_e is not None and n_e is not None and n_e > 1e-12:
            regressions.append(Regression(
                "geometry_max_frac_err", "geometry", o_e, n_e,
                "> 1e-12 acceptance bound",
            ))
    elif (o_geo is None) != (n_geo is None):
        notes.append("geometry: only in one round, skipped")

    # the grad key: grad-solves/sec through the scheduler (the served
    # differentiable-solving throughput) under `grad-pct`, plus the
    # per-grid adjoint/primal iteration ratio as a hard pin — the
    # adjoint reuses the same operator and preconditioner, so its
    # iteration count drifting far past the primal's means the adjoint
    # path stopped being "one extra solve"
    o_grad, n_grad = old.get("grad"), new.get("grad")
    if isinstance(o_grad, dict) and isinstance(n_grad, dict):
        o_g = o_grad.get("grad_solves_per_sec")
        n_g = n_grad.get("grad_solves_per_sec")
        if not one_sided("grad grad_solves_per_sec", "grad", o_g, n_g) \
                and o_g and n_g is not None:
            limit = tol["grad-pct"]
            if n_g < o_g * (1.0 - limit):
                regressions.append(Regression(
                    "grad_solves_per_sec", "grad", o_g, n_g,
                    f"{(n_g / o_g - 1):.0%} > {limit:.0%} drop",
                ))
        o_rows = {tuple(r["grid"]): r for r in o_grad.get("rows") or []}
        n_rows = {tuple(r["grid"]): r for r in n_grad.get("rows") or []}
        for key in sorted(o_rows.keys() & n_rows.keys()):
            o_r, n_r = o_rows[key].get("ratio"), n_rows[key].get("ratio")
            if o_r is None or n_r is None:
                continue
            where_grad = f"grad {_grid_label(key)}"
            limit = tol["grad-pct"]
            if n_r > max(o_r * (1.0 + limit), o_r + 0.1):
                regressions.append(Regression(
                    "grad_adjoint_ratio", where_grad, o_r, n_r,
                    f"adjoint/primal ratio +{(n_r / o_r - 1):.0%} > "
                    f"+{limit:.0%}",
                ))
    elif (o_grad is None) != (n_grad is None):
        notes.append("grad: only in one round, skipped")

    # the bandwidth key: per-cell T_solver/GB/s drift between rounds
    # under `bandwidth-pct`, plus two hard pins carried by the new
    # round itself — the ≤0.6× modeled byte ratio and the bf16 l2
    # parity flag — which are acceptance facts, not noise-band numbers
    def bw_cells(rec):
        row = rec.get("bandwidth")
        if not isinstance(row, dict) or not row.get("available"):
            return {}
        return {
            (c.get("engine"), c.get("storage")): c
            for c in row.get("cells") or []
        }

    o_bw, n_bw = bw_cells(old), bw_cells(new)
    for key in sorted(o_bw.keys() & n_bw.keys()):
        where_bw = f"bandwidth {key[0]}/{key[1]}"
        o_t = o_bw[key].get("t_solver_s")
        n_t = n_bw[key].get("t_solver_s")
        if not one_sided("bandwidth t_solver_s", where_bw, o_t, n_t) and \
                o_t and n_t is not None:
            limit = tol["bandwidth-pct"]
            if n_t > o_t * (1.0 + limit):
                regressions.append(Regression(
                    "bandwidth_t_solver_s", where_bw, o_t, n_t,
                    f"+{(n_t / o_t - 1):.0%} > +{limit:.0%}",
                ))
        o_g = o_bw[key].get("hbm_gbps")
        n_g = n_bw[key].get("hbm_gbps")
        if not one_sided("bandwidth hbm_gbps", where_bw, o_g, n_g) and \
                o_g and n_g is not None:
            limit = tol["bandwidth-pct"]
            if n_g < o_g * (1.0 - limit):
                regressions.append(Regression(
                    "bandwidth_hbm_gbps", where_bw, o_g, n_g,
                    f"{(n_g / o_g - 1):.0%} > {limit:.0%} bandwidth drop",
                ))
    if n_bw:
        for key, cell in sorted(n_bw.items()):
            ratio = cell.get("byte_ratio_vs_f32")
            gate = new.get("bandwidth", {}).get("byte_ratio_gate", 0.6)
            if ratio is not None and ratio > gate:
                regressions.append(Regression(
                    "bandwidth_byte_ratio",
                    f"bandwidth {key[0]}/{key[1]}", gate, ratio,
                    f"modeled byte ratio {ratio:.2f}x > {gate:g}x gate",
                ))
            if cell.get("l2_parity") is False:
                regressions.append(Regression(
                    "bandwidth_l2_parity",
                    f"bandwidth {key[0]}/{key[1]}", 1, 0,
                    "bf16 l2 left the f32 parity band",
                ))
    if bool(o_bw) != bool(n_bw):
        notes.append("bandwidth: only in one round, skipped")

    # the fmg key: per-grid T_solver drift between rounds under
    # `fmg-pct`, plus two hard pins carried by the new round itself —
    # the constant-work-units pin (the O(N) claim) and every headline
    # row's wall-clock-vs-mg-pcg acceptance
    def fmg_rows(rec):
        row = rec.get("fmg")
        if not isinstance(row, dict):
            return {}
        return {
            tuple(r["grid"]): r for r in row.get("rows") or []
            if r.get("grid")
        }

    o_fmg, n_fmg = fmg_rows(old), fmg_rows(new)
    for key in sorted(o_fmg.keys() & n_fmg.keys()):
        where_fmg = f"fmg {_grid_label(key)}"
        o_t, n_t = o_fmg[key].get("t_solver_s"), n_fmg[key].get("t_solver_s")
        if not one_sided("fmg t_solver_s", where_fmg, o_t, n_t) and \
                o_t and n_t is not None:
            limit = tol["fmg-pct"]
            if n_t > o_t * (1.0 + limit):
                regressions.append(Regression(
                    "fmg_t_solver_s", where_fmg, o_t, n_t,
                    f"+{(n_t / o_t - 1):.0%} > +{limit:.0%}",
                ))
    if n_fmg:
        if new.get("fmg", {}).get("work_units_constant") is False:
            regressions.append(Regression(
                "fmg_work_units", "fmg", 1, 0,
                "work units per grid point left the ±20% constant band "
                "(the O(N) pin broke)",
            ))
        for key, row in sorted(n_fmg.items()):
            sp = row.get("speedup_vs_mg")
            if row.get("headline") and sp is not None and sp < 1.0:
                regressions.append(Regression(
                    "fmg_headline_speedup", f"fmg {_grid_label(key)}",
                    1.0, sp,
                    "headline F-cycle slower than mg-pcg at equal "
                    "accuracy (the wall-clock acceptance broke)",
                ))
    if bool(o_fmg) != bool(n_fmg):
        notes.append("fmg: only in one round, skipped")

    # the autotune key: tuned wall clock per shape under `autotune-pct`
    # between rounds, plus the hard pins in the new round — a tuned
    # config must never lose to the static default, and the persisted
    # registry must round-trip
    def tune_rows(rec):
        row = rec.get("autotune")
        if not isinstance(row, dict):
            return {}
        return {
            tuple(r["grid"]): r for r in row.get("rows") or []
            if r.get("grid")
        }

    o_at, n_at = tune_rows(old), tune_rows(new)
    for key in sorted(o_at.keys() & n_at.keys()):
        where_at = f"autotune {_grid_label(key)}"
        o_t, n_t = o_at[key].get("tuned_t_s"), n_at[key].get("tuned_t_s")
        if not one_sided("autotune tuned_t_s", where_at, o_t, n_t) and \
                o_t and n_t is not None:
            limit = tol["autotune-pct"]
            if n_t > o_t * (1.0 + limit):
                regressions.append(Regression(
                    "autotune_tuned_t_s", where_at, o_t, n_t,
                    f"+{(n_t / o_t - 1):.0%} > +{limit:.0%}",
                ))
    for key, row in sorted(n_at.items()):
        if row.get("tuned_loses"):
            regressions.append(Regression(
                "autotune_tuned_loses", f"autotune {_grid_label(key)}",
                row.get("static_t_s"), row.get("tuned_t_s"),
                "tuned config loses to the static default (the "
                "never-loses contract broke)",
            ))
        if row.get("roundtrip_ok") is False:
            regressions.append(Regression(
                "autotune_roundtrip", f"autotune {_grid_label(key)}",
                1, 0, "tuned-config registry round-trip broke",
            ))
    if bool(o_at) != bool(n_at):
        notes.append("autotune: only in one round, skipped")

    # the recycle key: the correlated-stream iteration cut and warm
    # solves/sec under `recycle-pct` between rounds, plus the hard pins
    # in the new round — the ≥2× cut (the ISSUE's acceptance number)
    # and the ≤10% analytic-l2 gap (a warm start must buy iterations,
    # never accuracy) are regressions outright
    def recycle_row(rec):
        row = rec.get("recycle")
        return row if isinstance(row, dict) and row.get("grid") else None

    o_rc, n_rc = recycle_row(old), recycle_row(new)
    if o_rc is not None and n_rc is not None:
        where_rc = f"recycle {_grid_label(tuple(n_rc['grid']))}"
        limit = tol["recycle-pct"]
        o_cut, n_cut = o_rc.get("iter_cut"), n_rc.get("iter_cut")
        if not one_sided("recycle iter_cut", where_rc, o_cut, n_cut) and \
                o_cut and n_cut is not None:
            if n_cut < o_cut * (1.0 - limit):
                regressions.append(Regression(
                    "recycle_iter_cut", where_rc, o_cut, n_cut,
                    f"-{(1 - n_cut / o_cut):.0%} > -{limit:.0%}",
                ))
        o_s = o_rc.get("solves_per_s_warm")
        n_s = n_rc.get("solves_per_s_warm")
        if not one_sided("recycle solves_per_s_warm", where_rc, o_s, n_s) \
                and o_s and n_s is not None:
            if n_s < o_s * (1.0 - limit):
                regressions.append(Regression(
                    "recycle_solves_per_s_warm", where_rc, o_s, n_s,
                    f"-{(1 - n_s / o_s):.0%} > -{limit:.0%}",
                ))
    if n_rc is not None:
        where_rc = f"recycle {_grid_label(tuple(n_rc['grid']))}"
        n_cut = n_rc.get("iter_cut")
        if n_cut is not None and n_cut < 2.0:
            regressions.append(Regression(
                "recycle_cut_pin", where_rc, 2.0, n_cut,
                "correlated-stream iteration cut below the 2x "
                "acceptance pin",
            ))
        gap = n_rc.get("l2_rel_gap_max")
        if gap is not None and gap > 0.10:
            regressions.append(Regression(
                "recycle_l2_gap", where_rc, 0.10, gap,
                "warm-stream analytic l2 left the 10% equal-accuracy "
                "band",
            ))
        if n_rc.get("converged") is False:
            regressions.append(Regression(
                "recycle_converged", where_rc, 1, 0,
                "a solve in the recycle stream failed to converge",
            ))
    if (o_rc is None) != (n_rc is None):
        notes.append("recycle: only in one round, skipped")

    # the contracts key (--stamp): two perf numbers are only comparable
    # under the same, clean engine-contract state — a new round measured
    # under violated contracts is a regression outright, and a changed
    # report hash means the deltas may be structural, not noise
    o_ct, n_ct = old.get("contracts"), new.get("contracts")
    if isinstance(o_ct, dict) and isinstance(n_ct, dict):
        if n_ct.get("clean") is False:
            regressions.append(Regression(
                "contracts_clean", "contracts", 1, 0,
                "new round measured under a violated engine-contract "
                "state",
            ))
        if o_ct.get("hash") != n_ct.get("hash"):
            notes.append(
                "contracts: report hash changed between rounds — the "
                "engine-contract state differs; perf deltas may be "
                "structural, not noise"
            )
    elif (o_ct is None) != (n_ct is None):
        notes.append("contracts: only in one round, skipped")

    return regressions, notes


def stamp(path: str) -> int:
    """Embed the current engine-contract state into a bench round.

    Runs the full contract matrix (abstract tracing only — cheap) and
    writes ``{"contracts": {"hash", "clean"}}`` into the record, so a
    later compare can refuse to read perf deltas across a contract
    change. Exit 0 when the matrix is clean, 1 when not (the stamp is
    still written — the compare gate is what fails the round).
    """
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"error: cannot read bench round {path}: {e}",
              file=sys.stderr)
        return 2
    rec = data.get("parsed", data) if isinstance(data, dict) else data
    if not isinstance(rec, dict):
        print(f"error: {path}: not a bench record", file=sys.stderr)
        return 2
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if ROOT not in sys.path:  # script invocation: tools/ is sys.path[0]
        sys.path.insert(0, ROOT)
    try:
        from poisson_ellipse_tpu.analysis import matrix
        from poisson_ellipse_tpu.parallel.mesh import virtual_cpu_devices

        # same virtual-mesh ritual as the analysis CLI: the matrix's
        # sharded cells trace against a (1, 2) mesh, which needs more
        # than the single default CPU device
        virtual_cpu_devices(8)
        report = matrix.run_matrix()
    except Exception as e:
        # the exit-code contract: 1 is "contracts not clean", never a
        # crash — an unimportable/unrunnable matrix is unusable input
        print(f"error: cannot run the contract matrix: {e}",
              file=sys.stderr)
        return 2
    rec["contracts"] = {
        "hash": matrix.report_hash(report),
        "clean": report["clean"],
    }
    with open(path, "w") as f:
        json.dump(data, f)
        f.write("\n")
    state = "clean" if report["clean"] else "NOT clean"
    print(
        f"stamped {os.path.basename(path)}: contracts {state} "
        f"({rec['contracts']['hash'][:12]})"
    )
    return 0 if report["clean"] else 1


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    if "--stamp" in argv:
        argv.remove("--stamp")
        if len(argv) != 1:
            print(
                "usage: python tools/bench_compare.py --stamp "
                "BENCH_rN.json",
                file=sys.stderr,
            )
            return 2
        return stamp(argv[0])
    if len(argv) not in (0, 2):
        print(
            "usage: python tools/bench_compare.py [--json] "
            "[OLD.json NEW.json | --stamp BENCH_rN.json]\n(no paths: the "
            "newest two BENCH_r*.json rounds in the repo root)",
            file=sys.stderr,
        )
        return 2
    if argv:
        old_path, new_path = argv
    else:
        rounds = newest_rounds()
        if len(rounds) < 2:
            print(
                f"need two BENCH_r*.json rounds in {ROOT} to compare, "
                f"found {len(rounds)}",
                file=sys.stderr,
            )
            return 2
        old_path, new_path = rounds
    try:
        tol = load_tolerances()
        old, new = load_round(old_path), load_round(new_path)
    except SystemExit as e:
        # the exit-code contract: unusable input is 2, NEVER 1 — a CI
        # gate reading 1 as "perf regression" must not misclassify a
        # corrupt artifact or a typo'd tolerance as a slowdown
        print(f"error: {e}", file=sys.stderr)
        return 2
    regressions, notes = compare(old, new, tol)
    if as_json:
        print(json.dumps({
            "old": os.path.basename(old_path),
            "new": os.path.basename(new_path),
            "tolerances": tol,
            "regressions": [
                {
                    "metric": r.metric, "where": r.where,
                    "old": r.old, "new": r.new, "limit": r.limit,
                }
                for r in regressions
            ],
            "notes": notes,
        }))
    else:
        print(
            f"bench_compare: {os.path.basename(old_path)} -> "
            f"{os.path.basename(new_path)}"
        )
        for note in notes:
            print(f"  note: {note}")
        for r in regressions:
            print(f"  {r}")
        if not regressions:
            print("  no regressions (within tolerances)")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
