"""Regenerate README.md's measured-performance blocks from a BENCH artifact.

The README's headline speedup and measured table are GENERATED — not
hand-edited — from the machine-readable JSON line `bench.py` prints
(which the round driver archives as `BENCH_r{N}.json`). One number, one
source:

    python bench.py > /tmp/bench.json   # or use the driver's BENCH_r*.json
    python tools/update_readme_bench.py [/tmp/bench.json]

With no argument the newest `BENCH_r*.json` in the repo root is used —
"newest" by parsed round number (mtime breaks ties), not filename sort,
so r100 beats r99 — and the chosen file is echoed. Both formats are
accepted: the driver artifact (``{"parsed": {...}}``) and bench.py's raw
stdout line. Every number in the generated text (headline grid,
iteration count, reference baseline, chip name) is derived from the
artifact's own rows; nothing is hardcoded here. The tool rewrites the
text between the ``<!-- bench:... -->`` marker pairs in README.md and
leaves everything else untouched; artifacts missing any of the
machine-readable keys are rejected with a pointer to re-run the bench.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
README = os.path.join(ROOT, "README.md")

# every key table_block/headline_block reads; a partial artifact gets the
# curated error below, never a bare KeyError
REQUIRED_KEYS = ("value", "vs_baseline", "grids", "config2", "eps_sweep", "f64")

# chip the committed budgets/artifacts were measured on: the honest
# fallback for artifacts that predate bench.py's "device" field
MEASURED_DEVICE = "TPU v5e"

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def _round_key(path: str) -> tuple[int, float]:
    m = _ROUND_RE.search(os.path.basename(path))
    n = int(m.group(1)) if m else -1
    return n, os.path.getmtime(path)


def newest_artifact(root: str = ROOT) -> str:
    """The highest-round (mtime tie-broken) BENCH_r*.json under root."""
    rounds = glob.glob(os.path.join(root, "BENCH_r*.json"))
    if not rounds:
        raise SystemExit(f"no BENCH_r*.json found in {root}; pass a path")
    picked = max(rounds, key=_round_key)
    print(
        f"using {os.path.basename(picked)} "
        f"(round {_round_key(picked)[0]}, newest of {len(rounds)} artifacts)"
    )
    return picked


def load_artifact(path: str | None, root: str = ROOT) -> tuple[dict, str]:
    """(parsed bench record, source label)."""
    if path is None:
        path = newest_artifact(root)
    with open(path) as f:
        data = json.load(f)
    rec = data.get("parsed", data)  # driver artifact vs raw bench line
    # empty rows are as unusable as absent ones (an aborted driver run
    # can serialize "grids": []) — same curated error, not an IndexError
    missing = [
        k
        for k in REQUIRED_KEYS
        if k not in rec or (isinstance(rec[k], list) and not rec[k])
    ]
    if missing:
        raise SystemExit(
            f"{path} predates the machine-readable bench rows "
            f"(missing: {', '.join(missing)}); re-run "
            "`python bench.py > out.json` and pass that file"
        )
    return rec, os.path.basename(path)


def fmt_t(t: float) -> str:
    return f"{t:.4f} s" if t < 1 else f"{t:.2f} s"


def headline_row(rec: dict) -> dict:
    """The grids row the headline `value` was measured on.

    Matched by the timing itself (both come from the same bench run);
    falls back to the first row carrying a reference baseline, so a
    hand-rounded artifact still resolves to the comparable row.
    """
    for row in rec["grids"]:
        if row["t_solver_s"] == rec["value"]:
            return row
    for row in rec["grids"]:
        if row.get("ref_p100_s"):
            return row
    return rec["grids"][0]


def _delta_of(rec: dict) -> str | None:
    m = re.search(r"to\s+(?:δ=)?([0-9.eE+-]+)\)", rec.get("metric", ""))
    return m.group(1) if m else None


def headline_block(rec: dict, src: str) -> str:
    row = headline_row(rec)
    M, N = row["grid"]
    delta = _delta_of(rec)
    iters = f"{row['iters']} iterations" + (f" to δ={delta}" if delta else "")
    device = rec.get("device", MEASURED_DEVICE)
    ref = row.get("ref_p100_s")
    vs = (
        f"**{rec['vs_baseline']:g}×** the reference's stage4 single-P100 "
        f"{ref} s" if ref else f"**{rec['vs_baseline']:g}×** the "
        "reference baseline"
    )
    return (
        f"Measured headline: **{fmt_t(rec['value'])}** for {M}×{N} "
        f"({iters}) on one {device} chip — {vs}. "
        f"(Generated from `{src}` by "
        f"`tools/update_readme_bench.py` — the same artifact as the "
        f"table below.)"
    )


def table_block(rec: dict, src: str) -> str:
    lines = [
        "`T_solver`, median, fenced, marginal-cost protocol (host↔device "
        "RTT cancelled); reference numbers from `BASELINE.md` (P100). "
        f"Generated from `{src}` by `tools/update_readme_bench.py`:",
        "",
        "| Grid | iters | engine | this framework | stage4 1×P100 | speedup |",
        "|---|---|---|---|---|---|",
    ]
    bold_grid = headline_row(rec)["grid"]
    for row in rec["grids"]:
        M, N = row["grid"]
        ref = f"{row['ref_p100_s']} s" if row.get("ref_p100_s") else "—"
        vs = f"**{row['vs_p100']:g}×**" if row.get("vs_p100") else "—"
        bold = "**" if row["grid"] == bold_grid else ""
        lines.append(
            f"| {M}×{N} | {row['iters']} | {row['engine']} | "
            f"{bold}{fmt_t(row['t_solver_s'])}{bold} | {ref} | {vs} |"
        )
    for key, note in (("config2", "BASELINE config 2"),
                      ("north_star", "north-star config"),
                      ("config4_1chip", "config-4 grid on ONE chip")):
        row = rec.get(key)
        if row is None:
            continue
        M, N = row["grid"]
        lines.append(
            f"| {M}×{N} | {row['iters']} | {row['engine']} | "
            f"{fmt_t(row['t_solver_s'])} | — ({note}) | — |"
        )
    pipe = rec.get("pipelined")  # absent in pre-pipelined artifacts
    if pipe is not None:
        M, N = pipe["grid"]
        vs = (
            f"{pipe['vs_xla']:g}× vs xla ({fmt_t(pipe['t_xla_s'])})"
            if pipe.get("vs_xla")
            else "—"
        )
        lines.append(
            f"| {M}×{N} | {pipe['iters']} | pipelined | "
            f"{fmt_t(pipe['t_solver_s'])} | — (1 fused reduction/iter) | "
            f"{vs} |"
        )
    f64 = rec["f64"]
    eps = rec["eps_sweep"]
    eps_iters = sorted({r["iters"] for r in eps})
    eps_span = (
        f"{eps_iters[0]}" if len(eps_iters) == 1
        else f"{eps_iters[0]}–{eps_iters[-1]}"
    )
    M, N = rec["config2"]["grid"]
    lines += [
        "",
        f"The f64 fidelity row (emulated f64 on TPU): "
        f"{f64['grid'][0]}×{f64['grid'][1]} converges in exactly the "
        f"published {f64['iters']} iterations at {fmt_t(f64['t_solver_s'])} "
        "— still faster than the reference's single-P100 f32 time. The "
        f"ε-stiffness sweep at {M}×{N} (BASELINE config 5) is flat: "
        f"{eps_span} iterations across ε ∈ {{1e-2 … 1e-6}} — the Jacobi "
        "preconditioner absorbs the 1/ε stiffness, so the solver does "
        "not degrade as the fictitious domain hardens.",
    ]
    obs = observability_lines(rec)
    if obs:
        lines += [""] + obs
    precond = precond_lines(rec)
    if precond:
        lines += [""] + precond
    spectrum = spectrum_lines(rec)
    if spectrum:
        lines += [""] + spectrum
    serving = serving_lines(rec)
    if serving:
        lines += [""] + serving
    fleet = fleet_lines(rec)
    if fleet:
        lines += [""] + fleet
    geometry = geometry_lines(rec)
    if geometry:
        lines += [""] + geometry
    grad = grad_lines(rec)
    if grad:
        lines += [""] + grad
    bandwidth = bandwidth_lines(rec)
    if bandwidth:
        lines += [""] + bandwidth
    fmg = fmg_lines(rec)
    if fmg:
        lines += [""] + fmg
    autotune = autotune_lines(rec)
    if autotune:
        lines += [""] + autotune
    recycle = recycle_lines(rec)
    if recycle:
        lines += [""] + recycle
    return "\n".join(lines)


def fmg_lines(rec: dict) -> list[str]:
    """Markdown for the artifact's ``fmg`` key (full multigrid as the
    solver, emitted since mg/fmg landed): T_solver + work units per
    grid point vs mg-pcg per grid. Pre-FMG artifacts lack the key and
    render without the table; a failed row (no t_solver_s) is skipped,
    not a crash."""
    fmg = rec.get("fmg")
    if not isinstance(fmg, dict):
        return []
    rows = [
        r for r in (fmg.get("rows") or [])
        if r.get("t_solver_s") and r.get("grid")
    ]
    if not rows:
        return []
    wu_pin = (
        "work units per grid point constant across grids (the O(N) pin)"
        if fmg.get("work_units_constant")
        else "WORK-UNIT PIN BROKEN"
    )
    lines = [
        "Full multigrid as the solver (`mg/fmg`: one O(N) F-cycle + a "
        "VERIFIED mg-pcg handoff against δ — accuracy measured, never "
        f"assumed; {wu_pin}; `fmg-pct` regression-gated by "
        "`tools/bench_compare.py`):",
        "",
        "| Grid | T_solver | handoff iters | work units/pt | vs mg-pcg |",
        "|---|---|---|---|---|",
    ]
    for r in rows:
        M, N = r["grid"]
        vs = (
            f"**{r['speedup_vs_mg']:g}×**"
            if r.get("speedup_vs_mg") else "—"
        )
        head = " (headline)" if r.get("headline") else ""
        lines.append(
            f"| {M}×{N}{head} | {fmt_t(r['t_solver_s'])} | "
            f"{r.get('iters', '—')} | "
            f"{r.get('work_units_per_point', '—')} | {vs} |"
        )
    return lines


def autotune_lines(rec: dict) -> list[str]:
    """Markdown for the artifact's ``autotune`` key (the closed-loop
    tuner, emitted since runtime/autotune landed): tuned-vs-static wall
    clock per shape. Pre-tuner artifacts lack the key and render
    without the table; a failed row (no tuned_t_s) is skipped."""
    at = rec.get("autotune")
    if not isinstance(at, dict):
        return []
    rows = [
        r for r in (at.get("rows") or [])
        if r.get("tuned_t_s") and r.get("grid")
    ]
    if not rows:
        return []
    lines = [
        "Telemetry-driven autotuning (`runtime.autotune`: per-shape "
        "configs scored from measured κ/Ritz predictions and GB/s, "
        "persisted next to the XLA cache; a tuned config that loses to "
        "the static default fails the `autotune-pct` gate):",
        "",
        "| Grid | tuned engine | tuned | static default | verdict |",
        "|---|---|---|---|---|",
    ]
    for r in rows:
        M, N = r["grid"]
        verdict = (
            "TUNED LOSES (gate fails)" if r.get("tuned_loses")
            else ("static stands" if r.get("tuned_engine")
                  == r.get("static_engine") else "tuned wins")
        )
        lines.append(
            f"| {M}×{N} | {r.get('tuned_engine', '—')} | "
            f"{fmt_t(r['tuned_t_s'])} | "
            f"{fmt_t(r['static_t_s'])} ({r.get('static_engine', '?')}) | "
            f"{verdict} |"
        )
    return lines


def recycle_lines(rec: dict) -> list[str]:
    """Markdown for the artifact's ``recycle`` key (Krylov recycling on
    the correlated stream, emitted since solver/recycle landed):
    cold-vs-warm iterations, the measured cut against the ≥2× pin, and
    solves/sec both ways. Pre-recycling artifacts lack the key and
    render without the block; a failed row (no iter_cut — the capture
    solve or harvest declined) is skipped, not a crash."""
    rc = rec.get("recycle")
    if not isinstance(rc, dict):
        return []
    if not rc.get("grid") or rc.get("iter_cut") is None:
        return []
    M, N = rc["grid"]
    verdict = (
        f"**{rc['iter_cut']:g}× cut**" if rc.get("valid")
        else f"{rc['iter_cut']:g}× (PIN BROKEN)"
    )
    gap = rc.get("l2_rel_gap_max")
    return [
        "Krylov recycling (`solver.recycle` + `runtime.solvecache`: one "
        "ring-carrying capture solve harvests a "
        f"{rc.get('basis_rank', '?')}-mode deflation basis, then each "
        "correlated request warm-starts from the previous solution "
        "deflated against its true residual; `recycle-pct` gated with "
        "the ≥2× cut hard-pinned by `tools/bench_compare.py`):",
        "",
        "| Grid | stream | iters cold → warm | cut | solves/s cold → "
        "warm | analytic-l2 gap |",
        "|---|---|---|---|---|---|",
        f"| {M}×{N} | {rc.get('stream', '—')} related requests | "
        f"{rc.get('iters_cold_mean', '—')} → "
        f"{rc.get('iters_warm_mean', '—')} | {verdict} | "
        f"{rc.get('solves_per_s_cold', '—')} → "
        f"{rc.get('solves_per_s_warm', '—')} | "
        + (f"{gap:.1%} |" if gap is not None else "— |"),
    ]


def bandwidth_lines(rec: dict) -> list[str]:
    """Markdown for the artifact's ``bandwidth`` key ({f32, bf16-
    storage} × {pipelined, sstep} at the HBM-bound grid, emitted since
    the precision/s-step axes landed). Pre-bandwidth artifacts lack the
    key and render without the table; a failed study
    (``available: false``) or empty cell list renders nothing — absence
    and failure are supported inputs, not errors."""
    bw = rec.get("bandwidth")
    if not isinstance(bw, dict) or not bw.get("available"):
        return []
    cells = [c for c in (bw.get("cells") or []) if c.get("t_solver_s")]
    if not cells:
        return []
    g = bw.get("grid", ["?", "?"])
    lines = [
        f"Memory-bandwidth frontier at {g[0]}×{g[1]} (bf16 storage / "
        "f32 compute + s-step CG; the bf16 cells run the guard's "
        "storage-promotion ladder, so their l2 is recovered at full "
        "width — regression-gated by `tools/bench_compare.py` "
        "`bandwidth-pct` with the ≤0.6× byte ratio and l2 parity as "
        "hard pins):",
        "",
        "| engine | storage | T_solver | GB/s | l2 err | bytes/iter vs f32 |",
        "|---|---|---|---|---|---|",
    ]
    for c in cells:
        ratio = c.get("byte_ratio_vs_f32")
        lines.append(
            f"| {c.get('engine', '?')} | {c.get('storage', '?')} | "
            f"{c['t_solver_s']:g} s | {c.get('hbm_gbps', 0):g} | "
            f"{c.get('l2_err', float('nan')):.3e} | "
            + (f"{ratio:.2f}×" if ratio is not None else "—")
            + " |"
        )
    return lines


def grad_lines(rec: dict) -> list[str]:
    """Prose for the artifact's ``grad`` key (differentiable serving,
    emitted since diff/ landed): grad-solves/sec through the scheduler
    plus the adjoint-vs-primal iteration ratio per grid. Pre-diff
    artifacts lack the key and render without the lines; a failed run
    (no grad_solves_per_sec) still renders any iteration-ratio rows it
    carries — absence and partial are supported inputs, not errors."""
    grad = rec.get("grad")
    if not isinstance(grad, dict):
        return []
    lines = []
    gps = grad.get("grad_solves_per_sec")
    if gps is not None and grad.get("grid"):
        g = grad["grid"]
        lines.append(
            f"Differentiable solving (`diff/`, IFT adjoints through the "
            f"converged solve): {gps:g} grad-solves/sec at "
            f"{g[0]}×{g[1]} through the scheduler "
            f"({grad.get('lanes', '?')} candidate lanes, each gradient "
            f"= primal + adjoint lane solve; regression-gated by "
            f"`tools/bench_compare.py` `grad-pct`)."
        )
    rows = [
        r for r in (grad.get("rows") or [])
        if r.get("ratio") is not None and r.get("grid")
    ]
    if rows:
        ratios = ", ".join(
            f"{r['grid'][0]}×{r['grid'][1]} "
            f"{r['adjoint_iters']}/{r['primal_iters']} "
            f"({r['ratio']:g})"
            for r in rows
        )
        lines.append(
            f"Adjoint-vs-primal iterations (same operator, same "
            f"preconditioner): {ratios}."
        )
    return lines


def fleet_lines(rec: dict) -> list[str]:
    """Markdown for the artifact's ``fleet`` key (emitted by bench.py
    since the replicated-serving layer landed): aggregate solves/sec
    per replica count plus the kill-drill handoff p99 and (since the
    survivability layer) the kill→rejoin recovery p99. Pre-fleet
    artifacts lack the key and render without the table; a failed row
    (no solves_per_sec) is skipped, a missing kill drill renders the
    table alone, and a pre-rejoin artifact renders the kill line
    without the recovery clause — absence and partial are supported
    inputs, not errors."""
    fleet = rec.get("fleet")
    if not isinstance(fleet, dict):
        return []
    rows = [
        r for r in (fleet.get("rows") or [])
        if r.get("solves_per_sec") is not None
        and r.get("replicas") is not None
    ]
    if not rows:
        return []
    lines = [
        "Replicated fleet (`fleet/`: lease-fenced scheduler replicas "
        "behind a shape-affinity router, journal-backed handoff on "
        "replica death; aggregate throughput regression-gated by "
        "`tools/bench_compare.py` `fleet-agg-pct`):",
        "",
        "| replicas | lanes each | aggregate solves/sec |",
        "|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r['replicas']} | {r.get('lanes', '—')} | "
            f"{r['solves_per_sec']:g} |"
        )
    if fleet.get("handoff_p99_s") is not None:
        adopted = fleet.get("adopted")
        completed = fleet.get("kill_completed")
        lines.append(
            f"Kill drill: replica 0 SIGKILLed mid-stream — "
            f"{fleet.get('handoffs', '?')} journal handoff(s)"
            + (f", {adopted} request(s) adopted" if adopted is not None
               else "")
            + f", handoff latency p99 {fleet['handoff_p99_s'] * 1e3:.2f} ms"
            + (f"; {completed} request(s) completed after the kill"
               if completed is not None else "")
            + "."
        )
    if fleet.get("rejoin_latency_s") is not None:
        lines.append(
            f"Rejoin drill: the victim re-entered as a fresh "
            f"incarnation ({fleet.get('rejoins', '?')} rejoin(s)) — "
            f"kill→first-completed-solve p99 "
            f"{fleet['rejoin_latency_s'] * 1e3:.2f} ms, regression-gated "
            f"by `rejoin-p99-pct`."
        )
    return lines


def geometry_lines(rec: dict) -> list[str]:
    """Prose for the artifact's ``geometry`` key (SDF quadrature
    assembly, emitted by bench.py since the geom layer landed).
    Pre-geometry artifacts lack the key and render without the lines; a
    failed row (no composite t_solver_s) renders the parity half only —
    absence and partial are both supported inputs, not errors."""
    geo = rec.get("geometry")
    if not isinstance(geo, dict):
        return []
    lines: list[str] = []
    if geo.get("max_frac_err") is not None:
        M, N = geo.get("grid", ("?", "?"))
        over = (
            f" (assembly {geo['assembly_overhead_x']:g}× the closed "
            f"form, {fmt_t(geo['assembly_quad_s'])} host-f64 one-time)"
            if geo.get("assembly_overhead_x") else ""
        )
        lines.append(
            f"Geometry (SDF quadrature, `geom.*`): the ellipse through "
            f"the bisection quadrature matches the closed form to "
            f"{geo['max_frac_err']:.1e} relative face fraction at "
            f"{M}×{N}, solving in {geo.get('sdf_ellipse_iters', '?')} "
            f"iterations (closed-form oracle "
            f"{geo.get('oracle_iters', '?')}){over}."
        )
    comp = geo.get("composite") or {}
    if comp.get("t_solver_s") is not None:
        lines.append(
            f"Composite domain ({comp.get('domain', 'composite')}): "
            f"{fmt_t(comp['t_solver_s'])} / {comp.get('iters', '?')} "
            "iterations through the validated arbitrary-SDF path "
            "(admissibility gate + degenerate-cut clamp), discrete "
            "maximum principle held."
        )
    return lines


def precond_lines(rec: dict) -> list[str]:
    """Markdown for the artifact's ``precond`` key (emitted by bench.py
    since the multigrid layer landed): mg-pcg/cheb-pcg vs diag-PCG per
    grid. Pre-multigrid artifacts lack the key and render without the
    table; a failed row (no iters) is skipped, not a crash."""
    rows = [
        r for r in (rec.get("precond") or [])
        if r.get("iters") and r.get("grid") and r.get("engine")
    ]
    if not rows:
        return []
    lines = [
        "Preconditioning (`mg/`: geometric-multigrid V-cycle and "
        "Chebyshev polynomial engines vs the reference's diagonal "
        "preconditioner — the iteration-count wall, killed; "
        "iters/T_solver regression-gated by `tools/bench_compare.py`):",
        "",
        "| Grid | engine | iters | vs diag iters | T_solver | vs diag |",
        "|---|---|---|---|---|---|",
    ]
    for r in rows:
        M, N = r["grid"]
        red = (
            f"**{r['iters_reduction']:g}× fewer**"
            if r.get("iters_reduction") else "—"
        )
        diag_i = f" (diag {r['diag_iters']})" if r.get("diag_iters") else ""
        vs = (
            f"{r['speedup_vs_diag']:g}×"
            if r.get("speedup_vs_diag") else "—"
        )
        lines.append(
            f"| {M}×{N} | {r['engine']} | {r['iters']}{diag_i} | {red} | "
            f"{fmt_t(r['t_solver_s'])} | {vs} |"
        )
    return lines


def spectrum_lines(rec: dict) -> list[str]:
    """Markdown for the artifact's ``spectrum`` key (emitted by bench.py
    since the diagnostics layer landed): the κ-per-grid table with
    predicted-vs-actual iterations. Pre-diagnostics artifacts lack the
    key and render without the table; a failed row (no kappa — the
    trace was unusable) is skipped, not a crash."""
    rows = [
        r for r in (rec.get("spectrum") or [])
        if r.get("kappa") is not None and r.get("grid")
    ]
    if not rows:
        return []
    lines = [
        "Spectral diagnostics (`obs.spectrum`: the Lanczos tridiagonal "
        "hiding in the recorded CG α/β — κ(M⁻¹A) is what the iteration "
        "counts *are*, and the yardstick preconditioner work is measured "
        "against; κ drift between rounds is regression-gated by "
        "`tools/bench_compare.py`):",
        "",
        "| Grid | κ(M⁻¹A) | CG rate | κ-bound iters | predicted | actual |",
        "|---|---|---|---|---|---|",
    ]
    for r in rows:
        M, N = r["grid"]
        rate = f"{r['cg_rate']:.5f}" if r.get("cg_rate") is not None else "—"
        bound = r.get("iters_bound")
        pred = r.get("predicted_iters")
        err = r.get("predicted_err")
        pred_cell = (
            f"{pred} ({err:+.1%})" if pred is not None and err is not None
            else (str(pred) if pred is not None else "—")
        )
        lines.append(
            f"| {M}×{N} | {r['kappa']:.4g} | {rate} | "
            f"{bound if bound is not None else '—'} | {pred_cell} | "
            f"{r.get('iters', '—')} |"
        )
    return lines


def serving_lines(rec: dict) -> list[str]:
    """Markdown for the artifact's serving keys (``throughput`` /
    ``coldstart``, emitted by bench.py since the batch layer landed).
    Pre-batch artifacts lack the keys and render without these lines;
    a failed/partial row (no solves_per_sec) is skipped, not a crash."""
    lines: list[str] = []
    thr = rec.get("throughput")
    rows = [
        r for r in (thr or [])
        if r.get("solves_per_sec") is not None and r.get("grid")
    ]
    if rows:
        lines += [
            "Serving throughput (`--lanes`, batched engine, marginal-cost "
            "protocol — aggregate solves/sec per dispatch):",
            "",
            "| Grid | lanes | T_batch | solves/sec | vs 1 lane |",
            "|---|---|---|---|---|",
        ]
        for r in rows:
            M, N = r["grid"]
            t = (
                fmt_t(r["t_batch_s"]) if r.get("t_batch_s") is not None
                else "—"
            )
            vs = (
                f"**{r['speedup_vs_1lane']:g}×**"
                if r.get("speedup_vs_1lane") else "—"
            )
            lines.append(
                f"| {M}×{N} | {r['lanes']} | {t} | "
                f"{r['solves_per_sec']:g} | {vs} |"
            )
    cold = rec.get("coldstart")
    if cold and cold.get("t_compile_s") is not None:
        M, N = cold["grid"]
        hit = (
            "the re-request was a cache HIT returning the same executable"
            f" ({cold['t_pool_warm_s'] * 1e3:.2f} ms)"
            if cold.get("pool_hit")
            else "the re-request MISSED the warm pool (regression)"
        )
        lines.append(
            f"Cold-start split ({M}×{N}, lanes={cold.get('lanes', '?')}): "
            f"compile {fmt_t(cold['t_compile_s'])} vs solve "
            f"{fmt_t(cold['t_solve_s'])}; with the AOT warm pool "
            f"(`runtime.compile_cache`), {hit}."
        )
    return lines


def observability_lines(rec: dict) -> list[str]:
    """Prose for the artifact's observability keys (``convergence`` /
    ``collectives``, emitted by bench.py since the obs layer landed).
    Pre-obs artifacts simply lack the keys and render without these
    lines — absence is a supported input, not an error."""
    lines: list[str] = []
    conv = rec.get("convergence")
    if conv and conv.get("iters"):
        M, N = conv["grid"]
        span = (
            f", step-norm {conv['diff_first']:.1e} → {conv['diff_final']:.1e}"
            if conv.get("diff_first") is not None
            and conv.get("diff_final") is not None
            else ""
        )
        lines.append(
            f"Convergence telemetry: the {M}×{N} {conv['engine']} solve's "
            f"per-iteration curve is captured on device "
            f"(`solve(..., history=True)`, zero host syncs in the loop) — "
            f"{conv['iters']} iterations traced{span}."
        )
    recov = rec.get("recovery")
    if recov and recov.get("converged") and recov.get("iters") is not None:
        M, N = recov["grid"]
        kinds = ", ".join(recov.get("recoveries", [])) or "none"
        clean = recov.get("clean_iters")
        parity = (
            f" (clean run: {clean} — oracle parity after recovery)"
            if clean is not None else ""
        )
        lines.append(
            f"Resilience drill (`resilience.guard`): a NaN injected into "
            f"the {M}×{N} solve's residual at iteration {recov['at']} is "
            f"detected from the per-chunk health word and recovered via "
            f"{kinds}; the guarded solve reconverges in {recov['iters']} "
            f"iterations{parity} — regression-checked in every artifact."
        )
    coll = rec.get("collectives")
    if coll and coll.get("available"):
        engines = coll.get("engines", {})
        classical = engines.get("xla", {}).get("psum_per_iter")
        pipelined = engines.get("pipelined", {}).get("psum_per_iter")
        if classical is not None and pipelined is not None:
            mesh = coll.get("mesh", ["?", "?"])
            lines.append(
                f"Static collective accounting (`obs.static_cost`, "
                f"{mesh[0]}×{mesh[1]} mesh, jaxpr-derived): classical "
                f"sharded loop **{classical}** psum/iteration, pipelined "
                f"**{pipelined}** — the halved-collectives property, "
                "regression-checked in every bench artifact."
            )
    abft = rec.get("abft")
    if abft and abft.get("available") and abft.get("overhead_pct") is not None:
        M, N = abft.get("grid", ["?", "?"])
        pin = (
            "collective counts identical on/off"
            if abft.get("collectives_identical")
            else "COLLECTIVE-CADENCE PIN BROKEN"
        )
        lines.append(
            f"ABFT silent-corruption checks (`resilience.abft`): "
            f"checks-on overhead **{abft['overhead_pct']:+.2f}%** of "
            f"T_solver at {M}×{N} (gate ≤{abft.get('gate_pct', 2):g}%), "
            f"{pin} at {abft.get('psum_per_iter', '?')} psum/iteration — "
            "every checksum partial rides the existing stacked "
            "convergence psum."
        )
    return lines


def splice(text: str, marker: str, replacement: str) -> str:
    begin, end = f"<!-- bench:{marker} -->", f"<!-- /bench:{marker} -->"
    pattern = re.compile(
        re.escape(begin) + r".*?" + re.escape(end), flags=re.DOTALL
    )
    if not pattern.search(text):
        raise SystemExit(f"README.md is missing the {begin} marker pair")
    return pattern.sub(f"{begin}\n{replacement}\n{end}", text)


def regenerate(readme_path: str, artifact_path: str | None,
               root: str = ROOT) -> str:
    """Rewrite the marker blocks in ``readme_path``; returns a summary."""
    rec, src = load_artifact(artifact_path, root=root)
    with open(readme_path) as f:
        text = f.read()
    text = splice(text, "headline", headline_block(rec, src))
    text = splice(text, "table", table_block(rec, src))
    with open(readme_path, "w") as f:
        f.write(text)
    return (
        f"{os.path.basename(readme_path)} regenerated from {src}: headline "
        f"{rec['value']} s / {rec['vs_baseline']}x, "
        f"{len(rec['grids'])} grid rows"
    )


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    print(regenerate(README, argv[0] if argv else None))
    return 0


if __name__ == "__main__":
    sys.exit(main())
