"""Benchmark: T_solver on the reference's headline grids, single TPU chip.

Prints ONE JSON line on stdout:
  {"metric": ..., "value": T_solver_800x1200_s, "unit": "s", "vs_baseline": speedup}

vs_baseline is the speedup over the reference's strongest published
single-accelerator number on the same grid: stage4 MPI+CUDA, 1 rank /
1×P100, 800×1200, T_solver = 0.83 s (Этап_4_1213.pdf table 1; BASELINE.md).
Convergence (δ=1e-6, weighted norm) and the iteration-count oracles
(546 @ 400×600, 989 @ 800×1200, 1858 @ 1600×2400, 2449 @ 2400×3200) are
checked and reported on stderr; a mismatch marks the run invalid.
"""

from __future__ import annotations

import json
import statistics
import sys
import time

import jax
import jax.numpy as jnp

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.solver.pcg import pcg
from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic

# (M, N, oracle_iters, reference stage4 1-GPU T_solver seconds or None)
GRIDS = [
    (400, 600, 546, None),
    (800, 1200, 989, 0.83),
    (1600, 2400, 1858, 4.85),
    (2400, 3200, 2449, 13.24),
]
HEADLINE = (800, 1200)
REPS = 3
BATCH = 4


def bench_grid(M: int, N: int, oracle: int):
    problem = Problem(M=M, N=N)
    a, b, rhs = assembly.assemble(problem, jnp.float32)
    run = jax.jit(lambda a, b, rhs: pcg(problem, a, b, rhs))
    result = run(a, b, rhs)  # compile + warm-up
    float(result.diff)  # forced host transfer: the only reliable sync here
    # Time BATCH back-to-back dispatches with one final scalar fetch as the
    # sync point: single-stream in-order execution makes syncing the last
    # result sufficient, and batching amortises the host↔device tunnel RTT
    # (~0.1 s under axon), which would otherwise swamp the small grids.
    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        for _ in range(BATCH):
            result = run(a, b, rhs)
        float(result.diff)
        times.append((time.perf_counter() - t0) / BATCH)
    t = statistics.median(times)
    iters = int(result.iters)
    err = float(l2_error_vs_analytic(problem, result.w))
    ok = bool(result.converged) and iters == oracle
    print(
        f"  {M}x{N}: T_solver={t:.4f}s iters={iters} (oracle {oracle}) "
        f"converged={bool(result.converged)} l2_err={err:.3e}",
        file=sys.stderr,
    )
    return t, ok


def main() -> int:
    print(f"devices: {jax.devices()}", file=sys.stderr)
    headline_t, baseline, all_ok = None, None, True
    for M, N, oracle, ref_t in GRIDS:
        t, ok = bench_grid(M, N, oracle)
        all_ok &= ok
        if ref_t is not None:
            print(
                f"    vs stage4 1-GPU P100 ({ref_t}s): {ref_t / t:.2f}x",
                file=sys.stderr,
            )
        if (M, N) == HEADLINE:
            headline_t, baseline = t, ref_t
    print(
        json.dumps(
            {
                "metric": "T_solver 800x1200 (989 PCG iters to 1e-6), f32, 1 chip",
                "value": round(headline_t, 5),
                "unit": "s",
                "vs_baseline": round(baseline / headline_t, 2),
                "valid": all_ok,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
