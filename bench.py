"""Benchmark: T_solver on the reference's headline grids, single TPU chip.

Prints ONE JSON line on stdout:
  {"metric": ..., "value": T_solver_800x1200_s, "unit": "s", "vs_baseline": speedup}

vs_baseline is the speedup over the reference's strongest published
single-accelerator number on the same grid: stage4 MPI+CUDA, 1 rank /
1×P100, 800×1200, T_solver = 0.83 s (Этап_4_1213.pdf table 1; BASELINE.md).
Convergence (δ=1e-6, weighted norm) and the iteration-count oracles
(546 @ 400×600, 989 @ 800×1200, 1858 @ 1600×2400, 2449 @ 2400×3200) are
checked and reported on stderr; a mismatch marks the run invalid.

Beyond the reference grids, the BASELINE.json target configs also run and
ride inside the same JSON line (the reference publishes no numbers for
them, so they carry no vs-ratio — convergence + L2-vs-analytic are the
checks):
  config 2    — 1024×1024 single-chip        -> "config2" key
  north star  — 4096×4096 single-chip        -> "north_star" key
  pipelined   — headline grid, the one-fused-reduction-per-iteration
                engine vs xla under the same protocol -> "pipelined" key
                (oracle check ±2 iterations: a documented reordering)
  config 5    — ε-sweep (1e-2..1e-6) @ 1024² -> "eps_sweep" key, with the
                fictitious-domain stiffness result asserted: iteration
                counts stay FLAT as ε shrinks (the Jacobi preconditioner
                absorbs the 1/ε stiffness — see ``bench_eps_sweep``).
  spectrum    — κ(M⁻¹A) + predicted-vs-actual iterations per published
                grid from the Lanczos-of-CG reconstruction
                (``obs.spectrum``) -> "spectrum" key; κ is regression-
                gated between rounds by ``tools/bench_compare.py``.
  precond     — mg-pcg / cheb-pcg vs diag-PCG per published grid
                ("precond" key): iters + T_solver + l2 parity, asserted
                ≥3× iteration reduction everywhere and a wall-clock win
                at ≥1600×2400 (ROADMAP item 1's acceptance record;
                iters/t_solver regression-gated per grid).
  serving     — "throughput" key: aggregate solves/sec with the batched
                engine at lanes ∈ {1, 8, 32} on 400×600 and the headline
                grid (marginal-cost protocol; lane-0 oracle equality);
                "coldstart" key: compile-vs-solve split with the AOT warm
                pool off/on (the re-request must be a cache HIT —
                ``runtime.compile_cache``'s no-recompile contract); and
                "serving" key: sustained solves/sec + p50/p99 latency
                under a seeded Poisson arrival stream through the
                continuous-batching scheduler (``serve.scheduler``,
                chunk-boundary lane retire/refill) vs the static-batch
                baseline — valid iff every request completes.
  fleet       — "fleet" key: aggregate solves/sec through the replicated
                fleet (``fleet.FleetRouter``) at 1/2/3 replicas under
                the same mixed Poisson stream (non-decreasing within
                the serving noise floor), plus the journal-handoff
                latency p99 of a mid-stream replica kill — valid iff
                every request completes at every width and the kill
                round loses nothing (``fleet-agg-pct`` gated).
  abft        — "abft" key: the silent-corruption checks' healthy-path
                cost at 800×1200 — checks-on vs checks-off T_solver
                (gate: ≤2% overhead) with the per-iteration collective
                counts pinned IDENTICAL from the jaxpr (every checksum
                partial rides the existing stacked convergence psum —
                ``resilience.abft``).
  geometry    — "geometry" key: the SDF-general assembly study at
                400×600 — ellipse-via-quadrature vs the closed form
                (≤1e-12 relative face-fraction error, ±2 iterations,
                asserted into ``valid``), host f64 assembly overhead,
                and a composite ellipse-minus-hole solve (converged +
                discrete maximum principle) as the arbitrary-geometry
                timing row (``geom.*``).
  fmg         — "fmg" key: full multigrid as the solver (``mg.fmg``) —
                T_solver + work units per grid point vs mg-pcg per
                published grid with the constant-work-per-point pin
                (±20% across grids, the O(N) claim) and a ≥4096²
                headline row whose wall clock must beat mg-pcg at
                equal accuracy (``fmg-pct`` gated between rounds).
  autotune    — "autotune" key: the closed-loop tuner
                (``runtime.autotune``) — tuned-vs-static-default wall
                clock per shape with the never-loses pin (a tuned
                config measuring slower than the static default fails
                the round AND the ``bench_compare`` gate) and the
                registry persistence round-trip (``autotune-pct``).
  recycle     — "recycle" key: Krylov recycling (``solver.recycle``) on
                a correlated request stream — one ring-carrying capture
                solve harvests the deflation basis, then ±1%-perturbed
                rhs requests run warm (previous solution + deflated_x0)
                vs cold; mean iteration cut hard-pinned ≥2× at ≤10%
                analytic-l2 gap, plus solves/sec both ways
                (``recycle-pct`` gated between rounds).
  grad        — "grad" key: differentiable solving as a served workload
                (``diff/``) — grad-solves/sec for a batch of grad=True
                requests (primal + IFT-adjoint lane pairs) through the
                scheduler at 400×600, valid iff every gradient lands
                finite and nonzero, plus the adjoint-vs-primal
                iteration ratio per published grid (the quoted ~2x
                cost of a gradient; ``grad-pct`` gated between rounds).
"""

from __future__ import annotations

import json
import math
import os
import statistics
import sys
import time

import jax

from poisson_ellipse_tpu.harness.run import run_once
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs.trace import event as trace_event, note

# (M, N, oracle_iters, reference stage4 1-GPU T_solver seconds or None)
GRIDS = [
    (400, 600, 546, None),
    (800, 1200, 989, 0.83),
    (1600, 2400, 1858, 4.85),
    (2400, 3200, 2449, 13.24),
]
HEADLINE = (800, 1200)
REPS = 3
BATCH = 9
# BASELINE.json config 5: ε-sweep grid + values (largest -> smallest)
EPS_GRID = (1024, 1024)
EPS_VALUES = (1e-2, 1e-3, 1e-4, 1e-5, 1e-6)


def bench_grid(M: int, N: int, oracle: int, ref_t: float | None):
    # run_once provides the measurement protocol: warm-up outside the
    # timed region, then the chained differential — each rep times one
    # plain dispatch and one chained dispatch of BATCH data-dependent
    # solves, reporting the median marginal cost (t_chain - t_1)/(BATCH-1)
    # so the fixed host<->device tunnel RTT cancels. engine="auto" selects
    # the fastest single-chip engine that fits (VMEM-resident mega-kernel
    # -> streamed -> XLA).
    report = run_once(
        Problem(M=M, N=N),
        mode="single",
        dtype="f32",
        engine="auto",
        repeat=REPS,
        batch=BATCH,
    )
    ok = report.converged and report.iters == oracle
    note(
        f"  {M}x{N}: T_solver={report.t_solver:.4f}s iters={report.iters} "
        f"(oracle {oracle}) converged={report.converged} "
        f"engine={report.engine} l2_err={report.l2_error:.3e}  "
        + report.roofline_line(),
    )
    row = {
        "grid": [M, N],
        "t_solver_s": round(report.t_solver, 5),
        "iters": report.iters,
        "converged": report.converged,
        "engine": report.engine,
        "l2_error": report.l2_error,
        # achieved GB/s under the roofline traffic model (0 for the
        # VMEM-resident engine): tools/bench_compare.py gates on it
        "hbm_gbps": report.hbm_gbps,
        "hbm_peak_frac": report.hbm_peak_frac,
        "ref_p100_s": ref_t,
        "vs_p100": round(ref_t / report.t_solver, 2) if ref_t else None,
    }
    return report.t_solver, ok, row


def bench_f64_row(grid: tuple[int, int] = HEADLINE, oracle: int = 989):
    """The f64 fidelity row: the reference is entirely double precision
    (SURVEY §7 names TPU f64 the single biggest fidelity risk), so the
    bench proves the emulated-f64 path converges in exactly the published
    iteration count at the headline grid. One plain repetition — this row
    is a correctness gate, not the timed headline."""
    M, N = grid
    report = run_once(
        Problem(M=M, N=N), mode="single", dtype="f64", engine="auto"
    )
    ok = report.converged and report.iters == oracle
    note(
        f"  {M}x{N} f64: T_solver={report.t_solver:.4f}s "
        f"iters={report.iters} (oracle {oracle}) converged={report.converged} "
        f"engine={report.engine} l2_err={report.l2_error:.3e}",
    )
    row = {
        "grid": [M, N],
        "t_solver_s": round(report.t_solver, 5),
        "iters": report.iters,
        "converged": report.converged,
        "engine": report.engine,
        "l2_error": report.l2_error,
    }
    return ok, row


def bench_baseline_config(M: int, N: int, label: str, amortised: bool,
                          repeat: int = 2):
    """One BASELINE.json target config (no published reference number:
    checks are convergence + a finite, small L2-vs-analytic error).

    amortised=False uses plain dispatch timing — at the north-star size a
    solve takes seconds, so the fixed ~0.16 s tunnel RTT is noise and the
    chained protocol would multiply a multi-second solve by BATCH.
    ``repeat`` overrides the plain-protocol repetition count (the 8192²
    row keeps the driver bench's wall clock bounded with one)."""
    report = run_once(
        Problem(M=M, N=N),
        mode="single",
        dtype="f32",
        engine="auto",
        repeat=REPS if amortised else repeat,
        batch=BATCH if amortised else 1,
    )
    ok = report.converged and math.isfinite(report.l2_error) \
        and report.l2_error < 1e-2
    note(
        f"  [{label}] {M}x{N}: T_solver={report.t_solver:.4f}s "
        f"iters={report.iters} converged={report.converged} "
        f"engine={report.engine} l2_err={report.l2_error:.3e}  "
        + report.roofline_line(),
    )
    row = {
        "grid": [M, N],
        "t_solver_s": round(report.t_solver, 5),
        "iters": report.iters,
        "converged": report.converged,
        "engine": report.engine,
        "l2_error": report.l2_error,
    }
    return row, ok


def bench_pipelined_row(grid: tuple[int, int] = HEADLINE, oracle: int = 989):
    """The pipelined-engine row at the headline grid: the same amortised
    protocol as the grid rows, engine pinned to ``pipelined``, plus an
    ``xla`` run under the identical protocol for the vs-xla ratio.

    The pipelined recurrence is a documented reordering (one fused
    reduction per iteration — ``ops.pipelined_pcg``), so its oracle check
    is ±2 iterations, not equality. Its single-chip contract is "no
    slower than xla" (the win itself is the sharded path's halved
    collectives; ``vs_xla`` makes the single-chip cost visible in the
    artifact — bench_multichip --engine pipelined carries the mesh side).
    """
    M, N = grid
    pipe = run_once(
        Problem(M=M, N=N), mode="single", dtype="f32", engine="pipelined",
        repeat=REPS, batch=BATCH,
    )
    ref = run_once(
        Problem(M=M, N=N), mode="single", dtype="f32", engine="xla",
        repeat=REPS, batch=BATCH,
    )
    ok = (
        pipe.converged
        and abs(pipe.iters - oracle) <= 2
        and ref.converged
        and ref.iters == oracle
    )
    vs_xla = round(ref.t_solver / pipe.t_solver, 3) if pipe.t_solver > 0 else None
    note(
        f"  {M}x{N} pipelined: T_solver={pipe.t_solver:.4f}s "
        f"iters={pipe.iters} (oracle {oracle}±2) converged={pipe.converged} "
        f"l2_err={pipe.l2_error:.3e}  vs xla {ref.t_solver:.4f}s -> "
        f"{vs_xla}x  " + pipe.roofline_line(),
    )
    row = {
        "grid": [M, N],
        "t_solver_s": round(pipe.t_solver, 5),
        "iters": pipe.iters,
        "converged": pipe.converged,
        "engine": "pipelined",
        "l2_error": pipe.l2_error,
        "t_xla_s": round(ref.t_solver, 5),
        "vs_xla": vs_xla,
    }
    return row, ok


def bench_eps_sweep():
    """BASELINE.json config 5: the fictitious-domain stiffness study.

    Smaller ε stiffens the raw operator (face coefficients scale as 1/ε
    outside the ellipse — ``ops/assembly.py``), but the stiff rows are
    diagonally dominated by the same 1/ε, so the Jacobi-preconditioned
    system's conditioning is ε-uniform: measured iteration counts are
    *flat* as ε → 0 (e.g. 315/287/285/285/285 over ε = 1/1e-1/1e-2/1e-4/
    1e-6 at 256²). That ε-robustness — the solver does not degrade as the
    fictitious domain hardens — is the study's result, and what the sweep
    asserts: every run converged and the iteration counts sit in a narrow
    band (≤ 25% spread) across four decades of ε.

    One jitted XLA solver serves every ε: ε reaches the solve only
    through the assembled (a, b, rhs) operands (h/δ/max_iter are
    ε-independent), so the sweep pays one compile, not five — keeping
    the driver-run bench's wall clock bounded. The compile is paid by a
    fenced warm-up dispatch BEFORE the timed loop (BENCH_r05's first
    sweep entry read 1.51 s against ~0.35 s for the identical
    921-iteration solves that followed — compile leaking into the first
    timed solve), and the sweep asserts the fix holds: per-iteration
    times across the (equal-iteration) entries must stay within 2×."""
    import jax.numpy as jnp

    from poisson_ellipse_tpu.ops import assembly
    from poisson_ellipse_tpu.solver.engine import build_solver
    from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic
    from poisson_ellipse_tpu.utils.timing import fence

    M, N = EPS_GRID
    solver, warm_args, _ = build_solver(
        Problem(M=M, N=N, eps=EPS_VALUES[0]), "xla", jnp.float32
    )
    # warm the executable outside the timed region: compile + first
    # dispatch land here, so entry 0's clock sees the same warm
    # executable as every later entry
    fence(solver(*warm_args))
    rows = []
    for eps in EPS_VALUES:
        problem = Problem(M=M, N=N, eps=eps)
        args = assembly.assemble(problem, jnp.float32)
        t0 = time.perf_counter()
        result = solver(*args)
        fence(result)
        t = time.perf_counter() - t0
        l2 = float(l2_error_vs_analytic(problem, result.w))
        row = {
            "eps": eps,
            "iters": int(result.iters),
            "converged": bool(result.converged),
            "t_solver_s": round(t, 5),
            "l2_error": l2,
        }
        note(
            f"  [eps-sweep] {M}x{N} eps={eps:g}: iters={row['iters']} "
            f"converged={row['converged']} engine=xla "
            f"T_solver={t:.4f}s l2_err={l2:.3e}",
        )
        rows.append(row)
    iters = [r["iters"] for r in rows]
    flat = (max(iters) - min(iters)) <= 0.25 * min(iters)
    # the warm-up regression fence: with the compile paid up front,
    # equal-iteration sweep entries are the same work on the same warm
    # executable — per-iteration times beyond 2× apart mean something
    # (compile, allocation churn) leaked back into a timed region
    per_iter = [r["t_solver_s"] / max(r["iters"], 1) for r in rows]
    warm = max(per_iter) <= 2.0 * min(per_iter)
    ok = all(r["converged"] for r in rows) and flat and warm
    note(
        f"  [eps-sweep] iters {iters} over eps {EPS_VALUES[0]:g} -> "
        f"{EPS_VALUES[-1]:g}: "
        + (
            "flat (eps-robust, preconditioner absorbs the stiffness) — OK"
            if flat
            else "TREND VIOLATION (iteration count is eps-sensitive)"
        )
        + (
            f"; per-iter spread {max(per_iter) / min(per_iter):.2f}x "
            + ("(warm) — OK" if warm else "> 2x — WARM-UP LEAK (regression)")
        ),
    )
    return rows, ok


def bench_convergence(grid: tuple[int, int] = (400, 600), oracle: int = 546):
    """On-device convergence telemetry summary for the artifact.

    One history-enabled xla solve at the smallest published grid: the
    per-iteration (zr, diff, α, β) series is captured inside the fused
    while_loop (``obs.convergence`` — zero host syncs), summarised into
    a handful of scalars the artifact can carry, and cross-checked: the
    final traced step-norm must equal the solver's own ``diff`` exactly
    (the trace records the loop's values, not a reconstruction).

    Returns ``(row, ok, (result, trace))`` — the solve is also exactly
    the input ``bench_spectrum`` needs for this grid, so the trace is
    handed on instead of paying the full history solve twice per round.
    """
    from poisson_ellipse_tpu.solver.engine import solve as engine_solve

    import jax.numpy as jnp

    M, N = grid
    result, trace = engine_solve(
        Problem(M=M, N=N), "xla", jnp.float32, history=True
    )
    v = trace.valid()
    n = int(result.iters)
    ok = (
        bool(result.converged)
        and result.iters == oracle
        and n > 0
        and float(v["diff"][-1]) == float(result.diff)
    )
    row = {
        "grid": [M, N],
        "engine": "xla",
        "iters": n,
        "converged": bool(result.converged),
        "diff_first": float(v["diff"][0]) if n else None,
        "diff_final": float(v["diff"][-1]) if n else None,
        "zr_first": float(v["zr"][0]) if n else None,
        "zr_final": float(v["zr"][-1]) if n else None,
    }
    note(
        f"  [convergence] {M}x{N} xla history: {n} iterations traced "
        f"on-device, diff {row['diff_first']:.3e} -> {row['diff_final']:.3e} "
        + ("— OK" if ok else "— MISMATCH vs PCGResult"),
    )
    return row, ok, (result, trace)


# grids from (M, N) up where the wall-clock criterion applies: below
# this the solve is dispatch-bound and mg's extra passes/iter can wash
# out the iteration win on latency alone
PRECOND_WALLCLOCK_FLOOR = (1600, 2400)


def bench_precond(grid_rows):
    """The preconditioner study: mg-pcg (+ the cheb-pcg first rung) vs
    diag-PCG per published grid — ROADMAP item 1's acceptance record.

    ``grid_rows`` are the diag-PCG rows ``bench_grid`` already measured
    (same protocol, no re-run). Per grid: iters, T_solver and
    l2-vs-analytic for mg-pcg under the identical amortised protocol,
    plus the ratios. Checks folded into ``valid``: every run converged;
    l2_err no more than 10% ABOVE diag's (one-sided: at equal δ the
    V-cycle lands at-or-below diag's algebraic error); iteration
    reduction ≥ 3× everywhere; and a wall-clock T_solver win at the
    ≥1600×2400 grids where the solve is streaming-bound (smaller grids
    are dispatch-bound and reported without the wall-clock gate). A
    cheb-pcg row at the headline grid records the cheap first rung.
    """
    diag_by_grid = {tuple(r["grid"]): r for r in grid_rows}
    rows = []
    all_ok = True
    for M, N, _oracle, _ref in GRIDS:
        diag = diag_by_grid.get((M, N))
        engines = ["mg-pcg"] + (["cheb-pcg"] if (M, N) == HEADLINE else [])
        for engine in engines:
            report = run_once(
                Problem(M=M, N=N), mode="single", dtype="f32",
                engine=engine, repeat=REPS, batch=BATCH,
            )
            row = {
                "grid": [M, N],
                "engine": engine,
                "t_solver_s": round(report.t_solver, 5),
                "iters": report.iters,
                "converged": report.converged,
                "l2_error": report.l2_error,
            }
            ok = report.converged
            if diag is not None:
                row["diag_iters"] = diag["iters"]
                row["diag_t_solver_s"] = diag["t_solver_s"]
                row["iters_reduction"] = (
                    round(diag["iters"] / report.iters, 2)
                    if report.iters else None
                )
                row["speedup_vs_diag"] = (
                    round(diag["t_solver_s"] / report.t_solver, 2)
                    if report.t_solver > 0 else None
                )
                # one-sided: fail only when the preconditioned solve is
                # WORSE than diag by >10%. At equal δ the step-norm rule
                # leaves the V-cycle with LESS algebraic error than diag
                # (measured 2× at 1600×2400) — more accurate must never
                # read as a parity miss
                l2_ok = (
                    diag["l2_error"] > 0
                    and report.l2_error <= diag["l2_error"] * 1.10
                )
                reduction_ok = (
                    row["iters_reduction"] is not None
                    and row["iters_reduction"] >= 3.0
                )
                wallclock_ok = (
                    M * N < PRECOND_WALLCLOCK_FLOOR[0]
                    * PRECOND_WALLCLOCK_FLOOR[1]
                    or engine != "mg-pcg"
                    or (
                        row["speedup_vs_diag"] is not None
                        and row["speedup_vs_diag"] > 1.0
                    )
                )
                ok = ok and l2_ok and reduction_ok and wallclock_ok
            all_ok &= ok
            note(
                f"  [precond] {M}x{N} {engine}: iters={report.iters} "
                f"(diag {row.get('diag_iters')}, "
                f"{row.get('iters_reduction')}x fewer) "
                f"T_solver={report.t_solver:.4f}s "
                f"({row.get('speedup_vs_diag')}x vs diag) "
                f"l2_err={report.l2_error:.3e} "
                + ("— OK" if ok else "— MISS (parity/reduction/wall-clock)"),
            )
            rows.append(row)
    return rows, all_ok


def bench_fmg(precond_rows, headline_grid: tuple[int, int] = (4096, 4096)):
    """Full multigrid as the solver: T_solver + work units per grid
    point vs mg-pcg per published grid, plus the ≥4096² headline row —
    ROADMAP item 4's acceptance record.

    Per grid: one fmg solve under the amortised protocol next to the
    mg-pcg row ``bench_precond`` already measured (same protocol, no
    re-run). Checks folded into ``valid``: every run converged; l2
    parity with mg-pcg (one-sided ≤10% worse — at equal δ the F-cycle
    seed usually lands BELOW); MEASURED per-point wall clock at the
    largest grid no more than 20% over the best published grid's (the
    O(N) pin; the model's level sum ``mg.fmg.work_units_per_point`` is
    reported per row as a column); and at the headline
    ≥4096² grid a wall-clock win over mg-pcg at equal accuracy (smaller
    grids are dispatch-bound and reported without the wall-clock gate).
    """
    from poisson_ellipse_tpu.mg import coarsen
    from poisson_ellipse_tpu.mg.fmg import work_units_per_point

    mg_by_grid = {
        tuple(r["grid"]): r for r in precond_rows
        if r.get("engine") == "mg-pcg"
    }
    rows = []
    all_ok = True
    grids = [(M, N) for M, N, _o, _r in GRIDS] + [headline_grid]
    for M, N in grids:
        headline = (M, N) == headline_grid
        report = run_once(
            Problem(M=M, N=N), mode="single", dtype="f32", engine="fmg",
            repeat=1 if headline else REPS, batch=1 if headline else BATCH,
        )
        wu = work_units_per_point(coarsen.num_levels(M, N))
        row = {
            "grid": [M, N],
            "t_solver_s": round(report.t_solver, 5),
            "iters": report.iters,  # the verification-handoff count
            "converged": report.converged,
            "l2_error": report.l2_error,
            "work_units_per_point": round(wu, 2),
            "headline": headline,
        }
        ok = report.converged
        mg = mg_by_grid.get((M, N))
        if mg is None and headline:
            # the ≥4096² acceptance comparison: one mg-pcg run at the
            # headline grid (bench_precond covers the published grids)
            mg_rep = run_once(
                Problem(M=M, N=N), mode="single", dtype="f32",
                engine="mg-pcg", repeat=1, batch=1,
            )
            mg = {
                "t_solver_s": round(mg_rep.t_solver, 5),
                "iters": mg_rep.iters,
                "l2_error": mg_rep.l2_error,
            }
        if mg is not None:
            row["mg_t_solver_s"] = mg["t_solver_s"]
            row["mg_iters"] = mg["iters"]
            row["speedup_vs_mg"] = (
                round(mg["t_solver_s"] / report.t_solver, 2)
                if report.t_solver > 0 else None
            )
            l2_ok = (
                mg["l2_error"] > 0
                and report.l2_error <= mg["l2_error"] * 1.10
            )
            # the wall-clock acceptance applies where the solve is
            # streaming-bound; dispatch-bound small grids only report
            wallclock_ok = (not headline) or (
                row["speedup_vs_mg"] is not None
                and row["speedup_vs_mg"] >= 1.0
            )
            ok = ok and l2_ok and wallclock_ok
        all_ok &= ok
        note(
            f"  [fmg] {M}x{N}: T_solver={report.t_solver:.4f}s "
            f"handoff_iters={report.iters} "
            f"wu/pt={wu:.1f} l2_err={report.l2_error:.3e} "
            f"({row.get('speedup_vs_mg')}x vs mg-pcg) "
            + ("— OK" if ok else "— MISS (parity/wall-clock)"),
        )
        rows.append(row)
    # the O(N) pin, MEASURED: per-point wall clock at the largest grid
    # must not exceed the best published per-point figure by >20%.
    # Super-linear work shows up exactly here; dispatch-bound small
    # grids only push their own per-point figure UP, which the
    # one-sided anchor-on-the-min allows. (The model's geometric level
    # sum — work_units_per_point, reported per row — is a pure function
    # of num_levels and cannot regress by measurement, so it is a
    # column, not the gate.)
    t_per_point = [
        r["t_solver_s"] / float(r["grid"][0] * r["grid"][1])
        for r in rows if r["t_solver_s"] > 0
    ]
    wu_ok = (
        len(t_per_point) == len(rows) and len(t_per_point) >= 2
        and t_per_point[-1] <= min(t_per_point[:-1]) * 1.20
    )
    if not wu_ok:
        note(f"  [fmg] O(N) per-point wall-clock pin MISS: "
             f"{[f'{t:.3e}' for t in t_per_point]}")
    return {"rows": rows, "work_units_constant": wu_ok}, all_ok and wu_ok


def bench_autotune(grids=((400, 600), (800, 1200), (1600, 2400))):
    """The closed-loop autotuner's acceptance row: tuned-vs-static wall
    clock per shape (``runtime.autotune`` with ``measure=True`` — the
    never-loses contract, measured).

    Per shape: telemetry probe → candidate scoring → winner, then one
    warmed dispatch each of the winner and the static default. Valid
    iff no tuned config loses to the static default (a measured loss is
    demoted by ``tune`` itself, so a row can only fail if demotion
    broke), and the tuned registry round-trips deterministically.
    ``tools/bench_compare.py`` gates ``tuned_t_s`` per shape between
    rounds (``autotune-pct``) and hard-fails any row with
    ``tuned_loses=True``.
    """
    import tempfile

    from poisson_ellipse_tpu.runtime import autotune

    rows = []
    all_ok = True
    with tempfile.TemporaryDirectory() as td:
        reg = autotune.TuneRegistry(os.path.join(td, "autotune.json"))
        for M, N in grids:
            problem = Problem(M=M, N=N)
            rep = autotune.tune(problem, registry=reg, persist=True,
                                measure=True)
            chosen = rep["chosen"]
            t_tuned = chosen.get("measured_t_s")
            t_static = chosen.get("static_measured_t_s")
            if t_tuned is None:
                # the winner IS the static default: measure it once so
                # the row still carries a gated wall-clock number
                t_static = autotune._measure_once(
                    problem, chosen["static_engine"], jax.numpy.float32
                )
                t_tuned = t_static
            loses = t_tuned > t_static * 1.05  # measurement noise floor
            # persistence round-trip: the registry must hand back the
            # exact config it was given (determinism is select()'s pin)
            reloaded = autotune.TuneRegistry(reg.path).load().get(rep["key"])
            roundtrip_ok = (
                reloaded is not None
                and reloaded.to_json() == chosen
            )
            ok = (not loses) and roundtrip_ok
            all_ok &= ok
            note(
                f"  [autotune] {M}x{N}: {chosen['engine']} "
                f"tuned={t_tuned:.4f}s static={t_static:.4f}s "
                f"({chosen['static_engine']}) "
                + ("— OK" if ok else "— MISS (loses/round-trip)"),
            )
            rows.append({
                "grid": [M, N],
                "tuned_engine": chosen["engine"],
                "knobs": chosen["knobs"],
                "static_engine": chosen["static_engine"],
                "tuned_t_s": round(t_tuned, 5),
                "static_t_s": round(t_static, 5),
                "tuned_loses": loses,
                "roundtrip_ok": roundtrip_ok,
                "demoted": rep["demoted_to_static"],
            })
    return {"rows": rows}, all_ok


SPECTRUM_GRIDS = ((400, 600, 546), (800, 1200, 989))


def bench_spectrum(precomputed=None):
    """Spectral diagnostics rows: κ(M⁻¹A) and predicted-vs-actual
    iterations per published grid (``obs.spectrum``).

    ``precomputed`` maps a grid to an already-run history solve's
    ``(result, trace)`` (bench_convergence hands its 400×600 one over —
    same engine/dtype/history, no second full solve).

    One history-enabled xla solve per grid; the Lanczos tridiagonal
    reconstructed from the recorded α/β yields the condition number the
    iteration-count wall is made of — the before/after yardstick any
    preconditioner work (ROADMAP item 1) reports against, regression-
    gated per round by ``tools/bench_compare.py`` (κ is grid-determined:
    round-over-round drift means the estimator broke). Checks: oracle
    iteration counts, a sane κ (finite, > 1, growing with the grid —
    the measured growth law behind 546 → 5889), and the Ritz-model
    iteration prediction within ±15% of actual."""
    from poisson_ellipse_tpu.obs import spectrum as obs_spectrum
    from poisson_ellipse_tpu.solver.engine import solve as engine_solve

    import jax.numpy as jnp

    rows = []
    all_ok = True
    prev_kappa = None
    for M, N, oracle in SPECTRUM_GRIDS:
        problem = Problem(M=M, N=N)
        if precomputed and (M, N) in precomputed:
            result, trace = precomputed[(M, N)]
        else:
            result, trace = engine_solve(
                problem, "xla", jnp.float32, history=True
            )
        rep = obs_spectrum.spectrum_report(
            trace, delta=problem.delta, actual_iters=int(result.iters)
        )
        pred = rep.get("predicted_iters")
        err = rep.get("predicted_err")
        ok = (
            bool(result.converged)
            and int(result.iters) == oracle
            and rep.get("available", False)
            and rep["kappa"] > 1.0
            and math.isfinite(rep["kappa"])
            and pred is not None
            and err is not None
            and abs(err) <= 0.15
            and (prev_kappa is None or rep["kappa"] > prev_kappa)
        )
        all_ok &= ok
        prev_kappa = rep.get("kappa") if rep.get("available") else prev_kappa
        row = {
            "grid": [M, N],
            "engine": "xla",
            "iters": int(result.iters),
            "converged": bool(result.converged),
            "kappa": rep.get("kappa"),
            "lambda_min": rep.get("lambda_min"),
            "lambda_max": rep.get("lambda_max"),
            "cg_rate": rep.get("cg_rate"),
            "iters_bound": rep.get("iters_bound"),
            "predicted_iters": pred,
            "predicted_err": err,
            "stagnated": rep.get("stagnated"),
        }
        rows.append(row)
        note(
            f"  [spectrum] {M}x{N}: kappa={row['kappa']} "
            f"rate={row['cg_rate']} predicted={pred} actual={row['iters']} "
            f"(oracle {oracle}) "
            + (
                f"err={err:+.1%} — OK"
                if ok
                else "— MISMATCH (kappa/prediction out of band)"
            ),
        )
    return rows, all_ok


def bench_recovery(grid: tuple[int, int] = (400, 600), oracle: int = 546):
    """Resilience row for the artifact: one guarded solve with a NaN
    injected into the carried residual mid-solve (``resilience.guard`` +
    ``resilience.faultinject``). The guard must detect it from the
    per-chunk health word, apply the direction-preserving true-residual
    restart, and reconverge to oracle parity (±2) — the detect-and-
    correct property, regression-checked in every artifact."""
    from poisson_ellipse_tpu.resilience import (
        FaultPlan,
        SolveError,
        guarded_solve,
        inject_nan,
    )

    import jax.numpy as jnp

    M, N = grid
    at = max(oracle // 2, 1)
    try:
        guarded = guarded_solve(
            Problem(M=M, N=N), "xla", jnp.float32, chunk=64,
            faults=FaultPlan(inject_nan(at, "r")),
        )
    except SolveError as e:
        note(
            f"  [recovery] {M}x{N} nan@{at}: solve aborted "
            f"({e.classification}) — recovery FAILED"
        )
        return {
            "grid": [M, N], "engine": "xla", "fault": "nan", "at": at,
            "converged": False, "aborted": e.classification,
        }, False
    n = int(guarded.result.iters)
    kinds = [event.kind for event in guarded.recoveries]
    ok = (
        bool(guarded.result.converged)
        and abs(n - oracle) <= 2
        and kinds == ["residual-restart"]
    )
    row = {
        "grid": [M, N],
        "engine": "xla",
        "fault": "nan",
        "at": at,
        "iters": n,
        "clean_iters": oracle,
        "converged": bool(guarded.result.converged),
        "recoveries": kinds,
    }
    note(
        f"  [recovery] {M}x{N} nan@{at}: {n} iterations "
        f"(clean oracle {oracle}), recoveries={kinds} "
        + ("— OK (oracle parity after recovery)" if ok else "— PARITY MISS"),
    )
    return row, ok


def bench_recycle(grid: tuple[int, int] = (128, 128), stream_len: int = 5,
                  scale_eps: float = 0.01):
    """Krylov recycling on a correlated request stream vs cold solves —
    the headline number of ``solver.recycle`` / ``runtime.solvecache``.

    One capture solve (history + a :data:`RECYCLE_CAP`-slot Lanczos
    ring) harvests the k-mode deflation basis; then a stream of
    ``stream_len`` correlated requests — the SAME operator with the rhs
    scalar-perturbed by ±``scale_eps`` (s·rhs has analytic solution s·u,
    so analytic-l2 parity is checkable per request) — runs twice:

    - **cold**: every request from x0 = 0 (the pre-recycling fleet);
    - **warm**: each request seeded semantic-cache style with the
      PREVIOUS request's solution (deliberately unscaled — a related,
      not identical, hit) and deflated on top via ``deflated_x0``
      against its true residual.

    The grid is chosen so the ring respects the basis-quality rule
    (cap ≥ ~40% of the iteration count — ``solver.recycle``): benching
    recycling with a starved ring would measure the misconfiguration,
    not the mechanism. Valid iff every solve converges, the warm
    stream's analytic l2 matches cold per request (≤10% relative: both
    streams sit on the same ~1e-3 discretisation floor and stop on the
    same step-norm δ, so the residual wiggle is solver-tolerance-level,
    two-sided, and bounded — measured ≤5% at the widest perturbation),
    and the mean iteration cut clears the ISSUE's ≥2× pin — which
    ``tools/bench_compare.py`` also hard-gates (``recycle-pct``).
    """
    import jax.numpy as jnp

    from poisson_ellipse_tpu.ops import assembly
    from poisson_ellipse_tpu.ops.stencil import apply_a
    from poisson_ellipse_tpu.solver import recycle as rec
    from poisson_ellipse_tpu.solver.pcg import pcg
    from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic

    M, N = grid
    problem = Problem(M=M, N=N)
    a, b, rhs = assembly.assemble(problem, jnp.float32)
    h1 = jnp.asarray(problem.h1, rhs.dtype)
    h2 = jnp.asarray(problem.h2, rhs.dtype)

    # capture solve: cold, ring-carrying; its basis is what the stream
    # recycles (serve shape: first request of a bucket pays full price)
    res0, trace0, ring = pcg(
        problem, a, b, rhs, history=True, recycle=rec.RECYCLE_CAP
    )
    basis = rec.harvest(problem, a, b, trace0, ring)
    if not bool(res0.converged) or basis is None:
        note("  [recycle] capture solve failed to converge or harvest")
        return {"grid": [M, N], "valid": False}, False

    # the correlated stream: ±scale_eps scalar perturbations around 1
    scales = [
        1.0 + scale_eps * (i + 1) * (1 if i % 2 == 0 else -1)
        for i in range(stream_len)
    ]
    streams = {"cold": [], "warm": []}
    l2 = {"cold": [], "warm": []}
    converged = True
    t_stream = {}
    for mode in ("cold", "warm"):
        w_prev = res0.w
        # warm-up: compile both executables outside the timed loop
        pcg(problem, a, b, rhs).w.block_until_ready()
        pcg(problem, a, b, rhs, x0=res0.w).w.block_until_ready()
        t0 = time.perf_counter()
        for s in scales:
            rhs_s = rhs * s
            if mode == "warm":
                r0 = rhs_s - apply_a(w_prev, a, b, h1, h2)
                x0 = rec.deflated_x0(basis, rhs_s, x0=w_prev, residual=r0)
                result = pcg(
                    problem, a, b, rhs_s,
                    x0=w_prev if x0 is None else x0,
                )
            else:
                result = pcg(problem, a, b, rhs_s)
            result.w.block_until_ready()
            converged &= bool(result.converged)
            streams[mode].append(int(result.iters))
            l2[mode].append(float(l2_error_vs_analytic(problem, result.w / s)))
            w_prev = result.w
        t_stream[mode] = time.perf_counter() - t0

    mean_cold = statistics.fmean(streams["cold"])
    mean_warm = max(statistics.fmean(streams["warm"]), 1e-9)
    iter_cut = mean_cold / mean_warm
    l2_gap = max(
        abs(wv - cv) / cv for wv, cv in zip(l2["warm"], l2["cold"])
    )
    sps = {m: len(scales) / t_stream[m] for m in t_stream}
    ok = bool(converged and iter_cut >= 2.0 and l2_gap <= 0.10)
    row = {
        "grid": [M, N],
        "stream": len(scales),
        "ring_cap": rec.RECYCLE_CAP,
        "basis_rank": basis.rank,
        "capture_iters": int(res0.iters),
        "iters_cold": streams["cold"],
        "iters_warm": streams["warm"],
        "iters_cold_mean": round(mean_cold, 2),
        "iters_warm_mean": round(mean_warm, 2),
        "iter_cut": round(iter_cut, 2),
        "l2_rel_gap_max": l2_gap,
        "solves_per_s_cold": round(sps["cold"], 3),
        "solves_per_s_warm": round(sps["warm"], 3),
        "converged": bool(converged),
        "valid": ok,
    }
    note(
        f"  [recycle] {M}x{N} stream of {len(scales)}: iters "
        f"{mean_cold:.1f} cold -> {mean_warm:.1f} warm "
        f"({iter_cut:.1f}x cut), {sps['cold']:.2f} -> {sps['warm']:.2f} "
        f"solves/s, l2 gap {l2_gap:.2%} "
        + ("— OK" if ok else "— BELOW THE 2x PIN"),
    )
    return row, ok


def bench_geometry(grid: tuple[int, int] = (400, 600), oracle: int = 546):
    """The geometry key: the SDF-general assembly's cost and fidelity.

    Three facts per round, folded into ``valid``:

    - **parity** — the ellipse THROUGH the bisection quadrature matches
      the closed form to ≤1e-12 relative face fraction, and its f32
      solve lands within ±2 iterations of the oracle (the
      closed-form-stays-default acceptance, measured);
    - **assembly overhead** — host-f64 quadrature assembly time vs the
      closed form (a one-time setup cost, but it must stay a *setup*
      cost — regression-gated between rounds);
    - **composite solve** — an ellipse-minus-hole domain through the
      validated path: converged, discrete maximum principle held, and
      its T_solver as the arbitrary-geometry timing row.
    """
    import numpy as np

    from poisson_ellipse_tpu.geom import quadrature, sdf
    from poisson_ellipse_tpu.models import ellipse as ellipse_mod
    from poisson_ellipse_tpu.ops import assembly as assembly_mod
    from poisson_ellipse_tpu.solver.engine import build_solver
    from poisson_ellipse_tpu.utils.timing import fence

    M, N = grid
    p = Problem(M=M, N=N)

    t0 = time.perf_counter()
    assembly_mod.assemble_numpy(p)
    t_cf = time.perf_counter() - t0
    t0 = time.perf_counter()
    la, lb = quadrature.segment_lengths(p, sdf.Ellipse())
    t_quad = time.perf_counter() - t0

    gi = np.arange(M + 1, dtype=np.float64)
    gj = np.arange(N + 1, dtype=np.float64)
    x = p.a1 + gi * p.h1
    y = p.a2 + gj * p.h2
    xc, yc = x[:, None], y[None, :]
    la_cf = ellipse_mod.segment_length_vertical(
        xc - 0.5 * p.h1, yc - 0.5 * p.h2, yc + 0.5 * p.h2, np
    )
    lb_cf = ellipse_mod.segment_length_horizontal(
        yc - 0.5 * p.h2, xc - 0.5 * p.h1, xc + 0.5 * p.h1, np
    )
    frac_err = max(
        float(np.abs(la / p.h2 - la_cf / p.h2).max()),
        float(np.abs(lb / p.h1 - lb_cf / p.h1).max()),
    )

    solver, args, _ = build_solver(p, "xla", geometry=sdf.Ellipse())
    res = solver(*args)
    fence(res)
    sdf_iters = int(res.iters)

    composite = sdf.Difference(sdf.Ellipse(), sdf.Circle(r=0.25))
    solver_c, args_c, _ = build_solver(p, "xla", geometry=composite)
    res_c = solver_c(*args_c)
    fence(res_c)  # warm-up: compile + first dispatch out of the timing
    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        res_c = solver_c(*args_c)
        fence(res_c)  # tpulint: disable=TPU008 — timing-protocol fence
        times.append(time.perf_counter() - t0)
    w_c = np.asarray(res_c.w)
    min_u = float(w_c.min())

    ok = (
        frac_err <= 1e-12
        and abs(sdf_iters - oracle) <= 2
        and bool(res_c.converged)
        and min_u >= -1e-6
    )
    row = {
        "grid": [M, N],
        "assembly_cf_s": round(t_cf, 5),
        "assembly_quad_s": round(t_quad, 5),
        "assembly_overhead_x": round(t_quad / max(t_cf, 1e-9), 2),
        "max_frac_err": frac_err,
        "sdf_ellipse_iters": sdf_iters,
        "oracle_iters": oracle,
        "composite": {
            "domain": "ellipse-minus-hole",
            "t_solver_s": round(statistics.median(times), 5),
            "iters": int(res_c.iters),
            "converged": bool(res_c.converged),
            "min_u": min_u,
        },
    }
    note(
        f"  [geometry] {M}x{N}: quad-vs-closed-form frac err "
        f"{frac_err:.2e}, sdf-ellipse {sdf_iters} iters (oracle "
        f"{oracle}), assembly {t_quad:.3f}s vs {t_cf:.3f}s, composite "
        f"{row['composite']['t_solver_s']}s/{row['composite']['iters']} "
        f"iters " + ("— OK" if ok else "— GEOMETRY CHECK FAILED"),
    )
    return row, ok


def bench_grad(grid: tuple[int, int] = (400, 600), lanes: int = 4,
               n_requests: int = 8):
    """The grad key: differentiable solving as a served workload.

    Two facts per round, folded into ``valid``:

    - **grad-solves/sec through the scheduler** — ``n_requests``
      ``grad=True`` requests (shifted-ellipse geometry, Dirichlet-energy
      objective) at ``grid`` drained through the continuous-batching
      scheduler with ``lanes`` candidate lanes: each is a primal + an
      IFT-adjoint lane solve (``diff.serving``), the batched-candidate
      traffic shape of a shape-optimization step. Valid iff every
      request completes with a finite nonzero gradient.
    - **adjoint-vs-primal iteration ratio per published grid** — one
      ``diff.adjoint`` gradient per GRIDS row; the adjoint reuses the
      same operator and preconditioner, so its iteration count should
      track the primal's (the ratio is the quoted cost of a gradient:
      ~2x a solve). Valid iff every adjoint converged.
    """
    import numpy as np

    from poisson_ellipse_tpu.diff.adjoint import ImplicitSolver
    from poisson_ellipse_tpu.geom import sdf
    from poisson_ellipse_tpu.serve.request import ServeRequest
    from poisson_ellipse_tpu.serve.scheduler import Scheduler

    M, N = grid
    p = Problem(M=M, N=N)
    geometry = {"kind": "ellipse", "cx": 0.05, "cy": -0.02, "rx": 0.9,
                "ry": 0.45}

    sched = Scheduler(lanes=lanes, chunk=32, queue_capacity=n_requests + 1,
                      keep_solutions=False)
    # warm the bucket executable before the timed stream (the compile
    # belongs to the coldstart key, not this one)
    warm = ServeRequest(problem=p, grad=True, geometry=dict(geometry),
                        objective={"kind": "energy"}, request_id="grad-warm")
    sched.submit_request(warm)
    sched.drain()
    sched.collect()

    t0 = time.perf_counter()
    for i in range(n_requests):
        req = ServeRequest(
            problem=p, grad=True, geometry=dict(geometry),
            objective={"kind": "energy"}, request_id=f"grad-{i:03d}",
        )
        sched.submit_request(req)
    results = sched.drain()
    wall = time.perf_counter() - t0

    ok = True
    for i in range(n_requests):
        res = results.get(f"grad-{i:03d}")
        good = (
            res is not None and res.outcome == "completed"
            and res.grad is not None
            and np.all(np.isfinite(res.grad))
            and float(np.abs(np.asarray(res.grad)).max()) > 0.0
        )
        ok &= bool(good)
    gps = n_requests / wall if wall > 0 else None

    # the per-grid adjoint/primal iteration ratio (one gradient per
    # published grid; the solver quotes both solves in `last`)
    rows = []
    import jax.numpy as jnp

    template = sdf.Ellipse(cx=0.05, cy=-0.02, rx=0.9, ry=0.45)
    for gm, gn, _oracle, _ref in GRIDS:
        solver = ImplicitSolver(Problem(M=gm, N=gn), template,
                                engine="xla")
        g = jax.grad(
            lambda q: jnp.sum(solver.solve(q) ** 2)
        )({"shape": jnp.asarray(sdf.params_of(template),
                                solver.dtype)})
        quotes = list(solver.last)
        ok &= (
            len(quotes) == 2
            and all(q["converged"] for q in quotes)
            and bool(np.all(np.isfinite(np.asarray(g["shape"]))))
        )
        primal_it = quotes[0]["iters"] if quotes else 0
        adjoint_it = quotes[1]["iters"] if len(quotes) > 1 else 0
        rows.append({
            "grid": [gm, gn],
            "primal_iters": primal_it,
            "adjoint_iters": adjoint_it,
            "ratio": round(adjoint_it / max(primal_it, 1), 3),
        })
        note(
            f"  [grad] {gm}x{gn}: primal {primal_it} + adjoint "
            f"{adjoint_it} iters (ratio "
            f"{rows[-1]['ratio']})"
        )

    row = {
        "grid": [M, N],
        "lanes": lanes,
        "n_requests": n_requests,
        "grad_solves_per_sec": (
            round(gps, 3) if gps is not None else None
        ),
        "wall_s": round(wall, 4),
        "rows": rows,
        "valid": bool(ok),
    }
    note(
        f"  [grad] {M}x{N} x{n_requests} grad requests over {lanes} "
        f"lanes: {row['grad_solves_per_sec']} grad-solves/s "
        + ("— OK" if ok else "— GRAD CHECK FAILED")
    )
    return row, ok


# the ABFT healthy-path overhead gate: checks-on vs checks-off T_solver
# at the headline grid (percent; tools/bench_compare.py diffs the
# measured overhead between rounds under [tool.bench_compare] abft-pp)
ABFT_OVERHEAD_GATE_PCT = 2.0


def bench_abft(grid: tuple[int, int] = (800, 1200)):
    """The ABFT key: the silent-corruption checks' healthy-path cost.

    One sharded solve at the headline grid with ``abft=False`` and one
    with ``abft=True`` (``parallel.pcg_sharded.build_sharded_stepper``),
    both fenced and timed over the full solve. The contract this key
    regression-pins: (1) collective counts per iteration are IDENTICAL
    — every checksum partial rides the existing stacked convergence
    psum, read from the jaxpr via ``obs.static_cost``; (2) the walltime
    overhead of checks-on is ≤ 2% of T_solver (the extra work is fused
    reductions over arrays the loop already touches). Single-device
    environments skip (``available: false``) rather than fake a mesh.
    """
    if len(jax.devices()) < 2:
        note("  [abft] fewer than 2 devices: overhead study skipped")
        return {"available": False}, True
    import jax.numpy as jnp

    from poisson_ellipse_tpu.obs.static_cost import loop_collectives
    from poisson_ellipse_tpu.parallel.mesh import make_mesh
    from poisson_ellipse_tpu.parallel.pcg_sharded import (
        build_sharded_stepper,
    )

    M, N = grid
    problem = Problem(M=M, N=N)
    mesh = make_mesh()
    stats = {}
    for abft in (False, True):
        try:
            init_fn, advance_fn = build_sharded_stepper(
                problem, mesh, jnp.float32, abft=abft
            )
            state0 = init_fn()
            # warm dispatch compiles the advance; the timed one is the
            # steady-state full solve (fenced)
            jax.block_until_ready(advance_fn(state0, 1))
            t0 = time.perf_counter()
            state = advance_fn(init_fn(), problem.max_iterations)
            jax.block_until_ready(state)  # tpulint: disable=TPU011
            t = time.perf_counter() - t0
            psum, ppermute = loop_collectives(
                advance_fn, (state0, problem.max_iterations)
            )
            stats[abft] = {
                "t": t,
                "iters": int(state[0]),
                "converged": bool(state[6]),
                "psum": psum,
                "ppermute": ppermute,
            }
        except Exception as e:  # noqa: BLE001 — the study must never kill
            # the artifact: the timing rows above already ran and must ship
            note(f"  [abft] study failed ({type(e).__name__}: {e})")
            return {"available": False, "error": str(e)}, True
    off, on = stats[False], stats[True]
    overhead_pct = (
        (on["t"] - off["t"]) / off["t"] * 100.0 if off["t"] > 0 else 0.0
    )
    same_collectives = (
        off["psum"] == on["psum"] and off["ppermute"] == on["ppermute"]
    )
    ok = (
        off["converged"] and on["converged"]
        and abs(on["iters"] - off["iters"]) <= 1
        and same_collectives
        and overhead_pct <= ABFT_OVERHEAD_GATE_PCT
    )
    row = {
        "available": True,
        "grid": [M, N],
        "mesh": [int(mesh.shape[a]) for a in mesh.axis_names],
        "t_off_s": round(off["t"], 5),
        "t_on_s": round(on["t"], 5),
        "overhead_pct": round(overhead_pct, 3),
        "gate_pct": ABFT_OVERHEAD_GATE_PCT,
        "iters_off": off["iters"],
        "iters_on": on["iters"],
        "psum_per_iter": on["psum"],
        "ppermute_per_iter": on["ppermute"],
        "collectives_identical": same_collectives,
        "ok": ok,
    }
    note(
        f"  [abft] {M}x{N}: off {off['t']:.4f}s, on {on['t']:.4f}s "
        f"-> {overhead_pct:+.2f}% (gate {ABFT_OVERHEAD_GATE_PCT:.0f}%), "
        f"psum/iter {off['psum']}->{on['psum']}, "
        f"ppermute/iter {off['ppermute']}->{on['ppermute']} "
        + ("— OK" if ok else "— GATE MISS"),
    )
    return row, ok


# bandwidth key: the modeled bf16/f32 byte ratio every cell must beat
# (acceptance: ≤ 0.6×), and the l2 parity band the guarded bf16 path
# must land in relative to the f32 cell (the guard's promotion rung
# finishes every narrow solve at full width, so parity is recovered,
# not approximate — the band absorbs iterate-path noise only)
BANDWIDTH_BYTE_RATIO_GATE = 0.6
BANDWIDTH_L2_BAND = 1.10
BANDWIDTH_GRID = (2400, 3200)


def bench_bandwidth(grid: tuple[int, int] = BANDWIDTH_GRID):
    """The memory-bandwidth-frontier key: {f32, bf16-storage} ×
    {pipelined, sstep} at the HBM-bound grid.

    Per cell: T_solver, achieved GB/s against the storage-width traffic
    model (``harness.roofline``), and the analytic l2_err. The f32
    cells run the raw engines fenced and warm (steady-state); the bf16
    cells run the PRODUCT path — ``resilience.guard`` with the storage
    promotion rung, because the raw narrow engines converge to the
    storage floor by design — under the guard's documented plain-wall-
    clock protocol (adapter builds included; ``protocol`` names this
    per cell, and the round-over-round gate in bench_compare compares
    like with like). A bf16 cell's GB/s apportions its bytes across
    the narrow phase and the full-width polish using the promotion
    iteration from the recovery log — never all-narrow for a run whose
    tail ran full-width. Gates folded into ``valid``: every cell
    converged, each bf16 cell's modeled HBM bytes/iter ≤ 0.6× its f32
    sibling's, and bf16 l2_err within the parity band of f32's.
    """
    import jax.numpy as jnp

    from poisson_ellipse_tpu.harness.roofline import (
        modeled_hbm_bytes_per_iter,
        roofline,
    )
    from poisson_ellipse_tpu.resilience.guard import guarded_solve
    from poisson_ellipse_tpu.solver.engine import build_solver
    from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic

    M, N = grid
    problem = Problem(M=M, N=N)
    cells = []
    ok = True
    try:
        for engine in ("pipelined", "sstep"):
            f32_l2 = None
            for storage in (None, "bf16"):
                if storage is None:
                    solver, args, _ = build_solver(problem, engine)
                    jax.block_until_ready(solver(*args))  # warm compile
                    t0 = time.perf_counter()
                    result = solver(*args)
                    jax.block_until_ready(result)  # tpulint: disable=TPU011
                    t = time.perf_counter() - t0
                    iters = int(result.iters)
                    converged = bool(result.converged)
                    w = result.w
                    narrow_iters = None
                else:
                    t0 = time.perf_counter()
                    guarded = guarded_solve(
                        problem, engine, jnp.float32, storage_dtype=storage
                    )
                    jax.block_until_ready(guarded.result.w)  # tpulint: disable=TPU011
                    t = time.perf_counter() - t0
                    iters = int(guarded.result.iters)
                    converged = bool(guarded.result.converged)
                    w = guarded.result.w.astype(jnp.float32)
                    # iterations the NARROW phase ran: up to the
                    # promotion event (whole run if it never fired)
                    narrow_iters = iters
                    for ev in guarded.recoveries:
                        if ev.kind == "storage-promotion":
                            narrow_iters = min(narrow_iters, ev.at_iter)
                l2 = float(l2_error_vs_analytic(problem, w))
                if storage is None or narrow_iters is None:
                    roof = roofline(
                        problem, engine, iters, t, jnp.float32,
                        storage_dtype=storage,
                    )
                else:
                    # apportion: narrow_iters at bf16 bytes + the
                    # full-width polish at f32 bytes, over the one
                    # measured wall clock
                    from poisson_ellipse_tpu.harness.roofline import (
                        hbm_peak_bytes_per_s,
                        modeled_hbm_bytes_per_iter,
                    )

                    total_bytes = (
                        narrow_iters * modeled_hbm_bytes_per_iter(
                            problem, engine, jnp.float32,
                            storage_dtype=storage,
                        )
                        + max(iters - narrow_iters, 0)
                        * modeled_hbm_bytes_per_iter(
                            problem, engine, jnp.float32
                        )
                    )
                    gbps = total_bytes / t / 1e9 if t > 0 else 0.0
                    peak = hbm_peak_bytes_per_s()
                    roof = {
                        "hbm_gbps": round(gbps, 2),
                        "hbm_peak_frac": (
                            round(total_bytes / t / peak, 4)
                            if peak and t > 0 else None
                        ),
                    }
                modeled = modeled_hbm_bytes_per_iter(
                    problem, engine, jnp.float32, storage_dtype=storage
                )
                if storage is None:
                    f32_l2 = l2
                    byte_ratio, parity = None, True
                else:
                    f32_modeled = modeled_hbm_bytes_per_iter(
                        problem, engine, jnp.float32
                    )
                    byte_ratio = modeled / f32_modeled
                    parity = l2 <= BANDWIDTH_L2_BAND * f32_l2
                    ok &= byte_ratio <= BANDWIDTH_BYTE_RATIO_GATE and parity
                ok &= converged
                cells.append({
                    "engine": engine,
                    "storage": storage or "f32",
                    # f32 cells: fenced steady-state dispatch; bf16
                    # cells: the guard's plain wall clock, builds
                    # included (the documented resilience stance)
                    "protocol": (
                        "fenced-warm" if storage is None
                        else "guarded-wall-clock"
                    ),
                    "t_solver_s": round(t, 5),
                    "iters": iters,
                    **(
                        {"narrow_iters": narrow_iters}
                        if narrow_iters is not None else {}
                    ),
                    "converged": converged,
                    "l2_err": l2,
                    "hbm_gbps": roof["hbm_gbps"],
                    "hbm_peak_frac": roof["hbm_peak_frac"],
                    "modeled_bytes_per_iter": modeled,
                    **(
                        {"byte_ratio_vs_f32": round(byte_ratio, 4),
                         "l2_parity": parity}
                        if byte_ratio is not None else {}
                    ),
                })
                note(
                    f"  [bandwidth] {engine}/{storage or 'f32'} {M}x{N}: "
                    f"{t:.3f}s, {iters} iters, l2 {l2:.3e}, "
                    f"{roof['hbm_gbps']:.0f} GB/s"
                    + (
                        f", bytes ratio {byte_ratio:.2f}x"
                        if byte_ratio is not None else ""
                    )
                )
    except Exception as e:  # noqa: BLE001 — the study must never kill
        # the artifact: every other key's rows already ran and must ship
        note(f"  [bandwidth] study failed ({type(e).__name__}: {e})")
        return {"available": False, "error": str(e)}, True
    return {
        "available": True,
        "grid": [M, N],
        "byte_ratio_gate": BANDWIDTH_BYTE_RATIO_GATE,
        "l2_band": BANDWIDTH_L2_BAND,
        "cells": cells,
        "ok": ok,
    }, ok


THROUGHPUT_LANES = (1, 8, 32)
THROUGHPUT_GRIDS = ((400, 600, 546), (800, 1200, 989))


def bench_throughput():
    """The serving-throughput study: aggregate solves/sec vs lane count.

    Each row runs the ``batched`` engine with lanes ∈ {1, 8, 32} under
    the same marginal-cost protocol as the grid rows (chained dispatches,
    fixed host↔device RTT cancelled), at 400×600 and the 800×1200
    headline grid. Lane 0 of the batched engine is bit-identical to the
    single solve, so the oracle check is exact equality per lane-batch.
    ``speedup_vs_1lane`` is the aggregate-throughput ratio — the number
    that justifies batching on a dispatch/latency-bound chip (BENCH_r05:
    1.29 ms/solve at 400×600 leaves most of the chip idle at 1 lane).
    """
    rows = []
    all_ok = True
    for M, N, oracle in THROUGHPUT_GRIDS:
        base_sps = None
        first_row = True
        for lanes in THROUGHPUT_LANES:
            report = run_once(
                Problem(M=M, N=N),
                mode="single",
                dtype="f32",
                engine="batched",
                lanes=lanes,
                repeat=REPS,
                batch=3,
            )
            sps = report.solves_per_sec or 0.0
            # vs-1-lane stays honest when the baseline row failed: later
            # rows carry None rather than silently rebasing on lanes=8
            if first_row:
                speedup = 1.0 if sps else None
            else:
                speedup = round(sps / base_sps, 3) if base_sps else None
            ok = (
                report.converged
                and report.iters == oracle
                and report.quarantined == 0
            )
            all_ok &= ok
            note(
                f"  [throughput] {M}x{N} lanes={lanes}: "
                f"T_batch={report.t_solver:.4f}s -> {sps:.2f} solves/s "
                f"({speedup}x vs 1 lane) iters={report.iters} "
                f"(oracle {oracle}) converged={report.converged}",
            )
            rows.append({
                "grid": [M, N],
                "lanes": lanes,
                "engine": "batched",
                "t_batch_s": round(report.t_solver, 5),
                "solves_per_sec": round(sps, 3),
                "speedup_vs_1lane": speedup,
                "iters": report.iters,
                "converged": report.converged,
            })
            if first_row:
                base_sps = sps or None
                first_row = False
    return rows, all_ok


def bench_coldstart(grid: tuple[int, int] = (400, 600), lanes: int = 8):
    """Compile-time vs solve-time split, warm pool off and on.

    Cold start is its own latency budget: the split lets future BENCH
    rounds regression-check it separately from T_solver. Three numbers:
    the AOT trace+compile cost a cacheless worker pays (`t_compile_s`),
    the steady-state solve it then runs (`t_solve_s`), and the warm
    pool's answer — a second request for the same shape bucket must be a
    cache HIT returning the already-compiled executable (`pool_hit`,
    `t_pool_warm_s` ≈ 0), which is the no-recompile contract
    ``runtime.compile_cache`` exists for.
    """
    import jax.numpy as jnp

    from poisson_ellipse_tpu.runtime.compile_cache import WarmPool
    from poisson_ellipse_tpu.solver.engine import build_solver
    from poisson_ellipse_tpu.utils.timing import fence

    M, N = grid
    problem = Problem(M=M, N=N)
    # warm pool OFF: the cold worker's path — trace + compile, timed
    solver, args, _ = build_solver(problem, "batched", jnp.float32,
                                   lanes=lanes)
    t0 = time.perf_counter()
    compiled = solver.lower(*args).compile()
    t_compile = time.perf_counter() - t0
    t0 = time.perf_counter()
    result = compiled(*args)
    fence(result)
    t_solve = time.perf_counter() - t0

    # warm pool ON: miss fills the bucket, the re-request must hit
    pool = WarmPool()
    t0 = time.perf_counter()
    first = pool.warmup("batched", grid, jnp.float32, lanes)
    t_pool_cold = time.perf_counter() - t0
    t0 = time.perf_counter()
    second = pool.warmup("batched", grid, jnp.float32, lanes)
    t_pool_warm = time.perf_counter() - t0
    hit = second.compiled is first.compiled and pool.hits == 1
    ok = bool(hit and jnp.all(result.converged))
    row = {
        "grid": [M, N],
        "engine": "batched",
        "lanes": lanes,
        "t_compile_s": round(t_compile, 4),
        "t_solve_s": round(t_solve, 4),
        "t_pool_cold_s": round(t_pool_cold, 4),
        "t_pool_warm_s": round(t_pool_warm, 6),
        "pool_hit": bool(hit),
    }
    note(
        f"  [coldstart] {M}x{N} lanes={lanes}: compile {t_compile:.3f}s "
        f"vs solve {t_solve:.4f}s; warm pool cold {t_pool_cold:.3f}s -> "
        f"re-request {t_pool_warm * 1e3:.2f} ms "
        + ("(HIT, same executable) — OK" if hit else "— MISSED (regression)"),
    )
    return row, ok


def bench_serving(n_requests: int = 32, lanes: int = 4,
                  grids=((40, 40), (48, 48)), seed: int = 0):
    """The serving key: sustained solves/sec + latency quantiles under a
    Poisson arrival stream, vs the static-batch baseline.

    The continuous-batching scheduler (``serve.scheduler``) retires and
    refills lanes at chunk boundaries, so a converged lane's slot goes
    straight to the next queued request; the static baseline solves the
    same request set in fixed ``lanes``-wide batches where every lane
    waits for the slowest (PR 5's whole-batch semantics). Reported:
    ``solves_per_sec`` for both disciplines plus the scheduler's
    p50/p99 time-in-system. Validity = every request completed (zero
    lost, zero unclassified) — the serving layer must never trade
    correctness for the throughput number.
    """
    import random

    import jax.numpy as jnp

    from poisson_ellipse_tpu.batch.driver import solve_batched
    from poisson_ellipse_tpu.serve import Scheduler

    rng = random.Random(seed)
    shapes = [rng.choice(list(grids)) for _ in range(n_requests)]

    # continuous batching: seeded arrival stream through the scheduler
    sched = Scheduler(lanes=lanes, chunk=32, queue_capacity=n_requests + 1,
                      keep_solutions=False)
    t0 = time.perf_counter()
    for i, (M, N) in enumerate(shapes):
        sched.submit(Problem(M=M, N=N), request_id=f"bench-{i:03d}")
        sched.step()
    results = sched.drain()
    t_stream = time.perf_counter() - t0
    lat = sorted(r.total_s for r in results.values())
    completed = sum(1 for r in results.values() if r.outcome == "completed")
    ok = completed == n_requests and len(results) == n_requests

    # static baseline: same requests, fixed lanes-wide batches per shape
    t0 = time.perf_counter()
    for M, N in sorted(set(shapes)):
        count = sum(1 for s in shapes if s == (M, N))
        p = Problem(M=M, N=N)
        done = 0
        while done < count:
            width = min(lanes, count - done)
            static = solve_batched(p, width, "batched", jnp.float32,
                                   chunk=1 << 30)
            ok &= bool(static.result.converged.all())
            done += width
    t_static = time.perf_counter() - t0

    def q(p):
        return lat[min(int(p * len(lat)), len(lat) - 1)] if lat else None

    row = {
        "requests": n_requests,
        "lanes": lanes,
        "grids": [list(g) for g in grids],
        "solves_per_sec": round(n_requests / t_stream, 3),
        "static_solves_per_sec": round(n_requests / t_static, 3),
        "latency_p50_s": round(q(0.50), 4) if lat else None,
        "latency_p99_s": round(q(0.99), 4) if lat else None,
        "completed": completed,
        "valid": bool(ok),
    }
    note(
        f"  [serving] {n_requests} requests over {sorted(set(shapes))} "
        f"lanes={lanes}: continuous {row['solves_per_sec']} solves/s "
        f"(p50 {row['latency_p50_s']}s, p99 {row['latency_p99_s']}s) vs "
        f"static {row['static_solves_per_sec']} solves/s — "
        + ("OK" if ok else "INCOMPLETE (regression)"),
    )
    return row, ok


# noise floor for the replicas-scaling gate: in-process replicas share
# one chip, so "non-decreasing aggregate throughput" is asserted within
# the serving wall-clock noise band, not as strict monotonic growth
FLEET_AGG_NOISE_FRAC = 0.25
FLEET_REPLICA_COUNTS = (1, 2, 3)


def bench_fleet(n_requests: int = 24, lanes: int = 2,
                grids=((10, 10), (12, 12)), seed: int = 0):
    """The fleet key: aggregate solves/sec vs replica count, plus the
    handoff-latency p99 of a mid-stream replica kill.

    The same seeded Poisson stream runs through a 1-, 2- and 3-replica
    fleet (``fleet.FleetRouter``: compile-bucket affinity routing,
    per-replica lanes). Validity folded into ``valid``: every request
    completes at every width, and aggregate solves/sec is non-decreasing
    1→3 replicas within the serving noise floor (in-process replicas
    share one chip, so the claim the gate defends is "replication does
    not COST throughput" — the scale-out win itself is a multi-host
    story). A final 2-replica round kills replica 0 mid-stream and
    reports the journal-handoff latency p99 — the fleet's
    recovery-time number, regression-gated by ``tools/bench_compare.py``
    (``fleet-agg-pct``).
    """
    import random
    import tempfile

    from poisson_ellipse_tpu.fleet import FleetRouter
    from poisson_ellipse_tpu.obs import metrics as obs_metrics
    from poisson_ellipse_tpu.resilience import faultinject

    def run_stream(replicas: int, kill_at=None, rejoin_at=None):
        rng = random.Random(seed)
        faults = []
        if kill_at is not None:
            faults.append(faultinject.replica_kill(
                at_request=kill_at, replica=0,
            ))
        with tempfile.TemporaryDirectory() as td:
            router = FleetRouter(
                replicas=replicas, journal_dir=td, lanes=lanes,
                chunk=4, queue_capacity=n_requests + 1,
                keep_solutions=False, backoff_base_s=0.001,
                faults=faultinject.FaultPlan(*faults),
            )
            t0 = time.perf_counter()
            for i in range(n_requests):
                M, N = rng.choice(list(grids))
                router.submit(Problem(M=M, N=N),
                              request_id=f"fleet-{i:03d}")
                router.step()
                if (rejoin_at is not None and i >= rejoin_at
                        and not router.rejoins
                        and not router.replicas[0].live):
                    router.rejoin_replica(0)
            results = router.drain()
            wall = time.perf_counter() - t0
        completed = sum(
            1 for r in results.values() if r.outcome == "completed"
        )
        return router, results, completed, wall

    # warm the bucket executables outside every timed round: the lru
    # cache (serve.scheduler._bucket_advance) is process-wide, so
    # WITHOUT this the 1-replica round would eat every compile and the
    # scaling comparison would measure the cache, not the fleet
    run_stream(1)

    rows = []
    all_ok = True
    prev_sps = None
    non_decreasing = True
    for replicas in FLEET_REPLICA_COUNTS:
        _, results, completed, wall = run_stream(replicas)
        sps = n_requests / wall if wall > 0 else 0.0
        ok = completed == n_requests and len(results) == n_requests
        if prev_sps is not None and sps < prev_sps * (
            1.0 - FLEET_AGG_NOISE_FRAC
        ):
            non_decreasing = False
        all_ok &= ok
        note(
            f"  [fleet] {replicas} replica(s) x {lanes} lanes: "
            f"{n_requests} requests in {wall:.3f}s -> {sps:.2f} "
            f"solves/s aggregate, completed {completed}/{n_requests} "
            + ("— OK" if ok else "— INCOMPLETE (regression)"),
        )
        rows.append({
            "replicas": replicas,
            "lanes": lanes,
            "solves_per_sec": round(sps, 3),
            "completed": completed,
            "wall_s": round(wall, 4),
        })
        prev_sps = sps
    all_ok &= non_decreasing

    # the kill→rejoin round: handoff latency under a real mid-stream
    # death, then the victim re-enters as a fresh incarnation and the
    # kill→first-completed-solve latency of the rejoiner is the fleet's
    # recovery-time-to-capacity number (rejoin_latency_s, p99)
    hist = obs_metrics.REGISTRY.histogram(
        obs_metrics.HANDOFF_LATENCY_SECONDS
    )
    rejoin_hist = obs_metrics.REGISTRY.histogram(
        obs_metrics.REJOIN_LATENCY_SECONDS
    )
    count_before = hist.count
    rejoin_count_before = rejoin_hist.count
    kill_at = max(n_requests // 3, 1)
    rejoin_at = max(2 * n_requests // 3, kill_at + 1)
    router, results, completed, _wall = run_stream(
        2, kill_at=kill_at, rejoin_at=rejoin_at
    )
    handoff_p99 = hist.quantile(0.99)
    rejoin_p99 = rejoin_hist.quantile(0.99)
    kill_ok = (
        completed == n_requests
        and router.handoffs >= 1
        and hist.count > count_before
        and router.rejoins >= 1
        and rejoin_hist.count > rejoin_count_before
    )
    all_ok &= kill_ok
    note(
        f"  [fleet] kill→rejoin drill (2 replicas, kill@{kill_at}, "
        f"rejoin@{rejoin_at}): completed {completed}/{n_requests}, "
        f"{router.handoffs} handoff(s), {router.adopted_total} adopted, "
        f"{router.rejoins} rejoin(s), "
        f"handoff p99 {handoff_p99 if handoff_p99 is None else round(handoff_p99, 5)}s, "
        f"rejoin p99 {rejoin_p99 if rejoin_p99 is None else round(rejoin_p99, 5)}s "
        + ("— OK" if kill_ok else "— RECOVERY MISS (regression)"),
    )
    row = {
        "rows": rows,
        "non_decreasing": non_decreasing,
        "handoff_p99_s": (
            round(handoff_p99, 6) if handoff_p99 is not None else None
        ),
        "rejoin_latency_s": (
            round(rejoin_p99, 6) if rejoin_p99 is not None else None
        ),
        "kill_completed": completed,
        "handoffs": router.handoffs,
        "adopted": router.adopted_total,
        "rejoins": router.rejoins,
    }
    return row, all_ok


def bench_collectives():
    """Static collective accounting for the artifact: psum/ppermute per
    iteration read from the jaxpr (``obs.static_cost``) on a 1×2 mesh of
    whatever devices this process has. THE regression this key pins: the
    classical sharded loop pays 2 psum per iteration, the pipelined
    recurrence 1. Single-device environments skip (``available: false``)
    rather than fake a mesh."""
    if len(jax.devices()) < 2:
        note("  [collectives] fewer than 2 devices: static accounting skipped")
        return {"available": False}, True
    from poisson_ellipse_tpu.obs import static_cost

    try:
        table = static_cost.collectives_table(
            Problem(M=40, N=40), engines=("xla", "pipelined"), mesh_shape=(1, 2)
        )
    except Exception as e:  # noqa: BLE001 — accounting must never kill the
        # artifact: the timing rows above already ran and must ship
        note(f"  [collectives] static accounting failed ({type(e).__name__}: {e})")
        return {"available": False, "error": str(e)}, True
    classical = table["engines"]["xla"]["psum_per_iter"]
    pipelined = table["engines"]["pipelined"]["psum_per_iter"]
    ok = classical == 2 and pipelined == 1
    note(
        f"  [collectives] static psum/iter (1x2 mesh): classical "
        f"{classical}, pipelined {pipelined} "
        + ("— OK (2 vs 1)" if ok else "— REGRESSION (expected 2 vs 1)"),
    )
    return table, ok


def main() -> int:
    note(f"devices: {jax.devices()}")
    headline_t, baseline, all_ok = None, None, True
    grid_rows = []
    for M, N, oracle, ref_t in GRIDS:
        t, ok, row = bench_grid(M, N, oracle, ref_t)
        all_ok &= ok
        grid_rows.append(row)
        if ref_t is not None:
            note(
                f"    vs stage4 1-GPU P100 ({ref_t}s): {ref_t / t:.2f}x",
            )
        if (M, N) == HEADLINE:
            headline_t, baseline = t, ref_t
    # BASELINE.json target configs (no reference numbers published).
    # The 8192² row is the config-4 grid on ONE chip (the xl engine
    # streams state beyond VMEM) — the reference reaches this size only
    # on a multi-node MPI cluster; pod weak-scaling remains
    # bench_multichip --real's job.
    config2, ok2 = bench_baseline_config(1024, 1024, "config2", amortised=True)
    north, okn = bench_baseline_config(4096, 4096, "north-star", amortised=False)
    xl8k, ok8 = bench_baseline_config(
        8192, 8192, "config4-1chip", amortised=False, repeat=1
    )
    pipe_row, okp = bench_pipelined_row()
    # the preconditioner study: mg-pcg/cheb-pcg vs the diag rows above
    # (ROADMAP item 1 — iteration reduction, l2 parity, wall-clock win)
    precond_rows, okpc = bench_precond(grid_rows)
    # full multigrid as the solver: O(N) F-cycle + verified handoff vs
    # mg-pcg per grid, work-units-per-point pin, ≥4096² headline row
    fmg_row, okfm = bench_fmg(precond_rows)
    # the closed-loop autotuner: tuned-vs-static wall clock per shape
    # (never-loses, measured) + registry round-trip
    tune_row, okat = bench_autotune()
    # the serving layer: lane-batched throughput + the cold-start split
    # (f32, before the f64 flip below)
    thr_rows, okt = bench_throughput()
    cold_row, okcs = bench_coldstart()
    # the continuous-batching front-end: sustained solves/sec + p50/p99
    # under a Poisson arrival stream vs the static-batch baseline
    serve_row, oksv = bench_serving()
    # the replicated fleet: aggregate solves/sec at 1/2/3 replicas +
    # journal-handoff latency p99 under a mid-stream replica kill
    fleet_row, okfl = bench_fleet()
    eps_rows, oke = bench_eps_sweep()
    # observability rows (f32, so they run before the f64 flip below):
    # on-device convergence telemetry + static collective accounting
    conv_row, okc, conv_solve = bench_convergence()
    coll_table, okl = bench_collectives()
    # spectral diagnostics: kappa + predicted-vs-actual iterations per
    # grid from the Lanczos-of-CG reconstruction (f32, pre-f64-flip);
    # the 400x600 history solve is bench_convergence's, not a re-run
    spec_rows, oks = bench_spectrum(precomputed={(400, 600): conv_solve})
    # resilience row: an injected NaN mid-solve must recover to oracle
    # parity through the guard (f32, before the f64 flip below)
    rec_row, okr = bench_recovery()
    # Krylov recycling: correlated stream vs cold solves — iteration
    # cut (≥2x pin) + solves/sec at equal analytic l2 (f32, pre-f64)
    rcy_row, okrc = bench_recycle()
    # ABFT overhead study: silent-corruption checks on vs off — ≤2%
    # T_solver and identical collective counts (f32, pre-f64-flip)
    abft_row, oka = bench_abft()
    # memory-bandwidth frontier: {f32, bf16-storage} × {pipelined,
    # sstep} at the HBM-bound grid — GB/s, T_solver, l2 parity and the
    # ≤0.6× modeled byte ratio (f32, pre-f64-flip)
    bw_row, okbw = bench_bandwidth()
    # geometry study: SDF-quadrature-vs-closed-form parity + overhead
    # and the composite-domain timing row (f32, pre-f64-flip)
    geom_row, okg = bench_geometry()
    # differentiable solving: grad-solves/sec through the scheduler +
    # adjoint-vs-primal iteration ratio per grid (f32, pre-f64-flip)
    grad_row, okgr = bench_grad()
    all_ok &= (
        ok2 & okn & ok8 & okp & okpc & okfm & okat & okt & okcs & oksv
        & okfl & oke & okc & okl & oks & okr & okrc & oka & okg & okgr
        & okbw
    )
    # f64 row last: resolve_dtype flips jax_enable_x64 process-globally,
    # which must not perturb the timed f32 rows above
    okf, f64_row = bench_f64_row()
    all_ok &= okf
    record = {
        "metric": "T_solver 800x1200 (989 PCG iters to 1e-6), f32, 1 chip",
        "value": round(headline_t, 5),
        "unit": "s",
        "vs_baseline": round(baseline / headline_t, 2),
        "valid": all_ok,
        # chip the run measured on, so the regenerated README
        # names the actual part instead of a hardcoded one
        "device": jax.devices()[0].device_kind,
        # machine-readable rows: tools/update_readme_bench.py
        # regenerates the README's measured table from these
        "grids": grid_rows,
        "config2": config2,
        "north_star": north,
        "config4_1chip": xl8k,
        "pipelined": pipe_row,
        # the preconditioner rows: mg-pcg (+ headline cheb-pcg) vs the
        # diag-PCG grid rows — iters/t_solver regression-gated per grid
        # by tools/bench_compare.py ([tool.bench_compare] precond-*)
        "precond": precond_rows,
        # full multigrid as the solver (mg.fmg): T_solver + work units
        # per grid point vs mg-pcg per grid, the constant-work pin, and
        # the ≥4096² headline row — gated by tools/bench_compare.py
        # ([tool.bench_compare] fmg-pct)
        "fmg": fmg_row,
        # the closed-loop autotuner (runtime.autotune): tuned-vs-static
        # wall clock per shape; a tuned config that loses to the static
        # default hard-fails the gate ([tool.bench_compare]
        # autotune-pct + the tuned_loses pin)
        "autotune": tune_row,
        # lane-batched serving throughput: solves/sec at lanes 1/8/32
        # under the marginal-cost protocol (batch.* engines)
        "throughput": thr_rows,
        # compile-vs-solve split, warm pool off/on: cold-start latency
        # as its own regression-checked number (runtime.compile_cache)
        "coldstart": cold_row,
        # continuous-batching serve layer: sustained solves/sec + p50/p99
        # latency under a Poisson arrival stream vs static batching
        # (serve.scheduler's retire-and-refill discipline)
        "serving": serve_row,
        # the replicated fleet: aggregate solves/sec at 1/2/3 replicas
        # (non-decreasing within the serving noise floor) + journal-
        # handoff latency p99 under a mid-stream replica kill, gated by
        # tools/bench_compare.py ([tool.bench_compare] fleet-agg-pct)
        "fleet": fleet_row,
        "eps_sweep": eps_rows,
        # on-device per-iteration telemetry summary (solve history=True)
        "convergence": conv_row,
        # static psum/ppermute accounting: the pipelined-1-vs-classical-2
        # property as a regression-checked artifact metric
        "collectives": coll_table,
        # Lanczos spectral diagnostics: kappa(M^-1 A) + predicted-vs-
        # actual iterations per grid (obs.spectrum), diffed between
        # rounds by tools/bench_compare.py
        "spectrum": spec_rows,
        # guarded-solve fault drill: injected NaN -> residual restart ->
        # oracle-parity reconvergence (resilience.guard)
        "recovery": rec_row,
        # Krylov recycling (solver.recycle): correlated-stream iteration
        # cut vs cold solves at equal analytic l2 + solves/sec — the
        # ≥2x cut is hard-pinned here AND by tools/bench_compare.py
        # ([tool.bench_compare] recycle-pct)
        "recycle": rcy_row,
        # ABFT silent-corruption checks: healthy-path overhead (≤2%
        # gate) with the 1-psum/iter cadence pinned identical on vs off
        "abft": abft_row,
        # memory-bandwidth frontier: {f32, bf16-storage} × {pipelined,
        # sstep} cells — measured GB/s + T_solver + analytic l2 per
        # cell, the ≤0.6× modeled byte-ratio gate, bf16-vs-f32 l2
        # parity via the guard's promotion rung; diffed between rounds
        # by tools/bench_compare.py ([tool.bench_compare] bandwidth-pct)
        "bandwidth": bw_row,
        # SDF geometry: quadrature-vs-closed-form parity (≤1e-12 frac
        # err, ±2 iters), host assembly overhead, and the composite-
        # domain (ellipse-minus-hole) solve row (geom.*)
        "geometry": geom_row,
        # differentiable solving (diff/): grad-solves/sec through the
        # scheduler (batched candidate lanes; gated by
        # tools/bench_compare.py [tool.bench_compare] grad-pct) +
        # adjoint-vs-primal iteration ratio per published grid
        "grad": grad_row,
        "f64": f64_row,
    }
    trace_event("bench_artifact", **record)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
