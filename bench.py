"""Benchmark: T_solver on the reference's headline grids, single TPU chip.

Prints ONE JSON line on stdout:
  {"metric": ..., "value": T_solver_800x1200_s, "unit": "s", "vs_baseline": speedup}

vs_baseline is the speedup over the reference's strongest published
single-accelerator number on the same grid: stage4 MPI+CUDA, 1 rank /
1×P100, 800×1200, T_solver = 0.83 s (Этап_4_1213.pdf table 1; BASELINE.md).
Convergence (δ=1e-6, weighted norm) and the iteration-count oracles
(546 @ 400×600, 989 @ 800×1200, 1858 @ 1600×2400, 2449 @ 2400×3200) are
checked and reported on stderr; a mismatch marks the run invalid.
"""

from __future__ import annotations

import json
import sys

import jax

from poisson_ellipse_tpu.harness.run import run_once
from poisson_ellipse_tpu.models.problem import Problem

# (M, N, oracle_iters, reference stage4 1-GPU T_solver seconds or None)
GRIDS = [
    (400, 600, 546, None),
    (800, 1200, 989, 0.83),
    (1600, 2400, 1858, 4.85),
    (2400, 3200, 2449, 13.24),
]
HEADLINE = (800, 1200)
REPS = 3
BATCH = 9


def bench_grid(M: int, N: int, oracle: int):
    # run_once provides the measurement protocol: warm-up outside the
    # timed region, then the chained differential — each rep times one
    # plain dispatch and one chained dispatch of BATCH data-dependent
    # solves, reporting the median marginal cost (t_chain - t_1)/(BATCH-1)
    # so the fixed host<->device tunnel RTT cancels. engine="auto" selects
    # the fastest single-chip engine that fits (VMEM-resident mega-kernel
    # -> streamed -> XLA).
    report = run_once(
        Problem(M=M, N=N),
        mode="single",
        dtype="f32",
        engine="auto",
        repeat=REPS,
        batch=BATCH,
    )
    ok = report.converged and report.iters == oracle
    print(
        f"  {M}x{N}: T_solver={report.t_solver:.4f}s iters={report.iters} "
        f"(oracle {oracle}) converged={report.converged} "
        f"engine={report.engine} l2_err={report.l2_error:.3e}  "
        + report.roofline_line(),
        file=sys.stderr,
    )
    return report.t_solver, ok


def bench_f64_row(grid: tuple[int, int] = HEADLINE, oracle: int = 989) -> bool:
    """The f64 fidelity row: the reference is entirely double precision
    (SURVEY §7 names TPU f64 the single biggest fidelity risk), so the
    bench proves the emulated-f64 path converges in exactly the published
    iteration count at the headline grid. One plain repetition — this row
    is a correctness gate, not the timed headline."""
    M, N = grid
    report = run_once(
        Problem(M=M, N=N), mode="single", dtype="f64", engine="auto"
    )
    ok = report.converged and report.iters == oracle
    print(
        f"  {M}x{N} f64: T_solver={report.t_solver:.4f}s "
        f"iters={report.iters} (oracle {oracle}) converged={report.converged} "
        f"engine={report.engine} l2_err={report.l2_error:.3e}",
        file=sys.stderr,
    )
    return ok


def main() -> int:
    print(f"devices: {jax.devices()}", file=sys.stderr)
    headline_t, baseline, all_ok = None, None, True
    for M, N, oracle, ref_t in GRIDS:
        t, ok = bench_grid(M, N, oracle)
        all_ok &= ok
        if ref_t is not None:
            print(
                f"    vs stage4 1-GPU P100 ({ref_t}s): {ref_t / t:.2f}x",
                file=sys.stderr,
            )
        if (M, N) == HEADLINE:
            headline_t, baseline = t, ref_t
    # f64 row last: resolve_dtype flips jax_enable_x64 process-globally,
    # which must not perturb the timed f32 rows above
    all_ok &= bench_f64_row()
    print(
        json.dumps(
            {
                "metric": "T_solver 800x1200 (989 PCG iters to 1e-6), f32, 1 chip",
                "value": round(headline_t, 5),
                "unit": "s",
                "vs_baseline": round(baseline / headline_t, 2),
                "valid": all_ok,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
