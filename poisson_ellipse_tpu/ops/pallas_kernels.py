"""Pallas TPU kernels for the hot PCG ops (reference stage4 kernel parity).

The reference's device-kernel inventory (``stage4-mpi+cuda/
poisson_mpi_cuda2.cu``): ``apply_A_kernel`` (:507-536), ``apply_Dinv_kernel``
(:541-562), ``dot_kernel`` (:574-598), ``update_w_r_kernel`` (fused axpy +
‖Δw‖² partials, :626-660), ``update_p_kernel`` (:663-676). Here the same
five live as Pallas kernels tiled over VMEM:

- the stencil reads a (TM+2)-row halo window per TM-row output tile. A
  ``BlockSpec`` index map cannot express overlapping windows (offsets are
  in whole blocks), so inputs stay in ``ANY``/HBM and each tile DMAs its
  window into VMEM scratch explicitly — the TPU-idiomatic form of the
  reference's 16×16 CUDA tiling (its halo reads come from L2 instead).
- the dot / update kernels are row-tiled reductions that accumulate a
  per-call scalar in SMEM scratch across the (sequential) TPU grid —
  where the CUDA dot deliberately ships 32768 partials to the host
  (:570-573, :779-785), the TPU grid's serial execution lets one SMEM
  cell do the whole reduction on device.

Layout contract (the "block" layout of ``ops.stencil``): operand arrays
are halo-extended, shape (bm+2, bn+2); outputs are (bm, bn). The stencil
pads internally up to Mosaic's (8, 128) DMA tiling (padding carries zero
coefficients, so padded nodes behave like the Dirichlet exterior — same
trick as ``parallel.mesh.padded_dims``); the elementwise/reduction
kernels want a row count with a power-of-two factor to tile well (see
``_row_tile``).

Measured on v5e (800×1200 / 2400×3200 full solves): the XLA-fused path
stays ahead of the Pallas stencil (0.072 s vs 0.078 s / 1.20 s vs 1.82 s)
because XLA fuses the stencil into the surrounding vector ops and its
slice windows need no alignment padding — so ``stencil="xla"`` remains
the solver default and these kernels are the explicitly-tiled alternative
(and the reference-kernel parity surface).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from poisson_ellipse_tpu.parallel.compat import shape_dtype_struct

# Rows of output computed per grid step. 128 keeps the three (TM+2)-row
# f32 input windows + one TM-row output tile a few MB — comfortably in
# the ~16 MB VMEM with room for Mosaic's own buffers.
TILE_ROWS = 128


# VMEM working-set budget for one kernel invocation's live blocks. The
# hardware has ~16 MB; leave headroom for Mosaic's own pipeline buffers.
_VMEM_BUDGET_BYTES = 8 * 1024 * 1024


def _row_tile(g1: int, g2: int, itemsize: int, n_buffers: int) -> int:
    """Largest 8-multiple row tile dividing g1 whose n_buffers blocks
    (double-buffered by the pipeline) fit the VMEM budget.

    The elementwise/reduction kernels use plain BlockSpec pipelining, so
    the tile must divide the row count exactly; callers pad rows to an
    8-multiple first (``_pad_rows``), which guarantees a divisor exists.
    Bounding by bytes (not a fixed row cap) keeps wide benchmark grids
    like 3201-column 2400x3200 compilable.
    """
    row_bytes = g2 * itemsize * n_buffers * 2  # ×2: pipeline double buffer
    cap = max(_VMEM_BUDGET_BYTES // max(row_bytes, 1), 8)
    best = 8
    for tm in range(8, min(cap, g1) + 1, 8):
        if g1 % tm == 0:
            best = tm
    return best if g1 % 8 == 0 else g1


def _pad_rows(*arrays):
    """Zero-pad each (g1, g2) array to an 8-multiple row count.

    Node grids are (M+1, N+1) — an odd row count for every even-M
    benchmark size — and a whole-array VMEM block would overflow on big
    grids, so the elementwise kernels tile over an 8-aligned padding
    instead (padding rows are zeros: harmless to the reductions, sliced
    off the outputs).
    """
    g1 = arrays[0].shape[0]
    k = round_up(g1, 8)
    if k == g1:
        return arrays
    return tuple(jnp.pad(x, ((0, k - g1), (0, 0))) for x in arrays)


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _stencil_kernel(h1, h2, tm, bn, w_hbm, a_hbm, b_hbm, out_ref, w_s, a_s, b_s, sems):
    """One TM-row tile of the 5-point variable-coefficient stencil."""
    r0 = pl.program_id(0) * tm
    copies = [
        pltpu.make_async_copy(src.at[pl.ds(r0, tm + 8), :], dst, sems.at[i])
        for i, (src, dst) in enumerate(
            [(w_hbm, w_s), (a_hbm, a_s), (b_hbm, b_s)]
        )
    ]
    for c in copies:
        c.start()
    for c in copies:
        c.wait()

    # expression tree mirrors ops.stencil.apply_a_block term for term so
    # the two paths agree to the ulp (iteration-count parity)
    wc = w_s[1 : tm + 1, 1 : bn + 1]
    ax = -(
        a_s[2 : tm + 2, 1 : bn + 1] * (w_s[2 : tm + 2, 1 : bn + 1] - wc) / h1
        - a_s[1 : tm + 1, 1 : bn + 1] * (wc - w_s[0:tm, 1 : bn + 1]) / h1
    ) / h1
    ay = -(
        b_s[1 : tm + 1, 2 : bn + 2] * (w_s[1 : tm + 1, 2 : bn + 2] - wc) / h2
        - b_s[1 : tm + 1, 1 : bn + 1] * (wc - w_s[1 : tm + 1, 0:bn]) / h2
    ) / h2
    out_ref[:] = ax + ay


def apply_a_block_pallas(w_ext, a_ext, b_ext, h1, h2, interpret=None,
                         vma=None):
    """A·w over a halo-extended block: (bm+2, bn+2) inputs → (bm, bn).

    Pallas twin of ``ops.stencil.apply_a_block`` (bit-compatible FP form:
    each difference divided by h before combining, as the reference does).

    ``vma``: mesh axis names the output varies over — required when the
    kernel runs per-shard inside ``jax.shard_map`` (whose vma checking
    needs every pallas_call out_shape annotated).

    Each TM-row output tile DMAs an aligned (TM+8)-row input window —
    Mosaic requires HBM slice offsets/sizes 8-row-aligned, so a bare
    (TM+2)-row halo window is not expressible. Inputs are therefore
    zero-padded up to ``round_up(bm, TM) + 8`` rows first; the pads of the
    loop-invariant coefficient arrays are hoisted out of solver loops by
    XLA's LICM, leaving ~one extra elementwise pass (over w) per call.
    """
    if interpret is None:
        interpret = _interpret_default()
    bm = w_ext.shape[0] - 2
    bn = w_ext.shape[1] - 2
    # balance the row tile across ceil(bm/TILE_ROWS) tiles (8-aligned) so
    # at most 7 garbage pad rows are computed per call, instead of up to
    # tm-1 with a fixed tile (bm=799 would waste 97 rows every iteration)
    n_tiles = -(-bm // TILE_ROWS)
    tm = round_up(-(-bm // n_tiles), 8)
    k = round_up(bm, tm)
    # Mosaic DMA slices must be (8, 128)-tile-aligned in both dims: pad
    # rows to k+8 (each tile DMAs an aligned (tm+8)-row window) and cols
    # to a lane multiple
    cols = round_up(bn + 2, 128)
    pad = ((0, k + 8 - (bm + 2)), (0, cols - (bn + 2)))
    w_p = jnp.pad(w_ext, pad)
    a_p = jnp.pad(a_ext, pad)
    b_p = jnp.pad(b_ext, pad)
    dtype = w_ext.dtype
    # grid spacings are compile-time constants of the problem; baking them
    # in as Python floats keeps them out of SMEM entirely
    kernel = functools.partial(_stencil_kernel, float(h1), float(h2), tm, bn)
    out = pl.pallas_call(
        kernel,
        grid=(k // tm,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        out_specs=pl.BlockSpec(
            (tm, bn), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=shape_dtype_struct((k, bn), dtype, vma=vma),
        scratch_shapes=[
            pltpu.VMEM((tm + 8, cols), dtype),
            pltpu.VMEM((tm + 8, cols), dtype),
            pltpu.VMEM((tm + 8, cols), dtype),
            pltpu.SemaphoreType.DMA((3,)),
        ],
        interpret=interpret,
    )(w_p, a_p, b_p)
    return out[:bm]


def apply_a_pallas(w, a, b, h1, h2, interpret=None):
    """A·w on the full node grid (Pallas twin of ``ops.stencil.apply_a``):
    interior written, boundary ring stays zero."""
    return jnp.pad(
        apply_a_block_pallas(w, a, b, h1, h2, interpret=interpret), 1
    )


def _stencil_dots_kernel(h1, h2, tm, bn, n_pairs, n_tiles, *refs):
    """One TM-row tile of the fused stencil + dot-partials pass.

    Layout of ``refs`` (the pallas_call flattens them positionally):
      inputs   w_hbm, a_hbm, b_hbm (ANY/HBM, DMA'd in aligned windows),
               then 2·n_pairs VMEM-blocked dot operands x₀ y₀ x₁ y₁ …
      outputs  out_ref (the stencil tile), sums_out (SMEM, (n_pairs,))
      scratch  w_s, a_s, b_s window buffers, DMA semaphores, SMEM acc
    """
    w_hbm, a_hbm, b_hbm = refs[0:3]
    pair_refs = refs[3 : 3 + 2 * n_pairs]
    out_ref, sums_out = refs[3 + 2 * n_pairs : 5 + 2 * n_pairs]
    w_s, a_s, b_s, sems, acc = refs[5 + 2 * n_pairs :]

    i = pl.program_id(0)
    r0 = i * tm
    copies = [
        pltpu.make_async_copy(src.at[pl.ds(r0, tm + 8), :], dst, sems.at[k])
        for k, (src, dst) in enumerate(
            [(w_hbm, w_s), (a_hbm, a_s), (b_hbm, b_s)]
        )
    ]
    for c in copies:
        c.start()
    for c in copies:
        c.wait()

    # expression tree mirrors ops.stencil.apply_a_block term for term
    # (each difference divided by h before combining) — ulp-compatible
    # with the XLA stencil, same as _stencil_kernel
    wc = w_s[1 : tm + 1, 1 : bn + 1]
    ax = -(
        a_s[2 : tm + 2, 1 : bn + 1] * (w_s[2 : tm + 2, 1 : bn + 1] - wc) / h1
        - a_s[1 : tm + 1, 1 : bn + 1] * (wc - w_s[0:tm, 1 : bn + 1]) / h1
    ) / h1
    ay = -(
        b_s[1 : tm + 1, 2 : bn + 2] * (w_s[1 : tm + 1, 2 : bn + 2] - wc) / h2
        - b_s[1 : tm + 1, 1 : bn + 1] * (wc - w_s[1 : tm + 1, 0:bn]) / h2
    ) / h2
    out_ref[:] = ax + ay

    @pl.when(i == 0)
    def _():
        for j in range(n_pairs):
            acc[j] = jnp.zeros((), wc.dtype)

    for j in range(n_pairs):
        acc[j] += jnp.sum(pair_refs[2 * j][:] * pair_refs[2 * j + 1][:])

    @pl.when(i == n_tiles - 1)
    def _():
        for j in range(n_pairs):
            sums_out[j] = acc[j]


def apply_a_block_dots_pallas(w_ext, a_ext, b_ext, h1, h2, pairs,
                              interpret=None, vma=None):
    """A·w over a halo-extended block PLUS k dot partials, one VMEM pass.

    ``pairs`` is a sequence of (x, y) arrays shaped like the (bm, bn)
    output; returns ``(Aw_block, sums)`` with ``sums[j] = Σ xⱼ·yⱼ`` (raw,
    unweighted — the ``ops.reduction.grid_dots`` contract). The point is
    HBM economy for the pipelined iteration: the classical structure
    reads each dot operand once for the stencil pass and again for the
    reduction pass, whereas here every operand streams through VMEM
    exactly once while the stencil tile is in flight — and on a mesh the
    (k,) partials vector is exactly what rides the iteration's single
    stacked ``lax.psum`` (``parallel.pipelined_sharded``).

    Tiling/alignment contract is ``apply_a_block_pallas``'s: stencil
    inputs stay in ANY/HBM and are DMA'd in aligned (TM+8)-row windows;
    the dot operands ride ordinary double-buffered BlockSpec pipelining.
    The TPU grid runs tiles sequentially, so SMEM accumulators finish the
    reductions on device (``_dot_kernel``'s structure, widened to k).
    """
    if interpret is None:
        interpret = _interpret_default()
    pairs = tuple(pairs)
    n_pairs = len(pairs)
    if n_pairs == 0:
        raise ValueError("need at least one (x, y) dot pair")
    bm = w_ext.shape[0] - 2
    bn = w_ext.shape[1] - 2
    n_tiles = -(-bm // TILE_ROWS)
    tm = round_up(-(-bm // n_tiles), 8)
    k = round_up(bm, tm)
    cols = round_up(bn + 2, 128)
    pad = ((0, k + 8 - (bm + 2)), (0, cols - (bn + 2)))
    w_p = jnp.pad(w_ext, pad)
    a_p = jnp.pad(a_ext, pad)
    b_p = jnp.pad(b_ext, pad)
    # zero row padding: contributes nothing to the dot partials
    flat = []
    for x, y in pairs:
        flat += [jnp.pad(x, ((0, k - bm), (0, 0))), jnp.pad(y, ((0, k - bm), (0, 0)))]
    dtype = w_ext.dtype
    blk = lambda: pl.BlockSpec(
        (tm, bn), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    kernel = functools.partial(
        _stencil_dots_kernel, float(h1), float(h2), tm, bn, n_pairs,
        k // tm,
    )
    out, sums = pl.pallas_call(
        kernel,
        grid=(k // tm,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3
        + [blk() for _ in range(2 * n_pairs)],
        out_specs=(
            pl.BlockSpec((tm, bn), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ),
        out_shape=(
            shape_dtype_struct((k, bn), dtype, vma=vma),
            shape_dtype_struct((n_pairs,), dtype, vma=vma),
        ),
        scratch_shapes=[
            pltpu.VMEM((tm + 8, cols), dtype),
            pltpu.VMEM((tm + 8, cols), dtype),
            pltpu.VMEM((tm + 8, cols), dtype),
            pltpu.SemaphoreType.DMA((3,)),
            pltpu.SMEM((n_pairs,), dtype),
        ],
        interpret=interpret,
    )(w_p, a_p, b_p, *flat)
    return out[:bm], sums


def apply_a_dots_pallas(w, a, b, h1, h2, pairs, interpret=None):
    """Full-node-grid twin of ``apply_a_block_dots_pallas``: (M+1, N+1)
    inputs, stencil written on the interior with a zero boundary ring,
    dot pairs over the full node grid (iterates are zero on the ring, so
    full-grid sums equal interior sums — the ``ops.reduction`` layout
    invariant)."""
    # dot operands enter the kernel cropped to the stencil's (bm, bn)
    # interior tile shape; the ring they lose is exactly zero
    cropped = tuple((x[1:-1, 1:-1], y[1:-1, 1:-1]) for x, y in pairs)
    out, sums = apply_a_block_dots_pallas(
        w, a, b, h1, h2, cropped, interpret=interpret
    )
    return jnp.pad(out, 1), sums


def _batched_stencil_kernel(h1, h2, tm, bn, w_hbm, a_hbm, b_hbm, out_ref,
                            w_s, a_s, b_s, sems):
    """One (lane, TM-row) tile of the batched 5-point stencil.

    The lane dimension rides the FIRST grid axis: grid=(B, n_tiles), so
    each program DMAs its lane's aligned (TM+8)-row window of ``w`` and
    the lane-shared coefficient windows. Coefficient windows depend only
    on the row tile, so their DMA re-fetches per lane are VMEM-friendly
    re-reads of the same HBM lines (the shared-geometry serving layout).
    """
    lane = pl.program_id(0)
    r0 = pl.program_id(1) * tm
    copies = [
        pltpu.make_async_copy(
            w_hbm.at[lane, pl.ds(r0, tm + 8), :], w_s, sems.at[0]
        ),
        pltpu.make_async_copy(a_hbm.at[pl.ds(r0, tm + 8), :], a_s, sems.at[1]),
        pltpu.make_async_copy(b_hbm.at[pl.ds(r0, tm + 8), :], b_s, sems.at[2]),
    ]
    for c in copies:
        c.start()
    for c in copies:
        c.wait()

    # expression tree mirrors ops.stencil.apply_a_block term for term
    wc = w_s[1 : tm + 1, 1 : bn + 1]
    ax = -(
        a_s[2 : tm + 2, 1 : bn + 1] * (w_s[2 : tm + 2, 1 : bn + 1] - wc) / h1
        - a_s[1 : tm + 1, 1 : bn + 1] * (wc - w_s[0:tm, 1 : bn + 1]) / h1
    ) / h1
    ay = -(
        b_s[1 : tm + 1, 2 : bn + 2] * (w_s[1 : tm + 1, 2 : bn + 2] - wc) / h2
        - b_s[1 : tm + 1, 1 : bn + 1] * (wc - w_s[1 : tm + 1, 0:bn]) / h2
    ) / h2
    out_ref[0] = ax + ay


def _batched_tiling(w):
    """(tm, k, cols, pads) for a (B, bm+2, bn+2) batched operand — the
    ``apply_a_block_pallas`` alignment contract per lane."""
    bm = w.shape[1] - 2
    bn = w.shape[2] - 2
    n_tiles = -(-bm // TILE_ROWS)
    tm = round_up(-(-bm // n_tiles), 8)
    k = round_up(bm, tm)
    cols = round_up(bn + 2, 128)
    return bm, bn, tm, k, cols


def apply_a_batched_block_pallas(w, a_ext, b_ext, h1, h2, interpret=None):
    """A·w per lane over halo-extended blocks: (B, bm+2, bn+2) iterate,
    (bm+2, bn+2) lane-shared coefficients → (B, bm, bn).

    The batched twin of ``apply_a_block_pallas`` with the lane dimension
    mapped onto the Pallas grid — grid=(B, row_tiles) — so one kernel
    launch covers the whole batch instead of B launches (per-launch
    overhead is exactly what lane batching amortises).
    """
    if interpret is None:
        interpret = _interpret_default()
    B = w.shape[0]
    bm, bn, tm, k, cols = _batched_tiling(w)
    pad2 = ((0, k + 8 - (bm + 2)), (0, cols - (bn + 2)))
    w_p = jnp.pad(w, ((0, 0),) + pad2)
    a_p = jnp.pad(a_ext, pad2)
    b_p = jnp.pad(b_ext, pad2)
    dtype = w.dtype
    kernel = functools.partial(
        _batched_stencil_kernel, float(h1), float(h2), tm, bn
    )
    out = pl.pallas_call(
        kernel,
        grid=(B, k // tm),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        out_specs=pl.BlockSpec(
            (1, tm, bn), lambda l, i: (l, i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((B, k, bn), dtype),
        scratch_shapes=[
            pltpu.VMEM((tm + 8, cols), dtype),
            pltpu.VMEM((tm + 8, cols), dtype),
            pltpu.VMEM((tm + 8, cols), dtype),
            pltpu.SemaphoreType.DMA((3,)),
        ],
        interpret=interpret,
    )(w_p, a_p, b_p)
    return out[:, :bm]


def apply_a_batched_pallas(w, a, b, h1, h2, interpret=None):
    """A·w per lane on full (B, M+1, N+1) node grids (zero boundary
    ring), lane-shared (M+1, N+1) coefficients — the batched twin of
    ``apply_a_pallas``."""
    return jnp.pad(
        apply_a_batched_block_pallas(w, a, b, h1, h2, interpret=interpret),
        ((0, 0), (1, 1), (1, 1)),
    )


def _batched_stencil_dots_kernel(h1, h2, tm, bn, n_pairs, n_tiles, *refs):
    """One (lane, TM-row) tile of the fused batched stencil + per-lane
    dot partials. Ref layout follows ``_stencil_dots_kernel`` with the
    lane on grid axis 0 and a per-lane column in the (n_pairs, B) SMEM
    sums output; the TPU grid's sequential execution walks lane-major,
    so the accumulator finishes lane l before lane l+1 begins.
    """
    w_hbm, a_hbm, b_hbm = refs[0:3]
    pair_refs = refs[3 : 3 + 2 * n_pairs]
    out_ref, sums_out = refs[3 + 2 * n_pairs : 5 + 2 * n_pairs]
    w_s, a_s, b_s, sems, acc = refs[5 + 2 * n_pairs :]

    lane = pl.program_id(0)
    i = pl.program_id(1)
    r0 = i * tm
    copies = [
        pltpu.make_async_copy(
            w_hbm.at[lane, pl.ds(r0, tm + 8), :], w_s, sems.at[0]
        ),
        pltpu.make_async_copy(a_hbm.at[pl.ds(r0, tm + 8), :], a_s, sems.at[1]),
        pltpu.make_async_copy(b_hbm.at[pl.ds(r0, tm + 8), :], b_s, sems.at[2]),
    ]
    for c in copies:
        c.start()
    for c in copies:
        c.wait()

    wc = w_s[1 : tm + 1, 1 : bn + 1]
    ax = -(
        a_s[2 : tm + 2, 1 : bn + 1] * (w_s[2 : tm + 2, 1 : bn + 1] - wc) / h1
        - a_s[1 : tm + 1, 1 : bn + 1] * (wc - w_s[0:tm, 1 : bn + 1]) / h1
    ) / h1
    ay = -(
        b_s[1 : tm + 1, 2 : bn + 2] * (w_s[1 : tm + 1, 2 : bn + 2] - wc) / h2
        - b_s[1 : tm + 1, 1 : bn + 1] * (wc - w_s[1 : tm + 1, 0:bn]) / h2
    ) / h2
    out_ref[0] = ax + ay

    @pl.when(i == 0)
    def _():
        for j in range(n_pairs):
            acc[j] = jnp.zeros((), wc.dtype)

    for j in range(n_pairs):
        acc[j] += jnp.sum(pair_refs[2 * j][0] * pair_refs[2 * j + 1][0])

    @pl.when(i == n_tiles - 1)
    def _():
        for j in range(n_pairs):
            sums_out[j, lane] = acc[j]


def apply_a_dots_batched_pallas(w, a, b, h1, h2, pairs, interpret=None):
    """Per-lane A·w PLUS per-lane dot partials, one fused VMEM pass.

    ``w`` is (B, M+1, N+1); ``a``/``b`` lane-shared (M+1, N+1);
    ``pairs`` a sequence of ((B, M+1, N+1), (B, M+1, N+1)) operand
    pairs. Returns ``(Aw, sums)`` with ``Aw`` (B, M+1, N+1) (zero ring)
    and ``sums`` (n_pairs, B) raw per-lane Σ xⱼ·yⱼ — exactly the
    stacked (k, B) bundle of ``batch.batched_pcg.lane_dots``, produced
    while each lane's stencil tile is in flight. The batched pipelined
    engine's whole (8, B) bundle rides this single kernel launch.
    """
    if interpret is None:
        interpret = _interpret_default()
    pairs = tuple(pairs)
    n_pairs = len(pairs)
    if n_pairs == 0:
        raise ValueError("need at least one (x, y) dot pair")
    B = w.shape[0]
    bm, bn, tm, k, cols = _batched_tiling(w)
    pad2 = ((0, k + 8 - (bm + 2)), (0, cols - (bn + 2)))
    w_p = jnp.pad(w, ((0, 0),) + pad2)
    a_p = jnp.pad(a, pad2)
    b_p = jnp.pad(b, pad2)
    # dot operands enter cropped to the (bm, bn) interior tile shape and
    # zero-row-padded to the tile multiple (zero rows add nothing)
    flat = []
    for x, y in pairs:
        flat += [
            jnp.pad(x[:, 1:-1, 1:-1], ((0, 0), (0, k - bm), (0, 0))),
            jnp.pad(y[:, 1:-1, 1:-1], ((0, 0), (0, k - bm), (0, 0))),
        ]
    dtype = w.dtype
    blk = lambda: pl.BlockSpec(
        (1, tm, bn), lambda l, i: (l, i, 0), memory_space=pltpu.VMEM
    )
    kernel = functools.partial(
        _batched_stencil_dots_kernel, float(h1), float(h2), tm, bn,
        n_pairs, k // tm,
    )
    out, sums = pl.pallas_call(
        kernel,
        grid=(B, k // tm),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3
        + [blk() for _ in range(2 * n_pairs)],
        out_specs=(
            pl.BlockSpec(
                (1, tm, bn), lambda l, i: (l, i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B, k, bn), dtype),
            jax.ShapeDtypeStruct((n_pairs, B), dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((tm + 8, cols), dtype),
            pltpu.VMEM((tm + 8, cols), dtype),
            pltpu.VMEM((tm + 8, cols), dtype),
            pltpu.SemaphoreType.DMA((3,)),
            pltpu.SMEM((n_pairs,), dtype),
        ],
        interpret=interpret,
    )(w_p, a_p, b_p, *flat)
    return jnp.pad(out[:, :bm], ((0, 0), (1, 1), (1, 1))), sums


def _dinv_kernel(r_ref, d_ref, out_ref):
    d = d_ref[:]
    safe = jnp.where(d != 0.0, d, 1.0)
    out_ref[:] = jnp.where(d != 0.0, r_ref[:] / safe, 0.0)


def apply_dinv_pallas(r, d, interpret=None):
    """z = r / D with zero guard (``apply_Dinv_kernel``, cu:541-562)."""
    if interpret is None:
        interpret = _interpret_default()
    g1, g2 = r.shape
    r_p, d_p = _pad_rows(r, d)
    k = r_p.shape[0]
    tm = _row_tile(k, g2, r.dtype.itemsize, 3)
    out = pl.pallas_call(
        _dinv_kernel,
        grid=(k // tm,),
        in_specs=[
            pl.BlockSpec((tm, g2), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, g2), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (tm, g2), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((k, g2), r.dtype),
        interpret=interpret,
    )(r_p, d_p)
    return out[:g1]


def _dot_kernel(x_ref, y_ref, out_ref, acc):
    @pl.when(pl.program_id(0) == 0)
    def _():
        acc[0] = jnp.zeros((), x_ref.dtype)

    acc[0] += jnp.sum(x_ref[:] * y_ref[:])

    @pl.when(pl.program_id(0) == pl.num_programs(0) - 1)
    def _():
        out_ref[0] = acc[0]


def dot_pallas(x, y, h1, h2, interpret=None):
    """Grid-weighted inner product h1·h2·Σxy (``dot_kernel``, cu:574-598).

    The TPU grid runs tiles sequentially, so one SMEM accumulator
    replaces the reference's 32768 host-summed partials (cu:779-785).
    """
    if interpret is None:
        interpret = _interpret_default()
    g2 = x.shape[1]
    x_p, y_p = _pad_rows(x, y)  # zero rows contribute nothing to the sum
    k = x_p.shape[0]
    tm = _row_tile(k, g2, x.dtype.itemsize, 2)
    s = pl.pallas_call(
        _dot_kernel,
        grid=(k // tm,),
        in_specs=[
            pl.BlockSpec((tm, g2), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, g2), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((1,), x.dtype),
        scratch_shapes=[pltpu.SMEM((1,), x.dtype)],
        interpret=interpret,
    )(x_p, y_p)
    return s[0] * jnp.asarray(h1, x.dtype) * jnp.asarray(h2, x.dtype)


def _update_wr_kernel(alpha_ref, w_ref, r_ref, p_ref, ap_ref,
                      w_out, r_out, dw2_out, acc):
    @pl.when(pl.program_id(0) == 0)
    def _():
        acc[0] = jnp.zeros((), w_ref.dtype)

    alpha = alpha_ref[0]
    w_old = w_ref[:]
    w_new = w_old + alpha * p_ref[:]
    w_out[:] = w_new
    r_out[:] = r_ref[:] - alpha * ap_ref[:]
    # realised increment (w_new - w_old), not alpha*p: the two differ in
    # FP and the convergence oracle counts depend on it (cu:626-660 also
    # differences the stored iterates)
    dw = w_new - w_old
    acc[0] += jnp.sum(dw * dw)

    @pl.when(pl.program_id(0) == pl.num_programs(0) - 1)
    def _():
        dw2_out[0] = acc[0]


def update_w_r_pallas(alpha, w, r, p, ap, interpret=None):
    """Fused w += αp, r −= αAp, Σ(Δw)² (``update_w_r_kernel``, cu:626-660).

    Returns (w_new, r_new, dw2). The ‖Δw‖² partial is computed from the
    realised increment exactly as the reference kernel does.
    """
    if interpret is None:
        interpret = _interpret_default()
    g1, g2 = w.shape
    w_p, r_p, p_p, ap_p = _pad_rows(w, r, p, ap)
    k = w_p.shape[0]
    tm = _row_tile(k, g2, w.dtype.itemsize, 6)
    blk = lambda: pl.BlockSpec(
        (tm, g2), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    w_new, r_new, dw2 = pl.pallas_call(
        _update_wr_kernel,
        grid=(k // tm,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            blk(),
            blk(),
            blk(),
            blk(),
        ],
        out_specs=(blk(), blk(), pl.BlockSpec(memory_space=pltpu.SMEM)),
        out_shape=(
            jax.ShapeDtypeStruct((k, g2), w.dtype),
            jax.ShapeDtypeStruct((k, g2), w.dtype),
            jax.ShapeDtypeStruct((1,), w.dtype),
        ),
        scratch_shapes=[pltpu.SMEM((1,), w.dtype)],
        interpret=interpret,
    )(jnp.reshape(alpha, (1,)), w_p, r_p, p_p, ap_p)
    return w_new[:g1], r_new[:g1], dw2[0]


def _update_p_kernel(beta_ref, z_ref, p_ref, out_ref):
    out_ref[:] = z_ref[:] + beta_ref[0] * p_ref[:]


def update_p_pallas(beta, z, p, interpret=None):
    """p = z + βp (``update_p_kernel``, cu:663-676)."""
    if interpret is None:
        interpret = _interpret_default()
    g1, g2 = p.shape
    z_p, p_p = _pad_rows(z, p)
    k = z_p.shape[0]
    tm = _row_tile(k, g2, p.dtype.itemsize, 3)
    blk = lambda: pl.BlockSpec(
        (tm, g2), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    return pl.pallas_call(
        _update_p_kernel,
        grid=(k // tm,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), blk(), blk()],
        out_specs=blk(),
        out_shape=jax.ShapeDtypeStruct((k, g2), p.dtype),
        interpret=interpret,
    )(jnp.reshape(beta, (1,)), z_p, p_p)[:g1]


# --------------------------------------------------------------------------
# mixed-precision kernels: storage-width HBM tiles, compute-width VMEM math
# --------------------------------------------------------------------------
#
# The bf16-storage axis (``ops.precision``): arrays live at storage width
# in HBM — halving the stencil's dominant byte stream — and every tile is
# upcast to the compute dtype *after* the DMA, inside VMEM, so the
# arithmetic (and the SMEM dot accumulators) run at full precision. These
# are the explicitly-tiled twins of what the XLA path gets from fusing a
# ``convert_element_type`` into the consumer; the FP expression tree is
# the same term-for-term stencil as ``_stencil_kernel``, evaluated at
# compute width on upcast operands.


def _stencil_kernel_mixed(h1, h2, tm, bn, compute, w_hbm, a_hbm, b_hbm,
                          out_ref, w_s, a_s, b_s, sems):
    """One TM-row stencil tile: storage-width windows, compute-width math."""
    r0 = pl.program_id(0) * tm
    copies = [
        pltpu.make_async_copy(src.at[pl.ds(r0, tm + 8), :], dst, sems.at[i])
        for i, (src, dst) in enumerate(
            [(w_hbm, w_s), (a_hbm, a_s), (b_hbm, b_s)]
        )
    ]
    for c in copies:
        c.start()
    for c in copies:
        c.wait()

    # the tile-local upcast: the DMA moved storage-width bytes; the VPU
    # sees compute-width operands from here on
    w_c = w_s[:].astype(compute)
    a_c = a_s[:].astype(compute)
    b_c = b_s[:].astype(compute)
    wc = w_c[1 : tm + 1, 1 : bn + 1]
    ax = -(
        a_c[2 : tm + 2, 1 : bn + 1] * (w_c[2 : tm + 2, 1 : bn + 1] - wc) / h1
        - a_c[1 : tm + 1, 1 : bn + 1] * (wc - w_c[0:tm, 1 : bn + 1]) / h1
    ) / h1
    ay = -(
        b_c[1 : tm + 1, 2 : bn + 2] * (w_c[1 : tm + 1, 2 : bn + 2] - wc) / h2
        - b_c[1 : tm + 1, 1 : bn + 1] * (wc - w_c[1 : tm + 1, 0:bn]) / h2
    ) / h2
    out_ref[:] = (ax + ay).astype(out_ref.dtype)


def apply_a_block_mixed_pallas(w_ext, a_ext, b_ext, h1, h2,
                               compute_dtype=jnp.float32, out_dtype=None,
                               interpret=None, vma=None):
    """Mixed-precision A·w over a halo-extended block.

    Inputs may each carry their own (storage) dtype — bf16 state with
    bf16-rounded coefficients is the intended pairing — and are upcast
    tile-locally to ``compute_dtype`` in VMEM; the output is written at
    ``out_dtype`` (default: ``compute_dtype``, so downstream reductions
    see full-width values). Alignment/tiling contract is
    ``apply_a_block_pallas``'s.
    """
    if interpret is None:
        interpret = _interpret_default()
    out_dtype = jnp.dtype(out_dtype or compute_dtype)
    bm = w_ext.shape[0] - 2
    bn = w_ext.shape[1] - 2
    n_tiles = -(-bm // TILE_ROWS)
    tm = round_up(-(-bm // n_tiles), 8)
    k = round_up(bm, tm)
    cols = round_up(bn + 2, 128)
    pad = ((0, k + 8 - (bm + 2)), (0, cols - (bn + 2)))
    w_p = jnp.pad(w_ext, pad)
    a_p = jnp.pad(a_ext, pad)
    b_p = jnp.pad(b_ext, pad)
    kernel = functools.partial(
        _stencil_kernel_mixed, float(h1), float(h2), tm, bn,
        jnp.dtype(compute_dtype),
    )
    out = pl.pallas_call(
        kernel,
        grid=(k // tm,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        out_specs=pl.BlockSpec(
            (tm, bn), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=shape_dtype_struct((k, bn), out_dtype, vma=vma),
        scratch_shapes=[
            pltpu.VMEM((tm + 8, cols), w_p.dtype),
            pltpu.VMEM((tm + 8, cols), a_p.dtype),
            pltpu.VMEM((tm + 8, cols), b_p.dtype),
            pltpu.SemaphoreType.DMA((3,)),
        ],
        interpret=interpret,
    )(w_p, a_p, b_p)
    return out[:bm]


def apply_a_mixed_pallas(w, a, b, h1, h2, compute_dtype=jnp.float32,
                         out_dtype=None, interpret=None):
    """Full-node-grid mixed stencil: storage-width (M+1, N+1) inputs,
    compute-width interior output with a zero boundary ring."""
    return jnp.pad(
        apply_a_block_mixed_pallas(
            w, a, b, h1, h2, compute_dtype=compute_dtype,
            out_dtype=out_dtype, interpret=interpret,
        ),
        1,
    )


def _stencil_dots_kernel_mixed(h1, h2, tm, bn, n_pairs, n_tiles, compute,
                               *refs):
    """Mixed twin of ``_stencil_dots_kernel``: storage-width operands,
    compute-width stencil arithmetic AND dot accumulation (the SMEM
    accumulator is compute-width — the f32 accumulator route TPU018
    lints for)."""
    w_hbm, a_hbm, b_hbm = refs[0:3]
    pair_refs = refs[3 : 3 + 2 * n_pairs]
    out_ref, sums_out = refs[3 + 2 * n_pairs : 5 + 2 * n_pairs]
    w_s, a_s, b_s, sems, acc = refs[5 + 2 * n_pairs :]

    i = pl.program_id(0)
    r0 = i * tm
    copies = [
        pltpu.make_async_copy(src.at[pl.ds(r0, tm + 8), :], dst, sems.at[k])
        for k, (src, dst) in enumerate(
            [(w_hbm, w_s), (a_hbm, a_s), (b_hbm, b_s)]
        )
    ]
    for c in copies:
        c.start()
    for c in copies:
        c.wait()

    w_c = w_s[:].astype(compute)
    a_c = a_s[:].astype(compute)
    b_c = b_s[:].astype(compute)
    wc = w_c[1 : tm + 1, 1 : bn + 1]
    ax = -(
        a_c[2 : tm + 2, 1 : bn + 1] * (w_c[2 : tm + 2, 1 : bn + 1] - wc) / h1
        - a_c[1 : tm + 1, 1 : bn + 1] * (wc - w_c[0:tm, 1 : bn + 1]) / h1
    ) / h1
    ay = -(
        b_c[1 : tm + 1, 2 : bn + 2] * (w_c[1 : tm + 1, 2 : bn + 2] - wc) / h2
        - b_c[1 : tm + 1, 1 : bn + 1] * (wc - w_c[1 : tm + 1, 0:bn]) / h2
    ) / h2
    out_ref[:] = (ax + ay).astype(out_ref.dtype)

    @pl.when(i == 0)
    def _():
        for j in range(n_pairs):
            acc[j] = jnp.zeros((), compute)

    for j in range(n_pairs):
        acc[j] += jnp.sum(
            pair_refs[2 * j][:].astype(compute)
            * pair_refs[2 * j + 1][:].astype(compute)
        )

    @pl.when(i == n_tiles - 1)
    def _():
        for j in range(n_pairs):
            sums_out[j] = acc[j]


def apply_a_block_dots_mixed_pallas(w_ext, a_ext, b_ext, h1, h2, pairs,
                                    compute_dtype=jnp.float32,
                                    interpret=None, vma=None):
    """Mixed fused stencil + dot-partials pass over a halo-extended block.

    The storage-axis twin of ``apply_a_block_dots_pallas``: every operand
    (stencil inputs AND the 2·n_pairs dot operands) may stream at its own
    storage width and is upcast tile-locally; the stencil output and the
    (n_pairs,) partial sums come back at ``compute_dtype`` — reductions
    never accumulate at storage width (the TPU018 contract).
    """
    if interpret is None:
        interpret = _interpret_default()
    pairs = tuple(pairs)
    n_pairs = len(pairs)
    if n_pairs == 0:
        raise ValueError("need at least one (x, y) dot pair")
    compute = jnp.dtype(compute_dtype)
    bm = w_ext.shape[0] - 2
    bn = w_ext.shape[1] - 2
    n_tiles = -(-bm // TILE_ROWS)
    tm = round_up(-(-bm // n_tiles), 8)
    k = round_up(bm, tm)
    cols = round_up(bn + 2, 128)
    pad = ((0, k + 8 - (bm + 2)), (0, cols - (bn + 2)))
    w_p = jnp.pad(w_ext, pad)
    a_p = jnp.pad(a_ext, pad)
    b_p = jnp.pad(b_ext, pad)
    flat = []
    for x, y in pairs:
        flat += [jnp.pad(x, ((0, k - bm), (0, 0))),
                 jnp.pad(y, ((0, k - bm), (0, 0)))]
    blk = lambda: pl.BlockSpec(
        (tm, bn), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    kernel = functools.partial(
        _stencil_dots_kernel_mixed, float(h1), float(h2), tm, bn, n_pairs,
        k // tm, compute,
    )
    out, sums = pl.pallas_call(
        kernel,
        grid=(k // tm,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3
        + [blk() for _ in range(2 * n_pairs)],
        out_specs=(
            pl.BlockSpec((tm, bn), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ),
        out_shape=(
            shape_dtype_struct((k, bn), compute, vma=vma),
            shape_dtype_struct((n_pairs,), compute, vma=vma),
        ),
        scratch_shapes=[
            pltpu.VMEM((tm + 8, cols), w_p.dtype),
            pltpu.VMEM((tm + 8, cols), a_p.dtype),
            pltpu.VMEM((tm + 8, cols), b_p.dtype),
            pltpu.SemaphoreType.DMA((3,)),
            pltpu.SMEM((n_pairs,), compute),
        ],
        interpret=interpret,
    )(w_p, a_p, b_p, *flat)
    return out[:bm], sums


def apply_a_dots_mixed_pallas(w, a, b, h1, h2, pairs,
                              compute_dtype=jnp.float32, interpret=None):
    """Full-node-grid twin of ``apply_a_block_dots_mixed_pallas`` (ring
    cropped off the dot operands exactly as ``apply_a_dots_pallas``)."""
    cropped = tuple((x[1:-1, 1:-1], y[1:-1, 1:-1]) for x, y in pairs)
    out, sums = apply_a_block_dots_mixed_pallas(
        w, a, b, h1, h2, cropped, compute_dtype=compute_dtype,
        interpret=interpret,
    )
    return jnp.pad(out, 1), sums
