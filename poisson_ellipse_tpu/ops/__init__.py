"""Compute ops (reference layers L1 + L3): coefficient assembly, the 5-point
variable-coefficient stencil, the diagonal preconditioner, and grid-weighted
reductions — all as pure, jittable functions that XLA fuses on TPU."""

from poisson_ellipse_tpu.ops.assembly import (
    coefficients_at,
    rhs_at,
    assemble,
    assemble_numpy,
    assemble_on_device,
    numpy_dtype,
)
from poisson_ellipse_tpu.ops.stencil import (
    apply_a,
    apply_a_block,
    diag_d,
    diag_d_block,
    apply_dinv,
)
from poisson_ellipse_tpu.ops.reduction import grid_dot, grid_dots

__all__ = [
    "coefficients_at",
    "rhs_at",
    "assemble",
    "assemble_numpy",
    "assemble_on_device",
    "numpy_dtype",
    "apply_a",
    "apply_a_block",
    "diag_d",
    "diag_d_block",
    "apply_dinv",
    "grid_dot",
    "grid_dots",
]
