"""Fictitious-domain coefficient assembly (reference layer L1), vectorised.

The reference assembles per-edge diffusion coefficients a_ij (vertical faces)
and b_ij (horizontal faces) plus the indicator RHS B_ij in nested loops on
the CPU host (``stage0/Withoutopenmp1.cpp:42-61``; the distributed variant
``fictitious_regions_setup_local`` at ``stage4-mpi+cuda/poisson_mpi_cuda2.cu:146-192``
assembles each rank's block + one halo ring from *global* indices, with no
communication).

This module keeps exactly that contract, TPU-style: every function takes
arrays of **global node indices** ``gi``/``gj`` and evaluates the closed-form
geometry by broadcasting — so the same code assembles the whole grid on one
chip (``gi = 0..M``) or any device's halo-extended block inside ``shard_map``
(``gi = r0-1 .. r1``), with out-of-range indices masked to zero. No loops,
no communication, no host work.

Coefficient law (``stage0/Withoutopenmp1.cpp:53-54``; README.md:44-57):
    a_ij = 1                         face fully inside D  (|l − h2| < 1e-9)
         = 1/eps                     face fully outside   (l < 1e-9)
         = l/h2 + (1 − l/h2)/eps     cut face (length-weighted blend)
with eps = max(h1,h2)² by default, and symmetrically for b with h1.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from poisson_ellipse_tpu.models import ellipse
from poisson_ellipse_tpu.models.problem import Problem

# Tolerances from the reference's blend law (stage0/Withoutopenmp1.cpp:53-54).
_FULL_TOL = 1e-9
_EMPTY_TOL = 1e-9


def _blend(length, h, eps, xp=jnp):
    """Piecewise coefficient law for one face of length-in-D ``length``."""
    frac = length / h
    cut = frac + (1.0 - frac) / eps
    return xp.where(
        xp.abs(length - h) < _FULL_TOL,
        1.0,
        xp.where(length < _EMPTY_TOL, 1.0 / eps, cut),
    )


def _coefficients_xp(problem: Problem, x, y, xp):
    """Shared closed-form coefficient evaluation at node coordinates x × y.

    The single source of truth for the blend law applied to the segment
    closed forms; serves both the traced path (xp=jnp) and the float64
    host path (xp=numpy).
    """
    h1, h2 = problem.h1, problem.h2
    eps = problem.eps_value
    xc = x[:, None]
    yc = y[None, :]
    la = ellipse.segment_length_vertical(
        xc - 0.5 * h1, yc - 0.5 * h2, yc + 0.5 * h2, xp
    )
    lb = ellipse.segment_length_horizontal(
        yc - 0.5 * h2, xc - 0.5 * h1, xc + 0.5 * h1, xp
    )
    return _blend(la, h2, eps, xp), _blend(lb, h1, eps, xp)


def coefficients_at(problem: Problem, gi, gj, dtype=jnp.float32):
    """Assemble (a, b) at the outer product of global node indices gi × gj.

    a[i,j] lives on the vertical face x = x_i − h1/2, y ∈ [y_j − h2/2, y_j + h2/2];
    b[i,j] on the horizontal face y = y_j − h2/2, x ∈ [x_i − h1/2, x_i + h1/2]
    (``stage0/Withoutopenmp1.cpp:49-54``). Valid for 1 ≤ gi ≤ M, 1 ≤ gj ≤ N;
    indices outside that range (physical boundary ring, shard padding) yield 0,
    mirroring the zero-initialised (M+1)×(N+1) arrays of the reference.
    """
    gi = jnp.asarray(gi)
    gj = jnp.asarray(gj)
    x = problem.a1 + gi.astype(dtype) * jnp.asarray(problem.h1, dtype)
    y = problem.a2 + gj.astype(dtype) * jnp.asarray(problem.h2, dtype)
    a, b = _coefficients_xp(problem, x, y, jnp)
    valid = (
        ((gi >= 1) & (gi <= problem.M))[:, None]
        & ((gj >= 1) & (gj <= problem.N))[None, :]
    )
    zero = jnp.asarray(0.0, dtype)
    return jnp.where(valid, a, zero), jnp.where(valid, b, zero)


def rhs_at(problem: Problem, gi, gj, dtype=jnp.float32):
    """Indicator right-hand side B_ij = f_val·1[node inside D] on the interior.

    Reference: ``stage0/Withoutopenmp1.cpp:57-60`` — B is f_val at interior
    nodes (1 ≤ i ≤ M−1, 1 ≤ j ≤ N−1) strictly inside the ellipse, else 0.
    """
    gi = jnp.asarray(gi)
    gj = jnp.asarray(gj)
    x = problem.a1 + gi.astype(dtype) * jnp.asarray(problem.h1, dtype)
    y = problem.a2 + gj.astype(dtype) * jnp.asarray(problem.h2, dtype)
    inside = ellipse.is_in_d(x[:, None], y[None, :])
    interior = interior_mask(problem, gi, gj)
    return jnp.where(
        inside & interior, jnp.asarray(problem.f_val, dtype), jnp.asarray(0.0, dtype)
    )


def interior_mask(problem: Problem, gi, gj):
    """Boolean mask of interior nodes 1 ≤ gi ≤ M−1, 1 ≤ gj ≤ N−1."""
    gi = jnp.asarray(gi)
    gj = jnp.asarray(gj)
    return (
        ((gi >= 1) & (gi <= problem.M - 1))[:, None]
        & ((gj >= 1) & (gj <= problem.N - 1))[None, :]
    )


def assemble_numpy(problem: Problem, geometry=None, theta=None):
    """Full-precision host assembly in vectorised numpy float64.

    The geometry MUST be evaluated in f64 regardless of the solve dtype:
    segment lengths carry absolute rounding noise ~machine-eps of O(1)
    coordinates, and the cut-face blend amplifies any noise in l/h by
    1/eps = 1/max(h1,h2)² — in f32 that turns into O(10) errors (and even
    negative, SPD-breaking coefficients) on fine grids like 1024²+.
    Evaluating in f64 and *then* casting keeps coefficients exact to the
    target dtype's resolution. This mirrors the reference, which always
    assembles on the host in double (``poisson_mpi_cuda2.cu:146-192``).

    ``geometry=None`` (the default) is the hard-coded ellipse through
    its closed forms — BIT-identical to every pre-geometry release.
    A ``geom.sdf`` shape switches the face lengths to the adaptive
    bisection quadrature (``geom.quadrature``) and the RHS indicator to
    the SDF sign, with the degenerate-cut clamp at threshold ``theta``
    (default ``geom.quadrature.DEFAULT_THETA``; 0 disables). Every
    clamped face is REPORTED as one ``geom:degenerate-cut`` trace event
    carrying the counts — the defense is observable, never silent.

    Public API: the sharded solver pads/casts/lays these arrays out over
    the mesh. Uses the same closed forms as the traced path via
    ``_coefficients_xp(…, xp=numpy)``.
    """
    M, N = problem.M, problem.N
    gi = np.arange(M + 1, dtype=np.float64)
    gj = np.arange(N + 1, dtype=np.float64)
    x = problem.a1 + gi * problem.h1
    y = problem.a2 + gj * problem.h2
    if geometry is None:
        a, b = _coefficients_xp(problem, x, y, np)
        inside = ellipse.is_in_d(x[:, None], y[None, :])
    else:
        a, b, inside = _geometry_coefficients(problem, geometry, theta, x, y)

    valid = ((gi >= 1) & (gi <= M))[:, None] & ((gj >= 1) & (gj <= N))[None, :]
    a = np.where(valid, a, 0.0)
    b = np.where(valid, b, 0.0)

    interior = ((gi >= 1) & (gi <= M - 1))[:, None] & (
        (gj >= 1) & (gj <= N - 1)
    )[None, :]
    rhs = np.where(inside & interior, problem.f_val, 0.0)
    return a, b, rhs


# memo over the quadrature assembly, keyed (problem, geometry, theta):
# one geometry-threaded BUILD legitimately assembles 2-3 times (the
# operand set, the mg hierarchy's finest level, a validation pass), and
# the bisection sweep is the expensive host step — pay it once per
# distinct key, and emit the geom:degenerate-cut event once per
# distinct assembly rather than once per call. SDF shapes are frozen
# dataclasses (hashable); an unhashable custom shape just skips the
# memo. Entries are f64 read-backs — copies go out, so a caller
# mutating its arrays cannot poison later builds.
_GEOM_MEMO: dict = {}
_GEOM_MEMO_MAX = 8


def _geometry_coefficients(problem: Problem, geometry, theta, x, y):
    """The SDF-general twin of ``_coefficients_xp``: face lengths by
    bisection quadrature, the degenerate-cut clamp, the same blend law.
    Host f64 only (the traced path stays closed-form ellipse)."""
    from poisson_ellipse_tpu.geom import quadrature, sdf as geom_sdf
    from poisson_ellipse_tpu.obs import trace as obs_trace

    if theta is None:
        theta = quadrature.DEFAULT_THETA
    try:
        key = (problem, geometry, float(theta))
        hash(key)
    except TypeError:
        key = None
    if key is not None and key in _GEOM_MEMO:
        a, b, inside = _GEOM_MEMO[key]
        return a.copy(), b.copy(), inside.copy()
    la, lb = quadrature.segment_lengths(problem, geometry)
    la, a_empty, a_full = quadrature.clamp_lengths(la, problem.h2, theta)
    lb, b_empty, b_full = quadrature.clamp_lengths(lb, problem.h1, theta)
    clamped = a_empty + a_full + b_empty + b_full
    if clamped:
        obs_trace.event(
            "geom:degenerate-cut",
            theta=theta,
            clamped=clamped,
            to_empty=a_empty + b_empty,
            to_full=a_full + b_full,
            grid=[problem.M, problem.N],
        )
    eps = problem.eps_value
    a = _blend(la, problem.h2, eps, np)
    b = _blend(lb, problem.h1, eps, np)
    inside = np.asarray(
        geom_sdf.is_inside(geometry, x[:, None], y[None, :], np)
    )
    if key is not None:
        if len(_GEOM_MEMO) >= _GEOM_MEMO_MAX:
            _GEOM_MEMO.pop(next(iter(_GEOM_MEMO)))
        _GEOM_MEMO[key] = (a, b, inside)
        return a.copy(), b.copy(), inside.copy()
    return a, b, inside


def assemble(problem: Problem, dtype=jnp.float32, geometry=None, theta=None):
    """Assemble the full global (a, b, rhs) node-grid arrays, shape (M+1, N+1).

    Geometry is evaluated on the host in float64 (see ``assemble_numpy``
    for why this is mandatory) and cast to ``dtype`` — a one-time setup cost,
    exactly as the reference assembles on the CPU host before uploading
    (``poisson_mpi_cuda2.cu:716-759``). Row/col 0 of a,b are zero, matching
    the reference's (M+1)×(N+1) zero-initialised vectors
    (``stage0/Withoutopenmp1.cpp:111-112``). ``geometry``/``theta``
    select the SDF quadrature path (see ``assemble_numpy``); None keeps
    the closed-form ellipse bit-identical to before.
    """
    a, b, rhs = assemble_numpy(problem, geometry=geometry, theta=theta)
    return (
        jnp.asarray(a.astype(numpy_dtype(dtype))),
        jnp.asarray(b.astype(numpy_dtype(dtype))),
        jnp.asarray(rhs.astype(numpy_dtype(dtype))),
    )


def numpy_dtype(dtype):
    """The numpy dtype corresponding to a jax dtype spec."""
    return np.dtype(jnp.dtype(dtype).name)


def assemble_on_device(problem: Problem, dtype=jnp.float32):
    """Assemble the full grid with traced jnp ops (no host work).

    Only use where the trace dtype is f64 (e.g. the CPU-mesh distributed
    tests with x64 enabled) or on coarse grids — see ``assemble_numpy``
    for the f32 precision hazard.
    """
    gi = jnp.arange(problem.M + 1)
    gj = jnp.arange(problem.N + 1)
    a, b = coefficients_at(problem, gi, gj, dtype)
    rhs = rhs_at(problem, gi, gj, dtype)
    return a, b, rhs
