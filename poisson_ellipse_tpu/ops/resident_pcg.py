"""Whole-solve Pallas kernel: the entire PCG loop VMEM-resident.

The reference's stage4 pays, per iteration, 6 kernel launches + 6 device
syncs + >=3 device->host copies + 4 MPI_Sendrecv + 3 MPI_Allreduce
(``poisson_mpi_cuda2.cu:846-939``). The XLA while_loop path already
collapses that to ~8 fused kernels with zero host traffic; this module
collapses it to **zero per-iteration kernel boundaries**: one
``pallas_call`` holds the whole ``lax.while_loop``, with every operand
and iterate living in VMEM for the entire solve. HBM is touched exactly
twice — operands in at entry, solution out at exit.

This is the design point the chip's memory system rewards: the bench
part has ~128 MB of VMEM (measured; ``vmem_limit_bytes`` raised
accordingly), so grids whose ~17-array working set fits the 125 MB
residency budget — everything up to roughly 1100x1650, which covers the
reference's 400x600 and 800x1200 headline grids (``fits_resident`` is
the exact gate) — run the whole solve on-chip, where iteration cost is
pure VPU arithmetic (measured 3.5 us/iter @ 400x600, 7.9 @ 800x1200,
14.5 @ 1100x1650) instead of the ~40-75 us/iter the kernel-per-op
structure costs. Grids that don't fit fall back to the streamed
whole-solve kernel (``ops.streamed_pcg``) — ``solver.engine`` picks.

Arithmetic is the normalised-stencil form shared with ``fused_pcg``
(coefficients pre-divided by h^2 and pre-masked to the interior; the
preconditioner a multiply by a precomputed guarded 1/D), with the same
rotated loop whose value sequence matches the reference order
(``stage0/Withoutopenmp1.cpp:124-169``). The z iterate is eliminated
algebraically (p = r*Dinv + beta*p), which drops one resident array and
one store per iteration; verified to preserve the published
iteration-count oracles in f32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.parallel.compat import tpu_compiler_params
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.fused_pcg import fused_operands
from poisson_ellipse_tpu.solver.pcg import DENOM_GUARD, PCGResult
from poisson_ellipse_tpu.utils.device import scaled_vmem_budget

# Measured usable VMEM on the 128 MiB bench part (minus compiler
# reserves); scaled to the actual device's capacity at the use sites
# via ``utils.device.scaled_vmem_budget`` (device_kind-keyed table).
_VMEM_LIMIT = 127 * 1024 * 1024
_RESIDENT_BUDGET = 125 * 1024 * 1024
# Empirical working-set envelope: operands (6 coeffs + rhs) + scratch
# state (w, r, p) + w_out + ~6 Mosaic temporaries during the whole-array
# stencil/update expressions. Chip-measured with the scratch-state
# kernel: 1100x1650 (17 arrays = 124.9 MB) compiles and converges;
# 1200x1800 (157.7 MB) fails Mosaic allocation — hence BUDGET=125 MB.
_ARRAYS_RESIDENT = 17


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def padded_shape(problem: Problem) -> tuple[int, int]:
    g1, g2 = problem.node_shape
    return _round_up(g1, 8), _round_up(g2, 128)


def fits_resident(problem: Problem, dtype=jnp.float32, device=None) -> bool:
    """True if the whole solve's working set fits on-chip (on ``device``'s
    VMEM capacity; default: the default-backend device)."""
    g1p, g2p = padded_shape(problem)
    need = _ARRAYS_RESIDENT * g1p * g2p * jnp.dtype(dtype).itemsize
    return need <= scaled_vmem_budget(_RESIDENT_BUDGET, device)


def _shift_rows_down(x):
    """Row i-1 (zero row at the top: the ring is zero)."""
    zero = jnp.zeros((1, x.shape[1]), x.dtype)
    return jnp.concatenate([zero, x[:-1]], axis=0)


def _shift_rows_up(x):
    zero = jnp.zeros((1, x.shape[1]), x.dtype)
    return jnp.concatenate([x[1:], zero], axis=0)


def _shift_cols_right(x):
    zero = jnp.zeros((x.shape[0], 1), x.dtype)
    return jnp.concatenate([zero, x[:, :-1]], axis=1)


def _shift_cols_left(x):
    zero = jnp.zeros((x.shape[0], 1), x.dtype)
    return jnp.concatenate([x[:, 1:], zero], axis=1)


def _mega_kernel(h1, h2, delta, weighted, max_iter,
                 an, as_, bw, be, d, dinv, r0,
                 w_out, iters_out, diff_out, flags_out,
                 w_s, r_s, p_s):
    """The full PCG solve. Runs as a single grid-less invocation.

    State (w, r, p) lives in mutable VMEM scratch and the while_loop
    carries only scalars: carrying arrays would make Mosaic double-buffer
    them (an extra full-array copy each per iteration and ~3 more
    resident arrays of budget). In-place updates are value-safe on the
    breakdown path because alpha is forced to 0 there — w + 0·p and
    r − 0·ap are bitwise w and r, the reference's exit-before-touching
    semantics (``stage0/Withoutopenmp1.cpp:128``); p is rotated-loop
    state and is never read after exit.
    """
    dtype = r0.dtype
    an_v = an[...]
    as_v = as_[...]
    bw_v = bw[...]
    be_v = be[...]
    d_v = d[...]
    dinv_v = dinv[...]
    r_init = r0[...]

    h1h2 = jnp.asarray(h1 * h2, dtype)
    z0 = r_init * dinv_v
    zr0 = jnp.sum(z0 * r_init) * h1h2

    w_s[...] = jnp.zeros_like(r_init)
    r_s[...] = r_init
    p_s[...] = jnp.zeros_like(r_init)   # beta0 = 0 -> p1 = z0

    carry0 = (
        jnp.asarray(0, jnp.int32),
        zr0,
        jnp.asarray(0.0, dtype),       # beta
        jnp.asarray(jnp.inf, dtype),   # diff
        jnp.asarray(False),
        jnp.asarray(False),
    )

    def cond(c):
        k, _zr, _b, _d, conv, bd = c
        return (k < max_iter) & ~conv & ~bd

    def body(c):
        k, zr, beta, diff, _cv, _bd = c
        pn = r_s[...] * dinv_v + beta * p_s[...]
        p_s[...] = pn
        ap = d_v * pn - (
            an_v * _shift_rows_down(pn)
            + as_v * _shift_rows_up(pn)
            + bw_v * _shift_cols_right(pn)
            + be_v * _shift_cols_left(pn)
        )
        denom = jnp.sum(ap * pn) * h1h2
        breakdown = denom < DENOM_GUARD
        alpha = zr / jnp.where(breakdown, jnp.ones_like(denom), denom)
        alpha = jnp.where(breakdown, jnp.zeros_like(alpha), alpha)

        w = w_s[...]
        w_new = w + alpha * pn
        r_new = r_s[...] - alpha * ap
        w_s[...] = w_new
        r_s[...] = r_new
        # realised increment (w_new - w), not alpha*p: the convergence
        # oracle counts depend on the FP difference (cu:626-660)
        dw = w_new - w
        dw2 = jnp.sum(dw * dw)
        # two VPU reductions over VMEM-resident values inside ONE Mosaic
        # kernel: no collective and no HBM pass exists to fuse away
        # tpulint: disable=TPU007
        zr_new = jnp.sum((r_new * dinv_v) * r_new) * h1h2

        ndiff = jnp.sqrt(dw2 * h1h2) if weighted else jnp.sqrt(dw2)
        conv = ~breakdown & (ndiff < delta)
        ndiff = jnp.where(breakdown, diff, ndiff)
        beta_new = jnp.where(breakdown, beta, zr_new / zr)
        zr_out = jnp.where(breakdown, zr, zr_new)
        return (k + 1, zr_out, beta_new, ndiff, conv, breakdown)

    out = lax.while_loop(cond, body, carry0)
    w_out[...] = w_s[...]
    iters_out[0] = out[0]
    diff_out[0] = out[3]
    flags_out[0] = out[4].astype(jnp.int32)
    flags_out[1] = out[5].astype(jnp.int32)


def build_resident_solver(problem: Problem, dtype=jnp.float32,
                          interpret=None, geometry=None, theta=None):
    """(jitted whole-solve kernel, args) for a grid that fits VMEM.

    args are the f64-rounded normalised operands + RHS (the same operand
    set as ``fused_pcg.build_fused_solver``), so the two paths are
    value-identical where both apply.
    """
    import numpy as np

    if jnp.dtype(dtype).itemsize >= 8:
        raise ValueError("resident solver supports f32/bf16")
    if not fits_resident(problem, dtype):
        raise ValueError(
            f"grid {problem.M}x{problem.N} exceeds the VMEM-resident "
            "budget; use the streamed engine (ops.streamed_pcg) or let "
            "solver.engine pick"
        )
    if interpret is None:
        interpret = _interpret_default()
    g1, g2 = problem.node_shape
    g1p, g2p = padded_shape(problem)

    coeffs = fused_operands(problem, g1p, g2p, dtype, geometry=geometry,
                            theta=theta)
    _, _, rhs64 = assembly.assemble_numpy(problem, geometry=geometry,
                                          theta=theta)
    np_dtype = np.dtype(jnp.dtype(dtype).name)
    r0 = jnp.asarray(
        np.pad(rhs64, ((0, g1p - g1), (0, g2p - g2))).astype(np_dtype)
    )
    args = (*coeffs, r0)

    kernel = functools.partial(
        _mega_kernel,
        float(problem.h1), float(problem.h2), float(problem.delta),
        problem.norm == "weighted", problem.max_iterations,
    )
    vmem = lambda: pl.BlockSpec(memory_space=pltpu.VMEM)
    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)
    call = pl.pallas_call(
        kernel,
        in_specs=[vmem()] * 7,
        out_specs=(vmem(), smem(), smem(), smem()),
        out_shape=(
            jax.ShapeDtypeStruct((g1p, g2p), dtype),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((1,), dtype),
            jax.ShapeDtypeStruct((2,), jnp.int32),
        ),
        scratch_shapes=[
            pltpu.VMEM((g1p, g2p), dtype),  # w
            pltpu.VMEM((g1p, g2p), dtype),  # r
            pltpu.VMEM((g1p, g2p), dtype),  # p
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=scaled_vmem_budget(_VMEM_LIMIT)
        ),
        interpret=interpret,
    )

    def solver(*operands):
        w_pad, iters, diff, flags = call(*operands)
        return PCGResult(
            w=w_pad[:g1, :g2],
            iters=iters[0],
            diff=diff[0],
            converged=flags[0].astype(bool),
            breakdown=flags[1].astype(bool),
        )

    return jax.jit(solver), args


def solve_resident(problem: Problem, dtype=jnp.float32,
                   interpret=None) -> PCGResult:
    """Assemble and solve entirely on-chip (single kernel)."""
    solver, args = build_resident_solver(problem, dtype, interpret=interpret)
    return solver(*args)
