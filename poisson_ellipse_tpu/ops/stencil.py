"""5-point variable-coefficient stencil and diagonal preconditioner (layer L3).

Two layouts are supported by parallel function pairs:

- **global**: arrays are the full (M+1, N+1) node grid with an implicit
  Dirichlet boundary at rows/cols 0, M, N; the stencil writes the interior
  and leaves the boundary ring at zero — the TPU-native equivalent of the
  reference's interior loops (``stage0/Withoutopenmp1.cpp:75-103``, CUDA
  ``apply_A_kernel`` / ``apply_Dinv_kernel`` at
  ``stage4-mpi+cuda/poisson_mpi_cuda2.cu:507-562``).

- **block**: arrays are one device's halo-extended (bm+2, bn+2) block; the
  stencil evaluates all bm×bn owned nodes (the caller masks non-interior
  nodes), matching the per-rank contract of ``mat_A_local``
  (``stage2-mpi/poisson_mpi_decomp.cpp:194-213``: "requires fresh halos").

Floating-point forms mirror the reference exactly (each difference divided
by h before combining) so iteration counts are bit-comparable.
"""

from __future__ import annotations

import jax.numpy as jnp


def apply_a(w, a, b, h1, h2):
    """A·w on the full node grid; boundary ring stays zero.

    (Aw)_ij = −(a_{i+1,j}(w_{i+1,j}−w_ij)/h1 − a_ij(w_ij−w_{i−1,j})/h1)/h1
              −(b_{i,j+1}(w_{i,j+1}−w_ij)/h2 − b_ij(w_ij−w_{i,j−1})/h2)/h2
    Reference: ``stage0/Withoutopenmp1.cpp:83-85``.
    """
    return jnp.pad(apply_a_block(w, a, b, h1, h2), 1)


def apply_a_block(w_ext, a_ext, b_ext, h1, h2):
    """A·w over one halo-extended block: (bm+2, bn+2) inputs → (bm, bn) output.

    Evaluates every owned node; the caller is responsible for masking nodes
    that are not global-interior (physical boundary / shard padding), exactly
    as ``mat_A_local`` only writes owned interior nodes
    (``stage2-mpi/poisson_mpi_decomp.cpp:194-213``).
    """
    wc = w_ext[1:-1, 1:-1]
    ax = -(
        a_ext[2:, 1:-1] * (w_ext[2:, 1:-1] - wc) / h1
        - a_ext[1:-1, 1:-1] * (wc - w_ext[:-2, 1:-1]) / h1
    ) / h1
    ay = -(
        b_ext[1:-1, 2:] * (w_ext[1:-1, 2:] - wc) / h2
        - b_ext[1:-1, 1:-1] * (wc - w_ext[1:-1, :-2]) / h2
    ) / h2
    return ax + ay


def diag_d(a, b, h1, h2):
    """Diagonal of A on the full node grid: zero on the boundary ring.

    D_ij = (a_{i+1,j} + a_ij)/h1² + (b_{i,j+1} + b_ij)/h2²
    Reference: ``stage0/Withoutopenmp1.cpp:99``.
    """
    return jnp.pad(diag_d_block(a, b, h1, h2), 1)


def diag_d_block(a_ext, b_ext, h1, h2):
    """Diagonal of A over one halo-extended block → (bm, bn); caller masks."""
    return (a_ext[2:, 1:-1] + a_ext[1:-1, 1:-1]) / (h1 * h1) + (
        b_ext[1:-1, 2:] + b_ext[1:-1, 1:-1]
    ) / (h2 * h2)


def apply_dinv(r, d):
    """z = r / D with the reference's divide-by-zero guard.

    Where D == 0 (boundary ring, padding, degenerate cells) z is 0
    (``stage0/Withoutopenmp1.cpp:100``). Keeping the division explicit
    (rather than precomputing 1/D) preserves bitwise agreement with the
    reference's ``r[i][j] / D_ij``.
    """
    safe = jnp.where(d != 0.0, d, 1.0)
    return jnp.where(d != 0.0, r / safe, 0.0)
