"""s-step (communication-avoiding) PCG: s iterations per HBM/collective round.

The pipelined recurrence (``ops.pipelined_pcg``) got the iteration down
to ONE fused reduction; s-step CG (Chronopoulos & Gear 1989; the basis/
Gram formulation of Carson & Demmel 2013) goes below one: it advances
**s iterations per matrix-powers round**. One round

1. builds the monomial Krylov basis of the preconditioned operator
   Â = D⁻¹A from the current direction and residual,
       V = [p, Âp, …, Â^s p,  z, Âz, …, Â^{s-1} z]    (K = 2s+1 vectors)
   — for the 5-point stencil this is a cheap s-deep-halo kernel: the
   sharded form exchanges ONE s-deep halo and applies the stencil chain
   locally (``parallel.sstep_sharded``);
2. computes two small Gram matrices in ONE stacked reduction —
   Gm = h₁h₂·VᵀDV (the M-inner products: zr and the α-denominator are
   its quadratic forms) and Ge = VᵀV (the ‖Δx‖ step norm) — so the
   sharded form issues exactly ONE ``lax.psum`` per s iterations
   (vs 1/iter pipelined, 2/iter classical; jaxpr-pinned);
3. runs s CG iterations **in coordinates**: every iterate the inner
   steps touch stays in span(V), Â becomes the K×K shift matrix
   :func:`shift_matrix`, and α/β/convergence are O(K²) scalar work —
   no array passes, no reductions, no collectives;
4. reconstructs (x, r, p) from the coordinate vectors (one contraction
   against V) and rounds to storage width if a ``storage_dtype`` is set
   (``ops.precision`` — both bandwidth levers compose).

Monomial-basis round-off (the classical s-step hazard: powers of Â
align and the Gram system loses digits) is answered by the SAME
residual-replacement discipline the pipelined engine uses: every
:func:`~poisson_ellipse_tpu.ops.precision.replace_every` iterations the
block start rebuilds r = rhs − A·x from ground truth (both cadences
divide both block sizes, so a replacement always lands on a block
boundary), and s is capped at 4 — the measured-stable regime for this
operator family. Iteration counts land within the pipelined engine's
±2-style envelope of the classical oracle (asserted in
``tests/test_sstep.py``); bitwise parity remains the classical engines'
contract.

Convergence/breakdown semantics inside a block mirror the classical
loop per iteration: the (Ap⁺, p⁺) breakdown guard applies to the
coordinate-form denominator, a breakdown iteration discards its update
and exits, a converged iteration freezes p/zr, and the iteration count
includes the body that fired the exit. A chunk limit (``advance``'s
``limit``) is honoured exactly — the block's remaining inner steps are
masked off and the next dispatch re-anchors the basis at the boundary —
so guard chunking and fault injection stop at exact iterations; the
re-anchor makes chunked runs iteration-equivalent, not bitwise, to
straight runs (documented trade; the classical engines keep the bitwise
contract).

The carry layout IS the classical one — (k, x, r, p, zr, diff,
converged, breakdown) — so ``solver.checkpoint``, the guard's recovery
(``resilience.guard``), and the sharded reshard machinery apply
unchanged.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.precision import (
    load as _load,
    replace_every,
    resolve_storage_dtype,
    store as _store,
)
from poisson_ellipse_tpu.ops.stencil import apply_a, apply_dinv, diag_d
from poisson_ellipse_tpu.solver.pcg import (
    DENOM_GUARD,
    PCGResult,
    init_state as _classical_init,
    result_of,
)

# block sizes the engine supports: s=2 (conservative) and s=4 (the
# bandwidth headline). Both divide both residual-replacement cadences
# (32 f32 / 8 bf16), so replacements land on block boundaries.
SSTEP_CHOICES = (2, 4)
DEFAULT_S = 4


def basis_size(s: int) -> int:
    """K = 2s+1: s+1 powers of the direction, s of the residual."""
    return 2 * s + 1


# Per-power basis scaling: each stored basis vector is Â^j v / ρ^j with
# ρ = BASIS_SCALE. Gershgorin bounds λmax(D⁻¹A) ≤ 2 for this operator
# family (the same cap ``mg.cheby`` leans on), so ρ = 2 keeps the
# monomial columns' norms from growing with the power — a communication-
# free stabiliser (a norm-scaled basis would cost a reduction per power,
# which is exactly what this engine exists to avoid).
BASIS_SCALE = 2.0


def gram_dtype(compute_dtype):
    """The Gram accumulation dtype: f64 when x64 is available, else the
    compute dtype.

    Measured at 400×600 f32 (the stiff κ≈8e4 operator): an f32-
    accumulated Gram loses the digits the s=4 coordinate recurrence
    needs near convergence — 773 iterations vs the 546 oracle — while
    an f64 Gram restores EXACT classical parity. The f64 work is K²
    output scalars plus a widened accumulator over arrays that still
    stream at storage width (the convert fuses into the reduction), so
    the byte model is untouched; on x64-disabled processes the engine
    degrades to the f32 Gram (s=2 stays at exact parity there — its
    5-vector Gram holds the digits; s=4 trades iterations, documented).
    A Chebyshev–Leja Newton basis was measured and does NOT recover
    this (748 iters): the loss is accumulation round-off, not basis
    conditioning.
    """
    import jax

    if jax.config.jax_enable_x64 and jnp.dtype(compute_dtype).itemsize < 8:
        # gated on x64 the line above: never a silent downcast
        return jnp.float64  # tpulint: disable=TPU001
    return jnp.dtype(compute_dtype)


def shift_matrix(s: int, dtype=jnp.float32):
    """The K×K matrix B with coords(Â·v) = B·coords(v) for every vector
    the inner iterations can produce — the ρ-scaled monomial basis
    shifts each power to the next with weight ρ (p-part indices 0…s,
    z-part indices s+1…2s). Iteration j ≤ s−1 touches p-degree ≤ j and
    z-degree ≤ j−1, so the shift never falls off the basis (the
    Carson–Demmel degree bound)."""
    K = basis_size(s)
    B = np.zeros((K, K))
    for i in range(s):
        B[i + 1, i] = BASIS_SCALE
    for i in range(s - 1):
        B[s + 2 + i, s + 1 + i] = BASIS_SCALE
    return jnp.asarray(B, dtype)


def init_state(problem: Problem, a, b, rhs, storage_dtype=None):
    """The s-step carry at iteration 0 — exactly the classical carry
    (``solver.pcg.init_state``, no history tail)."""
    return _classical_init(problem, a, b, rhs, storage_dtype=storage_dtype)


def sstep_inner(Gm, Ge, Bm, s, k, limit, delta, hw, weighted,
                diff0, conv0, bd0, dtype):
    """The s masked CG iterations in K-dimensional coordinates.

    Pure scalar/K-vector work on the replicated Gram matrices — shared
    verbatim by the single-chip and sharded engines, which is what makes
    the sharded collective cadence 1 psum per s iterations: nothing in
    here reduces over the grid.

    Returns (k, x_c, z_c, p_c, zr, diff, converged, breakdown) with the
    classical per-iteration semantics (masked, so a mid-block exit or a
    chunk ``limit`` freezes the remaining steps).
    """
    K = Gm.shape[0]
    iz = s + 1
    x_c = jnp.zeros((K,), dtype)
    z_c = jnp.zeros((K,), dtype).at[iz].set(1.0)
    p_c = jnp.zeros((K,), dtype).at[0].set(1.0)
    # zr re-derived from the Gram diagonal: (z, r) = zᵀDz = Gm[z₀,z₀]
    zr = Gm[iz, iz]
    conv, bd, diff = conv0, bd0, diff0
    for _ in range(s):
        active = ~conv & ~bd & (k < limit)
        ap_c = Bm @ p_c
        denom = p_c @ (Gm @ ap_c)
        bd_fire = active & (denom < DENOM_GUARD)
        alpha = zr / jnp.where(denom < DENOM_GUARD, 1.0, denom)
        x_n = x_c + alpha * p_c
        z_n = z_c - alpha * ap_c
        zr_n = z_n @ (Gm @ z_n)
        # Ge is PSD up to round-off; clamp so a −ε quadratic form at the
        # storage floor cannot surface as a NaN step norm
        dw2 = alpha * alpha * jnp.maximum(p_c @ (Ge @ p_c), 0.0)
        diff_n = jnp.sqrt(dw2 * hw) if weighted else jnp.sqrt(dw2)
        conv_n = diff_n < delta
        beta = zr_n / jnp.where(zr == 0.0, 1.0, zr)
        p_n = z_n + beta * p_c
        upd = active & ~bd_fire
        k = k + active.astype(jnp.int32)
        x_c = jnp.where(upd, x_n, x_c)
        z_c = jnp.where(upd, z_n, z_c)
        diff = jnp.where(upd, diff_n, diff)
        adv = upd & ~conv_n
        p_c = jnp.where(adv, p_n, p_c)
        zr = jnp.where(adv, zr_n, zr)
        conv = conv | (upd & conv_n)
        bd = bd | bd_fire
    return k, x_c, z_c, p_c, zr, diff, conv, bd


def advance(problem: Problem, a, b, rhs, state, s: int = DEFAULT_S,
            limit=None, stencil: str = "xla", interpret=None,
            storage_dtype=None):
    """Advance the s-step carry until convergence/breakdown or iteration
    ``limit`` (honoured exactly — see module docstring on the mid-block
    re-anchor)."""
    if s not in SSTEP_CHOICES:
        raise ValueError(f"s must be one of {SSTEP_CHOICES}, got {s}")
    dtype = rhs.dtype
    st = resolve_storage_dtype(storage_dtype, dtype)
    cadence = replace_every(st, dtype)
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    hw = h1 * h2
    delta = jnp.asarray(problem.delta, dtype)
    weighted = problem.norm == "weighted"
    max_iter = (
        problem.max_iterations
        if limit is None
        else jnp.minimum(jnp.asarray(limit, jnp.int32),
                         problem.max_iterations)
    )
    d = diag_d(a, b, h1, h2)
    a_s, b_s = (_store(a, st), _store(b, st)) if st is not None else (a, b)
    d_s = _store(d, st) if st is not None else d

    if stencil == "pallas":
        if st is not None:
            from poisson_ellipse_tpu.ops.pallas_kernels import (
                apply_a_mixed_pallas,
            )

            def apply_stencil(v):
                return apply_a_mixed_pallas(
                    v, a_s, b_s, problem.h1, problem.h2,
                    compute_dtype=dtype, interpret=interpret,
                )

        else:
            from poisson_ellipse_tpu.ops.pallas_kernels import apply_a_pallas

            def apply_stencil(v):
                return apply_a_pallas(v, a, b, problem.h1, problem.h2,
                                      interpret=interpret)

    elif stencil == "xla":

        def apply_stencil(v):
            return apply_a(v, _load(a_s, dtype, st), _load(b_s, dtype, st),
                           h1, h2)

    else:
        raise ValueError(f"unknown stencil: {stencil!r}")

    def dinv(v):
        return apply_dinv(v, _load(d_s, dtype, st))

    def ahat(v):
        return dinv(apply_stencil(v))

    Bm = shift_matrix(s, dtype)

    def cond(state):
        k, converged, breakdown = state[0], state[6], state[7]
        return (k < max_iter) & ~converged & ~breakdown

    def body(state):
        k, x_sv, r_sv, p_sv, _zr, diff0, conv0, bd0 = state[:8]
        x = _load(x_sv, dtype, st)
        r = _load(r_sv, dtype, st)
        p = _load(p_sv, dtype, st)

        # residual replacement on the recurrence cadence: a block whose
        # s iterations CONTAIN a cadence multiple rebuilds r from
        # ground truth — the monomial basis's drift bound AND the
        # storage axis's (tightened cadence under bf16). Phrased as
        # containment, not block-start alignment: a chunk limit or
        # fault stop mid-block re-anchors block starts off the s-grid,
        # and an equality test would then never fire again for the
        # rest of the solve
        km = k % cadence
        do = (k > 0) & ((km == 0) | (km > cadence - s))
        r = lax.cond(do, lambda _: rhs - apply_stencil(x), lambda _: r, None)

        # matrix-powers basis: one stencil chain, no reductions
        z = dinv(r)
        if st is not None:
            # sub-compute storage: the direction reconstructed through a
            # storage-rounded basis accumulates drift the p-preserving
            # replacement cannot clear (measured: bf16+monomial climbs);
            # the tightened cadence pairs with a full p = z restart —
            # the ~25% iteration tax applies only to the replaced blocks
            # of the low-precision phase, which the guard's promotion
            # rung bounds anyway
            p = jnp.where(do, z, p)
        scale = jnp.asarray(1.0 / BASIS_SCALE, dtype)
        vs = [p]
        for _ in range(s):
            vs.append(ahat(vs[-1]) * scale)
        zs = [z]
        for _ in range(s - 1):
            zs.append(ahat(zs[-1]) * scale)
        V = jnp.stack(vs + zs)  # (K, M+1, N+1)

        # the block's ONE stacked reduction: both Gram matrices from a
        # single pass over V (D is diagonal, zero outside the interior,
        # so full-grid sums equal interior sums — the reduction-layout
        # invariant). Accumulation at gram_dtype (f64 under x64): the
        # measured parity requirement — the convert fuses into the
        # reduction, so V still streams at storage width
        d_c = _load(d_s, dtype, st)
        gd = gram_dtype(dtype)
        Vg = V.astype(gd)
        Gm = jnp.einsum("kij,lij->kl", Vg, Vg * d_c.astype(gd)) * hw.astype(gd)
        Ge = jnp.einsum("kij,lij->kl", Vg, Vg)

        k_n, x_c, z_c, p_c, zr_n, diff_n, conv_n, bd_n = sstep_inner(
            Gm, Ge, Bm.astype(gd), s, k, max_iter, delta.astype(gd),
            hw.astype(gd), weighted, diff0.astype(gd), conv0, bd0, gd,
        )
        x_c, z_c, p_c = (
            x_c.astype(dtype), z_c.astype(dtype), p_c.astype(dtype)
        )
        zr_n, diff_n = zr_n.astype(dtype), diff_n.astype(dtype)

        # reconstruct in full space (one contraction against the basis);
        # r = D·z exactly — the diagonal preconditioner's inverse pair
        x_new = x + jnp.tensordot(x_c, V, axes=1)
        z_new = jnp.tensordot(z_c, V, axes=1)
        r_new = d_c * z_new
        p_new = jnp.tensordot(p_c, V, axes=1)
        return (
            k_n,
            _store(x_new, st), _store(r_new, st), _store(p_new, st),
            zr_n, diff_n, conv_n, bd_n,
        )

    return lax.while_loop(cond, body, state)


def pcg_sstep(problem: Problem, a, b, rhs, s: int = DEFAULT_S,
              stencil: str = "xla", interpret=None, storage_dtype=None):
    """Run s-step PCG for pre-assembled coefficients ((M+1, N+1) grids).

    Jit-safe with ``problem``/``s`` static; the while_loop advances s
    iterations per body over the classical carry layout. ``stencil``
    "xla" or "pallas" (the basis chain through the per-op kernel; with a
    ``storage_dtype`` the mixed kernels — storage-width HBM tiles,
    compute-width VMEM math). Returns a :class:`PCGResult`.
    """
    state = advance(
        problem, a, b, rhs,
        init_state(problem, a, b, rhs, storage_dtype=storage_dtype),
        s=s, stencil=stencil, interpret=interpret,
        storage_dtype=storage_dtype,
    )
    return result_of(state)


def solve(problem: Problem, dtype=jnp.float32, s: int = DEFAULT_S,
          stencil: str = "xla", interpret=None, storage_dtype=None):
    """Assemble and solve on a single chip with the s-step recurrence."""
    a, b, rhs = assembly.assemble(problem, dtype)
    return pcg_sstep(problem, a, b, rhs, s=s, stencil=stencil,
                     interpret=interpret, storage_dtype=storage_dtype)
