"""Tile-wise whole-solve kernel for grids beyond full VMEM residency.

``ops.resident_pcg`` holds every operand and iterate in VMEM, but its
whole-array expressions make Mosaic materialise full-size temporaries,
capping it at ~1000x1500. This kernel removes that cap two ways:

- **tile-wise compute**: every sweep walks row tiles, so temporaries are
  tile-sized and the only full-size VMEM consumers are the arrays we
  *choose* to keep resident;
- **per-operand residency**: the PCG state (w, r, p) always stays in
  VMEM scratch across the whole ``lax.while_loop`` (the entire point —
  state never touches HBM); each loop-invariant operand (Dinv, a, b) and
  the ap intermediate is either VMEM-resident too (loaded once) or
  streamed per tile from HBM with ``make_async_copy`` double-buffering,
  chosen greedily to fill the measured ~127 MB of VMEM.

On the bench chip this makes 1600x2400 all-resident (zero HBM bytes per
iteration) and 2400x3200 stream only Dinv and ap (~6 array-passes/iter
vs the ~13 the XLA while_loop streams once the working set outgrows
VMEM) — the two reference grids where the XLA path is HBM-bound.

Per iteration, three tile sweeps inside one kernel:

  A   p <- r*Dinv + beta*p                       (rotated p-update)
  B   ap = A(p) tile-by-tile; denom partial      (stencil + dot)
  C   alpha; w += alpha*p; r -= alpha*ap;
      ||dw||^2 and (z, r) partials               (fused updates)

The stencil uses the reference's exact floating-point form (each
difference divided by h before combining, ``stage0/Withoutopenmp1.cpp:
75-88``) with the f64-rounded operand set, preserving the published
iteration-count oracles in f32. The preconditioner is a multiply by the
precomputed guarded 1/D (f64-rounded), as in ``ops.fused_pcg``.

p's scratch carries 8-row zero bands above and below the grid so the
stencil's row-neighbour reads are always in bounds; ring/padding output
rows are masked in-kernel (assembled coefficients are nonzero *adjacent*
to the ring, so masking inputs alone cannot zero the ring output —
same reason the reference's kernels guard on indices,
``poisson_mpi_cuda2.cu:512-516``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.solver.pcg import DENOM_GUARD, PCGResult

_VMEM_LIMIT = 127 * 1024 * 1024
_VMEM_USABLE = 114 * 1024 * 1024  # leave headroom for Mosaic temps
_BAND = 8  # zero band rows above/below the p scratch


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


class StreamPlan:
    """Which operands stay VMEM-resident, plus the tiling."""

    def __init__(self, problem: Problem, dtype):
        g1, g2 = problem.node_shape
        self.g2p = _round_up(g2, 128)
        self.tm = 64 if g1 >= 64 else _round_up(g1, 8)
        self.g1p = _round_up(g1, self.tm)
        self.n_tiles = self.g1p // self.tm
        item = jnp.dtype(dtype).itemsize
        row = self.g2p * item
        budget = _VMEM_USABLE
        # state is always resident: w, r + p with its zero bands
        budget -= (3 * self.g1p + 2 * _BAND) * row
        # per-operand buffer rows: streamed operands get a tile-sized
        # buffer (matching the kernel's scratch_shapes exactly), resident
        # ones hold the full padded array
        tile_rows = {"dinv": self.tm, "ap": self.tm,
                     "a": self.tm + 8, "b": self.tm}
        full_rows = {"dinv": self.g1p, "ap": self.g1p,
                     "a": self.g1p + 8, "b": self.g1p}
        # the gate: state + the minimum (all-streamed) buffer set must fit
        self.min_stream_bytes = sum(tile_rows.values()) * row
        self.fits = budget >= self.min_stream_bytes
        # greedy residency, highest streamed-passes-saved first (dinv is
        # read twice per iteration, ap written+read once each); upgrading
        # an operand to resident swaps its tile buffer for the full array
        budget -= self.min_stream_bytes
        self.resident = {}
        for name in ("dinv", "ap", "a", "b"):
            extra = (full_rows[name] - tile_rows[name]) * row
            take = self.fits and extra <= budget
            self.resident[name] = take
            if take:
                budget -= extra

    def streamed_passes_per_iter(self) -> float:
        """HBM array-passes per iteration (for the roofline report)."""
        p = 0.0
        if not self.resident["dinv"]:
            p += 2.0
        if not self.resident["ap"]:
            p += 2.0
        if not self.resident["a"]:
            p += 1.0 + 8.0 / self.tm
        if not self.resident["b"]:
            p += 1.0
        return p


def fits_streamed(problem: Problem, dtype=jnp.float32) -> bool:
    """True if the always-resident PCG state (w, r, banded p) plus the
    minimum double-buffered stream buffers fit the VMEM budget.

    The state itself cannot be streamed (it is read and written every
    pass of every iteration), so grids past this gate — e.g. the 4097²
    node grid, whose state alone is ~201 MB — need the sharded path.
    """
    return StreamPlan(problem, dtype).fits


def _shift_cols_right(x):
    zero = jnp.zeros((x.shape[0], 1), x.dtype)
    return jnp.concatenate([zero, x[:, :-1]], axis=1)


def _shift_cols_left(x):
    zero = jnp.zeros((x.shape[0], 1), x.dtype)
    return jnp.concatenate([x[:, 1:], zero], axis=1)


def _mega_kernel(problem: Problem, plan: StreamPlan, weighted: bool,
                 # HBM / maybe-VMEM inputs
                 dinv_hbm, a_hbm, b_hbm, r0_hbm,
                 # outputs
                 w_out, iters_out, diff_out, flags_out, ap_hbm,
                 # scratch
                 w_s, r_s, p_s, dinv_buf, a_buf, b_buf, ap_buf, sems):
    dtype = r0_hbm.dtype
    tm, g2p, n_tiles = plan.tm, plan.g2p, plan.n_tiles
    h1 = float(problem.h1)
    h2 = float(problem.h2)
    h1h2 = jnp.asarray(h1 * h2, dtype)
    delta = jnp.asarray(problem.delta, dtype)
    max_iter = problem.max_iterations
    M, N = problem.M, problem.N
    res = plan.resident

    # -- residency helpers -------------------------------------------------
    # serial copies: start+wait around each tile (the streamed arrays are
    # a small fraction of iteration time; see module docstring)

    def load(hbm, buf, sem, t, rows):
        cp = pltpu.make_async_copy(
            hbm.at[pl.ds(t * tm, rows), :], buf.at[pl.ds(0, rows), :], sem
        )
        cp.start()
        cp.wait()
        return buf

    def dinv_tile(t):
        if res["dinv"]:
            return dinv_buf[pl.ds(t * tm, tm), :]
        return load(dinv_hbm, dinv_buf, sems.at[0], t, tm)[0:tm, :]

    def a_win(t):
        """Rows t0 .. t0+tm (tm+1 rows; buffer is tm+8-aligned)."""
        if res["a"]:
            return a_buf[pl.ds(t * tm, tm + 1), :]
        return load(a_hbm, a_buf, sems.at[1], t, tm + 8)[0 : tm + 1, :]

    def b_tile(t):
        if res["b"]:
            return b_buf[pl.ds(t * tm, tm), :]
        return load(b_hbm, b_buf, sems.at[2], t, tm)[0:tm, :]

    def ap_store(t, val):
        if res["ap"]:
            ap_buf[pl.ds(t * tm, tm), :] = val
        else:
            ap_buf[...] = val
            cp = pltpu.make_async_copy(
                ap_buf, ap_hbm.at[pl.ds(t * tm, tm), :], sems.at[3]
            )
            cp.start()
            cp.wait()

    def ap_load(t):
        if res["ap"]:
            return ap_buf[pl.ds(t * tm, tm), :]
        cp = pltpu.make_async_copy(
            ap_hbm.at[pl.ds(t * tm, tm), :], ap_buf, sems.at[3]
        )
        cp.start()
        cp.wait()
        return ap_buf[...]

    # -- one-time initialisation ------------------------------------------
    for name, hbm, buf, rows in (
        ("dinv", dinv_hbm, dinv_buf, plan.g1p),
        ("a", a_hbm, a_buf, plan.g1p + 8),
        ("b", b_hbm, b_buf, plan.g1p),
    ):
        if res[name]:
            cp = pltpu.make_async_copy(hbm, buf, sems.at[0])
            cp.start()
            cp.wait()

    w_s[...] = jnp.zeros(w_s.shape, dtype)
    p_s[...] = jnp.zeros(p_s.shape, dtype)
    cp = pltpu.make_async_copy(r0_hbm, r_s, sems.at[0])
    cp.start()
    cp.wait()

    def tile_sum(fold):
        def body(t, acc):
            return acc + fold(t)
        return lax.fori_loop(0, n_tiles, body, jnp.zeros((), dtype))

    zr0 = tile_sum(
        lambda t: jnp.sum(
            (r_s[pl.ds(t * tm, tm), :] * dinv_tile(t))
            * r_s[pl.ds(t * tm, tm), :]
        )
    ) * h1h2

    # -- the stencil for one tile -----------------------------------------
    def stencil_tile(t):
        """A(p) on tile t, reference FP form, ring/padding masked.

        Row neighbours come from aligned 8-row block loads + value-level
        concats: Mosaic requires dynamic VMEM loads at sublane multiples,
        so a tile shifted by one row is not directly loadable.
        """
        pc = p_s[pl.ds(_BAND + t * tm, tm), :]
        p_above = p_s[pl.ds(_BAND + t * tm - 8, 8), :]
        p_below = p_s[pl.ds(_BAND + (t + 1) * tm, 8), :]
        pu = jnp.concatenate([p_above[7:8, :], pc[:-1]], axis=0)
        pd = jnp.concatenate([pc[1:], p_below[0:1, :]], axis=0)
        aw = a_win(t)
        ac = aw[0:tm, :]
        ad = aw[1 : tm + 1, :]
        bc = b_tile(t)
        br = _shift_cols_left(bc)
        pl_ = _shift_cols_right(pc)
        pr = _shift_cols_left(pc)
        ax = -(ad * (pd - pc) / h1 - ac * (pc - pu) / h1) / h1
        ay = -(br * (pr - pc) / h2 - bc * (pc - pl_) / h2) / h2
        gi = t * tm + lax.broadcasted_iota(jnp.int32, (tm, g2p), 0)
        gj = lax.broadcasted_iota(jnp.int32, (tm, g2p), 1)
        interior = (gi >= 1) & (gi <= M - 1) & (gj >= 1) & (gj <= N - 1)
        apt = jnp.where(interior, ax + ay, jnp.zeros_like(pc))
        return apt, pc

    # -- the while loop ----------------------------------------------------
    carry0 = (
        jnp.asarray(0, jnp.int32), zr0,
        jnp.asarray(0.0, dtype),            # beta
        jnp.asarray(jnp.inf, dtype),        # diff
        jnp.asarray(False), jnp.asarray(False),
    )

    def cond(c):
        k, _zr, _b, _d, conv, bd = c
        return (k < max_iter) & ~conv & ~bd

    def body(c):
        k, zr, beta, diff, _cv, _bd = c

        # pass A: p <- r*Dinv + beta*p
        def pass_a(t, _):
            rows = pl.ds(_BAND + t * tm, tm)
            p_s[rows, :] = (
                r_s[pl.ds(t * tm, tm), :] * dinv_tile(t)
                + beta * p_s[rows, :]
            )
            return 0
        lax.fori_loop(0, n_tiles, pass_a, 0)

        # pass B: ap = A(p), denom
        def pass_b(t, acc):
            apt, pc = stencil_tile(t)
            ap_store(t, apt)
            return acc + jnp.sum(apt * pc)
        denom = lax.fori_loop(
            0, n_tiles, pass_b, jnp.zeros((), dtype)
        ) * h1h2

        breakdown = denom < DENOM_GUARD
        alpha = zr / jnp.where(breakdown, jnp.ones_like(denom), denom)
        alpha = jnp.where(breakdown, jnp.zeros_like(alpha), alpha)

        # pass C: fused updates + both reductions
        def pass_c(t, acc):
            dw2a, zra = acc
            rows = pl.ds(t * tm, tm)
            w = w_s[rows, :]
            w_new = w + alpha * p_s[pl.ds(_BAND + t * tm, tm), :]
            dw = w_new - w
            w_s[rows, :] = w_new
            r_new = r_s[rows, :] - alpha * ap_load(t)
            r_s[rows, :] = r_new
            return (
                dw2a + jnp.sum(dw * dw),
                zra + jnp.sum((r_new * dinv_tile(t)) * r_new),
            )
        dw2, zr_raw = lax.fori_loop(
            0, n_tiles, pass_c,
            (jnp.zeros((), dtype), jnp.zeros((), dtype)),
        )
        zr_new = zr_raw * h1h2

        ndiff = jnp.sqrt(dw2 * h1h2) if weighted else jnp.sqrt(dw2)
        conv = ~breakdown & (ndiff < delta)
        ndiff = jnp.where(breakdown, diff, ndiff)
        beta_new = jnp.where(breakdown, beta, zr_new / zr)
        zr_out = jnp.where(breakdown, zr, zr_new)
        return (k + 1, zr_out, beta_new, ndiff, conv, breakdown)

    out = lax.while_loop(cond, body, carry0)

    cp = pltpu.make_async_copy(w_s, w_out, sems.at[0])
    cp.start()
    cp.wait()
    iters_out[0] = out[0]
    diff_out[0] = out[3]
    flags_out[0] = out[4].astype(jnp.int32)
    flags_out[1] = out[5].astype(jnp.int32)


def build_streamed_solver(problem: Problem, dtype=jnp.float32,
                          interpret=None):
    """(jitted whole-solve kernel, args) for large grids.

    args = (dinv, a, b, r0), all f64-assembled and rounded once (same
    operand fidelity as ``fused_pcg.build_fused_solver``).
    """
    import numpy as np

    if jnp.dtype(dtype).itemsize >= 8:
        raise ValueError("streamed solver supports f32/bf16")
    if interpret is None:
        interpret = _interpret_default()
    g1, g2 = problem.node_shape
    plan = StreamPlan(problem, dtype)
    if not plan.fits:
        raise ValueError(
            f"grid {problem.M}x{problem.N}: PCG state (w, r, p) alone "
            "exceeds the VMEM budget — the streamed engine cannot hold "
            "it on-chip; use the XLA path or the sharded solver"
        )
    g1p, g2p, tm = plan.g1p, plan.g2p, plan.tm
    np_dtype = np.dtype(jnp.dtype(dtype).name)

    a64, b64, rhs64 = assembly.assemble_numpy(problem)

    def padded(x, extra_rows=0):
        return jnp.asarray(
            np.pad(
                x, ((0, g1p + extra_rows - x.shape[0]), (0, g2p - x.shape[1]))
            ).astype(np_dtype)
        )

    # guarded 1/D from the f64 diagonal — shared with the fused engine
    from poisson_ellipse_tpu.ops.fused_pcg import interior_normalized

    dinv64 = interior_normalized(problem, a64, b64)[5]

    args = (padded(dinv64), padded(a64, 8), padded(b64), padded(rhs64))

    kernel = functools.partial(
        _mega_kernel, problem, plan, problem.norm == "weighted"
    )
    anyspec = lambda: pl.BlockSpec(memory_space=pl.ANY)
    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)
    res = plan.resident
    buf = lambda name, rows, extra=0: (
        pltpu.VMEM((g1p + extra, g2p), dtype)
        if res[name]
        else pltpu.VMEM((rows + extra, g2p), dtype)
    )
    call = pl.pallas_call(
        kernel,
        in_specs=[anyspec()] * 4,
        out_specs=(anyspec(), smem(), smem(), smem(), anyspec()),
        out_shape=(
            jax.ShapeDtypeStruct((g1p, g2p), dtype),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((1,), dtype),
            jax.ShapeDtypeStruct((2,), jnp.int32),
            # HBM scratch for ap when it is not VMEM-resident (an output
            # only because pallas scratch cannot live in HBM)
            jax.ShapeDtypeStruct(
                (8, g2p) if res["ap"] else (g1p, g2p), dtype
            ),
        ),
        scratch_shapes=[
            pltpu.VMEM((g1p, g2p), dtype),             # w
            pltpu.VMEM((g1p, g2p), dtype),             # r
            pltpu.VMEM((g1p + 2 * _BAND, g2p), dtype),  # p with bands
            buf("dinv", tm),
            buf("a", tm, 8),
            buf("b", tm),
            (pltpu.VMEM((g1p, g2p), dtype)
             if res["ap"] else pltpu.VMEM((tm, g2p), dtype)),
            pltpu.SemaphoreType.DMA((4,)),
        ],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT
        ),
        interpret=interpret,
    )

    def solver(dinv, a, b, r0):
        w_pad, iters, diff, flags, _ap = call(dinv, a, b, r0)
        return PCGResult(
            w=w_pad[:g1, :g2],
            iters=iters[0],
            diff=diff[0],
            converged=flags[0].astype(bool),
            breakdown=flags[1].astype(bool),
        )

    return jax.jit(solver), args


def solve_streamed(problem: Problem, dtype=jnp.float32,
                   interpret=None) -> PCGResult:
    solver, args = build_streamed_solver(problem, dtype, interpret=interpret)
    return solver(*args)
