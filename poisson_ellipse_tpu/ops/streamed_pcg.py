"""Tile-wise whole-solve kernel for grids beyond full VMEM residency.

``ops.resident_pcg`` holds every operand and iterate in VMEM, but its
whole-array expressions make Mosaic materialise full-size temporaries,
capping it at ~1000x1500. This kernel removes that cap two ways:

- **tile-wise compute**: every sweep walks row tiles, so temporaries are
  tile-sized and the only full-size VMEM consumers are the arrays we
  *choose* to keep resident;
- **per-operand residency**: the PCG state (w, r, p) always stays in
  VMEM scratch across the whole ``lax.while_loop`` (the entire point —
  state never touches HBM); each loop-invariant operand (Dinv, a, b) and
  the ap intermediate is either VMEM-resident too (loaded once) or
  streamed per tile from HBM into a 2-slot buffer, software-pipelined
  (the DMA for tile t+1 overlaps tile t's compute; ap stores lag two
  tiles), chosen greedily to fill the measured ~127 MB of VMEM.

Measured residency on the bench chip (``StreamPlan(...).resident``):
1600x2400 is **all-resident** — zero HBM bytes per iteration — while at
2400x3200 the state alone takes ~97 MB of the ~114 MB budget, so **all
four operands stream** (~5.1 array-passes/iter vs the ~13 the XLA
while_loop streams once the working set outgrows VMEM) behind the
double-buffered pipeline.

Per iteration, two tile sweeps inside one kernel (the two scalar sync
points of PCG — alpha needs the global denom, beta the global zr — set
the sweep-count floor):

  AB  p <- z + beta*p on tile t+1, then          (rotated p-update fused
      ap = A(p) on tile t; denom partial          with stencil + dot on a
                                                  one-tile lag)
  C   alpha; w += alpha*p; z/r update;
      ||dw||^2 and (z, r) partials               (fused updates)

In the dinv-resident regimes the state array holds r and z is formed on
the fly (z = r·Dinv, twice per iteration, both free — dinv is VMEM-
resident). In the all-streamed regime the state instead carries z
itself, which moves the single dinv stream entirely into pass C (the
z-update and the z²·(1/Dinv) inner product share it) and makes the AB
p-update operand-free — one dinv HBM pass per iteration instead of two,
with the published iteration counts preserved (see the z-state branch
in ``_mega_kernel``).

The stencil is the reference's algebraic form
(``stage0/Withoutopenmp1.cpp:75-88``) with the 1/h² factors hoisted into
the one-time f64 operand build (unmasked an = a/h1², bw = b/h2²; see
``stencil_tile``) — zero VPU divides per iteration, with the published
iteration-count oracles preserved in f32 (asserted by the bench on every
run). The preconditioner is a multiply by the precomputed guarded 1/D
(f64-rounded), as in ``ops.fused_pcg``.

p's scratch carries 8-row zero bands above and below the grid so the
stencil's row-neighbour reads are always in bounds; ring/padding output
rows are masked in-kernel (assembled coefficients are nonzero *adjacent*
to the ring, so masking inputs alone cannot zero the ring output —
same reason the reference's kernels guard on indices,
``poisson_mpi_cuda2.cu:512-516``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.parallel.compat import tpu_compiler_params
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.solver.pcg import DENOM_GUARD, PCGResult
from poisson_ellipse_tpu.utils.device import scaled_vmem_budget

# measured on the 128 MiB bench part; scaled to the actual device's
# capacity at the use sites (utils.device, device_kind-keyed)
_VMEM_LIMIT = 127 * 1024 * 1024
_VMEM_USABLE = 114 * 1024 * 1024  # leave headroom for Mosaic temps
_BAND = 8  # zero band rows above/below the p scratch


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


class StreamPlan:
    """Which operands stay VMEM-resident, plus the tiling.

    tm — row-tile height override (multiple of 8). Default (None) picks
    128 when that plan streams no more HBM traffic per iteration than the
    64-row plan, else 64: larger tiles cut per-tile loop/DMA bookkeeping
    (measured ~12% per iteration at 1600x2400 all-resident) but eat VMEM
    that the greedy residency pass and Mosaic temporaries want; 256 was
    measured slower (it demotes an operand to streamed).

    device — whose VMEM capacity bounds the plan (default: the
    default-backend device); the measured 128 MiB-part budget is scaled
    to it via ``utils.device.scaled_vmem_budget``.
    """

    def __init__(self, problem: Problem, dtype, tm: int | None = None,
                 device=None):
        self.device = device
        if tm is None:
            self._compute(problem, dtype, 64)
            fits64 = self.fits
            passes64 = self.streamed_passes_per_iter()
            state64 = dict(self.__dict__)
            self._compute(problem, dtype, 128)
            # keep 128 only when it streams no more HBM traffic than 64 —
            # comparing resident *counts* could trade a cheap-to-stream
            # operand for an expensive one behind an equal count
            if not (
                self.fits
                and (
                    not fits64
                    or self.streamed_passes_per_iter() <= passes64
                )
            ):
                self.__dict__.update(state64)
        else:
            if tm % 8 or tm < 8:
                raise ValueError(
                    f"tm must be a positive multiple of 8, got {tm}"
                )
            self._compute(problem, dtype, tm)

    def _compute(self, problem: Problem, dtype, tm: int) -> None:
        g1, g2 = problem.node_shape
        self.g2p = _round_up(g2, 128)
        self.tm = tm if g1 >= tm else _round_up(g1, 8)
        self.g1p = _round_up(g1, self.tm)
        self.n_tiles = self.g1p // self.tm
        item = jnp.dtype(dtype).itemsize
        row = self.g2p * item
        budget = scaled_vmem_budget(_VMEM_USABLE, self.device)
        # state is always resident: w, r + p with its zero bands
        budget -= (3 * self.g1p + 2 * _BAND) * row
        # per-operand buffer rows: streamed operands get a double-buffered
        # 2-slot tile buffer (the single source of the scratch_shapes row
        # counts), resident ones hold the full padded array ("a" carries
        # an 8-row halo in both forms)
        self.tile_rows = {"dinv": 2 * self.tm, "ap": 2 * self.tm,
                          "a": 2 * (self.tm + 8), "b": 2 * self.tm}
        self.full_rows = {"dinv": self.g1p, "ap": self.g1p,
                          "a": self.g1p + 8, "b": self.g1p}
        tile_rows, full_rows = self.tile_rows, self.full_rows
        # the gate: state + the minimum (all-streamed) buffer set must fit
        self.min_stream_bytes = sum(tile_rows.values()) * row
        self.fits = budget >= self.min_stream_bytes
        # greedy residency, highest streamed-passes-saved first (ap is
        # written+read each iteration = 2 passes; dinv costs only 1 —
        # the z-state regime reads it once, in pass C); upgrading an
        # operand to resident swaps its tile buffer for the full array
        budget -= self.min_stream_bytes
        self.resident = {}
        for name in ("ap", "dinv", "a", "b"):
            extra = (full_rows[name] - tile_rows[name]) * row
            take = self.fits and extra <= budget
            self.resident[name] = take
            if take:
                budget -= extra

    def streamed_passes_per_iter(self) -> float:
        """HBM array-passes per iteration (for the roofline report)."""
        p = 0.0
        if not self.resident["dinv"]:
            # read once, in pass C only: the all-streamed regime carries
            # z (= Dinv·r) as the resident state, so the AB sweep's
            # p-update needs no operand at all (``_mega_kernel``)
            p += 1.0
        if not self.resident["ap"]:
            p += 2.0
        if not self.resident["a"]:
            p += 1.0 + 8.0 / self.tm
        if not self.resident["b"]:
            p += 1.0
        return p


def fits_streamed(problem: Problem, dtype=jnp.float32, device=None) -> bool:
    """True if the always-resident PCG state (w, r, banded p) plus the
    minimum double-buffered stream buffers fit the VMEM budget (scaled
    to ``device``'s capacity).

    The state itself cannot be streamed by THIS kernel (it is read and
    written every pass of every iteration), so grids past this gate —
    e.g. the 4097² node grid, whose state alone is ~201 MB — take the
    xl engine (``ops.xl_pcg``, which streams state too) or the sharded
    path.
    """
    return StreamPlan(problem, dtype, device=device).fits


def _shift_cols_right(x):
    zero = jnp.zeros((x.shape[0], 1), x.dtype)
    return jnp.concatenate([zero, x[:, :-1]], axis=1)


def _shift_cols_left(x):
    zero = jnp.zeros((x.shape[0], 1), x.dtype)
    return jnp.concatenate([x[:, 1:], zero], axis=1)


_NSLOT = 2  # double buffering: prefetch tile t+1 while computing tile t


def _mega_kernel(problem: Problem, plan: StreamPlan, weighted: bool,
                 # HBM / maybe-VMEM inputs
                 dinv_hbm, a_hbm, b_hbm, r0_hbm,
                 # outputs
                 w_out, iters_out, diff_out, flags_out, ap_hbm,
                 # scratch
                 w_s, r_s, p_s, dinv_buf, a_buf, b_buf, ap_buf, sems):
    dtype = r0_hbm.dtype
    tm, g2p, n_tiles = plan.tm, plan.g2p, plan.n_tiles
    h1 = float(problem.h1)
    h2 = float(problem.h2)
    h1h2 = jnp.asarray(h1 * h2, dtype)
    delta = jnp.asarray(problem.delta, dtype)
    max_iter = problem.max_iterations
    M, N = problem.M, problem.N
    res = plan.resident

    # -- streamed-operand machinery ---------------------------------------
    # Each streamed operand owns a 2-slot buffer and 2 semaphores; loads
    # are software-pipelined (start t+1, wait t, compute t) so the DMA for
    # the next tile overlaps the current tile's compute. Resident operands
    # hold the full array and read directly.
    _SEM = {"dinv": 0, "a": 2, "b": 4, "ap": 6}
    # rows per buffer slot
    _ALLOC = {k: v // _NSLOT for k, v in plan.tile_rows.items()}
    _BUF = {"dinv": dinv_buf, "a": a_buf, "b": b_buf, "ap": ap_buf}
    _HBM = {"dinv": dinv_hbm, "a": a_hbm, "b": b_hbm, "ap": ap_hbm}

    def _load_copy(name, t, slot):
        rows = _ALLOC[name]
        return pltpu.make_async_copy(
            _HBM[name].at[pl.ds(t * tm, rows), :],
            _BUF[name].at[pl.ds(slot * rows, rows), :],
            sems.at[_SEM[name] + slot],
        )

    def _loader(name):
        """(start, wait) pair for the pipelined loop; None if resident."""
        if res[name]:
            return None
        return (
            lambda t, slot: _load_copy(name, t, slot).start(),
            lambda t, slot: _load_copy(name, t, slot).wait(),
        )

    def _read(name, t, slot, rows):
        """Tile rows of a (possibly resident) operand after its wait.

        The single operand-consumption chokepoint — which is where the
        storage axis lands: operand buffers typed at storage width
        (``build_streamed_solver(storage_dtype=…)``) are upcast
        tile-locally here, so the DMA stream (HBM bytes) stays narrow
        and the VPU arithmetic stays at compute width.
        """
        if res[name]:
            out = _BUF[name][pl.ds(t * tm, rows), :]
        else:
            out = _BUF[name][pl.ds(slot * _ALLOC[name], rows), :]
        return out.astype(dtype) if out.dtype != dtype else out

    def _pipelined(loaders, compute, carry0):
        """fori_loop over tiles with all streamed loads double-buffered."""
        loaders = [ld for ld in loaders if ld is not None]
        for start, _ in loaders:
            start(0, 0)

        def body(t, carry):
            slot = lax.rem(t, _NSLOT)

            @pl.when(t + 1 < n_tiles)
            def _():
                nxt = lax.rem(t + 1, _NSLOT)
                for start, _ in loaders:
                    start(t + 1, nxt)

            for _, wait in loaders:
                wait(t, slot)
            return compute(t, slot, carry)

        return lax.fori_loop(0, n_tiles, body, carry0)

    def _ap_store_copy(t, slot):
        return pltpu.make_async_copy(
            ap_buf.at[pl.ds(slot * tm, tm), :],
            ap_hbm.at[pl.ds(t * tm, tm), :],
            sems.at[_SEM["ap"] + slot],
        )

    # -- one-time initialisation ------------------------------------------
    for name in ("dinv", "a", "b"):
        if res[name]:
            cp = pltpu.make_async_copy(
                _HBM[name], _BUF[name], sems.at[_SEM[name]]
            )
            cp.start()
            cp.wait()

    w_s[...] = jnp.zeros(w_s.shape, dtype)
    p_s[...] = jnp.zeros(p_s.shape, dtype)
    cp = pltpu.make_async_copy(r0_hbm, r_s, sems.at[0])
    cp.start()
    cp.wait()

    def _zr0_tile(t, slot, acc):
        rt = r_s[pl.ds(t * tm, tm), :]
        zt = rt * _read("dinv", t, slot, tm)
        if not res["dinv"]:
            # the all-streamed regime carries z = Dinv·r as its resident
            # state (see the body's z-state branch): convert r0 in place
            r_s[pl.ds(t * tm, tm), :] = zt
        return acc + jnp.sum(zt * rt)

    zr0 = _pipelined(
        [_loader("dinv")], _zr0_tile, jnp.zeros((), dtype)
    ) * h1h2

    # -- the stencil for one tile -----------------------------------------
    def stencil_tile(t, slot):
        """A(p) on tile t in the normalised-difference form, ring/padding
        masked.

        The operands are the *unmasked* h²-normalised coefficients
        (an = a/h1², bw = b/h2²; see build_streamed_solver), so the
        reference's algebraic form (``stage0/Withoutopenmp1.cpp:75-88``)

          ap = an·(pc−pu) + as·(pc−pd) + bw·(pc−pl) + be·(pc−pr)

        costs zero VPU divides per iteration (the divides are hoisted
        into the one-time f64 operand build, same trick as the resident/
        fused engines) and the south/east coefficients come from offset
        slices of the same streamed rows. Unmasked operands are what make
        that slicing valid; interior values are unchanged, and the output
        mask below zeroes the ring/padding exactly as before.

        Row neighbours come from aligned 8-row block loads + value-level
        concats: Mosaic requires dynamic VMEM loads at sublane multiples,
        so a tile shifted by one row is not directly loadable.
        """
        pc = p_s[pl.ds(_BAND + t * tm, tm), :]
        p_above = p_s[pl.ds(_BAND + t * tm - 8, 8), :]
        p_below = p_s[pl.ds(_BAND + (t + 1) * tm, 8), :]
        pu = jnp.concatenate([p_above[7:8, :], pc[:-1]], axis=0)
        pd = jnp.concatenate([pc[1:], p_below[0:1, :]], axis=0)
        aw = _read("a", t, slot, tm + 1)
        anc = aw[0:tm, :]          # an rows of the tile (north)
        ans = aw[1 : tm + 1, :]    # an rows shifted one down = as (south)
        bwc = _read("b", t, slot, tm)
        bec = _shift_cols_left(bwc)
        pl_ = _shift_cols_right(pc)
        pr = _shift_cols_left(pc)
        ax = anc * (pc - pu) + ans * (pc - pd)
        ay = bwc * (pc - pl_) + bec * (pc - pr)
        gi = t * tm + lax.broadcasted_iota(jnp.int32, (tm, g2p), 0)
        gj = lax.broadcasted_iota(jnp.int32, (tm, g2p), 1)
        interior = (gi >= 1) & (gi <= M - 1) & (gj >= 1) & (gj <= N - 1)
        apt = jnp.where(interior, ax + ay, jnp.zeros_like(pc))
        return apt, pc

    # -- the while loop ----------------------------------------------------
    carry0 = (
        jnp.asarray(0, jnp.int32), zr0,
        jnp.asarray(0.0, dtype),            # beta
        jnp.asarray(jnp.inf, dtype),        # diff
        jnp.asarray(False), jnp.asarray(False),
    )

    def cond(c):
        k, _zr, _b, _d, conv, bd = c
        return (k < max_iter) & ~conv & ~bd

    def body(c):
        k, zr, beta, diff, _cv, _bd = c

        def p_update(t, dv=None):
            # p <- z + beta*p on tile t; in the r-state regime z is formed
            # on the fly as r·Dinv (dv = that tile's dinv rows), in the
            # z-state regime the state array already holds z (dv=None)
            rows = pl.ds(_BAND + t * tm, tm)
            zt = r_s[pl.ds(t * tm, tm), :]
            if dv is not None:
                zt = zt * dv
            p_s[rows, :] = zt + beta * p_s[rows, :]

        def store_ap(t, slot, apt):
            # Streamed ap stores lag two tiles behind (same slot), so a
            # slot is only rewritten after its previous store has drained.
            if res["ap"]:
                ap_buf[pl.ds(t * tm, tm), :] = apt
            else:
                @pl.when(t >= _NSLOT)
                def _():
                    _ap_store_copy(t - _NSLOT, slot).wait()

                ap_buf[pl.ds(slot * tm, tm), :] = apt
                _ap_store_copy(t, slot).start()

        def drain_ap_stores():
            if not res["ap"]:
                # trailing stores (n_tiles is static: unrolls)
                for t_tail in range(max(n_tiles - _NSLOT, 0), n_tiles):
                    _ap_store_copy(t_tail, t_tail % _NSLOT).wait()

        # Fused passes A+B in ONE sweep on a one-tile lag: step t updates
        # p on tile t+1 then applies the stencil to tile t, whose
        # row-neighbour reads touch only tiles t-1..t+1 — all already
        # updated. The per-tile arithmetic and accumulation order are
        # identical to separate A-then-B sweeps (bitwise-same results);
        # what changes is one fewer walk of the VMEM-resident state and
        # one fewer DMA pipeline drain per iteration.
        #
        # The state-array regime decides what the p-update reads: with
        # dinv resident the state is r and z is formed on the fly
        # (dv_at(t)); in the streamed-dinv z-state regime the state
        # already holds z (dv_at is None) — see pass C below.
        dv_at = (
            (lambda t: _BUF["dinv"][pl.ds(t * tm, tm), :])
            if res["dinv"]
            else (lambda t: None)
        )
        p_update(0, dv_at(0))

        def pass_ab(t, slot, acc):
            @pl.when(t + 1 < n_tiles)
            def _():
                p_update(t + 1, dv_at(t + 1))

            apt, pc = stencil_tile(t, slot)
            store_ap(t, slot, apt)
            return acc + jnp.sum(apt * pc)

        denom = _pipelined(
            [_loader("a"), _loader("b")],
            pass_ab, jnp.zeros((), dtype),
        ) * h1h2
        drain_ap_stores()

        breakdown = denom < DENOM_GUARD
        alpha = zr / jnp.where(breakdown, jnp.ones_like(denom), denom)
        alpha = jnp.where(breakdown, jnp.zeros_like(alpha), alpha)

        if res["dinv"]:
            # -- r-state pass C: fused updates + both reductions (dinv
            # reads are free — it is VMEM-resident)
            def pass_c(t, slot, acc):
                dw2a, zra = acc
                rows = pl.ds(t * tm, tm)
                w = w_s[rows, :]
                w_new = w + alpha * p_s[pl.ds(_BAND + t * tm, tm), :]
                dw = w_new - w
                w_s[rows, :] = w_new
                r_new = r_s[rows, :] - alpha * _read("ap", t, slot, tm)
                r_s[rows, :] = r_new
                return (
                    dw2a + jnp.sum(dw * dw),
                    zra + jnp.sum((r_new * dv_at(t)) * r_new),
                )

            c_loaders = [_loader("ap")]
        else:
            # -- streamed-dinv z-state pass C. The resident state array
            # carries z = Dinv·r instead of r (converted at init —
            # ``_zr0_tile``), so the AB p-update above needed NO operand
            # stream, and here
            #   z <- z − alpha·(Dinv·ap) and the next inner product
            #   Σ z·r = Σ z²·(1/Dinv)
            # both come off the ONE dinv stream (the guarded per-element
            # reciprocal costs VPU divides, but pass C is bandwidth-bound
            # with slack). One dinv pass and one pipeline drain fewer per
            # iteration than the r-state form (6.06 -> 5.06 passes at
            # 2400x3200). The per-element z evolution rounds differently
            # from (r − alpha·ap)·Dinv, but — unlike the scalar zr
            # recurrence of pipelined-CG, which drifts the convergence
            # sequence — it preserves the published iteration-count
            # oracles exactly (176 @ 200x132, 546 @ 400x600 verified
            # elementwise on the host; 2449 @ 2400x3200 asserted by the
            # bench on hardware).
            def pass_c(t, slot, acc):
                dw2a, zra = acc
                rows = pl.ds(t * tm, tm)
                w = w_s[rows, :]
                w_new = w + alpha * p_s[pl.ds(_BAND + t * tm, tm), :]
                dw = w_new - w
                w_s[rows, :] = w_new
                dvt = _read("dinv", t, slot, tm)
                z_new = r_s[rows, :] - alpha * (
                    dvt * _read("ap", t, slot, tm)
                )
                r_s[rows, :] = z_new
                # guarded reciprocal: d = 1/Dinv on the interior, 0 off it
                dt = jnp.where(
                    dvt != 0.0,
                    1.0 / jnp.where(dvt != 0.0, dvt, jnp.ones_like(dvt)),
                    jnp.zeros_like(dvt),
                )
                return (
                    dw2a + jnp.sum(dw * dw),
                    zra + jnp.sum((z_new * z_new) * dt),
                )

            c_loaders = [_loader("ap"), _loader("dinv")]

        dw2, zr_raw = _pipelined(
            c_loaders, pass_c,
            (jnp.zeros((), dtype), jnp.zeros((), dtype)),
        )
        zr_new = zr_raw * h1h2

        ndiff = jnp.sqrt(dw2 * h1h2) if weighted else jnp.sqrt(dw2)
        conv = ~breakdown & (ndiff < delta)
        ndiff = jnp.where(breakdown, diff, ndiff)
        beta_new = jnp.where(breakdown, beta, zr_new / zr)
        zr_out = jnp.where(breakdown, zr, zr_new)
        return (k + 1, zr_out, beta_new, ndiff, conv, breakdown)

    out = lax.while_loop(cond, body, carry0)

    cp = pltpu.make_async_copy(w_s, w_out, sems.at[0])
    cp.start()
    cp.wait()
    iters_out[0] = out[0]
    diff_out[0] = out[3]
    flags_out[0] = out[4].astype(jnp.int32)
    flags_out[1] = out[5].astype(jnp.int32)


def streamed_operand_set(problem: Problem, dtype, g1p: int, g2p: int,
                         geometry=None, theta=None):
    """(dinv, an, bw, r0): f64-assembled, rounded once, zero-padded to
    (g1p, g2p) — the operand fidelity contract shared by the streamed
    and xl engines (one copy; see ``fused_pcg.build_fused_solver``).

    dinv is the guarded 1/D from the f64 diagonal; an/bw are the
    UNMASKED h²-normalised coefficients (identical values at interior
    points to the fused/resident operand set) so the tile stencils'
    south/east offset slices are valid — the in-kernel output mask
    zeroes the ring. ``an`` carries an extra 8 padded rows for the
    stencil's aligned (tm+8)-row DMA windows.
    """
    import numpy as np

    from poisson_ellipse_tpu.ops.fused_pcg import (
        interior_normalized,
        normalized_unmasked,
    )

    np_dtype = np.dtype(jnp.dtype(dtype).name)
    a64, b64, rhs64 = assembly.assemble_numpy(problem, geometry=geometry,
                                              theta=theta)
    dinv64 = interior_normalized(problem, a64, b64)[5]
    anu64, bwu64 = normalized_unmasked(problem, a64, b64)

    def padded(x, extra_rows=0):
        return jnp.asarray(
            np.pad(
                x, ((0, g1p + extra_rows - x.shape[0]), (0, g2p - x.shape[1]))
            ).astype(np_dtype)
        )

    return (padded(dinv64), padded(anu64, 8), padded(bwu64), padded(rhs64))


def build_streamed_solver(problem: Problem, dtype=jnp.float32,
                          interpret=None, tm: int | None = None,
                          geometry=None, theta=None, storage_dtype=None):
    """(jitted whole-solve kernel, args) for large grids.

    args = (dinv, a, b, r0), all f64-assembled and rounded once (same
    operand fidelity as ``fused_pcg.build_fused_solver``).
    tm — row-tile height (see StreamPlan).

    ``storage_dtype`` (``ops.precision``): the state (w, r, p) is
    VMEM-resident here, so the engine's per-iteration HBM traffic IS the
    streamed operand set — a narrow storage dtype stores dinv/a/b at
    that width and the kernel upcasts each tile after its DMA
    (``_read``), cutting the per-iteration bytes by the storage ratio.
    r0 stays at compute width (read once per solve, not per iteration).
    """
    from poisson_ellipse_tpu.ops.precision import resolve_storage_dtype

    if jnp.dtype(dtype).itemsize >= 8:
        raise ValueError("streamed solver supports f32/bf16")
    st = resolve_storage_dtype(storage_dtype, dtype)
    if interpret is None:
        interpret = _interpret_default()
    g1, g2 = problem.node_shape
    # the plan budgets buffers at compute width — conservative under a
    # narrow storage dtype (the operand buffers shrink, never grow)
    plan = StreamPlan(problem, dtype, tm=tm)
    if not plan.fits:
        raise ValueError(
            f"grid {problem.M}x{problem.N}: PCG state (w, r, p) alone "
            "exceeds the VMEM budget — the streamed engine cannot hold "
            "it on-chip; use the xl engine (auto's pick there) or the "
            "sharded solver"
        )
    g1p, g2p, tm = plan.g1p, plan.g2p, plan.tm
    args = streamed_operand_set(problem, dtype, g1p, g2p,
                                geometry=geometry, theta=theta)
    if st is not None:
        dinv0, a0, b0, r00 = args
        args = (
            jnp.asarray(dinv0).astype(st), jnp.asarray(a0).astype(st),
            jnp.asarray(b0).astype(st), r00,
        )

    kernel = functools.partial(
        _mega_kernel, problem, plan, problem.norm == "weighted"
    )
    anyspec = lambda: pl.BlockSpec(memory_space=pl.ANY)
    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)
    res = plan.resident
    # resident operands hold the full padded array; streamed ones get a
    # 2-slot double buffer — row counts come from the plan (one source).
    # Operand buffers match the (possibly narrow) storage width; ap is
    # iteration state and stays at compute width.
    buf = lambda name: pltpu.VMEM(
        ((plan.full_rows if res[name] else plan.tile_rows)[name], g2p),
        st if (st is not None and name in ("dinv", "a", "b")) else dtype,
    )
    call = pl.pallas_call(
        kernel,
        in_specs=[anyspec()] * 4,
        out_specs=(anyspec(), smem(), smem(), smem(), anyspec()),
        out_shape=(
            jax.ShapeDtypeStruct((g1p, g2p), dtype),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((1,), dtype),
            jax.ShapeDtypeStruct((2,), jnp.int32),
            # HBM scratch for ap when it is not VMEM-resident (an output
            # only because pallas scratch cannot live in HBM)
            jax.ShapeDtypeStruct(
                (8, g2p) if res["ap"] else (g1p, g2p), dtype
            ),
        ),
        scratch_shapes=[
            pltpu.VMEM((g1p, g2p), dtype),             # w
            pltpu.VMEM((g1p, g2p), dtype),             # r (z when streamed)
            pltpu.VMEM((g1p + 2 * _BAND, g2p), dtype),  # p with bands
            buf("dinv"),
            buf("a"),
            buf("b"),
            buf("ap"),
            pltpu.SemaphoreType.DMA((8,)),
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=scaled_vmem_budget(_VMEM_LIMIT)
        ),
        interpret=interpret,
    )

    def solver(dinv, a, b, r0):
        w_pad, iters, diff, flags, _ap = call(dinv, a, b, r0)
        return PCGResult(
            w=w_pad[:g1, :g2],
            iters=iters[0],
            diff=diff[0],
            converged=flags[0].astype(bool),
            breakdown=flags[1].astype(bool),
        )

    # no donation: build-once-call-many — callers re-feed these operands
    # every dispatch (bench --repeat protocol)
    # tpulint: disable=TPU004
    return jax.jit(solver), args


def solve_streamed(problem: Problem, dtype=jnp.float32,
                   interpret=None) -> PCGResult:
    solver, args = build_streamed_solver(problem, dtype, interpret=interpret)
    return solver(*args)
