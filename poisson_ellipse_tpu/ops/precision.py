"""Storage-vs-compute precision policy: bf16 state in HBM, f32 math.

BENCH_r05 put the streamed engines at 82% of HBM peak at 2400×3200 —
per-iteration wall clock there is *bytes moved*, and every iterate (w,
r, p, z, …) plus the streamed operands (a, b, D) crosses HBM once or
more per iteration. Halving the width of everything that streams halves
the iteration's byte bill; the catch is that CG's recurrences are not
stable in bf16 arithmetic. The contract this module names is therefore
**storage ≠ compute**:

- arrays *live* in ``storage_dtype`` (bf16: 8-bit exponent — same
  dynamic range as f32, 8 mantissa bits) in HBM,
- every stencil application, axpy and reduction *upcasts to the compute
  dtype first* (tile-locally: XLA fuses the ``convert_element_type``
  into the consumer, so HBM reads stay storage-width; the Pallas mixed
  kernels do the same upcast explicitly in VMEM), and accumulates in
  compute precision,
- results are rounded back to storage width on store.

Accuracy is then *recovered, not hoped for*: the storage rounding floor
(~bf16 eps per store) is answered by (a) a tightened residual-
replacement cadence (:func:`replace_every`) for the recurrence engines,
(b) the guard's escalation ladder growing a ``bf16 → f32`` rung below
the existing ``f32 → f64`` one, and (c) a storage *promotion* on
convergence — a solve that stops inside bf16's floor is re-anchored and
polished at full compute width before the guard will return it
(``resilience.guard``), so the returned iterate meets the same final
true-residual gate as a full-precision run. The ABFT shadow recurrences
(``resilience.abft``) double as the low-precision drift alarm: their
rtol is keyed on the *effective* (storage) itemsize via
:func:`effective_dtype`.

``storage_dtype=None`` everywhere means "storage == compute": the
traced computation is byte-identical to the pre-storage-axis code
(jaxpr-pinned in ``tests/test_sstep.py``).
"""

from __future__ import annotations

import jax.numpy as jnp

# storage dtypes the axis accepts: half-width floats (the point), plus
# the identity widths so `--storage-dtype f32` is expressible
STORAGE_DTYPES = ("bf16", "f16", "f32", "f64")

_NAMES = {
    "bf16": jnp.bfloat16,
    "f16": jnp.float16,
    "f32": jnp.float32,
    # "f64" only names the identity storage width for f64-compute runs;
    # resolve_storage_dtype rejects any storage WIDER than compute, so a
    # silent downcast cannot hide in this table entry
    "f64": jnp.float64,  # tpulint: disable=TPU001
}


def resolve_storage_dtype(storage_dtype, compute_dtype):
    """Normalise a storage-dtype request against the compute dtype.

    Accepts a name ("bf16"), a dtype, or None. Returns a jnp dtype or
    None — None meaning "storage == compute", which every consumer
    treats as the exact pre-storage-axis code path. A storage dtype
    *wider* than compute is refused: the axis exists to shrink HBM
    bytes, and silently computing in less precision than the state is
    stored at would invert the accuracy contract.
    """
    if storage_dtype is None:
        return None
    if isinstance(storage_dtype, str):
        if storage_dtype in _NAMES:
            storage_dtype = _NAMES[storage_dtype]
        else:
            try:  # canonical dtype names ("bfloat16", "float16", …)
                storage_dtype = jnp.dtype(storage_dtype)
            except TypeError:
                raise ValueError(
                    f"unknown storage dtype {storage_dtype!r} "
                    f"(choose from {', '.join(STORAGE_DTYPES)})"
                ) from None
    st = jnp.dtype(storage_dtype)
    if not jnp.issubdtype(st, jnp.floating):
        raise ValueError(
            f"storage dtype must be floating, got {st.name}"
        )
    st = jnp.dtype(storage_dtype)
    ct = jnp.dtype(compute_dtype)
    if st == ct:
        return None
    if st.itemsize > ct.itemsize:
        raise ValueError(
            f"storage dtype {st.name} is wider than compute dtype "
            f"{ct.name}; storage exists to shrink HBM traffic — widen "
            "the compute dtype instead"
        )
    return jnp.dtype(st)


def store(x, storage_dtype):
    """Round to storage width (identity when storage is None)."""
    return x if storage_dtype is None else x.astype(storage_dtype)


def load(x, compute_dtype, storage_dtype):
    """Upcast a stored array to compute width (identity when None).

    The upcast is free on the HBM side: XLA fuses the convert into the
    consuming op, so the array is read at storage width and widened in
    registers/VMEM — the tile-local upcast the Pallas mixed kernels
    spell explicitly.
    """
    return x if storage_dtype is None else x.astype(compute_dtype)


def effective_dtype(compute_dtype, storage_dtype):
    """The dtype whose rounding floor governs the solve's drift — the
    storage dtype when one is set (every store rounds there), else the
    compute dtype. ABFT rtols and replacement cadences key on this.
    Accepts the short storage names ("bf16") as well as dtypes."""
    st = resolve_storage_dtype(storage_dtype, compute_dtype)
    return compute_dtype if st is None else st


def replace_every(storage_dtype=None, compute_dtype=jnp.float32) -> int:
    """Residual-replacement cadence (iterations) for the recurrence
    engines (pipelined, s-step).

    f32 storage drifts at ~2⁻²⁴/store and 32 iterations between
    ground-truth rebuilds bounds it (the measured
    ``ops.pipelined_pcg.REPLACE_EVERY`` fact); bf16/f16 storage rounds
    at ~2⁻⁸ per store, so the cadence tightens 4× — 8 iterations —
    keeping the recurrence-vs-truth gap in the same relative band.
    Both values divide the s-step block sizes (s ∈ {2, 4}), so a
    replacement always lands on a block boundary.
    """
    eff = jnp.dtype(effective_dtype(compute_dtype, storage_dtype))
    return 8 if eff.itemsize <= 2 else 32


def storage_itemsize(compute_dtype, storage_dtype=None) -> int:
    """Bytes per element as actually stored in HBM."""
    return jnp.dtype(effective_dtype(compute_dtype, storage_dtype)).itemsize
