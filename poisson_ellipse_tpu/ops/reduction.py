"""Grid-weighted reductions (reference layer L3, reduction side).

The reference's ``dot`` is the h1·h2-weighted inner product over interior
nodes (``stage0/Withoutopenmp1.cpp:64-72``); its CUDA form produces 32768
partial sums that are finished on the host (``poisson_mpi_cuda2.cu:574-598``,
``:779-785``). On TPU the whole reduction is one fused on-device ``jnp.sum``
— no partials, no host.

All iterate arrays (w, r, z, p) are maintained exactly zero outside the
interior, so summing the full array equals the interior sum while keeping
the reduction a single dense XLA op (better for the VPU than masked slices).
"""

from __future__ import annotations

import jax.numpy as jnp


def grid_dot(u, v, h1, h2):
    """(u, v) = h1·h2 · Σ u_ij v_ij (interior; arrays are zero elsewhere)."""
    return jnp.sum(u * v) * h1 * h2


def grid_sumsq(u):
    """Unweighted Σ u²  — used by the stage0 convergence-norm convention."""
    return jnp.sum(u * u)


def grid_dots(*pairs):
    """All Σ uᵢ·vᵢ of ``pairs`` as one stacked (k,) reduction.

    The fusion idiom shared by the single-chip and sharded loops: every
    inner product an iteration needs is emitted from ONE pass over the
    operands (XLA fuses the k elementwise products and row reductions
    into a single loop nest), and — decisive on the mesh — the stacked
    result is what rides a single ``lax.psum`` instead of k collectives
    (``parallel.pcg_sharded`` stacks by hand; this is that idiom named).
    Sums are raw (unweighted); callers apply their h1·h2 weights to the
    entries that want them, exactly as ``grid_dot`` would have.
    """
    return jnp.stack([jnp.sum(u * v) for u, v in pairs])
