"""Fused-iteration PCG: the whole loop body as two Pallas kernels.

The reference's stage4 runs six CUDA kernels + six device syncs + three
host round-trips per PCG iteration (``poisson_mpi_cuda2.cu:846-939``).
Measurements on the bench chip show the XLA while_loop path is
*overhead/compute*-bound, not HBM-bound (achieved streaming bandwidth is
~7 TB/s while one XLA iteration costs 40-480 us across the reference
grids — far above the pure-traffic bound), so the fusion targets are
kernel-count and per-element VPU work. One iteration is:

  K1  p = z + beta*p;  ap = A(p);  denom-partial      (one kernel)
  K2  alpha; w += alpha*p; r -= alpha*ap; ||dw||^2;
      z = r/D; (z,r)-partial                          (one kernel)
  +   one scalar fusion (beta, diff, convergence)

i.e. 3 launches/iteration vs the ~8 fusions XLA emits for the unfused
body — exactly the ``apply_A+dot`` / ``update_w_r+norm`` fusion SURVEY
section 7 step 6 calls for, plus the p-update folded into the stencil
(legal because the loop is rotated: beta is applied at the *start* of
the next body, which computes the same value sequence as the reference
order, ``stage0/Withoutopenmp1.cpp:124-169``).

Two loop-invariant rewrites keep the kernels off the VPU's slow paths —
both verified to preserve the published iteration-count oracles
(546/989/1858/2449) in f32 on hardware:

- the stencil runs in normalised form  ap = D*p - (an*p_up + as*p_dn +
  bw*p_lf + be*p_rt)  with the four shifted neighbour coefficients
  pre-divided by h^2 and pre-masked to the interior, so the kernel has
  zero divisions and zero mask logic (the reference bakes the same
  algebra into its per-iteration kernel, ``poisson_mpi_cuda2.cu:507-536``);
- the preconditioner is a multiply by a precomputed 1/D (guarded where
  D = 0), not an in-loop divide.

Layout: all state rides padded to (g1p, g2p) = (row-tile multiple, lane
multiple). Padding and ring carry zero coefficients, so every iterate
stays exactly zero there (same invariant as ``parallel.mesh.padded_dims``).

Row halos for the stencil come from extra ``BlockSpec``s of the same
operand: a (tm, lanes) mid block plus (8, lanes) neighbour blocks whose
index maps point one 8-row block before/after — overlapping windows are
inexpressible in a single BlockSpec, but two narrow extra specs give the
halo rows through the normal double-buffered pipeline (no manual DMA, no
alignment pads; this replaces round 1's serial make_async_copy windows,
which is why this stencil pipelines and that one did not).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs.convergence import (
    history_init,
    history_record,
    trace_of,
)
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.stencil import diag_d
from poisson_ellipse_tpu.solver.pcg import DENOM_GUARD, PCGResult

# VMEM working-set budget for one kernel's live blocks (x2 for the
# pipeline's double buffering). The chip exposes ~15 MB usable.
_VMEM_BUDGET = 11 * 1024 * 1024


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _pick_tile(g1: int, g2p: int, itemsize: int, n_buffers: int) -> int:
    """Row tile: multiple of 8, sized so n_buffers double-buffered blocks
    fit the VMEM budget (the 8-row halo specs are counted separately)."""
    per_row = g2p * itemsize * n_buffers * 2
    tm = max((_VMEM_BUDGET // max(per_row, 1)) // 8 * 8, 8)
    return min(tm, max(_round_up(g1, 8), 8), 512)


def _shift_down(mid, up_row):
    """Rows r0-1 .. r0+tm-2: predecessor of each row."""
    return jnp.concatenate([up_row, mid[:-1]], axis=0)


def _shift_up(mid, down_row):
    """Rows r0+1 .. r0+tm: successor of each row."""
    return jnp.concatenate([mid[1:], down_row], axis=0)


def _shift_left(x):
    """Column j-1 with a zero at j=0 (the Dirichlet ring is zero)."""
    zero = jnp.zeros((x.shape[0], 1), x.dtype)
    return jnp.concatenate([zero, x[:, :-1]], axis=1)


def _shift_right(x):
    """Column j+1 with a zero at the last (padded) column."""
    zero = jnp.zeros((x.shape[0], 1), x.dtype)
    return jnp.concatenate([x[:, 1:], zero], axis=1)


def _k1_kernel(n_tiles,
               beta_ref,
               z_up, z_mid, z_dn, p_up, p_mid, p_dn,
               an_mid, as_mid, bw_mid, be_mid, d_mid,
               pn_out, ap_out, denom_out, acc):
    """p = z + beta*p, ap = A(p), denominator partial — one row tile.

    The neighbour coefficients are pre-masked to the interior, so the
    clamped-garbage halo rows at the first/last tile are multiplied by
    exact zeros and the ring/padding output is exactly zero with no
    in-kernel masking.
    """
    i = pl.program_id(0)
    beta = beta_ref[0]
    pn = z_mid[:] + beta * p_mid[:]
    # halo rows of the *updated* p, built from the neighbour specs
    pn_row_up = z_up[7:8, :] + beta * p_up[7:8, :]
    pn_row_dn = z_dn[0:1, :] + beta * p_dn[0:1, :]

    ap = d_mid[:] * pn - (
        an_mid[:] * _shift_down(pn, pn_row_up)
        + as_mid[:] * _shift_up(pn, pn_row_dn)
        + bw_mid[:] * _shift_left(pn)
        + be_mid[:] * _shift_right(pn)
    )

    pn_out[:] = pn
    ap_out[:] = ap

    @pl.when(i == 0)
    def _():
        acc[0] = jnp.zeros((), pn.dtype)

    acc[0] += jnp.sum(ap * pn)

    @pl.when(i == n_tiles - 1)
    def _():
        denom_out[0] = acc[0]


def _k2_kernel(n_tiles,
               zr_ref, denom_ref,
               w_mid, r_mid, p_mid, ap_mid, dinv_mid,
               w_out, r_out, z_out, sums_out, acc):
    """alpha; w/r update; ||dw||^2 and (z,r) partials — one row tile.

    alpha is derived in-kernel from the (zr, denom) scalars so no extra
    scalar kernel sits between K1 and K2; on breakdown (denom under the
    reference's 1e-15 guard, ``stage0/Withoutopenmp1.cpp:128``) alpha is
    forced to 0, which holds w/r exactly (the reference exits before
    touching them).
    """
    i = pl.program_id(0)
    denom = denom_ref[0]
    breakdown = denom < DENOM_GUARD
    alpha = zr_ref[0] / jnp.where(breakdown, jnp.ones_like(denom), denom)
    alpha = jnp.where(breakdown, jnp.zeros_like(alpha), alpha)

    w = w_mid[:]
    w_new = w + alpha * p_mid[:]
    r_new = r_mid[:] - alpha * ap_mid[:]
    z = r_new * dinv_mid[:]
    # realised increment (w_new - w), not alpha*p: the convergence oracle
    # counts depend on the FP difference (poisson_mpi_cuda2.cu:626-660)
    dw = w_new - w

    w_out[:] = w_new
    r_out[:] = r_new
    z_out[:] = z

    @pl.when(i == 0)
    def _():
        acc[0] = jnp.zeros((), w.dtype)
        acc[1] = jnp.zeros((), w.dtype)

    acc[0] += jnp.sum(z * r_new)
    acc[1] += jnp.sum(dw * dw)

    @pl.when(i == n_tiles - 1)
    def _():
        sums_out[0] = acc[0]
        sums_out[1] = acc[1]


class _FusedKernels(NamedTuple):
    k1: callable
    k2: callable
    g1p: int
    g2p: int


def build_kernels(problem: Problem, g1: int, g2: int, dtype,
                  interpret=None) -> _FusedKernels:
    """Compile-ready K1/K2 closures for one grid size."""
    if interpret is None:
        interpret = _interpret_default()
    itemsize = jnp.dtype(dtype).itemsize
    g2p = _round_up(g2, 128)
    # K1 holds ~13 live (tm, g2p) blocks, K2 ~9; size for the larger set
    tm = _pick_tile(g1, g2p, itemsize, 13)
    g1p = _round_up(g1, tm)
    n_tiles = g1p // tm
    nb = max(g1p // 8 - 1, 0)  # last valid 8-row block index

    mid = lambda: pl.BlockSpec((tm, g2p), lambda i: (i, 0))
    c = tm // 8  # 8-row blocks per tile

    def up_map(i):
        return (jnp.maximum(i * c - 1, 0), 0)

    def dn_map(i):
        return (jnp.minimum((i + 1) * c, nb), 0)

    up = lambda: pl.BlockSpec((8, g2p), up_map)
    dn = lambda: pl.BlockSpec((8, g2p), dn_map)
    smem_in = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)

    k1 = pl.pallas_call(
        functools.partial(_k1_kernel, n_tiles),
        grid=(n_tiles,),
        in_specs=[smem_in(), up(), mid(), dn(), up(), mid(), dn(),
                  mid(), mid(), mid(), mid(), mid()],
        out_specs=(mid(), mid(), pl.BlockSpec(memory_space=pltpu.SMEM)),
        out_shape=(
            jax.ShapeDtypeStruct((g1p, g2p), dtype),
            jax.ShapeDtypeStruct((g1p, g2p), dtype),
            jax.ShapeDtypeStruct((1,), dtype),
        ),
        scratch_shapes=[pltpu.SMEM((1,), dtype)],
        interpret=interpret,
    )

    k2 = pl.pallas_call(
        functools.partial(_k2_kernel, n_tiles),
        grid=(n_tiles,),
        in_specs=[smem_in(), smem_in(),
                  mid(), mid(), mid(), mid(), mid()],
        out_specs=(mid(), mid(), mid(),
                   pl.BlockSpec(memory_space=pltpu.SMEM)),
        out_shape=(
            jax.ShapeDtypeStruct((g1p, g2p), dtype),
            jax.ShapeDtypeStruct((g1p, g2p), dtype),
            jax.ShapeDtypeStruct((g1p, g2p), dtype),
            jax.ShapeDtypeStruct((2,), dtype),
        ),
        scratch_shapes=[pltpu.SMEM((2,), dtype)],
        interpret=interpret,
    )

    def call_k1(beta, z, p, an, as_, bw, be, d):
        return k1(jnp.reshape(beta, (1,)), z, z, z, p, p, p,
                  an, as_, bw, be, d)

    def call_k2(zr, denom, w, r, p, ap, dinv):
        return k2(
            jnp.reshape(zr, (1,)), jnp.reshape(denom, (1,)),
            w, r, p, ap, dinv,
        )

    return _FusedKernels(k1=call_k1, k2=call_k2, g1p=g1p, g2p=g2p)


def _pad(x, g1p, g2p):
    return jnp.pad(x, ((0, g1p - x.shape[0]), (0, g2p - x.shape[1])))


def normalized_coefficients(problem: Problem, a, b, g1p: int, g2p: int,
                            dtype=None):
    """The loop-invariant operand set of the fused iteration.

    Returns (an, as_, bw, be, d, dinv), each (g1p, g2p):
      an_ij = a_ij / h1^2        ("north", multiplies p_{i-1,j})
      as_ij = a_{i+1,j} / h1^2   ("south", multiplies p_{i+1,j})
      bw_ij = b_ij / h2^2        ("west",  multiplies p_{i,j-1})
      be_ij = b_{i,j+1} / h2^2   ("east",  multiplies p_{i,j+1})
      d     = an + as_ + bw + be  (the operator diagonal, = diag_d)
      dinv  = 1/d where d != 0 else 0
    all masked to the interior 1..M-1 x 1..N-1, so the stencil
      ap = d*p - (an*p_up + as*p_dn + bw*p_lf + be*p_rt)
    is exactly zero on the ring/padding with no runtime masking.

    The divisions/sums happen in the *input* precision: pass f64 numpy
    a/b (``assembly.assemble_numpy``) with ``dtype=f32`` to get
    coefficients rounded once from the reference's double-precision
    values — the closest f32 can sit to the reference operator, and what
    keeps the iteration-count oracles exact. Jax-array (traced) inputs
    are supported too and computed in their own dtype.
    """
    if dtype is None:
        dtype = a.dtype
    pieces = interior_normalized(problem, a, b)
    import numpy as np

    xp = np if isinstance(a, np.ndarray) else jnp
    g1, g2 = a.shape
    pad = ((0, g1p - g1), (0, g2p - g2))
    return tuple(
        jnp.asarray(xp.pad(x, pad).astype(dtype)) for x in pieces
    )


def normalized_unmasked(problem: Problem, a, b):
    """(an, bw) = (a/h1², b/h2²) over the full grid, unmasked, in the
    input precision — the one place the 1/h² hoisting algebra lives.
    ``interior_normalized`` builds the masked operand set from these; the
    streamed engine uses them directly (its south/east coefficients are
    offset slices, which only works unmasked)."""
    ih1 = 1.0 / (float(problem.h1) * float(problem.h1))
    ih2 = 1.0 / (float(problem.h2) * float(problem.h2))
    return a * ih1, b * ih2


def interior_normalized(problem: Problem, a, b):
    """(an, as_, bw, be, d, dinv) in the *input* precision, unpadded.

    The single source of the normalised/guarded operand algebra — the
    streamed engine reuses the ``dinv`` element so the two "value
    identical" paths cannot drift (they share the code, not a copy).
    """
    import numpy as np

    xp = np if isinstance(a, np.ndarray) else jnp
    g1, g2 = a.shape
    an, bw = normalized_unmasked(problem, a, b)
    as_ = xp.roll(an, -1, axis=0)
    be = xp.roll(bw, -1, axis=1)
    gi = xp.arange(g1)[:, None]
    gj = xp.arange(g2)[None, :]
    interior = (
        (gi >= 1) & (gi <= problem.M - 1) & (gj >= 1) & (gj <= problem.N - 1)
    )
    z = xp.zeros((), an.dtype)
    an, as_, bw, be = (
        xp.where(interior, x, z) for x in (an, as_, bw, be)
    )
    d = an + as_ + bw + be
    dinv = xp.where(d != 0.0, 1.0 / xp.where(d != 0.0, d, 1.0), z)
    return an, as_, bw, be, d, dinv


def fused_operands(problem: Problem, g1p: int, g2p: int, dtype,
                   geometry=None, theta=None):
    """Device-ready loop-invariant operands, rounded once from the f64
    host assembly (the oracle-exact path; see normalized_coefficients).
    ``geometry``/``theta`` select the SDF quadrature assembly."""
    import numpy as np

    a64, b64, _ = assembly.assemble_numpy(problem, geometry=geometry,
                                          theta=theta)
    np_dtype = np.dtype(jnp.dtype(dtype).name)
    return normalized_coefficients(problem, a64, b64, g1p, g2p, np_dtype)


def rotated_state0(w0, r0, z0, p0, zr0, dtype):
    """Iteration-0 carry of the rotated fused loop — the one layout
    (k, w, r, z, p, zr, beta, diff, converged, breakdown) shared by the
    single-chip engine and ``parallel.fused_sharded`` (beta0 = 0 makes
    the first K1 produce p1 = z0, the reference's initial direction)."""
    return (
        jnp.asarray(0, jnp.int32),
        w0,
        r0,
        z0,
        p0,
        zr0,
        jnp.asarray(0.0, dtype),        # beta
        jnp.asarray(jnp.inf, dtype),    # diff
        jnp.asarray(False),
        jnp.asarray(False),
    )


def rotated_cond(max_iter):
    """while_loop predicate over the ``rotated_state0`` carry layout."""

    def cond(s):
        k = s[0]
        converged, breakdown = s[8], s[9]
        return (k < max_iter) & ~converged & ~breakdown

    return cond


def rotated_next_state(s, pn, w_new, r_new, z_new, zr_new, dw2,
                       breakdown, h1, h2, delta, weighted):
    """Scalar tail of one rotated iteration: the convergence test, the
    breakdown holds (zr/beta frozen so the exit state matches the
    reference's early return) and the next beta — one copy of the carry
    algebra shared by the single-chip and sharded fused engines."""
    k = s[0]
    zr, beta, diff = s[5], s[6], s[7]
    ndiff = jnp.sqrt(dw2 * h1 * h2) if weighted else jnp.sqrt(dw2)
    converged = ~breakdown & (ndiff < delta)
    ndiff = jnp.where(breakdown, diff, ndiff)
    beta_new = zr_new / jnp.where(breakdown, jnp.ones_like(zr), zr)
    return (
        k + 1, w_new, r_new, z_new, pn,
        jnp.where(breakdown, zr, zr_new),
        jnp.where(breakdown, beta, beta_new),
        ndiff, converged, breakdown,
    )


def _run_fused(problem: Problem, kern: _FusedKernels, coeffs, r0,
               g1: int, g2: int, history: bool = False):
    """The rotated while_loop given prebuilt kernels + operand set.

    ``history=True`` appends the four ``obs.convergence`` buffers to the
    rotated carry and records each iteration's (zr, diff, α, β) at the
    XLA level, outside the Pallas kernels — α re-derives K2's in-kernel
    value from the same (zr, denom) scalars and expression, so the trace
    matches what the kernel applied; returns (PCGResult, trace).
    """
    dtype = r0.dtype
    g1p, g2p = kern.g1p, kern.g2p
    an, as_, bw, be, d_p, dinv_p = coeffs

    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    delta = jnp.asarray(problem.delta, dtype)
    weighted = problem.norm == "weighted"

    z0 = r0 * dinv_p
    zr0 = jnp.sum(z0 * r0) * h1 * h2
    state0 = rotated_state0(
        jnp.zeros((g1p, g2p), dtype), r0, z0,
        jnp.zeros((g1p, g2p), dtype), zr0, dtype,
    )
    if history:
        state0 = state0 + history_init(problem.max_iterations, dtype)

    def body(s):
        k, w, r, z, p, zr, beta, _diff, _c, _bd = s[:10]
        pn, ap, denom_raw = kern.k1(beta, z, p, an, as_, bw, be, d_p)
        denom = denom_raw[0] * h1 * h2
        breakdown = denom < DENOM_GUARD
        w_new, r_new, z_new, sums = kern.k2(zr, denom, w, r, pn, ap, dinv_p)
        zr_new = sums[0] * h1 * h2
        out = rotated_next_state(
            s[:10], pn, w_new, r_new, z_new, zr_new, sums[1],
            breakdown, h1, h2, delta, weighted,
        )
        if history:
            # K2's guarded α, re-derived from the same scalars it read
            alpha = zr / jnp.where(breakdown, jnp.ones_like(denom), denom)
            alpha = jnp.where(breakdown, jnp.zeros_like(alpha), alpha)
            beta_new = zr_new / jnp.where(breakdown, jnp.ones_like(zr), zr)
            out = out + history_record(s[10:], k, zr_new, out[7], alpha, beta_new)
        return out

    out = lax.while_loop(
        rotated_cond(problem.max_iterations), body, state0
    )
    k, w = out[0], out[1]
    diff, converged, breakdown = out[7], out[8], out[9]
    result = PCGResult(
        w=w[:g1, :g2], iters=k, diff=diff,
        converged=converged, breakdown=breakdown,
    )
    if history:
        return result, trace_of(out[10:], k)
    return result


def pcg_fused(problem: Problem, a, b, rhs, interpret=None,
              history: bool = False):
    """PCG with the fused two-kernel iteration. Same value *sequence* as
    ``solver.pcg.pcg`` (reference order, rotated) up to the documented
    normalised-stencil rewrite. Jit-safe with traced a/b/rhs; the
    coefficient normalisation then runs in the input dtype — for the
    oracle-exact f64-rounded operand set use ``build_fused_solver``.

    f32/bf16 only (Pallas TPU has no f64 path); callers with f64 inputs
    should use the XLA path.
    """
    dtype = rhs.dtype
    if jnp.dtype(dtype).itemsize >= 8:
        raise ValueError("pcg_fused supports f32/bf16; use stencil='xla' for f64")
    g1, g2 = rhs.shape
    kern = build_kernels(problem, g1, g2, dtype, interpret=interpret)
    coeffs = normalized_coefficients(problem, a, b, kern.g1p, kern.g2p)
    r0 = _pad(rhs, kern.g1p, kern.g2p)
    return _run_fused(problem, kern, coeffs, r0, g1, g2, history=history)


def build_fused_solver(problem: Problem, dtype=jnp.float32, interpret=None,
                       history: bool = False, geometry=None, theta=None):
    """(jitted solver, args) with the f64-rounded operand set.

    The operands (normalised coefficients + RHS) are assembled on the
    host in double precision — exactly the reference's assembly
    (``fictitious_regions_setup_local``, ``poisson_mpi_cuda2.cu:146-192``)
    — and rounded once to the run dtype. This is the bench/CLI fused
    path; it reproduces the published iteration counts in f32.
    """
    import numpy as np

    if jnp.dtype(dtype).itemsize >= 8:
        raise ValueError("fused solver supports f32/bf16; use stencil='xla'")
    g1, g2 = problem.node_shape
    kern = build_kernels(problem, g1, g2, dtype, interpret=interpret)
    coeffs = fused_operands(problem, kern.g1p, kern.g2p, dtype,
                            geometry=geometry, theta=theta)
    _, _, rhs64 = assembly.assemble_numpy(problem, geometry=geometry,
                                          theta=theta)
    np_dtype = np.dtype(jnp.dtype(dtype).name)
    r0 = jnp.asarray(
        np.pad(
            rhs64, ((0, kern.g1p - g1), (0, kern.g2p - g2))
        ).astype(np_dtype)
    )
    args = (*coeffs, r0)

    def solver(an, as_, bw, be, d_p, dinv_p, r0):
        return _run_fused(
            problem, kern, (an, as_, bw, be, d_p, dinv_p), r0, g1, g2,
            history=history,
        )

    # no donation: build-once-call-many — callers re-feed these operands
    # every dispatch (bench --repeat protocol)
    # tpulint: disable=TPU004
    return jax.jit(solver), args


def solve_fused(problem: Problem, dtype=jnp.float32,
                interpret=None) -> PCGResult:
    """Assemble and solve with the fused iteration (single chip)."""
    solver, args = build_fused_solver(problem, dtype, interpret=interpret)
    return solver(*args)
