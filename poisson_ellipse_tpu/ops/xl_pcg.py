"""XL engine: whole-solve kernel for grids whose STATE exceeds VMEM.

``ops.streamed_pcg`` pins the PCG state in VMEM and streams operands;
past ~2400x3200 (f32) the state itself no longer fits and the framework
previously fell back to the XLA while_loop (~13 modelled HBM passes per
iteration, measured ~67% of HBM peak at 4096² — the north-star grid).
This kernel streams EVERYTHING — state and operands — through
double-buffered tile DMA, and restructures the iteration so the traffic
floor is lower than XLA's:

- **z-state form** (as the streamed engine's all-streamed regime): the
  state is (w, z, p) with z = Dinv·r, so the p-update needs no
  preconditioner stream and pass C reads dinv exactly once.
- **deferred w-update**: w += alpha*p is postponed one iteration and
  rides the NEXT AB sweep, where p's tile is already in VMEM for the
  p-update — p is read once per iteration instead of twice, and the
  realised ‖Δw‖² falls out for free. Convergence is therefore detected
  one sweep late (the loop body that *applies* iteration i's update is
  body i+1); the reported iteration count is exact, and the final
  body's extra stencil work is wasted once per solve, not per
  iteration.
- **VMEM ring for the stencil halo**: the updated direction pn is kept
  in a 3-tile ring, so the 5-point stencil's row neighbours come from
  VMEM, never re-read from HBM.

Per iteration, two sweeps (the two PCG scalar sync points set the
floor):

  AB  w += alpha*p_old; ||dw||^2;                 reads  z, p, w, a, b
      pn = z + beta*p_old -> ring + p_hbm;        writes w, p, ap
      ap = A(pn); denom partial
  C   z -= alpha*(Dinv*ap);                       reads  z, dinv, ap
      zr partial = sum(z^2 / Dinv)                writes z

= ~12.08 HBM array-passes/iter vs the XLA loop's ~13, at a higher
achieved fraction of peak. Measured (bench chip, f32): 4096² = 4.22 s
vs 5.16 s XLA (1.22×, 3226 iterations exact, 75.5% of HBM peak);
8192² = 28.7 s / 5889 iterations at 81.3% of peak on ONE chip — a grid
the reference reaches only on a multi-node MPI cluster. All per-element
FP forms are shared with the streamed z-state regime (verified there to
preserve the published iteration-count oracles); reductions are
tile-sequential as in every Pallas engine.

Reference lineage: this is the stage4 decomposition taken to its
single-chip limit — where ``poisson_mpi_cuda2.cu:846-939`` launches six
kernels and ships scalars through the host each iteration, here the
whole solve is ONE kernel launch and the scalars never leave SMEM.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.parallel.compat import tpu_compiler_params
from poisson_ellipse_tpu.ops.streamed_pcg import (
    _VMEM_LIMIT,
    _interpret_default,
    _round_up,
    _shift_cols_left,
    _shift_cols_right,
    streamed_operand_set,
)
from poisson_ellipse_tpu.solver.pcg import DENOM_GUARD, PCGResult
from poisson_ellipse_tpu.utils.device import scaled_vmem_budget

# Candidate row-tile heights for the default policy. Measured at 4096²
# the timings are flat across 64/96/128 (4.28-4.30 s) while 256 is ~3%
# slower and 384 overflows VMEM (the kernel holds ~25 tile slots), so
# the policy just minimises padded rows — at 4097 rows that picks 96
# (g1p = 4128 vs 4224 with 128), 2.3% less streamed work for free.
_TM_CANDIDATES = (64, 96, 128, 256)


class XLPlan:
    """Tiling of the XL solve (no residency choices: everything streams).

    ``dtype`` is accepted for interface parity with ``StreamPlan`` but
    does not influence the tiling: with no residency budget to fill,
    the tile choice is itemsize-independent (the ~16 tile buffers sit
    far below VMEM at every candidate size)."""

    def __init__(self, problem: Problem, dtype, tm: int | None = None):
        g1, g2 = problem.node_shape
        if tm is None:
            # least padded rows; larger tile breaks ties (fewer steps)
            tm = min(_TM_CANDIDATES, key=lambda t: (_round_up(g1, t), -t))
        if tm % 8 or tm < 8:
            raise ValueError(f"tm must be a positive multiple of 8, got {tm}")
        self.g2p = _round_up(g2, 128)
        self.tm = tm if g1 >= tm else _round_up(g1, 8)
        self.g1p = _round_up(g1, self.tm)
        self.n_tiles = self.g1p // self.tm

    def passes_per_iter(self) -> float:
        """Modelled HBM array-passes per iteration (roofline report)."""
        # AB: z r, p r, w r+w, pn w, ap w, a r (+8-row halo), b r;
        # C: z r+w, dinv r, ap r
        return 12.0 + 8.0 / self.tm


def _sem_map():
    """Semaphore base index per named DMA stream (2 slots each; the
    pn-store follows the 3-slot ring)."""
    names = ["z", "p", "w", "wst", "a", "b", "ap", "pnst",
             "zc", "dv", "apc", "zst", "r0"]
    out, i = {}, 0
    for n in names:
        out[n] = i
        i += 3 if n == "pnst" else 2
    return out, i


_SEM, _NSEMS = _sem_map()


def _mega_kernel(problem: Problem, plan: XLPlan, weighted: bool,
                 # HBM inputs
                 dinv_hbm, a_hbm, b_hbm, r0_hbm,
                 # outputs (w is the result; z/p/ap are HBM scratch)
                 w_hbm, iters_out, diff_out, flags_out,
                 z_hbm, p_hbm, ap_hbm,
                 # VMEM tile buffers + SMEM accumulators
                 z_buf, p_buf, w_buf, wout_buf, ring, a_buf, b_buf,
                 ap_buf, zc_buf, zcout_buf, dv_buf, apc_buf, acc, sems):
    dtype = r0_hbm.dtype
    tm, g2p, n_tiles = plan.tm, plan.g2p, plan.n_tiles
    h1h2 = jnp.asarray(float(problem.h1) * float(problem.h2), dtype)
    delta = jnp.asarray(problem.delta, dtype)
    max_iter = problem.max_iterations
    M, N = problem.M, problem.N

    _HBM = {"z": z_hbm, "p": p_hbm, "w": w_hbm, "dv": dinv_hbm,
            "a": a_hbm, "b": b_hbm, "zc": z_hbm, "apc": ap_hbm,
            "r0": r0_hbm}
    _BUF = {"z": z_buf, "p": p_buf, "w": w_buf, "dv": dv_buf,
            "a": a_buf, "b": b_buf, "zc": zc_buf, "apc": apc_buf,
            "r0": zc_buf}
    _ROWS = {"a": tm + 8}

    def load(name, t, slot):
        rows = _ROWS.get(name, tm)
        return pltpu.make_async_copy(
            _HBM[name].at[pl.ds(t * tm, rows), :],
            _BUF[name].at[pl.ds(slot * rows, rows), :],
            sems.at[_SEM[name] + slot],
        )

    def store(name, buf, hbm, t, slot):
        return pltpu.make_async_copy(
            buf.at[pl.ds(slot * tm, tm), :],
            hbm.at[pl.ds(t * tm, tm), :],
            sems.at[_SEM[name] + slot],
        )

    def tile_of(buf, slot, rows=None):
        rows = tm if rows is None else rows
        out = buf[pl.ds(slot * rows, rows), :]
        # operand buffers may be typed at a narrow storage width
        # (``build_xl_solver(storage_dtype=…)``): upcast tile-locally so
        # the arithmetic stays at compute width while the DMA stream —
        # this engine's bottleneck — stays narrow
        return out.astype(dtype) if out.dtype != dtype else out

    # -- one-time init sweep: w = 0, p = 0, z = r0*Dinv, zr0 ---------------
    # serial (one-time cost); w_buf doubles as the zero source.
    w_buf[...] = jnp.zeros(w_buf.shape, dtype)
    acc[0] = jnp.zeros((), dtype)

    def init_tile(t, carry):
        for name in ("r0", "dv"):
            cp = load(name, t, 0)
            cp.start()
            cp.wait()
        rt = tile_of(zc_buf, 0)
        zt = rt * tile_of(dv_buf, 0)
        zcout_buf[pl.ds(0, tm), :] = zt
        for name, buf, hbm in (("zst", zcout_buf, z_hbm),
                               ("wst", w_buf, w_hbm),
                               ("pnst", w_buf, p_hbm)):
            cp = store(name, buf, hbm, t, 0)
            cp.start()
            cp.wait()
        acc[0] += jnp.sum(zt * rt)
        return carry

    lax.fori_loop(0, n_tiles, init_tile, 0)
    zr0 = acc[0] * h1h2

    # -- the stencil on ring tile s (value-level, reference FP form) -------
    def stencil_ring(s, aslot):
        rslot = lax.rem(s, 3)
        pc = tile_of(ring, rslot)
        # aligned 8-row reads + value concats for the single halo rows
        # (Mosaic wants dynamic VMEM offsets at sublane multiples); the
        # unselected branches of the jnp.where reads are ring garbage at
        # the grid edges, discarded by the select.
        prev = lax.rem(s + 2, 3)
        nxt = lax.rem(s + 1, 3)
        above = ring[pl.ds(prev * tm + tm - 8, 8), :]
        below = ring[pl.ds(nxt * tm, 8), :]
        zero_row = jnp.zeros((1, g2p), dtype)
        up_row = jnp.where(s >= 1, above[7:8, :], zero_row)
        dn_row = jnp.where(s + 1 < n_tiles, below[0:1, :], zero_row)
        pu = jnp.concatenate([up_row, pc[:-1]], axis=0)
        pd = jnp.concatenate([pc[1:], dn_row], axis=0)
        aw = tile_of(a_buf, aslot, tm + 8)[0 : tm + 1, :]
        anc = aw[0:tm, :]
        ans = aw[1 : tm + 1, :]
        bwc = tile_of(b_buf, aslot)
        bec = _shift_cols_left(bwc)
        pl_ = _shift_cols_right(pc)
        pr = _shift_cols_left(pc)
        ax = anc * (pc - pu) + ans * (pc - pd)
        ay = bwc * (pc - pl_) + bec * (pc - pr)
        gi = s * tm + lax.broadcasted_iota(jnp.int32, (tm, g2p), 0)
        gj = lax.broadcasted_iota(jnp.int32, (tm, g2p), 1)
        interior = (gi >= 1) & (gi <= M - 1) & (gj >= 1) & (gj <= N - 1)
        return jnp.where(interior, ax + ay, jnp.zeros_like(pc)), pc

    # -- the while loop ----------------------------------------------------
    carry0 = (
        jnp.asarray(0, jnp.int32),          # bodies executed
        zr0,
        jnp.asarray(0.0, dtype),            # alpha (deferred: prev body's)
        jnp.asarray(0.0, dtype),            # beta
        jnp.asarray(jnp.inf, dtype),        # diff
        jnp.asarray(False), jnp.asarray(False),
    )

    def cond(c):
        i, _zr, _a, _b, _d, conv, bd = c
        # one extra body confirms the previous iteration's convergence
        return (i < max_iter + 1) & ~conv & ~bd

    def body(c):
        i, zr, alpha, beta, diff, _cv, _bd = c

        # ---- AB sweep: step t updates tile t (w += alpha p, pn = z +
        # beta p) and stencils tile t-1 (ring holds pn tiles t-2..t).
        # State loads (z/p/w) for tile t are prefetched at step t-1 into
        # slot t%2; a/b for stencil s are prefetched at step s into slot
        # s%2 and consumed at step s+1 — in-use and in-flight slots stay
        # disjoint for every stream.
        acc[0] = jnp.zeros((), dtype)   # dw2
        acc[1] = jnp.zeros((), dtype)   # denom partial
        for name in ("z", "p", "w"):
            load(name, 0, 0).start()

        def ab_step(t, carry):
            slot2 = lax.rem(t, 2)
            rslot = lax.rem(t, 3)

            @pl.when(t + 1 < n_tiles)
            def _():
                nslot = lax.rem(t + 1, 2)
                for name in ("z", "p", "w"):
                    load(name, t + 1, nslot).start()

            # ---- update phase for tile t
            @pl.when(t < n_tiles)
            def _():
                for name in ("z", "p", "w"):
                    load(name, t, slot2).wait()
                # stencil operands for this tile, consumed next step
                load("a", t, slot2).start()
                load("b", t, slot2).start()
                # slots being rewritten must have drained their stores
                @pl.when(t >= 2)
                def _():
                    store("wst", wout_buf, w_hbm, t - 2, slot2).wait()

                @pl.when(t >= 3)
                def _():
                    store("pnst", ring, p_hbm, t - 3, rslot).wait()

                pt = tile_of(p_buf, slot2)
                wt = tile_of(w_buf, slot2)
                zt = tile_of(z_buf, slot2)
                w_new = wt + alpha * pt
                dw = w_new - wt
                wout_buf[pl.ds(slot2 * tm, tm), :] = w_new
                store("wst", wout_buf, w_hbm, t, slot2).start()
                pn = zt + beta * pt
                ring[pl.ds(rslot * tm, tm), :] = pn
                store("pnst", ring, p_hbm, t, rslot).start()
                acc[0] += jnp.sum(dw * dw)

            # ---- stencil phase for tile t-1
            @pl.when(t >= 1)
            def _():
                s = t - 1
                aslot = lax.rem(s, 2)
                load("a", s, aslot).wait()
                load("b", s, aslot).wait()

                @pl.when(s >= 2)
                def _():
                    store("ap", ap_buf, ap_hbm, s - 2, aslot).wait()

                apt, pc = stencil_ring(s, aslot)
                ap_buf[pl.ds(aslot * tm, tm), :] = apt
                store("ap", ap_buf, ap_hbm, s, aslot).start()
                # per-tile SMEM accumulation inside one pipelined Mosaic
                # kernel (the dw2 cell fills in the update phase, this
                # one a stencil-lag behind): already one kernel, no
                # collective to stack
                # tpulint: disable=TPU007
                acc[1] += jnp.sum(apt * pc)

            return carry

        lax.fori_loop(0, n_tiles + 1, ab_step, 0)
        # drain trailing stores (static tails: unrolls)
        for tt in range(max(n_tiles - 2, 0), n_tiles):
            store("wst", wout_buf, w_hbm, tt, tt % 2).wait()
            store("ap", ap_buf, ap_hbm, tt, tt % 2).wait()
        for tt in range(max(n_tiles - 3, 0), n_tiles):
            store("pnst", ring, p_hbm, tt, tt % 3).wait()
        dw2 = acc[0]
        denom = acc[1] * h1h2

        ndiff = jnp.sqrt(dw2 * h1h2) if weighted else jnp.sqrt(dw2)
        # convergence of the PREVIOUS reference iteration (body 0 has no
        # previous update: alpha = 0 makes its dw2 exactly 0)
        conv = (i >= 1) & (ndiff < delta)
        ndiff = jnp.where(i >= 1, ndiff, diff)
        # this body's denominator belongs to reference iteration i+1: a
        # guard trip only counts while that iteration is within the cap —
        # the confirming body past max_iter evaluates a denominator the
        # reference never computes, and must not flag it
        breakdown = ~conv & (denom < DENOM_GUARD) & (i < max_iter)
        guard = denom < DENOM_GUARD
        alpha_new = zr / jnp.where(guard, jnp.ones_like(denom), denom)
        alpha_new = jnp.where(guard, jnp.zeros_like(alpha_new), alpha_new)

        # ---- C sweep: z update + zr partial off one dinv stream
        acc[2] = jnp.zeros((), dtype)
        for name in ("zc", "dv", "apc"):
            load(name, 0, 0).start()

        def c_step(t, carry):
            slot2 = lax.rem(t, 2)

            @pl.when(t + 1 < n_tiles)
            def _():
                nslot = lax.rem(t + 1, 2)
                for name in ("zc", "dv", "apc"):
                    load(name, t + 1, nslot).start()

            for name in ("zc", "dv", "apc"):
                load(name, t, slot2).wait()

            @pl.when(t >= 2)
            def _():
                store("zst", zcout_buf, z_hbm, t - 2, slot2).wait()

            dvt = tile_of(dv_buf, slot2)
            z_new = tile_of(zc_buf, slot2) - alpha_new * (
                dvt * tile_of(apc_buf, slot2)
            )
            zcout_buf[pl.ds(slot2 * tm, tm), :] = z_new
            store("zst", zcout_buf, z_hbm, t, slot2).start()
            # guarded reciprocal: d = 1/Dinv on the interior, 0 off it
            dt = jnp.where(
                dvt != 0.0,
                1.0 / jnp.where(dvt != 0.0, dvt, jnp.ones_like(dvt)),
                jnp.zeros_like(dvt),
            )
            # per-tile SMEM accumulation in the C sweep of the same
            # kernel — the AB-sweep cells are sequenced by the pipeline,
            # not by a fusable reduction pair
            # tpulint: disable=TPU007
            acc[2] += jnp.sum((z_new * z_new) * dt)
            return carry

        lax.fori_loop(0, n_tiles, c_step, 0)
        for tt in range(max(n_tiles - 2, 0), n_tiles):
            store("zst", zcout_buf, z_hbm, tt, tt % 2).wait()
        zr_new = acc[2] * h1h2

        zr_out = jnp.where(breakdown, zr, zr_new)
        beta_new = jnp.where(breakdown, beta, zr_new / zr)
        return (i + 1, zr_out, alpha_new, beta_new, ndiff, conv, breakdown)

    out = lax.while_loop(cond, body, carry0)
    bodies, conv, bd = out[0], out[5], out[6]
    # body i applies reference-iteration i's deferred w-update and checks
    # its convergence; its denominator belongs to reference-iteration
    # i+1. Converged exit therefore reports bodies-1; breakdown and the
    # max_iter cap report the body count (capped).
    iters_out[0] = jnp.where(
        conv, bodies - 1, jnp.minimum(bodies, max_iter)
    )
    diff_out[0] = out[4]
    flags_out[0] = conv.astype(jnp.int32)
    flags_out[1] = bd.astype(jnp.int32)


def build_xl_solver(problem: Problem, dtype=jnp.float32, interpret=None,
                    tm: int | None = None, _debug_raw: bool = False,
                    geometry=None, theta=None, storage_dtype=None):
    """(jitted whole-solve kernel, args) for state-beyond-VMEM grids.

    args = (dinv, a, b, r0): f64-assembled, rounded once — the shared
    operand fidelity contract (``fused_pcg.build_fused_solver``).
    _debug_raw returns the raw pallas outputs (w, iters, diff, flags,
    z, p, ap) — the HBM state scratch is inspectable for tests/debug.

    ``storage_dtype`` (``ops.precision``) streams the coefficient
    operands (dinv, a, b) at that width, upcast per tile inside the
    kernel (``tile_of``); the HBM state scratch stays at compute width —
    the operand share of this engine's ~12 passes/iter narrows, the
    state share keeps full precision (the conservative rung; the full
    state-narrow form is the sharded/sstep engines' territory).
    """
    from poisson_ellipse_tpu.ops.precision import resolve_storage_dtype

    if jnp.dtype(dtype).itemsize >= 8:
        raise ValueError("xl solver supports f32/bf16; use engine='xla'")
    st = resolve_storage_dtype(storage_dtype, dtype)
    if interpret is None:
        interpret = _interpret_default()
    g1, g2 = problem.node_shape
    plan = XLPlan(problem, dtype, tm=tm)
    g1p, g2p, tm = plan.g1p, plan.g2p, plan.tm
    args = streamed_operand_set(problem, dtype, g1p, g2p,
                                geometry=geometry, theta=theta)
    if st is not None:
        dinv0, a0, b0, r00 = args
        args = (
            jnp.asarray(dinv0).astype(st), jnp.asarray(a0).astype(st),
            jnp.asarray(b0).astype(st), r00,
        )

    kernel = functools.partial(
        _mega_kernel, problem, plan, problem.norm == "weighted"
    )
    anyspec = lambda: pl.BlockSpec(memory_space=pl.ANY)
    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)
    tile = lambda slots, rows=None, narrow=False: pltpu.VMEM(
        (slots * (rows if rows else tm), g2p),
        st if (narrow and st is not None) else dtype,
    )
    call = pl.pallas_call(
        kernel,
        in_specs=[anyspec()] * 4,
        out_specs=(anyspec(), smem(), smem(), smem(),
                   anyspec(), anyspec(), anyspec()),
        out_shape=(
            jax.ShapeDtypeStruct((g1p, g2p), dtype),       # w (result)
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((1,), dtype),
            jax.ShapeDtypeStruct((2,), jnp.int32),
            jax.ShapeDtypeStruct((g1p, g2p), dtype),       # z scratch
            jax.ShapeDtypeStruct((g1p, g2p), dtype),       # p scratch
            jax.ShapeDtypeStruct((g1p, g2p), dtype),       # ap scratch
        ),
        scratch_shapes=[
            tile(2),            # z_buf
            tile(2),            # p_buf
            tile(2),            # w_buf
            tile(2),            # wout_buf
            tile(3),            # ring (pn)
            tile(2, tm + 8, narrow=True),    # a_buf
            tile(2, narrow=True),            # b_buf
            tile(2),            # ap_buf
            tile(2),            # zc_buf
            tile(2),            # zcout_buf
            tile(2, narrow=True),            # dv_buf
            tile(2),            # apc_buf
            pltpu.SMEM((3,), dtype),
            pltpu.SemaphoreType.DMA((_NSEMS,)),
        ],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=scaled_vmem_budget(_VMEM_LIMIT)
        ),
        interpret=interpret,
    )

    if _debug_raw:
        return jax.jit(call), args

    def solver(dinv, a, b, r0):
        w_pad, iters, diff, flags, _z, _p, _ap = call(dinv, a, b, r0)
        return PCGResult(
            w=w_pad[:g1, :g2],
            iters=iters[0],
            diff=diff[0],
            converged=flags[0].astype(bool),
            breakdown=flags[1].astype(bool),
        )

    # no donation: build-once-call-many — callers re-feed these operands
    # every dispatch (bench --repeat protocol)
    # tpulint: disable=TPU004
    return jax.jit(solver), args


def solve_xl(problem: Problem, dtype=jnp.float32, interpret=None) -> PCGResult:
    solver, args = build_xl_solver(problem, dtype, interpret=interpret)
    return solver(*args)
