"""Pipelined PCG (Ghysels–Vanroose): one fused reduction per iteration.

The classical recurrence (``solver.pcg``) serializes every iteration on
TWO dependent global reductions — ``denom = (Ap, p)`` must finish before
the axpy updates that feed ``zr_new``/``‖Δw‖²`` can even start, so the
loop's critical path is stencil → reduce → update → reduce. Pipelined CG
(Ghysels & Vanroose 2014; the α/β derivation goes back to Chronopoulos &
Gear's s-step CG) reorders the recurrence so that **all** inner products
of an iteration are functions of vectors already in hand at its start,
letting them ride ONE stacked reduction — and leaving the iteration's
stencil application with no data dependence on that reduction, so the two
overlap. On the mesh this halves the collectives per iteration from 2
``lax.psum`` to 1 (``parallel.pipelined_sharded``); on a single chip it
shortens the reduce→broadcast critical path and shrinks the fusion count.

Recurrence, with M = D (Jacobi) and the reference's h1·h2-weighted dots.
Carry adds s = A·p, u = M⁻¹r, w = A·u (and the auxiliary z = A·M⁻¹s) to
the classical (x, r, p):

  [one fused dot bundle, from carried vectors only]
    γ = (r, u)   (w,u)  (w,p)  (s,u)  (s,p)  (u,u)  (u,p)  (p,p)
  [stencil of this iteration — independent of the bundle: overlaps it]
    m = M⁻¹ w
    n = A m
  β  = γ/γ₋₁                                  (0 at the first iteration)
  α  = γ / [(w,u) + β((w,p) + (s,u)) + β²(s,p)]
  z⁺ = n + β z      s⁺ = w + β s      p⁺ = u + β p
  x⁺ = x + α p⁺     r⁺ = r − α s⁺
  u⁺ = u − α M⁻¹s⁺  w⁺ = w − α z⁺

(M is diagonal, hence linear: M⁻¹s⁺ is exactly the classical q-recurrence
q⁺ = m + β q, so q needs no carry slot.) The α-denominator expands
(A p⁺, p⁺) = (w + βs, u + βp) directly from the bundle — the same value
Ghysels–Vanroose's scalar recursion δ − βγ/α₋₁ propagates, but evaluated
as inner products each iteration, which avoids that recursion's
catastrophic cancellation near convergence (their §4.3 stability
discussion; measured: the recursive form breaks down spuriously in f32
on the stiff 1/ε operators, the expanded form does not). Breakdown keeps
the reference's ``DENOM_GUARD`` semantics: that denominator under 1e-15
discards the iteration's update and exits, exactly as
``stage0/Withoutopenmp1.cpp:128`` returns before touching w/r. The
convergence norm ‖Δx‖ = α‖p⁺‖ is assembled from the bundle too:
(p⁺,p⁺) = (u,u) + 2β(u,p) + β²(p,p).

Accuracy note: pipelined CG is a *reordering* of the same Krylov
recurrence, not a bit-identical evaluation — α/β are algebraically equal
to the classical values but computed through different FP expressions,
and w = A·u is maintained by recurrence rather than recomputed, so
round-off accumulates differently. On the published oracle grids the
iteration counts land within ±2 of the ``xla`` engine and the solutions
within fractions of a percent in L2 (asserted in
``tests/test_pipelined.py``); bitwise oracle-count parity remains the
classical engines' contract.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs.convergence import (
    history_init,
    history_record,
    trace_of,
)
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.precision import (
    load as _pload,
    replace_every,
    resolve_storage_dtype,
    store as _pstore,
)
from poisson_ellipse_tpu.ops.reduction import grid_dots
from poisson_ellipse_tpu.ops.stencil import apply_a, apply_dinv, diag_d
from poisson_ellipse_tpu.solver.pcg import DENOM_GUARD, PCGResult

# Residual-replacement period (iterations). The recurrence-maintained
# vectors (r, u, w, z, s) accumulate round-off the classical loop does
# not have; every REPLACE_EVERY-th iteration recomputes them from x and
# p (4 stencil applications), which bounds the drift — without it the
# f32 path breaks down spuriously on the stiff 1/ε operators hundreds of
# iterations in (Ghysels & Vanroose §4.3's residual replacement, on a
# fixed cadence so chunked advances stay bit-identical to straight runs).
# Amortised cost: 4/32 ≈ 0.13 extra stencil passes per iteration.
# Under a sub-compute storage_dtype the cadence tightens (every store
# rounds at the storage floor): ``ops.precision.replace_every`` keys the
# period on the effective dtype — this constant is the f32 value.
REPLACE_EVERY = 32


def init_state(problem: Problem, a, b, rhs, stencil: str = "xla",
               interpret=None, history: bool = False, storage_dtype=None):
    """The pipelined carry at iteration 0 (the resumable solver state).

    Layout: (k, x, r, u, w, z, s, p, γ₋₁, diff, converged, breakdown).
    One stencil application (w₀ = A u₀) happens here, outside the loop;
    z/s/p start at zero because β = 0 on the first iteration rebuilds
    them from (n, w, u) alone. γ₋₁ starts at 1 — it only ever divides
    under a β that the first pass forces to 0, so the value never
    surfaces. ``history=True`` appends the four ``obs.convergence``
    buffers; the core layout is untouched.
    """
    dtype = rhs.dtype
    st = resolve_storage_dtype(storage_dtype, dtype)
    d = diag_d(a, b, jnp.asarray(problem.h1, dtype), jnp.asarray(problem.h2, dtype))
    apply_stencil = _stencil_fn(problem, a, b, d, stencil, dtype, interpret)
    r0 = rhs
    u0 = apply_dinv(r0, d)
    w0 = apply_stencil(u0)
    zeros = jnp.zeros_like(rhs, dtype=st or rhs.dtype)
    one = jnp.asarray(1.0, dtype)
    state = (
        jnp.asarray(0, jnp.int32),
        zeros,  # x
        _pstore(r0, st),
        _pstore(u0, st),
        _pstore(w0, st),
        zeros,  # z
        zeros,  # s
        zeros,  # p
        one,    # γ of the previous iteration
        jnp.asarray(jnp.inf, dtype),
        jnp.asarray(False),
        jnp.asarray(False),
    )
    if history:
        state = state + history_init(problem.max_iterations, dtype)
    return state


def _stencil_fn(problem: Problem, a, b, d, stencil: str, dtype,
                interpret=None):
    """The A·(·) closure for one engine flavour.

    "xla" leaves the stencil to XLA's fusion; "pallas" runs the fused
    stencil+partials kernel's stencil-only path for the init application
    (the in-loop call goes through ``apply_a_dots_pallas`` so the dot
    operands stream from HBM once, alongside the stencil's own reads).
    """
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    if stencil == "xla":
        return lambda m: apply_a(m, a, b, h1, h2)
    if stencil == "pallas":
        from poisson_ellipse_tpu.ops.pallas_kernels import apply_a_pallas

        return lambda m: apply_a_pallas(
            m, a, b, problem.h1, problem.h2, interpret=interpret
        )
    raise ValueError(f"unknown stencil: {stencil!r}")


def advance(problem: Problem, a, b, rhs, state, limit=None,
            stencil: str = "xla", interpret=None, history: bool = False,
            storage_dtype=None):
    """Advance the pipelined carry until convergence/breakdown or
    iteration ``limit`` (defaults to max_iterations).

    Chunked runs (limit=k, k+K, …) are bit-identical to one straight run
    — chunking only moves the while_loop boundary, not the arithmetic
    (same contract as ``solver.pcg.advance``). ``history=True``
    expects/returns the extended carry and records (γ, diff, α, β) per
    iteration — γ is this recurrence's zr-series (``obs.convergence``);
    pure extra stores, iterates bit-identical either way.
    """
    dtype = rhs.dtype
    st = resolve_storage_dtype(storage_dtype, dtype)
    replace_cadence = replace_every(st, dtype)
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    hw = h1 * h2
    delta = jnp.asarray(problem.delta, dtype)
    weighted = problem.norm == "weighted"
    max_iter = (
        problem.max_iterations
        if limit is None
        else jnp.minimum(
            jnp.asarray(limit, jnp.int32), problem.max_iterations
        )
    )
    d = diag_d(a, b, h1, h2)
    apply_stencil = _stencil_fn(problem, a, b, d, stencil, dtype, interpret)
    # operands stream at storage width when a storage dtype is set (the
    # upcast fuses into the consumers — reads stay narrow)
    a_s, b_s = (_pstore(a, st), _pstore(b, st)) if st is not None else (a, b)
    d_s = _pstore(d, st) if st is not None else d
    if st is not None and stencil == "xla":
        # the storage-width stencil: operands read narrow, upcast fused
        def apply_stencil(m):  # noqa: F811 — replaces the full-width closure
            return apply_a(m, _pload(a_s, dtype, st), _pload(b_s, dtype, st),
                           h1, h2)

    if stencil == "pallas":
        if st is not None:
            from poisson_ellipse_tpu.ops.pallas_kernels import (
                apply_a_dots_mixed_pallas,
                apply_a_mixed_pallas,
            )

            # replacement rebuilds apply the SAME storage-rounded
            # operator the in-loop kernel applies (operator consistency)
            def apply_stencil(m):  # noqa: F811
                return apply_a_mixed_pallas(
                    m, a_s, b_s, problem.h1, problem.h2,
                    compute_dtype=dtype, interpret=interpret,
                )

            def stencil_and_dots(m, r, u, w, s, p):
                # mixed one-VMEM-pass form: the dot operands stream at
                # storage width and are upcast tile-locally; partials
                # accumulate at compute width in SMEM
                stored = tuple(_pstore(v, st) for v in (r, u, w, s, p))
                return apply_a_dots_mixed_pallas(
                    m, a_s, b_s, problem.h1, problem.h2, _bundle(*stored),
                    compute_dtype=dtype, interpret=interpret,
                )

        else:
            from poisson_ellipse_tpu.ops.pallas_kernels import (
                apply_a_dots_pallas,
            )

            def stencil_and_dots(m, r, u, w, s, p):
                # one VMEM pass: n = A·m AND the eight dot partials, every
                # operand read from HBM exactly once
                return apply_a_dots_pallas(
                    m, a, b, problem.h1, problem.h2, _bundle(r, u, w, s, p),
                    interpret=interpret,
                )

    else:  # "xla" (anything else was rejected by _stencil_fn above)

        def stencil_and_dots(m, r, u, w, s, p):
            return apply_stencil(m), grid_dots(*_bundle(r, u, w, s, p))

    def cond(state):
        k = state[0]
        converged, breakdown = state[10], state[11]
        return (k < max_iter) & ~converged & ~breakdown

    def replace(k, x, r, u, w, z, s, p, rhs):
        """Residual replacement: rebuild the recurrence-maintained
        vectors from the ground-truth x and p. Keyed purely on the
        iteration counter, so chunking cannot move it."""

        def rebuilt(_):
            # dinv resolves at call time: the rebuild divides by the SAME
            # (possibly storage-rounded) D the in-loop recurrence uses
            r_t = rhs - apply_stencil(x)
            u_t = dinv(r_t)
            s_t = apply_stencil(p)
            return (
                r_t, u_t, apply_stencil(u_t),
                apply_stencil(dinv(s_t)), s_t,
            )

        do = (k > 0) & (k % replace_cadence == 0)
        return lax.cond(do, rebuilt, lambda _: (r, u, w, z, s), None)

    def dinv(v):
        # under a storage dtype D streams narrow too; the load fuses
        return apply_dinv(v, _pload(d_s, dtype, st) if st is not None else d)

    def body(state):
        k, x_s, r_sv, u_sv, w_sv, z_sv, s_sv, p_sv, g_prev, diff_prev, \
            _c, _bd = state[:12]
        # tile-local upcast (identity when st is None)
        x = _pload(x_s, dtype, st)
        r, u, w = (_pload(v, dtype, st) for v in (r_sv, u_sv, w_sv))
        z, s, p = (_pload(v, dtype, st) for v in (z_sv, s_sv, p_sv))
        r, u, w, z, s = replace(k, x, r, u, w, z, s, p, rhs)

        # the iteration's one fused reduction (γ and the α/norm terms)
        # and its one stencil application — the stencil has no data
        # dependence on the reduction, so on a mesh XLA overlaps the
        # psum with the halo exchange + stencil
        # (parallel.pipelined_sharded); here they share one fusion pass
        m = dinv(w)
        n, sums = stencil_and_dots(m, r, u, w, s, p)
        gamma = sums[0] * hw
        wu, wp, su, sp = sums[1], sums[2], sums[3], sums[4]
        uu, up, pp = sums[5], sums[6], sums[7]

        first = k == 0
        beta = jnp.where(
            first, 0.0, gamma / jnp.where(first, 1.0, g_prev)
        )
        # (A p⁺, p⁺) = (w + βs, u + βp), expanded over the bundle — the
        # reference's breakdown guard applies to it unchanged
        # (stage0/Withoutopenmp1.cpp:128)
        denom = (wu + beta * (wp + su) + beta * beta * sp) * hw
        breakdown = denom < DENOM_GUARD
        alpha = gamma / jnp.where(breakdown, 1.0, denom)

        z_new = n + beta * z
        s_new = w + beta * s
        p_new = u + beta * p
        x_new = x + alpha * p_new
        r_new = r - alpha * s_new
        u_new = u - alpha * dinv(s_new)
        w_new = w - alpha * z_new

        # ‖Δx‖ = α‖p⁺‖ from the bundle (no extra pass over x)
        pp_new = uu + 2.0 * beta * up + beta * beta * pp
        dw2 = alpha * alpha * pp_new
        diff = jnp.sqrt(dw2 * hw) if weighted else jnp.sqrt(dw2)
        converged = ~breakdown & (diff < delta)
        diff = jnp.where(breakdown, diff_prev, diff)

        # a breakdown iteration discards its update entirely (the
        # reference exits before touching w/r); updates round back to
        # storage width on store (identity when st is None)
        keep = lambda old, new: jnp.where(breakdown, old, _pstore(new, st))
        out = (
            k + 1,
            keep(x_s, x_new), keep(r_sv, r_new), keep(u_sv, u_new),
            keep(w_sv, w_new), keep(z_sv, z_new), keep(s_sv, s_new),
            keep(p_sv, p_new),
            jnp.where(breakdown, g_prev, gamma),
            diff, converged, breakdown,
        )
        if history:
            # applied α is 0 on a breakdown iteration (update discarded)
            # — the same recording every engine's trace uses
            out = out + history_record(
                state[12:], k, gamma, diff,
                jnp.where(breakdown, 0.0, alpha), beta,
            )
        return out

    return lax.while_loop(cond, body, state)


def _bundle(r, u, w, s, p):
    """The iteration's eight dot pairs, in bundle order: γ, the four
    α-denominator terms, and the three ‖Δx‖-recurrence terms."""
    return (
        (r, u),
        (w, u), (w, p), (s, u), (s, p),
        (u, u), (u, p), (p, p),
    )


def result_of(state) -> PCGResult:
    """View a pipelined carry as a PCGResult."""
    k, x = state[0], state[1]
    diff, converged, breakdown = state[9], state[10], state[11]
    return PCGResult(
        w=x, iters=k, diff=diff, converged=converged, breakdown=breakdown
    )


def pcg_pipelined(problem: Problem, a, b, rhs, stencil: str = "xla",
                  interpret=None, history: bool = False,
                  storage_dtype=None):
    """Run pipelined PCG for pre-assembled coefficients ((M+1, N+1) grids).

    Jit-safe with ``problem`` static; the while_loop carries
    (k, x, r, u, w, z, s, p, γ, diff, converged, breakdown) entirely on
    device. stencil "xla" (fused by XLA, any dtype) or "pallas" (the
    fused stencil+partials kernel, f32/bf16 on hardware; ``interpret``
    forces/suppresses the kernels' interpreter mode, default: interpret
    off-TPU). history=True additionally returns the per-iteration
    ``obs.ConvergenceTrace`` (γ/diff/α/β), captured on device.
    """
    state = advance(
        problem, a, b, rhs,
        init_state(problem, a, b, rhs, stencil=stencil, interpret=interpret,
                   history=history, storage_dtype=storage_dtype),
        stencil=stencil, interpret=interpret, history=history,
        storage_dtype=storage_dtype,
    )
    result = result_of(state)
    if history:
        return result, trace_of(state[12:], result.iters)
    return result


def solve(problem: Problem, dtype=jnp.float32, stencil: str = "xla",
          interpret=None, history: bool = False):
    """Assemble and solve on a single chip with the pipelined recurrence."""
    a, b, rhs = assembly.assemble(problem, dtype)
    return pcg_pipelined(problem, a, b, rhs, stencil=stencil,
                         interpret=interpret, history=history)
