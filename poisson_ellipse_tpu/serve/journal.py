"""Crash-safe request journal: a killed server replays, never loses.

The durability stance mirrors ``solver.checkpoint``'s integrity
manifest: every state transition rewrites one JSON snapshot under a
temporary name and ``os.replace``s it into place — atomic on POSIX, so
a kill at any instant leaves either the previous snapshot or the new
one on disk, never a torn file. The write-ahead contract is the
standard one: :meth:`RequestJournal.record_admit` returns only after
the snapshot holding the request is durable, and the scheduler
acknowledges admission only after that return — so on restart,
:meth:`unfinished` is exactly the set of acknowledged-but-unfinished
requests, and replaying them loses nothing the server ever promised.

Double completion is a journal-level error: :meth:`record_outcome` on a
request already in a terminal state raises instead of overwriting —
the chaos harness's zero-double-completion invariant is enforced where
the record lives, not just asserted after the fact.

Fleet fencing rides the same choke point: a journal owned by a fleet
replica carries a **fencing token** (``fence`` — issued by the fleet's
lease authority, ``fleet.replica``), every mutation calls
``fence.check()`` BEFORE touching the record, and every flushed
snapshot embeds ``fence.value``. A replica whose lease expired has its
token revoked, so a zombie resurrecting mid-handoff cannot admit or
complete anything — the stale write raises (and is trace-evented by the
token) at the exact layer the zero-lost/zero-double promises live.

Finished records are compacted: a terminal outcome *removes* the
request's record from the snapshot (its id is retained in a small
in-process set so double completion still raises) and bumps a durable
``finished`` counter, so each flush serializes only the live
admitted-but-unfinished set — O(live) disk work per transition on a
server meant to see millions of requests, not O(everything ever
served). Crash safety is unchanged: the compaction rides the same
atomic rename as the transition it records, so a restart either sees
the request admitted (and replays it — a single completion) or already
compacted (finished — never replayed).
"""

from __future__ import annotations

import json
import os
import tempfile
import time

from poisson_ellipse_tpu.serve.request import OUTCOMES, ServeRequest

JOURNAL_VERSION = 1


class DoubleCompletionError(RuntimeError):
    """A second terminal outcome for an already-finished request — the
    lost-or-doubled bug class the journal exists to make impossible."""


class RequestJournal:
    """One server's request ledger, snapshotted atomically per transition.

    ``path`` is the snapshot file; a missing file is an empty journal
    (first boot). A leftover ``<path>.tmp`` from a mid-write kill is
    ignored and overwritten — the rename never happened, so the main
    snapshot is still the truth.

    ``fence`` is an optional fencing token (``fleet.replica``'s
    ``FencingToken``, or any object with ``check()`` and ``value``):
    when set, every mutation is fenced — ``check()`` runs before the
    record is touched and raises on a revoked token — and every
    snapshot embeds ``value`` so the on-disk ledger names the epoch
    that wrote it.
    """

    def __init__(self, path, fence=None):
        self.path = os.fspath(path)
        self.fence = fence
        self._records: dict[str, dict] = {}
        self._finished_ids: set[str] = set()
        self._finished_total = 0
        # the fencing token embedded in the loaded snapshot (None for a
        # fresh journal or one written unfenced) — forensic evidence of
        # WHICH epoch last wrote the ledger, surfaced for the fleet's
        # stale-write tests and post-incident reads
        self.loaded_fence_token = None
        if os.path.exists(self.path):
            with open(self.path, encoding="utf-8") as fh:
                data = json.load(fh)
            if data.get("v") != JOURNAL_VERSION:
                raise ValueError(
                    f"journal {self.path} carries version {data.get('v')!r},"
                    f" expected {JOURNAL_VERSION}"
                )
            self._records = data["requests"]
            self._finished_total = data.get("finished", 0)
            self.loaded_fence_token = data.get("fence_token")
            # a snapshot predating compaction may still carry done
            # records — fold them into the counter and drop them
            done = [
                rid for rid, rec in self._records.items()
                if rec["state"] == "done"
            ]
            for rid in done:
                del self._records[rid]
            self._finished_total += len(done)

    # -- transitions --------------------------------------------------------

    def record_admit(self, request: ServeRequest) -> None:
        """Durably record an admission; the scheduler acknowledges the
        request only after this returns (the write-ahead contract).
        Replayed requests re-admit under their original id — idempotent,
        their spec is simply refreshed."""
        self._check_fence()
        if request.request_id in self._finished_ids:
            raise DoubleCompletionError(
                f"request {request.request_id} is already finished; "
                f"it cannot be re-admitted"
            )
        self._records[request.request_id] = {
            "state": "admitted",
            "spec": request.spec(),
            "t_admit_unix": time.time(),
        }
        self._flush()

    def record_outcome(self, request_id: str, outcome: str,
                       detail: str | None = None) -> None:
        """Durably record a terminal outcome — exactly once per request.
        The terminal record is compacted away (see the module
        docstring); only the durable ``finished`` counter and the
        in-process id set remember it."""
        self._check_fence()
        if outcome not in OUTCOMES:
            raise ValueError(f"outcome {outcome!r} not one of {OUTCOMES}")
        if request_id in self._finished_ids:
            raise DoubleCompletionError(
                f"request {request_id} already finished; "
                f"refusing the second outcome {outcome!r}"
            )
        if request_id not in self._records:
            raise KeyError(f"request {request_id} was never admitted")
        del self._records[request_id]
        self._finished_ids.add(request_id)
        self._finished_total += 1
        self._flush()

    # -- replay -------------------------------------------------------------

    def unfinished(self, now: float) -> list[ServeRequest]:
        """Admitted-but-unfinished requests, rebuilt for resubmission
        (deadline budgets restart from ``now`` — see
        ``ServeRequest.from_spec``). Admission order is preserved."""
        return [
            ServeRequest.from_spec(rec["spec"], now)
            for rec in self._records.values()
            if rec["state"] == "admitted"
        ]

    def admitted_ids(self) -> set[str]:
        """Ids with a LIVE admitted record (finished/compacted ones
        excluded) — the fleet's co-ownership audit reads this."""
        return {
            rid for rid, rec in self._records.items()
            if rec["state"] == "admitted"
        }

    def state_of(self, request_id: str) -> dict | None:
        """The live record, a compacted ``{"state": "done"}`` stub for a
        request this journal instance saw finish, or None."""
        rec = self._records.get(request_id)
        if rec is not None:
            return dict(rec)
        if request_id in self._finished_ids:
            return {"state": "done"}
        return None

    def counts(self) -> dict:
        return {
            "admitted": len(self._records) + self._finished_total,
            "finished": self._finished_total,
            "unfinished": len(self._records),
        }

    # -- durability ---------------------------------------------------------

    def _check_fence(self) -> None:
        """The fencing gate every mutation passes first: a revoked token
        raises (``fleet.replica.StaleLeaseError``) BEFORE the record is
        touched, so a fenced zombie's admit/outcome never lands — in
        memory or on disk."""
        if self.fence is not None:
            self.fence.check()

    def _flush(self) -> None:
        """Write-temp-fsync-rename, the ``solver.checkpoint`` idiom: a
        kill mid-write leaves the previous snapshot, never a torn one."""
        payload = {
            "v": JOURNAL_VERSION,
            "requests": self._records,
            "finished": self._finished_total,
        }
        if self.fence is not None:
            # every journal write carries the fencing token: the on-disk
            # snapshot names the epoch that produced it
            payload["fence_token"] = self.fence.value
        directory = os.path.dirname(os.path.abspath(self.path)) or "."
        fd, tmp = tempfile.mkstemp(prefix=".journal-", dir=directory)
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                json.dump(payload, fh)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
